"""Profiler windows + one-shot per-compiled-program records.

ISSUE 12 pillar 3, two tools:

- `ProfileWindow`: an on-demand `jax.profiler` trace window. Use as a
  context manager around a region (train loop Run), or arm it with
  `steps=N` and tick `StepDone()` from a step loop (the serving engine's
  `ProfileSteps`) so the trace covers exactly N engine steps. Every
  profiler call is guarded: on builds/backends without profiler support
  the window degrades to a no-op (`active` stays False) instead of
  raising — observability must never take the service down.

- `CompileLog`: ahead-of-time compiles a jitted callable ONCE per named
  program via `.lower(*args).compile()`, records compile wall time, the
  XLA memory analysis (temp/argument/output bytes — the static memory
  plan), and the donation set, then dispatches every subsequent call
  through the stored executable. The jit tracing cache does not see
  `.lower().compile()`, so the compiled object MUST be reused for
  dispatch or each call would pay tracing again (the bench's
  `_BenchFusedXent` established this idiom). Any failure — lowering,
  memory_analysis, or an aval mismatch at dispatch — permanently falls
  back to calling the original jit fn for that name, recording why.
"""

from __future__ import annotations

import time
from typing import Optional

import jax


def ProfilerSupported() -> bool:
  return hasattr(jax, "profiler") and hasattr(jax.profiler, "start_trace")


class ProfileWindow:
  """A start/stop (or N-step) jax.profiler trace window; no-op when
  unsupported. Traces land under `<logdir>/plugins/profile/<ts>/` (the
  XProf/TensorBoard layout jax.profiler writes)."""

  def __init__(self, logdir: str, steps: int = 0):
    self.logdir = logdir
    self.steps_remaining = int(steps)
    self.active = False
    self.error: Optional[str] = None

  def Start(self):
    """Starts the trace (idempotent)."""
    if self.active or self.error is not None:
      return self
    try:
      jax.profiler.start_trace(self.logdir)
      self.active = True
    except Exception as e:  # noqa: BLE001 - degrade to no-op
      self.error = f"{type(e).__name__}: {e}"
    return self

  def Stop(self):
    """Stops the trace (idempotent)."""
    if not self.active:
      return
    self.active = False
    try:
      jax.profiler.stop_trace()
    except Exception as e:  # noqa: BLE001
      self.error = f"{type(e).__name__}: {e}"

  def StepDone(self) -> bool:
    """Ticks an armed N-step window; returns True when the window closed
    (caller should drop its reference)."""
    if self.error is not None:
      return True
    self.steps_remaining -= 1
    if self.steps_remaining <= 0:
      self.Stop()
      return True
    return False

  def __enter__(self):
    return self.Start()

  def __exit__(self, *exc):
    self.Stop()
    return False


def CompileInfo(compiled) -> dict:
  """XLA static-memory-plan facts of a Compiled object; every accessor is
  version-guarded (memory_analysis is unavailable on some backends)."""
  info = {}
  try:
    ma = compiled.memory_analysis()
    for rec_key, attr in (("temp_bytes", "temp_size_in_bytes"),
                          ("argument_bytes", "argument_size_in_bytes"),
                          ("output_bytes", "output_size_in_bytes"),
                          ("code_bytes", "generated_code_size_in_bytes")):
      v = getattr(ma, attr, None)
      if v is not None:
        info[rec_key] = int(v)
  except Exception:  # noqa: BLE001 - analysis is best-effort metadata
    pass
  return info


class CompileLog:
  """One-shot AOT compile records + call-through-executable dispatch.

  registry: optional MetricsRegistry — each record's wall time and temp
  bytes are published as `<namespace>/<name>_compile_wall_s` /
  `_temp_bytes` gauges. donate: the donate_argnums the caller built its
  jit fn with (recorded; donation semantics ride the executable itself).
  """

  def __init__(self, registry=None, namespace: str = "compile",
               donate: tuple = ()):
    self._registry = registry
    self._namespace = namespace
    self._donate = tuple(donate)
    # name -> (compiled_or_None, record)
    self._programs: dict = {}

  def Records(self) -> dict:
    """{name: record} — one per compiled program (copies)."""
    return {n: dict(rec) for n, (_, rec) in self._programs.items()}

  def Call(self, name: str, fn, *args):
    """Calls `fn(*args)`, AOT-compiling + recording on first use of
    `name`. `fn` must be a jit wrapper (has .lower); anything else — or
    any compile/dispatch failure — degrades to plain calls forever."""
    entry = self._programs.get(name)
    if entry is None:
      entry = self._Compile(name, fn, args)
    compiled, rec = entry
    if compiled is None:
      return fn(*args)
    try:
      out = compiled(*args)
      rec["calls"] = rec.get("calls", 0) + 1
      return out
    except Exception as e:  # noqa: BLE001 - aval drift: fall back for good
      self._programs[name] = (None, rec)
      rec["fallback"] = f"dispatch: {type(e).__name__}: {e}"
      return fn(*args)

  def _Compile(self, name: str, fn, args):
    rec = {"name": name, "donated_argnums": list(self._donate)}
    compiled = None
    if hasattr(fn, "lower"):
      try:
        t0 = time.perf_counter()
        compiled = fn.lower(*args).compile()
        rec["compile_wall_s"] = round(time.perf_counter() - t0, 6)
        rec.update(CompileInfo(compiled))
      except Exception as e:  # noqa: BLE001
        compiled = None
        rec["fallback"] = f"compile: {type(e).__name__}: {e}"
    else:
      rec["fallback"] = "not a jit wrapper (no .lower)"
    if self._registry is not None and "compile_wall_s" in rec:
      self._registry.Gauge(
          f"{self._namespace}/{name}_compile_wall_s").Set(
              rec["compile_wall_s"])
      if "temp_bytes" in rec:
        self._registry.Gauge(
            f"{self._namespace}/{name}_temp_bytes").Set(rec["temp_bytes"])
    self._programs[name] = (compiled, rec)
    return self._programs[name]
