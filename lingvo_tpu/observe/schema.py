"""Shared telemetry schema: the single source of truth for metric keys.

Before this module, the stack had grown parallel telemetry dialects —
engine `ServingLoop.Stats()`, `GShardDecode`'s ad-hoc telemetry dict,
per-program `infeed_wait_s` timers — whose key sets drifted apart as each
PR added its keys to whichever surface it touched (the kv/paged-path keys
landed twice, once per surface, in PRs 10-11). Every key set is now
declared HERE, constructors validate against it, and the key-set tests
assert both runtime surfaces against these constants, so the next key
either lands everywhere or fails a test.

Conventions:
- Registry metric names are `namespace/key` with namespaces `serving/*`,
  `scheduler/*`, `kv_pages/*`, `state_slots/*`, `infeed/*`, `train/*`.
- A *surface* (Stats() dict, telemetry dict) is a plain-key view derived
  from registry values; the schema maps between the two.
"""

from __future__ import annotations

# -- serving engine Stats() --------------------------------------------------

# Monotonic counters the engine increments per step/commit; Stats() carries
# them under these exact plain keys, the registry under "serving/<key>".
ENGINE_COUNTER_KEYS = (
    "steps", "decode_steps", "mixed_steps",
    "tokens_emitted", "prompt_tokens",
    "dense_fallback_steps", "quantized_steps",
    "spec_cycles", "draft_tokens", "accepted_tokens",
    "spec_branches", "spec_width_clamps",
    "prefix_hit_tokens",
)

# Static engine configuration facts (set once at construction).
ENGINE_INFO_KEYS = (
    "paged_path", "kv_cache_dtype", "kv_bytes_per_token",
    "serve_int8_weights",
)

# Nested sub-dict sections always present in Stats().
ENGINE_SECTION_KEYS = ("scheduler", "kv_pages", "mixers", "prefix_cache")

# Keys every engine Stats() dict must carry. accepted_len_hist and
# accepted_depth_hist are two readings of the same per-verify histogram:
# hist[m] = rows whose accepted draft prefix length / accepted
# root-to-leaf tree depth was m (identical for chain speculation).
ENGINE_STATS_REQUIRED = frozenset(
    ENGINE_COUNTER_KEYS + ENGINE_INFO_KEYS + ENGINE_SECTION_KEYS
    + ("accepted_len_hist", "accepted_depth_hist"))

# Keys present only under specific configurations:
#   state_slots — stacks with O(1)-state mixers
#   spec        — engines with a draft source
#   trace       — engines with tracing enabled (the default)
#   compile     — per-compiled-program records (observe/profile.py)
#   watchdog    — engines with a stall watchdog (observe/watchdog.py)
ENGINE_STATS_OPTIONAL = frozenset(
    {"state_slots", "spec", "trace", "compile", "watchdog"})


def ValidateEngineStats(stats: dict) -> dict:
  """Asserts a Stats() dict matches the schema; returns it unchanged."""
  keys = set(stats)
  missing = ENGINE_STATS_REQUIRED - keys
  assert not missing, f"engine Stats() missing schema keys: {sorted(missing)}"
  unknown = keys - ENGINE_STATS_REQUIRED - ENGINE_STATS_OPTIONAL
  assert not unknown, f"engine Stats() keys not in schema: {sorted(unknown)}"
  pc = set(stats["prefix_cache"])
  assert pc == PREFIX_CACHE_STATS_KEYS, (
      f"prefix_cache section keys drifted from schema: {sorted(pc)}")
  kv = set(stats["kv_pages"])
  assert KV_PAGES_REQUIRED <= kv, (
      f"kv_pages section missing keys: {sorted(KV_PAGES_REQUIRED - kv)}")
  return stats


# -- GShardDecode telemetry --------------------------------------------------

# The batch-synchronous decode driver's per-DecodeOnce telemetry dict —
# also attached to every result record under "telemetry". Shared keys
# (below) mirror the engine surface so bench comparisons line up.
GSHARD_TELEMETRY_KEYS = (
    "prefill_s", "decode_s", "total_s",
    "prompt_tokens", "decode_tokens", "tokens_per_sec",
    "decode_state_bytes_per_seq",
    "kv_cache_dtype", "kv_bytes_per_token", "serve_int8_weights",
    "draft_tokens", "accepted_tokens", "accepted_len_hist",
    "spec_branches", "spec_width_clamps", "accepted_depth_hist",
    "prefix_hit_tokens", "prefix_cache", "step_programs",
    # SLO scheduling counters (engine scheduler section mirror) — the
    # batch-synchronous driver never preempts, so it zero-fills these
    "preemptions", "spilled_pages", "restored_pages", "host_bytes",
)

# Keys both serving surfaces advertise (values must mean the same thing).
SHARED_SERVING_KEYS = frozenset(GSHARD_TELEMETRY_KEYS) & (
    ENGINE_STATS_REQUIRED)


def GShardTelemetry(**values) -> dict:
  """Builds a telemetry dict, validating the exact schema key set."""
  keys = set(values)
  missing = set(GSHARD_TELEMETRY_KEYS) - keys
  assert not missing, f"telemetry missing schema keys: {sorted(missing)}"
  unknown = keys - set(GSHARD_TELEMETRY_KEYS)
  assert not unknown, f"telemetry keys not in schema: {sorted(unknown)}"
  return {k: values[k] for k in GSHARD_TELEMETRY_KEYS}


def PublishTelemetry(registry, values: dict, prefix: str = "serving/"):
  """Publishes a telemetry dict into a registry as gauges."""
  for k, v in values.items():
    registry.Gauge(prefix + k).Set(v)


def TelemetryFromRegistry(registry, prefix: str = "serving/") -> dict:
  """The telemetry dict as a VIEW over registry gauges (inverse of
  PublishTelemetry) — the single-source-of-truth path GShardDecode uses."""
  snap = registry.Snapshot()
  return GShardTelemetry(
      **{k: snap[prefix + k] for k in GSHARD_TELEMETRY_KEYS})


# -- compiled-step-program census ---------------------------------------------

# Names under which serving surfaces register per-step compiled programs
# with observe.CompileLog. "ragged" is the unified single-program step;
# decode/mixed/spec_verify are the legacy trio (step_mode='legacy').
# Draft programs deliberately don't count: the census answers "how many
# distinct shapes does one serving iteration dispatch through".
STEP_PROGRAM_NAMES = frozenset({"ragged", "decode", "mixed", "spec_verify"})

# The census key both serving surfaces expose: engine
# Stats()["compile"]["step_programs"] and GShardDecode telemetry's
# "step_programs" (2 per length bucket there — prefill + sample).
COMPILE_CENSUS_KEY = "step_programs"


# -- sub-surface key sets ----------------------------------------------------

# serving/scheduler.py Scheduler.Stats(). The SLO block (scheduler_mode
# onward) is all-zeros/'fifo' on legacy schedulers; queue_depth_high is
# the router's class-aware load signal ("scheduler/queue_depth_high" in
# registry snapshots: parked work ABOVE the default priority class).
SCHEDULER_STATS_KEYS = frozenset({
    "slots", "slots_live", "slots_prefill", "slots_live_peak", "queue_depth",
    "admitted", "finished", "cancelled", "rejected_overlong",
    "needs_kv_pages", "prefix_ordered_admissions", "width_clamps",
    "scheduler_mode", "preemptions", "restores", "preempted_queued",
    "quota_rejections", "spilled_pages", "restored_pages", "host_bytes",
    "queue_depth_high",
})

# serving/kv_cache.py PageAllocator.Stats() (page_bytes/pool_bytes only
# when the engine priced the pool via its KV census)
KV_PAGES_REQUIRED = frozenset({
    "num_pages", "page_size", "in_use", "free", "utilization",
    "peak_in_use", "num_sequences", "rolled_back_tokens", "shared_pages",
})
KV_PAGES_OPTIONAL = frozenset({"page_bytes", "pool_bytes"})

# serving/prefix_cache.py PrefixCache.Stats() — present on BOTH serving
# surfaces (engine Stats() section + GShardDecode telemetry key); surfaces
# without a cache report DisabledPrefixCacheStats().
PREFIX_CACHE_STATS_KEYS = frozenset({
    "enabled", "hits", "misses", "hit_tokens", "evictions", "cow_copies",
    "cached_pages", "cached_tokens", "stale_pages", "refreshed_pages",
})


def DisabledPrefixCacheStats() -> dict:
  """The prefix_cache section a surface WITHOUT a cache reports — same
  key set, all-zero counters, enabled=False."""
  out = {k: 0 for k in sorted(PREFIX_CACHE_STATS_KEYS)}
  out["enabled"] = False
  return out

# serving/router.py PrefixRouter.Stats() — the `router/*` registry section
# a fleet front-end exports. shadow_* describe the router-side radix
# index of what it has routed where; the *_routed counters partition
# requests_routed by why the chosen replica won (session pin, shadow
# prefix score, pure load balance).
ROUTER_STATS_KEYS = frozenset({
    "requests_routed", "pinned_routed", "prefix_routed", "balanced_routed",
    "rerouted_down", "sessions_pinned", "shadow_nodes", "shadow_evictions",
    "priority_routed",
})

# serving/fleet.py ServingFleet.Stats() — fleet-level view over N replica
# engines; `router` nests the ROUTER_STATS_KEYS dict above.
FLEET_STATS_KEYS = frozenset({
    "policy", "disaggregated", "replicas", "replicas_up", "replicas_down",
    "requests", "failovers", "resubmitted_requests",
    "handoffs", "handoff_pages", "handoff_fallbacks", "theta_swaps",
    "priority_requests", "quota_rejections",
    "router",
})

# observe/trace.py TraceRecorder.Stats()
TRACE_STATS_KEYS = frozenset({
    "events_emitted", "events_buffered", "events_dropped",
    "requests_open", "requests_completed",
})


# -- HTTP status endpoints (observe/export.py) --------------------------------

# Every path a StatusServer serves. The server builds its route table FROM
# this tuple (and asserts the two match), so a new endpoint lands here or
# the server refuses to start.
ENDPOINT_PATHS = ("/metrics", "/statusz", "/traces", "/healthz")

# /statusz JSON document: top-level keys. `snapshot`/`describe` are the
# owning registry's Snapshot()/Describe(); `stats` is the owner's richer
# structured view (engine Stats() with compile records, executor program
# records) or None; `build` is BuildInfo() below.
STATUSZ_REQUIRED = frozenset({"name", "build", "snapshot", "describe",
                              "stats"})
STATUSZ_OPTIONAL = frozenset({"watchdog"})

# observe/export.py BuildInfo() — the jax/config facts /statusz carries.
BUILD_INFO_KEYS = frozenset({
    "jax_version", "jaxlib_version", "backend", "device_count",
    "device_kind", "process_index", "process_count",
})


def ValidateStatusz(doc: dict) -> dict:
  """Asserts a /statusz document matches the schema; returns it unchanged."""
  keys = set(doc)
  missing = STATUSZ_REQUIRED - keys
  assert not missing, f"/statusz missing schema keys: {sorted(missing)}"
  unknown = keys - STATUSZ_REQUIRED - STATUSZ_OPTIONAL
  assert not unknown, f"/statusz keys not in schema: {sorted(unknown)}"
  bkeys = set(doc["build"])
  bmissing = BUILD_INFO_KEYS - bkeys
  assert not bmissing, f"/statusz build missing keys: {sorted(bmissing)}"
  return doc


# -- goodput / badput accounting (observe/goodput.py) -------------------------

# Wall-time classification buckets. `step` is the productive bucket;
# everything else is badput; `other` is the residual (wall − accounted), so
# the buckets always sum to ~wall time.
GOODPUT_BUCKETS = ("step", "compile", "checkpoint_save", "checkpoint_restore",
                   "eval", "infeed_wait", "recovery", "other")
GOODPUT_PRODUCTIVE = frozenset({"step"})

# observe/goodput.py GoodputTracker.Stats() — the `goodput/*` section.
GOODPUT_STATS_KEYS = frozenset(
    {f"{b}_s" for b in GOODPUT_BUCKETS} | {"wall_s", "productive_ratio"})


# -- stall watchdog (observe/watchdog.py) -------------------------------------

# Trip taxonomy: no heartbeat within k×EMA step time, a step-time
# regression, or serving queue growth without retirement.
WATCHDOG_TRIP_KINDS = ("no_heartbeat", "step_regression", "queue_stall")

# observe/watchdog.py StallWatchdog.Stats() — the `watchdog/*` section.
WATCHDOG_STATS_KEYS = frozenset({
    "healthy", "beats", "trips", "tripped", "last_beat_age_s",
    "step_ema_s", "capture_armed",
})
