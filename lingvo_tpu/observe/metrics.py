"""MetricsRegistry: counters / gauges / histograms with snapshot+delta.

A deliberately tiny, dependency-free registry the whole framework publishes
through (ISSUE 12 pillar 2). Design points:

- **Kinds.** `Counter` is monotonic (`Inc`), `Gauge` holds the latest value
  (`Set` — numeric by convention, but config facts like a dtype string are
  allowed; consumers that need numbers filter, see
  `SummaryWriter.FromRegistry`). `GaugeFn` registers a zero-arg callback
  evaluated lazily at snapshot time; re-registering the same name REPLACES
  the callback, so per-Run throwaway objects (eval infeeds) don't leak
  stale providers. `SectionFn` is a GaugeFn returning a whole dict, spliced
  into the snapshot as `section/key` — one callback per stats provider
  (scheduler, allocator) instead of one lambda per field. `Histogram`
  buckets observations against fixed bounds.
- **Snapshot + delta.** `Snapshot()` returns one flat plain-python dict —
  an atomic, consistent read under the registry lock. `Delta(prev)`
  subtracts a previous snapshot: counters and histogram counts are
  monotonic so deltas are rates over the interval; gauges report their
  current value (a delta of a level is meaningless).
- **Locking.** One lock per registry; every mutation is a few Python ops
  under it, cheap enough for per-token increments on the serving hot path
  (the bench's tracing-overhead criterion covers this).

Engines default to their OWN registry instance (test isolation, and a
multi-engine process keeps replicas separate); train-side programs publish
to the process-global `Default()` registry.
"""

from __future__ import annotations

import bisect
import threading

# Default histogram bounds: latency-ish seconds, log-spaced. Callers with
# different units pass their own bounds.
DEFAULT_BOUNDS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                  0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class Counter:
  """Monotonic counter. Mutate via Inc(); read via .value."""

  __slots__ = ("name", "value", "_lock")

  def __init__(self, name: str, lock):
    self.name = name
    self.value = 0
    self._lock = lock

  def Inc(self, n: int = 1):
    assert n >= 0, f"counter {self.name} is monotonic (Inc({n}))"
    with self._lock:
      self.value += n


class Gauge:
  """Latest-value gauge (numeric by convention; config facts allowed)."""

  __slots__ = ("name", "value", "_lock")

  def __init__(self, name: str, lock):
    self.name = name
    self.value = None
    self._lock = lock

  def Set(self, value):
    with self._lock:
      self.value = value


class Histogram:
  """Fixed-bounds histogram: counts[i] = observations <= bounds[i];
  counts[-1] = overflow. Snapshot form: {count, sum, mean, bounds,
  counts}."""

  __slots__ = ("name", "bounds", "counts", "total", "sum", "_lock")

  def __init__(self, name: str, lock, bounds=DEFAULT_BOUNDS):
    assert list(bounds) == sorted(bounds), bounds
    self.name = name
    self.bounds = tuple(float(b) for b in bounds)
    self.counts = [0] * (len(self.bounds) + 1)
    self.total = 0
    self.sum = 0.0
    self._lock = lock

  def Observe(self, value):
    v = float(value)
    with self._lock:
      self.counts[bisect.bisect_left(self.bounds, v)] += 1
      self.total += 1
      self.sum += v

  def _SnapshotLocked(self) -> dict:
    return {
        "count": self.total,
        "sum": self.sum,
        "mean": self.sum / self.total if self.total else 0.0,
        "bounds": list(self.bounds),
        "counts": list(self.counts),
    }


class MetricsRegistry:
  """Named metrics + atomic flat snapshots (module docstring)."""

  def __init__(self, name: str = ""):
    self.name = name
    self._lock = threading.RLock()
    self._counters: dict[str, Counter] = {}
    self._gauges: dict[str, Gauge] = {}
    self._gauge_fns: dict[str, object] = {}
    self._section_fns: dict[str, object] = {}
    self._histograms: dict[str, Histogram] = {}

  # -- registration (get-or-create; re-registration replaces callbacks) ----

  def Counter(self, name: str) -> Counter:
    with self._lock:
      if name not in self._counters:
        self._counters[name] = Counter(name, self._lock)
      return self._counters[name]

  def Gauge(self, name: str) -> Gauge:
    with self._lock:
      if name not in self._gauges:
        self._gauges[name] = Gauge(name, self._lock)
      return self._gauges[name]

  def GaugeFn(self, name: str, fn):
    """Lazy gauge: `fn()` evaluated at snapshot time. Replaces by name."""
    with self._lock:
      self._gauge_fns[name] = fn

  def SectionFn(self, section: str, fn):
    """Lazy dict provider: `fn()` items land as `section/key`. Replaces
    by name — a new provider instance (fresh engine run, throwaway eval
    infeed) simply takes the section over."""
    with self._lock:
      self._section_fns[section] = fn

  def Histogram(self, name: str, bounds=DEFAULT_BOUNDS) -> Histogram:
    with self._lock:
      if name not in self._histograms:
        self._histograms[name] = Histogram(name, self._lock, bounds)
      return self._histograms[name]

  def Describe(self) -> dict:
    """{name: kind} for every registered metric (sections as declared)."""
    with self._lock:
      out = {n: "counter" for n in self._counters}
      out.update({n: "gauge" for n in self._gauges})
      out.update({n: "gauge_fn" for n in self._gauge_fns})
      out.update({n: "section" for n in self._section_fns})
      out.update({n: "histogram" for n in self._histograms})
      return out

  # -- reads ----------------------------------------------------------------

  def Snapshot(self) -> dict:
    """One flat, mutually-consistent dict of every metric's current value.

    Callback (GaugeFn/SectionFn) errors surface as the exception string
    rather than killing the snapshot — stats must never take down a serving
    loop."""
    with self._lock:
      out = {}
      for n, c in self._counters.items():
        out[n] = c.value
      for n, g in self._gauges.items():
        out[n] = g.value
      for n, fn in self._gauge_fns.items():
        try:
          out[n] = fn()
        except Exception as e:  # noqa: BLE001
          out[n] = f"<error: {e}>"
      for section, fn in self._section_fns.items():
        try:
          for k, v in fn().items():
            out[f"{section}/{k}"] = v
        except Exception as e:  # noqa: BLE001
          out[section] = f"<error: {e}>"
      for n, h in self._histograms.items():
        out[n] = h._SnapshotLocked()
      return out

  def Delta(self, prev: dict) -> dict:
    """Current snapshot minus `prev` (a previous Snapshot() return).

    Counters subtract (monotonic ⇒ the interval's increment); histograms
    subtract count/sum/bucket-counts; gauges/sections report their current
    value. Metrics absent from `prev` report their full current value."""
    cur = self.Snapshot()
    with self._lock:
      counter_names = set(self._counters)
      hist_names = set(self._histograms)
    out = {}
    for n, v in cur.items():
      if n in counter_names and isinstance(prev.get(n), (int, float)):
        out[n] = v - prev[n]
      elif n in hist_names and isinstance(prev.get(n), dict):
        p = prev[n]
        out[n] = {
            "count": v["count"] - p.get("count", 0),
            "sum": v["sum"] - p.get("sum", 0.0),
            "bounds": v["bounds"],
            "counts": [a - b for a, b in
                       zip(v["counts"], p.get("counts", [0] * len(
                           v["counts"])))],
        }
        out[n]["mean"] = (out[n]["sum"] / out[n]["count"]
                          if out[n]["count"] else 0.0)
      else:
        out[n] = v
    return out


def HistogramQuantiles(snap: dict, qs=(0.5, 0.99)) -> dict:
  """Bucket-interpolated quantiles from a histogram snapshot dict.

  Linear interpolation inside the bucket the quantile rank lands in (the
  Prometheus `histogram_quantile` rule): the first bucket interpolates
  from 0, and ranks in the overflow bucket clamp to the highest finite
  bound (there is no upper edge to interpolate toward). Returns
  {q: value}; all zeros for an empty histogram."""
  total = snap["count"]
  bounds, counts = snap["bounds"], snap["counts"]
  out = {}
  for q in qs:
    if total <= 0 or not bounds:
      out[q] = 0.0
      continue
    rank = q * total
    cum = 0
    value = bounds[-1]   # default: rank fell in the overflow bucket
    for i, n in enumerate(counts[:len(bounds)]):
      if cum + n >= rank and n > 0:
        lo = bounds[i - 1] if i > 0 else 0.0
        value = lo + (bounds[i] - lo) * (rank - cum) / n
        break
      cum += n
    out[q] = value
  return out


_DEFAULT_LOCK = threading.Lock()
_DEFAULT: MetricsRegistry | None = None


def Default() -> MetricsRegistry:
  """The process-global registry (train/eval programs, infeeds)."""
  global _DEFAULT
  with _DEFAULT_LOCK:
    if _DEFAULT is None:
      _DEFAULT = MetricsRegistry("default")
    return _DEFAULT
