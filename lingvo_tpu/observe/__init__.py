"""lingvo_tpu.observe: the framework-wide observability layer (ISSUE 12).

Three pillars, one import:

- `MetricsRegistry` / `Default()` (observe/metrics.py): counters, gauges,
  histograms with atomic snapshots and monotonic-delta semantics. Serving
  engines own per-instance registries; train/eval programs and infeeds
  publish to the process-global default.
- `TraceRecorder` (observe/trace.py): per-request serving lifecycle traces
  in a lock-cheap ring buffer, derived per-request metrics, and Chrome
  trace-event JSON export (Perfetto-openable; one row per decode slot).
- `ProfileWindow` / `CompileLog` (observe/profile.py): on-demand
  jax.profiler trace windows (no-op when unsupported) and one-shot
  per-compiled-program records (compile wall time, XLA memory plan,
  donation set).

`observe.schema` declares every telemetry key set once — engine `Stats()`
and GShardDecode telemetry are views generated from it.
"""

from lingvo_tpu.observe import schema  # noqa: F401
from lingvo_tpu.observe.metrics import (  # noqa: F401
    DEFAULT_BOUNDS, Default, MetricsRegistry)
from lingvo_tpu.observe.profile import (  # noqa: F401
    CompileInfo, CompileLog, ProfileWindow, ProfilerSupported)
from lingvo_tpu.observe.trace import (  # noqa: F401
    RequestTrace, TraceRecorder)
