"""lingvo_tpu.observe: the framework-wide observability layer.

The in-process pillars (ISSUE 12), one import:

- `MetricsRegistry` / `Default()` (observe/metrics.py): counters, gauges,
  histograms with atomic snapshots and monotonic-delta semantics. Serving
  engines own per-instance registries; train/eval programs and infeeds
  publish to the process-global default.
- `TraceRecorder` (observe/trace.py): per-request serving lifecycle traces
  in a lock-cheap ring buffer, derived per-request metrics, and Chrome
  trace-event JSON export (Perfetto-openable; one row per decode slot).
- `ProfileWindow` / `CompileLog` (observe/profile.py): on-demand
  jax.profiler trace windows (no-op when unsupported) and one-shot
  per-compiled-program records (compile wall time, XLA memory plan,
  donation set).

And the fleet-facing layer (ISSUE 13) on top:

- `StatusServer` / `PrometheusText` (observe/export.py): a stdlib HTTP
  thread per process serving /metrics, /statusz, /traces, /healthz.
- `GoodputTracker` / `PublishMfu` (observe/goodput.py): wall-time
  goodput/badput buckets + the `train/mfu` lazy gauge.
- `StallWatchdog` (observe/watchdog.py): heartbeat liveness, stall trip
  taxonomy, automatic ProfileWindow flight capture.
- `observe.aggregate`: scrape-and-merge across N replica endpoints.

`observe.schema` declares every telemetry key set once — engine `Stats()`,
GShardDecode telemetry, endpoint paths, /statusz keys, goodput buckets and
watchdog stats are views generated from it.
"""

from lingvo_tpu.observe import aggregate  # noqa: F401
from lingvo_tpu.observe import schema  # noqa: F401
from lingvo_tpu.observe.export import (  # noqa: F401
    BuildInfo, MetricName, PrometheusText, StatusServer)
from lingvo_tpu.observe.goodput import (  # noqa: F401
    GoodputTracker, PeakFlopsPerDevice, PublishMfu)
from lingvo_tpu.observe.metrics import (  # noqa: F401
    DEFAULT_BOUNDS, Default, HistogramQuantiles, MetricsRegistry)
from lingvo_tpu.observe.profile import (  # noqa: F401
    CompileInfo, CompileLog, ProfileWindow, ProfilerSupported)
from lingvo_tpu.observe.trace import (  # noqa: F401
    RequestTrace, TraceRecorder)
from lingvo_tpu.observe.watchdog import StallWatchdog  # noqa: F401
