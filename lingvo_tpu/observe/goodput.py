"""Goodput/badput wall-time accounting + honest MFU publication.

Two questions a fleet dashboard asks of every trainer that this module
answers from the process-global registry:

- **Where did the wall time go?** `GoodputTracker` classifies elapsed
  time into the `schema.GOODPUT_BUCKETS`: `step` (productive device
  loops) vs badput — `compile`, `checkpoint_save`/`_restore`, `eval`,
  `infeed_wait`, `recovery` (transient-failure retries) — plus the
  residual `other` (wall − accounted), so the buckets always sum to
  ~wall time. Hooks are context managers (`with tracker.Track("eval")`)
  placed in the train/eval programs and the executor; the tracker
  publishes everything as a lazy `goodput/*` registry section, so the
  numbers are current at every scrape without a publish step.

  Under the PIPELINED executor (TrainProgram.pipeline_depth >= 1) the
  train attribution moves from Run-wall windows to loop-COMPLETION
  intervals (`_AttributePipelinedLoop`): device loops execute serially
  however far ahead the host dispatches, so completion-to-completion
  spans partition the wall; each span minus the infeed wait and compile
  seconds that accrued inside it lands in `step`. `checkpoint_save` then
  counts only the caller-side snapshot fence of an ACTUAL async write —
  a cadence no-op contributes zero — so a shrinking `other_s` +
  `checkpoint_save_s` against a fixed workload is exactly the badput the
  pipeline reclaimed (docs/pipelined_executor.md).

- **How fast relative to the hardware?** `PublishMfu` wires a
  `train/mfu` lazy gauge: the train-step executable's XLA cost analysis
  (flops/step, recorded by the programs' CompileLog/_RecordCompile or a
  lazy `.lower().cost_analysis()` — no second compile either way) × the
  `StepRateTracker` step-rate gauge ÷ nominal peak FLOP/s of the
  attached devices. Peak numbers are per-chip dense-matmul nominals; on
  CPU the denominator is a placeholder, so treat CPU MFU as relative
  only (the flops numerator and the published `train/flops_per_step`
  are exact everywhere).
"""

from __future__ import annotations

import contextlib
import threading
import time

import jax

from lingvo_tpu.observe import schema

# Nominal peak dense-matmul FLOP/s per chip by device-kind substring
# (bf16 numbers for TPUs). Matched case-insensitively, first hit wins;
# order newest-first so "v5p" matches before "v5".
PEAK_FLOPS_BY_KIND = (
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
    ("cpu", 1e11),   # placeholder: CPU MFU is relative, not absolute
)
DEFAULT_PEAK_FLOPS = 100e12


def PeakFlopsPerDevice(device_kind: str | None = None) -> float:
  """Nominal per-chip peak FLOP/s for a device kind (default: device 0)."""
  if device_kind is None:
    devs = jax.devices()
    device_kind = devs[0].device_kind if devs else ""
  kind = (device_kind or "").lower()
  for sub, peak in PEAK_FLOPS_BY_KIND:
    if sub in kind:
      return peak
  return DEFAULT_PEAK_FLOPS


class GoodputTracker:
  """Accumulates wall time into goodput/badput buckets (module docstring).

  clock: injectable monotonic-seconds source (tests). Registering with a
  registry publishes `Stats()` as the lazy `goodput/*` section. One
  tracker per process is the normal shape (`Get()`); programs and the
  executor all feed the same one so buckets partition ONE wall clock.
  """

  def __init__(self, registry=None, clock=time.perf_counter,
               section: str = "goodput"):
    self._clock = clock
    self._lock = threading.Lock()
    self._t0 = clock()
    self._buckets = {b: 0.0 for b in schema.GOODPUT_BUCKETS if b != "other"}
    if registry is not None:
      registry.SectionFn(section, self.Stats)

  def Add(self, bucket: str, seconds: float):
    assert bucket in self._buckets, (
        f"unknown goodput bucket {bucket!r}; schema.GOODPUT_BUCKETS = "
        f"{schema.GOODPUT_BUCKETS}")
    with self._lock:
      self._buckets[bucket] += max(float(seconds), 0.0)

  def CompileSeconds(self) -> float:
    """Monotonic total of the compile bucket — callers snapshot it around
    a window to find how much compilation happened inside."""
    with self._lock:
      return self._buckets["compile"]

  def Snapshot(self) -> dict:
    """Raw bucket totals {bucket: seconds} at this instant — a cheap
    before/after basis for windowed deltas (bench sections, tests)
    without the wall/residual derivation Stats() adds."""
    with self._lock:
      return dict(self._buckets)

  @contextlib.contextmanager
  def Track(self, bucket: str):
    """Attributes the wall time of the enclosed block to `bucket`."""
    t0 = self._clock()
    try:
      yield
    finally:
      self.Add(bucket, self._clock() - t0)

  @contextlib.contextmanager
  def TrackExcludingCompile(self, bucket: str):
    """Like Track, minus any compile seconds the jax.monitoring listener
    attributed during the block — lazy jit compiles inside a step/eval
    window must not be double-counted as productive (or eval) time."""
    t0 = self._clock()
    c0 = self.CompileSeconds()
    try:
      yield
    finally:
      elapsed = self._clock() - t0
      compiled = self.CompileSeconds() - c0
      self.Add(bucket, max(elapsed - compiled, 0.0))

  def Reset(self):
    with self._lock:
      self._t0 = self._clock()
      for b in self._buckets:
        self._buckets[b] = 0.0

  def Stats(self) -> dict:
    """`goodput/*` section: per-bucket seconds + wall + productive ratio.
    `other_s` is the residual (clamped at 0), so the buckets sum to wall —
    up to the slight compile-event overcount noted above."""
    with self._lock:
      wall = max(self._clock() - self._t0, 0.0)
      out = {f"{b}_s": round(v, 6) for b, v in self._buckets.items()}
      accounted = sum(self._buckets.values())
      productive = sum(self._buckets[b] for b in schema.GOODPUT_PRODUCTIVE)
    out["other_s"] = round(max(wall - accounted, 0.0), 6)
    out["wall_s"] = round(wall, 6)
    out["productive_ratio"] = round(productive / wall, 6) if wall else 0.0
    assert set(out) == set(schema.GOODPUT_STATS_KEYS)
    return out


_GET_LOCK = threading.Lock()
_TRACKER: GoodputTracker | None = None

# duration events covering the whole compile pipeline: jaxpr trace,
# MLIR lowering, XLA backend compile — they fire on every cache miss,
# AOT or lazy, so the listener sees each compile exactly once. Inner-jit
# trace/lowering events nest inside the outer jit's, so the compile
# bucket can overcount by the nested fraction (<1% in practice): the
# buckets sum to ~wall, not exactly wall.
_COMPILE_EVENT_PREFIX = "/jax/core/compile/"


def _OnJaxEvent(event: str, duration_s: float, **_):
  """jax.monitoring duration listener feeding the global tracker. This is
  how lazily-jitted programs (no AOT CompileLog) still land their compile
  wall in the compile bucket instead of hiding inside a step window."""
  if event.startswith(_COMPILE_EVENT_PREFIX) and _TRACKER is not None:
    _TRACKER.Add("compile", duration_s)


def Get() -> GoodputTracker:
  """The process-global tracker, registered on observe.Default()."""
  global _TRACKER
  with _GET_LOCK:
    if _TRACKER is None:
      from lingvo_tpu.observe import metrics as metrics_lib
      _TRACKER = GoodputTracker(registry=metrics_lib.Default())
      try:
        jax.monitoring.register_event_duration_secs_listener(_OnJaxEvent)
      except Exception:  # noqa: BLE001 - accounting must never break jax
        pass
    return _TRACKER


def PublishMfu(registry, flops_per_step: float,
               rate_gauge: str = "train/train_steps_per_second",
               name: str = "train/mfu",
               peak_flops: float | None = None):
  """Wires `train/mfu` as a lazy gauge over the step-rate gauge.

  mfu = flops_per_step × steps_per_second / (per-device peak × #devices).
  Reading the rate gauge's `.value` inside the GaugeFn is safe: the
  registry lock is an RLock and the snapshot already holds it. Also
  publishes the inputs (`train/flops_per_step`, `train/peak_flops`) so a
  scraper can recompute with its own peak numbers."""
  if peak_flops is None:
    peak_flops = PeakFlopsPerDevice() * max(jax.device_count(), 1)
  flops = float(flops_per_step)
  registry.Gauge("train/flops_per_step").Set(flops)
  registry.Gauge("train/peak_flops").Set(float(peak_flops))
  rate_g = registry.Gauge(rate_gauge)

  def _Mfu():
    rate = rate_g.value
    if not isinstance(rate, (int, float)) or rate <= 0 or peak_flops <= 0:
      return 0.0
    return flops * rate / peak_flops

  registry.GaugeFn(name, _Mfu)
