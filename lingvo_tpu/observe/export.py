"""Fleet-facing status endpoints: /metrics, /statusz, /traces, /healthz.

Everything PR 12 built is trapped in-process — nothing can be scraped and
no replica can see another. This module opens the door with zero new
dependencies: `StatusServer` runs a stdlib `ThreadingHTTPServer` on a
daemon thread per process and serves

    /metrics   Prometheus text exposition of a MetricsRegistry snapshot
               (counters, gauges, histograms with cumulative buckets;
               string config facts as `_info{value="..."} 1` series)
    /statusz   one JSON document: registry snapshot + Describe() kinds +
               the owner's structured stats (engine Stats() with compile
               records) + jax/build facts — the scrape target
               observe/aggregate.py merges across replicas
    /traces    the existing Chrome trace export (Perfetto-openable)
    /healthz   watchdog-derived liveness: 200 while healthy, 503 after a
               trip. The CHECK runs at scrape time on the HTTP thread —
               a hung step loop cannot self-report, so the scraper's
               thread is the one that must evaluate the trip conditions.

The route table is built from `schema.ENDPOINT_PATHS` and the /statusz
document is validated by `schema.ValidateStatusz`, so endpoint keys can't
drift from the shared schema. Serving stats must never take the service
down: handler errors return 500 with the error string, and the server
binds 127.0.0.1 by default (expose deliberately via host="0.0.0.0").
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import jax

from lingvo_tpu.observe import schema

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def MetricName(name: str) -> str:
  """Registry name -> valid Prometheus metric name (`serving/ttft_s` ->
  `serving_ttft_s`); a leading digit gets an underscore prefix."""
  out = _NAME_RE.sub("_", name)
  if out and out[0].isdigit():
    out = "_" + out
  return out


def _LabelValue(v) -> str:
  return str(v).replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _Num(v) -> str:
  """Prometheus sample value formatting (ints stay integral)."""
  if isinstance(v, bool):
    return "1" if v else "0"
  if isinstance(v, int):
    return str(v)
  return repr(float(v))


def KindOf(name: str, describe: dict) -> str:
  """Metric kind for a SNAPSHOT key: exact Describe() entry, else the
  section prefix (`scheduler/queue_depth` -> section `scheduler` ->
  gauge), else gauge."""
  kind = describe.get(name)
  if kind is not None:
    return "gauge" if kind in ("gauge_fn", "section") else kind
  head = name.split("/", 1)[0]
  if describe.get(head) == "section":
    return "gauge"
  return "gauge"


def _IsHistogramSnapshot(v) -> bool:
  return isinstance(v, dict) and "counts" in v and "bounds" in v


def PrometheusText(snapshot: dict, describe: Optional[dict] = None) -> str:
  """A MetricsRegistry Snapshot() as Prometheus text exposition (v0.0.4).

  Numeric values emit as their Describe() kind (counter/gauge); bools as
  0/1 gauges; strings (config facts, `<error: ...>` callback failures) as
  `<name>_info{value="..."} 1`; histogram snapshot dicts as cumulative
  `_bucket{le=...}` series + `_sum` + `_count`; anything else (lists,
  nested dicts) is skipped — it belongs to /statusz, not /metrics."""
  describe = describe or {}
  lines = []
  for name in sorted(snapshot):
    v = snapshot[name]
    mname = MetricName(name)
    if _IsHistogramSnapshot(v):
      lines.append(f"# TYPE {mname} histogram")
      cum = 0
      for bound, n in zip(v["bounds"], v["counts"]):
        cum += n
        lines.append(f'{mname}_bucket{{le="{_Num(bound)}"}} {cum}')
      lines.append(f'{mname}_bucket{{le="+Inf"}} {v["count"]}')
      lines.append(f"{mname}_sum {_Num(v['sum'])}")
      lines.append(f"{mname}_count {v['count']}")
      continue
    if isinstance(v, bool) or isinstance(v, (int, float)):
      lines.append(f"# TYPE {mname} {KindOf(name, describe)}")
      lines.append(f"{mname} {_Num(v)}")
    elif isinstance(v, str):
      lines.append(f"# TYPE {mname}_info gauge")
      lines.append(f'{mname}_info{{value="{_LabelValue(v)}"}} 1')
    elif v is None:
      lines.append(f"# TYPE {mname}_info gauge")
      lines.append(f'{mname}_info{{value="none"}} 1')
    # lists / nested dicts: /statusz carries them
  return "\n".join(lines) + "\n"


def BuildInfo() -> dict:
  """The jax/config facts /statusz carries (schema.BUILD_INFO_KEYS)."""
  import jaxlib
  devs = jax.devices()
  return {
      "jax_version": jax.__version__,
      "jaxlib_version": getattr(jaxlib, "__version__", "unknown"),
      "backend": jax.default_backend(),
      "device_count": jax.device_count(),
      "device_kind": devs[0].device_kind if devs else "unknown",
      "process_index": jax.process_index(),
      "process_count": jax.process_count(),
  }


def _JsonDefault(o):
  """numpy scalars/arrays and anything else stringify instead of raising —
  a weird Stats() value must not 500 the whole /statusz page."""
  try:
    import numpy as np
    if isinstance(o, np.ndarray):
      return o.tolist()
    if isinstance(o, np.generic):
      return o.item()
  except Exception:  # noqa: BLE001
    pass
  return str(o)


class _Httpd(ThreadingHTTPServer):
  daemon_threads = True
  allow_reuse_address = True
  status: "StatusServer" = None


class _Handler(BaseHTTPRequestHandler):

  def log_message(self, *args):  # noqa: D102 - silence per-request stderr
    pass

  def do_GET(self):  # noqa: N802 - http.server API
    status = self.server.status
    path = self.path.split("?", 1)[0]
    fn = status._routes.get(path)
    if fn is None:
      self._Reply(404, "text/plain; charset=utf-8",
                  "not found; endpoints: "
                  + ", ".join(schema.ENDPOINT_PATHS) + "\n")
      return
    try:
      code, ctype, body = fn()
    except Exception as e:  # noqa: BLE001 - stats must not kill the server
      code, ctype, body = 500, "text/plain; charset=utf-8", (
          f"<error: {type(e).__name__}: {e}>\n")
    self._Reply(code, ctype, body)

  def _Reply(self, code: int, ctype: str, body: str):
    data = body.encode("utf-8")
    try:
      self.send_response(code)
      self.send_header("Content-Type", ctype)
      self.send_header("Content-Length", str(len(data)))
      self.end_headers()
      self.wfile.write(data)
    except (BrokenPipeError, ConnectionResetError):
      pass  # scraper went away mid-reply


class StatusServer:
  """A per-process status HTTP server over one MetricsRegistry.

  port=0 binds an ephemeral port (tests, multi-engine processes); the
  bound port is `self.port` and `Url(path)` builds scrape URLs.
  statusz_fn: zero-arg callable returning the owner's structured stats
  (engine `Stats()`), spliced into /statusz as `stats`. trace: a
  TraceRecorder for /traces (404 without one). watchdog: a StallWatchdog
  — /healthz runs its `Check()` at scrape time and flips to 503 on a
  trip (200 `{"healthy": true, "watchdog": false}` without one).
  """

  def __init__(self, port: int = 0, host: str = "127.0.0.1", *,
               registry=None, name: str = "", statusz_fn=None, trace=None,
               watchdog=None):
    self._registry = registry
    self.name = name
    self._statusz_fn = statusz_fn
    self._trace = trace
    self._watchdog = watchdog
    self._routes = {
        "/metrics": self._Metrics,
        "/statusz": self._Statusz,
        "/traces": self._Traces,
        "/healthz": self._Healthz,
    }
    assert set(self._routes) == set(schema.ENDPOINT_PATHS), (
        "route table drifted from schema.ENDPOINT_PATHS")
    self._httpd = _Httpd((host, port), _Handler)
    self._httpd.status = self
    self.host = self._httpd.server_address[0]
    self.port = self._httpd.server_address[1]
    self._thread: Optional[threading.Thread] = None

  def Start(self) -> "StatusServer":
    if self._thread is None:
      self._thread = threading.Thread(
          target=self._httpd.serve_forever, daemon=True,
          name=f"status-server-{self.name or self.port}")
      self._thread.start()
    return self

  def Stop(self):
    if self._thread is not None:
      self._httpd.shutdown()
      self._thread.join(timeout=5.0)
      self._thread = None
    self._httpd.server_close()

  def Url(self, path: str = "/metrics") -> str:
    return f"http://{self.host}:{self.port}{path}"

  # -- endpoint bodies (run on the HTTP threads) ------------------------------

  def _Metrics(self):
    if self._registry is None:
      return 404, "text/plain; charset=utf-8", "no registry\n"
    body = PrometheusText(self._registry.Snapshot(),
                          self._registry.Describe())
    return 200, "text/plain; version=0.0.4; charset=utf-8", body

  def Statusz(self) -> dict:
    """The /statusz document (schema-validated), also used in-process."""
    doc = {
        "name": self.name,
        "build": BuildInfo(),
        "snapshot": (self._registry.Snapshot()
                     if self._registry is not None else {}),
        "describe": (self._registry.Describe()
                     if self._registry is not None else {}),
        "stats": self._statusz_fn() if self._statusz_fn is not None else None,
    }
    if self._watchdog is not None:
      doc["watchdog"] = self._watchdog.Stats()
    return schema.ValidateStatusz(doc)

  def _Statusz(self):
    body = json.dumps(self.Statusz(), default=_JsonDefault, indent=1)
    return 200, "application/json; charset=utf-8", body + "\n"

  def _Traces(self):
    if self._trace is None:
      return 404, "text/plain; charset=utf-8", "tracing disabled\n"
    body = json.dumps(self._trace.ChromeTrace(), default=_JsonDefault)
    return 200, "application/json; charset=utf-8", body + "\n"

  def _Healthz(self):
    if self._watchdog is None:
      body = json.dumps({"healthy": True, "watchdog": False})
      return 200, "application/json; charset=utf-8", body + "\n"
    stats = self._watchdog.Check()
    code = 200 if stats["healthy"] else 503
    return code, "application/json; charset=utf-8", (
        json.dumps(stats, default=_JsonDefault) + "\n")
