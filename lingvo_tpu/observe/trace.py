"""Per-request serving traces: ring-buffer recorder + Chrome trace export.

ISSUE 12 pillar 1. The serving engine emits lifecycle events — submit →
admit (slot + pages) → prefill chunk(s) → per-token decode / spec-verify
with accepted length → rollback → retire (eos/length/cancelled) — into a
`TraceRecorder`. Two storage tiers make it lock-cheap AND lossless where
it matters:

- a bounded **ring buffer** of raw events (`deque(maxlen=capacity)`):
  constant memory under any load; old events fall off the back.
- a per-request **record** (`RequestTrace`) updated on every event:
  open requests are NEVER evicted, so a request's lifecycle survives any
  amount of ring wraparound (the wraparound-without-loss satellite);
  completed records move to a second bounded deque.

Derived per-request metrics (queue_wait, TTFT, per-output-token latency,
tokens, pages held, spec acceptance) come from the records.
`ChromeTrace()` exports the Chrome trace-event JSON format — open the file
in Perfetto (ui.perfetto.dev) and each decode slot is one row, with every
request's queued/prefill/decode phases as nested duration events and
spec-verify/rollback instants on top. `tools/trace_report.py` turns the
same file into a latency table.

Every Emit is a timestamp + deque append + a few record-field updates
under one lock — no allocation-heavy formatting on the hot path; all
derivation happens at export time.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

# Chrome-trace row used for requests that were never admitted to a slot
# (cancelled while queued). Real slots are tids 0..max_batch-1.
_QUEUE_ONLY_TID = 10**6


class RequestTrace:
  """One request's lifecycle record (timestamps are recorder-clock
  seconds; see TraceRecorder for which event sets which field)."""

  __slots__ = (
      "req_id", "slot", "prompt_tokens", "max_new", "pages",
      "submit_ts", "admit_ts", "first_token_ts", "last_token_ts",
      "retire_ts", "finish_reason", "tokens", "prefill_chunks",
      "prefill_tokens", "spec_cycles", "draft_tokens", "accepted_tokens",
      "rolled_back_tokens", "prefix_hit_tokens",
  )

  def __init__(self, req_id):
    self.req_id = req_id
    self.slot: Optional[int] = None
    self.prompt_tokens = 0
    self.max_new = 0
    self.pages = 0
    self.submit_ts: Optional[float] = None
    self.admit_ts: Optional[float] = None
    self.first_token_ts: Optional[float] = None
    self.last_token_ts: Optional[float] = None
    self.retire_ts: Optional[float] = None
    self.finish_reason: Optional[str] = None
    self.tokens = 0
    self.prefill_chunks = 0
    self.prefill_tokens = 0
    self.spec_cycles = 0
    self.draft_tokens = 0
    self.accepted_tokens = 0
    self.rolled_back_tokens = 0
    self.prefix_hit_tokens = 0

  @property
  def complete(self) -> bool:
    return self.submit_ts is not None and self.retire_ts is not None

  def Metrics(self) -> dict:
    """Derived per-request metrics (None where the phase never happened)."""
    queue_wait = (self.admit_ts - self.submit_ts
                  if self.admit_ts is not None else None)
    ttft = (self.first_token_ts - self.submit_ts
            if self.first_token_ts is not None else None)
    # per-output-token latency over the decode phase (first token lands
    # with the final prefill chunk, so it is excluded from the rate)
    tpot = None
    if self.first_token_ts is not None and self.tokens > 1:
      tpot = ((self.last_token_ts - self.first_token_ts)
              / (self.tokens - 1))
    total = (self.retire_ts - self.submit_ts
             if self.complete else None)
    out = {
        "req_id": self.req_id,
        "slot": self.slot,
        "prompt_tokens": self.prompt_tokens,
        "max_new": self.max_new,
        "tokens": self.tokens,
        "pages": self.pages,
        "finish_reason": self.finish_reason,
        "queue_wait_s": queue_wait,
        "ttft_s": ttft,
        "tpot_s": tpot,
        "total_s": total,
        "prefill_chunks": self.prefill_chunks,
    }
    if self.draft_tokens:
      out["spec_cycles"] = self.spec_cycles
      out["draft_tokens"] = self.draft_tokens
      out["accepted_tokens"] = self.accepted_tokens
      out["spec_acceptance"] = self.accepted_tokens / self.draft_tokens
      out["rolled_back_tokens"] = self.rolled_back_tokens
    if self.prefix_hit_tokens:
      out["prefix_hit_tokens"] = self.prefix_hit_tokens
    return out


class TraceRecorder:
  """Lock-cheap lifecycle recorder (module docstring).

  capacity: raw-event ring size. completed_capacity: retained completed
  request records (oldest evicted first). clock: timestamp source —
  injectable for deterministic tests.
  """

  # event kind -> record update, dispatched in Emit
  KINDS = ("submit", "prefix_hit", "admit", "prefill_chunk", "token",
           "spec_verify", "rollback", "retire")

  def __init__(self, capacity: int = 8192, completed_capacity: int = 4096,
               clock=time.perf_counter):
    import collections
    assert capacity >= 1 and completed_capacity >= 1
    self._clock = clock
    self._lock = threading.Lock()
    self._ring = collections.deque(maxlen=capacity)
    self._open: dict = {}
    self._completed = collections.deque(maxlen=completed_capacity)
    self._emitted = 0
    self.epoch = clock()

  # -- emission (hot path; one lock, no formatting) --------------------------

  def Emit(self, kind: str, req_id, a: int = 0, b: int = 0,
           reason: Optional[str] = None):
    """Records one event. (a, b) are kind-specific small ints:
    submit(prompt_tokens, max_new) · prefix_hit(tokens) ·
    admit(slot, pages) · prefill_chunk(tokens) · token(n) ·
    spec_verify(drafted, accepted) · rollback(tokens) ·
    retire(pages_freed) + reason."""
    ts = self._clock()
    with self._lock:
      self._ring.append((ts, kind, req_id, a, b, reason))
      self._emitted += 1
      rec = self._open.get(req_id)
      if rec is None:
        if kind != "submit":
          return  # unknown/already-retired request: keep the raw event only
        rec = RequestTrace(req_id)
        self._open[req_id] = rec
        rec.submit_ts = ts
        rec.prompt_tokens = a
        rec.max_new = b
      elif kind == "prefix_hit":
        rec.prefix_hit_tokens += a
      elif kind == "admit":
        rec.admit_ts = ts
        rec.slot = a
        rec.pages = b
      elif kind == "prefill_chunk":
        rec.prefill_chunks += 1
        rec.prefill_tokens += a
      elif kind == "token":
        if rec.first_token_ts is None:
          rec.first_token_ts = ts
        rec.last_token_ts = ts
        rec.tokens += a
      elif kind == "spec_verify":
        rec.spec_cycles += 1
        rec.draft_tokens += a
        rec.accepted_tokens += b
      elif kind == "rollback":
        rec.rolled_back_tokens += a
      elif kind == "retire":
        rec.retire_ts = ts
        rec.finish_reason = reason
        del self._open[req_id]
        self._completed.append(rec)

  # convenience emitters (one per lifecycle kind)
  def Submit(self, req_id, prompt_tokens: int = 0, max_new: int = 0):
    self.Emit("submit", req_id, prompt_tokens, max_new)

  def PrefixHit(self, req_id, tokens: int):
    """Prompt tokens served from the prefix cache (between submit and
    admit: the hit is resolved during the admission the request wins)."""
    self.Emit("prefix_hit", req_id, tokens)

  def Admit(self, req_id, slot: int, pages: int = 0):
    self.Emit("admit", req_id, slot, pages)

  def PrefillChunk(self, req_id, tokens: int):
    self.Emit("prefill_chunk", req_id, tokens)

  def Token(self, req_id, n: int = 1):
    self.Emit("token", req_id, n)

  def SpecVerify(self, req_id, drafted: int, accepted: int):
    self.Emit("spec_verify", req_id, drafted, accepted)

  def Rollback(self, req_id, tokens: int):
    self.Emit("rollback", req_id, tokens)

  def Retire(self, req_id, reason: str, pages_freed: int = 0):
    self.Emit("retire", req_id, pages_freed, reason=reason)

  # -- reads -----------------------------------------------------------------

  def Events(self) -> list:
    """Raw ring contents, oldest first: (ts, kind, req_id, a, b, reason)."""
    with self._lock:
      return list(self._ring)

  def Requests(self) -> dict:
    """{req_id: RequestTrace} — open AND retained completed records."""
    with self._lock:
      out = {r.req_id: r for r in self._completed}
      out.update(self._open)
      return out

  def Get(self, req_id) -> Optional[RequestTrace]:
    return self.Requests().get(req_id)

  def PerRequestMetrics(self) -> dict:
    return {rid: rec.Metrics() for rid, rec in self.Requests().items()}

  def Stats(self) -> dict:
    with self._lock:
      return {
          "events_emitted": self._emitted,
          "events_buffered": len(self._ring),
          "events_dropped": self._emitted - len(self._ring),
          "requests_open": len(self._open),
          "requests_completed": len(self._completed),
      }

  # -- Chrome trace-event export ---------------------------------------------

  def _Us(self, ts: float) -> float:
    return (ts - self.epoch) * 1e6

  def ChromeTrace(self) -> dict:
    """Chrome trace-event JSON (object form): one pid ("serving"), one tid
    per decode slot, per-request queued/prefill/decode duration pairs plus
    spec-verify/rollback instants from the ring. Extra top-level key
    `perRequest` carries the derived metrics (ignored by viewers, consumed
    by tools/trace_report.py)."""
    records = self.Requests()
    raw = self.Events()
    ev = [{"ph": "M", "pid": 0, "tid": 0, "name": "process_name",
           "args": {"name": "serving"}}]
    tids = {}
    for rec in records.values():
      tid = rec.slot if rec.slot is not None else _QUEUE_ONLY_TID
      tids.setdefault(
          tid, f"slot {rec.slot}" if rec.slot is not None else "queued-only")

    def _Span(name, tid, t0, t1, args=None):
      if t0 is None or t1 is None:
        return  # phase never completed: no unmatched B without its E
      ev.append({"ph": "B", "pid": 0, "tid": tid, "name": name,
                 "cat": "serving", "ts": self._Us(t0),
                 **({"args": args} if args else {})})
      ev.append({"ph": "E", "pid": 0, "tid": tid, "name": name,
                 "cat": "serving", "ts": self._Us(t1)})

    per_request = {}
    for rec in records.values():
      tid = rec.slot if rec.slot is not None else _QUEUE_ONLY_TID
      name = f"req {rec.req_id}"
      m = rec.Metrics()
      per_request[str(rec.req_id)] = m
      # queued: submit -> admit (or retire, for cancelled-while-queued)
      _Span(f"{name} queued", tid, rec.submit_ts,
            rec.admit_ts if rec.admit_ts is not None else rec.retire_ts,
            {"prompt_tokens": rec.prompt_tokens, "max_new": rec.max_new})
      # prefill: admit -> first token (the first token IS the final
      # prefill chunk's sample, so this span covers all prompt chunks)
      _Span(f"{name} prefill", tid, rec.admit_ts, rec.first_token_ts,
            {"prompt_tokens": rec.prompt_tokens, "pages": rec.pages,
             "chunks": rec.prefill_chunks})
      # decode: first token -> retire, args carry the derived metrics
      _Span(f"{name} decode", tid, rec.first_token_ts, rec.retire_ts,
            {k: v for k, v in m.items() if v is not None})
    for ts, kind, req_id, a, b, _reason in raw:
      if kind not in ("spec_verify", "rollback"):
        continue
      rec = records.get(req_id)
      tid = (rec.slot if rec is not None and rec.slot is not None
             else _QUEUE_ONLY_TID)
      args = ({"drafted": a, "accepted": b} if kind == "spec_verify"
              else {"tokens": a})
      ev.append({"ph": "i", "pid": 0, "tid": tid, "s": "t",
                 "name": f"{kind} req {req_id}", "cat": "serving",
                 "ts": self._Us(ts), "args": args})
    for tid, label in sorted(tids.items()):
      ev.append({"ph": "M", "pid": 0, "tid": tid, "name": "thread_name",
                 "args": {"name": label}})
    # stable order: metadata first, then by timestamp with E before B at
    # shared endpoints (adjacent phases touch), instants after the B
    phase_rank = {"M": -1, "E": 0, "B": 1, "i": 2}
    ev.sort(key=lambda e: (e.get("ts", -1), phase_rank.get(e["ph"], 3)))
    return {"traceEvents": ev, "displayTimeUnit": "ms",
            "perRequest": per_request}

  def Export(self, path: str) -> dict:
    """Writes ChromeTrace() JSON to `path`; returns the trace dict."""
    trace = self.ChromeTrace()
    with open(path, "w") as f:
      json.dump(trace, f)
    return trace
