"""Int8 weight serving: rewrite a theta so decode matmuls run in int8.

The export path (`serving/export.py`) freezes every eligible float leaf to
its int8 dequantization grid and saves the (w_int8, scale) pairs as the
`theta_int8` artifact. This module is the consumer side: it rewrites a
theta — either a live float theta or a restored frozen one — so that the
leaves the decode projections touch become `quant_utils.Int8Weight` nodes,
which ProjectionLayer / MultiHeadedAttention / SharedEmbeddingSoftmaxLayer
route through `Int8Einsum` integer matmuls.

Layouts: an integer matmul can only fold a scale out of the accumulator if
the scale is constant along the CONTRACTION axes, so each leaf's layout is
keyed by how its einsum contracts it (the export walk used to assume the
2-D 'dv' [in, out] layout for everything — wrong for `w_post` and `emb`,
whose per-channel axes lead). MoE expert weights (wi/wo/wm/pw_in/pw_out)
stay float in the serving theta: their einsums carry an expert dimension
the integer path does not thread yet (they are still frozen/quantized in
the export artifact, with legacy per-last-dim scales).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import quant_utils

# Leaf name -> (layout, contract_ndim) for serving-eligible weights, keyed
# by how each consuming einsum contracts the weight:
#   w        [in, out]   "...i,io->...o"    contract in      -> dv, 1
#   w_query/ [D, N, H]   "BTD,DNH->BTNH"    contract D       -> dv, 1
#   w_key/w_value
#   w_post   [D, N, H]   "BTNH,DNH->BTD"    contract (N, H)  -> vd, 2
#   emb      [V, D]      "...d,vd->...v"    contract D       -> vd, 1
#                        (EmbLookup gathers int8 rows and dequantizes by
#                         the per-row scale instead of a matmul)
SERVING_WEIGHT_LAYOUTS = {
    "w": ("dv", 1),
    "w_query": ("dv", 1),
    "w_key": ("dv", 1),
    "w_value": ("dv", 1),
    "w_post": ("vd", 2),
    "emb": ("vd", 1),
}


def WeightLayoutFor(name: str):
  """(layout, contract_ndim) for a leaf name; legacy all-but-last-dim
  reduction (dv, None) for artifact-only names like MoE experts."""
  return SERVING_WEIGHT_LAYOUTS.get(name, ("dv", None))


def _LeafName(path: str) -> str:
  return path.rsplit(".", 1)[-1]


def IsStackedPath(path: str) -> bool:
  """Repeated stacks (transformer.RepeatedTransformerLayer) store the whole
  body theta with a leading repeat axis that lax.scan / vmap slice off
  before any einsum sees the weight — quantization must treat axis 0 as a
  batch axis (one scale set PER REPEAT), never as a contraction axis."""
  return ".body." in f".{path}."


def QuantizeLeafInt8(leaf, layout, contract_ndim, stacked):
  """float leaf -> Int8Weight under the given layout; stacked leaves get
  per-repeat scales via a vmap over the leading repeat axis."""
  if not stacked:
    return quant_utils.Int8Weight.Quantize(leaf, layout=layout,
                                           contract_ndim=contract_ndim)
  w_int8, scale = jax.vmap(lambda w: quant_utils.Int8QuantizeWeight(
      w, per_channel=True, layout=layout, contract_ndim=contract_ndim))(leaf)
  # the sliced-per-repeat view an einsum actually consumes has the declared
  # layout; the full stacked node only ever Dequant()s (which broadcasts)
  return quant_utils.Int8Weight(w_int8, scale, layout=layout,
                                contract_ndim=contract_ndim)


def Int8ServingTheta(theta, mode: str = "int8"):
  """Rewrite serving-eligible leaves of `theta` -> (new_theta, paths).

  mode='int8' replaces each eligible float leaf with an `Int8Weight`
  pytree node (integer matmuls at serve time). mode='dequant' replaces it
  with the plain float dequantization grid `w_int8 * scale` — bitwise
  identical to what `Export(..., quantize_int8=True)` freezes, useful for
  asserting the freeze contract without changing any matmul.
  """
  assert mode in ("int8", "dequant"), mode
  new_theta = theta.DeepCopy()
  paths = []
  for path, leaf in theta.FlattenItems():
    name = _LeafName(path)
    if name not in SERVING_WEIGHT_LAYOUTS:
      continue
    stacked = IsStackedPath(path)
    if not hasattr(leaf, "ndim") or leaf.ndim < (3 if stacked else 2):
      continue
    if not jnp.issubdtype(leaf.dtype, jnp.floating):
      continue
    layout, k = SERVING_WEIGHT_LAYOUTS[name]
    w8 = QuantizeLeafInt8(leaf, layout, k, stacked)
    if mode == "dequant":
      new_theta.Set(path, w8.Dequant().astype(leaf.dtype))
    else:
      new_theta.Set(path, w8)
    paths.append(path)
  if not paths:
    raise ValueError("Int8ServingTheta: no serving-eligible leaves found")
  return new_theta, paths


def Int8ServingThetaFromArtifact(theta, int8_tree, mode: str = "int8"):
  """Build a serving theta from an exported `theta_int8` artifact.

  `theta` is the restored frozen theta (every eligible leaf already equals
  its dequantization grid — the export freeze contract); `int8_tree` is
  `Predictor.Int8Weights()`: {path: {"w_int8", "scale"}}. Only paths whose
  leaf name has a serving layout are rewritten; artifact-only paths (MoE
  experts, w_proj, ...) stay as their frozen floats.
  """
  assert mode in ("int8", "dequant"), mode
  new_theta = theta.DeepCopy()
  paths = []
  for path, pair in int8_tree.items():
    name = _LeafName(path)
    if name not in SERVING_WEIGHT_LAYOUTS:
      continue
    layout, k = SERVING_WEIGHT_LAYOUTS[name]
    w8 = quant_utils.Int8Weight(
        jnp.asarray(pair["w_int8"], dtype=jnp.int8),
        jnp.asarray(pair["scale"], dtype=jnp.float32),
        layout=layout, contract_ndim=k)
    if mode == "dequant":
      frozen = theta.Get(path)
      new_theta.Set(path, w8.Dequant().astype(frozen.dtype))
    else:
      new_theta.Set(path, w8)
    paths.append(path)
  if not paths:
    raise ValueError(
        "Int8ServingThetaFromArtifact: artifact has no serving-eligible "
        "paths")
  return new_theta, paths
