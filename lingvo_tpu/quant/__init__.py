"""Quantized serving: low-bit KV caches and integer-matmul weight serving.

The subsystem spans the stack — `quant/kv.py` owns the KV-cache numerics
(quantize-on-write / dequantize-on-read, byte accounting, stack census),
`quant/weights.py` owns the serving-theta rewrite that turns exported
`theta_int8` artifacts (or a live float theta) into `Int8Weight` leaves the
layers consume via integer matmuls. Entry points are the `kv_cache_dtype`
and `serve_int8_weights` knobs on `ServingLoop` / `GShardDecode` /
`TransformerLm.Params`. See docs/quantized_serving.md for the numerics
contract.
"""

from lingvo_tpu.quant import kv
from lingvo_tpu.quant import weights

__all__ = ["kv", "weights"]
