"""KV-cache quantization: per-token-per-head int8 with f32 scale sidecars.

Numerics contract (docs/quantized_serving.md):

  - Quantization happens exactly once, at WRITE time, in the same scatter
    that places a token's K/V into its page (`PagedStep`) or cache row
    (`ExtendStep`/`Prefill`). Each written token row [N, H] gets one
    symmetric max-abs scale PER HEAD — `scale[n] = max(|x[n, :]|) / 127`.
    Writes touch only the written slots, so quantization is purely local:
    no page-level re-quantization ever revisits (and re-rounds) already
    written tokens. That is why the sidecar is per-slot-per-head rather
    than the coarser per-page granularity — a page-level max grows as
    tokens stream in, and rescaling in place would be lossy.
  - Dequantization happens at READ time, inside the decode kernel (both
    the Pallas and XLA lowerings share `ops.block_decode._DequantPages`,
    which is what makes the twins bitwise-identical) or just before the
    dense `_Atten` fallback.
  - Scale sidecars for the paged pool are stored TRANSPOSED as
    [num_pages, N, page_size] f32 so the Pallas block's minor dimension is
    page_size (already gated to a multiple of 128 lanes by
    `SupportedOnTpu`). The dense cache keeps the natural [B, L, N] layout
    (it is XLA-only).

fp8 (float8_e4m3) storage reuses this exact plumbing — the registry below
reserves the name — but is a follow-on until the CI toolchain can
round-trip fp8 scatters.
"""

from __future__ import annotations

import jax.numpy as jnp

# Storage dtypes the KV pools understand. "" / None means "fprop dtype" —
# the bit-exact legacy cache. Only int8 carries scale sidecars.
KV_CACHE_DTYPES = ("float32", "bfloat16", "int8")


def ResolveKvCacheDtype(kv_cache_dtype, fprop_dtype):
  """-> (pool storage dtype, quantized?: bool).

  None/'' keeps the legacy behavior: the pool is allocated in the layer's
  fprop dtype and every read/write is a plain cast-free copy (bit-exact
  with the pre-quantization engine). 'float32'/'bfloat16' change only the
  storage dtype; 'int8' additionally switches on the scale sidecars and
  quantize-on-write.
  """
  if not kv_cache_dtype:
    return jnp.dtype(fprop_dtype), False
  if kv_cache_dtype not in KV_CACHE_DTYPES:
    raise ValueError(
        f"kv_cache_dtype={kv_cache_dtype!r} not in {KV_CACHE_DTYPES}")
  if kv_cache_dtype == "int8":
    return jnp.dtype(jnp.int8), True
  return jnp.dtype(kv_cache_dtype), False


def QuantizeKv(x):
  """[..., N, H] float K/V rows -> ([..., N, H] int8, [..., N] f32 scale).

  Symmetric per-head max-abs over H. The scale floor (1e-8) keeps all-zero
  rows well-defined: they quantize to zeros and dequantize to zeros.
  """
  x32 = x.astype(jnp.float32)
  amax = jnp.max(jnp.abs(x32), axis=-1)
  scale = jnp.maximum(amax / 127.0, 1e-8)
  q = jnp.clip(jnp.round(x32 / scale[..., None]), -128, 127).astype(jnp.int8)
  return q, scale


def DequantKv(q, scale):
  """([..., N, H] int8, [..., N] f32) -> [..., N, H] f32."""
  return q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)


def KvBytesPerToken(num_heads: int, dim_per_head: int, kv_cache_dtype,
                    fprop_dtype) -> int:
  """K + V bytes one cached token costs in one attention layer, sidecars
  included (int8 adds 2 * N f32 scales per token)."""
  dtype, quantized = ResolveKvCacheDtype(kv_cache_dtype, fprop_dtype)
  per = 2 * num_heads * dim_per_head * dtype.itemsize
  if quantized:
    per += 2 * num_heads * 4
  return per


def StackKvCensus(task, kv_cache_dtype=None):
  """Walk a TransformerLm-shaped task's stack -> KV telemetry dict.

  Duck-types the same three stack shapes the serving engine walks
  (Stacked x_layers / Repeated body / Repeated-of-Stacked) and sums
  repetitions x per-layer `KvBytesPerToken()`. SSM mixers keep O(1) state
  slots, not KV, so they contribute zero here (int8 state slots are a
  documented follow-on). Returns None when the task has no recognizable
  stack (e.g. non-LM tasks in GShardDecode).
  """
  stack = getattr(task, "stack", None)
  if stack is None:
    return None
  layers = []
  if hasattr(stack, "x_layers"):
    layers = [(l, 1) for l in stack.x_layers]
  elif hasattr(stack, "body"):
    reps = int(getattr(stack.p, "num_layers", 1) or 1)
    body = stack.body
    if hasattr(body, "x_layers"):
      layers = [(l, reps) for l in body.x_layers]
    else:
      layers = [(body, reps)]
  attens = []
  for layer, reps in layers:
    atten = getattr(getattr(layer, "self_atten", None), "atten", None)
    if atten is not None and hasattr(atten, "KvBytesPerToken"):
      attens.append((atten, reps))
  if not attens:
    return {"kv_cache_dtype": None, "kv_bytes_per_token": 0,
            "attention_layers": 0}
  total = sum(reps * a.KvBytesPerToken(kv_cache_dtype) for a, reps in attens)
  return {
      "kv_cache_dtype": attens[0][0].KvCacheDtype(kv_cache_dtype),
      "kv_bytes_per_token": int(total),
      "attention_layers": int(sum(reps for _, reps in attens)),
  }
