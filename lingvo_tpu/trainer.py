"""Trainer CLI: the operator entry point.

Re-designs `lingvo/trainer.py`: `--model` selects a registered experiment,
`--mode` picks train/eval/decode/inspect, `--logdir` receives config +
analysis + summaries. The runner/job-thread machinery of the reference
collapses into the executor (single-program SPMD: every chip runs the same
program; multi-host launches run this same binary per host).

Usage:
  python -m lingvo_tpu.trainer --model=image.mnist.LeNet5 \
      --logdir=/tmp/mnist --mode=train
  python -m lingvo_tpu.trainer --model=... --mode=inspect_model
  python -m lingvo_tpu.trainer --list_models
"""

from __future__ import annotations

import argparse
import os
import sys

from lingvo_tpu import model_registry


def _MultiHostMesh(task):
  """Default multi-host layout: data parallelism over all devices with
  ZeRO/FSDP state sharding over the same axis (model-parallel multi-host
  layouts come from experiment-provided ProgramSchedules). Returns
  (mesh, input_sharding, state_sharding_fn)."""
  import jax
  from jax.sharding import PartitionSpec
  from lingvo_tpu.parallel import mesh as mesh_lib
  mesh = mesh_lib.MakeMesh({"data": jax.device_count()})
  return (mesh, PartitionSpec("data"),
          lambda state: mesh_lib.TrainStateShardings(
              mesh, task, state, fsdp_axis="data"))


def _BuildSchedule(model_params, args):
  import jax
  from lingvo_tpu.runners import program as program_lib
  task_p = model_params.task
  if task_p.input is None and model_params.input is not None:
    task_p.input = model_params.input
  cls = model_registry.GetClass(args.model)
  inst = cls()
  # Experiment-provided schedule takes precedence (ref GetProgramSchedule).
  ps = inst.ProgramSchedule()
  input_generators = {}
  train_p = program_lib.TrainProgram.Params().Set(
      task=task_p, logdir=args.logdir,
      steps_per_loop=task_p.train.tpu_steps_per_loop)
  from lingvo_tpu.core import base_model as base_model_lib
  from lingvo_tpu.core import base_model_params as bmp
  eval_programs = []
  has_decode = task_p.cls.Decode is not base_model_lib.BaseTask.Decode
  for ds in ("Test", "Dev"):
    try:
      ds_params = inst.GetDatasetParams(ds)
    except bmp.DatasetError:
      continue  # dataset genuinely not defined; real errors propagate
    ep = program_lib.EvalProgram.Params().Set(
        task=task_p, logdir=args.logdir, dataset_name=ds,
        name=f"eval_{ds.lower()}")
    from lingvo_tpu.core import input_policy
    input_generators[ds] = input_policy.Instantiate(ds_params)
    eval_programs.append(ep)
    if has_decode and ds == "Test":
      eval_programs.append(program_lib.DecodeProgram.Params().Set(
          task=task_p, logdir=args.logdir, dataset_name=ds,
          name=f"decode_{ds.lower()}"))
  if ps is None:
    ps = program_lib.SimpleProgramSchedule.Params().Set(
        train_program=train_p, eval_programs=eval_programs,
        train_executions_per_eval=args.train_executions_per_eval)
  task = None  # schedule instantiates from params
  sched_cls = ps.cls
  # Single task instance shared by all programs.
  task = task_p.Instantiate()
  task.FinalizePaths()
  if jax.process_count() > 1:
    # multi-host default: data-parallel mesh over every device, FSDP-style
    # state shardings, per-host input shards joined into global batches
    mesh, input_sharding, sharding_fn = _MultiHostMesh(task)
    for prog_p in [train_p] + eval_programs:
      prog_p.mesh = mesh
      prog_p.input_sharding = input_sharding
      prog_p.state_sharding_fn = sharding_fn
  return sched_cls(ps, task=task, input_generators=input_generators), task


def main(argv=None):
  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument("--model", default="", help="Registered model name.")
  parser.add_argument("--logdir", default="/tmp/lingvo_tpu",
                      help="Output directory.")
  parser.add_argument("--mode", default="train",
                      choices=["train", "eval", "decode", "inspect_model",
                               "inspect_params", "export", "shell"],
                      help="What to run. 'export' writes the serving bundle "
                      "(ref --mode=write_inference_graph); 'shell' drops "
                      "into an interactive prompt with the model loaded "
                      "(ref --mode=shell ipython_kernel).")
  parser.add_argument("--export_dir", default="",
                      help="'export' output dir (default <logdir>/export).")
  parser.add_argument("--allow_fresh_init", action="store_true",
                      help="let 'export' serialize randomly initialized "
                      "weights when the logdir has no checkpoint "
                      "(default: hard error).")
  parser.add_argument("--export_int8", action="store_true",
                      help="'export' freezes matmul weights to the "
                      "per-channel int8 grid and bundles the int8+scale "
                      "artifact (theta_int8) for integer-math serving.")
  parser.add_argument("--job", default="executor_tpu",
                      help="executor_tpu (train), or evaler/decoder "
                           "(checkpoint-polling follower jobs).")
  parser.add_argument("--poll_interval_secs", type=float, default=10.0)
  parser.add_argument("--poll_timeout_secs", type=float, default=3600.0,
                      help="Follower jobs exit after this long without a "
                           "new checkpoint (also exit early when the "
                           "trainer's FINISHED marker appears).")
  # multi-host control plane (ref trainer.py:210-278 cluster_spec flags)
  parser.add_argument("--coordinator_address", default=None,
                      help="host:port of process 0 (jax.distributed).")
  parser.add_argument("--num_processes", type=int, default=None)
  parser.add_argument("--process_id", type=int, default=None)
  parser.add_argument("--mlperf_benchmark", default="",
                      help="If set, write MLPerf :::MLLOG compliance events "
                           "to <logdir>/mlperf_log.txt.")
  parser.add_argument("--max_steps", type=int, default=None,
                      help="Override task max_steps.")
  parser.add_argument("--train_executions_per_eval", type=int, default=1)
  parser.add_argument("--list_models", action="store_true")
  args = parser.parse_args(argv)

  if args.list_models:
    import lingvo_tpu.models.all_params  # noqa: F401  (populate registry)
    from lingvo_tpu import datasets as datasets_lib
    for name in sorted(model_registry.GetRegisteredModels()):
      try:
        ds = datasets_lib.GetDatasets(model_registry.GetClass(name))
      except Exception:  # noqa: BLE001 - listing must never crash
        ds = []
      print(f"{name}  [{', '.join(ds)}]" if ds else name)
    return 0

  if not args.model:
    parser.error("--model is required")

  if args.coordinator_address or args.num_processes:
    from lingvo_tpu.core import cluster
    cluster.InitDistributed(
        coordinator_address=args.coordinator_address,
        num_processes=args.num_processes, process_id=args.process_id)

  model_params = model_registry.GetParams(args.model, "Train")
  if args.max_steps is not None:
    model_params.task.train.max_steps = args.max_steps

  if args.mode == "inspect_params":
    print(model_params.ToText())
    return 0

  if args.mode == "inspect_model":
    task = model_params.task.Instantiate()
    task.FinalizePaths()
    import numpy as np
    total = 0
    for path, wp in task.VariableSpecs().FlattenItems():
      n = int(np.prod(wp.shape)) if wp.shape else 1
      total += n
      print(f"{path:<60} {str(tuple(wp.shape)):<20} {n}")
    print(f"{'TOTAL':<60} {'':<20} {total}")
    return 0

  if args.mode in ("export", "shell"):
    import jax
    from lingvo_tpu.core import checkpointer as checkpointer_lib
    task = model_params.task.Instantiate()
    task.FinalizePaths()
    state = task.CreateTrainState(jax.random.PRNGKey(1234))
    ckpt = checkpointer_lib.Checkpointer(os.path.join(args.logdir, "train"))
    step = None
    if ckpt.LatestStep() is not None:
      state, step = ckpt.Restore(state)
    ckpt.Close()
    if args.mode == "export":
      if step is None and not args.allow_fresh_init:
        print(f"no checkpoint in {args.logdir}/train — refusing to export "
              "random weights (pass --allow_fresh_init to override)",
              file=sys.stderr)
        return 1
      from lingvo_tpu.serving import export as export_lib
      out_dir = args.export_dir or os.path.join(args.logdir, "export")
      # serve what eval/decode blessed: EMA weights when the task keeps them
      theta = state.ema_theta if "ema_theta" in state else state.theta
      export_lib.InferenceGraphExporter.Export(
          task, theta, out_dir, quantize_int8=args.export_int8)
      which = "ema_theta" if "ema_theta" in state else "theta"
      print(f"exported inference bundle ({which}, ckpt step {step}) -> "
            f"{out_dir}")
      return 0
    banner = (f"lingvo_tpu shell: `task` ({type(task).__name__}), `state` "
              f"(step {step}), `model_params`, jax/jnp/np loaded")
    ns = dict(task=task, state=state, model_params=model_params, jax=jax)
    import jax.numpy as jnp
    import numpy as np
    ns.update(jnp=jnp, np=np)
    try:
      import IPython
      IPython.start_ipython(argv=[], user_ns=ns, display_banner=False)
    except ImportError:
      import code
      code.interact(banner=banner, local=ns)
    return 0

  schedule, task = _BuildSchedule(model_params, args)
  if args.mode == "train":
    from lingvo_tpu.runners import executor as executor_lib
    execu = executor_lib.ExecutorTpu(model_params, args.logdir,
                                     schedule=schedule, task=task,
                                     mlperf_benchmark=args.mlperf_benchmark)
    execu.Start()
    return 0
  if args.mode in ("eval", "decode"):
    # follower jobs never construct an executor: the trainer owns
    # trainer_params.txt / model_analysis.txt and the save-side manager
    progs = [pr for pr in schedule.programs
             if (args.mode == "eval" and "eval" in pr.p.name) or
             (args.mode == "decode" and "decode" in pr.p.name)]
    from lingvo_tpu.core import checkpointer as checkpointer_lib
    from lingvo_tpu.runners import base_runner
    if args.job in ("evaler", "decoder"):
      # checkpoint-following job (ref base_runner.py:224-298): keep polling
      # the trainer's dir and score every new checkpoint until training ends
      poller = base_runner.CheckpointPollingRunner(
          task, progs, os.path.join(args.logdir, "train"),
          poll_interval_secs=args.poll_interval_secs,
          timeout_secs=args.poll_timeout_secs)
      poller.Run()
      return 0
    import jax
    from lingvo_tpu.runners import program as program_lib
    ckpt = checkpointer_lib.Checkpointer(os.path.join(args.logdir, "train"))
    state = program_lib.PlaceStateForPrograms(
        progs, task.CreateTrainState(jax.random.PRNGKey(1234)))
    state, step = ckpt.Restore(state)
    for prog in progs:
      _, results = prog.Run(state)
      print(f"[{prog.p.name}] step={step} {results}")
    ckpt.Close()
    return 0
  return 1


if __name__ == "__main__":
  sys.exit(main())
