"""Global debug/assert flags (ref `lingvo/core/py_utils_flags.py`:
--enable_asserts, --enable_check_numerics etc.).

Env-var driven (LINGVO_TPU_<NAME>=1) with programmatic override — flags
configure debug tooling only, never model semantics (SURVEY §5: all model
config lives in the Params tree)."""

from __future__ import annotations

import os

_OVERRIDES: dict[str, bool] = {}


def _Flag(name: str, default: bool = False) -> bool:
  if name in _OVERRIDES:
    return _OVERRIDES[name]
  return os.environ.get(f"LINGVO_TPU_{name.upper()}", "") in ("1", "true")


def SetFlag(name: str, value: bool) -> None:
  _OVERRIDES[name] = value


def enable_asserts() -> bool:
  """Shape/value assert helpers in py_utils become real checks."""
  return _Flag("enable_asserts", True)


def enable_check_numerics() -> bool:
  """CheckNumerics wrappers raise on NaN/Inf activations."""
  return _Flag("enable_check_numerics")


def use_eager_pallas_interpret() -> bool:
  """Force Pallas kernels to interpret mode (debugging off-TPU)."""
  return _Flag("pallas_interpret")
