"""Tokenizer layer: Params-configured wrappers over the native tokenizers.

Re-designs the reference's tokenizer surface (`lingvo/core/tokenizers.py`
AsciiTokenizer/VocabFileTokenizer/BpeTokenizer, `wpm_encoder.py` WpmTokenizer,
backed by the C++ kernels in `ops/tokenizer_ops_kernels.cc`): a tokenizer is
an instantiable Params object exposing

  StringsToIds(strs, max_length) -> (ids, labels, paddings)

where `ids` is sos-prefixed and `labels` eos-suffixed (teacher forcing
layout, ref `tokenizers.py` StringsToIds contract), plus
`IdsToStrings(ids, lens)`. The heavy lifting runs in the C++ library
(`ops/cc/tokenizer.cc`, `ops/cc/subword.cc`) via ctypes.
"""

from __future__ import annotations

import numpy as np

from lingvo_tpu.core import hyperparams


class BaseTokenizer:
  """Base: sos/eos framing around a raw text->ids encoder."""

  @classmethod
  def Params(cls):
    p = hyperparams.InstantiableParams(cls)
    p.Define("name", "tokenizer", "Name.")
    p.Define("vocab_size", 0, "Vocabulary size (0 = from vocab file).")
    p.Define("target_sos_id", 1, "Id prepended to ids.")
    p.Define("target_eos_id", 2, "Id appended to labels.")
    p.Define("target_unk_id", 0, "OOV id.")
    p.Define("append_eos", True, "Whether labels end with eos.")
    return p

  def __init__(self, params):
    self.p = params.Copy()

  # -- subclass points -------------------------------------------------------
  def _EncodeRaw(self, texts, max_len):
    """-> (ids [b, max_len] int32, lens [b] int32), no sos/eos."""
    raise NotImplementedError

  def _DecodeRaw(self, ids, lens):
    raise NotImplementedError

  # -- public API ------------------------------------------------------------
  def StringsToIds(self, texts, max_length: int):
    """Teacher-forcing layout: ids=[sos, w...], labels=[w..., eos].

    Returns (ids, labels, paddings), all [b, max_length]; paddings marks
    positions past each sequence's eos.
    """
    p = self.p
    raw, lens = self._EncodeRaw(texts, max_length - 1)
    b = len(texts)
    ids = np.zeros((b, max_length), np.int32)
    labels = np.zeros((b, max_length), np.int32)
    paddings = np.ones((b, max_length), np.float32)
    for i in range(b):
      n = int(lens[i])
      ids[i, 0] = p.target_sos_id
      ids[i, 1:n + 1] = raw[i, :n]
      labels[i, :n] = raw[i, :n]
      if p.append_eos:
        labels[i, n] = p.target_eos_id
        paddings[i, :n + 1] = 0.0
      else:
        paddings[i, :n] = 0.0
    return ids, labels, paddings

  def IdsToStrings(self, ids, lens=None):
    ids = np.asarray(ids)
    if lens is None:
      lens = np.full((len(ids),), ids.shape[1], np.int32)
    # strip framing ids before decode
    p = self.p
    cleaned, clens = [], []
    for i in range(len(ids)):
      row = [t for t in ids[i, :int(lens[i])]
             if t not in (p.target_sos_id, p.target_eos_id)]
      cleaned.append(row)
      clens.append(len(row))
    width = max(clens) if clens else 1
    arr = np.zeros((len(ids), max(width, 1)), np.int32)
    for i, row in enumerate(cleaned):
      arr[i, :len(row)] = row
    return self._DecodeRaw(arr, np.asarray(clens, np.int32))

  @property
  def vocab_size(self) -> int:
    return self.p.vocab_size


def _LensFromPaddings(paddings):
  return (1.0 - paddings).sum(axis=-1).astype(np.int32)


class AsciiTokenizer(BaseTokenizer):
  """Char-level (ref `ascii_tokenizer.cc` id space; sos=0 eos=1 unk=73)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.vocab_size = 76
    p.target_sos_id = 0
    p.target_eos_id = 1
    p.target_unk_id = 73
    return p

  def _EncodeRaw(self, texts, max_len):
    from lingvo_tpu.ops import native
    ids, paddings = native.AsciiTokenizer().StringsToIds(
        texts, max_len, append_eos=False)
    return ids, _LensFromPaddings(paddings)

  def _DecodeRaw(self, ids, lens):
    from lingvo_tpu.ops import native
    return native.AsciiTokenizer().IdsToStrings(ids, lens)


class _FileBackedTokenizer(BaseTokenizer):
  """Shared lazy-load plumbing for vocab-file tokenizers."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("vocab_filepath", "", "Vocab file (one token per line).")
    p.Define("unk_token", "<unk>", "OOV token string.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self._impl = None

  def _Load(self):
    raise NotImplementedError

  @property
  def impl(self):
    if self._impl is None:
      self._impl = self._Load()
    return self._impl

  @property
  def vocab_size(self) -> int:
    return self.p.vocab_size or self.impl.vocab_size

  def _EncodeRaw(self, texts, max_len):
    ids, paddings = self.impl.StringsToIds(texts, max_len)
    return ids, _LensFromPaddings(paddings)

  def _DecodeRaw(self, ids, lens):
    return self.impl.IdsToStrings(ids, lens)


class VocabFileTokenizer(_FileBackedTokenizer):
  """Whole-word vocab lookup (ref `simple_vocab.cc` semantics)."""

  def _Load(self):
    from lingvo_tpu.ops import native
    return native.VocabTokenizer(self.p.vocab_filepath, self.p.unk_token)


class WpmTokenizer(_FileBackedTokenizer):
  """Greedy longest-match wordpiece (ref `wpm_encoder.py`); auto-detects
  sentencepiece ▁ or BERT ## marker convention from the vocab file."""

  def _Load(self):
    from lingvo_tpu.ops import native
    return native.WpmTokenizer(self.p.vocab_filepath, self.p.unk_token)


class BpeTokenizer(_FileBackedTokenizer):
  """Merge-ops BPE (ref `BpeWordsToIds` kernel: codes + vocab files)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("codes_filepath", "", "BPE merge-operations file.")
    return p

  def _Load(self):
    from lingvo_tpu.ops import native
    return native.BpeTokenizer(self.p.codes_filepath, self.p.vocab_filepath,
                               self.p.unk_token)


class _SpmAdapter:
  """Adapts core.sentencepiece.SentencePieceModel to the native-tokenizer
  (StringsToIds/IdsToStrings over fixed-width arrays) interface."""

  def __init__(self, model):
    self.model = model
    self.vocab_size = model.vocab_size

  def StringsToIds(self, texts, max_len):
    b = len(texts)
    ids = np.zeros((b, max_len), np.int32)
    paddings = np.ones((b, max_len), np.float32)
    for i, text in enumerate(texts):
      row = self.model.EncodeAsIds(text)[:max_len]
      ids[i, :len(row)] = row
      paddings[i, :len(row)] = 0.0
    return ids, paddings

  def IdsToStrings(self, ids, lens):
    return [self.model.DecodeIds([int(t) for t in ids[i, :int(lens[i])]])
            for i in range(len(ids))]


class SentencePieceTokenizer(_FileBackedTokenizer):
  """SentencePiece .model tokenizer (ref `tokenizers.py`
  SentencePieceTokenizer / `gshard_utils.py:448` LoadSpm), backed by the
  from-scratch model reader in `core/sentencepiece.py` (unigram Viterbi /
  BPE merges, byte fallback) — no external spm library needed.

  `vocab_filepath` points at the serialized `.model` file. sos/eos/unk ids
  default to -1 = "take the model's TrainerSpec value" (resolved lazily on
  first use, like the sibling tokenizers' file loads); set them explicitly
  to override the model file. A model without a usable id (e.g. T5-style
  bos_id=-1) fails loudly rather than framing with a wrong id.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.target_sos_id = -1
    p.target_eos_id = -1
    p.target_unk_id = -1
    return p

  def _Load(self):
    from lingvo_tpu.core import sentencepiece as spm
    impl = _SpmAdapter(spm.SentencePieceModel.FromFile(self.p.vocab_filepath))
    m, p = impl.model, self.p
    for attr, mid in (("target_sos_id", m.bos_id), ("target_eos_id", m.eos_id),
                      ("target_unk_id", m.unk_id)):
      if getattr(p, attr) < 0:  # -1 = defer to the model file
        if mid < 0:
          raise ValueError(
              f"{p.vocab_filepath}: model defines no id for {attr} "
              f"(TrainerSpec value {mid}); set p.{attr} explicitly")
        setattr(p, attr, mid)
    return impl

  def StringsToIds(self, texts, max_length: int):
    self.impl  # resolve special ids from the model before framing
    return super().StringsToIds(texts, max_length)

  def IdsToStrings(self, ids, lens=None):
    self.impl  # resolve special ids before sos/eos stripping
    return super().IdsToStrings(ids, lens)
