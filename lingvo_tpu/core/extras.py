"""Specialty math layers/ops (ref the `lingvo/core` long tail: `entmax.py`,
`differentiable_assignment.py` (Sinkhorn), `reversible_layers.py`)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core.nested_map import NestedMap


def Entmax15(logits, axis: int = -1):
  """1.5-entmax: sparse softmax (ref `entmax.py`; Peters et al. 2019).

  Exact algorithm via sorting: p_i = max(0, (z_i - tau))^2 where z = x/2
  and tau solves sum(p) = 1.
  """
  x = logits.astype(jnp.float32) / 2.0
  x = x - jnp.max(x, axis=axis, keepdims=True)
  sort = jnp.flip(jnp.sort(x, axis=axis), axis=axis)
  k = jnp.arange(1, x.shape[axis] + 1, dtype=jnp.float32)
  shape = [1] * x.ndim
  shape[axis] = -1
  k = k.reshape(shape)
  mean = jnp.cumsum(sort, axis=axis) / k
  mean_sq = jnp.cumsum(sort ** 2, axis=axis) / k
  ss = k * (mean_sq - mean ** 2)
  delta = (1.0 - ss) / k
  # masked sqrt: sqrt(0)'s infinite VJP would NaN the whole gradient for
  # any sparse output (delta clamps to exactly 0 off-support)
  pos = delta > 0
  delta = jnp.maximum(delta, 0.0)
  tau = mean - jnp.sqrt(jnp.where(pos, delta, 1.0)) * pos.astype(
      delta.dtype)
  support = (tau <= sort).astype(jnp.float32)
  k_star = jnp.sum(support, axis=axis, keepdims=True)
  # gather tau at the support size
  idx = jnp.clip(k_star.astype(jnp.int32) - 1, 0, x.shape[axis] - 1)
  tau_star = jnp.take_along_axis(tau, idx, axis=axis)
  out = jnp.maximum(x - tau_star, 0.0) ** 2
  return out / jnp.maximum(jnp.sum(out, axis=axis, keepdims=True), 1e-12)


def SinkhornAssignment(scores, num_iters: int = 20, temperature: float = 1.0):
  """Differentiable (soft) assignment via Sinkhorn iterations in log space
  (ref `differentiable_assignment.py`): returns a doubly-stochastic-ish
  matrix from a [.., n, m] score matrix."""
  log_p = scores.astype(jnp.float32) / temperature

  def _Iter(log_p, _):
    log_p = log_p - jax.nn.logsumexp(log_p, axis=-1, keepdims=True)
    log_p = log_p - jax.nn.logsumexp(log_p, axis=-2, keepdims=True)
    return log_p, ()

  log_p, _ = jax.lax.scan(_Iter, log_p, None, length=num_iters)
  return jnp.exp(log_p)


class ReversibleLayer(base_layer.BaseLayer):
  """RevNet-style reversible residual block (ref `reversible_layers.py`):

    y1 = x1 + F(x2) ; y2 = x2 + G(y1)

  The backward pass RECONSTRUCTS (x1, x2) from (y1, y2) instead of storing
  the inputs, so intra-F/G activations are never kept. Each block still
  saves its OUTPUT pair as the vjp residual, so a plain Python stack of N
  blocks stores N boundary pairs (O(depth) boundaries, O(1) interiors);
  true O(1)-in-depth needs a scan-style driver that re-derives boundaries
  sequentially. F/G are arbitrary sub-layers with signature
  FProp(theta, x) -> same-shape output.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("f", None, "F sub-layer Params.")
    p.Define("g", None, "G sub-layer Params.")
    return p

  def __init__(self, params):
    super().__init__(params)
    assert self.p.f is not None and self.p.g is not None
    self.CreateChild("f", self.p.f)
    self.CreateChild("g", self.p.g)

  def FProp(self, theta, x1, x2):
    f_fn = lambda th, x: self.f.FProp(th, x)
    g_fn = lambda th, x: self.g.FProp(th, x)
    return _ReversibleCall(f_fn, g_fn, theta.f, theta.g, x1, x2)

  def Reverse(self, theta, y1, y2):
    """Exact input reconstruction (tests / invertible-flow uses; the custom
    vjp inlines its own equivalent reconstruction)."""
    x2 = y2 - self.g.FProp(theta.g, y1)
    x1 = y1 - self.f.FProp(theta.f, x2)
    return x1, x2


from functools import partial


@partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _ReversibleCall(f_fn, g_fn, theta_f, theta_g, x1, x2):
  y1 = x1 + f_fn(theta_f, x2)
  y2 = x2 + g_fn(theta_g, y1)
  return y1, y2


def _ReversibleFwd(f_fn, g_fn, theta_f, theta_g, x1, x2):
  y1, y2 = _ReversibleCall(f_fn, g_fn, theta_f, theta_g, x1, x2)
  # store only OUTPUTS: inputs are reconstructed in the bwd pass
  return (y1, y2), (theta_f, theta_g, y1, y2)


def _ReversibleBwd(f_fn, g_fn, res, grads):
  theta_f, theta_g, y1, y2 = res
  dy1, dy2 = grads
  # ONE vjp trace of G serves both the reconstruction (primal gy1) and the
  # backprop through y2 = x2 + G(y1)
  gy1, g_vjp = jax.vjp(lambda th, y: g_fn(th, y), theta_g, y1)
  x2 = y2 - gy1
  _, f_vjp_x = jax.vjp(lambda th, x: f_fn(th, x), theta_f, x2)
  d_theta_g, dy1_from_g = g_vjp(dy2)
  dy1_total = dy1 + dy1_from_g
  d_theta_f, dx2_from_f = f_vjp_x(dy1_total)
  dx1 = dy1_total
  dx2 = dy2 + dx2_from_f
  return d_theta_f, d_theta_g, dx1, dx2


_ReversibleCall.defvjp(_ReversibleFwd, _ReversibleBwd)
