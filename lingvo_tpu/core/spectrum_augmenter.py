"""SpecAugment: frequency and time masking on spectrogram features.

Re-designs `lingvo/core/spectrum_augmenter.py` (1073 LoC): the on-device
masking path only (time-warp omitted — the reference's own TPU path skips it
too). Masks are drawn from the deterministic step-seed stream, identity at
eval.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import py_utils


class SpectrumAugmenter(base_layer.BaseLayer):

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("freq_mask_max_bins", 27, "F: max width of a frequency mask.")
    p.Define("freq_mask_count", 2, "Number of frequency masks.")
    p.Define("time_mask_max_frames", 50, "T: max width of a time mask.")
    p.Define("time_mask_count", 2, "Number of time masks.")
    p.Define("time_mask_max_ratio", 1.0,
             "Cap time-mask width at ratio * seq_len.")
    return p

  def _NameIsRequired(self):
    return False

  def _OneMask(self, key, size: int, max_width, batch: int,
               choose_range=None):
    """[batch, size] multiplicative mask with one random span zeroed.

    max_width may be a python int or a per-example int array. Start is drawn
    from [0, limit - width] INCLUSIVE, where limit is the per-example valid
    length (`choose_range`, ref _GetMask choose_range) or `size` — so masks
    land inside real content, and can sit flush at its end.
    """
    k1, k2 = jax.random.split(key)
    if isinstance(max_width, int):
      width = jax.random.randint(k1, (batch,), 0, max_width + 1)
    else:
      width = (jax.random.uniform(k1, (batch,)) *
               (max_width + 1).astype(jnp.float32)).astype(jnp.int32)
    limit = (jnp.full((batch,), size, jnp.int32) if choose_range is None
             else choose_range.astype(jnp.int32))
    start = jax.random.randint(k2, (batch,), 0,
                               jnp.maximum(limit - width + 1, 1))
    pos = jnp.arange(size)[None, :]
    inside = (pos >= start[:, None]) & (pos < (start + width)[:, None])
    return 1.0 - inside.astype(jnp.float32)

  def FProp(self, theta, features, paddings=None):
    """features: [b, t, f] or [b, t, f, c]; returns same shape."""
    p = self.p
    if py_utils.DoEval() or not py_utils.HasStepSeed():
      return features
    squeeze = False
    if features.ndim == 3:
      features = features[..., None]
      squeeze = True
    b, t, f, c = features.shape
    key = py_utils.StepSeed(f"{self.path}/specaug")
    mask = jnp.ones((b, t, f), jnp.float32)
    seq_lens = (py_utils.LengthsFromPaddings(paddings)
                if paddings is not None else None)
    if seq_lens is not None and p.time_mask_max_ratio < 1.0:
      # width cap = min(absolute cap, ratio * per-example length)
      time_width = jnp.minimum(
          jnp.asarray(p.time_mask_max_frames, jnp.int32),
          (seq_lens.astype(jnp.float32) *
           p.time_mask_max_ratio).astype(jnp.int32))
    else:
      time_width = p.time_mask_max_frames
    for i in range(p.freq_mask_count):
      fk = jax.random.fold_in(key, 100 + i)
      mask = mask * self._OneMask(fk, f, p.freq_mask_max_bins, b)[:, None, :]
    for i in range(p.time_mask_count):
      tk = jax.random.fold_in(key, 200 + i)
      mask = mask * self._OneMask(tk, t, time_width, b,
                                  choose_range=seq_lens)[:, :, None]
    out = features * mask[..., None].astype(features.dtype)
    return out[..., 0] if squeeze else out
