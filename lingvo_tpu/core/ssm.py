"""Gated state-space-duality sequence mixer with an O(1) decode state.

`GatedSSMLayer` is a drop-in alternative to `attention.MultiHeadedAttention`
behind `transformer.TransformerAttentionLayer`: same FProp signature, same
`InitStates`/`ExtendStep`/`Prefill` incremental-decode contract, same
`InitPagedStates`/`PagedStep` serving contract — so hybrid stacks decode
through GShardDecode and the continuous-batching engine unchanged. The
difference is the cache: instead of a `[B, T, N, H]` KV cache that grows
with sequence length, the decode state is a fixed `[B, N, H, S]` matrix per
sequence — O(1) in T, which is the whole point (PAPERS.md: "Compiler-First
State Space Duality and Portable O(1) Autoregressive Caching").

Per head n, the mixer is a gated linear recurrence in SSD form
(Mamba-2 / gated-linear-attention family):

    b_t = x_t W_b      [S]   write key        c_t = x_t W_c   [S] read key
    v_t = x_t W_v      [H]   value            g_t = x_t W_g   [H] gate
    a_t = exp(-softplus(x_t w_dt + b_dt) * exp(A_log))        scalar decay
    S_t = a_t S_{t-1} + v_t outer b_t                         [H, S] state
    y_t = S_t c_t + d_skip * v_t
    out_t = W_post . RMSNorm_head(y_t * silu(g_t))

Training/prefill lowers through `ops/ssd_scan.SsdScan` (chunked XLA or the
bitwise-equal Pallas twin); single-token decode is `ssd_scan.SequentialStep`
— literally the same float ops the `sequential` lowering scans over, so the
decode path and the sequential reference agree bitwise by construction.

Numerics: projections/gating run in fprop dtype-friendly f32 (scan state is
always f32 — the recurrence compounds over thousands of steps); the final
output projection casts back to fprop dtype.

Not supported (asserted, not silently wrong): cross-attention inputs,
additive `atten_mask`s, and non-causal (`causal=False`) FProp — a linear
recurrence is causal by nature.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.core.py_utils import WeightInit, WeightParams
from lingvo_tpu.ops import ssd_scan


class GatedSSMLayer(base_layer.BaseLayer):
  """Gated SSD mixer; plug-compatible with MultiHeadedAttention."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Model dim (set by the wrapping layer).")
    p.Define("hidden_dim", 0, "Total mixer hidden dim (N*H); 0 = input_dim.")
    p.Define("num_heads", 1, "Number of heads.")
    p.Define("dim_per_head", 0, "Per-head value dim H (0 = hidden/heads).")
    p.Define("state_dim", 64, "Per-head state width S (the O(1) cache is "
             "[N, H, S] floats per sequence).")
    p.Define("use_bias", True, "Bias on the value/gate/output projections.")
    p.Define("chunk_size", 64, "Scan chunk width Q for the chunked/Pallas "
             "lowerings (training + prefill).")
    p.Define(
        "scan_lowering", "auto",
        "ops/ssd_scan lowering for multi-token calls: 'auto' (Pallas on "
        "real TPU when SupportedOnTpu, chunked XLA elsewhere), 'chunked', "
        "'pallas', 'associative', or 'sequential'.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.input_dim > 0 and p.num_heads > 0
    hidden = p.hidden_dim or p.input_dim
    self._dim_per_head = p.dim_per_head or hidden // p.num_heads
    n, h, s, d = p.num_heads, self._dim_per_head, p.state_dim, p.input_dim
    assert s > 0
    wsdm = p.weight_split_dims_mapping  # e.g. (None, 'model', None)
    wsdm2 = tuple(wsdm[:2]) if wsdm else None
    for name, width in (("v", h), ("b", s), ("c", s), ("gate", h)):
      self.CreateVariable(
          f"w_{name}",
          WeightParams((d, n, width), p.params_init, p.dtype,
                       tensor_split_dims_mapping=wsdm))
    if p.use_bias:
      for name, width in (("v", h), ("gate", h)):
        self.CreateVariable(
            f"b_{name}",
            WeightParams((n, width), WeightInit.Constant(0.0), p.dtype))
    # Input-dependent decay: a = exp(-softplus(x w_dt + b_dt) * exp(a_log)).
    # b_dt = -2 puts softplus ~0.13, i.e. a ~0.88/step at init — history
    # survives ~tens of steps; a_log tunes the per-head timescale.
    self.CreateVariable(
        "w_dt",
        WeightParams((d, n), p.params_init, p.dtype,
                     tensor_split_dims_mapping=wsdm2))
    self.CreateVariable(
        "b_dt", WeightParams((n,), WeightInit.Constant(-2.0), p.dtype))
    self.CreateVariable(
        "a_log", WeightParams((n,), WeightInit.Constant(0.0), p.dtype))
    self.CreateVariable(
        "d_skip", WeightParams((n,), WeightInit.Constant(1.0), p.dtype))
    # Per-head RMS norm on the gated scan output ((1 + scale) convention,
    # matching layers.LayerNorm).
    self.CreateVariable(
        "norm_scale",
        WeightParams((n, h), WeightInit.Constant(0.0), p.dtype))
    self.CreateVariable(
        "w_post",
        WeightParams((d, n, h), p.params_init, p.dtype,
                     tensor_split_dims_mapping=wsdm))
    if p.use_bias:
      self.CreateVariable(
          "b_post", WeightParams((d,), WeightInit.Constant(0.0), p.dtype))

  # -- projections -----------------------------------------------------------

  def _Project(self, theta, x):
    """x: [B, T, D] -> (decay_log, b, c, v, gate), all f32.

    decay_log [B, T, N]; b/c [B, T, N, S]; v/gate [B, T, N, H].
    """
    th = self.CastTheta(theta)
    v = jnp.einsum("btd,dnh->btnh", x, th.w_v)
    gate = jnp.einsum("btd,dnh->btnh", x, th.w_gate)
    if self.p.use_bias:
      v = v + th.b_v
      gate = gate + th.b_gate
    b = jnp.einsum("btd,dns->btns", x, th.w_b).astype(jnp.float32)
    c = jnp.einsum("btd,dns->btns", x, th.w_c).astype(jnp.float32)
    dt_raw = (jnp.einsum("btd,dn->btn", x, th.w_dt).astype(jnp.float32)
              + th.b_dt.astype(jnp.float32))
    rate = jnp.exp(th.a_log.astype(jnp.float32))
    decay_log = -jax.nn.softplus(dt_raw) * rate
    return decay_log, b, c, v.astype(jnp.float32), gate.astype(jnp.float32)

  def _Finish(self, theta, y, v, gate):
    """Skip + gate + per-head RMS norm + output projection.

    y/v/gate: [B, T, N, H] f32 -> [B, T, D] in fprop dtype.
    """
    th = self.CastTheta(theta)
    y = y + th.d_skip.astype(jnp.float32)[:, None] * v
    y = y * jax.nn.silu(gate)
    var = jnp.mean(jnp.square(y), axis=-1, keepdims=True)
    y = y * jax.lax.rsqrt(var + 1e-6)
    y = y * (1.0 + th.norm_scale.astype(jnp.float32))
    out = jnp.einsum("btnh,dnh->btd", y.astype(self.fprop_dtype), th.w_post)
    if self.p.use_bias:
      out = out + th.b_post
    return out

  @staticmethod
  def _MaskScanInputs(decay_log, v, paddings=None, segment_ids=None):
    """Apply the ssd_scan masking contract.

    Padded steps become exact identity (decay_log = 0, v = 0); segment
    starts become resets (decay_log = RESET_LOG). Resets are applied first
    so a padded step can never resurrect cross-segment state (packed inputs
    only pad at the tail, where nothing reads the state anyway).
    """
    if segment_ids is not None:
      prev = jnp.concatenate([segment_ids[:, :1], segment_ids[:, :-1]],
                             axis=1)
      is_reset = (segment_ids != prev)[..., None]           # [B, T, 1]
      decay_log = jnp.where(is_reset, ssd_scan.RESET_LOG, decay_log)
    if paddings is not None:
      valid = (1.0 - paddings.astype(jnp.float32))          # [B, T]
      decay_log = decay_log * valid[..., None]
      v = v * valid[..., None, None]
    return decay_log, v

  # -- training / full-sequence ----------------------------------------------

  def FProp(self, theta, query_vec, key_vec=None, value_vec=None,
            paddings=None, atten_mask=None, segment_ids=None, causal=False):
    """Returns ([B, T, D] output, None) — probs slot kept for API parity."""
    if key_vec is not None or value_vec is not None:
      raise NotImplementedError(
          "GatedSSMLayer is a self-mixer; cross-attention layers must keep "
          "MultiHeadedAttention")
    if atten_mask is not None:
      raise NotImplementedError(
          "GatedSSMLayer cannot apply additive attention masks; use "
          "paddings/segment_ids")
    if not causal:
      raise ValueError(
          "GatedSSMLayer is causal by construction; bidirectional stacks "
          "(causal=False) must keep attention")
    decay_log, b, c, v, gate = self._Project(theta, query_vec)
    decay_log, v = self._MaskScanInputs(decay_log, v, paddings, segment_ids)
    y, _ = ssd_scan.SsdScan(
        decay_log, b, c, v, chunk_size=self.p.chunk_size,
        lowering=self.p.scan_lowering)
    out = self._Finish(theta, y, v, gate)
    if paddings is not None:
      out = py_utils.ApplyPadding(paddings, out)
    return out, None

  # -- incremental decode ----------------------------------------------------

  def InitStates(self, theta, batch_size: int, max_len: int) -> NestedMap:
    """O(1) decode state: [B, N, H, S] f32, independent of max_len."""
    del theta, max_len
    n, h, s = self.p.num_heads, self._dim_per_head, self.p.state_dim
    return NestedMap(
        state=jnp.zeros((batch_size, n, h, s), jnp.float32),
        time_step=jnp.zeros((), jnp.int32))

  def StateBytesPerSlot(self) -> int:
    """Decode-state bytes per sequence (f32 state matrix)."""
    return self.p.num_heads * self._dim_per_head * self.p.state_dim * 4

  def ExtendStep(self, theta, query_vec, cached_states: NestedMap,
                 paddings=None):
    """query_vec: [B, 1, D]; returns ([B, 1, D], updated states).

    Routes the recurrence through ssd_scan.SequentialStep — the exact float
    ops of the 'sequential' lowering — so an ExtendStep chain and a
    sequential-lowering FProp agree bitwise on the state trajectory.
    """
    t = cached_states.time_step
    decay_log, b, c, v, gate = self._Project(theta, query_vec)
    if paddings is not None:
      pad_t = jax.lax.dynamic_slice_in_dim(paddings, t, 1, axis=1)  # [B, 1]
      decay_log, v = self._MaskScanInputs(decay_log, v, pad_t)
    s_new, y = ssd_scan.SequentialStep(
        cached_states.state, decay_log[:, 0], b[:, 0], c[:, 0], v[:, 0])
    out = self._Finish(theta, y[:, None], v, gate)
    return out, NestedMap(state=s_new, time_step=t + 1)

  def Prefill(self, theta, query_vec, cached_states: NestedMap,
              paddings=None, live_len: int | None = None):
    """Whole-chunk state priming: [B, C, D] for slots [t, t + C).

    A prefill starting at t=0 that covers the whole sequence is bitwise
    identical to FProp (same projections, same scan, zero initial state);
    live_len is irrelevant here — the state is O(1) regardless of length.
    """
    del live_len
    t = cached_states.time_step
    c_len = query_vec.shape[1]
    decay_log, b, c, v, gate = self._Project(theta, query_vec)
    if paddings is not None:
      pad_c = jax.lax.dynamic_slice_in_dim(paddings, t, c_len, axis=1)
      decay_log, v = self._MaskScanInputs(decay_log, v, pad_c)
    y, s_new = ssd_scan.SsdScan(
        decay_log, b, c, v, s0=cached_states.state,
        chunk_size=self.p.chunk_size, lowering=self.p.scan_lowering)
    out = self._Finish(theta, y, v, gate)
    return out, NestedMap(state=s_new, time_step=t + c_len)

  # -- continuous-batching serving -------------------------------------------

  def InitPagedStates(self, theta, num_pages: int, page_size: int,
                      num_slots: int = 0,
                      kv_cache_dtype: str | None = None) -> NestedMap:
    """One fixed [N, H, S] state per engine slot — no page pool share.

    The serving engine passes num_slots = its slot count; attention layers
    ignore it and SSM layers ignore the page-pool geometry. There is no
    time_step: per-row positions ride each PagedStep call (q_pos).
    kv_cache_dtype is accepted for stack-level threading and ignored —
    quantized SSM state slots are a documented follow-on."""
    del theta, num_pages, page_size, kv_cache_dtype
    assert num_slots > 0, (
        "GatedSSMLayer.InitPagedStates needs the engine slot count "
        "(InitPagedDecodeState(..., num_slots=max_slots))")
    n, h, s = self.p.num_heads, self._dim_per_head, self.p.state_dim
    return NestedMap(state=jnp.zeros((num_slots, n, h, s), jnp.float32))

  def PagedStep(self, theta, query_vec, cached_states: NestedMap,
                block_tables, q_pos, in_len, collect_col_states: bool = False,
                col_parent=None):
    """One continuous-batching step; query_vec [B, C, D], B = engine slots.

    block_tables is ignored — the O(1) state needs no pages. Slot re-use is
    handled device-side: a row starting a fresh request arrives with
    q_pos == 0 and its state resets to zero, so stale state from an evicted
    or finished occupant can never leak (the attention analogue is the
    engine masking via block tables). Rows past in_len are identity steps.

    collect_col_states (speculative-decoding verify steps): additionally
    return the state AFTER every column as `col_states` [B, C, N, H, S], so
    the engine can roll the slot back to the last ACCEPTED column when a
    draft suffix is rejected — the snapshot-and-restore half of KV-cursor
    rollback, for state that (unlike KV pages) is destructively folded.
    The columns are advanced through ssd_scan.SequentialStep, the exact
    float ops of the C == 1 decode path, so a verify step's per-column
    state trajectory (and output) is bitwise identical to feeding the same
    tokens one step at a time — the greedy-identity bar of spec decoding.

    col_parent (tree speculation, requires collect_col_states): [B, C]
    int32 parent COLUMN of each packed column (-1 = the row's incoming
    state). A column's recurrence then starts from its parent's trajectory
    entry instead of the packed predecessor's, which is what makes sibling
    branches independent continuations of their shared ancestor. Chain
    rows ship col_parent[:, j] == j - 1, gathering exactly the value the
    plain scan carries — the trajectory stays bitwise identical.
    """
    del block_tables
    b, c_len, _ = query_vec.shape
    q_pos = q_pos.astype(jnp.int32)
    in_len = in_len.astype(jnp.int32)
    state = jnp.where((q_pos == 0)[:, None, None, None], 0.0,
                      cached_states.state)
    decay_log, b_proj, c_proj, v, gate = self._Project(theta, query_vec)
    # paddings convention: 1.0 = invalid step.
    invalid = (jnp.arange(c_len, dtype=jnp.int32)[None]
               >= in_len[:, None]).astype(jnp.float32)
    decay_log, v = self._MaskScanInputs(decay_log, v, invalid)
    if collect_col_states:
      xs = tuple(jnp.moveaxis(t, 1, 0)
                 for t in (decay_log, b_proj, c_proj, v))
      if col_parent is not None:
        parent = jnp.clip(col_parent.astype(jnp.int32), -1, c_len - 1)

        def _TreeCol(traj, xs):
          j, dl, bb, cc, vv = xs
          pj = jax.lax.dynamic_index_in_dim(parent, j, axis=1,
                                            keepdims=False)       # [B]
          s_par = jnp.take_along_axis(
              traj, jnp.clip(pj, 0, None)[:, None, None, None, None],
              axis=1)[:, 0]
          s_in = jnp.where((pj < 0)[:, None, None, None], state, s_par)
          s_next, y_t = ssd_scan.SequentialStep(s_in, dl, bb, cc, vv)
          traj = jax.lax.dynamic_update_slice_in_dim(
              traj, s_next[:, None], j, axis=1)
          return traj, y_t

        traj0 = jnp.zeros((b, c_len) + state.shape[1:], jnp.float32)
        traj, ys = jax.lax.scan(
            _TreeCol, traj0,
            (jnp.arange(c_len, dtype=jnp.int32),) + xs)
        y = jnp.moveaxis(ys, 0, 1)
        out = self._Finish(theta, y, v, gate)
        return out, NestedMap(state=traj[:, -1], col_states=traj)

      def _Col(s, xs):
        dl, bb, cc, vv = xs
        s_next, y_t = ssd_scan.SequentialStep(s, dl, bb, cc, vv)
        return s_next, (y_t, s_next)

      s_new, (ys, cols) = jax.lax.scan(_Col, state, xs)
      y = jnp.moveaxis(ys, 0, 1)
      out = self._Finish(theta, y, v, gate)
      return out, NestedMap(state=s_new,
                            col_states=jnp.moveaxis(cols, 0, 1))
    if c_len == 1:
      s_new, y = ssd_scan.SequentialStep(
          state, decay_log[:, 0], b_proj[:, 0], c_proj[:, 0], v[:, 0])
      y = y[:, None]
    else:
      y, s_new = ssd_scan.SsdScan(
          decay_log, b_proj, c_proj, v, s0=state,
          chunk_size=min(self.p.chunk_size, c_len),
          lowering=self.p.scan_lowering)
    out = self._Finish(theta, y, v, gate)
    return out, NestedMap(state=s_new)

  def RaggedStep(self, theta, query_vec, cached_states: NestedMap,
                 block_tables, rows, collect_col_states: bool = False):
    """Packed-token step (core/ragged.py RaggedRows): query_vec [1, T, D].

    The O(1) recurrence is inherently per-row, so the ragged step is the
    EXISTING PagedStep on a row view of the pack: gather each slot's chunk
    off the token axis through rows.row_cols ([B, wmax, D]), run the
    per-row-length scan (rows.row_len masks the tail as identity steps —
    including whole rows with 0 tokens this step), scatter outputs back to
    token order. rows.row_q_pos carries the slot-reuse reset trigger
    (q_pos == 0), which is why 0-token live rows ride with their true
    sequence position, never 0.
    """
    del block_tables
    x_rows = query_vec[0][rows.row_cols]             # [B, wmax, D]
    wmax = x_rows.shape[1]
    out_rows, new_states = self.PagedStep(
        theta, x_rows, cached_states, None, rows.row_q_pos, rows.row_len,
        collect_col_states=collect_col_states,
        col_parent=rows.col_parent if collect_col_states else None)
    row = jnp.clip(rows.row_of.astype(jnp.int32), 0, x_rows.shape[0] - 1)
    col = jnp.clip(rows.col_of.astype(jnp.int32), 0, wmax - 1)
    return out_rows[row, col][None], new_states
