"""Teacher/student distillation task wrapper (ref
`lingvo/core/distillation_task.py`).

Both models live in one task; the teacher's variables are frozen (excluded
from every learner via a variable filter and wrapped in stop_gradient), and
the loss mixes the student's ground-truth loss with a soft-label KL against
the teacher's logits. Teacher weights typically arrive via
`train.init_from_checkpoint_rules` (warm start) mapping `teacher\\..*`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_model
from lingvo_tpu.core.nested_map import NestedMap


class DistillationTask(base_model.BaseTask):
  """Wraps a teacher task and a student task of the same interface."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("teacher", None, "Teacher task Params (frozen).")
    p.Define("student", None, "Student task Params (trained).")
    p.Define("distill_weight", 0.5,
             "Mix: loss = (1-w) * student_loss + w * distill_KL.")
    p.Define("temperature", 1.0, "Soft-label temperature.")
    return p

  def __init__(self, params):
    params = params.Copy()
    # freeze the teacher in every learner (ref: teacher vars excluded from
    # BProp) — set on the learner params before they instantiate
    learners = params.train.learner
    for lp in (learners if isinstance(learners, (list, tuple))
               else [learners]):
      assert lp.bprop_variable_exclusion is None, (
          "DistillationTask owns bprop_variable_exclusion")
      lp.bprop_variable_exclusion = r"^teacher\."
    super().__init__(params)
    p = self.p
    assert p.teacher is not None and p.student is not None
    self.CreateChild("teacher", p.teacher)
    self.CreateChild("student", p.student)

  def ComputePredictions(self, theta, input_batch):
    frozen_teacher = jax.tree_util.tree_map(jax.lax.stop_gradient,
                                            theta.teacher)
    teacher_preds = self.teacher.ComputePredictions(frozen_teacher,
                                                    input_batch)
    student_preds = self.student.ComputePredictions(theta.student,
                                                    input_batch)
    return NestedMap(teacher=teacher_preds, student=student_preds)

  def ComputeLoss(self, theta, predictions, input_batch):
    p = self.p
    metrics, per_example = self.student.ComputeLoss(
        theta.student, predictions.student, input_batch)
    hard_loss, weight = metrics.loss
    t = p.temperature
    t_logits = predictions.teacher.logits.astype(jnp.float32) / t
    s_logits = predictions.student.logits.astype(jnp.float32) / t
    t_probs = jax.nn.softmax(t_logits, axis=-1)
    kl = jnp.sum(
        t_probs * (jax.nn.log_softmax(t_logits, -1)
                   - jax.nn.log_softmax(s_logits, -1)), axis=-1)
    if "paddings" in input_batch:
      w = 1.0 - input_batch.paddings
      distill_loss = jnp.sum(kl * w) / jnp.maximum(jnp.sum(w), 1e-8)
    else:
      distill_loss = jnp.mean(kl)
    distill_loss = distill_loss * (t * t)  # classic T^2 scaling
    total = (1.0 - p.distill_weight) * hard_loss + (
        p.distill_weight * distill_loss)
    metrics.loss = (total, weight)
    metrics.hard_loss = (hard_loss, weight)
    metrics.distill_loss = (distill_loss, weight)
    return metrics, per_example
