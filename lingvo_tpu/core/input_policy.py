"""Input-placement policy (ref `lingvo/core/input_policy.py`).

The reference wraps an input generator's params so its graph nodes land on
`cluster.input_device` (a TF device string). In the JAX stack, input
generators run host-side by construction and batches move to devices via
explicit `jax.device_put` with a sharding (see `parallel/mesh.PutBatch`), so
device placement needs no subclass surgery. `Apply` remains the hook: it
consults the current cluster and, for multi-host runs, wraps the generator
so each process reads only its per-host shard (the `InfeedContextScope`
host-sharding concept, ref `cluster.py:47-59`).
"""

from __future__ import annotations


def Apply(input_params):
  """Possibly updates input_params according to the cluster's input policy.

  On multi-host runs (an explicit cluster with several infeed hosts, or —
  absent a cluster context — a multi-process jax runtime), stamps this
  process's (host_index, num_hosts) into the generator params before
  instantiation: file-based generators shard their file list with them
  (`FileBasedSequenceInputGenerator` routes them into the native yielder),
  and generators with a `seed` param get it diverged per host so synthetic
  streams don't feed duplicate rows. A generator without those params on a
  multi-host run fails loudly: every host silently reading the full stream
  corrupts epoch and global-batch accounting.
  """
  from lingvo_tpu.core import cluster as cluster_lib
  current = cluster_lib.Current()
  if current is not None and current.num_infeed_hosts > 1:
    shard, num_shards = current.InputShardParams()
  else:
    import jax
    if jax.process_count() <= 1:
      return input_params
    shard, num_shards = jax.process_index(), jax.process_count()
  if "num_hosts" not in input_params or "host_index" not in input_params:
    raise ValueError(
        f"{input_params.cls.__name__} has no num_hosts/host_index params "
        f"but the cluster has {num_shards} infeed hosts; add them (see "
        f"BaseInputGenerator) or run single-host input.")
  out = input_params.Copy().Set(num_hosts=num_shards, host_index=shard)
  if "seed" in out and isinstance(out.seed, int):
    out.seed = out.seed + 1000003 * shard
  return out


def Instantiate(input_params):
  """The one chokepoint for turning input params into a generator.

  Every runner/task/tool must instantiate input generators through here
  (never `params.Instantiate()` directly) so multi-host shard stamping is
  never skipped.
  """
  return Apply(input_params).Instantiate()
