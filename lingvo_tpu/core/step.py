"""Step API: layers that process sequences one timestep at a time.

Re-designs the reference's Step abstraction (`lingvo/core/step.py:40`,
`lingvo/core/steps/{rnn,attention,embedding}_steps.py`) the TPU-native way.
A Step is a layer with three phases:

  prepared = step.PrepareExternalInputs(theta, external_inputs)   # once
  state0   = step.ZeroState(theta, prepared, batch_size)          # once
  out, s1  = step.FProp(theta, prepared, step_inputs, padding, s) # per step

All state is a NestedMap of fixed-shape arrays, so a Step composes directly
with `jax.lax.scan` (see `RunOverSequence`) and with jit'd autoregressive
decode loops — the reference needed its hand-written `recurrent.Recurrent`
while-loop wrapper (`step.py:660` RecurrentStepWrapper) for the same thing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import rnn_cell
from lingvo_tpu.core import seq_attention
from lingvo_tpu.core.nested_map import NestedMap


class Step(base_layer.BaseLayer):
  """A layer processing input sequences step-by-step (ref `step.py:40`)."""

  def PrepareExternalInputs(self, theta, external_inputs):
    """Precomputes per-sequence quantities (e.g. packed attention source).

    Default: recursively prepares Step children, keyed by child name
    (ref `step.py:65`).
    """
    external_inputs = external_inputs or NestedMap()
    packed = NestedMap()
    for name, child in self.children.items():
      if isinstance(child, Step):
        packed[name] = child.PrepareExternalInputs(
            self.ChildTheta(theta, name),
            external_inputs.get(name, NestedMap()))
      elif isinstance(child, list) and child and isinstance(child[0], Step):
        ctheta = self.ChildTheta(theta, name)
        packed[name] = [
            c.PrepareExternalInputs(ctheta[i],
                                    external_inputs.get(name, NestedMap()))
            for i, c in enumerate(child)
        ]
    return packed

  def ZeroState(self, theta, prepared_inputs, batch_size):
    """Initial recurrent state; default recurses over Step children."""
    state0 = NestedMap()
    for name, child in self.children.items():
      if isinstance(child, Step):
        state0[name] = child.ZeroState(
            self.ChildTheta(theta, name), prepared_inputs.get(name),
            batch_size)
      elif isinstance(child, list) and child and isinstance(child[0], Step):
        ctheta = self.ChildTheta(theta, name)
        state0[name] = [
            c.ZeroState(ctheta[i], prepared_inputs[name][i], batch_size)
            for i, c in enumerate(child)
        ]
    return state0

  def FProp(self, theta, prepared_inputs, step_inputs, padding, state0):
    """One step. Returns (output NestedMap, state1 NestedMap).

    step_inputs.inputs is a list of [b, ...] tensors for this timestep;
    padding is [b] (1.0 = padded).
    """
    raise NotImplementedError(type(self).__name__)


class StatelessLayerStep(Step):
  """Wraps any stateless layer as a Step (ref `step.py:168`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("layer", None, "Params of the layer to wrap.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self.CreateChild("layer", self.p.layer)

  def FProp(self, theta, prepared_inputs, step_inputs, padding, state0):
    del prepared_inputs, padding
    out = self.layer.FProp(
        self.ChildTheta(theta, "layer"), *step_inputs.inputs)
    return NestedMap(output=out), state0


class StackStep(Step):
  """Sequential composition of steps with optional residual connections.

  Output of step i feeds step i+1's inputs. With residuals on
  (`residual_start >= 0`), for i >= residual_start:
  `output[i] = sub[i](output[i-1]) + output[i - residual_stride]` where
  `output[-1]` is the stack's step input (ref `step.py:212-247`). An optional
  `step_inputs.context` tensor is fed to every layer.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("sub", [], "List of sub-step Params.")
    p.Define("residual_start", -1,
             "Index at which residual connections start; <0 disables.")
    p.Define("residual_stride", 1, "Distance between residual endpoints.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self.CreateChildren("sub", list(self.p.sub))

  def PrepareExternalInputs(self, theta, external_inputs):
    external_inputs = external_inputs or NestedMap()
    ctheta = self.ChildTheta(theta, "sub")
    return NestedMap(sub=[
        s.PrepareExternalInputs(ctheta[i], external_inputs)
        for i, s in enumerate(self.sub)
    ])

  def ZeroState(self, theta, prepared_inputs, batch_size):
    ctheta = self.ChildTheta(theta, "sub")
    return NestedMap(sub=[
        s.ZeroState(ctheta[i], prepared_inputs.sub[i], batch_size)
        for i, s in enumerate(self.sub)
    ])

  def FProp(self, theta, prepared_inputs, step_inputs, padding, state0):
    p = self.p
    ctheta = self.ChildTheta(theta, "sub")
    inputs = list(step_inputs.inputs)
    additional = [step_inputs.context] if "context" in step_inputs else []
    # residual_outputs[j+1] = output of layer j; [0] = the stack's input.
    residual_outputs = [jnp.concatenate(inputs, axis=-1)
                        if len(inputs) > 1 else inputs[0]]
    state1 = NestedMap(sub=[])
    for i, s in enumerate(self.sub):
      out, sub_state = s.FProp(ctheta[i], prepared_inputs.sub[i],
                               NestedMap(inputs=inputs + additional), padding,
                               state0.sub[i])
      state1.sub.append(sub_state)
      output = out.output
      if p.residual_start >= 0 and i >= p.residual_start:
        idx = i + 1 - p.residual_stride
        if idx < 0:
          raise ValueError(
              f"residual connection at layer {i} would reach before the "
              f"stack input (residual_stride={p.residual_stride}); set "
              f"residual_start >= residual_stride - 1")
        output = output + residual_outputs[idx]
      residual_outputs.append(output)
      inputs = [output]
    return NestedMap(output=inputs[0]), state1


class ParallelStep(Step):
  """Runs several steps on the same input; concatenates outputs on the last
  dim (ref `step.py:341`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("sub", [], "List of sub-step Params.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self.CreateChildren("sub", list(self.p.sub))

  def PrepareExternalInputs(self, theta, external_inputs):
    external_inputs = external_inputs or NestedMap()
    ctheta = self.ChildTheta(theta, "sub")
    return NestedMap(sub=[
        s.PrepareExternalInputs(ctheta[i], external_inputs)
        for i, s in enumerate(self.sub)
    ])

  def ZeroState(self, theta, prepared_inputs, batch_size):
    ctheta = self.ChildTheta(theta, "sub")
    return NestedMap(sub=[
        s.ZeroState(ctheta[i], prepared_inputs.sub[i], batch_size)
        for i, s in enumerate(self.sub)
    ])

  def FProp(self, theta, prepared_inputs, step_inputs, padding, state0):
    ctheta = self.ChildTheta(theta, "sub")
    outs, state1 = [], NestedMap(sub=[])
    for i, s in enumerate(self.sub):
      out, sub_state = s.FProp(ctheta[i], prepared_inputs.sub[i], step_inputs,
                               padding, state0.sub[i])
      outs.append(out.output)
      state1.sub.append(sub_state)
    return NestedMap(output=jnp.concatenate(outs, axis=-1)), state1


class IteratorStep(Step):
  """Iterates over the time dim of a tensor provided as an external input;
  state is the time index (ref `step.py:572`)."""

  def PrepareExternalInputs(self, theta, external_inputs):
    return external_inputs  # .inputs [b, t, ...], .paddings [b, t]

  def ZeroState(self, theta, prepared_inputs, batch_size):
    del theta, batch_size
    return NestedMap(t=jnp.zeros((), jnp.int32))

  def FProp(self, theta, prepared_inputs, step_inputs, padding, state0):
    del theta, step_inputs, padding
    t = state0.t
    out = jax.tree_util.tree_map(
        lambda x: jax.lax.dynamic_index_in_dim(x, t, axis=1, keepdims=False),
        prepared_inputs.inputs)
    pad = jax.lax.dynamic_index_in_dim(
        prepared_inputs.paddings, t, axis=1, keepdims=False)
    return NestedMap(output=out, padding=pad), NestedMap(t=t + 1)


class RnnStep(Step):
  """An RNN cell as a Step (ref `steps/rnn_steps.py:21`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("cell", rnn_cell.LSTMCellSimple.Params(), "The RNN cell.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self.CreateChild("cell", self.p.cell)

  def ZeroState(self, theta, prepared_inputs, batch_size):
    del theta, prepared_inputs
    return self.cell.InitState(batch_size)

  def FProp(self, theta, prepared_inputs, step_inputs, padding, state0):
    del prepared_inputs
    x = step_inputs.inputs[0]
    if len(step_inputs.inputs) > 1:
      x = jnp.concatenate(step_inputs.inputs, axis=-1)
    state1 = self.cell.FProp(self.ChildTheta(theta, "cell"), state0, x,
                             padding)
    return NestedMap(output=self.cell.GetOutput(state1)), state1


def RnnStackStep(cell_tpl, num_layers, residual_start=1):
  """A stack of RnnSteps with residuals (ref `steps/rnn_steps.py:99`)."""
  subs = []
  for i in range(num_layers):
    subs.append(RnnStep.Params().Set(name=f"rnn_{i}", cell=cell_tpl.Copy()))
  return StackStep.Params().Set(sub=subs, residual_start=residual_start)


class AttentionStep(Step):
  """Per-step attention over a fixed source sequence
  (ref `steps/attention_steps.py:23`).

  external_inputs: .src [b, t, d], .paddings [b, t] (optionally .context).
  step_inputs: [query [b, q]]. Output: .context [b, d], .probs [b, t].
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("atten", seq_attention.AdditiveAttention.Params(),
             "Sequence attention params.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self.CreateChild("atten", self.p.atten)

  def PrepareExternalInputs(self, theta, external_inputs):
    packed = self.atten.PackSource(
        self.ChildTheta(theta, "atten"), external_inputs.src,
        external_inputs.paddings)
    return NestedMap(packed=packed,
                     src_len=external_inputs.src.shape[1])

  def ZeroState(self, theta, prepared_inputs, batch_size):
    del theta
    return NestedMap(
        atten=self.atten.ZeroAttentionState(batch_size,
                                            prepared_inputs.src_len))

  def FProp(self, theta, prepared_inputs, step_inputs, padding, state0):
    del padding
    query = step_inputs.inputs[0]
    context, probs, atten_state = self.atten.ComputeContextVector(
        self.ChildTheta(theta, "atten"), prepared_inputs.packed, query,
        state0.atten)
    return (NestedMap(output=context, context=context, probs=probs),
            NestedMap(atten=atten_state))


class EmbeddingStep(Step):
  """Per-step embedding lookup (ref `steps/embedding_steps.py:23`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    from lingvo_tpu.core import layers  # local to avoid import cycle
    p.Define("emb", layers.SimpleEmbeddingLayer.Params(), "Embedding layer.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self.CreateChild("emb", self.p.emb)

  def FProp(self, theta, prepared_inputs, step_inputs, padding, state0):
    del prepared_inputs, padding
    out = self.emb.EmbLookup(self.ChildTheta(theta, "emb"),
                             step_inputs.inputs[0])
    return NestedMap(output=out), state0


def RunOverSequence(step, theta, prepared_inputs, inputs, paddings,
                    state0=None, extra_step_inputs=None):
  """Drives a Step over a [b, t, ...] sequence with `jax.lax.scan`.

  The TPU-native replacement for the reference's RecurrentStepWrapper
  (`step.py:660`): one compiled scan, differentiable, no host loop.

  Returns (outputs NestedMap with leaves [b, t, ...], final state).
  """
  b, t = paddings.shape[0], paddings.shape[1]
  if state0 is None:
    state0 = step.ZeroState(theta, prepared_inputs, b)
  xs = NestedMap(
      inp=jax.tree_util.tree_map(lambda x: jnp.swapaxes(x, 0, 1), inputs),
      pad=jnp.swapaxes(paddings, 0, 1))

  def _Body(state, xs_t):
    step_inputs = NestedMap(inputs=list(xs_t.inp) if isinstance(
        xs_t.inp, (list, tuple)) else [xs_t.inp])
    if extra_step_inputs:
      step_inputs.inputs.extend(extra_step_inputs)
    out, state1 = step.FProp(theta, prepared_inputs, step_inputs, xs_t.pad,
                             state)
    return state1, out

  final_state, outs = jax.lax.scan(_Body, state0, xs, length=t)
  outs = jax.tree_util.tree_map(lambda x: jnp.swapaxes(x, 0, 1), outs)
  return outs, final_state
