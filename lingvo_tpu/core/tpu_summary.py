"""In-loop summaries: scalars/tensors recorded inside the compiled step.

Re-designs `lingvo/core/tpu_summary.py` (scalar/tensor collected by a
context and hoisted out of `tf.while_loop` via `RewriteLoopContext:99`,
`merge_all:227`) the JAX way: model code calls `tpu_summary.scalar(...)`
anywhere inside FProp; a trace-time context collects the (tracer) values and
the train/eval step returns them as part of its output pytree — under jit
there is no graph surgery to do, values simply flow out as results. The
program layer writes them to TensorBoard next to the regular metrics.

Like the reference, which could only merge summaries emitted inside its
training while-loop, values recorded inside a `lax.scan` body are local to
that trace: scan-over-layers code must carry them out of the scan itself
(the same contract as `py_utils.AddAuxLoss`; see `CollectSummaries`).
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap

_STACK_NAME = "tpu_summary"


def _SafeName(name: str) -> str:
  """Summary names travel as NestedMap keys: map '/'/'.'-scoped names (the
  reference's convention) onto valid identifiers."""
  safe = "".join(c if (c.isalnum() or c == "_") else "_" for c in name)
  if not safe or safe[0].isdigit():
    safe = "s_" + safe
  return safe


def Context():
  """Context collecting summaries emitted by FProp; yields the live dict."""
  return py_utils.NamedCollectionContext(_STACK_NAME)


def enabled() -> bool:
  return py_utils.NamedCollectionActive(_STACK_NAME)


def scalar(name: str, value: Any) -> None:
  """Records a scalar summary; repeated emissions merge into a mean.

  Matches the reference semantics where one summary name emitted at several
  points (or several microbatches) produces one merged value
  (`tpu_summary.py:227` merge_all).
  """
  collected = py_utils.NamedCollectionTop(_STACK_NAME)
  if collected is None:
    return
  name = _SafeName(name)
  v = jnp.asarray(value, jnp.float32)
  prev = collected.get(name)
  if prev is None:
    collected[name] = (v, jnp.asarray(1.0, jnp.float32))
  else:
    ps, pc = prev
    collected[name] = (ps + v, pc + 1.0)


def tensor(name: str, value: Any) -> None:
  """Records a full tensor summary (last emission wins)."""
  collected = py_utils.NamedCollectionTop(_STACK_NAME)
  if collected is None:
    return
  collected[_SafeName(name)] = (jnp.asarray(value), None)


def Merged(collected: dict) -> NestedMap:
  """Merges a collected dict into {name: value} (means for scalars)."""
  out = NestedMap()
  for name, (val, count) in collected.items():
    out[name] = val if count is None else val / count
  return out


def CollectSummaries(fn):
  """Wraps a scan/vmap body so its summaries exit via the return value.

  Returns a callable whose result is `(fn(...), summaries NestedMap)`; the
  caller re-emits each entry with `scalar`/`tensor` AFTER the scan (e.g. on
  the aggregated carry), keeping tracers inside their trace.
  """

  def _Wrapped(*args, **kwargs):
    with Context() as collected:
      out = fn(*args, **kwargs)
    return out, Merged(collected)

  return _Wrapped
