"""Symbolic dimensions in Params (ref `lingvo/core/symbolic.py`, `tshape.py`).

Experiment templates can set dims to sympy symbols (e.g. blocks whose widths
scale together) and resolve them at instantiation time:

  D = symbolic.Symbol("model_dim")
  p.hidden_dim = 4 * D
  with symbolic.SymbolToValueMap({D: 1024}):
    hidden = symbolic.EvalExpr(p.hidden_dim)   # -> 4096

Layers that may receive symbolic dims call `EvalExpr` (integers pass
through untouched, so non-symbolic configs pay nothing).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any

import sympy

_TLS = threading.local()


def Symbol(name: str) -> "sympy.Symbol":
  """A positive-integer symbolic dimension."""
  return sympy.Symbol(name, positive=True, integer=True)


def _Maps() -> list:
  if not hasattr(_TLS, "maps"):
    _TLS.maps = []
  return _TLS.maps


@contextlib.contextmanager
def SymbolToValueMap(mapping: dict):
  """Binds symbol values for EvalExpr within the scope (stackable; inner
  scopes override, ref symbolic.SymbolToValueMap)."""
  _Maps().append(dict(mapping))
  try:
    yield
  finally:
    _Maps().pop()


def IsExpr(v: Any) -> bool:
  return isinstance(v, sympy.Expr) and not isinstance(v, sympy.Integer)


def EvalExpr(v: Any) -> Any:
  """Resolves a (possibly symbolic) value with the active symbol bindings.

  Plain ints/floats/tuples pass through; unresolved symbols raise.
  """
  if isinstance(v, (list, tuple)):
    return type(v)(EvalExpr(x) for x in v)
  if not isinstance(v, sympy.Expr):
    return v
  subs = {}
  for m in _Maps():
    subs.update(m)
  out = v.subs(subs) if subs else v
  if isinstance(out, sympy.Integer):
    return int(out)
  if isinstance(out, (sympy.Float, sympy.Rational)):
    return float(out)
  if out.free_symbols:
    raise ValueError(
        f"Unresolved symbols {out.free_symbols} in {v}; wrap instantiation "
        "in symbolic.SymbolToValueMap({...})")
  return out


class Shape:
  """Symbolic tensor shape algebra (ref `tshape.Shape`): concatenation,
  slicing, and products stay symbolic until evaluated."""

  def __init__(self, dims):
    self._dims = list(dims)

  def __getitem__(self, i):
    out = self._dims[i]
    return Shape(out) if isinstance(out, list) else out

  def __len__(self):
    return len(self._dims)

  def __add__(self, other):
    other_dims = other._dims if isinstance(other, Shape) else list(other)
    return Shape(self._dims + other_dims)

  def __eq__(self, other):
    other_dims = other._dims if isinstance(other, Shape) else list(other)
    return [sympy.simplify(a - b) == 0 if IsExpr(a) or IsExpr(b) else a == b
            for a, b in zip(self._dims, other_dims)] == [True] * len(
                self._dims)

  @property
  def size(self):
    out = 1
    for d in self._dims:
      out = out * d
    return out

  def ToTuple(self):
    return tuple(EvalExpr(d) for d in self._dims)

  def __repr__(self):
    return f"Shape({self._dims})"
