"""Shared token-sampling helper for the decode paths.

One function, used by both serving surfaces (`runners/gshard_decode.py`
`_SampleLoop` and `serving/engine.py` `_Step`) so the two stay
token-identical under the same (seed, temperature, top_k) triple:

- `temperature <= 0` lowers to pure argmax — bitwise the greedy path, no
  RNG traffic at all (the branch is resolved at trace time, so the jitted
  greedy program is unchanged by this module's existence).
- `temperature > 0` divides logits by the temperature, optionally keeps
  only the top-k logits per row, and draws from `jax.random.categorical`.
- `row_seeds` gives each batch row its own stream: row i draws from
  `fold_in(key, row_seeds[i])`. Two requests with the same per-request
  seed produce the same continuation regardless of which batch rows or
  neighbors they were scheduled with — the property the continuous-
  batching engine needs for replayable requests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def SampleFromLogits(logits, key, temperature: float = 0.0,
                     top_k: int = 0, row_seeds=None, positions=None):
  """Draws one token id per row from `logits`.

  Args:
    logits: [B, V] float logits (any float dtype).
    key: PRNGKey for this step. Unused (may be anything) when
      `temperature <= 0`.
    temperature: static python float. <= 0 means greedy argmax.
    top_k: static python int. > 0 restricts sampling to the k largest
      logits per row (applied after temperature, which doesn't change
      the top-k set). 0 = full-vocab sampling.
    row_seeds: optional [B] int32 per-request seeds. When given, row i
      samples from `fold_in(key, row_seeds[i])` instead of the shared
      per-step key, making each row's draw independent of its batch
      neighbors.
    positions: optional [B] int32 per-row output index, folded in after
      row_seeds. For callers whose `key` is already per-step (a scan over
      split keys) this is unnecessary; the continuous-batching engine
      uses a FIXED key and passes each request's tokens-generated-so-far
      here, so a request's stream depends only on (key, seed, position),
      never on which engine iteration decoded it. Requires row_seeds.

  Returns:
    [B] int32 token ids.
  """
  if temperature <= 0.0:
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)
  logits = logits.astype(jnp.float32) / float(temperature)
  if top_k > 0 and top_k < logits.shape[-1]:
    # kth-largest per row; ties at the threshold all stay live, which
    # only widens the candidate set and keeps the mask monotone in k
    kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
    logits = jnp.where(logits < kth, -jnp.inf, logits)
  if row_seeds is None:
    assert positions is None, "positions requires row_seeds"
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)

  def _RowKey(seed, pos):
    k = jax.random.fold_in(key, seed)
    return k if pos is None else jax.random.fold_in(k, pos)

  if positions is None:
    row_keys = jax.vmap(lambda s: _RowKey(s, None))(
        row_seeds.astype(jnp.uint32))
  else:
    row_keys = jax.vmap(_RowKey)(row_seeds.astype(jnp.uint32),
                                 positions.astype(jnp.uint32))
  return jax.vmap(
      lambda k, l: jax.random.categorical(k, l, axis=-1))(
          row_keys, logits).astype(jnp.int32)
