"""Shared token-sampling helper for the decode paths.

One function, used by both serving surfaces (`runners/gshard_decode.py`
`_SampleLoop` and `serving/engine.py` `_Step`) so the two stay
token-identical under the same (seed, temperature, top_k) triple:

- `temperature <= 0` lowers to pure argmax — bitwise the greedy path, no
  RNG traffic at all (the branch is resolved at trace time, so the jitted
  greedy program is unchanged by this module's existence).
- `temperature > 0` divides logits by the temperature, optionally keeps
  only the top-k logits per row, and draws from `jax.random.categorical`.
- `row_seeds` gives each batch row its own stream: row i draws from
  `fold_in(key, row_seeds[i])`. Two requests with the same per-request
  seed produce the same continuation regardless of which batch rows or
  neighbors they were scheduled with — the property the continuous-
  batching engine needs for replayable requests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _TransformLogits(logits, temperature: float, top_k: int):
  """Temperature + top-k mask, exactly as SampleFromLogits applies them."""
  logits = logits.astype(jnp.float32) / float(temperature)
  if top_k > 0 and top_k < logits.shape[-1]:
    kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
    logits = jnp.where(logits < kth, -jnp.inf, logits)
  return logits


def SampleFromLogits(logits, key, temperature: float = 0.0,
                     top_k: int = 0, row_seeds=None, positions=None):
  """Draws one token id per row from `logits`.

  Args:
    logits: [B, V] float logits (any float dtype).
    key: PRNGKey for this step. Unused (may be anything) when
      `temperature <= 0`.
    temperature: static python float. <= 0 means greedy argmax.
    top_k: static python int. > 0 restricts sampling to the k largest
      logits per row (applied after temperature, which doesn't change
      the top-k set). 0 = full-vocab sampling.
    row_seeds: optional [B] int32 per-request seeds. When given, row i
      samples from `fold_in(key, row_seeds[i])` instead of the shared
      per-step key, making each row's draw independent of its batch
      neighbors.
    positions: optional [B] int32 per-row output index, folded in after
      row_seeds. For callers whose `key` is already per-step (a scan over
      split keys) this is unnecessary; the continuous-batching engine
      uses a FIXED key and passes each request's tokens-generated-so-far
      here, so a request's stream depends only on (key, seed, position),
      never on which engine iteration decoded it. Requires row_seeds.

  Returns:
    [B] int32 token ids.
  """
  if temperature <= 0.0:
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)
  # ties at the top-k threshold all stay live, which only widens the
  # candidate set and keeps the mask monotone in k
  logits = _TransformLogits(logits, temperature, top_k)
  if row_seeds is None:
    assert positions is None, "positions requires row_seeds"
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)

  def _RowKey(seed, pos):
    k = jax.random.fold_in(key, seed)
    return k if pos is None else jax.random.fold_in(k, pos)

  if positions is None:
    row_keys = jax.vmap(lambda s: _RowKey(s, None))(
        row_seeds.astype(jnp.uint32))
  else:
    row_keys = jax.vmap(_RowKey)(row_seeds.astype(jnp.uint32),
                                 positions.astype(jnp.uint32))
  return jax.vmap(
      lambda k, l: jax.random.categorical(k, l, axis=-1))(
          row_keys, logits).astype(jnp.int32)


def SpecVerifyTokens(target_logits, draft_tokens, draft_logits, key,
                     temperature: float = 0.0, top_k: int = 0,
                     row_seeds=None, row_pos=None, draft_valid=None):
  """Draft-and-verify acceptance over one ragged verify step.

  The verify step fed each row its last emitted token t0 followed by K
  draft proposals d_1..d_K, so `target_logits[:, j]` is the target
  distribution for the token AFTER verify input j (col 0 predicts the
  token after t0, i.e. what the non-speculative engine would emit next).
  `draft_tokens[:, j]` (= d_{j+1}) is checked against col j.

  Acceptance rules:
  - `temperature <= 0`: greedy — accept the longest prefix of proposals
    that match the target argmax chain. The emitted tokens are the target
    argmaxes themselves, so the output stream is bitwise identical to the
    non-speculative greedy engine no matter what the draft proposed.
  - `temperature > 0`: standard residual speculative sampling. Proposal j
    is accepted iff u_j < p_j(d)/q_j(d) with p/q the temperature/top-k
    transformed target/draft distributions; on first rejection the token
    is drawn from the normalized residual max(p - q, 0) — accept-or-
    residual together emit exactly p, so any draft leaves each request's
    output law unchanged. When every valid proposal is accepted, the
    bonus token at the next column is drawn with the SAME (key, row seed,
    output position) categorical call the non-speculative engine would
    have used at that stream position (bitwise).

  Args:
    target_logits: [B, C, V] verify-step logits (C = K+1 columns).
    draft_tokens: [B, K] int32 proposals.
    draft_logits: [B, K, V] draft logits at each proposal (ignored when
      temperature <= 0; must be given otherwise).
    key: engine PRNGKey (as SampleFromLogits).
    temperature/top_k: static sampling controls (as SampleFromLogits).
    row_seeds: [B] int32 per-request seeds (required at temperature > 0).
    row_pos: [B] int32 output index of col 0's token per row — the draw at
      col j uses stream position row_pos + j, composing with the
      per-request replayable streams.
    draft_valid: optional [B, K] bool — proposals beyond a row's ragged
      in_len are marked invalid and can never be accepted.

  Returns:
    (out_tokens [B, C] int32, accept_len [B] int32). The caller emits
    out_tokens[i, :accept_len[i] + 1]; entries past that are unconsumed.
  """
  b, c, _ = target_logits.shape
  k = c - 1
  assert draft_tokens.shape[1] == k, (draft_tokens.shape, c)
  if draft_valid is None:
    draft_valid = jnp.ones((b, k), bool)
  if temperature <= 0.0:
    g = jnp.argmax(target_logits, axis=-1).astype(jnp.int32)   # [B, C]
    match = (g[:, :k] == draft_tokens) & draft_valid
    accept_len = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                         axis=1)
    return g, accept_len.astype(jnp.int32)

  assert row_seeds is not None and row_pos is not None, (
      "speculative sampling at temperature > 0 needs per-request streams")
  tl = _TransformLogits(target_logits, temperature, top_k)      # [B, C, V]
  ql = _TransformLogits(draft_logits, temperature, top_k)       # [B, K, V]
  p = jax.nn.softmax(tl, axis=-1)
  q = jax.nn.softmax(ql, axis=-1)
  pos = (row_pos.astype(jnp.uint32)[:, None]
         + jnp.arange(c, dtype=jnp.uint32)[None])               # [B, C]

  def _PosKey(seed, pp):
    return jax.random.fold_in(jax.random.fold_in(key, seed), pp)

  keys = jax.vmap(jax.vmap(_PosKey, (None, 0)))(
      row_seeds.astype(jnp.uint32), pos)                        # [B, C] keys
  # acceptance coin per proposal column: u_j q_j(d) < p_j(d)
  u = jax.vmap(jax.vmap(lambda kk: jax.random.uniform(
      jax.random.fold_in(kk, 1))))(keys[:, :k])                 # [B, K]
  d_idx = draft_tokens[..., None].astype(jnp.int32)
  p_d = jnp.take_along_axis(p[:, :k], d_idx, axis=-1)[..., 0]
  q_d = jnp.take_along_axis(q[:, :k], d_idx, axis=-1)[..., 0]
  accept = (u * q_d < p_d) & draft_valid
  accept_len = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1),
                       axis=1).astype(jnp.int32)
  # the non-speculative draw at every column (bitwise the SampleFromLogits
  # call the legacy engine makes at that stream position) — used as the
  # bonus token when all valid proposals were accepted
  bonus = jax.vmap(jax.vmap(
      lambda kk, ll: jax.random.categorical(kk, ll, axis=-1)))(
          keys, tl).astype(jnp.int32)                           # [B, C]
  # residual token per proposal column: sample norm(max(p - q, 0)); if the
  # residual is identically zero (p == q) any draw from p is lawful
  resid = jnp.maximum(p[:, :k] - q, 0.0)
  degenerate = jnp.sum(resid, axis=-1, keepdims=True) <= 0.0
  resid_logits = jnp.where(degenerate, tl[:, :k],
                           jnp.log(jnp.maximum(resid, 1e-30)))
  rej = jax.vmap(jax.vmap(
      lambda kk, ll: jax.random.categorical(
          jax.random.fold_in(kk, 2), ll, axis=-1)))(
              keys[:, :k], resid_logits).astype(jnp.int32)      # [B, K]
  # col accept_len is a REJECTION when a valid proposal exists there,
  # else the all-accepted bonus position
  n_valid = jnp.sum(jnp.cumprod(draft_valid.astype(jnp.int32), axis=1),
                    axis=1)
  rejected = accept_len < n_valid                               # [B]
  rej_pad = jnp.concatenate([rej, bonus[:, -1:]], axis=1)       # [B, C]
  at_cut = jnp.where(rejected[:, None], rej_pad, bonus)
  d_pad = jnp.concatenate(
      [draft_tokens.astype(jnp.int32),
       jnp.zeros((b, 1), jnp.int32)], axis=1)
  cols = jnp.arange(c, dtype=jnp.int32)[None]
  out = jnp.where(cols < accept_len[:, None], d_pad,
                  jnp.where(cols == accept_len[:, None], at_cut, bonus))
  return out.astype(jnp.int32), accept_len


def SpecVerifyTree(target_logits, draft_tokens, branches, draft_logits, key,
                   temperature: float = 0.0, top_k: int = 0,
                   row_seeds=None, row_pos=None, draft_valid=None):
  """Branch-aware acceptance over one TREE verify step.

  The verify step fed each row its last committed token t0 (tree column 0)
  plus R draft nodes in DFS order, so `target_logits[:, j + 1]` is the
  target distribution AFTER draft node j and `target_logits[:, 0]` the one
  after t0. The tree branches once, at depth 1: `branches[b, i, d - 1]` is
  the draft index of branch i's node at depth d (-1 = absent), so each
  branch is a root-anchored chain and chain speculation is the W == 1
  degenerate case.

  Acceptance walks the tree depth-first by construction of the walk: at
  depth 1 the sibling set is all branch heads; once a branch is entered,
  deeper candidates come only from that branch (a root-to-leaf path).

  - `temperature <= 0`: greedy — a candidate is lawful iff it equals the
    argmax of its PARENT's target distribution, so the walk accepts the
    longest lawful root-to-leaf argmax chain (leftmost branch on sibling
    ties — duplicate siblings carry identical continuations of the argmax
    chain, so the emitted stream is the same either way). Emitted tokens
    are the target argmaxes themselves: byte-identical to the
    non-speculative engine.
  - `temperature > 0`: residual speculative sampling generalized over the
    sibling set (multi-round rejection): candidate i at a node is accepted
    iff u_i < p_i(x)/q(x), where p_1 is the (temperature/top-k) target at
    the node and p_{i+1} = norm(max(p_i - q_i, 0)) the residual left after
    rejecting candidate i. Accept-or-residual over the set emits exactly
    the target law at every node (exact for i.i.d. draft-sampled siblings
    — the draft sources sample siblings i.i.d. at temperature > 0), so
    each request's output distribution equals the non-speculative
    engine's. Stream keys reuse the chain convention — depth d draws at
    stream position row_pos + d - 1 with coin fold 1 (sibling i > 0 adds
    fold (3, i)), residual fold 2, and the full-acceptance bonus is the
    plain positional draw.

  Args:
    target_logits: [B, C, V] verify-step logits, C = R + 1 DFS columns.
    draft_tokens: [B, R] int32 draft-node proposals (DFS order).
    branches: [B, W, K] int32 draft index per (branch, depth), -1 absent.
    draft_logits: [B, R, V] draft distribution each proposal was drawn
      from (ignored at temperature <= 0; required otherwise).
    key/temperature/top_k/row_seeds/row_pos: as SpecVerifyTokens.
    draft_valid: optional [B, R] bool — budget-clamped nodes can never be
      accepted.

  Returns:
    (out_tokens [B, K + 1] int32, accept_depth [B] int32,
     branch [B] int32). The caller emits out_tokens[i, :accept_depth + 1];
    `branch` is the accepted branch index (0 when nothing was accepted),
    which the engine uses to locate the winning path's DFS columns for KV
    repair and SSM column select.
  """
  b, c, _ = target_logits.shape
  _, w, kd = branches.shape
  r = draft_tokens.shape[1]
  assert c >= r + 1, (c, r)
  if draft_valid is None:
    draft_valid = jnp.ones((b, r), bool)
  b_idx = jnp.arange(b)
  branches = branches.astype(jnp.int32)
  draft_tokens = draft_tokens.astype(jnp.int32)

  def _NodeTok(j):            # j: [B] draft index (clipped for gathers)
    return draft_tokens[b_idx, jnp.clip(j, 0, max(r - 1, 0))]

  def _NodeValid(j):
    return (j >= 0) & draft_valid[b_idx, jnp.clip(j, 0, max(r - 1, 0))]

  cur_col = jnp.zeros((b,), jnp.int32)
  alive = jnp.ones((b,), bool)
  m = jnp.zeros((b,), jnp.int32)
  branch = jnp.zeros((b,), jnp.int32)

  if temperature <= 0.0:
    g = jnp.argmax(target_logits, axis=-1).astype(jnp.int32)      # [B, C]
    out = [g[b_idx, cur_col]]
    for d in range(1, kd + 1):
      expect = g[b_idx, cur_col]                                  # [B]
      cand = branches[:, :, d - 1]                                # [B, W]
      ok = (_NodeValid(cand.T).T
            & (_NodeTok(cand.T).T == expect[:, None]))            # [B, W]
      if d > 1:
        ok = ok & (jnp.arange(w)[None] == branch[:, None])
      any_ok = jnp.any(ok, axis=1)
      first = jnp.argmax(ok, axis=1).astype(jnp.int32)
      if d == 1:
        branch = jnp.where(any_ok, first, branch)
      j_acc = branches[b_idx, jnp.where(any_ok, first, branch),
                       d - 1]
      alive = alive & any_ok
      m = m + alive.astype(jnp.int32)
      cur_col = jnp.where(alive, j_acc + 1, cur_col)
      out.append(g[b_idx, cur_col])
    # out[t] is the argmax AFTER the t-th accepted path node: accepted
    # drafts for t < m (they ARE those argmaxes), the correction/bonus at
    # t == m, unconsumed past it.
    return (jnp.stack(out[:kd + 1], axis=1).astype(jnp.int32), m,
            branch)

  assert row_seeds is not None and row_pos is not None, (
      "speculative sampling at temperature > 0 needs per-request streams")
  tl = _TransformLogits(target_logits, temperature, top_k)        # [B, C, V]
  ql = _TransformLogits(draft_logits, temperature, top_k)         # [B, R, V]
  p = jax.nn.softmax(tl, axis=-1)
  q = jax.nn.softmax(ql, axis=-1)
  seeds = row_seeds.astype(jnp.uint32)

  def _PosKey(seed, pp):
    return jax.random.fold_in(jax.random.fold_in(key, seed), pp)

  pos_keys = jax.vmap(_PosKey)
  acc_toks, finals = [], []
  for d in range(1, kd + 1):
    kk = pos_keys(seeds, row_pos.astype(jnp.uint32) + (d - 1))    # [B] keys
    p_work = jnp.take_along_axis(
        p, cur_col[:, None, None], axis=1)[:, 0]                  # [B, V]
    degenerate = jnp.zeros((b,), bool)
    accepted = jnp.zeros((b,), bool)
    j_acc = jnp.zeros((b,), jnp.int32)
    br_acc = branch
    for i in range(w):
      j_i = branches[:, i, d - 1]                                 # [B]
      cand_ok = _NodeValid(j_i) & ~accepted
      if d > 1:
        cand_ok = cand_ok & (branch == i)
      x_i = _NodeTok(j_i)
      q_i = q[b_idx, jnp.clip(j_i, 0, max(r - 1, 0))]             # [B, V]
      coin = jax.vmap(
          lambda kx: jax.random.uniform(jax.random.fold_in(kx, 1))
          if i == 0 else
          jax.random.uniform(
              jax.random.fold_in(jax.random.fold_in(kx, 3), i)))(kk)
      p_x = p_work[b_idx, x_i]
      q_x = q_i[b_idx, x_i]
      acc_now = cand_ok & (coin * q_x < p_x)
      j_acc = jnp.where(acc_now, j_i, j_acc)
      if d == 1:
        br_acc = jnp.where(acc_now, i, br_acc)
      accepted = accepted | acc_now
      considered = cand_ok & ~acc_now
      resid = jnp.maximum(p_work - q_i, 0.0)
      z = jnp.sum(resid, axis=-1, keepdims=True)
      p_next = jnp.where(z > 0.0, resid / jnp.maximum(z, 1e-30), p_work)
      degenerate = degenerate | (considered & (z[:, 0] <= 0.0))
      p_work = jnp.where(considered[:, None], p_next, p_work)
    # correction draw from the post-set residual (fallback to the target
    # at the node when the residual vanished — p == q there, any lawful)
    tl_cur = jnp.take_along_axis(
        tl, cur_col[:, None, None], axis=1)[:, 0]                 # [B, V]
    corr_logits = jnp.where(degenerate[:, None], tl_cur,
                            jnp.log(jnp.maximum(p_work, 1e-30)))
    corr = jax.vmap(
        lambda kx, ll: jax.random.categorical(
            jax.random.fold_in(kx, 2), ll, axis=-1))(
                kk, corr_logits).astype(jnp.int32)                # [B]
    step_alive = alive & accepted
    # the token emitted at stream position row_pos + d - 1: the accepted
    # draft if the walk survives, else (if it died exactly here) the
    # correction
    acc_toks.append(_NodeTok(j_acc))
    finals.append(corr)
    branch = jnp.where(alive, br_acc, branch)
    m = m + step_alive.astype(jnp.int32)
    cur_col = jnp.where(step_alive, j_acc + 1, cur_col)
    alive = step_alive
  # full-acceptance bonus: the plain positional draw at the leaf
  kk_b = pos_keys(seeds, row_pos.astype(jnp.uint32) + kd)
  tl_leaf = jnp.take_along_axis(tl, cur_col[:, None, None], axis=1)[:, 0]
  bonus = jax.vmap(lambda kx, ll: jax.random.categorical(
      kx, ll, axis=-1))(kk_b, tl_leaf).astype(jnp.int32)
  acc_mat = jnp.stack(acc_toks, axis=1) if kd else jnp.zeros((b, 0),
                                                             jnp.int32)
  fin_mat = (jnp.concatenate([jnp.stack(finals, axis=1), bonus[:, None]],
                             axis=1) if kd else bonus[:, None])   # [B, K+1]
  cols = jnp.arange(kd + 1, dtype=jnp.int32)[None]
  at_cut = jnp.take_along_axis(fin_mat, m[:, None], axis=1)[:, 0]
  acc_pad = jnp.concatenate(
      [acc_mat, jnp.zeros((b, 1), jnp.int32)], axis=1)
  out = jnp.where(cols < m[:, None], acc_pad,
                  jnp.where(cols == m[:, None], at_cut[:, None], 0))
  return out.astype(jnp.int32), m, branch
