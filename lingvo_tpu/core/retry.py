"""Retry helpers + failure taxonomy for the training loop.

Re-designs the reference's `retry.py:27` (generic exponential-backoff
decorator) and the error classification of `base_runner._RunLoop`
(`base_runner.py:399-528`): transient infrastructure errors (Unavailable /
Aborted / deadline / connection loss — the things a preempted TPU or flaky
tunnel produce) are retryable, typically by restoring the last checkpoint;
compilation and shape/type errors are programmer errors and fatal.
"""

from __future__ import annotations

import functools
import random
import time
from typing import Callable

# Substrings identifying retryable infrastructure failures (jax/PJRT wraps
# grpc status names into exception text).
TRANSIENT_PATTERNS = (
    "UNAVAILABLE",
    "Unavailable",
    "DEADLINE_EXCEEDED",
    "DeadlineExceeded",
    "ABORTED",
    "Socket closed",
    "Connection reset",
    "connection attempts failed",
    "failed to connect",
    "heartbeat failure",
)

# Substrings identifying definitely-NOT-retryable failures even when they
# co-occur with transient-looking text (ref _RunLoop: compile errors fatal).
FATAL_PATTERNS = (
    "Compilation failure",
    "RESOURCE_EXHAUSTED",
    "Out of memory",
    "INVALID_ARGUMENT",
)


def IsTransient(exc: BaseException) -> bool:
  """True when `exc` looks like a retryable infrastructure failure."""
  text = f"{type(exc).__name__}: {exc}"
  if any(pat in text for pat in FATAL_PATTERNS):
    return False
  return any(pat in text for pat in TRANSIENT_PATTERNS)


def Retry(initial_delay_sec: float = 1.0,
          max_delay_sec: float = 60.0,
          max_retries: int = 5,
          retry_if: Callable[[BaseException], bool] = IsTransient):
  """Exponential-backoff retry decorator (ref `retry.py:27`).

  Retries calls whose exception satisfies `retry_if`, sleeping
  initial_delay * 2^attempt (jittered, capped at max_delay) between tries.
  Non-matching exceptions and attempts past max_retries re-raise.
  """

  def Decorator(fn):
    @functools.wraps(fn)
    def Wrapped(*args, **kwargs):
      delay = initial_delay_sec
      for attempt in range(max_retries + 1):
        try:
          return fn(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001
          if attempt >= max_retries or not retry_if(e):
            raise
          sleep = min(delay, max_delay_sec) * (0.5 + random.random())
          print(f"[retry] {type(e).__name__} (attempt {attempt + 1}/"
                f"{max_retries}), retrying in {sleep:.1f}s: {e}", flush=True)
          time.sleep(sleep)
          delay *= 2
      raise AssertionError("unreachable")

    return Wrapped

  return Decorator
