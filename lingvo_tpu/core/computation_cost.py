"""Static computation-cost estimates (ref `lingvo/core/computation_cost.py`).

The reference walks layer FPropMeta metadata to sum FLOPs; under XLA the
compiler itself is the authority — `Compiled.cost_analysis()` reports the
flops/bytes of the exact program that will run (fusions included). This
module wraps that for any jittable fn and derives MFU given a step time.
"""

from __future__ import annotations

from typing import Any, Callable

import jax


def CostAnalysisOf(compiled) -> dict[str, float]:
  """Normalizes a jax Compiled's cost_analysis() to a plain dict.

  Keys of interest: 'flops', 'bytes accessed', 'transcendentals'.
  """
  analysis = compiled.cost_analysis()
  if isinstance(analysis, (list, tuple)):  # per-device list on some backends
    analysis = analysis[0]
  return dict(analysis) if analysis else {}


def CostAnalysis(fn: Callable, *args, **kwargs) -> dict[str, float]:
  """Compiles fn(*args) abstractly and returns XLA's cost analysis.

  When you already hold a jitted+compiled fn, use CostAnalysisOf on its
  Compiled instead (avoids a second compilation).
  """
  return CostAnalysisOf(jax.jit(fn).lower(*args, **kwargs).compile())


def Flops(fn: Callable, *args, **kwargs) -> float:
  return float(CostAnalysis(fn, *args, **kwargs).get("flops", 0.0))


def Mfu(flops_per_step: float, step_time_s: float,
        peak_flops: float) -> float:
  """Model FLOPs utilization for a measured step time."""
  if step_time_s <= 0 or peak_flops <= 0:
    return 0.0
  return flops_per_step / (step_time_s * peak_flops)


def TrainStepCost(task, state, batch) -> dict[str, float]:
  """Cost analysis of a task's full TrainStep (fwd+bwd+optimizer)."""
  return CostAnalysis(task.TrainStep, state, batch)
