"""Beam search + sampling decoders, fully in XLA (no host round-trips).

TPU-native re-design of the reference's decoding stack: the C++
`BeamSearchStep` kernel + host-driven while loop (`beam_search_helper.py:200`,
`ops/beam_search_step_op_kernels.cc`) becomes a jittable `lax.scan` whose
per-step top-k and hypothesis bookkeeping are pure XLA ops — the approach the
reference itself uses for its giant LMs (`flat_beam_search_helper.py:69`),
generalized: length normalization, valid-eos logit delta, finished-hyp
freezing, and batched KV-cache reordering by parent beam.

`TargetSequenceSampler` mirrors `target_sequence_sampler.py` (temperature /
top-k sampling loop).
"""

from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp

from lingvo_tpu.core import hyperparams
from lingvo_tpu.core.nested_map import NestedMap

NEG_INF = -1.0e9


def _GatherBeams(tree, parent_idx, batch_size, num_hyps):
  """Reorders [B*K, ...] state leaves by parent beam: new[b,k] = old[b,parent[b,k]].

  Leaf-shape agnostic past the leading B*K axis, so it covers dense
  [B*K, S, N, H] KV caches and any paged [B*K, S/page, page, N, H] view of
  them identically — the paged flash-decode path (docs/decode_fast_path.md)
  keeps the cache in the dense layout, pages being a read-side blocking of
  the time axis, so beam reordering needs no paged-specific handling
  (asserted in test_mt_beam_search.py).
  """

  def _One(x):
    if not hasattr(x, "ndim") or x.ndim == 0:
      return x
    shaped = x.reshape((batch_size, num_hyps) + x.shape[1:])
    gathered = jnp.take_along_axis(
        shaped,
        parent_idx.reshape((batch_size, num_hyps) +
                           (1,) * (x.ndim - 1)).astype(jnp.int32),
        axis=1)
    return gathered.reshape(x.shape)

  return jax.tree_util.tree_map(_One, tree)


def LengthNorm(lengths, alpha: float):
  """GNMT length normalization: ((5+len)/6)^alpha (ref beam scoring)."""
  return jnp.power((5.0 + lengths.astype(jnp.float32)) / 6.0, alpha)


class BeamSearchHelper:
  """Flat beam search over a step function.

  step_fn(states, ids_t) -> (log_probs [B*K, V], new_states): one decoder
  step on flattened beams; states' leaves lead with B*K.
  """

  @classmethod
  def Params(cls):
    p = hyperparams.InstantiableParams(cls)
    p.Define("name", "beam_search", "Name.")
    p.Define("num_hyps_per_beam", 4, "Beam width K.")
    p.Define("target_seq_len", 32, "Max decode steps.")
    p.Define("target_sos_id", 1, "Start-of-sequence id.")
    p.Define("target_eos_id", 2, "End-of-sequence id.")
    p.Define("length_normalization", 0.6, "GNMT alpha.")
    p.Define("valid_eos_max_logit_delta", 5.0,
             "EOS only allowed when within delta of the best logit "
             "(ref x_ops.cc BeamSearchStep semantics).")
    p.Define("coverage_penalty", 0.0,
             "GNMT coverage penalty beta (ref x_ops.cc BeamSearchStep "
             "coverage scoring): beta * sum_t log(min(cum_atten_t, 1)). "
             "Needs a step_fn returning (log_probs, new_states, "
             "atten_probs).")
    return p

  def __init__(self, params):
    self.p = params.Copy()

  def Search(self, batch_size: int, init_states: NestedMap,
             step_fn: Callable, src_len: int = 1,
             src_paddings=None) -> NestedMap:
    """Runs beam search; returns NestedMap(topk_ids [B,K,T], topk_lens,
    topk_scores [B,K]) sorted best-first. `src_len` sizes the coverage
    accumulator when coverage_penalty > 0 (step_fn must then return
    attention probs [B*K, src_len] as a third output)."""
    p = self.p
    k = p.num_hyps_per_beam
    t_max = p.target_seq_len
    bk = batch_size * k

    # initial hyp scores: beam 0 active, others -inf (all start identical)
    init_scores = jnp.tile(
        jnp.array([0.0] + [NEG_INF] * (k - 1), jnp.float32), (batch_size,))
    init_ids = jnp.full((bk,), p.target_sos_id, jnp.int32)

    def _Step(carry, t):
      states, last_ids, scores, done, ids_so_far, lens, coverage = carry
      step_out = step_fn(states, last_ids[:, None])
      if len(step_out) == 3:
        log_probs, new_states, atten_probs = step_out
      else:
        assert p.coverage_penalty == 0.0, (
            "coverage_penalty > 0 needs a step_fn returning "
            "(log_probs, new_states, atten_probs); got a 2-tuple — the "
            "penalty would silently corrupt every hyp score")
        log_probs, new_states = step_out
        atten_probs = None
      vocab = log_probs.shape[-1]
      log_probs = jax.nn.log_softmax(log_probs.astype(jnp.float32), axis=-1)

      # valid-eos: EOS candidate only when near the best continuation
      best = jnp.max(log_probs, axis=-1, keepdims=True)
      eos_mask = jnp.zeros((vocab,)).at[p.target_eos_id].set(1.0)
      eos_invalid = (log_probs < best - p.valid_eos_max_logit_delta)
      log_probs = jnp.where((eos_mask > 0) & eos_invalid, NEG_INF, log_probs)

      # finished hyps: frozen — only EOS continuation at zero cost
      frozen = jnp.full((vocab,), NEG_INF).at[p.target_eos_id].set(0.0)
      log_probs = jnp.where(done[:, None], frozen[None, :], log_probs)

      total = scores[:, None] + log_probs                       # [B*K, V]
      total = total.reshape(batch_size, k * vocab)
      new_scores, flat_idx = jax.lax.top_k(total, k)            # [B, K]
      parent = flat_idx // vocab                                # [B, K]
      token = (flat_idx % vocab).astype(jnp.int32)              # [B, K]

      # reorder states/history by parent
      new_states = _GatherBeams(new_states, parent, batch_size, k)
      ids_so_far = _GatherBeams(ids_so_far, parent, batch_size, k)
      lens = _GatherBeams(lens, parent, batch_size, k)
      if atten_probs is not None:
        # accumulate source coverage for live hyps, then follow the parents
        coverage = _GatherBeams(
            coverage + atten_probs * (1.0 - done[:, None].astype(jnp.float32)),
            parent, batch_size, k)
      done = _GatherBeams(done, parent, batch_size, k)

      token_flat = token.reshape(bk)
      new_done = done | (token_flat == p.target_eos_id)
      ids_so_far = ids_so_far.at[:, t].set(
          jnp.where(done, p.target_eos_id, token_flat))
      lens = lens + (1 - done.astype(jnp.int32))
      return (new_states, token_flat, new_scores.reshape(bk), new_done,
              ids_so_far, lens, coverage), ()

    ids0 = jnp.full((bk, t_max), p.target_eos_id, jnp.int32)
    lens0 = jnp.zeros((bk,), jnp.int32)
    done0 = jnp.zeros((bk,), jnp.bool_)
    cov0 = jnp.zeros((bk, src_len), jnp.float32)
    carry = (init_states, init_ids, init_scores, done0, ids0, lens0, cov0)
    (states, _, scores, done, ids, lens, coverage), _ = jax.lax.scan(
        _Step, carry, jnp.arange(t_max))

    # normalized scores + best-first ordering
    norm_scores = scores / LengthNorm(jnp.maximum(lens, 1),
                                      p.length_normalization)
    if p.coverage_penalty > 0.0:
      # GNMT: beta * sum_t log(min(coverage_t, 1)) over real source positions
      cp_terms = jnp.log(jnp.clip(coverage, 1e-10, 1.0))
      if src_paddings is not None:
        nonpad = (1.0 - src_paddings)[:, None, :]          # [B, 1, T]
        nonpad = jnp.broadcast_to(nonpad,
                                  (batch_size, k, src_len)).reshape(bk,
                                                                    src_len)
        cp_terms = cp_terms * nonpad
      norm_scores = norm_scores + p.coverage_penalty * jnp.sum(cp_terms, -1)
    norm_scores = norm_scores.reshape(batch_size, k)
    order = jnp.argsort(-norm_scores, axis=-1)
    topk_scores = jnp.take_along_axis(norm_scores, order, axis=1)
    ids = ids.reshape(batch_size, k, t_max)
    topk_ids = jnp.take_along_axis(ids, order[:, :, None], axis=1)
    lens = lens.reshape(batch_size, k)
    topk_lens = jnp.take_along_axis(lens, order, axis=1)
    return NestedMap(
        topk_ids=topk_ids, topk_lens=topk_lens, topk_scores=topk_scores)


def MergeBeamSearchOutputs(max_hyps_per_beam: int, beam_search_outputs):
  """Merges beam-search outputs from several decoders (model ensembling,
  ref `beam_search_helper.py:681` MergeBeamSearchOutputs).

  Each element is a NestedMap(topk_ids [B,K_i,T], topk_lens [B,K_i],
  topk_scores [B,K_i]) with a common B and T. Hypotheses are pooled,
  duplicates (identical token prefixes up to their length) keep only the
  best-scoring copy, and the top `max_hyps_per_beam` by score come back in
  the same layout. Pure jnp with static shapes, so it jits.
  """
  ids = jnp.concatenate([o.topk_ids for o in beam_search_outputs], axis=1)
  lens = jnp.concatenate([o.topk_lens for o in beam_search_outputs], axis=1)
  scores = jnp.concatenate([o.topk_scores for o in beam_search_outputs],
                           axis=1)
  if ids.shape[1] < max_hyps_per_beam:
    # keep the documented [B, max_hyps_per_beam, T] layout even when the
    # pool is smaller than requested: pad with blank -inf slots
    pad = max_hyps_per_beam - ids.shape[1]
    ids = jnp.pad(ids, ((0, 0), (0, pad), (0, 0)))
    lens = jnp.pad(lens, ((0, 0), (0, pad)))
    scores = jnp.pad(scores, ((0, 0), (0, pad)),
                     constant_values=-jnp.inf)
  b, k, t = ids.shape
  # duplicate = same length and same ids within that length
  pos = jnp.arange(t)
  valid = pos[None, None, :] < lens[:, :, None]              # [B,K,T]
  masked = jnp.where(valid, ids, -1)
  same = jnp.all(masked[:, :, None, :] == masked[:, None, :, :], axis=-1)
  same &= lens[:, :, None] == lens[:, None, :]               # [B,K,K]
  # a hyp is a duplicate if an equal hyp exists with (better score) or
  # (equal score and lower index) — keeps exactly one representative
  better = (scores[:, None, :] > scores[:, :, None]) | (
      (scores[:, None, :] == scores[:, :, None]) &
      (jnp.arange(k)[None, None, :] < jnp.arange(k)[None, :, None]))
  dup = jnp.any(same & better, axis=-1)                      # [B,K]
  pooled = jnp.where(dup, -jnp.inf, scores)
  order = jnp.argsort(-pooled, axis=-1)[:, :max_hyps_per_beam]
  out_scores = jnp.take_along_axis(pooled, order, axis=1)
  # slots beyond the unique-hyp count would otherwise carry -inf scores
  # with live duplicate ids; blank them so consumers see empty hyps
  live = jnp.isfinite(out_scores)
  return NestedMap(
      topk_ids=jnp.where(
          live[:, :, None],
          jnp.take_along_axis(ids, order[:, :, None], axis=1), 0),
      topk_lens=jnp.where(
          live, jnp.take_along_axis(lens, order, axis=1), 0),
      topk_scores=out_scores)


class GreedySearchHelper:
  """Argmax decoding (ref GreedySearchHelper:752)."""

  @classmethod
  def Params(cls):
    p = hyperparams.InstantiableParams(cls)
    p.Define("name", "greedy_search", "Name.")
    p.Define("target_seq_len", 32, "Max steps.")
    p.Define("target_sos_id", 1, "SOS.")
    p.Define("target_eos_id", 2, "EOS.")
    return p

  def __init__(self, params):
    self.p = params.Copy()

  def Search(self, batch_size: int, init_states: NestedMap,
             step_fn: Callable) -> NestedMap:
    p = self.p

    def _Step(carry, t):
      states, last_ids, done, ids, lens = carry
      log_probs, new_states = step_fn(states, last_ids[:, None])
      token = jnp.argmax(log_probs, axis=-1).astype(jnp.int32)
      token = jnp.where(done, p.target_eos_id, token)
      ids = ids.at[:, t].set(token)
      new_done = done | (token == p.target_eos_id)
      lens = lens + (1 - done.astype(jnp.int32))
      return (new_states, token, new_done, ids, lens), ()

    ids0 = jnp.full((batch_size, p.target_seq_len), p.target_eos_id,
                    jnp.int32)
    init_ids = jnp.full((batch_size,), p.target_sos_id, jnp.int32)
    done0 = jnp.zeros((batch_size,), jnp.bool_)
    lens0 = jnp.zeros((batch_size,), jnp.int32)
    (states, _, done, ids, lens), _ = jax.lax.scan(
        _Step, (init_states, init_ids, done0, ids0, lens0),
        jnp.arange(p.target_seq_len))
    return NestedMap(hyp_ids=ids, hyp_lens=lens)


class TargetSequenceSampler:
  """Temperature / top-k sampling (ref target_sequence_sampler.py)."""

  @classmethod
  def Params(cls):
    p = hyperparams.InstantiableParams(cls)
    p.Define("name", "sampler", "Name.")
    p.Define("target_seq_len", 32, "Max steps.")
    p.Define("target_sos_id", 1, "SOS.")
    p.Define("target_eos_id", 2, "EOS.")
    p.Define("temperature", 1.0, "Softmax temperature (0 = argmax).")
    p.Define("top_k", 0, "If >0, sample only from the top-k logits.")
    p.Define("top_p", 0.0, "If >0, nucleus sampling cumulative mass.")
    return p

  def __init__(self, params):
    self.p = params.Copy()

  def Sample(self, key: jax.Array, batch_size: int, init_states: NestedMap,
             step_fn: Callable) -> NestedMap:
    p = self.p

    def _Step(carry, t):
      states, last_ids, done, ids, lens = carry
      log_probs, new_states = step_fn(states, last_ids[:, None])
      logits = log_probs.astype(jnp.float32)
      if p.top_k > 0:
        kth = jax.lax.top_k(logits, p.top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, NEG_INF, logits)
      if p.top_p > 0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum((cum < p.top_p).astype(jnp.int32), axis=-1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None],
                                     axis=-1)
        logits = jnp.where(logits < cutoff, NEG_INF, logits)
      if p.temperature > 0:
        step_key = jax.random.fold_in(key, t)
        token = jax.random.categorical(step_key, logits / p.temperature,
                                       axis=-1).astype(jnp.int32)
      else:
        token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
      token = jnp.where(done, p.target_eos_id, token)
      ids = ids.at[:, t].set(token)
      new_done = done | (token == p.target_eos_id)
      lens = lens + (1 - done.astype(jnp.int32))
      return (new_states, token, new_done, ids, lens), ()

    ids0 = jnp.full((batch_size, p.target_seq_len), p.target_eos_id,
                    jnp.int32)
    init_ids = jnp.full((batch_size,), p.target_sos_id, jnp.int32)
    done0 = jnp.zeros((batch_size,), jnp.bool_)
    lens0 = jnp.zeros((batch_size,), jnp.int32)
    (states, _, done, ids, lens), _ = jax.lax.scan(
        _Step, (init_states, init_ids, done0, ids0, lens0),
        jnp.arange(p.target_seq_len))
    return NestedMap(ids=ids, lens=lens)
