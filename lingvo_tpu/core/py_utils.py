"""Foundation utilities: weight specs/initializers, seeds, paddings, shapes.

TPU-native replacement for the load-bearing parts of the reference's
`lingvo/core/py_utils.py` (7k LoC): `WeightInit`/`WeightParams`
(`py_utils.py:1085-1313`), deterministic name-derived seeds
(`GenerateSeedFromName`, `py_utils.py:1555`), shape asserts
(`py_utils.py:94-592`), and sequence-padding math. Everything TF-graph-specific
(variable stores, sessions, collections, infeed) is intentionally absent — JAX
pytrees + explicit PRNG keys replace it.
"""

from __future__ import annotations

import dataclasses
import hashlib
import math
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.core.nested_map import NestedMap

# ---------------------------------------------------------------------------
# Deterministic seeds.
# ---------------------------------------------------------------------------


def GenerateSeedFromName(name: str) -> int:
  """Stable uint32 seed derived from a variable/layer path name.

  Parity in spirit with the reference's md5-based scheme
  (`py_utils.py:1555-1566`): the same layer path always gets the same init
  stream, so goldens survive refactors that don't rename layers.
  """
  digest = hashlib.md5(name.encode("utf-8")).hexdigest()
  return int(digest[:8], 16)


def FoldInName(key: jax.Array, name: str) -> jax.Array:
  """Folds a name-derived seed into a PRNG key."""
  return jax.random.fold_in(key, GenerateSeedFromName(name))


# ---------------------------------------------------------------------------
# Weight specs & initializers.
# ---------------------------------------------------------------------------


from lingvo_tpu.core.hyperparams import RegisterSerializableType


@RegisterSerializableType
@dataclasses.dataclass(frozen=True)
class WeightInit:
  """An initializer spec: method name + scale.

  Mirrors the reference's WeightInit method catalogue
  (`py_utils.py:1085-1239`) but is a plain frozen dataclass evaluated with
  `jax.random` at variable-creation time.
  """

  method: str = "xavier"
  scale: float = 1.0

  @classmethod
  def Gaussian(cls, scale: float = 1.0) -> "WeightInit":
    return cls("gaussian", scale)

  @classmethod
  def Uniform(cls, scale: float = 1.0) -> "WeightInit":
    return cls("uniform", scale)

  @classmethod
  def UniformUnitScaling(cls, scale: float = 1.0) -> "WeightInit":
    return cls("uniform_unit_scaling", scale)

  @classmethod
  def Xavier(cls, scale: float = 1.0) -> "WeightInit":
    return cls("xavier", scale)

  @classmethod
  def XavierWithFixupParams(cls, scale: float = 1.0, depth: float = 1.0,
                            layers_per_residual_block: float = 1.0) -> "WeightInit":
    return cls("xavier", scale * (depth ** (-1.0 / (2 * layers_per_residual_block))))

  @classmethod
  def GaussianSqrtDim(cls, scale: float = 1.0) -> "WeightInit":
    return cls("gaussian_sqrt_dim", scale)

  @classmethod
  def GaussianSqrtFanIn(cls, scale: float = 1.0) -> "WeightInit":
    return cls("gaussian_sqrt_fanin", scale)

  @classmethod
  def GaussianSqrtFanOut(cls, scale: float = 1.0) -> "WeightInit":
    return cls("gaussian_sqrt_fanout", scale)

  @classmethod
  def UniformSqrtDim(cls, scale: float = 1.0) -> "WeightInit":
    return cls("uniform_sqrt_dim", scale)

  @classmethod
  def Constant(cls, scale: float = 0.0) -> "WeightInit":
    return cls("constant", scale)

  @classmethod
  def TruncatedGaussian(cls, scale: float = 1.0) -> "WeightInit":
    return cls("truncated_gaussian", scale)

  @classmethod
  def TruncatedGaussianSqrtDim(cls, scale: float = 1.0) -> "WeightInit":
    return cls("truncated_gaussian_sqrt_dim", scale)

  @classmethod
  def TruncatedGaussianSqrtFanIn(cls, scale: float = 1.0) -> "WeightInit":
    return cls("truncated_gaussian_sqrt_fanin", scale)


@dataclasses.dataclass
class WeightParams:
  """Spec for one learnable weight.

  `tensor_split_dims_mapping` names a mesh axis (or None) per tensor dim —
  the TPU-native equivalent of the reference's per-var sharding annotations
  (`base_layer.py:262-280` + `gshard_utils.GetVarSharding:430`), lowered here
  to a `jax.sharding.PartitionSpec` by `parallel/mesh.py`.
  """

  shape: Sequence[int]
  init: WeightInit = dataclasses.field(default_factory=WeightInit)
  dtype: Any = jnp.float32
  collections: Sequence[str] = ()
  tensor_split_dims_mapping: Sequence[str | None] | None = None

  def __post_init__(self):
    self.shape = tuple(int(d) for d in self.shape)


def InitWeight(key: jax.Array, wp: WeightParams) -> jax.Array:
  """Materializes a weight from its spec with the given PRNG key."""
  shape = tuple(wp.shape)
  method, scale = wp.init.method, wp.init.scale
  dtype = wp.dtype

  def _dim0():
    return max(1, shape[0]) if shape else 1

  def _fans():
    if len(shape) < 1:
      return 1, 1
    if len(shape) == 1:
      return shape[0], shape[0]
    receptive = int(np.prod(shape[:-2])) if len(shape) > 2 else 1
    return shape[-2] * receptive, shape[-1] * receptive

  if method == "constant":
    return jnp.full(shape, scale, dtype)
  if method == "gaussian":
    return scale * jax.random.normal(key, shape, dtype)
  if method == "uniform":
    return jax.random.uniform(key, shape, dtype, -scale, scale)
  if method == "uniform_unit_scaling":
    return scale * math.sqrt(3.0 / _dim0()) * jax.random.uniform(
        key, shape, dtype, -1.0, 1.0)
  if method == "gaussian_sqrt_dim":
    return (scale / math.sqrt(_dim0())) * jax.random.normal(key, shape, dtype)
  if method == "uniform_sqrt_dim":
    s = scale / math.sqrt(_dim0())
    return jax.random.uniform(key, shape, dtype, -s, s)
  if method == "gaussian_sqrt_fanin":
    fan_in, _ = _fans()
    return (scale / math.sqrt(fan_in)) * jax.random.normal(key, shape, dtype)
  if method == "gaussian_sqrt_fanout":
    _, fan_out = _fans()
    return (scale / math.sqrt(fan_out)) * jax.random.normal(key, shape, dtype)
  if method == "xavier":
    fan_in, fan_out = _fans()
    limit = scale * math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, dtype, -limit, limit)
  if method == "truncated_gaussian":
    return scale * jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)
  if method == "truncated_gaussian_sqrt_dim":
    return (scale / math.sqrt(_dim0())) * jax.random.truncated_normal(
        key, -2.0, 2.0, shape, dtype)
  if method == "truncated_gaussian_sqrt_fanin":
    fan_in, _ = _fans()
    return (scale / math.sqrt(fan_in)) * jax.random.truncated_normal(
        key, -2.0, 2.0, shape, dtype)
  raise ValueError(f"Unknown init method {method!r}")


# ---------------------------------------------------------------------------
# Shape checks (host-side; static shapes only, as XLA requires).
# ---------------------------------------------------------------------------


def HasShape(x: jax.Array, expected: Sequence[int], msg: str = "") -> jax.Array:
  """Asserts x's static shape matches `expected` (-1 = any). Returns x."""
  shape = tuple(x.shape)
  if len(shape) != len(expected) or any(
      e not in (-1, s) for s, e in zip(shape, expected)):
    raise ValueError(f"Shape mismatch: got {shape}, want {tuple(expected)}. {msg}")
  return x


def HasRank(x: jax.Array, rank: int) -> jax.Array:
  if x.ndim != rank:
    raise ValueError(f"Rank mismatch: got {x.ndim}, want {rank}")
  return x


def GetShape(x: jax.Array, ndims: int | None = None) -> list[int]:
  s = list(x.shape)
  return s if ndims is None else s[:ndims]


# ---------------------------------------------------------------------------
# Padding / masking math (paddings are 1.0 at padded positions, like the ref).
# ---------------------------------------------------------------------------


def PaddingsFromLengths(lengths: jax.Array, maxlen: int) -> jax.Array:
  """[b] lengths -> [b, maxlen] paddings (1.0 where padded)."""
  pos = jnp.arange(maxlen)[None, :]
  return (pos >= lengths[:, None]).astype(jnp.float32)

def LengthsFromPaddings(paddings: jax.Array) -> jax.Array:
  """[b, t] paddings -> [b] int32 lengths."""
  return jnp.sum(1.0 - paddings, axis=1).astype(jnp.int32)


def ApplyPadding(padding: jax.Array, x: jax.Array, pad_value: float = 0.0) -> jax.Array:
  """Zeroes (or sets) padded positions; padding broadcast against x."""
  while padding.ndim < x.ndim:
    padding = padding[..., None]
  if pad_value == 0.0:
    return x * (1.0 - padding).astype(x.dtype)
  return jnp.where(padding > 0.5, jnp.asarray(pad_value, x.dtype), x)


def SequenceMask(paddings: jax.Array, dtype=jnp.float32) -> jax.Array:
  return (1.0 - paddings).astype(dtype)


def RoundUpToBucket(n: int, buckets) -> int:
  """Smallest bucket >= n; n itself when it exceeds every bucket.

  Serving-shape bucketing: jitted decode programs recompile per distinct
  static length, so callers round prompt/decode lengths up to a small
  fixed set and hit the jit cache on repeat traffic.
  """
  if n < 0:
    raise ValueError(f"RoundUpToBucket needs n >= 0, got {n}")
  for b in sorted(buckets):
    if n <= b:
      return int(b)
  return int(n)


# ---------------------------------------------------------------------------
# Numeric hygiene.
# ---------------------------------------------------------------------------


_ENABLE_CHECK_NUMERICS = False


def EnableCheckNumerics(enable: bool = True) -> None:
  """Globally enables CheckNumerics (call before tracing; debug builds only)."""
  global _ENABLE_CHECK_NUMERICS
  _ENABLE_CHECK_NUMERICS = enable


def CheckNumerics(x: jax.Array, msg: str = "") -> jax.Array:
  """NaN/Inf check (active only after EnableCheckNumerics; identity otherwise).

  Ref semantics: `py_utils.CheckNumerics` gated by --enable_check_numerics
  (`py_utils_flags.py`). Uses a host callback so it works under jit; keep it
  out of the steady-state hot path.
  """
  if not _ENABLE_CHECK_NUMERICS:
    return x

  def _check(v, _msg=msg):
    if not np.all(np.isfinite(v)):
      raise FloatingPointError(f"Non-finite values detected: {_msg}")

  jax.debug.callback(_check, x)
  return x


def IsFinite(tree: Any) -> jax.Array:
  """True iff every leaf of the pytree is finite."""
  leaves = jax.tree_util.tree_leaves(tree)
  if not leaves:
    return jnp.asarray(True)
  finite = [jnp.all(jnp.isfinite(l)) for l in leaves if hasattr(l, "dtype")
            and jnp.issubdtype(l.dtype, jnp.inexact)]
  if not finite:
    return jnp.asarray(True)
  return jnp.stack(finite).all()


def GlobalNorm(tree: Any) -> jax.Array:
  leaves = [l for l in jax.tree_util.tree_leaves(tree) if hasattr(l, "dtype")]
  if not leaves:
    return jnp.asarray(0.0)
  return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves))


# ---------------------------------------------------------------------------
# Trace-time contexts: step seeds, eval mode, forward state updates.
#
# These are thread-local stacks entered INSIDE a traced function, so the values
# they carry are tracers — randomness stays a function of the step key (parity
# with the reference's deterministic step seeds, `py_utils.GenerateStepSeedPair`)
# and state updates stay functional (the JAX answer to the reference's
# assign-op batch-norm moving averages).
# ---------------------------------------------------------------------------

import contextlib
import threading

_TLS = threading.local()


def _Stack(name: str) -> list:
  if not hasattr(_TLS, name):
    setattr(_TLS, name, [])
  return getattr(_TLS, name)


@contextlib.contextmanager
def GlobalStepContext(step):
  """Makes the global step available to schedule-dependent layers (e.g.
  quantization clip schedules) during FProp. Entered by TrainStep."""
  stack = _Stack("global_step")
  stack.append(step)
  try:
    yield
  finally:
    stack.pop()


def GetGlobalStep():
  """Current global step inside FProp, or None outside TrainStep."""
  stack = _Stack("global_step")
  return stack[-1] if stack else None


@contextlib.contextmanager
def StepSeedContext(key: jax.Array):
  """Makes a per-step PRNG key available to stochastic layers during FProp."""
  stack = _Stack("step_seed")
  stack.append(key)
  try:
    yield
  finally:
    stack.pop()


def HasStepSeed() -> bool:
  return bool(_Stack("step_seed"))


def StepSeed(name: str, extra: jax.Array | None = None) -> jax.Array:
  """Derives a layer-unique key from the current step seed context.

  `extra` (e.g. a scan loop index) is folded in for layers whose FProp is
  traced once but executed many times; any active StepSeedSalt values (scan
  indices from enclosing repeat layers) are folded in automatically.
  """
  stack = _Stack("step_seed")
  if not stack:
    raise RuntimeError(
        "No StepSeedContext active; wrap the train FProp in "
        "py_utils.StepSeedContext(step_key)")
  key = jax.random.fold_in(stack[-1], GenerateSeedFromName(name))
  for salt in _Stack("seed_salt"):
    key = jax.random.fold_in(key, salt)
  if extra is not None:
    key = jax.random.fold_in(key, extra)
  return key


@contextlib.contextmanager
def StepSeedSalt(salt: jax.Array):
  """Folds `salt` (e.g. a lax.scan index) into all StepSeed draws inside."""
  stack = _Stack("seed_salt")
  stack.append(salt)
  try:
    yield
  finally:
    stack.pop()


@contextlib.contextmanager
def EvalContext(do_eval: bool = True):
  """Marks FProp as eval-mode (disables dropout & stat updates)."""
  stack = _Stack("do_eval")
  stack.append(do_eval)
  try:
    yield
  finally:
    stack.pop()


def DoEval() -> bool:
  stack = _Stack("do_eval")
  return stack[-1] if stack else False


def ForwardStateContext():
  """Collects state updates emitted during FProp (BN moving stats etc.).

  Yields a plain dict {full_slash_path: value}; keys are the emitting layer's
  unique `layer.path` plus the state name, so sibling layers never collide.

  Usage (inside the traced train step):
    with py_utils.ForwardStateContext() as updates:
      loss = task.FProp(theta, batch)
    new_theta = py_utils.ApplyForwardStateUpdates(theta, updates, root_layer)
  """
  return NamedCollectionContext("fwd_state")


def AddForwardStateUpdate(path: str, value: Any) -> None:
  """Records a functional state update under slash `path` (no-op outside
  context)."""
  stack = _Stack("fwd_state")
  if stack:
    stack[-1][path] = value


@contextlib.contextmanager
def NamedCollectionContext(name: str):
  """Generic trace-time collection stack (aux losses, in-loop summaries)."""
  stack = _Stack(name)
  collected: dict[str, Any] = {}
  stack.append(collected)
  try:
    yield collected
  finally:
    stack.pop()


def NamedCollectionTop(name: str):
  """The innermost active collection dict for `name`, or None."""
  stack = _Stack(name)
  return stack[-1] if stack else None


def NamedCollectionActive(name: str) -> bool:
  return bool(_Stack(name))


def AuxLossContext():
  """Collects auxiliary losses (MoE load-balancing etc.) emitted in FProp.

  Yields a dict {path: scalar}; the train step adds their sum to the
  optimized loss (ref: gshard aux_loss accumulation).
  """
  return NamedCollectionContext("aux_loss")


def AddAuxLoss(path: str, value: Any) -> None:
  """Adds an aux loss scalar (accumulates across repeated python calls).

  IMPORTANT: values recorded inside a `lax.scan`/`vmap` body are tracers
  local to that trace — layers that scan a body must wrap the body call in
  `CollectAuxLosses` and re-emit the carried-out sum outside the scan
  (RepeatedTransformerLayer / PipelinedLayer do).
  """
  stack = _Stack("aux_loss")
  if stack:
    prev = stack[-1].get(path)
    stack[-1][path] = value if prev is None else prev + value


class _AuxFlag:
  """Mutable trace-time flag shared across scan-body invocations."""

  def __init__(self):
    self.emitted = False


def CollectAuxLosses(fn, flag: _AuxFlag):
  """Wraps a scan/vmap body so aux losses exit via the return value.

  Returns a callable with the same signature as `fn` whose result is
  `(fn(...), aux_sum_scalar_f32)`; sets `flag.emitted` at trace time if the
  body emitted any aux loss. The caller re-emits the summed scalar with
  AddAuxLoss AFTER the scan, keeping tracers inside their trace.
  """

  def _Wrapped(*args, **kwargs):
    with AuxLossContext() as aux:
      out = fn(*args, **kwargs)
    if aux:
      flag.emitted = True
    import jax.numpy as jnp_
    aux_sum = (sum(jnp_.asarray(v, jnp_.float32) for v in aux.values())
               if aux else jnp_.zeros((), jnp_.float32))
    return out, aux_sum

  return _Wrapped


def NewAuxFlag() -> _AuxFlag:
  return _AuxFlag()


def ApplyForwardStateUpdates(theta: NestedMap, updates: dict,
                             root_layer) -> NestedMap:
  """Merges collected forward-state updates back into a theta pytree.

  Update keys are full layer paths ('<root>/<child>/.../<var>'); the leading
  root-layer name is stripped to produce theta-relative dotted keys.
  """
  if not updates:
    return theta
  root = root_layer.path if hasattr(root_layer, "path") else str(root_layer)
  new_theta = theta.DeepCopy()
  for path, value in updates.items():
    rel = path[len(root) + 1:] if path.startswith(root + "/") else path
    parts = []
    node: Any = new_theta
    for seg in rel.split("/"):
      # Child-list segments 'name_3' correspond to theta path 'name[3]'.
      if isinstance(node, dict) and seg not in node and "_" in seg:
        base, _, idx = seg.rpartition("_")
        if idx.isdigit() and base in node and isinstance(node[base], list):
          parts.append(f"{base}[{idx}]")
          node = node[base][int(idx)]
          continue
      parts.append(seg)
      node = node[seg] if isinstance(node, dict) and seg in node else None
    new_theta.Set(".".join(parts), value)
  return new_theta


# ---------------------------------------------------------------------------
# Misc.
# ---------------------------------------------------------------------------


def MaybeBfloat16(x: jax.Array, fprop_dtype) -> jax.Array:
  """Casts float inputs to the layer's fprop dtype (bf16 activations policy)."""
  if fprop_dtype is not None and jnp.issubdtype(x.dtype, jnp.floating):
    return x.astype(fprop_dtype)
  return x


def Transform(fn, *trees):
  return jax.tree_util.tree_map(fn, *trees)


def Flatten(tree):
  return jax.tree_util.tree_leaves(tree)


def Pack(template, values):
  return jax.tree_util.tree_unflatten(
      jax.tree_util.tree_structure(template), list(values))


def CountParams(theta: Any) -> int:
  return sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(theta)
             if hasattr(l, "shape"))


__all__ = [
    "NestedMap", "WeightInit", "WeightParams", "InitWeight",
    "GenerateSeedFromName", "FoldInName", "HasShape", "HasRank", "GetShape",
    "PaddingsFromLengths", "LengthsFromPaddings", "ApplyPadding",
    "SequenceMask", "CheckNumerics", "IsFinite", "GlobalNorm",
    "MaybeBfloat16", "Transform", "Flatten", "Pack", "CountParams",
]
