"""Batch-major attention family.

TPU-native re-design of `lingvo/core/batch_major_attention.py` (10k LoC).
Capability surface reproduced: `MultiHeadedAttention` (ref `:481`) with
rotary/relative-bias options, KV-cache incremental decoding, packed-sequence
segment masks; `LocalSelfAttention` sliding-window blocked attention (ref
`:2656`); `ChunkwiseSelfAttention` (ref `:4008`).

Layout is [B, T, N, H] throughout (batch, time, heads, per-head dim) — the
reference's batch-major layout, which XLA tiles well onto the MXU. Logits and
softmax run in float32 regardless of fprop dtype (TPU numerics policy);
everything else stays bf16-friendly. Projections are einsums with mesh-axis
sharding slots: w_q [D, N, H] splits as (data=None, 'model' on N) for
Megatron-style TP — the compiler inserts the collectives (GSPMD), matching
the reference's sharding-by-annotation design (§2.9 of SURVEY.md).
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core import py_utils
from lingvo_tpu.core import quant_utils
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.core.py_utils import WeightInit, WeightParams
from lingvo_tpu.quant import kv as kv_quant

_NEG_INF = -2.3819763e38  # lowest bf16-safe additive mask value / 100


def CausalMask(t: int, dtype=jnp.float32) -> jax.Array:
  """[1, 1, t, t] additive mask: 0 on/below diagonal, -inf above."""
  mask = jnp.tril(jnp.ones((t, t), jnp.bool_))
  return jnp.where(mask, 0.0, _NEG_INF).astype(dtype)[None, None, :, :]


def PaddingsToMask(paddings: jax.Array, dtype=jnp.float32) -> jax.Array:
  """[b, s] paddings -> [b, 1, 1, s] additive key mask."""
  return (paddings[:, None, None, :] * _NEG_INF).astype(dtype)


def SegmentMask(q_segment_ids: jax.Array, k_segment_ids: jax.Array,
                dtype=jnp.float32) -> jax.Array:
  """Packed-sequence mask: [b, 1, t, s]; cross-segment pairs masked.

  Ref: the segment_ids produced by PackSequences (`pack_ops.cc`) gate
  attention in GShard LMs.
  """
  same = (q_segment_ids[:, :, None] == k_segment_ids[:, None, :])
  return jnp.where(same, 0.0, _NEG_INF).astype(dtype)[:, None, :, :]


class PerDimScaleLayer(base_layer.BaseLayer):
  """Learned per-dim query scaling (ref batch_major_attention.PerDimScale)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("dim", 0, "Per-head dim.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self.CreateVariable(
        "per_dim_scale",
        WeightParams((self.p.dim,), WeightInit.Constant(0.0), self.p.dtype))

  def FProp(self, theta, inputs):
    th = self.CastTheta(theta)
    r_softplus_0 = 1.442695041
    scale = r_softplus_0 / math.sqrt(self.p.dim)
    return inputs * (jax.nn.softplus(th.per_dim_scale) * scale).astype(
        inputs.dtype)


class MultiHeadedAttention(base_layer.BaseLayer):
  """Dot-product multi-headed attention (ref `batch_major_attention.py:481`).

  FProp computes full attention; ExtendStep does one-token incremental decode
  against a KV cache (the Step-API equivalent, all-static shapes for jit).
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Query/output model dim.")
    p.Define("source_dim", 0, "Key/value input dim (0 = input_dim).")
    p.Define("hidden_dim", 0, "Total attention hidden dim (N*H).")
    p.Define("num_heads", 1, "Number of heads.")
    p.Define("dim_per_head", 0, "Per-head dim (0 = hidden/num_heads).")
    p.Define("use_bias", True, "Bias on projections.")
    p.Define("enable_per_dim_scale", True,
             "Learned per-dim query scale instead of 1/sqrt(H).")
    p.Define("atten_dropout_prob", 0.0, "Attention prob dropout.")
    p.Define("atten_logit_cap", 0.0, "If >0, tanh-cap logits.")
    p.Define("use_rotary_position_emb", False, "Apply RoPE to q/k.")
    p.Define(
        "use_flash_attention", False,
        "Use the fused Pallas flash kernel when eligible (self-attention, "
        "causal-or-full, no paddings/segments/rel-bias/dropout/logit-cap); "
        "falls back to the einsum path otherwise.")
    p.Define(
        "decode_page_size", 0,
        "If >0, ExtendStep reads the KV cache through the length-aware "
        "paged flash-decode kernel (ops/flash_decode.py) in pages of this "
        "many slots, touching only pages up to time_step instead of the "
        "whole max_len cache. 0 = legacy dense path (exact legacy "
        "numerics). Requires max_len % decode_page_size == 0 and no "
        "rel-pos bias / logit cap / prob quantization; ineligible configs "
        "fall back to the dense path.")
    p.Define(
        "kv_cache_dtype", None,
        "Storage dtype for the decode KV caches (dense ExtendStep cache "
        "and the block-table page pool): None/'' = fprop dtype (bit-exact "
        "legacy caches), 'float32'/'bfloat16' = plain storage cast, "
        "'int8' = quantize-on-write with per-token-per-head f32 scale "
        "sidecars and dequantize-on-read (lingvo_tpu/quant/kv.py). "
        "Training FProp never touches this.")
    p.Define("rel_pos_emb_dim", 0,
             "If >0, learned relative position bias buckets (T5-style).")
    p.Define("rel_pos_max_distance", 128, "Relative bucket clip distance.")
    p.Define("qdomain_weight", None,
             "QDomain params for the q/k/v/post projection weights (ref "
             "batch_major_attention.py:303 TrackQWeight).")
    p.Define("qdomain_softmax", None,
             "QDomain for post-softmax attention probs (ref attention.py:440 "
             "qsoftmax; natural range [0,1] — FixedRangeQDomain(0,1) is the "
             "scan-safe choice). Disables the flash-kernel path: the fused "
             "kernel never materializes probs.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.input_dim > 0 and p.num_heads > 0
    hidden = p.hidden_dim or p.input_dim
    self._dim_per_head = p.dim_per_head or hidden // p.num_heads
    n, h, d = p.num_heads, self._dim_per_head, p.input_dim
    sd = p.source_dim or d
    wsdm = p.weight_split_dims_mapping  # e.g. (None, 'model', None)
    for name, in_dim in (("query", d), ("key", sd), ("value", sd)):
      self.CreateVariable(
          f"w_{name}",
          WeightParams((in_dim, n, h), p.params_init, p.dtype,
                       tensor_split_dims_mapping=wsdm))
      if p.use_bias:
        self.CreateVariable(
            f"b_{name}", WeightParams((n, h), WeightInit.Constant(0.0),
                                      p.dtype))
    self.CreateVariable(
        "w_post",
        WeightParams((d, n, h), p.params_init, p.dtype,
                     tensor_split_dims_mapping=wsdm))
    if p.use_bias:
      self.CreateVariable(
          "b_post", WeightParams((d,), WeightInit.Constant(0.0), p.dtype))
    if p.enable_per_dim_scale:
      self.CreateChild("per_dim_scale",
                       PerDimScaleLayer.Params().Set(dim=h))
    if p.use_rotary_position_emb:
      self.CreateChild(
          "rotary",
          layers_lib.RotaryPositionalEmbeddingLayer.Params().Set(
              embedding_dim=h))
    if p.rel_pos_emb_dim > 0:
      self.CreateVariable(
          "rel_pos_bias",
          WeightParams((p.num_heads, 2 * p.rel_pos_max_distance + 1),
                       WeightInit.Constant(0.0), p.dtype))
    self.CreateChild("atten_dropout",
                     layers_lib.DeterministicDropoutLayer.Params())
    if p.qdomain_weight is not None:
      self.CreateChild("qdomain_weight", p.qdomain_weight.Copy())
    if p.qdomain_softmax is not None:
      self.CreateChild("qdomain_softmax", p.qdomain_softmax.Copy())

  # -- projections -----------------------------------------------------------

  def _QProjWeight(self, theta, w):
    if self.p.qdomain_weight is None:
      return w
    return self.qdomain_weight.QuantizeWeight(
        self.ChildTheta(theta, "qdomain_weight"), w)

  def _QProbs(self, theta, probs):
    """Fake-quantize post-softmax probs (all softmax sites route here)."""
    if self.p.qdomain_softmax is None:
      return probs
    return self.qdomain_softmax.QuantizeAct(
        self.ChildTheta(theta, "qdomain_softmax"), "softmax", probs)

  def _HeadsProj(self, theta, name, x):
    th = self.CastTheta(theta)
    w = th[f"w_{name}"]
    if isinstance(w, quant_utils.Int8Weight):
      # int8-serving theta: [B,T,D] x int8 [D,N,H] on the MXU ('dv' layout,
      # per-(N,H)-channel scales). Fake-quant domains don't compose with
      # the real integer path.
      assert self.p.qdomain_weight is None
      out = w.Einsum(self.ToFPropDtype(x))
    else:
      out = jnp.einsum("BTD,DNH->BTNH", self.ToFPropDtype(x),
                       self._QProjWeight(theta, w))
    if self.p.use_bias:
      out = out + th[f"b_{name}"]
    return out

  def _PostProj(self, theta, ctx):
    th = self.CastTheta(theta)
    w = th.w_post
    if isinstance(w, quant_utils.Int8Weight):
      # [B,T,N,H] contracts (N, H) against int8 [D,N,H] ('vd' layout,
      # per-D-channel scales).
      assert self.p.qdomain_weight is None
      out = w.Einsum(ctx)
    else:
      out = jnp.einsum("BTNH,DNH->BTD", ctx, self._QProjWeight(theta, w))
    if self.p.use_bias:
      out = out + th.b_post
    return out

  def _ScaleQuery(self, theta, q):
    if self.p.enable_per_dim_scale:
      return self.per_dim_scale.FProp(
          self.ChildTheta(theta, "per_dim_scale"), q)
    return q * (1.0 / math.sqrt(self._dim_per_head))

  def _RelPosBias(self, theta, t: int, s: int):
    p = self.p
    th = self.CastTheta(theta)
    rel = jnp.arange(s)[None, :] - jnp.arange(t)[:, None]
    rel = jnp.clip(rel, -p.rel_pos_max_distance, p.rel_pos_max_distance)
    idx = rel + p.rel_pos_max_distance
    return th.rel_pos_bias[:, idx][None]  # [1, N, T, S]

  # -- core ------------------------------------------------------------------

  def _Atten(self, theta, q, k, v, atten_mask):
    """q:[B,T,N,H] k,v:[B,S,N,H] mask additive broadcastable [B,N,T,S]."""
    p = self.p
    logits = jnp.einsum("BTNH,BSNH->BNTS", q, k)
    if p.atten_logit_cap > 0:
      logits = p.atten_logit_cap * jnp.tanh(logits / p.atten_logit_cap)
    logits = logits.astype(jnp.float32)
    if p.rel_pos_emb_dim > 0:
      logits = logits + self._RelPosBias(theta, q.shape[1],
                                         k.shape[1]).astype(jnp.float32)
    if atten_mask is not None:
      logits = logits + atten_mask.astype(jnp.float32)
    # Stacked masks can sum below f32 min (-inf -> NaN softmax rows on fully
    # masked queries); clamp keeps rows finite, padding zeroes them later.
    logits = jnp.maximum(logits, _NEG_INF)
    probs = self._QProbs(theta, jax.nn.softmax(logits, axis=-1).astype(
        q.dtype))
    if p.atten_dropout_prob > 0:
      probs = self.atten_dropout.FProp(
          self.ChildTheta(theta, "atten_dropout"), probs,
          keep_prob=1.0 - p.atten_dropout_prob)
    return jnp.einsum("BNTS,BSNH->BTNH", probs, v), probs

  def _FlashEligible(self, key_vec, atten_mask, needs_seg, t):
    """Self-attention with only causal/padding/segment masking can run the
    fused kernel (paddings/segment_ids fold into the kernel's segment mask;
    arbitrary additive atten_mask cannot). On real TPU the segment path
    further requires t % 128 == 0 (Mosaic lane alignment) — shorter inputs
    fall back to the einsum path."""
    p = self.p
    if not (p.use_flash_attention and key_vec is None
            and atten_mask is None and
            p.rel_pos_emb_dim == 0 and p.atten_logit_cap == 0 and
            p.atten_dropout_prob == 0 and p.qdomain_softmax is None and
            t % 16 == 0):
      return False
    if jax.default_backend() == "tpu":
      from lingvo_tpu.ops import flash_attention
      return flash_attention.SupportedOnTpu(t, with_segments=needs_seg)
    return True

  def FProp(self, theta, query_vec, key_vec=None, value_vec=None,
            paddings=None, atten_mask=None, segment_ids=None, causal=False):
    """Returns ([B,T,D] output, [B,N,T,S] probs or None on the flash path).

    atten_mask: optional additive mask (e.g. CausalMask). paddings are key
    paddings [B,S]. segment_ids: [B,T] packed-input ids for both q and k
    (self-attention) — adds a SegmentMask. `causal=True` is an alternative
    to passing CausalMask that lets the fused flash kernel run.
    """
    use_flash = self._FlashEligible(
        key_vec, atten_mask, paddings is not None or segment_ids is not None,
        query_vec.shape[1])
    key_vec = query_vec if key_vec is None else key_vec
    value_vec = key_vec if value_vec is None else value_vec
    q = self._HeadsProj(theta, "query", query_vec)
    k = self._HeadsProj(theta, "key", key_vec)
    v = self._HeadsProj(theta, "value", value_vec)
    if self.p.use_rotary_position_emb:
      rt = self.ChildTheta(theta, "rotary")
      q = self.rotary.FProp(rt, q)
      k = self.rotary.FProp(rt, k)
    q = self._ScaleQuery(theta, q)
    if use_flash:
      from lingvo_tpu.ops import flash_attention
      # paddings/segment_ids both become the kernel's segment mask: padding
      # gets segment 0 (packed inputs already carry 0 there; enforce it so
      # pad keys never leak into real queries)
      seg = segment_ids
      if paddings is not None:
        base = segment_ids if segment_ids is not None else jnp.ones_like(
            paddings, jnp.int32)
        seg = jnp.where(paddings > 0.5, 0, base).astype(jnp.int32)
      # the kernel scales by 1/sqrt(h) internally; q already carries the
      # (learned) query scale, so cancel the kernel's factor.
      h = self._dim_per_head
      ctx = flash_attention.FlashAttention(
          q * math.sqrt(h), k, v, causal=causal, segment_ids=seg)
      if paddings is not None:
        # strict path parity: flash pad queries attend only pad keys while
        # the einsum path lets them attend real keys — both garbage, but a
        # downstream consumer mixing across time without re-masking would
        # see different numerics depending on the engaged path. Zero them.
        ctx = py_utils.ApplyPadding(paddings, ctx)
      return self._PostProj(theta, ctx), None
    mask = atten_mask
    if causal:
      cm = CausalMask(query_vec.shape[1])
      mask = cm if mask is None else mask + cm
    if paddings is not None:
      pm = PaddingsToMask(paddings)
      mask = pm if mask is None else mask + pm
    if segment_ids is not None:
      sm = SegmentMask(segment_ids, segment_ids)
      mask = sm if mask is None else mask + sm
    ctx, probs = self._Atten(theta, q, k, v, mask)
    return self._PostProj(theta, ctx), probs

  # -- chunk streaming (ref conformer streaming / stream_step_test_base) -----

  def InitStreamStates(self, batch_size: int, left_context: int) -> NestedMap:
    """Sliding-window streaming state: the last left_context-1 source frames'
    K/V (cached PRE-rotary — rotary attention depends only on relative
    position, so each chunk re-rotates with local positions) + paddings."""
    n, h = self.p.num_heads, self._dim_per_head
    ctx = max(left_context - 1, 0)
    dtype = self.fprop_dtype
    return NestedMap(
        key=jnp.zeros((batch_size, ctx, n, h), dtype),
        value=jnp.zeros((batch_size, ctx, n, h), dtype),
        paddings=jnp.ones((batch_size, ctx), jnp.float32),
        left_context=left_context)

  def StreamStep(self, theta, inputs, paddings, cached_states):
    """One chunk of causal sliding-window attention.

    inputs [B, C, D], paddings [B, C] -> (out [B, C, D], new states).
    Equivalent to offline LocalSelfAttention(left_context, right_context=0)
    consumed chunk by chunk (asserted by streaming-equivalence tests).
    """
    p = self.p
    assert p.rel_pos_emb_dim <= 0, (
        "StreamStep computes chunk-local query indices; the T5 relative "
        "bias would use wrong buckets (needs a ctx_len offset)")
    left = cached_states.left_context
    ctx_len = cached_states.key.shape[1]
    b, c, _ = inputs.shape
    q = self._HeadsProj(theta, "query", inputs)
    k_new = self._HeadsProj(theta, "key", inputs)
    v_new = self._HeadsProj(theta, "value", inputs)
    k_cat = jnp.concatenate(
        [cached_states.key, k_new.astype(cached_states.key.dtype)], axis=1)
    v_cat = jnp.concatenate(
        [cached_states.value, v_new.astype(cached_states.value.dtype)],
        axis=1)
    pad_cat = jnp.concatenate([cached_states.paddings, paddings], axis=1)
    if p.use_rotary_position_emb:
      rt = self.ChildTheta(theta, "rotary")
      s = ctx_len + c
      pos_k = jnp.arange(s, dtype=jnp.float32)[None]
      pos_q = pos_k[:, ctx_len:]
      q = self.rotary.FProp(rt, q, position=pos_q)
      k_rot = self.rotary.FProp(rt, k_cat, position=pos_k)
    else:
      k_rot = k_cat
    q = self._ScaleQuery(theta, q)
    # window mask: query i (global ctx_len+i) sees j with
    # 0 <= (ctx_len+i) - j <= left-1
    qpos = ctx_len + jnp.arange(c)[:, None]
    jpos = jnp.arange(ctx_len + c)[None, :]
    visible = (qpos >= jpos) & (qpos - jpos <= left - 1)
    mask = jnp.where(visible, 0.0, _NEG_INF)[None, None]
    mask = mask + PaddingsToMask(pad_cat)
    ctx_vec, _ = self._Atten(theta, q, k_rot, v_cat, mask)
    out = self._PostProj(theta, ctx_vec)
    out = py_utils.ApplyPadding(paddings, out)
    keep = ctx_len  # buffer length stays fixed
    new_states = NestedMap(
        key=k_cat[:, c:] if keep else k_cat[:, :0],
        value=v_cat[:, c:] if keep else v_cat[:, :0],
        paddings=pad_cat[:, c:] if keep else pad_cat[:, :0],
        left_context=left)
    return out, new_states

  # -- incremental decode ----------------------------------------------------

  def _KvDtype(self, kv_cache_dtype=None):
    """(cache storage dtype, quantized?) — an explicit override beats the
    layer param; None/'' on both means the legacy fprop-dtype cache."""
    return kv_quant.ResolveKvCacheDtype(
        kv_cache_dtype or self.p.kv_cache_dtype, self.fprop_dtype)

  def KvCacheDtype(self, kv_cache_dtype=None) -> str:
    """The effective cache storage dtype name (telemetry)."""
    return str(self._KvDtype(kv_cache_dtype)[0])

  def KvBytesPerToken(self, kv_cache_dtype=None) -> int:
    """K + V bytes per cached token in this layer, scale sidecars included."""
    return kv_quant.KvBytesPerToken(self.p.num_heads, self._dim_per_head,
                                    kv_cache_dtype or self.p.kv_cache_dtype,
                                    self.fprop_dtype)

  def InitStates(self, theta, batch_size: int, max_len: int) -> NestedMap:
    n, h = self.p.num_heads, self._dim_per_head
    dtype, quantized = self._KvDtype()
    states = NestedMap(
        key=jnp.zeros((batch_size, max_len, n, h), dtype),
        value=jnp.zeros((batch_size, max_len, n, h), dtype),
        time_step=jnp.zeros((), jnp.int32))
    if quantized:
      # per-token-per-head f32 scales; unwritten slots stay (0, scale 0) ->
      # dequantize to exact zeros, and are masked anyway.
      states.key_scale = jnp.zeros((batch_size, max_len, n), jnp.float32)
      states.value_scale = jnp.zeros((batch_size, max_len, n), jnp.float32)
    return states

  def PagedDecodeEligible(self, max_len: int) -> bool:
    """The paged flash-decode kernel handles plain masked softmax attention
    only; rel-pos bias, logit caps, attention dropout, and prob quantization
    stay dense — as do shapes the Pallas kernel can't tile on real TPU."""
    p = self.p
    from lingvo_tpu.ops import flash_decode
    if jax.default_backend() == "tpu" and not flash_decode.SupportedOnTpu(
        p.decode_page_size, self._dim_per_head):
      return False
    return (flash_decode.SupportedShape(max_len, p.decode_page_size)
            and p.rel_pos_emb_dim == 0 and p.atten_logit_cap == 0
            and p.atten_dropout_prob == 0.0 and p.qdomain_softmax is None)

  def ExtendStep(self, theta, query_vec, cached_states: NestedMap,
                 paddings=None):
    """query_vec: [B, 1, D]; returns ([B, 1, D], updated states)."""
    t = cached_states.time_step
    q = self._HeadsProj(theta, "query", query_vec)
    k_new = self._HeadsProj(theta, "key", query_vec)
    v_new = self._HeadsProj(theta, "value", query_vec)
    if self.p.use_rotary_position_emb:
      rt = self.ChildTheta(theta, "rotary")
      pos = t.astype(jnp.float32)[None, None]
      q = self.rotary.FProp(rt, q, position=pos)
      k_new = self.rotary.FProp(rt, k_new, position=pos)
    q = self._ScaleQuery(theta, q)
    quantized = "key_scale" in cached_states
    if quantized:
      k_new, k_s = kv_quant.QuantizeKv(k_new)              # int8, [B,1,N]
      v_new, v_s = kv_quant.QuantizeKv(v_new)
      key_scale = jax.lax.dynamic_update_slice_in_dim(
          cached_states.key_scale, k_s, t, axis=1)
      value_scale = jax.lax.dynamic_update_slice_in_dim(
          cached_states.value_scale, v_s, t, axis=1)
    key_cache = jax.lax.dynamic_update_slice_in_dim(
        cached_states.key, k_new.astype(cached_states.key.dtype), t, axis=1)
    value_cache = jax.lax.dynamic_update_slice_in_dim(
        cached_states.value, v_new.astype(cached_states.value.dtype), t,
        axis=1)
    max_len = key_cache.shape[1]
    if self.PagedDecodeEligible(max_len) and not quantized:
      # length-aware paged read: only cache pages up to time_step are
      # touched (O(t) per step instead of O(max_len)); q carries the
      # learned scale already, the kernel applies none.
      from lingvo_tpu.ops import flash_decode
      ctx = flash_decode.FlashDecode(
          q, key_cache, value_cache, t,
          page_size=self.p.decode_page_size, cache_paddings=paddings)
    else:
      # mask out future (and unwritten) positions; quantized caches
      # dequantize-on-read and run the dense einsum path (the contiguous
      # flash_decode kernel has no scale plumbing — the block-table kernel
      # in PagedStep is the quantized hot path).
      k_read, v_read = key_cache, value_cache
      if quantized:
        k_read = kv_quant.DequantKv(key_cache, key_scale)
        v_read = kv_quant.DequantKv(value_cache, value_scale)
      pos_ids = jnp.arange(max_len)[None, None, None, :]
      mask = jnp.where(pos_ids <= t, 0.0, _NEG_INF)
      if paddings is not None:
        mask = mask + PaddingsToMask(paddings)
      ctx, _ = self._Atten(theta, q, k_read, v_read, mask)
    new_states = NestedMap(
        key=key_cache, value=value_cache, time_step=t + 1)
    if quantized:
      new_states.key_scale = key_scale
      new_states.value_scale = value_scale
    return self._PostProj(theta, ctx), new_states

  def Prefill(self, theta, query_vec, cached_states: NestedMap,
              paddings=None, live_len: int | None = None):
    """Chunked prefill: one full-attention pass over a whole prompt chunk.

    query_vec: [B, C, D] occupying cache slots [time_step, time_step + C);
    K/V for all C positions land in the cache in ONE dynamic_update_slice
    (vs C sequential ExtendStep calls). Returns ([B, C, D], states). The
    written cache is bit-identical to the per-token path (projections and
    rotary are elementwise-per-position); outputs match to float tolerance
    (the [C, S] context matmul blocks differently than C matvecs).

    live_len: optional STATIC bound with time_step + C <= live_len; the
    attention read touches only cache slots [0, live_len) instead of the
    whole max_len cache (the decode tail is unwritten and masked anyway —
    skipping it only removes exact-zero softmax contributions). Callers
    with static chunk offsets (gshard_decode) pass start + C.
    """
    assert self.p.rel_pos_emb_dim <= 0, (
        "Prefill computes chunk-local query indices; the T5 relative bias "
        "would use wrong buckets (needs a time_step offset)")
    t = cached_states.time_step
    c = query_vec.shape[1]
    q = self._HeadsProj(theta, "query", query_vec)
    k_new = self._HeadsProj(theta, "key", query_vec)
    v_new = self._HeadsProj(theta, "value", query_vec)
    if self.p.use_rotary_position_emb:
      rt = self.ChildTheta(theta, "rotary")
      pos = (t + jnp.arange(c, dtype=jnp.int32)).astype(jnp.float32)[None, :]
      q = self.rotary.FProp(rt, q, position=pos)
      k_new = self.rotary.FProp(rt, k_new, position=pos)
    q = self._ScaleQuery(theta, q)
    quantized = "key_scale" in cached_states
    if quantized:
      k_new, k_s = kv_quant.QuantizeKv(k_new)              # int8, [B,C,N]
      v_new, v_s = kv_quant.QuantizeKv(v_new)
      key_scale = jax.lax.dynamic_update_slice_in_dim(
          cached_states.key_scale, k_s, t, axis=1)
      value_scale = jax.lax.dynamic_update_slice_in_dim(
          cached_states.value_scale, v_s, t, axis=1)
    key_cache = jax.lax.dynamic_update_slice_in_dim(
        cached_states.key, k_new.astype(cached_states.key.dtype), t, axis=1)
    value_cache = jax.lax.dynamic_update_slice_in_dim(
        cached_states.value, v_new.astype(cached_states.value.dtype), t,
        axis=1)
    live = key_cache.shape[1] if live_len is None else live_len
    k_read, v_read = key_cache[:, :live], value_cache[:, :live]
    if quantized:
      k_read = kv_quant.DequantKv(k_read, key_scale[:, :live])
      v_read = kv_quant.DequantKv(v_read, value_scale[:, :live])
    # query i (global slot t+i) sees slot s iff s <= t+i (causal within the
    # chunk + everything already cached); unwritten tail slots masked.
    slot = jnp.arange(live)[None, None, None, :]
    qpos = t + jnp.arange(c)[None, None, :, None]
    mask = jnp.where(slot <= qpos, 0.0, _NEG_INF)
    if paddings is not None:
      mask = mask + PaddingsToMask(paddings[:, :live])
    ctx, _ = self._Atten(theta, q, k_read, v_read, mask)
    new_states = NestedMap(
        key=key_cache, value=value_cache, time_step=t + c)
    if quantized:
      new_states.key_scale = key_scale
      new_states.value_scale = value_scale
    return self._PostProj(theta, ctx), new_states

  # -- block-table paged decode (serving engine) -----------------------------

  def InitPagedStates(self, theta, num_pages: int, page_size: int,
                      num_slots: int = 0,
                      kv_cache_dtype: str | None = None) -> NestedMap:
    """Global KV page pool [num_pages, page_size, N, H] shared by all
    sequences; which pages belong to whom lives host-side in the serving
    engine's block tables, so there is no time_step here (per-sequence
    lengths ride each PagedStep call). The engine reserves the LAST page as
    the trash page that padding-token writes scatter into — allocate with
    one extra page and never hand page num_pages-1 to the allocator.
    num_slots is the engine slot count, consumed by O(1)-state mixers
    (ssm.GatedSSMLayer) and ignored here. kv_cache_dtype overrides the
    layer's p.kv_cache_dtype; 'int8' adds the [num_pages, N, page_size]
    f32 scale sidecars (transposed so the Pallas scale block's minor dim
    is page_size — see lingvo_tpu/quant/kv.py)."""
    del theta, num_slots
    n, h = self.p.num_heads, self._dim_per_head
    dtype, quantized = self._KvDtype(kv_cache_dtype)
    states = NestedMap(
        key=jnp.zeros((num_pages, page_size, n, h), dtype),
        value=jnp.zeros((num_pages, page_size, n, h), dtype))
    if quantized:
      states.key_scale = jnp.zeros((num_pages, n, page_size), jnp.float32)
      states.value_scale = jnp.zeros((num_pages, n, page_size), jnp.float32)
    return states

  def BlockDecodeEligible(self, page_size: int) -> bool:
    """Same gate family as PagedDecodeEligible, for the block-table kernel:
    plain masked-softmax attention only. Ineligible configs run PagedStep's
    gather-dense fallback (exact, just not paged-fast) — the engine surfaces
    that in its stats so a dense run never masquerades as paged."""
    p = self.p
    if jax.default_backend() == "tpu":
      from lingvo_tpu.ops import block_decode
      if not block_decode.SupportedOnTpu(page_size, self._dim_per_head):
        return False
    return (page_size > 0 and p.rel_pos_emb_dim == 0
            and p.atten_logit_cap == 0 and p.atten_dropout_prob == 0.0
            and p.qdomain_softmax is None)

  def QuantizedDecodeEligible(self, page_size: int) -> bool:
    """Whether the int8 block-table kernels can serve this layer: the
    BlockDecodeEligible gate plus the int8-aware TPU tiling check. An
    int8 pool that fails this gate still decodes correctly — PagedStep
    gathers, dequantizes, and runs the dense einsum path — but the engine
    reports the step as 'dense' so the fallback is never silent."""
    p = self.p
    if jax.default_backend() == "tpu":
      from lingvo_tpu.ops import block_decode
      if not block_decode.SupportedOnTpu(page_size, self._dim_per_head,
                                         kv_dtype="int8"):
        return False
    return (page_size > 0 and p.rel_pos_emb_dim == 0
            and p.atten_logit_cap == 0 and p.atten_dropout_prob == 0.0
            and p.qdomain_softmax is None)

  def PagedStep(self, theta, query_vec, cached_states: NestedMap,
                block_tables, q_pos, in_len):
    """One continuous-batching step against the block-table page pool.

    query_vec: [B, C, D] — row b's tokens for global slots
    [q_pos[b], q_pos[b] + in_len[b]); queries past in_len[b] are padding
    (their pool writes go to the trash page, their outputs are garbage the
    engine discards). C == 1 is the steady-state decode step; C > 1 is a
    chunked-prefill step (decode rows riding a mixed step use in_len == 1).
    block_tables: [B, t_pages] int32 physical page ids (allocator-owned;
    rows own disjoint pages, so valid writes never collide). q_pos/in_len:
    [B] int32. Returns ([B, C, D], updated states). Unlike ExtendStep the
    layout is LEFT-aligned with no cache_paddings: rotary attention depends
    only on relative position, so numerics match the right-aligned dense
    path (asserted by the engine parity tests).
    """
    from lingvo_tpu.ops import block_decode
    p = self.p
    assert p.rel_pos_emb_dim <= 0, (
        "PagedStep computes positions from q_pos; the T5 relative bias "
        "would use wrong buckets")
    k_pool, v_pool = cached_states.key, cached_states.value
    np_total, page_size = k_pool.shape[0], k_pool.shape[1]
    t_pages = block_tables.shape[1]
    b, c, _ = query_vec.shape
    q_pos = q_pos.astype(jnp.int32)
    in_len = in_len.astype(jnp.int32)
    q = self._HeadsProj(theta, "query", query_vec)
    k_new = self._HeadsProj(theta, "key", query_vec)
    v_new = self._HeadsProj(theta, "value", query_vec)
    pos_i = q_pos[:, None] + jnp.arange(c, dtype=jnp.int32)[None]  # [B, C]
    if p.use_rotary_position_emb:
      rt = self.ChildTheta(theta, "rotary")
      pos = pos_i.astype(jnp.float32)
      q = self.rotary.FProp(rt, q, position=pos)
      k_new = self.rotary.FProp(rt, k_new, position=pos)
    q = self._ScaleQuery(theta, q)
    # scatter the chunk's K/V through the block table BEFORE the attention
    # read (chunk self-attention needs them); padding queries write to the
    # trash page (pool page np_total - 1, never in any block table)
    valid = jnp.arange(c, dtype=jnp.int32)[None] < in_len[:, None]  # [B, C]
    logical = jnp.clip(pos_i // page_size, 0, t_pages - 1)
    phys = jnp.take_along_axis(
        jnp.clip(block_tables.astype(jnp.int32), 0, np_total - 1),
        logical, axis=1)                                           # [B, C]
    phys = jnp.where(valid, phys, np_total - 1)
    off = jnp.where(valid, pos_i % page_size,
                    jnp.arange(c, dtype=jnp.int32)[None] % page_size)
    quantized = "key_scale" in cached_states
    k_scale = v_scale = None
    if quantized:
      # quantize-on-write: each token row gets its own per-head scale, so
      # the scatter below is the ONLY write this token's page ever sees —
      # no page-level re-quantization. Sidecar layout [NP, N, P]: the two
      # advanced indices (phys, off) around the head slice broadcast to
      # the front, so the update shape is [B, C, N] == the scale shape.
      k_new, k_s = kv_quant.QuantizeKv(k_new)              # int8, [B,C,N]
      v_new, v_s = kv_quant.QuantizeKv(v_new)
      k_scale = cached_states.key_scale.at[phys, :, off].set(k_s)
      v_scale = cached_states.value_scale.at[phys, :, off].set(v_s)
    k_pool = k_pool.at[phys, off].set(k_new.astype(k_pool.dtype))
    v_pool = v_pool.at[phys, off].set(v_new.astype(v_pool.dtype))
    new_states = NestedMap(key=k_pool, value=v_pool)
    if quantized:
      new_states.key_scale = k_scale
      new_states.value_scale = v_scale
    eligible = (self.QuantizedDecodeEligible(page_size) if quantized
                else self.BlockDecodeEligible(page_size))
    if eligible:
      if c == 1:
        ctx = block_decode.BlockDecode(
            q, k_pool, v_pool, block_tables, q_pos + in_len,
            page_size=page_size, k_scale=k_scale, v_scale=v_scale)
      else:
        ctx = block_decode.BlockPrefill(
            q, k_pool, v_pool, block_tables, q_pos, in_len,
            page_size=page_size, k_scale=k_scale, v_scale=v_scale)
    else:
      # gather-dense fallback: materialize the row's logical cache view and
      # run the einsum path (handles logit cap / dropout / prob quant).
      # Slots <= q_pos + c are by construction inside the row's live prefix
      # (owned pages); everything past is stale/foreign and masked.
      k_dense = block_decode.GatherPages(k_pool, block_tables)
      v_dense = block_decode.GatherPages(v_pool, block_tables)
      if quantized:
        k_dense = kv_quant.DequantKv(
            k_dense, block_decode.GatherScales(k_scale, block_tables))
        v_dense = kv_quant.DequantKv(
            v_dense, block_decode.GatherScales(v_scale, block_tables))
      slot = jnp.arange(t_pages * page_size)[None, None, None, :]
      mask = jnp.where(slot <= pos_i[:, None, :, None], 0.0, _NEG_INF)
      ctx, _ = self._Atten(theta, q, k_dense, v_dense, mask)
    return self._PostProj(theta, ctx), new_states

  def RaggedStep(self, theta, query_vec, cached_states: NestedMap,
                 block_tables, rows):
    """One PACKED continuous-batching step (core/ragged.py RaggedRows).

    query_vec: [1, T, D] — all rows' tokens flattened on one token axis;
    token t belongs to slot rows.row_of[t] and lands at global kv slot
    rows.pos[t] through that row's block table. Decode rows contribute one
    token, prefill chunks and spec-verify windows several — the single
    program the engine compiles instead of three (decode / mixed /
    verify). Padding tokens (rows.valid == False) scatter to the trash
    page and emit garbage the engine discards. Returns ([1, T, D],
    updated states). Same numerics per token as PagedStep — the ragged
    op twins (ops/ragged_block_attend.py) carry the bitwise proof at the
    op level.
    """
    from lingvo_tpu.ops import block_decode
    from lingvo_tpu.ops import ragged_block_attend
    p = self.p
    assert p.rel_pos_emb_dim <= 0, (
        "RaggedStep computes positions from rows.pos; the T5 relative "
        "bias would use wrong buckets")
    k_pool, v_pool = cached_states.key, cached_states.value
    np_total, page_size = k_pool.shape[0], k_pool.shape[1]
    b, t_pages = block_tables.shape
    t = query_vec.shape[1]
    pos = rows.pos.astype(jnp.int32)                               # [T]
    valid = rows.valid
    row = jnp.clip(rows.row_of.astype(jnp.int32), 0, b - 1)
    # Tree rows decouple the KV SLOT (pos, DFS-ordered, collision-free)
    # from the LOGICAL position (pos_ids = q_pos + depth) a token embeds
    # at; on chain rows pos_ids == pos bitwise.
    rot_pos = rows.pos_ids.astype(jnp.int32)
    q_start = rows.row_q_pos.astype(jnp.int32)[row]                # [T]
    q = self._HeadsProj(theta, "query", query_vec)                 # [1,T,N,H]
    k_new = self._HeadsProj(theta, "key", query_vec)
    v_new = self._HeadsProj(theta, "value", query_vec)
    if p.use_rotary_position_emb:
      rt = self.ChildTheta(theta, "rotary")
      posf = rot_pos[None].astype(jnp.float32)
      q = self.rotary.FProp(rt, q, position=posf)
      k_new = self.rotary.FProp(rt, k_new, position=posf)
    q = self._ScaleQuery(theta, q)
    # scatter each token's K/V through ITS row's block table before the
    # read (later tokens of the same prefill chunk attend to earlier ones);
    # padding tokens write to the trash page (pool page np_total - 1)
    logical = jnp.clip(pos // page_size, 0, t_pages - 1)
    phys = jnp.clip(block_tables.astype(jnp.int32),
                    0, np_total - 1)[row, logical]                 # [T]
    phys = jnp.where(valid, phys, np_total - 1)
    off = jnp.where(valid, pos % page_size,
                    jnp.arange(t, dtype=jnp.int32) % page_size)
    quantized = "key_scale" in cached_states
    k_scale = v_scale = None
    if quantized:
      k_new, k_s = kv_quant.QuantizeKv(k_new)              # int8, [1,T,N]
      v_new, v_s = kv_quant.QuantizeKv(v_new)
      k_scale = cached_states.key_scale.at[phys, :, off].set(k_s[0])
      v_scale = cached_states.value_scale.at[phys, :, off].set(v_s[0])
    k_pool = k_pool.at[phys, off].set(k_new[0].astype(k_pool.dtype))
    v_pool = v_pool.at[phys, off].set(v_new[0].astype(v_pool.dtype))
    new_states = NestedMap(key=k_pool, value=v_pool)
    if quantized:
      new_states.key_scale = k_scale
      new_states.value_scale = v_scale
    eligible = (self.QuantizedDecodeEligible(page_size) if quantized
                else self.BlockDecodeEligible(page_size))
    # token t attends over its row's slots [0, pos[t]]; q_end = 0 marks
    # padding (the ragged op emits exact zeros there)
    q_end = jnp.where(valid, pos + 1, 0)
    if eligible:
      ctx = ragged_block_attend.RaggedAttend(
          q[0], k_pool, v_pool, block_tables, row, q_end,
          page_size=page_size, k_scale=k_scale, v_scale=v_scale,
          q_start=q_start, anc_lo=rows.anc_lo, anc_hi=rows.anc_hi)[None]
    else:
      # gather-dense fallback at token granularity: each token is a batch
      # row of one query over its row's materialized cache view (handles
      # logit cap / dropout / prob quant exactly like PagedStep's)
      k_dense = block_decode.GatherPages(k_pool, block_tables)
      v_dense = block_decode.GatherPages(v_pool, block_tables)
      if quantized:
        k_dense = kv_quant.DequantKv(
            k_dense, block_decode.GatherScales(k_scale, block_tables))
        v_dense = kv_quant.DequantKv(
            v_dense, block_decode.GatherScales(v_scale, block_tables))
      slot = jnp.arange(t_pages * page_size)[None, None, None, :]
      # padding tokens see slot 0 only (garbage, but never an all-masked
      # softmax row)
      horizon = jnp.where(valid, pos, 0)
      ok = ragged_block_attend._AncestorOk(
          slot, slot - q_start[:, None, None, None],
          rows.anc_lo[:, None, None, None], rows.anc_hi[:, None, None, None])
      # padding tokens keep their slot-0 escape hatch regardless of mask
      ok = ok | ~valid[:, None, None, None]
      mask = jnp.where(
          (slot <= horizon[:, None, None, None]) & ok, 0.0, _NEG_INF)
      ctx, _ = self._Atten(theta, q[0][:, None], k_dense[row],
                           v_dense[row], mask)
      ctx = ctx[:, 0][None]
    return self._PostProj(theta, ctx), new_states


class LocalSelfAttention(MultiHeadedAttention):
  """Blocked sliding-window self-attention (ref
  `batch_major_attention.py:2656`).

  Each block of W queries attends to keys in [left_context, right_context]
  around it, materializing only [B, #blocks, W, (prev+cur+next)*W] logits —
  O(T*W) memory instead of O(T^2). Requires left/right context <= block_size.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("block_size", 64, "Query block width W.")
    p.Define("left_context", 64,
             "How many past positions each query sees (incl. itself - 1).")
    p.Define("right_context", 0, "Future positions visible (0 = causal).")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.left_context <= p.block_size + 1, "left_context > block_size+1"
    assert p.right_context <= p.block_size, "right_context > block_size"

  def _AddRelPositionBias(self, theta, qb, kb, rel, logits):
    """Hook for relative-position logit bias (LocalSelfAttentionXL).

    qb: [B, L, W, N, H] (query pre-scaled); kb: [B, L, 3W, N, H];
    rel: [W, 3W] int relative positions; logits: [B, L, N, W, 3W].
    """
    del qb, kb, rel
    return logits

  def FProp(self, theta, query_vec, key_vec=None, value_vec=None,
            paddings=None, atten_mask=None, segment_ids=None, causal=False):
    p = self.p
    del key_vec, value_vec  # self-attention only
    # causality is inherent to the window config (right_context=0); the
    # kwarg exists for signature compatibility with the base class.
    del causal
    if atten_mask is not None:
      raise NotImplementedError(
          "LocalSelfAttention cannot apply a dense [T, T] atten_mask to its "
          "windowed logits; use segment_ids (packed inputs) or paddings.")
    b, t, d = query_vec.shape
    w = p.block_size
    num_blocks = -(-t // w)
    pad_t = num_blocks * w - t
    x = jnp.pad(query_vec, ((0, 0), (0, pad_t), (0, 0)))
    pads = jnp.ones((b, num_blocks * w), jnp.float32)
    if paddings is None:
      pads = pads.at[:, :t].set(0.0)
    else:
      pads = pads.at[:, :t].set(paddings)

    q = self._HeadsProj(theta, "query", x)
    k = self._HeadsProj(theta, "key", x)
    v = self._HeadsProj(theta, "value", x)
    if p.use_rotary_position_emb:
      rt = self.ChildTheta(theta, "rotary")
      q = self.rotary.FProp(rt, q)
      k = self.rotary.FProp(rt, k)
    q = self._ScaleQuery(theta, q)
    n, h = p.num_heads, self._dim_per_head

    def _Blocked(arr):
      return arr.reshape(b, num_blocks, w, n, h)

    def _WithNeighbors(arr):
      """[B, nb, 3W, N, H]: prev | cur | next blocks as key context."""
      blocked = _Blocked(arr)
      prev = jnp.pad(blocked, ((0, 0), (1, 0), (0, 0), (0, 0), (0, 0)))[:, :-1]
      nxt = jnp.pad(blocked, ((0, 0), (0, 1), (0, 0), (0, 0), (0, 0)))[:, 1:]
      return jnp.concatenate([prev, blocked, nxt], axis=2)

    qb = _Blocked(q)
    kb = _WithNeighbors(k)
    vb = _WithNeighbors(v)
    logits = jnp.einsum("BLQNH,BLKNH->BLNQK", qb, kb).astype(jnp.float32)

    # Relative position of key col to query row within the 3W context:
    # key absolute offset = col - W + block_start; query = row + block_start.
    rel = (jnp.arange(3 * w)[None, :] - w) - jnp.arange(w)[:, None]
    logits = self._AddRelPositionBias(theta, qb, kb, rel, logits)
    visible = (rel >= -p.left_context + 1) & (rel <= p.right_context)
    logits = jnp.where(visible[None, None, None, :, :], logits, _NEG_INF)

    # key paddings within each 3W window
    pads_blocked = pads.reshape(b, num_blocks, w)
    pads_prev = jnp.pad(pads_blocked, ((0, 0), (1, 0), (0, 0)),
                        constant_values=1.0)[:, :-1]
    pads_next = jnp.pad(pads_blocked, ((0, 0), (0, 1), (0, 0)),
                        constant_values=1.0)[:, 1:]
    kpads = jnp.concatenate([pads_prev, pads_blocked, pads_next], axis=2)
    logits = logits + (kpads[:, :, None, None, :] * _NEG_INF)
    if segment_ids is not None:
      # Packed inputs: queries must not see keys of a different segment even
      # inside the window. Padded positions get segment -1 (matches nothing
      # unpadded; padding is masked above anyway).
      seg = jnp.pad(segment_ids.astype(jnp.int32), ((0, 0), (0, pad_t)),
                    constant_values=-1)
      seg_blocked = seg.reshape(b, num_blocks, w)
      seg_prev = jnp.pad(seg_blocked, ((0, 0), (1, 0), (0, 0)),
                         constant_values=-1)[:, :-1]
      seg_next = jnp.pad(seg_blocked, ((0, 0), (0, 1), (0, 0)),
                         constant_values=-1)[:, 1:]
      kseg = jnp.concatenate([seg_prev, seg_blocked, seg_next], axis=2)
      same = seg_blocked[:, :, :, None] == kseg[:, :, None, :]  # [B,L,Q,K]
      logits = jnp.where(same[:, :, None, :, :], logits, _NEG_INF)
    logits = jnp.maximum(logits, _NEG_INF)

    probs = self._QProbs(theta, jax.nn.softmax(logits, axis=-1).astype(
        q.dtype))
    if p.atten_dropout_prob > 0:
      probs = self.atten_dropout.FProp(
          self.ChildTheta(theta, "atten_dropout"), probs,
          keep_prob=1.0 - p.atten_dropout_prob)
    ctx = jnp.einsum("BLNQK,BLKNH->BLQNH", probs, vb)
    ctx = ctx.reshape(b, num_blocks * w, n, h)[:, :t]
    out = self._PostProj(theta, ctx)
    if paddings is not None:
      out = py_utils.ApplyPadding(paddings, out)
    return out, probs


class ChunkwiseSelfAttention(MultiHeadedAttention):
  """Chunked self-attention: full attention within fixed chunks, none across
  (ref `batch_major_attention.py:4008`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("chunk_size", 64, "Chunk width.")
    p.Define("causal", True, "Causal masking within chunks.")
    return p

  def FProp(self, theta, query_vec, key_vec=None, value_vec=None,
            paddings=None, atten_mask=None, segment_ids=None, causal=False):
    p = self.p
    del causal  # governed by p.causal (within-chunk masking)
    if atten_mask is not None:
      raise NotImplementedError(
          "ChunkwiseSelfAttention cannot apply a dense [T, T] atten_mask to "
          "its chunked logits; use segment_ids (packed inputs) or paddings.")
    b, t, d = query_vec.shape
    c = p.chunk_size
    num_chunks = -(-t // c)
    pad_t = num_chunks * c - t
    x = jnp.pad(query_vec, ((0, 0), (0, pad_t), (0, 0)))
    pads = jnp.ones((b, num_chunks * c), jnp.float32)
    pads = pads.at[:, :t].set(
        paddings if paddings is not None else jnp.zeros((b, t)))

    q = self._HeadsProj(theta, "query", x)
    k = self._HeadsProj(theta, "key", x)
    v = self._HeadsProj(theta, "value", x)
    if p.use_rotary_position_emb:
      rt = self.ChildTheta(theta, "rotary")
      q = self.rotary.FProp(rt, q)
      k = self.rotary.FProp(rt, k)
    q = self._ScaleQuery(theta, q)
    n, h = p.num_heads, self._dim_per_head

    def _Chunked(arr):
      return arr.reshape(b, num_chunks, c, n, h)

    qc, kc, vc = _Chunked(q), _Chunked(k), _Chunked(v)
    logits = jnp.einsum("BLQNH,BLKNH->BLNQK", qc, kc).astype(jnp.float32)
    if p.causal:
      causal = jnp.tril(jnp.ones((c, c), jnp.bool_))
      logits = jnp.where(causal[None, None, None], logits, _NEG_INF)
    pads_c = pads.reshape(b, num_chunks, c)
    logits = logits + pads_c[:, :, None, None, :] * _NEG_INF
    if segment_ids is not None:
      seg = jnp.pad(segment_ids.astype(jnp.int32), ((0, 0), (0, pad_t)),
                    constant_values=-1)
      seg_c = seg.reshape(b, num_chunks, c)
      same = seg_c[:, :, :, None] == seg_c[:, :, None, :]     # [B,L,Q,K]
      logits = jnp.where(same[:, :, None, :, :], logits, _NEG_INF)
    logits = jnp.maximum(logits, _NEG_INF)
    probs = self._QProbs(theta, jax.nn.softmax(logits, -1).astype(q.dtype))
    ctx = jnp.einsum("BLNQK,BLKNH->BLQNH", probs, vc)
    ctx = ctx.reshape(b, num_chunks * c, n, h)[:, :t]
    out = self._PostProj(theta, ctx)
    if paddings is not None:
      out = py_utils.ApplyPadding(paddings, out)
    return out, probs
