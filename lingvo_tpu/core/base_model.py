"""BaseTask / BaseModel: the trainable unit and its container.

Re-designs `lingvo/core/base_model.py` (`BaseTask:116`, `BaseModel:1138`)
TPU-natively. A task still splits into `ComputePredictions` / `ComputeLoss`
(ref `:465,:486`) returning a `metrics` NestedMap of (value, weight) pairs —
but FProp is pure, BProp is replaced by a pure `TrainStep(state, batch)`
built with `jax.value_and_grad` + the Learner, and EMA is a functional state
field rather than assign ops (ref `ExecutorEma`, `base_model.py:69`).

The train state is the single pytree that programs/checkpointers handle:
  TrainState = NestedMap(step, theta, opt_states, ema_theta?)
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import hyperparams
from lingvo_tpu.core import learner as learner_lib
from lingvo_tpu.core import py_utils
from lingvo_tpu.core import tpu_summary
from lingvo_tpu.core.nested_map import NestedMap


class BaseTask(base_layer.BaseLayer):
  """A trainable task: model graph + loss + (optional) decode logic."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input", None, "Input generator params for this task.")
    tp = hyperparams.Params()
    tp.Define("learner", learner_lib.Learner.Params(),
              "Learner (or list of Learners, e.g. GAN).")
    tp.Define("ema_decay", 0.0, "If >0, keep an EMA copy of theta.")
    tp.Define("ema_decay_moving_vars", True,
              "Whether EMA also covers non-trainable vars.")
    tp.Define("start_up_delay_steps", 0, "Kept for parity; unused on TPU.")
    tp.Define("max_steps", 4_000_000, "Training halts after this step.")
    tp.Define("tpu_steps_per_loop", 100, "Device steps per host loop.")
    tp.Define("save_interval_steps", 1000, "Checkpoint every N steps.")
    tp.Define("save_max_to_keep", 10, "Checkpoints kept by GC.")
    tp.Define("summary_interval_steps", 100, "Summary cadence.")
    tp.Define("early_stop_window", 0,
              "Stop after this many steps without eval-loss improvement "
              "(0 = disabled; ref early_stop.EarlyStop).")
    tp.Define("early_stop_tolerance", 0.0, "Improvement margin.")
    tp.Define("early_stop_metric", "loss", "Eval metric to watch.")
    tp.Define("early_stop_program", "eval_test",
              "Which eval program's results feed the plateau detector.")
    tp.Define("init_from_checkpoint_rules", {},
              "Warm start (ref checkpointer.py:214): "
              "{ckpt_train_dir: [(target_var_regex, source_var_template), "
              "...]} — on fresh init, theta leaves whose path matches a "
              "target regex are loaded from the source checkpoint's var at "
              "re.sub(target_regex, source_template, path), with dtype "
              "casting (ref bfloat16_variables.py). Applied only when no "
              "checkpoint exists in the run's own train dir.")
    tp.Define("init_from_npz", "",
              "Warm start from a converted reference checkpoint "
              "(tools/convert_tf_checkpoint.py .npz); applied on fresh "
              "init like init_from_checkpoint_rules.")
    tp.Define("init_from_npz_rules", None,
              "Optional [(target_regex, source_template)] name mapping for "
              "init_from_npz (None = npz keys are already theta paths).")
    tp.Define("pruning", None,
              "Optional core.pruning.PruningSchedule params: magnitude "
              "masks updated at the schedule cadence and re-applied after "
              "every program run (ref model_pruning hooks, "
              "base_model.py:1105).")
    p.Define("train", tp, "Training hyperparams.")
    ep = hyperparams.Params()
    ep.Define("samples_per_summary", 1000, "Max eval examples per run.")
    ep.Define("decoder_samples_per_summary", 0, "Decode sample count.")
    p.Define("eval", ep, "Eval hyperparams.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    lp = p.train.learner
    if isinstance(lp, (list, tuple)):
      self.CreateChildren("learners", list(lp))
    else:
      self.CreateChildren("learners", [lp])
    if p.input is not None:
      self._input_params = p.input
    else:
      self._input_params = None

  # ---- subclass points (ref base_model.py:465-486) -------------------------

  def ComputePredictions(self, theta: NestedMap,
                         input_batch: NestedMap) -> NestedMap:
    raise NotImplementedError

  def ComputeLoss(self, theta: NestedMap, predictions: NestedMap,
                  input_batch: NestedMap) -> tuple[NestedMap, NestedMap]:
    """Returns (metrics NestedMap of (value, weight), per_example NestedMap).

    metrics must contain the learner's loss_name entry ('loss' by default).
    """
    raise NotImplementedError

  def FProp(self, theta: NestedMap,
            input_batch: NestedMap) -> tuple[NestedMap, NestedMap]:
    predictions = self.ComputePredictions(theta, input_batch)
    return self.ComputeLoss(theta, predictions, input_batch)

  # ---- decode/inference hooks (ref base_model.py:918-1000) -----------------

  def Decode(self, theta: NestedMap, input_batch: NestedMap) -> NestedMap:
    """Returns per-example decode output tensors (device side)."""
    raise NotImplementedError(f"{type(self).__name__}.Decode")

  def CreateDecoderMetrics(self) -> dict:
    """Host-side metric objects keyed by name."""
    return {}

  def PostProcessDecodeOut(self, decode_out: NestedMap,
                           decoder_metrics: dict) -> None:
    """Consumes one batch of (host) decode output into decoder_metrics."""

  def DecodeFinalize(self, decoder_metrics: dict) -> dict[str, float]:
    return {k: m.value for k, m in decoder_metrics.items()}

  def Inference(self) -> dict:
    """Returns {subgraph_name: (fn, example_inputs)} for export."""
    raise NotImplementedError(f"{type(self).__name__}.Inference")

  # ---- train state ---------------------------------------------------------

  def CreateTrainState(self, key: jax.Array) -> NestedMap:
    """Initializes theta + optimizer state + step counter (+ EMA)."""
    theta = self.InstantiateVariables(key)
    state = NestedMap(
        step=jnp.zeros((), jnp.int32),
        theta=theta,
        opt_states=[lrn.InitState(self._TrainableSubset(theta, lrn))
                    for lrn in self.learners],
    )
    if self.p.train.ema_decay > 0:
      state.ema_theta = jax.tree_util.tree_map(lambda x: x, theta)
    return state

  def _VarPathsAndSpecs(self):
    specs = self.VariableSpecs()
    return specs.FlattenItems()

  def _TrainableSubset(self, theta: NestedMap,
                       lrn: learner_lib.Learner) -> NestedMap:
    """Filters theta to this learner's trainable vars (structure-pruning)."""
    specs = self.VariableSpecs()
    flat_specs = dict(specs.FlattenItems())
    return theta.FilterKeyVal(
        lambda k, v: lrn.TrainableFilter(k, flat_specs.get(k)))

  def _MergeSubset(self, theta: NestedMap, subset: NestedMap) -> NestedMap:
    """Writes subset leaves back into a copy of theta."""
    new_theta = theta.DeepCopy()
    for k, v in subset.FlattenItems():
      new_theta.Set(k, v)
    return new_theta

  def TrainStep(self, state: NestedMap, input_batch: NestedMap,
                base_step_key: jax.Array | None = None
                ) -> tuple[NestedMap, NestedMap]:
    """One pure training step: returns (new_state, metrics+stats).

    Jit/pjit this (or wrap in lax.scan over batches for steps_per_loop).
    """
    p = self.p
    step_key = jax.random.fold_in(
        base_step_key if base_step_key is not None else jax.random.PRNGKey(0),
        state.step)

    theta = state.theta
    new_opt_states = []
    all_stats = NestedMap()
    metrics = per_example = None
    fwd_updates: dict = {}
    summaries = NestedMap()
    for i, lrn in enumerate(self.learners):

      def _Loss(trainable, frozen_rest, lrn=lrn):
        full_theta = self._MergeSubset(frozen_rest, trainable)
        with py_utils.StepSeedContext(step_key), \
             py_utils.GlobalStepContext(state.step):
          with py_utils.ForwardStateContext() as fwd:
            with py_utils.AuxLossContext() as aux_losses, \
                 tpu_summary.Context() as summaries_:
              metrics_, per_example_ = self.FProp(full_theta, input_batch)
        loss_val, loss_w = metrics_[lrn.p.loss_name]
        total = jnp.asarray(loss_val, jnp.float32)
        if aux_losses:
          aux_total = sum(jnp.asarray(v, jnp.float32)
                          for v in aux_losses.values())
          total = total + aux_total
          metrics_ = metrics_.Copy()
          metrics_.aux_loss = (aux_total, loss_w)
        reg = lrn.RegularizationLoss(trainable)
        # fwd updates are tracers from this trace: they MUST exit via aux.
        return total + reg, (metrics_, per_example_, fwd,
                             tpu_summary.Merged(summaries_))

      trainable = self._TrainableSubset(theta, lrn)
      (_, (metrics, per_example, fwd_updates, summaries)), grads = (
          jax.value_and_grad(_Loss, has_aux=True)(trainable, theta))
      new_trainable, new_opt_state, stats = lrn.Apply(
          trainable, grads, state.step, state.opt_states[i])
      theta = self._MergeSubset(theta, new_trainable)
      new_opt_states.append(new_opt_state)
      prefix = f"{lrn.p.name}_" if len(self.learners) > 1 else ""
      for k, v in stats.FlattenItems():
        all_stats[f"{prefix}{k}"] = v

    # Functional forward-state updates (BN moving stats).
    if fwd_updates:
      theta = py_utils.ApplyForwardStateUpdates(theta, fwd_updates, self)

    new_state = NestedMap(
        step=state.step + 1, theta=theta, opt_states=new_opt_states)
    if "ema_theta" in state:
      decay = jnp.minimum(
          p.train.ema_decay,
          (1.0 + state.step.astype(jnp.float32)) /
          (10.0 + state.step.astype(jnp.float32)))
      if p.train.ema_decay_moving_vars:
        ema_mask = None
      else:
        # Static per-leaf mask: non_trainable vars (BN moving stats) track
        # theta directly instead of being EMA-smoothed.
        specs = dict(self.VariableSpecs().FlattenItems())
        ema_mask = theta.TransformWithKey(
            lambda k, v: "non_trainable" not in tuple(
                getattr(specs.get(k), "collections", ()) or ()))
      if ema_mask is None:
        new_state.ema_theta = jax.tree_util.tree_map(
            lambda e, t: e * decay + t.astype(e.dtype) * (1.0 - decay),
            state.ema_theta, theta)
      else:
        new_state.ema_theta = jax.tree_util.tree_map(
            lambda e, t, m: (e * decay + t.astype(e.dtype) *
                             (1.0 - decay)) if m else t,
            state.ema_theta, theta, ema_mask)
    out_metrics = metrics.Copy() if metrics is not None else NestedMap()
    out_metrics_stats = NestedMap(metrics=out_metrics, stats=all_stats,
                                  per_example=per_example or NestedMap(),
                                  summaries=summaries)
    return new_state, out_metrics_stats

  def EvalStep(self, theta: NestedMap, input_batch: NestedMap,
               step=None) -> tuple[NestedMap, NestedMap]:
    """One pure eval step (eval-mode FProp).

    `step` (optional): the global step, for schedule-dependent layers
    (quantization clip caps must anneal identically in train and eval).
    """
    import contextlib
    step_ctx = (py_utils.GlobalStepContext(step) if step is not None
                else contextlib.nullcontext())
    with py_utils.EvalContext(), step_ctx:
      return self.FProp(theta, input_batch)

  # ---- input ---------------------------------------------------------------

  def CreateInputGenerator(self):
    if self._input_params is None:
      raise ValueError(f"Task {self.p.name} has no input params")
    from lingvo_tpu.core import input_policy
    return input_policy.Instantiate(self._input_params)


class BaseModel(base_layer.BaseLayer):
  """Container of one or more tasks (ref base_model.py:1138)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("model", None, "Unused; parity slot.")
    return p

  def GetTask(self, task_name: str | None = None) -> BaseTask:
    raise NotImplementedError

  @property
  def tasks(self) -> list[BaseTask]:
    raise NotImplementedError


class SingleTaskModel(BaseModel):
  """Model with exactly one task (ref base_model.py:1379)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("task", None, "The task params.")
    p.Define("input", None, "Input params (attached by registry).")
    return p

  def __init__(self, params):
    if params.task is not None and params.input is not None:
      if params.task.input is None:
        params = params.Copy()
        params.task.input = params.input
    super().__init__(params)
    self.CreateChild("_task", self.p.task)

  def GetTask(self, task_name: str | None = None) -> BaseTask:
    return self._task

  @property
  def tasks(self):
    return [self._task]


class MultiTaskModel(BaseModel):
  """Model with several named tasks sampled by a schedule
  (ref base_model.py:1480)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("task_params", None,
             "Params with one sub-Params per task name.")
    p.Define("task_probs", None,
             "Params with one float per task name (sampling weights).")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self._task_names = sorted(k for k, _ in p.task_params.IterParams())
    for name, task_p in p.task_params.IterParams():
      self.CreateChild(f"task_{name}", task_p)

  @property
  def task_names(self):
    return list(self._task_names)

  def GetTask(self, task_name: str | None = None) -> BaseTask:
    if task_name is None:
      task_name = self._task_names[0]
    return self._children[f"task_{task_name}"]

  @property
  def tasks(self):
    return [self.GetTask(n) for n in self._task_names]
