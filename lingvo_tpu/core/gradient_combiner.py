"""Combining gradients from multiple losses (multi-task training).

Re-designs `lingvo/core/gradient_combiner.py` (abstract Combine over
{loss_name: (loss_metric, grads)}) with concrete TPU-friendly combiners:
plain weighted sums and PCGrad-style gradient surgery
(https://arxiv.org/abs/2001.06782, cited by the reference docstring).
All combiners are pure pytree functions — jit/pjit them freely.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core.nested_map import NestedMap


class GradientCombiner(base_layer.BaseLayer):
  """Interface (ref `gradient_combiner.py:27`)."""

  def _NameIsRequired(self):
    return False

  def Combine(self, vmap: NestedMap, losses_and_gradients: dict) -> NestedMap:
    """losses_and_gradients: {name: NestedMap(loss_metric=(loss, w),
    grads=<tree like vmap>)} -> combined grads tree."""
    raise NotImplementedError(type(self).__name__)


class LinearCombiner(GradientCombiner):
  """Weighted sum of per-loss gradients (the default TF behavior)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("loss_weights", None,
             "Optional {loss_name: weight}; default = each loss's metric "
             "weight normalized away (plain sum).")
    return p

  def Combine(self, vmap, losses_and_gradients):
    weights = self.p.loss_weights or {}
    combined = None
    for name, lg in losses_and_gradients.items():
      w = weights.get(name, 1.0)
      scaled = jax.tree_util.tree_map(lambda g: w * g, lg.grads)
      combined = scaled if combined is None else jax.tree_util.tree_map(
          jnp.add, combined, scaled)
    return combined


class PCGradCombiner(GradientCombiner):
  """Gradient surgery: project away conflicting components.

  For each ordered pair (i, j), if <g_i, g_j> < 0, g_i is projected onto the
  normal plane of g_j (computed over the flattened full gradient, in task
  order — the deterministic variant of PCGrad, which keeps the combine
  jit-compatible and reproducible across hosts).
  """

  def Combine(self, vmap, losses_and_gradients):
    from jax.flatten_util import ravel_pytree
    names = list(losses_and_gradients.keys())
    grads = [losses_and_gradients[n].grads for n in names]
    unravel = None
    flats = []
    for g in grads:
      flat, unravel = ravel_pytree(g)
      flats.append(flat.astype(jnp.float32))

    projected = []
    for i, gi in enumerate(flats):
      out = gi
      for j, gj in enumerate(flats):
        if i == j:
          continue
        dot = jnp.sum(out * gj)
        denom = jnp.sum(gj * gj) + 1e-12
        out = out - jnp.minimum(dot, 0.0) / denom * gj
      projected.append(out)
    ref_flat, unravel = ravel_pytree(grads[0])
    return unravel(sum(projected).astype(ref_flat.dtype))
