"""Cluster spec: run topology for single- and multi-host training.

Re-designs `lingvo/core/cluster.py` (673 LoC). The reference models a TF1
job zoo (controller/worker/ps/input/...) with device placement; the
TPU-native runtime collapses to: process topology (hosts x local devices),
mesh geometry, and per-host infeed sharding. Also carries the reference's
thread-local current-cluster stack (`cluster_factory.Current`) and
job-role-gated summary writing (`cluster.add_summary`).
"""

from __future__ import annotations

import contextlib
import threading

import jax

from lingvo_tpu.core import hyperparams

_TLS = threading.local()


class Cluster:

  @classmethod
  def Params(cls):
    p = hyperparams.InstantiableParams(cls)
    p.Define("name", "cluster", "Name.")
    p.Define("job", "executor_tpu", "This process's role.")
    p.Define("mode", "sync", "sync (SPMD) | async (unsupported on TPU).")
    p.Define("do_eval", False, "Eval-mode graph construction.")
    p.Define("add_summary", None,
             "Whether this job writes summaries (None = by role).")
    p.Define("mesh_axes", None, "dict axis->size for the device mesh.")
    p.Define("num_infeed_hosts", 0, "0 = jax.process_count().")
    p.Define("infeed_host_index", -1, "-1 = jax.process_index().")
    return p

  def __init__(self, params):
    self.p = params.Copy()

  # ---- topology ------------------------------------------------------------

  @property
  def num_devices(self) -> int:
    return jax.device_count()

  @property
  def num_devices_per_host(self) -> int:
    return jax.local_device_count()

  @property
  def num_infeed_hosts(self) -> int:
    return self.p.num_infeed_hosts or jax.process_count()

  @property
  def infeed_host_index(self) -> int:
    idx = self.p.infeed_host_index
    return jax.process_index() if idx < 0 else idx

  @property
  def do_eval(self) -> bool:
    return self.p.do_eval

  @property
  def add_summary(self) -> bool:
    if self.p.add_summary is not None:
      return self.p.add_summary
    # by role: trainers/executors write summaries; decoders do their own
    return self.p.job in ("executor_tpu", "trainer", "trainer_client",
                          "controller", "evaler")

  def MakeMesh(self):
    from lingvo_tpu.parallel import mesh as mesh_lib
    axes = self.p.mesh_axes or {mesh_lib.DATA_AXIS: -1}
    return mesh_lib.MakeMesh(axes)

  def InputShardParams(self):
    """(shard_index, num_shards) for this host's input pipeline (the
    InfeedContextScope equivalent, ref cluster.py:47-59)."""
    return self.infeed_host_index, self.num_infeed_hosts


def _Stack():
  if not hasattr(_TLS, "stack"):
    _TLS.stack = []
  return _TLS.stack


def Current() -> Cluster:
  """The innermost active cluster (a default one outside any scope)."""
  stack = _Stack()
  if stack:
    return stack[-1]
  return Cluster(Cluster.Params())


@contextlib.contextmanager
def ClusterScope(cluster: Cluster):
  """ref cluster_factory.Cluster(params) context."""
  stack = _Stack()
  stack.append(cluster)
  try:
    yield cluster
  finally:
    stack.pop()


@contextlib.contextmanager
def SetEval(do_eval: bool = True):
  """ref cluster_factory.SetEval."""
  cur = Current()
  p = cur.p.Copy()
  p.do_eval = do_eval
  with ClusterScope(Cluster(p)) as c:
    yield c


def InitDistributed(coordinator_address: str | None = None,
                    num_processes: int | None = None,
                    process_id: int | None = None) -> None:
  """Multi-host control plane: jax.distributed over DCN (the gRPC
  tf.distribute.Server equivalent, ref trainer.py:256-278). No-op when
  single-process or already initialized."""
  if num_processes is None and coordinator_address is None:
    return
  try:
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id)
  except RuntimeError:
    pass  # already initialized
