"""MLPerf-compliance structured logging (ref `lingvo/core/ml_perf_log.py`:
`mlperf_print:80` emitting `:::MLLOG` lines; hooks in the executor at
run start/stop and per-block boundaries).

Format (MLPerf logging spec): one line per event —
  :::MLLOG {"namespace": ..., "time_ms": ..., "event_type": ...,
            "key": ..., "value": ..., "metadata": {...}}
"""

from __future__ import annotations

import json
import sys
import time

INTERVAL_START = "INTERVAL_START"
INTERVAL_END = "INTERVAL_END"
POINT_IN_TIME = "POINT_IN_TIME"

# standard keys (subset the executor emits)
RUN_START = "run_start"
RUN_STOP = "run_stop"
INIT_START = "init_start"
INIT_STOP = "init_stop"
BLOCK_START = "block_start"
BLOCK_STOP = "block_stop"
EVAL_ACCURACY = "eval_accuracy"
GLOBAL_BATCH_SIZE = "global_batch_size"
SUBMISSION_BENCHMARK = "submission_benchmark"

_EVENT_TYPES = {
    RUN_START: INTERVAL_START,
    RUN_STOP: INTERVAL_END,
    INIT_START: INTERVAL_START,
    INIT_STOP: INTERVAL_END,
    BLOCK_START: INTERVAL_START,
    BLOCK_STOP: INTERVAL_END,
}


class MlPerfLogger:
  """Writes :::MLLOG lines to a file (and optionally stderr)."""

  def __init__(self, path: str | None = None, benchmark: str = "",
               org: str = "", platform: str = "", echo: bool = False):
    # truncate: the compliance checker expects exactly ONE run per log
    self._file = open(path, "w") if path else None
    self._echo = echo
    self._benchmark = benchmark
    if benchmark:
      self.Print(SUBMISSION_BENCHMARK, benchmark)
    if org:
      self.Print("submission_org", org)
    if platform:
      self.Print("submission_platform", platform)

  def Print(self, key: str, value=None, metadata: dict | None = None,
            event_type: str | None = None):
    """Emits one MLLOG line (ref mlperf_print:80)."""
    record = {
        "namespace": "",
        "time_ms": int(time.time() * 1000),
        "event_type": event_type or _EVENT_TYPES.get(key, POINT_IN_TIME),
        "key": key,
        "value": value,
        "metadata": metadata or {},
    }
    line = ":::MLLOG " + json.dumps(record)
    if self._file is not None:
      self._file.write(line + "\n")
      self._file.flush()
    if self._echo:
      print(line, file=sys.stderr)

  def Close(self):
    if self._file is not None:
      self._file.close()
      self._file = None
