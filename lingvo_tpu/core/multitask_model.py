"""Cross-task variable sharing by regex (ref `lingvo/core/multitask_model.py`
RegExSharedVariableModel).

The reference shares TF variable *objects* between tasks whose variable
names match renaming rules, so any task's update is every task's update. In
the functional stack there are no variable objects — each task's train
state owns a theta pytree — so sharing is a state relation instead:

  * `SharedVariableRules(rules)` maps a task's variable path to a canonical
    key via `re.sub` (first matching rule wins; non-matching paths stay
    task-private). Two (task, path) leaves that map to the same canonical
    key are shared.
  * `UnifyStates` makes shared leaves identical at init (first task in
    sorted order donates its initialization).
  * `Propagate(states, from_task)` pushes the trainer's post-update values
    of shared leaves to all other tasks.

Only theta is shared; optimizer slots remain per-task (each task's
optimizer sees the shared weights as its own — same observable behavior as
the reference under one-task-at-a-time program scheduling, where the
training task's slots are the only ones advancing).

`runners/program.py` MultiTaskProgramSchedule applies these hooks when its
`variable_renaming_rules` param is set.
"""

from __future__ import annotations

import re
from typing import Sequence, Tuple

from lingvo_tpu.core.nested_map import NestedMap


class SharedVariableRules:
  """Compiled (pattern, replacement) rules over variable paths."""

  def __init__(self, rules: Sequence[Tuple[str, str]]):
    self._rules = [(re.compile(pat), repl) for pat, repl in rules]
    self._shared_paths = None  # computed once; the mapping is static

  def CanonicalKey(self, path: str) -> str | None:
    r"""Canonical share key for a theta path, or None if task-private.

    Replacement supports backrefs (`\1`): e.g. rule
    `(r"enc\.(.*)", r"shared_enc.\1")` (theta paths are dotted) shares every encoder variable across
    all tasks under one key per variable.
    """
    for pat, repl in self._rules:
      if pat.fullmatch(path):
        return pat.sub(repl, path)
    return None

  def SharedPaths(self, states: NestedMap) -> dict[str, list[tuple[str, str]]]:
    """canonical key -> [(task_name, theta_path), ...] with >= 1 entry.

    Cached after the first call: the path structure is fixed at state
    creation, and Propagate runs every train cycle.
    """
    if self._shared_paths is None:
      out: dict[str, list[tuple[str, str]]] = {}
      for task_name in sorted(states.keys()):
        theta = states.GetItem(task_name).theta
        for path, _ in theta.FlattenItems():
          key = self.CanonicalKey(path)
          if key is not None:
            out.setdefault(key, []).append((task_name, path))
      self._shared_paths = out
    return self._shared_paths

  def UnifyStates(self, states: NestedMap) -> NestedMap:
    """Makes shared leaves identical: first task in sorted order donates.

    Raises if two leaves sharing a key have different shapes — a wrong rule
    silently pairing unrelated variables is the dangerous failure mode.
    """
    for key, entries in self.SharedPaths(states).items():
      donor_task, donor_path = entries[0]
      donor = states.GetItem(donor_task).theta.GetItem(donor_path)
      for task_name, path in entries[1:]:
        leaf = states.GetItem(task_name).theta.GetItem(path)
        if getattr(leaf, "shape", None) != getattr(donor, "shape", None):
          raise ValueError(
              f"rule key {key!r} pairs {donor_task}/{donor_path} "
              f"{getattr(donor, 'shape', None)} with {task_name}/{path} "
              f"{getattr(leaf, 'shape', None)}")
        states.GetItem(task_name).theta.Set(path, donor)
    return states

  def Propagate(self, states: NestedMap, from_task: str) -> NestedMap:
    """Pushes `from_task`'s shared values to every tied leaf.

    Every entry under a key is overwritten — including `from_task`'s own
    other paths, which can diverge during its train cycle when one task
    maps several of its own variables to the same key (the reference's
    single-TF-variable sharing can't diverge, so neither may this).
    """
    for _, entries in self.SharedPaths(states).items():
      sources = [(t, p) for t, p in entries if t == from_task]
      if not sources:
        continue
      src_task, src_path = sources[0]
      value = states.GetItem(from_task).theta.GetItem(src_path)
      for task_name, path in entries:
        if (task_name, path) != (src_task, src_path):
          states.GetItem(task_name).theta.Set(path, value)
    return states
