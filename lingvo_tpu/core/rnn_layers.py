"""RNN layers: FRNN (functional scan), bidirectional, stacked.

Re-designs `lingvo/core/rnn_layers.py` (RNN:69, FRNN:365, bidirectional
variants). Batch-major inputs [b, t, d]; internally time-major for lax.scan.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import recurrent
from lingvo_tpu.core import rnn_cell
from lingvo_tpu.core.nested_map import NestedMap


class FRNN(base_layer.BaseLayer):
  """Functional RNN over a cell (ref FRNN:365)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("cell", rnn_cell.LSTMCellSimple.Params(), "The RNN cell.")
    p.Define("reverse", False, "Process the sequence right-to-left.")
    p.Define("remat", False, "Rematerialize steps in BPTT.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self.CreateChild("cell", self.p.cell)

  def FProp(self, theta, inputs, paddings=None, state0=None):
    """inputs [b, t, d] -> (outputs [b, t, h], final_state)."""
    p = self.p
    b, t = inputs.shape[0], inputs.shape[1]
    if paddings is None:
      paddings = jnp.zeros((b, t), jnp.float32)
    if state0 is None:
      state0 = self.cell.InitState(b)
    # time-parallel input transform (SRU's big matmul runs here, not in scan)
    inputs = self.cell.PreProcessInputs(theta.cell, inputs)
    xs = NestedMap(
        x=jnp.swapaxes(inputs, 0, 1),          # [t, b, d]
        padding=jnp.swapaxes(paddings, 0, 1))  # [t, b]
    if p.reverse:
      xs = xs.Transform(lambda v: jnp.flip(v, axis=0))

    def _Cell(theta_cell, state, inputs_t):
      return self.cell.FProp(theta_cell, state, inputs_t.x, inputs_t.padding,
                             preprocessed=True)

    all_states, final_state = recurrent.Recurrent(
        theta.cell, state0, xs, _Cell, remat=p.remat)
    out = jax.vmap(self.cell.GetOutput)(all_states)  # [t, b, h]
    if p.reverse:
      out = jnp.flip(out, axis=0)
    return jnp.swapaxes(out, 0, 1), final_state


class BidirectionalFRNN(base_layer.BaseLayer):
  """Concatenated forward + backward FRNN (ref BidirectionalFRNN)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("fwd", rnn_cell.LSTMCellSimple.Params(), "Forward cell.")
    p.Define("bak", rnn_cell.LSTMCellSimple.Params(), "Backward cell.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self.CreateChild("fwd_rnn", FRNN.Params().Set(cell=self.p.fwd))
    self.CreateChild("bak_rnn", FRNN.Params().Set(cell=self.p.bak,
                                                  reverse=True))

  def FProp(self, theta, inputs, paddings=None):
    out_f, _ = self.fwd_rnn.FProp(theta.fwd_rnn, inputs, paddings)
    out_b, _ = self.bak_rnn.FProp(theta.bak_rnn, inputs, paddings)
    return jnp.concatenate([out_f, out_b], axis=-1)


class StackedFRNNLayerByLayer(base_layer.BaseLayer):
  """N stacked FRNNs with optional skip connections (ref StackedFRNN)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("cell_tpl", rnn_cell.LSTMCellSimple.Params(), "Cell template.")
    p.Define("num_layers", 1, "Depth.")
    p.Define("num_input_nodes", 0, "Input dim.")
    p.Define("num_output_nodes", 0, "Hidden/output dim.")
    p.Define("skip_start", 1,
             "Residual connections from this layer index (-1 = none).")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    cells = []
    for i in range(p.num_layers):
      cp = p.cell_tpl.Copy()
      cp.num_input_nodes = p.num_input_nodes if i == 0 else p.num_output_nodes
      cp.num_output_nodes = p.num_output_nodes
      cells.append(FRNN.Params().Set(cell=cp))
    self.CreateChildren("rnn", cells)

  def FProp(self, theta, inputs, paddings=None):
    p = self.p
    x = inputs
    for i, layer in enumerate(self.rnn):
      out, _ = layer.FProp(theta.rnn[i], x, paddings)
      if p.skip_start >= 0 and i >= p.skip_start and out.shape == x.shape:
        out = out + x
      x = out
    return x


class FRNNWithAttention(base_layer.BaseLayer):
  """Functional RNN whose cell consumes per-step attention context (ref
  `rnn_layers.py:756` FRNNWithAttention): the seq2seq decoder recurrence —
  cell input is [x_t, ctx_{t-1}], the cell output queries the attention.

  Uses the core/seq_attention per-step API, so any of that family
  (additive, location-sensitive, monotonic, ...) plugs in.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("cell", rnn_cell.LSTMCellSimple.Params(), "The RNN cell.")
    p.Define("attention", None,
             "seq_attention Params (source_dim/query_dim set by caller).")
    p.Define("output_prev_atten_ctx", False,
             "Emit ctx_{t-1} (pre-update) instead of ctx_t per step.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.attention is not None
    self.CreateChild("cell", p.cell)
    self.CreateChild("atten", p.attention)

  def FProp(self, theta, source_vecs, source_paddings, inputs,
            paddings=None, state0=None):
    """source_vecs [b, s, ds]; inputs [b, t, d] ->
    (outputs [b, t, h], contexts [b, t, ds], final_state)."""
    p = self.p
    b, t = inputs.shape[0], inputs.shape[1]
    src_len = source_vecs.shape[1]
    packed = self.atten.PackSource(
        self.ChildTheta(theta, "atten"), source_vecs, source_paddings)
    if paddings is None:
      paddings = jnp.zeros((b, t), jnp.float32)
    cell_state = state0 if state0 is not None else self.cell.InitState(b)
    atten_state = self.atten.ZeroAttentionState(b, src_len)
    ctx0 = jnp.zeros((b, source_vecs.shape[-1]), source_vecs.dtype)

    def _Step(carry, per_t):
      cell_state, atten_state, ctx = carry
      x_t, pad_t = per_t
      cell_in = jnp.concatenate([x_t, ctx.astype(x_t.dtype)], axis=-1)
      new_cell = self.cell.FProp(theta.cell, cell_state, cell_in,
                                 padding=pad_t)
      query = self.cell.GetOutput(new_cell)
      new_ctx, probs, new_atten = self.atten.ComputeContextVector(
          self.ChildTheta(theta, "atten"), packed, query, atten_state)
      # padded steps hold the attention state and carried context too —
      # stateful aligners (location-sensitive, monotonic) must not advance
      # over padding frames
      def _Hold(new, old):
        pad = pad_t.reshape((-1,) + (1,) * (new.ndim - 1)).astype(new.dtype)
        return new * (1 - pad) + old * pad

      new_atten = jax.tree_util.tree_map(_Hold, new_atten, atten_state)
      new_ctx = _Hold(new_ctx, ctx)
      emit_ctx = ctx if p.output_prev_atten_ctx else new_ctx
      return (new_cell, new_atten, new_ctx), (query, emit_ctx, probs)

    (final_cell, _, _), (outs, ctxs, probs) = jax.lax.scan(
        _Step, (cell_state, atten_state, ctx0),
        (inputs.swapaxes(0, 1), paddings.swapaxes(0, 1)))
    return (outs.swapaxes(0, 1), ctxs.swapaxes(0, 1), final_cell)
