"""Attention variants: Transformer-XL relative attention, Performer FAVOR+,
routing (clustered sparse) attention, funnel pooling.

Re-designs the remaining attention breadth of
`lingvo/core/batch_major_attention.py` — XL-style relative attention
(`:2233`), `MultiHeadedFavorAttention:2125` + `favor_attention.py`,
`RoutingAttention:4458` (k-means clustered sparse attention), funnel
down/up-sampling (`:5943, :8162, :8423`) — on the batch-major JAX stack.
All variants reuse MultiHeadedAttention's projections, so they drop into
TransformerLayer via `tr_atten_tpl.atten_tpl`.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from lingvo_tpu.core import attention as attention_lib
from lingvo_tpu.core import base_layer
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.core.py_utils import WeightInit, WeightParams

_NEG_INF = attention_lib._NEG_INF


def _SinusoidRelEmbedding(dist, d: int):
  """[len(dist), d] sinusoid embedding of relative distances."""
  pos = jnp.asarray(dist, jnp.float32)
  inv = 1.0 / (10000.0 ** (jnp.arange(0, d, 2, jnp.float32) / d))
  ang = pos[:, None] * inv[None, :]
  emb = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)
  return emb[:, :d]


class _XLBiasVariables:
  """Shared w_rel/u_bias/v_bias creation for XL-style attention layers."""

  def _CreateXLBiasVariables(self):
    p = self.p
    n, h = p.num_heads, self._dim_per_head
    self.CreateVariable(
        "w_rel", WeightParams((p.input_dim, n, h), p.params_init, p.dtype))
    self.CreateVariable("u_bias", WeightParams((n, h),
                                               WeightInit.Constant(0.0),
                                               p.dtype))
    self.CreateVariable("v_bias", WeightParams((n, h),
                                               WeightInit.Constant(0.0),
                                               p.dtype))


class TransformerXLAttention(attention_lib.MultiHeadedAttention,
                             _XLBiasVariables):
  """Transformer-XL relative position attention (ref
  `batch_major_attention.py:2233`):

    logits[i,j] = (q_i + u) . k_j + (q_i + v) . r_{i-j}

  with sinusoidal relative embeddings r projected per head and learned
  content/position biases u/v.
  """

  def __init__(self, params):
    super().__init__(params)
    self._CreateXLBiasVariables()

  def _SinusoidRel(self, t: int):
    """[2t-1, D] sinusoid embedding of relative distance t-1 .. -(t-1)."""
    return _SinusoidRelEmbedding(jnp.arange(t - 1, -t, -1), self.p.input_dim)

  def FProp(self, theta, query_vec, key_vec=None, value_vec=None,
            paddings=None, atten_mask=None, segment_ids=None, causal=False):
    p = self.p
    th = self.CastTheta(theta)
    assert key_vec is None and value_vec is None, "XL attention is self-attn"
    b, t, _ = query_vec.shape
    q = self._HeadsProj(theta, "query", query_vec)        # [B,T,N,H]
    k = self._HeadsProj(theta, "key", query_vec)
    v = self._HeadsProj(theta, "value", query_vec)
    scale = 1.0 / math.sqrt(self._dim_per_head)

    rel = self._SinusoidRel(t).astype(q.dtype)            # [2T-1, D]
    r = jnp.einsum("rd,dnh->rnh", rel, th.w_rel)          # [2T-1, N, H]

    ac = jnp.einsum("btnh,bsnh->bnts", q + th.u_bias, k)  # content term
    bd_full = jnp.einsum("btnh,rnh->bntr", q + th.v_bias, r)
    # rel index: r[0] is distance t-1 (far past); logits need r_{i-j}
    idx = (jnp.arange(t)[:, None] - jnp.arange(t)[None, :])  # i-j
    idx = (t - 1) - idx                                   # -> index into r
    bd = jnp.take_along_axis(
        bd_full, jnp.broadcast_to(idx[None, None], (b, p.num_heads, t, t)),
        axis=-1)
    logits = (ac + bd) * scale
    logits = logits.astype(jnp.float32)
    mask = atten_mask
    if causal:
      cm = attention_lib.CausalMask(t)
      mask = cm if mask is None else mask + cm
    if paddings is not None:
      pm = attention_lib.PaddingsToMask(paddings)
      mask = pm if mask is None else mask + pm
    if segment_ids is not None:
      sm = attention_lib.SegmentMask(segment_ids, segment_ids)
      mask = sm if mask is None else mask + sm
    if mask is not None:
      logits = logits + mask.astype(jnp.float32)
    logits = jnp.maximum(logits, _NEG_INF)
    probs = self._QProbs(theta, jax.nn.softmax(logits, axis=-1).astype(
        q.dtype))
    if p.atten_dropout_prob > 0:
      probs = self.atten_dropout.FProp(
          self.ChildTheta(theta, "atten_dropout"), probs,
          keep_prob=1.0 - p.atten_dropout_prob)
    ctx = jnp.einsum("bnts,bsnh->btnh", probs, v)
    return self._PostProj(theta, ctx), probs


class LocalSelfAttentionXL(attention_lib.LocalSelfAttention,
                           _XLBiasVariables):
  """Sliding-window attention with Transformer-XL relative position bias
  (ref `batch_major_attention.py:3754` LocalSelfAttentionXL).

  Adds `(u . k) + (q + v) . r_{i-j}` to the blocked windowed logits; the
  relative embeddings only span the 3W window, so cost stays O(T * W).
  """

  def __init__(self, params):
    super().__init__(params)
    self._CreateXLBiasVariables()

  def _AddRelPositionBias(self, theta, qb, kb, rel, logits):
    p = self.p
    th = self.CastTheta(theta)
    w = p.block_size
    scale = 1.0 / math.sqrt(self._dim_per_head)
    # sinusoid embeddings for every distinct rel distance in the window:
    # rel ranges over [-(2w-1), ..., 2w-1] -> index r_idx = rel + (2w - 1)
    sin_emb = _SinusoidRelEmbedding(
        jnp.arange(-(2 * w - 1), 2 * w), p.input_dim)
    r = jnp.einsum("rd,dnh->rnh", sin_emb.astype(qb.dtype), th.w_rel)

    # content bias: scale * (u . k)  [B, L, N, 1, 3W]
    content = scale * jnp.einsum("nh,blknh->blnk", th.u_bias, kb)
    # position terms: qb is already scaled by the base class, so
    # q_scaled . r + scale * (v . r)
    pos_q = jnp.einsum("blqnh,rnh->blnqr", qb, r)
    pos_v = scale * jnp.einsum("nh,rnh->nr", th.v_bias, r)
    r_idx = rel + (2 * w - 1)                               # [W, 3W]
    pos = pos_q + pos_v[None, None, :, None, :]
    # gather the r index per (query row, key col)
    pos = jnp.take_along_axis(
        pos,
        jnp.broadcast_to(r_idx[None, None, None],
                         pos.shape[:3] + r_idx.shape),
        axis=-1)
    return logits + (content[:, :, :, None, :] + pos).astype(logits.dtype)


class PerformerAttention(attention_lib.MultiHeadedAttention):
  """FAVOR+ linear attention (ref `MultiHeadedFavorAttention:2125`,
  `favor_attention.py`): positive random-feature softmax kernel; O(T) memory
  and time. Causal mode uses the prefix-sum formulation."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("num_random_features", 128, "Random feature dim M.")
    p.Define("favor_seed", 1234, "Fixed seed for the projection matrix.")
    return p

  def _Features(self, x, proj, per_token_stab: bool):
    """Positive softmax-kernel features: exp(w.x - |x|^2/2) / sqrt(M).

    Stabilizer subtlety (FAVOR+): a per-token max cancels only in the
    query position of the num/den ratio; KEY features must use a stabilizer
    CONSTANT across tokens (here: max over tokens+features per head) or
    large-norm keys get systematically down-weighted.
    """
    m = self.p.num_random_features
    # x: [B,T,N,H]; proj: [H, M]
    xw = jnp.einsum("btnh,hm->btnm", x, proj)
    sq = 0.5 * jnp.sum(x * x, axis=-1, keepdims=True)
    if per_token_stab:
      stab = jnp.max(xw - sq, axis=-1, keepdims=True)      # [B,T,N,1]
    else:
      stab = jnp.max(xw - sq, axis=(1, 3), keepdims=True)  # [B,1,N,1]
    stab = jax.lax.stop_gradient(stab)
    return jnp.exp(xw - sq - stab) / math.sqrt(m)

  def FProp(self, theta, query_vec, key_vec=None, value_vec=None,
            paddings=None, atten_mask=None, segment_ids=None, causal=False):
    p = self.p
    assert atten_mask is None and segment_ids is None, (
        "Performer supports paddings/causal only (kernelized logits cannot "
        "take arbitrary additive masks)")
    assert p.atten_dropout_prob == 0.0, (
        "Performer never materializes attention probs; atten_dropout_prob "
        "cannot apply — configure residual dropout instead")
    key_vec = query_vec if key_vec is None else key_vec
    value_vec = key_vec if value_vec is None else value_vec
    q = self._HeadsProj(theta, "query", query_vec)
    k = self._HeadsProj(theta, "key", key_vec)
    v = self._HeadsProj(theta, "value", value_vec)
    h = self._dim_per_head
    # scale queries/keys by h^-1/4 each (softmax kernel of q.k/sqrt(h))
    q = q * (h ** -0.25)
    k = k * (h ** -0.25)
    proj = jax.random.normal(
        jax.random.PRNGKey(p.favor_seed), (h, p.num_random_features),
        jnp.float32).astype(q.dtype)
    qf = self._Features(q, proj, per_token_stab=True)     # [B,T,N,M]
    kf = self._Features(k, proj, per_token_stab=False)
    if paddings is not None:
      kf = kf * (1.0 - paddings)[:, :, None, None].astype(kf.dtype)
    if causal:
      # prefix sums over time (ref favor causal numerator/denominator)
      kv = jnp.einsum("bsnm,bsnh->bsnmh", kf, v)
      kv = jnp.cumsum(kv, axis=1)
      z = jnp.cumsum(kf, axis=1)
      num = jnp.einsum("btnm,btnmh->btnh", qf, kv)
      den = jnp.einsum("btnm,btnm->btn", qf, z)
    else:
      kv = jnp.einsum("bsnm,bsnh->bnmh", kf, v)
      z = jnp.sum(kf, axis=1)                             # [B,N,M]
      num = jnp.einsum("btnm,bnmh->btnh", qf, kv)
      den = jnp.einsum("btnm,bnm->btn", qf, z)
    ctx = num / jnp.maximum(den[..., None], 1e-6)
    out = self._PostProj(theta, ctx)
    if paddings is not None:
      out = py_utils.ApplyPadding(paddings, out)
    return out, None  # probs never materialized (that's the point)


class RoutingAttention(attention_lib.MultiHeadedAttention):
  """Clustered sparse attention (ref `RoutingAttention:4458` +
  `attention_util.KMeansClusteringForAtten:656`): queries and keys are
  routed to the nearest of C learned centroids; each query attends only to
  the W keys of its own cluster (capacity-truncated, MoE-style one-hot
  dispatch — all static shapes).
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("num_clusters", 4, "C.")
    p.Define("attention_window", 0, "Keys per cluster W (0 = 2*T/C).")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateVariable(
        "centroids",
        WeightParams((p.num_heads, p.num_clusters, self._dim_per_head),
                     p.params_init, p.dtype))

  def _Assign(self, x, centroids):
    """Nearest-centroid assignment on the unit sphere (ref k-means attn).

    x: [B,T,N,H] -> one-hot [B,T,N,C]."""
    xn = x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), 1e-6)
    cn = centroids / jnp.maximum(
        jnp.linalg.norm(centroids, axis=-1, keepdims=True), 1e-6)
    sim = jnp.einsum("btnh,nch->btnc", xn, cn)
    return jax.nn.one_hot(jnp.argmax(sim, -1), self.p.num_clusters,
                          dtype=x.dtype)

  def FProp(self, theta, query_vec, key_vec=None, value_vec=None,
            paddings=None, atten_mask=None, segment_ids=None, causal=False):
    p = self.p
    th = self.CastTheta(theta)
    assert key_vec is None and value_vec is None, "routing is self-attn"
    assert atten_mask is None and segment_ids is None, (
        "routing attention supports paddings/causal only")
    b, t, _ = query_vec.shape
    c = p.num_clusters
    w = p.attention_window or max(2 * t // c, 1)
    w = min(w, t)
    q = self._HeadsProj(theta, "query", query_vec)
    k = self._HeadsProj(theta, "key", query_vec)
    v = self._HeadsProj(theta, "value", query_vec)
    q = self._ScaleQuery(theta, q)

    k_assign = self._Assign(k, th.centroids)              # [B,T,N,C]
    if paddings is not None:
      k_assign = k_assign * (1.0 - paddings)[:, :, None, None]
    # capacity: first W keys per cluster (cumsum position, MoE-style)
    pos = jnp.cumsum(k_assign, axis=1) - k_assign
    k_keep = k_assign * (pos < w)
    slot = jnp.sum(pos * k_keep, axis=-1)                 # [B,T,N]
    slot_oh = jax.nn.one_hot(slot.astype(jnp.int32), w, dtype=q.dtype)
    # dispatch keys/values into [B,N,C,W,H]
    disp = (k_keep[..., None] * slot_oh[..., None, :])    # [B,T,N,C,W]
    k_c = jnp.einsum("btncw,btnh->bncwh", disp, k)
    v_c = jnp.einsum("btncw,btnh->bncwh", disp, v)
    k_valid = jnp.einsum("btncw->bncw", disp)             # 1 if slot filled

    q_assign = self._Assign(q, th.centroids)              # [B,T,N,C]
    # per-query logits against its cluster's W keys
    logits = jnp.einsum("btnh,bncwh->btncw", q, k_c)
    logits = jnp.where(k_valid[:, None] > 0, logits, _NEG_INF)
    if causal:
      # key global positions per slot: [B,N,C,W]
      key_pos = jnp.einsum("btncw,t->bncw", disp,
                           jnp.arange(t, dtype=q.dtype))
      q_pos = jnp.arange(t, dtype=q.dtype)[None, :, None, None, None]
      logits = jnp.where(key_pos[:, None] <= q_pos, logits, _NEG_INF)
    logits = logits * q_assign[..., None]  # zero out non-own clusters
    logits = jnp.where(q_assign[..., None] > 0, logits, _NEG_INF)
    logits = jnp.maximum(logits.astype(jnp.float32), _NEG_INF)
    flat = logits.reshape(b, t, p.num_heads, c * w)
    probs = self._QProbs(theta, jax.nn.softmax(flat, axis=-1).astype(q.dtype))
    # a query whose cluster has no visible key has a fully-masked row:
    # softmax would go uniform and leak — zero masked slots outright
    probs = probs * (flat > 0.5 * _NEG_INF).astype(probs.dtype)
    if p.atten_dropout_prob > 0:
      probs = self.atten_dropout.FProp(
          self.ChildTheta(theta, "atten_dropout"), probs,
          keep_prob=1.0 - p.atten_dropout_prob)
    probs = probs.reshape(b, t, p.num_heads, c, w)
    ctx = jnp.einsum("btncw,bncwh->btnh", probs, v_c)
    out = self._PostProj(theta, ctx)
    if paddings is not None:
      out = py_utils.ApplyPadding(paddings, out)
    return out, None


class FunnelPoolingLayer(base_layer.BaseLayer):
  """Strided mean-pooling over time (ref `FunnelPoolingLayer:8162`):
  halves (or /stride) the sequence for the deeper funnel blocks."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("stride", 2, "Time pooling stride.")
    return p

  def FProp(self, theta, inputs, paddings=None):
    """[B, T, D] -> ([B, ceil(T/s), D], pooled paddings)."""
    p = self.p
    s = p.stride
    b, t, d = inputs.shape
    pad_t = (-t) % s
    x = jnp.pad(inputs, ((0, 0), (0, pad_t), (0, 0)))
    if paddings is None:
      pads = jnp.zeros((b, t), jnp.float32)
    else:
      pads = paddings
    pads = jnp.pad(pads, ((0, 0), (0, pad_t)), constant_values=1.0)
    nonpad = (1.0 - pads)[..., None]
    x = (x * nonpad.astype(x.dtype)).reshape(b, -1, s, d).sum(axis=2)
    cnt = nonpad.reshape(b, -1, s, 1).sum(axis=2)
    x = x / jnp.maximum(cnt, 1.0).astype(x.dtype)
    # a pooled frame is padding only when ALL its inputs were padding
    new_pads = (cnt[..., 0] == 0).astype(jnp.float32)
    return x, new_pads


class FunnelUpsampleLayer(base_layer.BaseLayer):
  """Nearest-neighbor upsampling back to the original rate (ref `:8423`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("stride", 2, "Repeat factor (inverse of the pooling stride).")
    return p

  def FProp(self, theta, inputs, target_len: int | None = None):
    out = jnp.repeat(inputs, self.p.stride, axis=1)
    if target_len is not None:
      out = out[:, :target_len]
    return out
