"""Base classes for experiment definitions (experiments-as-code).

Semantics follow `lingvo/core/base_model_params.py`: an experiment is a class
with dataset methods (`Train()/Dev()/Test()`), a `Task()` returning the task
Params, and `Model()` wrapping it into a trainable model Params tree.
"""

from __future__ import annotations

import inspect

from lingvo_tpu.core import hyperparams


class DatasetError(Exception):
  pass


class _BaseModelParams:
  """Shared dataset-reflection machinery."""

  _registry_key: str = ""

  def GetAllDatasetParams(self) -> dict:
    out = {}
    for name in self.GetDatasetNames():
      out[name] = self.GetDatasetParams(name)
    return out

  def GetDatasetNames(self) -> list[str]:
    """Dataset methods actually defined by the experiment (not base stubs)."""
    base_owners = ("_BaseModelParams", "SingleTaskModelParams",
                   "MultiTaskModelParams")
    names = []
    for name, member in inspect.getmembers(type(self), inspect.isfunction):
      if name.startswith("_") or name in (
          "Task", "Model", "ProgramSchedule", "GetDatasetParams",
          "GetAllDatasetParams", "GetDatasetNames"):
        continue
      if member.__qualname__.split(".")[0] in base_owners:
        continue  # inherited raising stub, not a real dataset
      sig = inspect.signature(member)
      if len(sig.parameters) == 1:  # only self
        names.append(name)
    return sorted(set(names))

  def GetDatasetParams(self, dataset: str) -> hyperparams.Params:
    method = getattr(self, dataset, None)
    if method is None or dataset.startswith("_"):
      raise DatasetError(
          f"Dataset {dataset!r} not found on {type(self).__name__}; "
          f"available: {self.GetDatasetNames()}")
    return method()

  def ProgramSchedule(self):
    """Optional override: returns a ProgramSchedule params tree."""
    return None


class SingleTaskModelParams(_BaseModelParams):
  """One-task experiment: defines Task() and dataset methods."""

  def Train(self) -> hyperparams.Params:
    raise DatasetError("Train() dataset not defined")

  def Dev(self) -> hyperparams.Params:
    raise DatasetError("Dev() dataset not defined")

  def Test(self) -> hyperparams.Params:
    raise DatasetError("Test() dataset not defined")

  def Task(self) -> hyperparams.InstantiableParams:
    raise NotImplementedError

  def Model(self) -> hyperparams.InstantiableParams:
    from lingvo_tpu.core import base_model
    p = base_model.SingleTaskModel.Params()
    p.task = self.Task()
    p.name = p.task.name or type(self).__name__
    return p


class MultiTaskModelParams(_BaseModelParams):
  """Multi-task experiment: defines per-task params."""

  def Task(self) -> hyperparams.Params:
    raise NotImplementedError

  def Model(self) -> hyperparams.InstantiableParams:
    from lingvo_tpu.core import base_model
    p = base_model.MultiTaskModel.Params()
    p.task_params = self.Task()
    p.name = type(self).__name__
    return p
