"""Summaries: TensorBoard event files, attention images, step-rate tracking.

Re-designs `lingvo/core/summary_utils.py` (job-gated scalar/histogram/image
summaries, `AddAttentionSummary:157`, `StepRateTracker:393`) for the JAX
stack: a thin writer over tensorboardX event files (always paired with the
machine-readable JSONL the programs already emit), image summaries rendered
from attention probability tensors without a plotting dependency, and a
steps/sec + examples/sec tracker.

Summary writing is gated by the cluster role (ref `cluster.add_summary`,
`cluster.py:144-146`): follower eval/decode jobs write to their own
subdirectories, so one TensorBoard run shows train + eval curves side by
side.
"""

from __future__ import annotations

import time

import numpy as np


class SummaryWriter:
  """Event-file writer; falls back to no-op when tensorboardX is missing.

  Writes are serialized with a lock: under deferred telemetry
  (runners/infeed.py) the train program writes from a background worker
  while Flush/Close may come from the main thread at program boundaries.
  """

  def __init__(self, logdir: str, enabled: bool = True):
    import threading
    self._writer = None
    self._enabled = enabled
    self._logdir = logdir
    self._lock = threading.Lock()
    if not enabled:
      return
    try:
      from tensorboardX import SummaryWriter as TbWriter
      self._writer = TbWriter(logdir=logdir)
    except Exception:  # pragma: no cover - tensorboardX present in CI
      self._writer = None

  @property
  def enabled(self) -> bool:
    return self._writer is not None

  def Scalar(self, tag: str, value, step: int):
    with self._lock:
      if self._writer is not None:
        self._writer.add_scalar(tag, float(value), step)

  def Scalars(self, values: dict, step: int, prefix: str = ""):
    for k, v in values.items():
      if isinstance(v, (int, float, np.floating, np.integer)):
        self.Scalar(f"{prefix}{k}" if prefix else k, v, step)

  def FromRegistry(self, registry, step: int, prefix: str = ""):
    """Writes an observe.MetricsRegistry snapshot as scalar summaries.

    The bridge from the metrics registry (observe/metrics.py) to event
    files: numeric counters/gauges/section values go through Scalars'
    numeric filter unchanged; histogram snapshots (dict-valued) flatten
    to `<name>/count|sum|mean` plus bucket-interpolated `/p50|/p99`
    quantiles, so TensorBoard sees tail latency without the trace
    tooling. Returns the snapshot it wrote from."""
    from lingvo_tpu.observe import metrics as observe_metrics
    snap = registry.Snapshot()
    flat = {}
    for k, v in snap.items():
      if isinstance(v, dict) and "counts" in v and "bounds" in v:
        for field in ("count", "sum", "mean"):
          flat[f"{k}/{field}"] = v[field]
        quantiles = observe_metrics.HistogramQuantiles(v, qs=(0.5, 0.99))
        flat[f"{k}/p50"] = quantiles[0.5]
        flat[f"{k}/p99"] = quantiles[0.99]
      else:
        flat[k] = v
    self.Scalars(flat, step, prefix=prefix)
    return snap

  def Histogram(self, tag: str, values, step: int):
    with self._lock:
      if self._writer is not None:
        self._writer.add_histogram(tag, np.asarray(values), step)

  def Image(self, tag: str, image_hwc, step: int):
    """image_hwc: [H, W, C] float in [0, 1] or uint8."""
    with self._lock:
      if self._writer is not None:
        img = np.asarray(image_hwc)
        if img.dtype != np.uint8:
          img = (np.clip(img, 0.0, 1.0) * 255).astype(np.uint8)
        self._writer.add_image(tag, img, step, dataformats="HWC")

  def Text(self, tag: str, text: str, step: int):
    with self._lock:
      if self._writer is not None:
        self._writer.add_text(tag, text, step)

  def Flush(self):
    with self._lock:
      if self._writer is not None:
        self._writer.flush()

  def Close(self):
    with self._lock:
      if self._writer is not None:
        self._writer.close()
        self._writer = None


def AttentionProbsToImage(probs) -> np.ndarray:
  """[T_query, T_source] probs -> [T_query, T_source, 3] heatmap in [0,1].

  Dependency-free rendering (ref `AddAttentionSummary:157` / `plot.py`, which
  route through matplotlib): intensity-normalized viridis-ish ramp.
  """
  p = np.asarray(probs, np.float32)
  p = p / max(float(p.max()), 1e-8)
  # simple two-anchor color ramp: dark blue -> yellow
  lo = np.array([0.07, 0.0, 0.33], np.float32)
  hi = np.array([0.99, 0.91, 0.14], np.float32)
  return lo[None, None] + p[..., None] * (hi - lo)[None, None]


def AddAttentionSummary(writer: SummaryWriter, name: str, probs, step: int,
                        max_entries: int = 4):
  """Writes attention-prob images (ref summary_utils.AddAttentionSummary:157).

  probs: [B, T, S] or [B, N, T, S] (first head is rendered).
  """
  if not writer.enabled:
    return
  p = np.asarray(probs)
  if p.ndim == 4:
    p = p[:, 0]
  for i in range(min(p.shape[0], max_entries)):
    writer.Image(f"{name}/{i}", AttentionProbsToImage(p[i]), step)


class StepRateTracker:
  """steps/sec + examples/sec with decaying window (ref StepRateTracker:393).

  registry: optional observe.MetricsRegistry — each Update publishes the
  smoothed rates as `train/<name>_steps_per_second` /
  `_examples_per_second` gauges, so the cross-Run rate is readable from
  the registry between summary writes."""

  def __init__(self, registry=None, name: str = "train"):
    self._start = None
    self._last_step = 0
    self._rate = 0.0
    self._example_rate = 0.0
    self._g_steps = self._g_examples = None
    if registry is not None:
      self._g_steps = registry.Gauge(f"train/{name}_steps_per_second")
      self._g_examples = registry.Gauge(f"train/{name}_examples_per_second")

  def Update(self, step: int, examples_per_step: float = 0.0):
    now = time.time()
    if self._start is None:
      self._start = now
      self._last_step = step
      return self._rate
    dt = max(now - self._start, 1e-6)
    steps = step - self._last_step
    inst = steps / dt
    # exponential decay toward the instantaneous rate (windowed smoothing)
    blend = 0.5 if self._rate else 1.0
    self._rate = blend * inst + (1 - blend) * self._rate
    self._example_rate = self._rate * examples_per_step
    self._start = now
    self._last_step = step
    if self._g_steps is not None:
      self._g_steps.Set(self._rate)
      self._g_examples.Set(self._example_rate)
    return self._rate

  @property
  def steps_per_second(self) -> float:
    return self._rate

  @property
  def examples_per_second(self) -> float:
    return self._example_rate
