"""Composable data sources over the native record pipeline.

Re-designs `lingvo/core/datasource.py` (SimpleDataSource:85,
CrossBatchMixingDataSource:194, CurriculumDataSource:253) + the length-bucket
batching of `ops/record_batcher.cc`: sources yield raw records from the C++
yielder; a processor maps record -> NestedMap of numpy arrays; the batcher
groups by length bucket with per-bucket batch sizes and flush semantics.
"""

from __future__ import annotations

import bisect
from typing import Callable, Sequence

import numpy as np

from lingvo_tpu.core import hyperparams
from lingvo_tpu.core.nested_map import NestedMap


class DataSource:

  @classmethod
  def Params(cls):
    p = hyperparams.InstantiableParams(cls)
    p.Define("name", "", "Name.")
    return p

  def __init__(self, params):
    self.p = params.Copy()

  def __iter__(self):
    raise NotImplementedError


class SimpleDataSource(DataSource):
  """Records from file pattern(s) with optional weighted mixing
  (ref SimpleDataSource:85)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("file_pattern", "", "Pattern 'type:glob' or list of patterns.")
    p.Define("weights", None, "Mix weights when file_pattern is a list.")
    p.Define("shuffle_buffer_size", 10000, "Shuffle ring size.")
    p.Define("num_threads", 2, "Reader threads per pattern.")
    p.Define("max_epochs", 0, "0 = repeat forever.")
    p.Define("shuffle", True, "Shuffle.")
    p.Define("seed", 301, "Seed.")
    p.Define("shard_index", 0, "This host.")
    p.Define("num_shards", 1, "Total infeed hosts.")
    return p

  def __iter__(self):
    from lingvo_tpu.ops import native
    p = self.p
    patterns = (p.file_pattern if isinstance(p.file_pattern, (list, tuple))
                else [p.file_pattern])
    if len(patterns) == 1:
      yielder = native.RecordYielder(
          patterns[0], seed=p.seed,
          shuffle_buffer_size=p.shuffle_buffer_size,
          num_threads=p.num_threads, max_epochs=p.max_epochs,
          shuffle=p.shuffle, shard_index=p.shard_index,
          num_shards=p.num_shards)
      try:
        yield from yielder
      finally:
        yielder.Close()
      return
    # weighted mix: python-side sampling over child yielders (keeps
    # ownership simple; the C++ mix is available via ops.native for the
    # single-process hot path)
    weights = p.weights or [1.0] * len(patterns)
    kids = [
        native.RecordYielder(
            pat, seed=p.seed + 17 * i,
            shuffle_buffer_size=p.shuffle_buffer_size,
            num_threads=p.num_threads, max_epochs=p.max_epochs,
            shuffle=p.shuffle, shard_index=p.shard_index,
            num_shards=p.num_shards) for i, pat in enumerate(patterns)
    ]
    rng = np.random.RandomState(p.seed)
    probs = np.asarray(weights, np.float64)
    probs = probs / probs.sum()
    try:
      alive = [True] * len(kids)
      while any(alive):
        k = rng.choice(len(kids), p=probs)
        if not alive[k]:
          continue
        rec = kids[k].Next()
        if rec is None:
          alive[k] = False
          continue
        yield rec
    finally:
      for kid in kids:
        kid.Close()


class CurriculumDataSource(DataSource):
  """Switches sources at step boundaries (ref CurriculumDataSource:253).

  The executor advances `SetStep`; iteration reflects the current stage.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("sub", [], "List of DataSource params, one per stage.")
    p.Define("boundaries", [], "Global-step boundaries between stages.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self._step = 0
    self._sources = [sp.Instantiate() for sp in self.p.sub]
    self._iters: list = [None] * len(self._sources)

  def SetStep(self, step: int):
    self._step = step

  def _StageIter(self, stage: int):
    # One live iterator per stage, created lazily and reused across records
    # (a fresh iterator per record would re-open readers and repeat data).
    if self._iters[stage] is None:
      self._iters[stage] = iter(self._sources[stage])
    return self._iters[stage]

  def __iter__(self):
    while True:
      stage = bisect.bisect_right(list(self.p.boundaries), self._step)
      rec = next(self._StageIter(stage), None)
      if rec is None:
        return
      yield rec


class SequenceBatcher:
  """Length-bucketed batching (ref record_batcher.cc RecordBatcher:89).

  processor(record_bytes) -> NestedMap with a scalar 'bucket_key' (e.g.
  sequence length) and array fields; batches are emitted when a bucket
  fills (bucket_batch_limit entries) with fields padded to the bucket bound.
  """

  def __init__(self, source, processor: Callable,
               bucket_upper_bound: Sequence[int],
               bucket_batch_limit: Sequence[int],
               pad_field_to_bucket: Sequence[str] = ("ids", "paddings",
                                                     "labels"),
               flush_every_n: int = 0):
    """flush_every_n: if >0, partially-filled buckets are emitted after
    this many processed records since the bucket's oldest entry (ref
    record_batcher.cc flush timeouts — bounded staleness for rare
    buckets, in records instead of wall-clock for determinism)."""
    assert len(bucket_upper_bound) == len(bucket_batch_limit)
    self._source = source
    self._processor = processor
    self._bounds = list(bucket_upper_bound)
    self._limits = list(bucket_batch_limit)
    self._pad_fields = set(pad_field_to_bucket)
    self._flush_every_n = flush_every_n
    # stats (ref RecordBatcher stats logging); exported as train summaries
    # via FileBasedSequenceInputGenerator.InputStats
    self.stats = {
        "records": 0, "dropped_too_long": 0, "batches": 0,
        "flushed_partial": 0,
    }

  def Snapshot(self) -> dict:
    """Copy of the counters, safe to export from another thread."""
    return dict(self.stats)

  def __iter__(self):
    buckets: list[list[NestedMap]] = [[] for _ in self._bounds]
    oldest: list[int] = [0] * len(self._bounds)
    for record in self._source:
      ex = self._processor(record)
      if ex is None:
        continue
      self.stats["records"] += 1
      if self._flush_every_n:
        # sweep EVERY bucket on EVERY processed record (even ones about to
        # be dropped): a rare bucket must not hold its entries past the
        # staleness bound while other traffic flows
        for j, bucket in enumerate(buckets):
          if bucket and (self.stats["records"] - oldest[j]
                         >= self._flush_every_n):
            self.stats["batches"] += 1
            self.stats["flushed_partial"] += 1
            yield self._Assemble(bucket, self._bounds[j])
            buckets[j] = []
      key = int(ex.bucket_key)
      idx = bisect.bisect_left(self._bounds, key)
      if idx >= len(self._bounds):
        self.stats["dropped_too_long"] += 1
        continue  # longer than the largest bucket: dropped (ref behavior)
      if not buckets[idx]:
        oldest[idx] = self.stats["records"]
      buckets[idx].append(ex)
      if len(buckets[idx]) >= self._limits[idx]:
        self.stats["batches"] += 1
        yield self._Assemble(buckets[idx], self._bounds[idx])
        buckets[idx] = []
    for idx, bucket in enumerate(buckets):  # final flush
      if bucket:
        self.stats["batches"] += 1
        self.stats["flushed_partial"] += 1
        yield self._Assemble(bucket, self._bounds[idx])

  def _Assemble(self, examples: list[NestedMap], bound: int) -> NestedMap:
    out = NestedMap()
    keys = [k for k, _ in examples[0].FlattenItems() if k != "bucket_key"]
    for k in keys:
      vals = [ex.GetItem(k) for ex in examples]
      if k.split(".")[-1] in self._pad_fields or any(
          np.ndim(v) >= 1 and np.shape(v)[0] != bound for v in vals):
        padded = []
        for v in vals:
          v = np.asarray(v)
          if v.ndim >= 1 and v.shape[0] < bound:
            pad_width = [(0, bound - v.shape[0])] + [(0, 0)] * (v.ndim - 1)
            fill = 1.0 if k.endswith("paddings") else 0
            v = np.pad(v, pad_width, constant_values=fill)
          padded.append(v)
        vals = padded
      out.Set(k, np.stack(vals))
    return out


class CrossBatchMixingDataSource(DataSource):
  """Example-level mixing across sources (ref CrossBatchMixingDataSource:194):
  each record is drawn from a child source sampled by weight, so one batch
  interleaves examples from every source (vs whole-batch switching)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("sub", [], "Child DataSource Params.")
    p.Define("weights", [], "Sampling weight per child.")
    p.Define("seed", 301, "Sampling seed.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert len(p.sub) == len(p.weights) and p.sub
    self._sources = [sp.Instantiate() for sp in p.sub]

  def __iter__(self):
    p = self.p
    rng = np.random.RandomState(p.seed)
    iters = [iter(s) for s in self._sources]
    probs = np.asarray(p.weights, np.float64)
    probs = probs / probs.sum()
    alive = [True] * len(iters)
    while any(alive):
      k = rng.choice(len(iters), p=probs)
      if not alive[k]:
        continue
      rec = next(iters[k], None)
      if rec is None:
        alive[k] = False
        # renormalize over live children (a dead child must not starve)
        live = np.asarray(alive, np.float64) * np.asarray(p.weights)
        if live.sum() == 0:
          return
        probs = live / live.sum()
        continue
      yield rec


class PrefixedDataSource(DataSource):
  """Prepends a directory prefix to the wrapped source's file patterns
  (ref PrefixedDataSource:325 — dataset roots differ per cluster)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("sub", None, "Wrapped DataSource Params (SimpleDataSource).")
    p.Define("file_pattern_prefix", "", "Directory prefix to prepend.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    sub = p.sub.Copy()
    prefix = p.file_pattern_prefix.rstrip("/")

    def _Prefix(pat: str) -> str:
      if ":" in pat:
        kind, rest = pat.split(":", 1)
        return f"{kind}:{prefix}/{rest}"
      return f"{prefix}/{pat}"

    if isinstance(sub.file_pattern, (list, tuple)):
      sub.file_pattern = [_Prefix(x) for x in sub.file_pattern]
    else:
      sub.file_pattern = _Prefix(sub.file_pattern)
    self._source = sub.Instantiate()

  def __iter__(self):
    return iter(self._source)


class TfdsDataSource(DataSource):
  """tensorflow_datasets adapter (ref TFDatasetSource family:351): yields
  serialized examples from a TFDS builder when the package is available;
  raises a clear error otherwise (the package is optional)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("dataset", "", "TFDS name, e.g. 'lm1b'.")
    p.Define("split", "train", "Split.")
    p.Define("shuffle_files", True, "Shuffle input files.")
    p.Define("field", "text", "Example field to yield (bytes).")
    return p

  def __iter__(self):
    try:
      import tensorflow_datasets as tfds  # type: ignore
    except ImportError as e:
      raise ImportError(
          "TfdsDataSource needs the optional tensorflow_datasets package; "
          "use SimpleDataSource over exported files instead") from e
    p = self.p
    ds = tfds.load(p.dataset, split=p.split,
                   shuffle_files=p.shuffle_files)
    for ex in tfds.as_numpy(ds):
      val = ex[p.field]
      yield val if isinstance(val, bytes) else str(val).encode()
