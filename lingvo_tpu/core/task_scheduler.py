"""Multi-task sampling schedulers (ref lingvo/core/task_scheduler.py).

The executor samples a task each program cycle (ref executor.py:573):
constant probabilities, exponentially-annealed interpolation, and
adaptive (loss-proportional) scheduling.
"""

from __future__ import annotations

import numpy as np

from lingvo_tpu.core import hyperparams


class TaskScheduler:

  @classmethod
  def Params(cls):
    p = hyperparams.InstantiableParams(cls)
    p.Define("name", "scheduler", "Name.")
    p.Define("task_probs", [], "List of (task_name, prob).")
    p.Define("seed", 0, "Sampling seed.")
    return p

  def __init__(self, params):
    self.p = params.Copy()
    self._rng = np.random.RandomState(self.p.seed)
    self.cur_probs = None

  def Sample(self, current_step: int) -> str:
    raise NotImplementedError


class ConstantScheduler(TaskScheduler):
  """Fixed sampling probabilities (ref ConstantScheduler)."""

  def __init__(self, params):
    super().__init__(params)
    names = [t for t, _ in self.p.task_probs]
    probs = np.asarray([p for _, p in self.p.task_probs], np.float64)
    self._names = names
    self._probs = probs / probs.sum()
    self.cur_probs = self._probs

  def Sample(self, current_step: int) -> str:
    return str(self._rng.choice(self._names, p=self._probs))


class ExponentialScheduler(TaskScheduler):
  """Interpolates each task's prob from start to final with exp decay
  (ref ExponentialScheduler)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("alpha", 1e-5, "Decay rate exponent per step.")
    p.Define("task_probs_final", [], "(task, final prob) pairs.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self._names = [t for t, _ in self.p.task_probs]
    self._start = np.asarray([p for _, p in self.p.task_probs], np.float64)
    self._final = np.asarray([p for _, p in self.p.task_probs_final],
                             np.float64)

  def Sample(self, current_step: int) -> str:
    decay = np.exp(-self.p.alpha * current_step)
    probs = self._start * decay + self._final * (1 - decay)
    probs = probs / probs.sum()
    self.cur_probs = probs
    return str(self._rng.choice(self._names, p=probs))


class AdaptiveScheduler(TaskScheduler):
  """Samples proportionally to how far each task is from its target metric
  (ref AdaptiveScheduler): tasks lagging their goal get more steps."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("targets", [], "(task, target_metric_value) pairs.")
    p.Define("temperature", 1.0, "Sampling temperature.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self._names = [t for t, _ in self.p.targets]
    self._targets = {t: v for t, v in self.p.targets}
    self._latest = {t: None for t in self._names}

  def ReportMetric(self, task_name: str, value: float) -> None:
    self._latest[task_name] = value

  def Sample(self, current_step: int) -> str:
    gaps = []
    for t in self._names:
      latest = self._latest[t]
      if latest is None:
        gaps.append(1.0)
      else:
        gaps.append(max(latest / max(self._targets[t], 1e-8), 1e-3))
    gaps = np.asarray(gaps, np.float64)**(1.0 / self.p.temperature)
    probs = gaps / gaps.sum()
    self.cur_probs = probs
    return str(self._rng.choice(self._names, p=probs))
