"""MASS masked-seq2seq example synthesis (ref `lingvo/core/ops/mass_op.cc`):
pick a contiguous span; the encoder source masks the span, the decoder
reconstructs it (inputs = shifted span, also masked per the MASS recipe).

Pure numpy — runs in the input pipeline's record processor (the C++ op's
role); deterministic per (seed, example)."""

from __future__ import annotations

import numpy as np

from lingvo_tpu.core.nested_map import NestedMap


def MassExample(ids: np.ndarray, mask_id: int, seed: int,
                mask_ratio: float = 0.5,
                span_len: int | None = None) -> NestedMap:
  """ids: [n] content token ids -> NestedMap(src, tgt) MASS pair.

  src.ids: ids with the span replaced by mask_id.
  tgt.ids: decoder inputs — the span shifted right, non-span positions
           masked (MASS trains only on the span); tgt.labels: the span;
           tgt.weights: 1 on span positions.
  """
  ids = np.asarray(ids, np.int32)
  n = len(ids)
  rng = np.random.RandomState(seed % (2**31))
  span = span_len if span_len is not None else max(1, int(n * mask_ratio))
  span = min(span, n)
  start = rng.randint(0, n - span + 1)
  end = start + span

  src = ids.copy()
  src[start:end] = mask_id

  labels = ids.copy()
  weights = np.zeros(n, np.float32)
  weights[start:end] = 1.0
  # decoder input: previous target token inside the span, mask elsewhere
  dec_in = np.full(n, mask_id, np.int32)
  dec_in[start + 1:end] = ids[start:end - 1]
  return NestedMap(
      src=NestedMap(ids=src),
      tgt=NestedMap(ids=dec_in, labels=labels, weights=weights),
      span=(int(start), int(end)))
