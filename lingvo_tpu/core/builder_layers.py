"""Builder combinator layers + the pattern-based Builder DSL.

Re-designs `lingvo/core/builder.py` (~900 LoC) + `builder_layers.py` (1.5k):
composite layers assembled from sub-layer Params — sequential chains,
parallel branches with a merge, per-element maps, named-endpoint graphs,
prefix truncation, and learned soft gating. The reference's FPropMeta
shape/flops metadata machinery is unnecessary here (jax.eval_shape subsumes
it); what remains is the composition surface GShard/car builders rely on.

The `Builder` class mirrors the reference DSL verbs (`_Seq`, `_Par`,
`_Map`, `_Graph`, `_Rep`) as thin constructors over these layers.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.core.py_utils import WeightInit, WeightParams


class SequentialLayer(base_layer.BaseLayer):
  """Runs sub-layers in order, output feeding the next input
  (ref builder_layers.SequentialLayer)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("sub", [], "List of sub-layer Params.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self.CreateChildren("sub", [sp.Copy() for sp in self.p.sub])

  def FProp(self, theta, *args):
    out = args
    for i, layer in enumerate(self.sub):
      out = layer.FProp(theta.sub[i], *out)
      if not isinstance(out, tuple):
        out = (out,)
    return out[0] if len(out) == 1 else out


class ParallelLayer(base_layer.BaseLayer):
  """Runs sub-layers on the same inputs, merging outputs
  (ref builder_layers.ParallelLayer)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("sub", [], "List of sub-layer Params.")
    p.Define("merge_fn", None,
             "fn(list_of_outputs) -> merged (default: elementwise sum).")
    return p

  def __init__(self, params):
    super().__init__(params)
    self.CreateChildren("sub", [sp.Copy() for sp in self.p.sub])

  def FProp(self, theta, *args):
    outs = [layer.FProp(theta.sub[i], *args)
            for i, layer in enumerate(self.sub)]
    merge = self.p.merge_fn or (lambda xs: sum(xs[1:], xs[0]))
    return merge(outs)


class MapLayer(base_layer.BaseLayer):
  """Applies one sub-layer to every element of a list/NestedMap input
  (ref builder_layers.MapLayer)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("sub", None, "The mapped sub-layer Params.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self.CreateChild("sub", self.p.sub)

  def FProp(self, theta, inputs):
    if isinstance(inputs, NestedMap):
      return inputs.Transform(lambda x: self.sub.FProp(theta.sub, x))
    return type(inputs)(self.sub.FProp(theta.sub, x) for x in inputs)


class GraphLayer(base_layer.BaseLayer):
  """Named-endpoint dataflow graph (ref builder.py `_Graph`):

  p.input_endpoints / p.output_endpoints name NestedMap fields; each
  sub-layer is a ('in1,in2->out1', layer params) edge evaluated in order.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_endpoints", [], "Names of graph inputs.")
    p.Define("output_endpoints", [], "Names of graph outputs.")
    p.Define("sub", [], "List of (signature, layer Params).")
    return p

  def __init__(self, params):
    super().__init__(params)
    subs = []
    self._sigs = []
    for sig, sp in self.p.sub:
      ins, outs = sig.split("->")
      self._sigs.append(([s.strip() for s in ins.split(",")],
                         [s.strip() for s in outs.split(",")]))
      subs.append(sp.Copy())
    self.CreateChildren("sub", subs)

  def FProp(self, theta, inputs: NestedMap) -> NestedMap:
    env = inputs.Copy()
    for i, ((ins, outs), layer) in enumerate(zip(self._sigs, self.sub)):
      args = [env.GetItem(name) for name in ins]
      result = layer.FProp(theta.sub[i], *args)
      if not isinstance(result, tuple):
        result = (result,)
      assert len(result) == len(outs), (outs, len(result))
      for name, value in zip(outs, result):
        env.Set(name, value)
    return NestedMap({name: env.GetItem(name)
                      for name in self.p.output_endpoints})


class FirstNLayer(base_layer.BaseLayer):
  """Passes through the first n args (ref builder_layers.FirstNLayer)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("n", 1, "How many leading args to return.")
    return p

  def FProp(self, theta, *args):
    out = args[:self.p.n]
    return out[0] if len(out) == 1 else out


class SoftCondLayer(base_layer.BaseLayer):
  """Learned soft mixture over N sub-layer instantiations
  (ref builder_layers.SoftCondLayer): weight = softmax(w . mean(x))."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("sub", None, "Sub-layer template (instantiated num_experts x).")
    p.Define("num_experts", 2, "N.")
    p.Define("cond_dim", 0, "Input feature dim for the gate.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.cond_dim > 0
    self.CreateChildren("sub",
                        [p.sub.Copy() for _ in range(p.num_experts)])
    self.CreateVariable(
        "gate_w", WeightParams((p.cond_dim, p.num_experts), p.params_init,
                               p.dtype))

  def FProp(self, theta, inputs, *args):
    th = self.CastTheta(theta)
    pooled = jnp.mean(inputs, axis=tuple(range(1, inputs.ndim - 1)))
    gates = jax.nn.softmax(
        jnp.einsum("bd,de->be", pooled, th.gate_w).astype(jnp.float32),
        axis=-1)                                          # [B, N]
    outs = [layer.FProp(theta.sub[i], inputs, *args)
            for i, layer in enumerate(self.sub)]
    stacked = jnp.stack(outs, axis=1)                     # [B, N, ...]
    g = gates.reshape(gates.shape + (1,) * (stacked.ndim - 2)).astype(
        stacked.dtype)
    return jnp.sum(stacked * g, axis=1)


class Builder:
  """The DSL verbs (ref builder.Base): thin constructors over the
  combinator layers. Subclass and add model-specific pieces."""

  def _Seq(self, name, *subs):
    return SequentialLayer.Params().Set(name=name, sub=list(subs))

  def _Par(self, name, *subs, merge_fn=None):
    return ParallelLayer.Params().Set(name=name, sub=list(subs),
                                      merge_fn=merge_fn)

  def _Map(self, name, sub):
    return MapLayer.Params().Set(name=name, sub=sub)

  def _Graph(self, name, input_endpoints, output_endpoints, *edges):
    return GraphLayer.Params().Set(
        name=name, input_endpoints=list(input_endpoints),
        output_endpoints=list(output_endpoints), sub=list(edges))

  def _FirstN(self, name, n):
    return FirstNLayer.Params().Set(name=name, n=n)

  def _Rep(self, name, n, sub):
    return SequentialLayer.Params().Set(
        name=name, sub=[sub.Copy() for _ in range(n)])
