"""Learning-rate schedules (ref: lingvo/core/schedule.py, 998 LoC).

Each schedule is a Params-configured layer-like object whose `Value(step)` is
a pure jnp function of the global step — directly usable inside jit.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from lingvo_tpu.core import base_layer


class BaseSchedule(base_layer.BaseLayer):

  def _NameIsRequired(self):
    return False

  def Value(self, step):
    raise NotImplementedError

  def FProp(self, theta, step):
    return self.Value(step)


class Constant(BaseSchedule):

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("value", 1.0, "The constant value.")
    return p

  def Value(self, step):
    return jnp.asarray(self.p.value, jnp.float32)


class PiecewiseConstant(BaseSchedule):
  """Piecewise constant by step boundaries (`schedule.py` PiecewiseConstant)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("boundaries", [], "Step boundaries (ascending).")
    p.Define("values", [], "len(boundaries)+1 values.")
    return p

  def Value(self, step):
    p = self.p
    assert len(p.values) == len(p.boundaries) + 1
    step = jnp.asarray(step, jnp.int32)
    index = jnp.sum(
        (step >= jnp.asarray(p.boundaries, jnp.int32)).astype(jnp.int32)
    ) if p.boundaries else 0
    return jnp.asarray(jnp.array(p.values, jnp.float32)[index], jnp.float32)


class Polynomial(BaseSchedule):
  """Polynomial interpolation between (x0,y0) and (x1,y1)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("power", 1, "Polynomial power.")
    p.Define("start", (0, 0.0), "(step, value) start point.")
    p.Define("limit", (1, 1.0), "(step, value) end point.")
    p.Define("origin", "start", "'start' or 'limit': where f(x)=x^p anchors.")
    return p

  def Value(self, step):
    p = self.p
    x = jnp.asarray(step, jnp.float32)
    x0, y0 = p.start
    x1, y1 = p.limit
    ratio = jnp.clip((x - x0) / max(1.0, (x1 - x0)), 0.0, 1.0)
    if p.origin == "start":
      f = ratio**p.power
    else:
      f = 1.0 - (1.0 - ratio)**p.power
    return jnp.asarray(y0 + f * (y1 - y0), jnp.float32)


class LinearRampupExponentialDecay(BaseSchedule):
  """Warmup then exponential decay (`schedule.py` LinearRampupExponentialDecayScaledByNumSplitSchedule, un-split)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("warmup", 100, "Steps of linear warmup to max.")
    p.Define("decay_start", 1000, "Step to start decay.")
    p.Define("decay_end", 10000, "Step decay reaches min.")
    p.Define("max", 1.0, "Peak multiplier.")
    p.Define("min", 0.01, "Final multiplier.")
    return p

  def Value(self, step):
    p = self.p
    x = jnp.asarray(step, jnp.float32)
    warm = x / max(1.0, p.warmup) * p.max
    ratio = jnp.clip((x - p.decay_start) / max(1.0, p.decay_end - p.decay_start),
                     0.0, 1.0)
    decayed = p.max * (p.min / p.max)**ratio
    val = jnp.where(x < p.warmup, warm, jnp.where(x < p.decay_start,
                                                  p.max, decayed))
    return jnp.maximum(val, 0.0)


class TransformerSchedule(BaseSchedule):
  """warmup_steps^-1.5 ramp then rsqrt decay (`schedule.py` TransformerSchedule)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("warmup_steps", 4000, "Warmup steps.")
    p.Define("model_dim", 512, "Model dim; scales by model_dim^-0.5.")
    p.Define("worker_replicas", 1, "Data-parallel replicas (kept for parity).")
    p.Define("decay_end", None, "If set, freeze value after this step.")
    return p

  def Value(self, step):
    p = self.p
    x = jnp.maximum(jnp.asarray(step, jnp.float32), 1.0)
    if p.decay_end is not None:
      x = jnp.minimum(x, float(p.decay_end))
    return (p.model_dim**-0.5) * jnp.minimum(
        (x + 1) * p.warmup_steps**-1.5, (x + 1)**-0.5)


class LinearRampupCosineDecay(BaseSchedule):
  """Linear warmup then cosine decay to min_ratio (modern LM default)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("warmup_steps", 1000, "Warmup steps.")
    p.Define("total_steps", 100000, "Steps at which decay completes.")
    p.Define("min_ratio", 0.1, "Final value as a fraction of peak.")
    p.Define("max", 1.0, "Peak value.")
    return p

  def Value(self, step):
    p = self.p
    x = jnp.asarray(step, jnp.float32)
    warm = x / max(1.0, p.warmup_steps)
    ratio = jnp.clip((x - p.warmup_steps) /
                     max(1.0, p.total_steps - p.warmup_steps), 0.0, 1.0)
    cos = p.min_ratio + (1 - p.min_ratio) * 0.5 * (1 + jnp.cos(math.pi * ratio))
    return p.max * jnp.where(x < p.warmup_steps, warm, cos)


class ExponentialDecay(BaseSchedule):

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("start_step", 0, "Decay start.")
    p.Define("half_life_steps", 1000, "Steps per halving.")
    p.Define("min", 0.0, "Floor.")
    return p

  def Value(self, step):
    p = self.p
    x = jnp.maximum(jnp.asarray(step, jnp.float32) - p.start_step, 0.0)
    return jnp.maximum(0.5**(x / p.half_life_steps), p.min)


class DevBasedSchedule(BaseSchedule):
  """Anneal-on-plateau: decay the LR multiplier when the dev metric stalls
  (ref `schedule.py:728` DevBasedSchedule).

  The trigger lives on the HOST: the evaler writes a metric history file
  (`early_stop.MetricHistory`), and between program runs the trainer calls
  `UpdateFromHistory(...)`, which applies the reference's algorithm::

    ref_step = max(ref_step, best_step)
    if last_step - ref_step > window:
      cur_factor = max(cur_factor * decay, min_factor); ref_step = last_step

  `Value(step)` returns the current multiplier as a trace-time constant —
  programs watch `HostStateKey()` and re-jit when it changes (rare: a
  handful of decays per run), which replaces the reference's mutable
  cur_factor variable without any in-graph file reads.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("history_path", "",
             "MetricHistory jsonl path (set by the trainer wiring).")
    p.Define("tolerance", 0.0, "Minimum significant metric improvement.")
    p.Define("window", 10000, "Steps since best/last decay before decaying.")
    p.Define("decay", 0.5, "Multiplier decay factor.")
    p.Define("min_factor", 0.01, "Multiplier floor.")
    p.Define("minimize", True, "Lower metric is better.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self._cur_factor = 1.0
    self._history_path = self.p.history_path or None

  def SetMetricHistory(self, metric_history) -> None:
    """Points this schedule at a live early_stop.MetricHistory."""
    self._history_path = metric_history.path

  def UpdateFromHistory(self) -> bool:
    """Host-side decay check; returns True if the multiplier changed.

    RESTART-SAFE BY REPLAY: instead of checkpointing cur_factor (the
    reference keeps it in a TF variable), the full decay algorithm is
    deterministically replayed over the metric-history file — a decay can
    only trigger when a new record lands, so replaying records reproduces
    the incremental state exactly, and a restarted job recovers the same
    multiplier from the same file.
    """
    from lingvo_tpu.core import early_stop
    p = self.p
    if not self._history_path:
      return False
    history = early_stop.ReadHistory(self._history_path)
    if not history:
      return False
    factor, ref_step = 1.0, 0
    best_step, best_val = 0, None
    for step, val in history:
      better = (best_val is None or
                (val < best_val - p.tolerance if p.minimize else
                 val > best_val + p.tolerance))
      if better:
        best_val, best_step = val, step
      ref_step = max(ref_step, best_step)
      if step - ref_step > p.window:
        factor = max(factor * p.decay, p.min_factor)
        ref_step = step
    changed = factor != self._cur_factor
    self._cur_factor = factor
    return changed

  def HostStateKey(self):
    """Changes whenever jitted consumers must re-trace."""
    return self._cur_factor

  def Value(self, step):
    del step
    return jnp.asarray(self._cur_factor, jnp.float32)
