"""Checkpointing: save cadence policy + orbax-backed storage.

Re-designs `lingvo/core/checkpointer.py` + `saver.py`: same policy surface —
save-by-steps/secs (`ShouldSave:281`), restore-or-init (`Restore:354`),
max_to_keep GC with keep_every_n (`saver.py:297`), saved-value sanity checks
(`saver.py:64-95`), async saving (`saver.py:335`) — implemented over
`orbax.checkpoint` which already speaks sharded jax.Array natively (the
TPU-native replacement for the reference's graph-mode sharded Saver).

Two save surfaces:
- `Save` — synchronous write (the caller blocks through the orbax write);
  used at exit-time force saves and by anything needing write-then-read.
- `SaveAsync` — the pipelined executor's cadence save: snapshot the state
  on the calling thread (a cheap device-side copy fence; only THAT is
  `checkpoint_save` badput) and run the orbax write on a background
  worker. `WaitForPendingSave` is the barrier — Restore/Close/the final
  force-save all cross it, so a restore can never read a half-written
  step and worker errors surface at the next fence instead of vanishing.

Goodput attribution lives INSIDE the save calls, gated on an actual write:
a cadence no-op contributes zero `checkpoint_save` badput.
"""

from __future__ import annotations

import os
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any

import jax
import numpy as np

from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap


class Checkpointer:

  # multi-host wallclock cadence probes the clock every this many steps
  _SECONDS_CHECK_STRIDE = 10

  def __init__(self,
               train_dir: str,
               save_interval_steps: int = 1000,
               save_interval_seconds: int | None = None,
               max_to_keep: int = 10,
               keep_every_n_steps: int | None = None,
               async_save: bool = True,
               sanity_checks: bool = True,
               goodput=None):
    """goodput: injectable GoodputTracker (tests); None resolves the
    process-global tracker lazily on the first actual write."""
    import orbax.checkpoint as ocp
    self._train_dir = os.path.abspath(train_dir)
    os.makedirs(self._train_dir, exist_ok=True)
    self._save_interval_steps = save_interval_steps
    self._save_interval_seconds = save_interval_seconds
    self._sanity_checks = sanity_checks
    self._goodput = goodput
    self._last_save_time = time.time()
    self._last_save_step = -1
    self._last_probe_step = -(self._SECONDS_CHECK_STRIDE + 1)
    # SaveAsync background writer: one worker => writes land in submission
    # order; at most one write outstanding (SaveAsync barriers on the
    # previous one, so a slow filesystem applies backpressure to the
    # cadence instead of queueing unbounded snapshots)
    self._save_pool: ThreadPoolExecutor | None = None
    self._pending_save: Future | None = None
    options = ocp.CheckpointManagerOptions(
        max_to_keep=max_to_keep,
        keep_period=keep_every_n_steps,
        enable_async_checkpointing=async_save,
    )
    self._mgr = ocp.CheckpointManager(self._train_dir, options=options)

  @property
  def train_dir(self) -> str:
    return self._train_dir

  def ShouldSave(self, step: int) -> bool:
    """Save cadence by steps or wallclock (ref checkpointer.py:281-312).

    Multi-process: the wallclock decision is made on process 0 and
    broadcast — per-host clocks drift, and a host entering the collective
    save alone deadlocks it. (Step cadence is naturally consistent.)
    """
    if step == self._last_save_step:
      return False
    if self._save_interval_seconds is not None:
      if jax.process_count() > 1:
        # the broadcast is a blocking cross-host barrier: probe the clock
        # on a coarse step stride (a save lands at most stride steps late)
        # instead of taxing every step. Stride by steps-since-last-probe,
        # not step % stride: the executor advances step by
        # tpu_steps_per_loop per Save call, and for loop sizes coprime
        # with the stride a modulus probe fires in as few as 1 in stride
        # calls, widening the data-loss window stride-fold.
        if self._last_probe_step > step:
          # step rolled backwards (crash-retry Restore replays from an
          # older checkpoint): a stale high-water probe step would suppress
          # probing for the whole replayed span
          self._last_probe_step = -(self._SECONDS_CHECK_STRIDE + 1)
        if step - self._last_probe_step < self._SECONDS_CHECK_STRIDE:
          return False
        self._last_probe_step = step
        due = (time.time() - self._last_save_time
               >= self._save_interval_seconds)
        from jax.experimental import multihost_utils
        return bool(multihost_utils.broadcast_one_to_all(np.asarray(due)))
      return time.time() - self._last_save_time >= self._save_interval_seconds
    return step % max(1, self._save_interval_steps) == 0

  def _SanityCheck(self, state: NestedMap) -> None:
    """All saved floats must be finite (ref saver.py IsFinite checks).

    Single-process fast path: one device-side all-finite reduce -> one
    scalar transfer; only on failure walk leaves host-side to name the
    offender. Multi-process: each host checks ONLY its addressable shards
    (what it will write) — eager cross-host reductions outside an
    explicitly coordinated jit can deadlock the collective runtime.
    """
    if jax.process_count() > 1:
      import jax.numpy as jnp
      bad_path = None
      for path, leaf in state.FlattenItems():
        if not (isinstance(leaf, jax.Array) and
                jnp.issubdtype(leaf.dtype, jnp.floating)):
          continue
        # device-side per-shard reduce: one scalar transfer per local
        # shard, no cross-host collectives
        for s in leaf.addressable_shards:
          if not bool(jnp.isfinite(s.data).all()):
            bad_path = path
            break
        if bad_path:
          break
      # Coordinated verdict: every host must agree before entering (or
      # skipping) the collective orbax save, else the healthy hosts hang
      # in the save barrier waiting for the raising one.
      from jax.experimental import multihost_utils
      all_ok = multihost_utils.process_allgather(
          np.asarray([bad_path is None]))
      if not bool(np.all(all_ok)):
        raise ValueError(
            "Checkpoint sanity check failed: non-finite values"
            + (f" in {bad_path} (this host)" if bad_path else
               " on another host"))
      return
    if bool(py_utils.IsFinite(state)):
      return
    for path, leaf in state.FlattenItems():
      arr = np.asarray(leaf)
      if np.issubdtype(arr.dtype, np.floating) and not np.all(
          np.isfinite(arr)):
        raise ValueError(
            f"Checkpoint sanity check failed: non-finite values in {path}")
    raise ValueError("Checkpoint sanity check failed: non-finite values")

  def _Goodput(self):
    if self._goodput is None:
      from lingvo_tpu.observe import goodput as goodput_lib
      self._goodput = goodput_lib.Get()
    return self._goodput

  def _Submit(self, fn, *args) -> Future:
    if self._save_pool is None:
      self._save_pool = ThreadPoolExecutor(
          max_workers=1, thread_name_prefix="ckpt-save")
    return self._save_pool.submit(fn, *args)

  def Save(self, step: int, state: NestedMap, force: bool = False) -> bool:
    """Saves if the policy says so (or force). Returns True if saved.
    Synchronous: blocks through the orbax write (after barriering any
    in-flight SaveAsync, preserving write order). The write still runs on
    the save worker: orbax's CheckpointManager finalizes an async save
    only from the thread that wrote it, so EVERY write goes through the
    one worker to keep that thread identity stable."""
    if not force and not self.ShouldSave(step):
      return False
    with self._Goodput().Track("checkpoint_save"):
      self.WaitForPendingSave()
      if self._sanity_checks and jax.process_count() > 1:
        self._SanityCheck(state)   # collectives stay on the main thread
      self._last_save_time = time.time()
      self._last_save_step = step
      self._Submit(self._WriteSnapshot, step, state).result()
    return True

  def _SnapshotState(self, state: NestedMap) -> NestedMap:
    """Decouples the to-be-saved values from the training pipeline. On
    donating backends (non-CPU) each device leaf becomes an enqueued
    device-side copy — ordered before any later dispatch that donates the
    original buffers, and dispatched asynchronously, so the caller-side
    cost is one enqueue per leaf, not a device sync. On CPU (no donation)
    the immutable arrays are shared by reference. Either way the NestedMap
    container is fresh: the executor mutates its own in place (pruning)."""
    if jax.default_backend() == "cpu":
      return state.Transform(lambda x: x)
    import jax.numpy as jnp
    return state.Transform(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x)

  def SaveAsync(self, step: int, state: NestedMap,
                force: bool = False) -> bool:
    """Cadence save with the orbax write on a background worker. Only the
    caller-side fence — waiting out the previous write plus the snapshot
    enqueue — counts as `checkpoint_save` badput; the write itself overlaps
    training. Returns True if a write was scheduled. Errors from the
    scheduled write surface at the next WaitForPendingSave barrier
    (Restore / Close / the final force-save / the next SaveAsync)."""
    if not force and not self.ShouldSave(step):
      return False
    with self._Goodput().Track("checkpoint_save"):
      self.WaitForPendingSave()
      snap = self._SnapshotState(state)
      if self._sanity_checks and jax.process_count() > 1:
        # the multi-process check coordinates via process_allgather, which
        # must stay on the main thread (worker-side collectives can
        # interleave with program collectives and deadlock)
        self._SanityCheck(snap)
      # cadence marks advance at SUBMIT time: the decision "a save for
      # this step exists" is made now, even though the bytes land later
      self._last_save_time = time.time()
      self._last_save_step = step
      self._pending_save = self._Submit(self._WriteSnapshot, step, snap)
    return True

  def _WriteSnapshot(self, step: int, snap: NestedMap) -> None:
    """Save-worker body of Save/SaveAsync (the ONLY _mgr.save caller)."""
    if self._sanity_checks and jax.process_count() <= 1:
      # single-process: no collectives involved — check off-thread so a
      # full finiteness reduce doesn't sit on the training critical path
      self._SanityCheck(snap)
    import orbax.checkpoint as ocp
    self._mgr.save(step, args=ocp.args.StandardSave(dict(snap)))

  def WaitForPendingSave(self) -> None:
    """Barrier for SaveAsync: blocks until the in-flight write (if any)
    finishes and re-raises its error. Restore, Close, and the executor's
    recovery/final-save paths all cross this before touching checkpoints."""
    fut, self._pending_save = self._pending_save, None
    if fut is not None:
      fut.result()

  def LatestStep(self) -> int | None:
    return self._mgr.latest_step()

  def Restore(self, state_template: NestedMap,
              step: int | None = None) -> tuple[NestedMap, int]:
    """Restore-or-init: returns (state, start_step).

    If no checkpoint exists, returns the template unchanged with step 0
    (ref Restore:354 'restore or init' semantics).
    """
    import orbax.checkpoint as ocp
    self.WaitForPendingSave()   # never read around an in-flight write
    self._mgr.wait_until_finished()  # nor around an orbax finalize/GC pass
    target = step if step is not None else self._mgr.latest_step()
    if target is None:
      return state_template, 0
    def _Abstract(x):
      if isinstance(x, jax.ShapeDtypeStruct):
        return x  # already abstract (e.g. a jax.eval_shape'd template)
      if isinstance(x, jax.Array):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
      return jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)

    abstract = jax.tree_util.tree_map(_Abstract, dict(state_template))
    restored = self._mgr.restore(
        target, args=ocp.args.StandardRestore(abstract))
    state = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(state_template),
        jax.tree_util.tree_leaves(restored))
    return state, int(target)

  def WaitUntilFinished(self) -> None:
    self.WaitForPendingSave()
    self._mgr.wait_until_finished()

  def Close(self) -> None:
    self.WaitForPendingSave()
    if self._save_pool is not None:
      self._save_pool.shutdown(wait=True)
      self._save_pool = None
    self._mgr.wait_until_finished()
    self._mgr.close()


def ApplyInitFromCheckpointRules(state: NestedMap, rules: dict) -> NestedMap:
  """Warm-start partial restore with regex var mapping + dtype casting.

  Re-designs `_BuildInitFromCheckpointRules` (ref `checkpointer.py:214`) +
  `bfloat16_variables.py`: `rules` maps a source checkpoint's *train dir*
  to a list of (target_regex, source_template) pairs. Every `state.theta`
  leaf whose path fully matches a target regex is replaced by the source
  checkpoint's variable at `re.sub(target_regex, source_template, path)`,
  cast to the target dtype. Shapes must match; a matching rule whose source
  variable is missing raises (silent partial warm starts hide config bugs).

  Returns the updated state (step untouched — warm start is initialization,
  not resumption).
  """
  import re

  import jax.numpy as jnp
  import orbax.checkpoint as ocp

  def _ToNested(node):
    if isinstance(node, dict):
      return NestedMap({k: _ToNested(v) for k, v in node.items()})
    return node

  for ckpt_dir, pairs in rules.items():
    mgr = ocp.CheckpointManager(os.path.abspath(ckpt_dir),
                                item_handlers=ocp.PyTreeCheckpointHandler())
    try:
      src_step = mgr.latest_step()
      if src_step is None:
        raise FileNotFoundError(
            f"init_from_checkpoint_rules: no checkpoint in {ckpt_dir}")
      # resolve target path -> source path BEFORE any I/O
      mapping = {}  # target path -> source path
      for path, _ in state.theta.FlattenItems():
        for target_regex, source_tpl in pairs:
          if re.fullmatch(target_regex, path):
            mapping[path] = re.sub(target_regex, source_tpl, path)
            break  # first matching rule wins
      # partial restore: only the mapped source vars are read (a few vars
      # from a 175B checkpoint must not materialize the whole thing on host)
      # orbax >= 0.9 wraps the metadata tree in an object with `.tree`;
      # 0.7.x (this container) returns the raw dict
      meta_obj = mgr.item_metadata(src_step)
      meta = _ToNested(getattr(meta_obj, "tree", meta_obj))
      meta_flat = dict(meta.GetItem("theta").FlattenItems())
      for path, src_path in mapping.items():
        if src_path not in meta_flat:
          raise KeyError(
              f"init_from_checkpoint_rules: {path!r} maps to source var "
              f"{src_path!r} which is not in {ckpt_dir} "
              f"(has {len(meta_flat)} vars)")
      abstract: dict = {"theta": {}}
      for src_path in set(mapping.values()):
        node = abstract["theta"]
        parts = src_path.split(".")
        for key in parts[:-1]:
          node = node.setdefault(key, {})
        m = meta_flat[src_path]
        node[parts[-1]] = jax.ShapeDtypeStruct(tuple(m.shape), m.dtype)
      try:
        restore_args = ocp.args.PyTreeRestore(abstract,
                                              partial_restore=True)
      except TypeError:
        # orbax 0.7.x: no partial_restore kwarg — the transformations-mode
        # equivalent (transforms={} + per-leaf restore_args) reads only the
        # leaves present in `abstract`
        per_leaf = jax.tree_util.tree_map(
            lambda s: ocp.ArrayRestoreArgs(dtype=s.dtype,
                                           global_shape=s.shape), abstract)
        restore_args = ocp.args.PyTreeRestore(
            abstract, restore_args=per_leaf, transforms={})
      restored = mgr.restore(src_step, args=restore_args)
      src_flat = dict(_ToNested(dict(restored)["theta"]).FlattenItems())
      n_loaded = 0
      for path, src_path in mapping.items():
        value = state.theta.GetItem(path)
        src_val = src_flat[src_path]
        if tuple(np.shape(src_val)) != tuple(np.shape(value)):
          raise ValueError(
              f"init_from_checkpoint_rules: shape mismatch for {path}: "
              f"{np.shape(value)} vs source {np.shape(src_val)}")
        # host-side cast + direct sharded placement (see ImportNpzCheckpoint)
        host_val = np.asarray(src_val).astype(value.dtype)
        if isinstance(value, jax.Array) and hasattr(value, "sharding"):
          # keep the target's (possibly multi-host) sharding layout
          new_val = jax.device_put(host_val, value.sharding)
        else:
          new_val = jnp.asarray(host_val)
        state.theta.Set(path, new_val)
        # EMA shadows theta at init (base_model copies theta into
        # ema_theta BEFORE warm start runs): mirror the warm value or
        # eval/decode (use_ema=True) would score random weights
        if "ema_theta" in state:
          state.ema_theta.Set(path, new_val)
        n_loaded += 1
      print(f"[checkpointer] warm start: {n_loaded} vars from {ckpt_dir} "
            f"@ step {src_step}", flush=True)
    finally:
      mgr.close()
  return state


def ImportNpzCheckpoint(state: NestedMap, npz_path: str,
                        rules=None) -> NestedMap:
  """Initializes state.theta from a converted reference checkpoint.

  The .npz is produced by `tools/convert_tf_checkpoint.py` (dotted-path
  keys -> arrays). `rules` is an optional list of (target_regex,
  source_template) pairs like init_from_checkpoint_rules; None means
  identity mapping (the npz keys already use this framework's theta
  paths). Matched leaves are shape-checked and dtype-cast; theta paths with
  no matching npz entry keep their fresh initialization, but a RULE whose
  mapped source is missing raises (a silent miss hides naming bugs).
  """
  import re

  import jax.numpy as jnp

  src = np.load(npz_path)
  src_keys = set(src.files)
  n_loaded = 0
  for path, value in state.theta.FlattenItems():
    if rules is None:
      src_path = path if path in src_keys else None
      required = False
    else:
      src_path = None
      required = False
      for target_regex, source_tpl in rules:
        if re.fullmatch(target_regex, path):
          src_path = re.sub(target_regex, source_tpl, path)
          required = True
          break
    if src_path is None:
      continue
    if src_path not in src_keys:
      if required:
        raise KeyError(
            f"ImportNpzCheckpoint: {path!r} maps to {src_path!r} which is "
            f"not in {npz_path} ({len(src_keys)} vars)")
      continue
    src_val = src[src_path]
    if tuple(src_val.shape) != tuple(np.shape(value)):
      raise ValueError(
          f"ImportNpzCheckpoint: shape mismatch for {path}: "
          f"{np.shape(value)} vs source {src_val.shape}")
    # cast on the host, then place directly into the target's sharding —
    # never materialize the full array on one device (a sharded expert
    # table can exceed a single chip's HBM)
    host_val = np.asarray(src_val).astype(value.dtype)
    if isinstance(value, jax.Array) and hasattr(value, "sharding"):
      new_val = jax.device_put(host_val, value.sharding)
    else:
      new_val = jnp.asarray(host_val)
    state.theta.Set(path, new_val)
    if "ema_theta" in state:
      state.ema_theta.Set(path, new_val)
    n_loaded += 1
  print(f"[checkpointer] npz import: {n_loaded} vars from {npz_path}",
        flush=True)
  return state
