"""Conditional-computation transformer (CCT) layers.

Re-designs `lingvo/core/layers_with_attention.py:2323` (CCTAttentionLayer),
`:2640` (CCTFeedForwardLayer) and `layers.py:6565` (CCTGatingNetwork) from
https://arxiv.org/abs/2002.07106: per-token scalar gates that are continuous
(sigmoid + annealed noise) during training and hard 0/1 at eval, so XLA sees
the SAME static graph in both modes — conditional compute as masking, which
is the only TPU-friendly form (no dynamic shapes, no token gather/scatter).

An optional compute-budget auxiliary loss (mean gate activation) rides the
standard aux-loss channel (`py_utils.AddAuxLoss`), like MoE load balancing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import layers
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.core.py_utils import WeightInit, WeightParams


class CCTGatingNetwork(base_layer.BaseLayer):
  """Continuous-for-train / discrete-for-eval gate (ref `layers.py:6565`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Input depth.")
    p.Define("hidden_layer_dim", 0, "Hidden depth (0 = input_dim).")
    p.Define("num_outputs", 1, "Number of scalar gates per position.")
    p.Define("noise_std", 1.0, "Full-strength gating noise std.")
    p.Define("noise_warmup_steps", 1.0, "Steps to reach full noise.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.input_dim
    hidden = p.hidden_layer_dim or p.input_dim
    self.CreateVariable(
        "w1", WeightParams((p.input_dim, hidden), p.params_init, p.dtype))
    self.CreateVariable(
        "b1", WeightParams((hidden,), WeightInit.Constant(0.0), p.dtype))
    self.CreateVariable(
        "w2", WeightParams((hidden, p.num_outputs), p.params_init, p.dtype))
    self.CreateVariable(
        "b2", WeightParams((p.num_outputs,), WeightInit.Constant(0.0),
                           p.dtype))

  def FProp(self, theta, inputs):
    """[..., input_dim] -> gates [..., num_outputs] in [0, 1]."""
    p = self.p
    th = self.CastTheta(theta)
    x = self.ToFPropDtype(inputs)
    h = jax.nn.relu(jnp.einsum("...d,dh->...h", x, th.w1) + th.b1)
    logits = (jnp.einsum("...h,ho->...o", h, th.w2) + th.b2).astype(
        jnp.float32)
    if py_utils.DoEval():
      return (logits >= 0.0).astype(jnp.float32)
    # annealed deterministic noise pushes logits toward saturation
    step = py_utils.GetGlobalStep()
    frac = (jnp.minimum(jnp.asarray(step, jnp.float32),
                        p.noise_warmup_steps) / p.noise_warmup_steps
            if step is not None else 1.0)
    noise_std = p.noise_std * frac
    if py_utils.HasStepSeed():
      key = py_utils.StepSeed(self.path + "/gate_noise")
      logits = logits + noise_std * jax.random.normal(key, logits.shape)
    return jax.nn.sigmoid(logits)


class CCTAttentionLayer(base_layer.BaseLayer):
  """Pre-LN attention with query and key/value gating (ref `:2323`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Model dim.")
    p.Define("num_heads", 8, "Heads.")
    p.Define("is_masked", False, "Causal self-attention.")
    p.Define("gating_tpl", CCTGatingNetwork.Params(), "Gate template.")
    p.Define("gate_loss_weight", 0.0,
             "If >0, adds mean gate activation as an aux compute-budget "
             "loss.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.input_dim
    from lingvo_tpu.core import attention as attention_lib
    self.CreateChild("ln", layers.LayerNorm.Params().Set(
        input_dim=p.input_dim))
    self.CreateChild(
        "atten",
        attention_lib.MultiHeadedAttention.Params().Set(
            input_dim=p.input_dim, hidden_dim=p.input_dim,
            num_heads=p.num_heads))
    self.CreateChild("query_gating",
                     p.gating_tpl.Copy().Set(input_dim=p.input_dim,
                                             num_outputs=1))
    self.CreateChild("kv_gating",
                     p.gating_tpl.Copy().Set(input_dim=p.input_dim,
                                             num_outputs=1))

  def FProp(self, theta, query_vec, source_vecs=None, paddings=None,
            source_paddings=None, segment_ids=None):
    """[b, t, d] -> (gated attention output + residual, gates).

    `paddings` mask the query side; cross-attention masks keys with
    `source_paddings` (the attention core consumes KEY-side paddings).
    """
    p = self.p
    x = self.ln.FProp(self.ChildTheta(theta, "ln"), query_vec)
    kv_src = x if source_vecs is None else source_vecs
    kv_gate = self.kv_gating.FProp(
        self.ChildTheta(theta, "kv_gating"), kv_src)       # [b, s, 1]
    gated_kv = kv_src * kv_gate.astype(kv_src.dtype)
    if source_vecs is None:
      out, _ = self.atten.FProp(
          self.ChildTheta(theta, "atten"), x, key_vec=gated_kv,
          value_vec=gated_kv, paddings=paddings, segment_ids=segment_ids,
          causal=p.is_masked)
    else:
      out, _ = self.atten.FProp(
          self.ChildTheta(theta, "atten"), x, key_vec=gated_kv,
          value_vec=gated_kv, paddings=source_paddings)
    q_gate = self.query_gating.FProp(
        self.ChildTheta(theta, "query_gating"), x)         # [b, t, 1]
    out = out * q_gate.astype(out.dtype)
    if p.gate_loss_weight > 0:
      py_utils.AddAuxLoss(
          self.path + "/gate_budget",
          p.gate_loss_weight * (jnp.mean(q_gate) + jnp.mean(kv_gate)))
    return query_vec + out, NestedMap(query_gate=q_gate, kv_gate=kv_gate)


class CCTFeedForwardLayer(base_layer.BaseLayer):
  """FFN split into independently-gated blocks (ref `:2640`).

  hidden_dim is divided into `num_blocks` chunks; each chunk has its own
  scalar per-token gate. Gated-off chunks contribute nothing (and at eval
  the gates are exactly 0/1, making per-token compute conditional in the
  masking sense)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Model dim.")
    p.Define("hidden_dim", 0, "Total FFN hidden dim across blocks.")
    p.Define("num_blocks", 4, "Independently gated hidden chunks.")
    p.Define("activation", "RELU", "Hidden activation.")
    p.Define("gating_tpl", CCTGatingNetwork.Params(), "Gate template.")
    p.Define("gate_loss_weight", 0.0, "Aux compute-budget loss weight.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.input_dim and p.hidden_dim
    assert p.hidden_dim % p.num_blocks == 0
    self.CreateChild("ln", layers.LayerNorm.Params().Set(
        input_dim=p.input_dim))
    self.CreateVariable(
        "w_in", WeightParams((p.input_dim, p.hidden_dim), p.params_init,
                             p.dtype))
    self.CreateVariable(
        "b_in", WeightParams((p.hidden_dim,), WeightInit.Constant(0.0),
                             p.dtype))
    self.CreateVariable(
        "w_out", WeightParams((p.hidden_dim, p.input_dim), p.params_init,
                              p.dtype))
    self.CreateVariable(
        "b_out", WeightParams((p.input_dim,), WeightInit.Constant(0.0),
                              p.dtype))
    self.CreateChild("gating",
                     p.gating_tpl.Copy().Set(input_dim=p.input_dim,
                                             num_outputs=p.num_blocks))

  def FProp(self, theta, inputs, paddings=None):
    p = self.p
    th = self.CastTheta(theta)
    from lingvo_tpu.core import activations
    x = self.ln.FProp(self.ChildTheta(theta, "ln"), inputs)
    h = activations.GetFn(p.activation)(
        jnp.einsum("btd,dh->bth", x, th.w_in) + th.b_in)
    gates = self.gating.FProp(self.ChildTheta(theta, "gating"), x)
    # expand per-block gates across their hidden chunk: [b,t,K] -> [b,t,H]
    b, t, _ = h.shape
    gate_h = jnp.repeat(gates, p.hidden_dim // p.num_blocks, axis=-1)
    h = h * gate_h.astype(h.dtype)
    out = jnp.einsum("bth,hd->btd", h, th.w_out) + th.b_out
    if paddings is not None:
      out = out * (1.0 - paddings)[:, :, None].astype(out.dtype)
    if p.gate_loss_weight > 0:
      py_utils.AddAuxLoss(self.path + "/gate_budget",
                          p.gate_loss_weight * jnp.mean(gates))
    return inputs + out, gates
