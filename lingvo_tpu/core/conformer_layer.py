"""Conformer block: FFN/2 + MHSA + LConv + FFN/2 + LN.

Re-designs `lingvo/core/conformer_layer.py` (LConvLayer:35,
ConformerLayer:471). Streaming support comes from the causal depthwise conv
(left-pad) + LocalSelfAttention options on the attention template.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import attention as attention_lib
from lingvo_tpu.core import base_layer
from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core import py_utils
from lingvo_tpu.core import quant_utils
from lingvo_tpu.core import transformer as transformer_lib
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.core.py_utils import WeightInit, WeightParams


class LConvLayer(quant_utils.QuantizableLayer):
  """Lightweight conv block: LN -> pw-GLU -> dw-conv -> norm -> swish -> pw
  (ref LConvLayer:35). The inherited `qdomain` param fake-quantizes the
  pointwise + depthwise conv weights (ref batch_major_attention.py:9016-9097
  conv_qdomain/linear_qdomain threading into the conformer builder)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Model dim.")
    p.Define("kernel_size", 32, "Depthwise kernel size.")
    p.Define("causal", False, "Causal depthwise conv (streaming).")
    p.Define("conv_norm", "bn", "'bn' | 'ln' on the conv branch.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    d = p.input_dim
    self.CreateChild("ln", layers_lib.LayerNorm.Params().Set(input_dim=d))
    self.CreateVariable(
        "pw_in", WeightParams((d, 2 * d), p.params_init, p.dtype))
    self.CreateVariable(
        "dw", WeightParams((p.kernel_size, d), p.params_init, p.dtype))
    if p.conv_norm == "bn":
      self.CreateChild("norm", layers_lib.BatchNormLayer.Params().Set(dim=d))
    else:
      self.CreateChild("norm", layers_lib.LayerNorm.Params().Set(input_dim=d))
    self.CreateVariable("pw_out", WeightParams((d, d), p.params_init, p.dtype))
    self._CreateQDomain()

  def FProp(self, theta, inputs, paddings=None):
    p = self.p
    th = self.CastTheta(theta)
    x = self.ln.FProp(theta.ln, inputs)
    gated = jnp.einsum("btd,de->bte", x, self.QWeight(theta, th.pw_in))
    a, b = jnp.split(gated, 2, axis=-1)
    x = a * jax.nn.sigmoid(b)  # GLU
    if paddings is not None:
      x = py_utils.ApplyPadding(paddings, x)
    # depthwise conv over time: [b,t,d] with kernel [k,d]
    k = p.kernel_size
    if p.causal:
      pad = [(0, 0), (k - 1, 0), (0, 0)]
    else:
      pad = [(0, 0), ((k - 1) // 2, k // 2), (0, 0)]
    xp = jnp.pad(x, pad)
    x = jax.lax.conv_general_dilated(
        xp, self.QWeight(theta, th.dw)[:, None, :],  # [k, 1, d] HIO-ish
        window_strides=(1,),
        padding="VALID",
        feature_group_count=p.input_dim,
        dimension_numbers=("NHC", "HIO", "NHC"))
    if p.conv_norm == "bn":
      x = self.norm.FProp(theta.norm, x, paddings)
    else:
      x = self.norm.FProp(theta.norm, x)
    x = jax.nn.silu(x)
    x = jnp.einsum("btd,de->bte", x, self.QWeight(theta, th.pw_out))
    if paddings is not None:
      x = py_utils.ApplyPadding(paddings, x)
    return inputs + x

  # -- chunk streaming -------------------------------------------------------

  def InitStreamStates(self, batch_size: int) -> NestedMap:
    """Causal-conv ring buffer: the last kernel-1 post-GLU frames."""
    p = self.p
    assert p.causal, "streaming LConv requires causal=True"
    assert p.conv_norm == "ln", (
        "streaming LConv requires conv_norm='ln' (BatchNorm pools over time)")
    return NestedMap(conv_input=jnp.zeros(
        (batch_size, p.kernel_size - 1, p.input_dim), self.fprop_dtype))

  def StreamStep(self, theta, inputs, paddings, cached_states):
    """inputs [B, C, D] -> (out [B, C, D], new states); equals causal FProp
    consumed chunk by chunk (offline==streaming equivalence)."""
    p = self.p
    th = self.CastTheta(theta)
    x = self.ln.FProp(theta.ln, inputs)
    gated = jnp.einsum("btd,de->bte", x, self.QWeight(theta, th.pw_in))
    a, b_ = jnp.split(gated, 2, axis=-1)
    x = a * jax.nn.sigmoid(b_)  # GLU
    if paddings is not None:
      x = py_utils.ApplyPadding(paddings, x)
    xc = jnp.concatenate(
        [cached_states.conv_input.astype(x.dtype), x], axis=1)
    y = jax.lax.conv_general_dilated(
        xc, self.QWeight(theta, th.dw)[:, None, :], window_strides=(1,),
        padding="VALID", feature_group_count=p.input_dim,
        dimension_numbers=("NHC", "HIO", "NHC"))
    y = self.norm.FProp(theta.norm, y)
    y = jax.nn.silu(y)
    y = jnp.einsum("btd,de->bte", y, self.QWeight(theta, th.pw_out))
    if paddings is not None:
      y = py_utils.ApplyPadding(paddings, y)
    c = inputs.shape[1]
    new_states = NestedMap(conv_input=xc[:, c:])
    return inputs + y, new_states


class ConformerLayer(base_layer.BaseLayer):
  """Macaron conformer block (ref ConformerLayer:471)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Model dim.")
    p.Define("atten_num_heads", 4, "Heads.")
    p.Define("ffn_hidden_dim", 0, "FFN dim (0 = 4x input).")
    p.Define("kernel_size", 32, "LConv kernel.")
    p.Define("causal", False, "Streaming-friendly (causal conv + local "
             "attention window).")
    p.Define("atten_left_context", 0,
             "If >0 use LocalSelfAttention with this left context.")
    p.Define("atten_right_context", 0, "Right context for local attention.")
    p.Define("dropout_prob", 0.0, "Residual dropout.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    d = p.input_dim
    h = p.ffn_hidden_dim or 4 * d
    ffn = transformer_lib.TransformerFeedForwardLayer.Params().Set(
        input_dim=d, hidden_dim=h, activation="SILU",
        residual_dropout_prob=p.dropout_prob, add_skip_connection=False)
    self.CreateChild("ffn_start", ffn.Copy())
    self.CreateChild("ffn_end", ffn.Copy())
    if p.atten_left_context > 0:
      # block must satisfy left_context <= block+1 and right_context <= block
      block = max(p.atten_left_context - 1, p.atten_right_context, 1)
      atten = attention_lib.LocalSelfAttention.Params().Set(
          block_size=block,
          left_context=p.atten_left_context,
          right_context=p.atten_right_context)
    else:
      atten = attention_lib.MultiHeadedAttention.Params()
    self.CreateChild(
        "atten_ln", layers_lib.LayerNorm.Params().Set(input_dim=d))
    self.CreateChild(
        "atten",
        atten.Set(input_dim=d, hidden_dim=d, num_heads=p.atten_num_heads,
                  atten_dropout_prob=p.dropout_prob,
                  use_rotary_position_emb=True))
    self.CreateChild(
        "lconv",
        LConvLayer.Params().Set(
            input_dim=d, kernel_size=p.kernel_size, causal=p.causal,
            # BatchNorm pools statistics over time => future leaks into the
            # past; streaming mode must use LayerNorm on the conv branch.
            conv_norm="ln" if p.causal else "bn"))
    self.CreateChild(
        "final_ln", layers_lib.LayerNorm.Params().Set(input_dim=d))

  def FProp(self, theta, inputs, paddings=None):
    x = inputs + 0.5 * self.ffn_start.FProp(theta.ffn_start, inputs, paddings)
    a = self.atten_ln.FProp(theta.atten_ln, x)
    mask = None
    if self.p.causal and self.p.atten_left_context <= 0:
      mask = attention_lib.CausalMask(x.shape[1])
    atten_out, _ = self.atten.FProp(theta.atten, a, paddings=paddings,
                                    atten_mask=mask)
    x = x + atten_out
    x = self.lconv.FProp(theta.lconv, x, paddings)
    x = x + 0.5 * self.ffn_end.FProp(theta.ffn_end, x, paddings)
    x = self.final_ln.FProp(theta.final_ln, x)
    if paddings is not None:
      x = py_utils.ApplyPadding(paddings, x)
    return x

  # -- chunk streaming (ref conformer StreamStep + stream_step_test_base) ----

  def InitStreamStates(self, batch_size: int) -> NestedMap:
    p = self.p
    assert p.causal and p.atten_left_context > 0, (
        "streaming conformer requires causal=True and a finite "
        "atten_left_context window")
    assert p.atten_right_context == 0, (
        "streaming is strictly causal; atten_right_context > 0 would "
        "silently diverge from offline FProp")
    return NestedMap(
        atten=self.atten.InitStreamStates(batch_size, p.atten_left_context),
        lconv=self.lconv.InitStreamStates(batch_size))

  def StreamStep(self, theta, inputs, paddings, cached_states):
    """inputs [B, C, D] -> (out [B, C, D], new states); equals offline FProp
    (causal + windowed attention) consumed chunk by chunk."""
    x = inputs + 0.5 * self.ffn_start.FProp(theta.ffn_start, inputs, paddings)
    a = self.atten_ln.FProp(theta.atten_ln, x)
    atten_out, new_atten = self.atten.StreamStep(theta.atten, a, paddings,
                                                 cached_states.atten)
    x = x + atten_out
    x, new_lconv = self.lconv.StreamStep(theta.lconv, x, paddings,
                                         cached_states.lconv)
    x = x + 0.5 * self.ffn_end.FProp(theta.ffn_end, x, paddings)
    x = self.final_ln.FProp(theta.final_ln, x)
    if paddings is not None:
      x = py_utils.ApplyPadding(paddings, x)
    return x, NestedMap(atten=new_atten, lconv=new_lconv)
