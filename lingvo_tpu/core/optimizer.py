"""Optimizers as pure update rules over theta pytrees.

Capability parity with the reference's `lingvo/core/optimizer.py` (SGD:336,
Momentum:346, RMSProp:368, Adagrad:390, Adam:436, Accumulator:507,
CompositeOptimizer:199, XLAShardingAdafactor:905-1275) — but each optimizer is
a pure `(state, grads, params, lr) -> (new_params, new_state)` function, so it
jits and shards under GSPMD with no special casing. The Adafactor here keeps
the reference's factored-second-moment math (row/col accumulators, update
clipping, decay schedule) and its state inherits each weight's mesh sharding
on the corresponding dims — the TPU-native equivalent of the reference's
per-var sharded slots.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap


def _TreeMap(fn, *trees):
  return jax.tree_util.tree_map(fn, *trees)


class BaseOptimizer(base_layer.BaseLayer):
  """Interface: InitState(params) -> state; Update(...) -> (params, state)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("add_summary_in_apply", True, "Emit lr summary (via learner).")
    return p

  def _NameIsRequired(self):
    return False

  def InitState(self, params: NestedMap) -> NestedMap:
    return NestedMap()

  def Update(self, state: NestedMap, grads: NestedMap, params: NestedMap,
             lr, step) -> tuple[NestedMap, NestedMap]:
    raise NotImplementedError


class SGD(BaseOptimizer):

  def Update(self, state, grads, params, lr, step):
    new_params = _TreeMap(lambda p, g: p - lr * g.astype(p.dtype), params,
                          grads)
    return new_params, state


class Momentum(BaseOptimizer):

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("momentum", 0.9, "Momentum coefficient.")
    p.Define("use_nesterov", False, "Nesterov variant.")
    return p

  def InitState(self, params):
    return NestedMap(m=_TreeMap(jnp.zeros_like, params))

  def Update(self, state, grads, params, lr, step):
    p = self.p
    new_m = _TreeMap(lambda m, g: p.momentum * m + g, state.m, grads)
    if p.use_nesterov:
      upd = _TreeMap(lambda m, g: p.momentum * m + g, new_m, grads)
    else:
      upd = new_m
    new_params = _TreeMap(lambda w, u: w - lr * u.astype(w.dtype), params, upd)
    return new_params, NestedMap(m=new_m)


class RMSProp(BaseOptimizer):

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("decay", 0.9, "Decay of the moving second moment.")
    p.Define("momentum", 0.0, "Optional momentum.")
    p.Define("epsilon", 1.0, "Stability term (ref default 1.0).")
    return p

  def InitState(self, params):
    return NestedMap(
        ms=_TreeMap(jnp.ones_like, params),
        mom=_TreeMap(jnp.zeros_like, params))

  def Update(self, state, grads, params, lr, step):
    p = self.p
    new_ms = _TreeMap(
        lambda ms, g: p.decay * ms + (1 - p.decay) * jnp.square(g), state.ms,
        grads)
    new_mom = _TreeMap(
        lambda mom, ms, g: p.momentum * mom + lr * g * jax.lax.rsqrt(
            ms + p.epsilon), state.mom, new_ms, grads)
    new_params = _TreeMap(lambda w, m: w - m.astype(w.dtype), params, new_mom)
    return new_params, NestedMap(ms=new_ms, mom=new_mom)


class Adagrad(BaseOptimizer):

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("initial_accumulator_value", 0.1, "Initial accumulator.")
    return p

  def InitState(self, params):
    return NestedMap(acc=_TreeMap(
        lambda x: jnp.full_like(x, self.p.initial_accumulator_value), params))

  def Update(self, state, grads, params, lr, step):
    new_acc = _TreeMap(lambda a, g: a + jnp.square(g), state.acc, grads)
    new_params = _TreeMap(
        lambda w, g, a: w - (lr * g * jax.lax.rsqrt(a + 1e-30)).astype(w.dtype),
        params, grads, new_acc)
    return new_params, NestedMap(acc=new_acc)


class Adam(BaseOptimizer):

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("beta1", 0.9, "First-moment decay.")
    p.Define("beta2", 0.999, "Second-moment decay.")
    p.Define("epsilon", 1e-6, "Stability term (ref default 1e-6).")
    return p

  def InitState(self, params):
    return NestedMap(
        m=_TreeMap(jnp.zeros_like, params),
        v=_TreeMap(jnp.zeros_like, params))

  def Update(self, state, grads, params, lr, step):
    p = self.p
    t = (jnp.asarray(step, jnp.float32) + 1.0)
    new_m = _TreeMap(lambda m, g: p.beta1 * m + (1 - p.beta1) * g, state.m,
                     grads)
    new_v = _TreeMap(lambda v, g: p.beta2 * v + (1 - p.beta2) * jnp.square(g),
                     state.v, grads)
    correction = jnp.sqrt(1.0 - p.beta2**t) / (1.0 - p.beta1**t)
    new_params = _TreeMap(
        lambda w, m, v: w - (lr * correction * m /
                             (jnp.sqrt(v) + p.epsilon)).astype(w.dtype),
        params, new_m, new_v)
    return new_params, NestedMap(m=new_m, v=new_v)


class AdamW(Adam):
  """Adam with decoupled weight decay."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("weight_decay", 0.0, "Decoupled weight decay rate.")
    return p

  def Update(self, state, grads, params, lr, step):
    new_params, new_state = super().Update(state, grads, params, lr, step)
    wd = self.p.weight_decay
    if wd:
      new_params = _TreeMap(lambda nw, w: nw - lr * wd * w, new_params, params)
    return new_params, new_state


class Adafactor(BaseOptimizer):
  """Sharding-aware Adafactor (ref `XLAShardingAdafactor`, optimizer.py:905).

  Factored second moments for rank>=2 weights (row accumulator over the last
  dim, col accumulator over the second-to-last), update RMS clipping, and the
  pow-decay schedule. State tensors are reduced forms of the weight, so under
  GSPMD they shard wherever the weight shards — no extra annotation needed for
  the factored slots.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("beta1", 0.0, "If >0 keep a first moment (uses more memory).")
    p.Define("decay_adam", 0.99, "Second-moment decay asymptote.")
    p.Define("decay_pow", 0.8, "decay = 1 - (step+1)^-decay_pow if >0.")
    p.Define("epsilon1", 1e-30, "Grad^2 regularizer.")
    p.Define("epsilon2", 1e-3, "RMS-of-param floor for update scale.")
    p.Define("multiply_by_parameter_scale", True,
             "Scale updates by RMS(param) (Adafactor's LR-free mode).")
    p.Define("clipping_threshold", 1.0, "Update RMS clip.")
    p.Define("factored", True, "Use factored second moments for rank>=2.")
    p.Define("min_dim_size_to_factor", 128,
             "Only factor when both factored dims are at least this size.")
    return p

  def _ShouldFactor(self, shape):
    p = self.p
    return (p.factored and len(shape) >= 2 and
            shape[-1] >= p.min_dim_size_to_factor and
            shape[-2] >= p.min_dim_size_to_factor)

  def InitState(self, params):
    p = self.p

    def _Slot(w):
      slot = NestedMap()
      if self._ShouldFactor(w.shape):
        slot.vr = jnp.zeros(w.shape[:-1], jnp.float32)   # reduce last dim
        slot.vc = jnp.zeros(w.shape[:-2] + w.shape[-1:], jnp.float32)
      else:
        slot.v = jnp.zeros(w.shape, jnp.float32)
      if p.beta1 > 0:
        slot.m = jnp.zeros(w.shape, jnp.float32)
      return slot

    return NestedMap(slots=jax.tree_util.tree_map(_Slot, params))

  def Update(self, state, grads, params, lr, step):
    p = self.p
    t = jnp.asarray(step, jnp.float32) + 1.0
    if p.decay_pow > 0:
      decay = 1.0 - t**(-p.decay_pow)
    else:
      decay = p.decay_adam
    decay = jnp.minimum(decay, p.decay_adam)

    def _Upd(w, g, slot):
      g32 = g.astype(jnp.float32)
      gsq = jnp.square(g32) + p.epsilon1
      new_slot = NestedMap()
      if self._ShouldFactor(w.shape):
        vr = decay * slot.vr + (1 - decay) * jnp.mean(gsq, axis=-1)
        vc = decay * slot.vc + (1 - decay) * jnp.mean(gsq, axis=-2)
        new_slot.vr, new_slot.vc = vr, vc
        # u = g / sqrt(vhat); vhat = vr*vc / mean_row(vr)
        row_mean = jnp.mean(vr, axis=-1, keepdims=True)
        r = jax.lax.rsqrt(vr / row_mean)[..., None]
        c = jax.lax.rsqrt(vc)[..., None, :]
        u = g32 * r * c
      else:
        v = decay * slot.v + (1 - decay) * gsq
        new_slot.v = v
        u = g32 * jax.lax.rsqrt(v)
      if p.clipping_threshold > 0:
        u_rms = jnp.sqrt(jnp.mean(jnp.square(u)) + 1e-30)
        u = u / jnp.maximum(1.0, u_rms / p.clipping_threshold)
      scale = lr
      if p.multiply_by_parameter_scale:
        param_rms = jnp.sqrt(jnp.mean(jnp.square(w.astype(jnp.float32))))
        scale = lr * jnp.maximum(param_rms, p.epsilon2)
      if p.beta1 > 0:
        m = p.beta1 * slot.m + (1 - p.beta1) * u
        new_slot.m = m
        u = m
      new_w = w - (scale * u).astype(w.dtype)
      return new_w, new_slot

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = jax.tree_util.tree_leaves(grads)
    flat_s = treedef.flatten_up_to(state.slots)
    out = [_Upd(w, g, s) for w, g, s in zip(flat_p, flat_g, flat_s)]
    new_params = jax.tree_util.tree_unflatten(treedef, [o[0] for o in out])
    new_slots = jax.tree_util.tree_unflatten(treedef, [o[1] for o in out])
    return new_params, NestedMap(slots=new_slots)


class Accumulator(BaseOptimizer):
  """Gradient accumulation wrapper (ref optimizer.Accumulator:507)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("optimizer_tpl", Adam.Params(), "Inner optimizer.")
    p.Define("accum_steps", 1, "Number of micro-steps per real update.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self.CreateChild("opt", self.p.optimizer_tpl)

  def InitState(self, params):
    return NestedMap(
        inner=self.opt.InitState(params),
        accum=_TreeMap(jnp.zeros_like, params),
        count=jnp.zeros((), jnp.int32))

  def Update(self, state, grads, params, lr, step):
    p = self.p
    accum = _TreeMap(lambda a, g: a + g, state.accum, grads)
    count = state.count + 1
    do_apply = count >= p.accum_steps

    mean_grads = _TreeMap(lambda a: a / p.accum_steps, accum)
    applied_params, applied_inner = self.opt.Update(state.inner, mean_grads,
                                                    params, lr, step)
    new_params = _TreeMap(
        lambda ap, w: jnp.where(do_apply, ap, w), applied_params, params)
    new_inner = _TreeMap(
        lambda ni, oi: jnp.where(do_apply, ni, oi), applied_inner, state.inner)
    new_accum = _TreeMap(
        lambda a: jnp.where(do_apply, jnp.zeros_like(a), a), accum)
    new_count = jnp.where(do_apply, 0, count)
    return new_params, NestedMap(
        inner=new_inner, accum=new_accum, count=new_count)


class CompositeOptimizer(BaseOptimizer):
  """Regex -> sub-optimizer routing (ref optimizer.CompositeOptimizer:199).

  Routing is resolved at trace time from theta paths (static), so the compiled
  program contains exactly one update rule per variable.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("optimizer_map", [],
             "List of (regex, optimizer Params, lr multiplier). First match "
             "wins; a '.*' default entry is required.")
    return p

  def __init__(self, params):
    super().__init__(params)
    subs = [tpl for _, tpl, _ in self.p.optimizer_map]
    self.CreateChildren("subs", subs)

  def _RouteIndex(self, path: str) -> int:
    import re
    for i, (regex, _, _) in enumerate(self.p.optimizer_map):
      if re.match(regex, path):
        return i
    raise ValueError(f"No optimizer_map entry matches {path!r}")

  def InitState(self, params):
    # Each sub-optimizer gets full-tree state; unused slots are pruned by
    # masking grads to the routed subset at Update time. Simpler and correct,
    # at the cost of memory for non-routed slots only when state is nonzero.
    items = params.FlattenItems() if isinstance(params, NestedMap) else []
    routes = {k: self._RouteIndex(k) for k, _ in items}
    self._routes = routes
    return NestedMap(
        subs=[opt.InitState(params) for opt in self.subs])

  def Update(self, state, grads, params, lr, step):
    if not hasattr(self, "_routes"):
      self._routes = {
          k: self._RouteIndex(k) for k, _ in params.FlattenItems()
      }
    new_params = params
    new_states = []
    for i, opt in enumerate(self.subs):
      mult = self.p.optimizer_map[i][2]
      masked = params.TransformWithKey(
          lambda k, v, i=i: grads.GetItem(k)
          if self._routes.get(k) == i else jnp.zeros_like(v))
      upd_params, upd_state = opt.Update(state.subs[i], masked, new_params,
                                         lr * mult, step)
      new_params = new_params.TransformWithKey(
          lambda k, v, i=i: upd_params.GetItem(k)
          if self._routes.get(k) == i else v)
      new_states.append(upd_state)
    return new_params, NestedMap(subs=new_states)


class DistributedShampoo(BaseOptimizer):
  """Shampoo with factored Kronecker preconditioners (ref
  `optimizer.py:689` DistributedShampoo + `distributed_shampoo.py`).

  For each matrix-shaped weight [m, n] (with m, n <= block limit):
    L += G G^T ; R += G^T G ; update = L^{-1/4} G R^{-1/4}
  computed via eigendecompositions refreshed every
  `statistics_compute_steps` (the reference computes inverse roots out of
  band in the preconditioner service; here lax.cond-gated eigh on device —
  no service needed). Non-matrix or oversized weights fall back to
  diagonal AdaGrad, matching the reference's fallback. Grafting to the
  AdaGrad magnitude keeps the step size comparable (ref graft option).
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("block_size", 1024, "Max dim preconditioned (bigger: diag).")
    p.Define("statistics_compute_steps", 10,
             "Refresh the inverse roots every N steps.")
    p.Define("epsilon", 1e-6, "Damping added to the factor diagonals.")
    p.Define("beta2", 1.0, "Statistics decay (1.0 = accumulate, ref).")
    p.Define("graft_epsilon", 1e-8, "AdaGrad graft stability.")
    return p

  def _Preconditioned(self, w):
    p = self.p
    return (w.ndim == 2 and w.shape[0] <= p.block_size
            and w.shape[1] <= p.block_size)

  def InitState(self, params):
    p = self.p

    def _Stat(side):
      def _One(w):
        if self._Preconditioned(w):
          n = w.shape[0 if side == "l" else 1]
          return jnp.zeros((n, n), jnp.float32)
        return jnp.zeros((), jnp.float32)  # placeholder
      return _One

    def _Root(side):
      def _One(w):
        if self._Preconditioned(w):
          n = w.shape[0 if side == "l" else 1]
          return jnp.eye(n, dtype=jnp.float32)
        return jnp.zeros((), jnp.float32)
      return _One

    return NestedMap(
        stat_l=_TreeMap(_Stat("l"), params),
        stat_r=_TreeMap(_Stat("r"), params),
        root_l=_TreeMap(_Root("l"), params),
        root_r=_TreeMap(_Root("r"), params),
        accum=_TreeMap(jnp.zeros_like, params))  # diagonal AdaGrad

  def _InverseQuarterRoot(self, stat):
    """(stat/trace-normalized + eps I)^{-1/4} via eigh (f32)."""
    p = self.p
    n = stat.shape[0]
    damped = stat + p.epsilon * jnp.eye(n, dtype=stat.dtype)
    evals, evecs = jnp.linalg.eigh(damped)
    inv_root = jnp.power(jnp.maximum(evals, p.epsilon), -0.25)
    return (evecs * inv_root[None, :]) @ evecs.T

  def Update(self, state, grads, params, lr, step):
    p = self.p
    step = jnp.asarray(step, jnp.int32)
    refresh = (step % p.statistics_compute_steps) == 0

    new_accum = _TreeMap(lambda a, g: a + jnp.square(g.astype(a.dtype)),
                         state.accum, grads)

    def _UpdateOne(w, g, sl, sr, rl, rr, accum):
      g32 = g.astype(jnp.float32)
      # diagonal AdaGrad magnitude (graft target / fallback)
      adagrad_dir = g32 / (jnp.sqrt(accum) + p.graft_epsilon)
      if not self._Preconditioned(w):
        return (w - (lr * adagrad_dir).astype(w.dtype), sl, sr, rl, rr)
      new_sl = p.beta2 * sl + g32 @ g32.T
      new_sr = p.beta2 * sr + g32.T @ g32
      new_rl = jax.lax.cond(refresh,
                            lambda s: self._InverseQuarterRoot(s),
                            lambda s: rl, new_sl)
      new_rr = jax.lax.cond(refresh,
                            lambda s: self._InverseQuarterRoot(s),
                            lambda s: rr, new_sr)
      precond = new_rl @ g32 @ new_rr
      # graft: give the Shampoo DIRECTION the AdaGrad step NORM
      pn = jnp.maximum(jnp.linalg.norm(precond), 1e-16)
      an = jnp.linalg.norm(adagrad_dir)
      update = precond * (an / pn)
      return (w - (lr * update).astype(w.dtype), new_sl, new_sr, new_rl,
              new_rr)

    results = jax.tree_util.tree_map(
        _UpdateOne, params, grads, state.stat_l, state.stat_r, state.root_l,
        state.root_r, new_accum,
        is_leaf=lambda x: isinstance(x, jax.Array) or hasattr(x, "ndim"))
    # unzip the per-leaf tuples back into parallel trees
    new_params = jax.tree_util.tree_map(
        lambda t: t[0], results, is_leaf=lambda x: isinstance(x, tuple))
    new_state = NestedMap(
        stat_l=jax.tree_util.tree_map(lambda t: t[1], results,
                                      is_leaf=lambda x: isinstance(x, tuple)),
        stat_r=jax.tree_util.tree_map(lambda t: t[2], results,
                                      is_leaf=lambda x: isinstance(x, tuple)),
        root_l=jax.tree_util.tree_map(lambda t: t[3], results,
                                      is_leaf=lambda x: isinstance(x, tuple)),
        root_r=jax.tree_util.tree_map(lambda t: t[4], results,
                                      is_leaf=lambda x: isinstance(x, tuple)),
        accum=new_accum)
    return new_params, new_state


class EGDD(BaseOptimizer):
  """Exponentiated Gradient Delta-Delta: momentum with per-weight adaptive
  gain and a per-tensor adaptive lr scale (ref `egdd.py:29`).

  momentum <- mu * momentum + lr * gain * grad
  w        <- w - lr_scale * momentum
  with gain/lr_scale updated by unnormalized exponentiated gradient [KW97]:
  gain by sign agreement between grad and its EMA (gbar); lr_scale by the
  inner product of the (normalized) grad and previous momentum.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("momentum", 0.9, "Momentum coefficient (mu).")
    p.Define("beta", 0.9, "Decay of the gradient EMA (gbar).")
    p.Define("gain_learning_rate", 0.01, "EG step on per-weight gains.")
    p.Define("scale_learning_rate", 0.001, "EG step on per-tensor lr scale.")
    p.Define("initial_gain", 1.0, "Initial per-weight gain.")
    p.Define("min_gain", 1e-2, "Gain lower clip.")
    p.Define("max_gain", 1e2, "Gain upper clip.")
    p.Define("initial_scale", 1.0, "Initial lr scale.")
    p.Define("min_scale", 1e-1, "lr scale lower clip.")
    p.Define("max_scale", 1e1, "lr scale upper clip.")
    p.Define("use_directions", True,
             "lr-scale update from normalized grad/momentum directions.")
    p.Define("use_signs", True,
             "Gain update from sign(grad)*sign(gbar) instead of magnitudes.")
    return p

  def InitState(self, params):
    # All slots in f32 regardless of param dtype: the EG exponent math needs
    # the precision, and a stable state dtype keeps lax.scan carries and
    # donated buffers happy when params are bf16.
    p = self.p
    f32 = lambda x: jnp.zeros(x.shape, jnp.float32)
    return NestedMap(
        m=_TreeMap(f32, params),
        gbar=_TreeMap(f32, params),
        gain=_TreeMap(
            lambda x: jnp.full(x.shape, p.initial_gain, jnp.float32), params),
        lr_scale=_TreeMap(
            lambda x: jnp.asarray(p.initial_scale, jnp.float32), params))

  def Update(self, state, grads, params, lr, step):
    p = self.p
    t = jnp.maximum(jnp.asarray(step, jnp.float32), 1.0)

    def _One(w, g, m, gbar, gain, lr_scale):
      g = g.astype(jnp.float32)
      m32 = m.astype(jnp.float32)
      if p.use_directions:
        gn = g / (jnp.linalg.norm(g) + 1e-10)
        mn = m32 / (jnp.linalg.norm(m32) + 1e-10)
        inner = jnp.sum(gn * mn)
      else:
        inner = jnp.sum(g * m32)
      new_scale = jnp.clip(
          lr_scale * jnp.exp(p.scale_learning_rate * inner), p.min_scale,
          p.max_scale)
      corrected_gbar = gbar / (1.0 - p.beta ** jnp.maximum(t - 1.0, 1.0))
      if p.use_signs:
        gain_grad = jnp.sign(g) * jnp.sign(gbar)
      else:
        gain_grad = g * corrected_gbar
      new_gain = jnp.clip(gain * jnp.exp(p.gain_learning_rate * gain_grad),
                          p.min_gain, p.max_gain)
      new_m = p.momentum * m32 + lr * new_gain * g
      new_gbar = p.beta * gbar + (1.0 - p.beta) * g
      new_w = w - (new_scale * new_m).astype(w.dtype)
      return new_w, new_m, new_gbar, new_gain, new_scale

    outs = _TreeMap(_One, params, grads, state.m, state.gbar, state.gain,
                    state.lr_scale)
    # outs is a tree of 5-tuples at the leaves; split into five trees
    def _Pick(i):
      return jax.tree_util.tree_map(
          lambda tup: tup[i], outs,
          is_leaf=lambda x: isinstance(x, tuple) and len(x) == 5)

    return _Pick(0), NestedMap(m=_Pick(1), gbar=_Pick(2), gain=_Pick(3),
                               lr_scale=_Pick(4))


class AdaGraft(BaseOptimizer):
  """Grafts one optimizer's step MAGNITUDE onto another's DIRECTION
  (ref `optimizer.py:803` AdaGraft / the adagraft.py paper recipe):
  per-tensor, update = |delta_M| * delta_D / |delta_D|."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("magnitude_optimizer", None, "Optimizer supplying step size.")
    p.Define("direction_optimizer", None, "Optimizer supplying direction.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.magnitude_optimizer is not None
    assert p.direction_optimizer is not None
    self.CreateChild("mag", p.magnitude_optimizer)
    self.CreateChild("dir", p.direction_optimizer)

  def InitState(self, params):
    return NestedMap(mag=self.mag.InitState(params),
                     dir=self.dir.InitState(params))

  def Update(self, state, grads, params, lr, step):
    mag_params, mag_state = self.mag.Update(state.mag, grads, params, lr,
                                            step)
    dir_params, dir_state = self.dir.Update(state.dir, grads, params, lr,
                                            step)

    def _Graft(w, wm, wd):
      dm = (wm - w).astype(jnp.float32)
      dd = (wd - w).astype(jnp.float32)
      dd_norm = jnp.maximum(jnp.linalg.norm(dd), 1e-16)
      step_len = jnp.linalg.norm(dm)
      return (w + (step_len * dd / dd_norm).astype(w.dtype))

    new_params = _TreeMap(_Graft, params, mag_params, dir_params)
    return new_params, NestedMap(mag=mag_state, dir=dir_state)
