"""SentencePiece model inference without the `sentencepiece` library.

The reference wraps the SentencePiece C++ library for its spm tokenizers
(`lingvo/core/tokenizers.py` SentencePieceTokenizer, `gshard_utils.LoadSpm`
at `gshard_utils.py:448`). That library is not available in this image, so
this module implements the inference half from scratch:

  * a minimal protobuf wire-format parser for `sentencepiece_model.proto`
    (ModelProto → pieces [piece, score, type], TrainerSpec model_type and
    unk/bos/eos/pad ids, NormalizerSpec whitespace options);
  * unigram-LM segmentation via Viterbi over a piece dictionary;
  * BPE segmentation via the standard best-scoring-adjacent-merge loop;
  * byte-fallback (`<0xXX>` pieces) for out-of-vocab characters;
  * decoding back to text (▁ → space, byte pieces → utf-8);
  * a writer + tiny unigram trainer so tests and `tools/build_vocab.py`
    can produce real `.model` files.

Only inference-quality parity is targeted (same segmentation rules), not
training parity (no EM pruning, no precompiled normalizer charsmap — text
is assumed already unicode-normalized).
"""

from __future__ import annotations

import collections
import math
import struct
from typing import Dict, List, Sequence, Tuple

_WS = "▁"  # ▁ (LOWER ONE EIGHTH BLOCK), sentencepiece whitespace marker

# SentencePiece.Type enum values (sentencepiece_model.proto).
NORMAL = 1
UNKNOWN = 2
CONTROL = 3
USER_DEFINED = 4
UNUSED = 5
BYTE = 6

# TrainerSpec.ModelType enum values.
UNIGRAM = 1
BPE = 2
WORD = 3
CHAR = 4


# ---------------------------------------------------------------------------
# Protobuf wire format (just enough for sentencepiece_model.proto)
# ---------------------------------------------------------------------------


def _ReadVarint(buf: bytes, pos: int) -> Tuple[int, int]:
  result = 0
  shift = 0
  while True:
    b = buf[pos]
    pos += 1
    result |= (b & 0x7F) << shift
    if not b & 0x80:
      return result, pos
    shift += 7
    if shift > 63:
      raise ValueError("varint too long (corrupt model file)")


def _IterFields(buf: bytes):
  """Yields (field_number, wire_type, value) over a serialized message.

  wire types: 0 varint (value int), 1 fixed64 (bytes), 2 length-delimited
  (bytes), 5 fixed32 (bytes). Groups (3/4) are rejected.
  """
  pos = 0
  n = len(buf)
  while pos < n:
    key, pos = _ReadVarint(buf, pos)
    field, wire = key >> 3, key & 7
    if wire == 0:
      val, pos = _ReadVarint(buf, pos)
    elif wire == 1:
      val, pos = buf[pos:pos + 8], pos + 8
    elif wire == 2:
      ln, pos = _ReadVarint(buf, pos)
      val, pos = buf[pos:pos + ln], pos + ln
    elif wire == 5:
      val, pos = buf[pos:pos + 4], pos + 4
    else:
      raise ValueError(f"unsupported wire type {wire} (corrupt model file)")
    yield field, wire, val


def _Varint(v: int) -> bytes:
  out = bytearray()
  while True:
    b = v & 0x7F
    v >>= 7
    if v:
      out.append(b | 0x80)
    else:
      out.append(b)
      return bytes(out)


def _Key(field: int, wire: int) -> bytes:
  return _Varint((field << 3) | wire)


def _LenDelim(field: int, payload: bytes) -> bytes:
  return _Key(field, 2) + _Varint(len(payload)) + payload


# ---------------------------------------------------------------------------
# Model
# ---------------------------------------------------------------------------


class SentencePieceModel:
  """Parsed .model file + encode/decode.

  Attributes:
    pieces: list of (piece_str, score, type).
    model_type: UNIGRAM or BPE (WORD/CHAR degenerate to whole-word/char).
    unk_id / bos_id / eos_id / pad_id: special ids from TrainerSpec.
  """

  def __init__(self, pieces: List[Tuple[str, float, int]],
               model_type: int = UNIGRAM, unk_id: int = 0, bos_id: int = 1,
               eos_id: int = 2, pad_id: int = -1, add_dummy_prefix: bool = True,
               remove_extra_whitespaces: bool = True,
               escape_whitespaces: bool = True):
    self.pieces = pieces
    self.model_type = model_type
    self.unk_id = unk_id
    self.bos_id = bos_id
    self.eos_id = eos_id
    self.pad_id = pad_id
    self.add_dummy_prefix = add_dummy_prefix
    self.remove_extra_whitespaces = remove_extra_whitespaces
    self.escape_whitespaces = escape_whitespaces

    self._piece_to_id: Dict[str, int] = {}
    self._byte_ids: Dict[int, int] = {}
    self._max_piece_len = 1
    scores = []
    for i, (piece, score, typ) in enumerate(pieces):
      if typ == BYTE:
        try:
          self._byte_ids[int(piece[1:-1], 16)] = i  # "<0xAB>"
        except ValueError:
          pass
      if typ in (NORMAL, USER_DEFINED, BYTE):
        self._piece_to_id.setdefault(piece, i)
        self._max_piece_len = max(self._max_piece_len, len(piece))
        scores.append(score)
    # OOV single characters score worse than any real piece (the library's
    # unk penalty: min_score - 10).
    self._unk_score = (min(scores) if scores else 0.0) - 10.0

  @property
  def vocab_size(self) -> int:
    return len(self.pieces)

  # -- parse / serialize ----------------------------------------------------

  @classmethod
  def FromFile(cls, path: str) -> "SentencePieceModel":
    with open(path, "rb") as f:
      return cls.FromBytes(f.read())

  @classmethod
  def FromBytes(cls, buf: bytes) -> "SentencePieceModel":
    pieces: List[Tuple[str, float, int]] = []
    kwargs = {}
    for field, wire, val in _IterFields(buf):
      if field == 1 and wire == 2:  # repeated SentencePiece pieces
        piece, score, typ = "", 0.0, NORMAL
        for f2, w2, v2 in _IterFields(val):
          if f2 == 1 and w2 == 2:
            piece = v2.decode("utf-8")
          elif f2 == 2 and w2 == 5:
            score = struct.unpack("<f", v2)[0]
          elif f2 == 3 and w2 == 0:
            typ = v2
        pieces.append((piece, score, typ))
      elif field == 2 and wire == 2:  # TrainerSpec
        for f2, w2, v2 in _IterFields(val):
          if w2 != 0:
            continue
          if f2 == 3:
            kwargs["model_type"] = v2
          elif f2 == 40:
            kwargs["unk_id"] = _ToSigned(v2)
          elif f2 == 41:
            kwargs["bos_id"] = _ToSigned(v2)
          elif f2 == 42:
            kwargs["eos_id"] = _ToSigned(v2)
          elif f2 == 43:
            kwargs["pad_id"] = _ToSigned(v2)
      elif field == 3 and wire == 2:  # NormalizerSpec
        for f2, w2, v2 in _IterFields(val):
          if w2 != 0:
            continue
          if f2 == 3:
            kwargs["add_dummy_prefix"] = bool(v2)
          elif f2 == 4:
            kwargs["remove_extra_whitespaces"] = bool(v2)
          elif f2 == 5:
            kwargs["escape_whitespaces"] = bool(v2)
    return cls(pieces, **kwargs)

  def ToBytes(self) -> bytes:
    out = bytearray()
    for piece, score, typ in self.pieces:
      body = _LenDelim(1, piece.encode("utf-8"))
      body += _Key(2, 5) + struct.pack("<f", score)
      body += _Key(3, 0) + _Varint(typ)
      out += _LenDelim(1, bytes(body))
    trainer = (_Key(3, 0) + _Varint(self.model_type)
               + _Key(40, 0) + _FromSigned(self.unk_id)
               + _Key(41, 0) + _FromSigned(self.bos_id)
               + _Key(42, 0) + _FromSigned(self.eos_id)
               + _Key(43, 0) + _FromSigned(self.pad_id))
    out += _LenDelim(2, trainer)
    norm = (_Key(3, 0) + _Varint(int(self.add_dummy_prefix))
            + _Key(4, 0) + _Varint(int(self.remove_extra_whitespaces))
            + _Key(5, 0) + _Varint(int(self.escape_whitespaces)))
    out += _LenDelim(3, norm)
    return bytes(out)

  def Save(self, path: str) -> None:
    with open(path, "wb") as f:
      f.write(self.ToBytes())

  # -- encode ---------------------------------------------------------------

  def _Normalize(self, text: str) -> str:
    if self.remove_extra_whitespaces:
      text = " ".join(text.split())
    if self.add_dummy_prefix:
      text = " " + text
    if self.escape_whitespaces:
      text = text.replace(" ", _WS)
    return text

  def EncodeAsIds(self, text: str) -> List[int]:
    return [pid for _, pid in self._Segment(self._Normalize(text))]

  def EncodeAsPieces(self, text: str) -> List[str]:
    return [s for s, _ in self._Segment(self._Normalize(text))]

  def _Segment(self, text: str) -> List[Tuple[str, int]]:
    if not text:
      return []
    if self.model_type == BPE:
      return self._SegmentBpe(text)
    if self.model_type == CHAR:
      return [self._LookupOrUnk(c) for c in text]
    if self.model_type == WORD:
      return [self._LookupOrUnk(w) for w in text.split(_WS) if w]
    return self._SegmentUnigram(text)

  def _LookupOrUnk(self, piece: str) -> Tuple[str, int]:
    pid = self._piece_to_id.get(piece)
    if pid is not None:
      return piece, pid
    return piece, self.unk_id

  def _ByteFallback(self, ch: str) -> List[Tuple[str, int]]:
    if not self._byte_ids:
      return [(ch, self.unk_id)]
    out = []
    for b in ch.encode("utf-8"):
      bid = self._byte_ids.get(b)
      out.append((self.pieces[bid][0] if bid is not None else ch,
                  bid if bid is not None else self.unk_id))
    return out

  def _SegmentUnigram(self, text: str) -> List[Tuple[str, int]]:
    """Viterbi best segmentation by summed piece scores (log probs)."""
    n = len(text)
    best = [-math.inf] * (n + 1)
    back: List[Tuple[int, int]] = [(-1, -1)] * (n + 1)  # (start, piece_id)
    best[0] = 0.0
    lookup = self._piece_to_id
    maxlen = self._max_piece_len
    for end in range(1, n + 1):
      for start in range(max(0, end - maxlen), end):
        if best[start] == -math.inf:
          continue
        pid = lookup.get(text[start:end])
        if pid is not None:
          s = best[start] + self.pieces[pid][1]
          if s > best[end]:
            best[end], back[end] = s, (start, pid)
      if best[end] == -math.inf and end >= 1:
        # single-char unk hop keeps the lattice connected
        s = best[end - 1] + self._unk_score
        if s > best[end]:
          best[end], back[end] = s, (end - 1, -1)
    out: List[Tuple[str, int]] = []
    end = n
    while end > 0:
      start, pid = back[end]
      if pid >= 0:
        out.append((text[start:end], pid))
      else:
        out[len(out):] = reversed(self._ByteFallback(text[start:end]))
      end = start
    out.reverse()
    return out

  def _SegmentBpe(self, text: str) -> List[Tuple[str, int]]:
    """Iteratively merge the adjacent pair whose merged piece scores best
    (sentencepiece BPE: scores encode -merge_rank, so max score = earliest
    learned merge)."""
    symbols = list(text)
    while len(symbols) > 1:
      best_score, best_i = -math.inf, -1
      for i in range(len(symbols) - 1):
        pid = self._piece_to_id.get(symbols[i] + symbols[i + 1])
        if pid is not None and self.pieces[pid][1] > best_score:
          best_score, best_i = self.pieces[pid][1], i
      if best_i < 0:
        break
      symbols[best_i:best_i + 2] = [symbols[best_i] + symbols[best_i + 1]]
    out: List[Tuple[str, int]] = []
    for s in symbols:
      pid = self._piece_to_id.get(s)
      if pid is not None:
        out.append((s, pid))
      else:
        out.extend(self._ByteFallback(s))
    return out

  # -- decode ---------------------------------------------------------------

  def DecodeIds(self, ids: Sequence[int]) -> str:
    parts: List[str] = []
    byte_run: List[int] = []

    def _FlushBytes():
      if byte_run:
        parts.append(bytes(byte_run).decode("utf-8", errors="replace"))
        byte_run.clear()

    for i in ids:
      if i < 0 or i >= len(self.pieces):
        continue
      piece, _, typ = self.pieces[i]
      if typ == BYTE:
        byte_run.append(int(piece[1:-1], 16))
        continue
      _FlushBytes()
      if typ in (CONTROL, UNUSED):
        continue
      if typ == UNKNOWN:
        parts.append(" ⁇ ")  # the library renders unk as ⁇
        continue
      parts.append(piece)
    _FlushBytes()
    text = "".join(parts)
    if self.escape_whitespaces:
      text = text.replace(_WS, " ")
    return text.lstrip(" ") if self.add_dummy_prefix else text


def _ToSigned(v: int) -> int:
  return v - (1 << 64) if v >= (1 << 63) else v


def _FromSigned(v: int) -> bytes:
  return _Varint(v + (1 << 64) if v < 0 else v)


# ---------------------------------------------------------------------------
# Tiny trainer (frequency-scored unigram; for tests and build_vocab tool)
# ---------------------------------------------------------------------------


def TrainUnigramModel(texts, vocab_size: int,
                      byte_fallback: bool = False,
                      specials: Sequence[str] = ("<unk>", "<s>", "</s>"),
                      ) -> SentencePieceModel:
  """Builds a usable unigram .model from a corpus.

  Not the library's EM-pruned trainer — pieces are the corpus' characters
  plus its most frequent words/word-prefixes (▁-marked), scored by log
  relative frequency. Good enough to exercise real spm files end-to-end.

  `vocab_size` is a hard cap: specials and byte pieces are budgeted first,
  then characters by frequency, then substrings. `specials` are emitted
  first in order; `<unk>` is typed UNKNOWN, `<pad>`/`<s>`/`</s>` and other
  bracketed tokens CONTROL, and unk/bos/eos/pad ids are taken from their
  positions (matching the words-format convention of specials-first).
  """
  char_counts: collections.Counter = collections.Counter()
  sub_counts: collections.Counter = collections.Counter()
  for text in texts:
    for word in text.split():
      marked = _WS + word
      char_counts.update(marked)
      for ln in range(2, min(len(marked), 16) + 1):
        sub_counts[marked[:ln]] += 1
      for ln in range(2, min(len(word), 8) + 1):  # word-internal suffixes
        sub_counts[word[-ln:]] += 1

  if "<unk>" not in specials:
    raise ValueError("specials must include '<unk>' (OOV pieces need an id)")
  pieces: List[Tuple[str, float, int]] = [
      (s, 0.0, UNKNOWN if s == "<unk>" else CONTROL) for s in specials]
  ids = {s: i for i, s in enumerate(specials)}
  if byte_fallback:
    pieces += [(f"<0x{b:02X}>", 0.0, BYTE) for b in range(256)]
  if len(pieces) >= vocab_size:
    raise ValueError(
        f"vocab_size={vocab_size} cannot even hold the {len(pieces)} "
        "special/byte pieces")
  total = sum(char_counts.values()) + sum(sub_counts.values()) or 1

  def _Score(count: int) -> float:
    return math.log(count / total)

  seen = set()
  budget = vocab_size - len(pieces)
  for ch, c in char_counts.most_common():
    if budget <= 0:
      break  # rarest chars fall to unk/byte-fallback, vocab_size is a cap
    pieces.append((ch, _Score(c), NORMAL))
    seen.add(ch)
    budget -= 1
  # Longer frequent substrings score higher than their chars combined, so
  # Viterbi prefers them; break count ties toward longer pieces.
  ranked = sorted(sub_counts.items(), key=lambda kv: (-kv[1], -len(kv[0])))
  for sub, c in ranked:
    if budget <= 0:
      break
    if sub in seen:
      continue
    pieces.append((sub, _Score(c) + 0.1 * len(sub), NORMAL))
    seen.add(sub)
    budget -= 1
  return SentencePieceModel(
      pieces, model_type=UNIGRAM, unk_id=ids.get("<unk>", -1),
      bos_id=ids.get("<s>", -1), eos_id=ids.get("</s>", -1),
      pad_id=ids.get("<pad>", -1))
