"""Params: typed, frozen-schema configuration trees (experiments-as-code).

Re-implements the semantics of the reference's config system
(`lingvo/core/hyperparams.py:266,1129`): every object in the framework is built
from a serializable `Params` tree created by `cls.Params()`, overridden in
experiment subclasses, and instantiated with `p.Instantiate()`. Text
round-tripping (`ToText`/`FromText`) gives full reproducibility of every run.

Design differences from the reference (deliberate, TPU-native):
  * no proto serialization — text format only (the text format IS the schema);
  * values may be arbitrary Python/JAX objects; only text-representable ones
    round-trip;
  * `Instantiate()` threads no TF graph state; instantiated layers are pure.
"""

from __future__ import annotations

import ast
import copy as _copy
import dataclasses
import enum
import inspect
import re
from typing import Any, Callable, TypeVar

T = TypeVar("T")


@dataclasses.dataclass
class _Param:
  name: str
  default: Any
  description: str


def _QuoteString(s: str) -> str:
  return repr(s)


# Types whose repr() is a constructor call with literal args; they round-trip
# through ToText/FromText. Register with RegisterSerializableType.
_SERIALIZABLE_TYPES: dict[str, type] = {}


def RegisterSerializableType(cls: type) -> type:
  _SERIALIZABLE_TYPES[cls.__name__] = cls
  return cls


def _IsNamedTuple(x: Any) -> bool:
  return isinstance(x, tuple) and hasattr(x, "_fields")


class Params:
  """An ordered, schema-frozen mapping of name -> value with nesting.

  Attribute access reads/writes parameter values. New parameters can only be
  added via `Define` (so typos in experiment overrides fail loudly).
  """

  _immutable: bool

  def __init__(self):
    self.__dict__["_params"] = {}  # name -> _Param
    self.__dict__["_immutable"] = False

  # ---- schema --------------------------------------------------------------

  def Define(self, name: str, default: Any, description: str) -> None:
    """Defines a new parameter with a default value and docstring."""
    if self._immutable:
      raise TypeError(f"This Params instance is immutable: {self}")
    if not re.match(r"^[a-z][a-z0-9_]*$", name):
      raise AttributeError(f"Parameter name must be lowercase_snake: {name!r}")
    if name in self._params:
      raise AttributeError(f"Parameter {name!r} is already defined")
    self._params[name] = _Param(name, default, description)

  def Delete(self, *names: str) -> "Params":
    """Removes parameters from the schema. Returns self."""
    if self._immutable:
      raise TypeError(f"This Params instance is immutable: {self}")
    for name in names:
      if name not in self._params:
        raise AttributeError(f"Parameter {name!r} not found")
      del self._params[name]
    return self

  # ---- value access --------------------------------------------------------

  def __getattr__(self, name: str) -> Any:
    if name.startswith("_"):
      raise AttributeError(name)
    params = self.__dict__["_params"]
    try:
      return params[name].default
    except KeyError as e:
      raise AttributeError(
          f"{name!r} not defined; known params: {sorted(params)}") from e

  def __setattr__(self, name: str, value: Any) -> None:
    if self._immutable:
      raise TypeError(f"This Params instance is immutable; cannot set {name}")
    params = self.__dict__["_params"]
    if name not in params:
      raise AttributeError(
          f"{name!r} not defined via Define(); known params: {sorted(params)}")
    params[name].default = value

  def Get(self, path: str) -> Any:
    """Gets a (possibly dotted) parameter value."""
    current: Any = self
    for part in path.split("."):
      current = getattr(current, part)
    return current

  def Set(self, **kwargs: Any) -> "Params":
    """Sets multiple parameters (dotted names use __ as separator). Returns self."""
    for name, value in kwargs.items():
      parts = name.split("__")
      target = self
      for part in parts[:-1]:
        target = getattr(target, part)
      setattr(target, parts[-1], value)
    return self

  def SetPath(self, path: str, value: Any) -> "Params":
    """Sets a dotted-path parameter. Returns self."""
    parts = path.split(".")
    target = self
    for part in parts[:-1]:
      target = getattr(target, part)
    setattr(target, parts[-1], value)
    return self

  def __contains__(self, name: str) -> bool:
    return name in self._params

  def Has(self, name: str) -> bool:
    return name in self._params

  def IterParams(self):
    for name, p in self._params.items():
      yield name, p.default

  def GetKeys(self) -> list[str]:
    return sorted(self._params.keys())

  def __len__(self) -> int:
    return len(self._params)

  # ---- copy / freeze -------------------------------------------------------

  def Copy(self) -> "Params":
    """Deep copy (sub-Params deep-copied; other values copy.deepcopy'd)."""
    return self._CopyTo(type(self)())

  def _CopyTo(self, res: "Params") -> "Params":
    res.__dict__["_params"] = {}
    for name, p in self._params.items():
      if isinstance(p.default, Params):
        v = p.default.Copy()
      else:
        try:
          v = _copy.deepcopy(p.default)
        except TypeError:
          # runtime handles (jax Mesh/Device objects, callables bound to
          # device state) are not picklable — share the reference, like the
          # reference shares non-copyable param values
          v = p.default
      res.__dict__["_params"][name] = _Param(name, v, p.description)
    if isinstance(res, InstantiableParams) and isinstance(
        self, InstantiableParams):
      res.__dict__["_cls"] = self.__dict__["_cls"]
    return res

  def __deepcopy__(self, memo):
    result = self.Copy()
    memo[id(self)] = result
    return result

  def Freeze(self) -> "Params":
    """Makes this Params tree immutable (recursively). Returns self."""
    self.__dict__["_immutable"] = True
    for p in self._params.values():
      if isinstance(p.default, Params):
        p.default.Freeze()
    return self

  @property
  def is_immutable(self) -> bool:
    return self._immutable

  # ---- equality / repr -----------------------------------------------------

  def __eq__(self, other: Any) -> bool:
    if not isinstance(other, Params):
      return NotImplemented
    if set(self._params) != set(other._params):
      return False
    for name, p in self._params.items():
      if p.default != other._params[name].default:
        return False
    return True

  def __ne__(self, other):
    eq = self.__eq__(other)
    return eq if eq is NotImplemented else not eq

  def __repr__(self) -> str:
    return self.ToText()

  def __str__(self) -> str:
    return self.ToText()

  # ---- text serialization --------------------------------------------------

  def ToText(self, prefix: str = "") -> str:
    """Serializes to 'dotted.key : value' lines, sorted by key."""
    lines: list[str] = []

    def _Append(key: str, value: Any):
      lines.append(f"{key} : {_ValueToText(value)}")

    def _Walk(params: "Params", prefix: str):
      for name in sorted(params._params):
        v = params._params[name].default
        key = f"{prefix}{name}"
        if isinstance(v, Params):
          if isinstance(v, InstantiableParams):
            lines.append(f"{key}.cls : {_ClassToText(v.cls)}")
          _Walk(v, key + ".")
        else:
          _Append(key, v)

    if isinstance(self, InstantiableParams):
      lines.append(f"{prefix}cls : {_ClassToText(self.cls)}")
    _Walk(self, prefix)
    return "\n".join(lines) + "\n"

  def FromText(self, text: str) -> "Params":
    """Applies 'key : value' lines to this tree. Values parsed as literals.

    Only keys already in the schema are set ('cls' lines are checked to match,
    not used to construct — reconstruction requires the experiment code, which
    is the reference's behavior too).
    """
    if self._immutable:
      raise TypeError("Cannot FromText on immutable Params")
    for line in text.splitlines():
      line = line.strip()
      if not line or line.startswith("#"):
        continue
      if " : " not in line:
        raise ValueError(f"Malformed params line: {line!r}")
      key, value_text = line.split(" : ", 1)
      key = key.strip()
      if key == "cls" or key.endswith(".cls"):
        continue
      target: Any = self
      parts = key.split(".")
      for part in parts[:-1]:
        target = getattr(target, part)
      setattr(target, parts[-1], _TextToValue(value_text.strip()))
    return self

  def TextDiff(self, other: "Params") -> str:
    """Returns a human-readable diff of two Params trees."""
    mine = dict(
        line.split(" : ", 1) for line in self.ToText().splitlines() if line)
    theirs = dict(
        line.split(" : ", 1) for line in other.ToText().splitlines() if line)
    out = []
    for k in sorted(set(mine) | set(theirs)):
      a, b = mine.get(k), theirs.get(k)
      if a != b:
        out.append(f"{k}: {a} -> {b}")
    return "\n".join(out)


class InstantiableParams(Params):
  """Params bound to a class; `Instantiate()` constructs cls(params)."""

  def __init__(self, cls: type | None = None):
    super().__init__()
    self.__dict__["_cls"] = cls

  @property
  def cls(self) -> type:
    return self.__dict__["_cls"]

  def SetClass(self, cls: type) -> "InstantiableParams":
    """Rebinds the class to instantiate (e.g. policy wrappers subclassing
    the original cls, ref input_policy.py); returns self for chaining."""
    self.__dict__["_cls"] = cls
    return self

  def Instantiate(self, **kwargs: Any):
    """Constructs the bound class with this params tree."""
    if self.cls is None:
      raise ValueError("InstantiableParams has no bound class")
    return self.cls(self, **kwargs)

  def Copy(self) -> "InstantiableParams":
    return self._CopyTo(type(self)(self.cls))


def _ClassToText(cls: type | None) -> str:
  if cls is None:
    return "None"
  return f"type/{cls.__module__}/{cls.__qualname__}"


def _ValueToText(v: Any) -> str:
  if isinstance(v, str):
    return _QuoteString(v)
  if isinstance(v, enum.Enum):
    return f"enum/{type(v).__module__}/{type(v).__qualname__}/{v.name}"
  if inspect.isclass(v):
    return _ClassToText(v)
  if callable(v):
    mod = getattr(v, "__module__", "?")
    name = getattr(v, "__qualname__", getattr(v, "__name__", repr(v)))
    return f"callable/{mod}/{name}"
  if isinstance(v, dict) and not v:
    return "{}"
  if _IsNamedTuple(v):
    return repr(v)
  return repr(v)


def _TextToValue(text: str) -> Any:
  if text == "None":
    return None
  if text in ("True", "False"):
    return text == "True"
  if text.startswith(("type/", "callable/")):
    _, mod, qualname = text.split("/", 2)
    import importlib
    obj: Any = importlib.import_module(mod)
    for part in qualname.split("."):
      obj = getattr(obj, part)
    return obj
  if text.startswith("enum/"):
    _, mod, rest = text.split("/", 2)
    qualname, member = rest.rsplit("/", 1)
    import importlib
    obj = importlib.import_module(mod)
    for part in qualname.split("."):
      obj = getattr(obj, part)
    return obj[member]
  try:
    return ast.literal_eval(text)
  except (ValueError, SyntaxError):
    pass
  # Registered dataclass-style reprs: Name(k=literal, ...).
  m = re.match(r"^([A-Za-z_][A-Za-z0-9_]*)\(", text)
  if m and m.group(1) in _SERIALIZABLE_TYPES:
    cls = _SERIALIZABLE_TYPES[m.group(1)]
    try:
      node = ast.parse(text, mode="eval").body
      if isinstance(node, ast.Call):
        args = [ast.literal_eval(a) for a in node.args]
        kwargs = {k.arg: ast.literal_eval(k.value) for k in node.keywords}
        return cls(*args, **kwargs)
    except (ValueError, SyntaxError):
      pass
  raise ValueError(
      f"Cannot parse params value {text!r}; non-literal types must be "
      "registered with hyperparams.RegisterSerializableType")
