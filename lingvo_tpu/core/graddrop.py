"""Gradient Sign Dropout (GradDrop) for multi-task shared representations.

Re-designs `lingvo/core/graddrop.py` (the NeurIPS-2020 GradDrop algorithm)
functionally: the reference wraps an identity op with a custom gradient and
needs `SetLosses` + `tf.gradients` graph surgery to obtain per-loss
gradients at that point. In JAX the same effect falls out of `custom_vjp`
on a "split" primitive: `GradDropSplit(x, key, n)` hands each downstream
task its own copy of the shared tensor, so the backward pass naturally
receives one cotangent per task and can combine them with sign dropout
before passing a single gradient to the trunk.

Usage::

  xs = graddrop.GradDropSplit(shared, step_key, len(task_heads), cfg)
  losses = [head_i(xs[i]) for i ...]   # per-task heads / losses
  total = sum(losses)                  # backprop as usual

Head weights get ordinary gradients; only d(total)/d(shared) is modified.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class GradDropConfig:
  """Static GradDrop knobs (ref `graddrop.py` Params)."""

  keep_prob_function: str = "linear"    # 'linear' | 'sigmoid'
  keep_prob_function_scale: float = 1.0
  use_input_sign_only: bool = True
  keep_gradnorm_constant: bool = True
  marginalize_batch_dim: bool = True
  epsilon: float = 1e-7
  leak_ratios: tuple = ()               # per-task; () = all zeros


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def GradDropSplit(x, key, n: int, cfg: GradDropConfig):
  """Returns n copies of x whose combined backward grad is sign-dropped."""
  del key, cfg
  return (x,) * n


def _Fwd(x, key, n, cfg):
  return (x,) * n, (x, key)


def _Bwd(n, cfg, res, gs):
  x, key = res
  eps = cfg.epsilon
  per_loss_grads = [g.astype(jnp.float32) for g in gs]

  # Signal used for sign decisions: grad * input (or input sign only).
  x32 = x.astype(jnp.float32)
  if cfg.use_input_sign_only:
    x_abs = jnp.abs((jnp.abs(x32) <= eps).astype(jnp.float32) + x32)
    signal = [g * (x32 / x_abs) for g in per_loss_grads]
  else:
    signal = [g * x32 for g in per_loss_grads]
  if cfg.marginalize_batch_dim:
    signal = [jnp.sum(s, axis=0, keepdims=True) for s in signal]

  sign_pos = [(s > 0.0).astype(jnp.float32) for s in signal]
  sign_neg = [(s < 0.0).astype(jnp.float32) for s in signal]

  # Purity (eq. 1 of the paper): probability of keeping positive signs.
  abs_sum = sum(jnp.abs(s) for s in signal)
  prob_pos = sum(signal) / (2.0 * abs_sum + eps)
  prob_pos = prob_pos * cfg.keep_prob_function_scale
  if cfg.keep_prob_function == "sigmoid":
    # sigmoid'(0) = 0.25, so 4x matches the linear slope at 0
    prob_pos = jax.nn.sigmoid(4.0 * prob_pos)
  elif cfg.keep_prob_function == "linear":
    prob_pos = prob_pos + 0.5
  else:
    raise ValueError(cfg.keep_prob_function)

  u = jax.random.uniform(key, prob_pos.shape)
  choose_pos = (prob_pos >= u).astype(jnp.float32) - 0.5   # +-0.5
  masks = [((sp - sn) * choose_pos >= 0).astype(jnp.float32)
           for sp, sn in zip(sign_pos, sign_neg)]

  leaks = cfg.leak_ratios or (0.0,) * n
  if len(leaks) != n:
    raise ValueError(
        f"leak_ratios has {len(leaks)} entries for {n} tasks")
  transformed = [
      g * (leak + (1.0 - leak) * mask)
      for leak, g, mask in zip(leaks, per_loss_grads, masks)
  ]
  combined = sum(transformed)

  if cfg.keep_gradnorm_constant:
    original = sum(per_loss_grads)
    combined = combined * (jnp.linalg.norm(original) /
                           (jnp.linalg.norm(combined) + eps))
  return combined.astype(x.dtype), None


GradDropSplit.defvjp(_Fwd, _Bwd)
