"""Insertion-based sequence framework (Insertion Transformer).

Re-designs `lingvo/core/insertion.py` (`SymbolInsertionLayer:130` + sequence
utilities): sampling a random "canvas" (observed subset) of the target
sequence and building the slot/token targets an insertion model trains on.

TPU-first deviation from the reference: the reference trims the canvas to
the batch max length and boolean-masks the target list — both dynamic
shapes. Here every output keeps the static [b, t] shape with
paddings/weights doing the masking, so the whole pipeline jits: the canvas
is [b, t] (padded past each example's sampled length) and targets are dense
[b, t] token/slot/weight tensors instead of a ragged [num_targets, 3] list.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap


def SequenceTrimLastToken(x, x_paddings):
  """Trims the last valid token of each sequence (ref `insertion.py:27`)."""
  seq_len = jnp.sum(1.0 - x_paddings, axis=1)
  last = jnp.maximum(seq_len - 1.0, 0.0)
  keep = (jnp.arange(x.shape[1])[None, :] < last[:, None])
  return x * keep.astype(x.dtype), jnp.where(keep, x_paddings, 1.0)


def SequenceAppendToken(x, x_paddings, token, extend: bool = False):
  """Appends `token` after the last valid token (ref `insertion.py:48`).

  extend=True grows the time dim by one; otherwise the token must fit in
  existing padding (the final position is overwritten if the row is full).
  """
  if extend:
    x = jnp.pad(x, ((0, 0), (0, 1)))
    x_paddings = jnp.pad(x_paddings, ((0, 0), (0, 1)), constant_values=1.0)
  t = x.shape[1]
  seq_len = jnp.sum(1.0 - x_paddings, axis=1).astype(jnp.int32)
  write_at = jnp.minimum(seq_len, t - 1)
  onehot = jax.nn.one_hot(write_at, t, dtype=x.dtype)
  x = x * (1 - onehot).astype(x.dtype) + onehot * token
  new_pad = x_paddings * (1.0 - onehot.astype(x_paddings.dtype))
  return x, new_pad


def SequenceConcat(x, x_paddings, y, y_paddings, pad=0):
  """Concats y after x's valid tokens (ref `insertion.py:79`).

  Output time dim = x_t + y_t; slots past the combined length hold `pad`.
  """
  b, xt = x.shape
  yt = y.shape[1]
  t = xt + yt
  x_len = jnp.sum(1.0 - x_paddings, axis=1).astype(jnp.int32)   # [b]
  y_len = jnp.sum(1.0 - y_paddings, axis=1).astype(jnp.int32)
  pos = jnp.arange(t)[None, :]                                  # [1, t]
  # from x where pos < x_len; from y where x_len <= pos < x_len + y_len
  x_gather = jnp.clip(pos, 0, xt - 1)
  y_gather = jnp.clip(pos - x_len[:, None], 0, yt - 1)
  x_part = jnp.take_along_axis(jnp.pad(x, ((0, 0), (0, t - xt))), x_gather,
                               axis=1)
  y_part = jnp.take_along_axis(jnp.pad(y, ((0, 0), (0, t - yt))), y_gather,
                               axis=1)
  from_x = pos < x_len[:, None]
  valid = pos < (x_len + y_len)[:, None]
  out = jnp.where(from_x, x_part, y_part)
  out = jnp.where(valid, out, pad)
  return out, (1.0 - valid.astype(jnp.float32))


class SymbolInsertionLayer(base_layer.BaseLayer):
  """Sampled roll-in canvas + insertion targets (ref `insertion.py:130`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("rollin_policy", "oracle", "{oracle, uniform}.")
    p.Define("oracle_policy", "uniform", "{uniform}.")
    return p

  def FProp(self, theta, x, x_paddings=None, eos_id=1,
            force_sample_last_token=True, key=None):
    """x: [b, t] int ids -> NestedMap of canvas + dense targets.

    Returns:
      canvas [b, t], canvas_indices [b, t] (into x; invalid slots point at
      t-1), canvas_paddings [b, t]; target_tokens [b, t] (the insertion at
      each source position, <eos> for observed slots), target_slots [b, t]
      (which canvas slot each target inserts into), target_weights [b, t]
      (0 for padded positions and redundant <eos> duplicates).
    """
    p = self.p
    del theta
    rollin = p.oracle_policy if p.rollin_policy == "oracle" else p.rollin_policy
    if rollin != "uniform" or p.oracle_policy != "uniform":
      raise ValueError(f"Unsupported policy: {rollin}/{p.oracle_policy}")
    b, t = x.shape
    if x_paddings is None:
      x_paddings = jnp.zeros((b, t), jnp.float32)
    if key is None:
      key = (py_utils.StepSeed(self.path + "/rollin")
             if py_utils.HasStepSeed()
             else jax.random.PRNGKey(p.random_seed or 0))
    k_ratio, k_gumbel = jax.random.split(key)

    x_len = jnp.round(jnp.sum(1.0 - x_paddings, axis=1)).astype(jnp.int32)
    ratio = jax.random.uniform(k_ratio, (b,))
    if force_sample_last_token:
      c_len = jnp.minimum((ratio * x_len).astype(jnp.int32), x_len - 1) + 1
    else:
      c_len = jnp.minimum((ratio * (x_len + 1)).astype(jnp.int32), x_len)

    # Gumbel-max over valid positions; optionally force the last token.
    pos = jnp.arange(t)[None, :]
    z_logits = jnp.where(pos >= x_len[:, None], -1e9, 0.0)
    if force_sample_last_token:
      z_logits = z_logits + jnp.where(pos == (x_len - 1)[:, None], 1e9, 0.0)
    z = -jnp.log(-jnp.log(
        jnp.clip(jax.random.uniform(k_gumbel, (b, t)), 1e-20, 1.0)))
    order = jnp.argsort(-(z_logits + z), axis=1)           # [b, t]
    # first c_len entries are the sampled canvas; others -> sentinel t-1
    rank = jnp.arange(t)[None, :]
    c_indices = jnp.where(rank < c_len[:, None], order, t - 1)
    c_indices = jnp.sort(c_indices, axis=1)
    canvas = jnp.take_along_axis(x, c_indices, axis=1)
    canvas_paddings = (rank >= c_len[:, None]).astype(jnp.float32)
    canvas = canvas * (1 - canvas_paddings).astype(canvas.dtype)

    # observed flags over x (scatter of the sampled indices)
    observed = jnp.zeros((b, t), jnp.int32)
    valid_canvas = (rank < c_len[:, None]).astype(jnp.int32)
    observed = jax.vmap(
        lambda obs, idx, val: obs.at[idx].max(val))(observed, c_indices,
                                                    valid_canvas)
    # slot of each x position = # observed tokens strictly before it
    x_segments = jnp.cumsum(observed, axis=1) - observed

    observed_b = observed.astype(bool)
    prev_observed = jnp.pad(observed_b[:, :-1], ((0, 0), (1, 0)),
                            constant_values=True)
    x_valid = (1.0 - x_paddings).astype(bool)

    target_tokens = jnp.where(observed_b, eos_id, x).astype(jnp.int32)
    target_weights = jnp.ones((b, t), jnp.float32)
    # an observed token whose predecessor is unobserved shares its slot with
    # a real insertion -> its <eos> target gets weight 0 (ref `:300-309`)
    target_weights = jnp.where(observed_b & ~prev_observed, 0.0,
                               target_weights)
    target_weights = jnp.where(x_valid, target_weights, 0.0)

    return NestedMap(
        canvas=canvas,
        canvas_indices=c_indices,
        canvas_paddings=canvas_paddings,
        target_tokens=target_tokens,
        target_slots=x_segments,
        target_weights=target_weights)
