"""Scatter updates (ref `lingvo/core/scatter_update.py`).

The reference toggles between `tf.tensor_scatter_nd_update` and
`tf.InplaceUpdate` because in-place semantics mattered for TF grappler; in
JAX `x.at[...]` is already functional AND buffer-donating under jit, so the
inplace flag is a documented no-op kept for call-site parity.
"""

from __future__ import annotations

import contextlib

import jax.numpy as jnp


@contextlib.contextmanager
def SetInplaceUpdate(inplace_update: bool):
  """Parity shim (ref `scatter_update.py:26`): XLA decides buffer reuse."""
  del inplace_update
  yield


def Update(x, i, v, *, inplace_update=None):
  """Returns x with x[i] = v (ref `scatter_update.py:41`).

  i: int scalar or [n] indices into dim 0; v: matching update slice(s).
  """
  del inplace_update
  return x.at[i].set(v)


def Add(x, i, v):
  """Returns x with x[i] += v."""
  return x.at[i].add(v)


def UpdateSlice(x, start_indices, update):
  """Dynamic-slice update (lax.dynamic_update_slice wrapper)."""
  import jax
  return jax.lax.dynamic_update_slice(x, update.astype(x.dtype),
                                      tuple(jnp.asarray(s) for s in
                                            start_indices))
