"""Activation registry: name -> fn (ref: lingvo/core/activations.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

_ACTIVATIONS = {
    "NONE": lambda x: x,
    "RELU": jax.nn.relu,
    "RELU6": jax.nn.relu6,
    "RELU_SQUARED": lambda x: jnp.square(jax.nn.relu(x)),
    "LEAKY_RELU": jax.nn.leaky_relu,
    "SIGMOID": jax.nn.sigmoid,
    "TANH": jnp.tanh,
    "GELU": lambda x: jax.nn.gelu(x, approximate=False),
    "GELU_APPROXIMATE": lambda x: jax.nn.gelu(x, approximate=True),
    "GELU_RAW": lambda x: jax.nn.gelu(x, approximate=False),
    "SWISH": jax.nn.silu,
    "SILU": jax.nn.silu,
    "SOFTPLUS": jax.nn.softplus,
    "EXP": jnp.exp,
}


def GetFn(name: str):
  if name not in _ACTIVATIONS:
    raise ValueError(f"Unknown activation {name!r}; known: {sorted(_ACTIVATIONS)}")
  return _ACTIVATIONS[name]


def Register(name: str, fn) -> None:
  _ACTIVATIONS[name.upper()] = fn
