"""NestedMap: a dot-accessible nested dict, registered as a JAX pytree.

TPU-native re-design of the reference's universal batch/theta/state container
(`lingvo/core/nested_map.py:81`). Unlike the reference (which carries its own
Flatten/Pack machinery on top of TF), this NestedMap is a first-class JAX pytree
node, so `jax.tree_util`, `jax.jit`, `jax.grad`, shardings etc. all traverse it
natively.  Keys are flattened in sorted order, matching the reference's stable
ordering guarantee.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Iterable

import jax

_NAME_SEPARATOR = "."
_VALID_KEY_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Attributes of dict/NestedMap itself that must not be shadowed by keys.
_RESERVED = frozenset(dir(dict)) | frozenset(
    ("Flatten", "FlattenItems", "Pack", "Transform", "TransformWithKey",
     "Filter", "FilterKeyVal", "Get", "GetItem", "Set", "Copy", "DeepCopy",
     "IsCompatible", "VLog", "DebugString")
)


class NestedMap(dict):
  """A dict with attribute access and stable-order pytree flattening."""

  __slots__ = ()

  def __init__(self, *args, **kwargs):
    super().__init__(*args, **kwargs)
    for key in self.keys():
      NestedMap.CheckKey(key)

  # ---- attribute access ----------------------------------------------------

  def __getattr__(self, name: str) -> Any:
    try:
      return self[name]
    except KeyError as e:
      raise AttributeError(
          f"'NestedMap' has no attribute {name!r}; keys: {sorted(self.keys())}"
      ) from e

  def __setattr__(self, name: str, value: Any) -> None:
    NestedMap.CheckKey(name)
    self[name] = value

  def __delattr__(self, name: str) -> None:
    try:
      del self[name]
    except KeyError as e:
      raise AttributeError(name) from e

  def __setitem__(self, key: str, value: Any) -> None:
    NestedMap.CheckKey(key)
    super().__setitem__(key, value)

  @staticmethod
  def CheckKey(key: Any) -> None:
    if not isinstance(key, str) or not _VALID_KEY_RE.match(key):
      raise ValueError(f"Invalid NestedMap key {key!r}")
    if key in _RESERVED:
      raise ValueError(f"NestedMap key {key!r} shadows a reserved attribute")

  # ---- copies --------------------------------------------------------------

  def Copy(self) -> "NestedMap":
    """Shallow copy (one level)."""
    return NestedMap(self)

  def DeepCopy(self) -> "NestedMap":
    """Structural copy: containers are rebuilt, leaves are shared."""
    return jax.tree_util.tree_map(lambda x: x, self)

  def __deepcopy__(self, memo):
    import copy as _copy
    result = NestedMap()
    memo[id(self)] = result
    for k, v in self.items():
      super(NestedMap, result).__setitem__(k, _copy.deepcopy(v, memo))
    return result

  # ---- dotted-path get/set -------------------------------------------------

  def Get(self, path: str, default: Any = None) -> Any:
    """Returns the value at dotted `path` ('a.b[0].c' style), or default."""
    try:
      return self.GetItem(path)
    except (KeyError, IndexError, TypeError):
      return default

  def GetItem(self, path: str) -> Any:
    """Returns the value at dotted `path`; raises on missing."""
    current = self
    for part in re.split(r"\.|(\[\d+\])", path):
      if not part:
        continue
      if part.startswith("["):
        current = current[int(part[1:-1])]
      else:
        current = current[part] if isinstance(current, dict) else getattr(
            current, part)
    return current

  def Set(self, path: str, value: Any) -> None:
    """Sets `path` to `value`, creating intermediate NestedMaps as needed."""
    parts = [p for p in re.split(r"\.|(\[\d+\])", path) if p]
    current = self
    for i, part in enumerate(parts[:-1]):
      nxt = parts[i + 1]
      if part.startswith("["):
        idx = int(part[1:-1])
        while len(current) <= idx:
          current.append(NestedMap() if not nxt.startswith("[") else [])
        current = current[idx]
      else:
        if isinstance(current, dict):
          if part not in current or current[part] is None:
            current[part] = [] if nxt.startswith("[") else NestedMap()
          current = current[part]
        else:
          current = getattr(current, part)
    last = parts[-1]
    if last.startswith("["):
      idx = int(last[1:-1])
      while len(current) <= idx:
        current.append(None)
      current[idx] = value
    else:
      current[last] = value

  # ---- flatten / pack ------------------------------------------------------

  def Flatten(self) -> list[Any]:
    """Flattens leaves in sorted-key order (lists flattened in order)."""
    return jax.tree_util.tree_leaves(self)

  def FlattenItems(self) -> list[tuple[str, Any]]:
    """Returns [(dotted_key, leaf)] in stable order."""
    paths_and_leaves = jax.tree_util.tree_flatten_with_path(self)[0]
    out = []
    for path, leaf in paths_and_leaves:
      parts = []
      for p in path:
        if isinstance(p, jax.tree_util.DictKey):
          parts.append(str(p.key))
        elif isinstance(p, jax.tree_util.SequenceKey):
          if parts:
            parts[-1] += f"[{p.idx}]"
          else:
            parts.append(f"[{p.idx}]")
        else:
          parts.append(str(p))
      out.append((_NAME_SEPARATOR.join(parts), leaf))
    return out

  def Pack(self, values: Iterable[Any]) -> "NestedMap":
    """Packs flat `values` back into this map's structure."""
    treedef = jax.tree_util.tree_structure(self)
    return jax.tree_util.tree_unflatten(treedef, list(values))

  # ---- transforms ----------------------------------------------------------

  def Transform(self, fn: Callable[[Any], Any]) -> "NestedMap":
    """Applies fn to every leaf; returns a new NestedMap."""
    return jax.tree_util.tree_map(fn, self)

  def TransformWithKey(self, fn: Callable[[str, Any], Any]) -> "NestedMap":
    items = self.FlattenItems()
    return self.Pack([fn(k, v) for k, v in items])

  def Filter(self, fn: Callable[[Any], bool]) -> "NestedMap":
    """Keeps only leaves where fn(value); prunes empty subtrees."""
    return self.FilterKeyVal(lambda _, v: fn(v))

  def FilterKeyVal(self, fn: Callable[[str, Any], bool]) -> "NestedMap":
    """Keeps only leaves where fn(dotted_key, value); prunes empty subtrees."""

    def _Recurse(node: Any, prefix: str) -> Any:
      if isinstance(node, dict):
        out = NestedMap()
        for k in node:
          key = f"{prefix}{_NAME_SEPARATOR}{k}" if prefix else k
          sub = _Recurse(node[k], key)
          if sub is not _PRUNE:
            out[k] = sub
        return out if out else _PRUNE
      if isinstance(node, (list, tuple)):
        if hasattr(node, "_fields"):  # namedtuple: all-or-nothing leaf
          return node if fn(prefix, node) else _PRUNE
        # Preserve arity: pruned elements become None placeholders so indices
        # in the filtered tree still correspond to the original tree (needed
        # for trainable-subset <-> full-theta merges).
        out_l = []
        any_kept = False
        for i, v in enumerate(node):
          sub = _Recurse(v, f"{prefix}[{i}]")
          if sub is _PRUNE:
            out_l.append(None)
          else:
            any_kept = True
            out_l.append(sub)
        if not any_kept:
          return _PRUNE
        return type(node)(out_l) if isinstance(node, tuple) else out_l
      return node if fn(prefix, node) else _PRUNE

    result = _Recurse(self, "")
    return NestedMap() if result is _PRUNE else result

  def IsCompatible(self, other: "NestedMap") -> bool:
    """True iff `other` has the same nested structure."""
    return (jax.tree_util.tree_structure(self) ==
            jax.tree_util.tree_structure(other))

  def DebugString(self) -> str:
    return "\n".join(f"{k}: {v!r}" for k, v in self.FlattenItems())


class _Prune:
  pass


_PRUNE = _Prune()


def _nested_map_flatten(nm: NestedMap):
  keys = sorted(nm.keys())
  return [nm[k] for k in keys], tuple(keys)


def _nested_map_flatten_with_keys(nm: NestedMap):
  keys = sorted(nm.keys())
  return [(jax.tree_util.DictKey(k), nm[k]) for k in keys], tuple(keys)


def _nested_map_unflatten(keys, values):
  nm = NestedMap()
  for k, v in zip(keys, values):
    dict.__setitem__(nm, k, v)
  return nm


jax.tree_util.register_pytree_with_keys(
    NestedMap,
    _nested_map_flatten_with_keys,
    _nested_map_unflatten,
    flatten_func=_nested_map_flatten,
)

# jax.export serialization support: NestedMap aux data (the sorted key tuple)
# round-trips as JSON so exported inference graphs can carry NestedMap
# feeds/fetches.
try:
  import json as _json

  from jax import export as _jax_export

  _jax_export.register_pytree_node_serialization(
      NestedMap,
      serialized_name="lingvo_tpu.NestedMap",
      serialize_auxdata=lambda keys: _json.dumps(list(keys)).encode(),
      deserialize_auxdata=lambda data: tuple(_json.loads(data.decode())),
  )
except (ImportError, AttributeError):  # older jax without the API
  pass
