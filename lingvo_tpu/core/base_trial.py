"""Trial API for hyperparameter-tuning services (ref lingvo/base_trial.py).

A Trial can override model params before construction, receives eval
measures, and can request early stopping. NoOpTrial is the default.
"""

from __future__ import annotations


class Trial:

  def OverrideModelParams(self, model_params):
    """Mutates/returns model params for this trial."""
    raise NotImplementedError

  def ReportEvalMeasure(self, global_step: int, metrics: dict,
                        checkpoint_path: str = "") -> bool:
    """Reports metrics; returns True if the trial should stop early."""
    raise NotImplementedError

  def ReportDone(self, infeasible: bool = False, reason: str = "") -> None:
    raise NotImplementedError

  def ShouldStop(self) -> bool:
    raise NotImplementedError

  @property
  def Name(self) -> str:
    return ""


class NoOpTrial(Trial):
  """Training without a tuning service (ref NoOpTrial)."""

  def OverrideModelParams(self, model_params):
    return model_params

  def ReportEvalMeasure(self, global_step, metrics, checkpoint_path=""):
    return False

  def ReportDone(self, infeasible=False, reason=""):
    pass

  def ShouldStop(self):
    return False
