"""Seq2seq (per-step) attention family for recurrent decoders.

Re-designs the reference's time-major attention library
(`lingvo/core/attention.py`: AdditiveAttention:547, DotProductAttention:1015,
LocationSensitiveAttention:2334, MonotonicAttention:2900,
GmmMonotonicAttention:3267, MergerLayer:3608, MultiSourceAttention:3856) for
JAX decoders: everything is batch-major (the reference's time-major layout is
a TF-graph perf artifact; under jit the compiler owns layout), source
projections are cached once in `PackSource`, and each decode step is a pure
function of (packed source, query, attention state) — the shape that drops
directly into `lax.scan` teacher forcing and flat beam search.

API:
  packed = atten.PackSource(theta, source_vecs [B,T,D], source_paddings)
  state0 = atten.ZeroAttentionState(B, T)
  ctx, probs, state1 = atten.ComputeContextVector(theta, packed, query, state0)
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.core.py_utils import WeightParams

_NEG_INF = -1.0e9


def _MaskedSoftmax(scores, paddings):
  scores = jnp.where(paddings > 0.5, _NEG_INF, scores)
  return jax.nn.softmax(scores.astype(jnp.float32), axis=-1)


class BaseSequenceAttention(base_layer.BaseLayer):
  """Per-step attention over a packed source."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("source_dim", 0, "Encoder output dim.")
    p.Define("query_dim", 0, "Decoder query dim.")
    p.Define("hidden_dim", 0, "Attention hidden dim.")
    return p

  def PackSource(self, theta, source_vecs, source_paddings) -> NestedMap:
    """Caches per-source projections (ref InitForSourcePacked)."""
    return NestedMap(source=source_vecs, paddings=source_paddings)

  def ZeroAttentionState(self, batch_size: int, src_len: int) -> NestedMap:
    return NestedMap(dummy=jnp.zeros((batch_size, 1), jnp.float32))

  def ComputeContextVector(self, theta, packed, query, atten_state):
    """query [B, Dq] -> (context [B, Ds], probs [B, T], new_state)."""
    raise NotImplementedError


class AdditiveAttention(BaseSequenceAttention):
  """v . tanh(W_s s + W_q q) (ref `attention.py:547`)."""

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.source_dim and p.query_dim and p.hidden_dim
    self.CreateVariable(
        "w_source", WeightParams((p.source_dim, p.hidden_dim), p.params_init,
                                 p.dtype))
    self.CreateVariable(
        "w_query", WeightParams((p.query_dim, p.hidden_dim), p.params_init,
                                p.dtype))
    self.CreateVariable("v", WeightParams((p.hidden_dim,), p.params_init,
                                          p.dtype))

  def PackSource(self, theta, source_vecs, source_paddings):
    th = self.CastTheta(theta)
    return NestedMap(
        source=source_vecs,
        projected=jnp.einsum("btd,dh->bth", source_vecs, th.w_source),
        paddings=source_paddings)

  def _Scores(self, theta, packed, query, extra=0.0):
    th = self.CastTheta(theta)
    q = jnp.einsum("bd,dh->bh", query, th.w_query)
    act = jnp.tanh(packed.projected + q[:, None, :] + extra)
    return jnp.einsum("bth,h->bt", act, th.v)

  def ComputeContextVector(self, theta, packed, query, atten_state):
    probs = _MaskedSoftmax(self._Scores(theta, packed, query),
                           packed.paddings)
    ctx = jnp.einsum("bt,btd->bd", probs.astype(packed.source.dtype),
                     packed.source)
    return ctx, probs, atten_state


class DotProductAttention(BaseSequenceAttention):
  """Scaled dot-product per-step attention (ref `attention.py:1015`)."""

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.source_dim and p.query_dim
    if p.query_dim != p.source_dim:
      self.CreateVariable(
          "w_query", WeightParams((p.query_dim, p.source_dim), p.params_init,
                                  p.dtype))

  def ComputeContextVector(self, theta, packed, query, atten_state):
    p = self.p
    th = self.CastTheta(theta)
    if p.query_dim != p.source_dim:
      query = jnp.einsum("bd,de->be", query, th.w_query)
    scores = jnp.einsum("bd,btd->bt", query, packed.source) / math.sqrt(
        p.source_dim)
    probs = _MaskedSoftmax(scores, packed.paddings)
    ctx = jnp.einsum("bt,btd->bd", probs.astype(packed.source.dtype),
                     packed.source)
    return ctx, probs, atten_state


class LocationSensitiveAttention(AdditiveAttention):
  """Additive attention + convolutional location features over the previous
  attention distribution (ref `attention.py:2334` — the ASR aligner: biases
  the score toward positions near the last attended frame)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("location_filters", 8, "Conv channels over prev probs.")
    p.Define("location_kernel_size", 11, "Conv width over source time.")
    p.Define("use_cumulative_probs", True,
             "Convolve cumulative (all prior steps) probs as well.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    in_ch = 2 if p.use_cumulative_probs else 1
    self.CreateVariable(
        "location_conv",
        WeightParams((p.location_kernel_size, in_ch, p.location_filters),
                     p.params_init, p.dtype))
    self.CreateVariable(
        "w_location",
        WeightParams((p.location_filters, p.hidden_dim), p.params_init,
                     p.dtype))

  def ZeroAttentionState(self, batch_size, src_len):
    # attention starts "parked" at frame 0 (ref: init prev probs one-hot)
    init = jnp.zeros((batch_size, src_len), jnp.float32).at[:, 0].set(1.0)
    return NestedMap(prev_probs=init, cum_probs=init)

  def ComputeContextVector(self, theta, packed, query, atten_state):
    p = self.p
    th = self.CastTheta(theta)
    feats = atten_state.prev_probs[..., None]            # [B, T, 1]
    if p.use_cumulative_probs:
      feats = jnp.concatenate(
          [feats, atten_state.cum_probs[..., None]], axis=-1)
    loc = jax.lax.conv_general_dilated(
        feats.astype(th.location_conv.dtype), th.location_conv,
        window_strides=(1,), padding="SAME",
        dimension_numbers=("NWC", "WIO", "NWC"))         # [B, T, F]
    extra = jnp.einsum("btf,fh->bth", loc, th.w_location)
    probs = _MaskedSoftmax(self._Scores(theta, packed, query, extra),
                           packed.paddings)
    ctx = jnp.einsum("bt,btd->bd", probs.astype(packed.source.dtype),
                     packed.source)
    new_state = NestedMap(prev_probs=probs,
                          cum_probs=atten_state.cum_probs + probs)
    return ctx, probs, new_state


class MonotonicAttention(AdditiveAttention):
  """Soft monotonic alignment (ref `attention.py:2900`, Raffel et al.):
  the expected-alignment recurrence computed in parallel over source time.

  alpha_t(j) = p(j) * [alpha_{t-1}(j-1) (1-p(j-1)) ... ] — implemented with
  the standard cumprod formulation; state carries alpha_{t-1}.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("hidden_bias_init", -1.0,
             "Initial energy bias (negative = attend later).")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateVariable("energy_bias", WeightParams((1,),
                                                    py_utils.WeightInit.Constant(
                                                        p.hidden_bias_init),
                                                    p.dtype))

  def ZeroAttentionState(self, batch_size, src_len):
    init = jnp.zeros((batch_size, src_len), jnp.float32).at[:, 0].set(1.0)
    return NestedMap(prev_alpha=init)

  def ComputeContextVector(self, theta, packed, query, atten_state):
    th = self.CastTheta(theta)
    energy = self._Scores(theta, packed, query) + th.energy_bias.astype(
        jnp.float32)
    p_choose = jax.nn.sigmoid(energy)                    # [B, T]
    p_choose = jnp.where(packed.paddings > 0.5, 0.0, p_choose)
    # parallel monotonic recurrence (Raffel eq. 11):
    # alpha_j = p_j * cumprod(1-p)_j * cumsum(prev_alpha / cumprod(1-p))_j
    one_minus = jnp.clip(1.0 - p_choose, 1e-10, 1.0)
    cumprod = jnp.cumprod(one_minus, axis=-1) / one_minus  # exclusive
    alpha = p_choose * cumprod * jnp.cumsum(
        atten_state.prev_alpha / jnp.maximum(cumprod, 1e-10), axis=-1)
    denom = jnp.maximum(jnp.sum(alpha, -1, keepdims=True), 1e-10)
    probs = alpha / denom
    ctx = jnp.einsum("bt,btd->bd", probs.astype(packed.source.dtype),
                     packed.source)
    return ctx, probs, NestedMap(prev_alpha=alpha)


class GmmMonotonicAttention(BaseSequenceAttention):
  """GMM-based monotonic attention (ref `attention.py:3267`): mixture means
  only move forward (softplus increments), giving soft monotonic alignment
  without energies over the whole source."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("num_mixtures", 5, "GMM components.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.query_dim and p.hidden_dim
    self.CreateVariable(
        "w_hidden", WeightParams((p.query_dim, p.hidden_dim), p.params_init,
                                 p.dtype))
    self.CreateVariable(
        "w_gmm", WeightParams((p.hidden_dim, 3 * p.num_mixtures),
                              p.params_init, p.dtype))

  def ZeroAttentionState(self, batch_size, src_len):
    return NestedMap(
        mu=jnp.zeros((batch_size, self.p.num_mixtures), jnp.float32))

  def ComputeContextVector(self, theta, packed, query, atten_state):
    p = self.p
    th = self.CastTheta(theta)
    h = jnp.tanh(jnp.einsum("bd,dh->bh", query, th.w_hidden))
    gmm = jnp.einsum("bh,hk->bk", h, th.w_gmm).astype(jnp.float32)
    w, delta, sigma = jnp.split(gmm, 3, axis=-1)         # [B, M] each
    weights = jax.nn.softmax(w, axis=-1)
    mu = atten_state.mu + jax.nn.softplus(delta)         # forward-only
    sigma = jax.nn.softplus(sigma) + 1e-3
    t = packed.source.shape[1]
    pos = jnp.arange(t, dtype=jnp.float32)[None, None, :]  # [1, 1, T]
    dens = weights[..., None] * jnp.exp(
        -0.5 * ((pos - mu[..., None]) / sigma[..., None]) ** 2) / (
            sigma[..., None] * math.sqrt(2 * math.pi))
    scores = jnp.sum(dens, axis=1)                       # [B, T]
    scores = jnp.where(packed.paddings > 0.5, 0.0, scores)
    probs = scores / jnp.maximum(jnp.sum(scores, -1, keepdims=True), 1e-10)
    ctx = jnp.einsum("bt,btd->bd", probs.astype(packed.source.dtype),
                     packed.source)
    return ctx, probs, NestedMap(mu=mu)


class MergerLayer(base_layer.BaseLayer):
  """Combines several context vectors (ref `attention.py:3608` MergerLayer):
  'mean' | 'sum' | 'concat' | 'weighted_sum' (learned scalar weights)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("merger_op", "mean", "mean|sum|concat|weighted_sum.")
    p.Define("num_sources", 2, "How many inputs (for weighted_sum).")
    p.Define("source_dim", 0, "Per-source dim (for weighted_sum).")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    if p.merger_op == "weighted_sum":
      self.CreateVariable(
          "weights",
          WeightParams((p.num_sources,),
                       py_utils.WeightInit.Constant(1.0 / p.num_sources),
                       p.dtype))

  def FProp(self, theta, contexts):
    p = self.p
    if p.merger_op == "mean":
      return sum(contexts) / len(contexts)
    if p.merger_op == "sum":
      return sum(contexts)
    if p.merger_op == "concat":
      return jnp.concatenate(contexts, axis=-1)
    if p.merger_op == "weighted_sum":
      th = self.CastTheta(theta)
      w = jax.nn.softmax(th.weights.astype(jnp.float32))
      return sum(w[i] * c for i, c in enumerate(contexts))
    raise ValueError(f"Unknown merger_op {p.merger_op}")


class MultiSourceAttention(base_layer.BaseLayer):
  """One attention per source + merger (ref `attention.py:3856`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("source_atten_tpls", [],
             "List of (name, attention Params) per source.")
    p.Define("merger_tpl", MergerLayer.Params(), "How to combine contexts.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self._names = [name for name, _ in p.source_atten_tpls]
    for name, tpl in p.source_atten_tpls:
      self.CreateChild(f"atten_{name}", tpl)
    self.CreateChild("merger",
                     p.merger_tpl.Copy().Set(num_sources=len(self._names)))

  def PackSource(self, theta, sources: NestedMap, paddings: NestedMap):
    packed = NestedMap()
    for name in self._names:
      packed.Set(name, getattr(self, f"atten_{name}").PackSource(
          self.ChildTheta(theta, f"atten_{name}"), sources.GetItem(name),
          paddings.GetItem(name)))
    return packed

  def ZeroAttentionState(self, batch_size, src_lens: dict):
    st = NestedMap()
    for name in self._names:
      st.Set(name, getattr(self, f"atten_{name}").ZeroAttentionState(
          batch_size, src_lens[name]))
    return st

  def ComputeContextVector(self, theta, packed, query, atten_state):
    ctxs, new_state = [], NestedMap()
    probs0 = None
    for name in self._names:
      att = getattr(self, f"atten_{name}")
      ctx, probs, st = att.ComputeContextVector(
          self.ChildTheta(theta, f"atten_{name}"), packed.GetItem(name),
          query, atten_state.GetItem(name))
      ctxs.append(ctx)
      new_state.Set(name, st)
      if probs0 is None:
        probs0 = probs
    merged = self.merger.FProp(self.ChildTheta(theta, "merger"), ctxs)
    return merged, probs0, new_state
