"""Host-side op equivalents: cached calls, permutations, static maps.

Re-designs the small CPU kernels the reference registers as TF ops:
`ops/functional_ops_kernels.cc` (CachedCall: run a function once, replay the
cached tensors), `ops/random_ops_kernels.cc` (RandomPermutationSequence:
epoch-wise shuffled id batches for sampling-without-replacement input
pipelines), `ops/static_map_op.cc` (compile-time string<->int maps), and
`ops/ml_perf_subword_op.cc` (MLPerf transformer subword detokenizer). In
the JAX stack these run on the host by construction, so they are plain
Python with numpy RNG — no kernel registry needed.
"""

from __future__ import annotations

import glob as glob_lib
import threading
from typing import Sequence

import numpy as np


class CachedCall:
  """Calls `fn` once; replays its result afterwards (ref CachedCall op).

  Thread-safe; `Reset()` drops the cache (ref op is per-session-run
  persistent, which a process-lifetime cache subsumes).
  """

  def __init__(self, fn):
    self._fn = fn
    self._lock = threading.Lock()
    self._has_result = False
    self._result = None

  def __call__(self):
    with self._lock:
      if not self._has_result:
        self._result = self._fn()
        self._has_result = True
      return self._result

  def Reset(self):
    with self._lock:
      self._has_result = False
      self._result = None


class RandomPermutationSequence:
  """Batches of a random permutation of [0, num) (ref
  `random_ops_kernels.cc:27`).

  Each epoch is one shuffled permutation, consumed `batch` ids at a time
  (the final slice of an epoch may be short). With `repeat=False`,
  `GetNext()` raises StopIteration at epoch end; with `repeat=True` a fresh
  permutation starts seamlessly.
  """

  def __init__(self, num: int, batch: int, repeat: bool = False,
               seed: int = 0):
    assert num > 0 and batch > 0
    self._num = num
    self._batch = batch
    self._repeat = repeat
    self._rng = np.random.default_rng(seed if seed else None)
    self._lock = threading.Lock()
    self._ids: list[int] = []
    self._Fill()

  def _Fill(self):
    self._ids = list(self._rng.permutation(self._num))

  def GetNext(self) -> np.ndarray:
    with self._lock:
      if not self._ids:
        if not self._repeat:
          raise StopIteration("Epoch ended.")
        self._Fill()
      take = self._ids[:self._batch]
      del self._ids[:len(take)]
      return np.asarray(take, np.int64)

  def __iter__(self):
    return self

  def __next__(self) -> np.ndarray:
    return self.GetNext()


class StaticMap:
  """Frozen string<->int map (ref `static_map_op.cc` StaticMapStringInt /
  StaticMapIntString, `x_ops.cc:926-985`).

  Built once from keys (ids default to positions) and vectorized both ways
  with an unknown fallback, like the reference ops' `unk` attr. Lookup of
  arrays preserves shape.
  """

  def __init__(self, keys: Sequence[str], ids: Sequence[int] | None = None,
               unk_id: int = -1, unk_token: str = ""):
    if ids is None:
      ids = range(len(keys))
    ids = [int(i) for i in ids]
    if len(set(keys)) != len(keys):
      raise ValueError("duplicate keys in StaticMap")
    if len(set(ids)) != len(ids):
      raise ValueError("duplicate ids in StaticMap")
    if len(keys) != len(ids):
      raise ValueError(f"{len(keys)} keys vs {len(ids)} ids")
    self._to_id = dict(zip(keys, ids))
    self._to_str = dict(zip(ids, keys))
    self._unk_id = unk_id
    self._unk_token = unk_token

  def StrToId(self, strs) -> np.ndarray:
    arr = np.asarray(strs)
    flat = [self._to_id.get(s, self._unk_id) for s in arr.reshape(-1)]
    return np.asarray(flat, np.int32).reshape(arr.shape)

  def IdToStr(self, ids) -> np.ndarray:
    arr = np.asarray(ids)
    flat = [self._to_str.get(int(i), self._unk_token)
            for i in arr.reshape(-1)]
    return np.asarray(flat, object).reshape(arr.shape)

  def __len__(self) -> int:
    return len(self._to_id)


class MlPerfSubword:
  """MLPerf transformer subword detokenizer (ref `ml_perf_subword_op.cc`).

  Vocab lines are quoted subtokens whose trailing `_` marks a word end
  (e.g. `'Wie_'`, `'geht'`, `'s_'`). Decode joins the subtokens, splits on
  `_`, and re-inserts spaces only between alphanumeric-starting fragments —
  punctuation glues to the previous word, matching the reference kernel.
  """

  def __init__(self, vocab_lines: Sequence[str] | None = None,
               vocab_glob: str | None = None):
    if (vocab_lines is None) == (vocab_glob is None):
      raise ValueError("pass exactly one of vocab_lines / vocab_glob")
    if vocab_glob is not None:
      files = sorted(glob_lib.glob(vocab_glob))
      if not files:
        raise FileNotFoundError(f"no vocab files match {vocab_glob!r}")
      vocab_lines = []
      for path in files:
        with open(path, encoding="utf-8") as f:
          vocab_lines.extend(f.read().splitlines())
    self._id_to_token = [self._StripQuotes(line) for line in vocab_lines]

  @staticmethod
  def _StripQuotes(line: str) -> str:
    line = line.strip()
    if len(line) >= 2 and line[0] == line[-1] and line[0] in "'\"":
      return line[1:-1]
    return line

  def Decode(self, ids: Sequence[int]) -> str:
    tokens = []
    for i in ids:
      if not 0 <= int(i) < len(self._id_to_token):
        raise IndexError(f"id {i} out of range [0, {len(self._id_to_token)})")
      tokens.append(self._id_to_token[int(i)])
    fragments = "".join(tokens).split("_")
    out = []
    for i, frag in enumerate(fragments):
      if (i > 0 and fragments[i - 1][:1].isalnum() and frag[:1].isalnum()):
        out.append(" ")
      out.append(frag)
    return "".join(out)
