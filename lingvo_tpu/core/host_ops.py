"""Host-side op equivalents: cached calls, random permutation sequences.

Re-designs the small CPU kernels the reference registers as TF ops:
`ops/functional_ops_kernels.cc` (CachedCall: run a function once, replay the
cached tensors) and `ops/random_ops_kernels.cc` (RandomPermutationSequence:
epoch-wise shuffled id batches for sampling-without-replacement input
pipelines). In the JAX stack these run on the host by construction, so they
are plain Python with numpy RNG — no kernel registry needed.
"""

from __future__ import annotations

import threading

import numpy as np


class CachedCall:
  """Calls `fn` once; replays its result afterwards (ref CachedCall op).

  Thread-safe; `Reset()` drops the cache (ref op is per-session-run
  persistent, which a process-lifetime cache subsumes).
  """

  def __init__(self, fn):
    self._fn = fn
    self._lock = threading.Lock()
    self._has_result = False
    self._result = None

  def __call__(self):
    with self._lock:
      if not self._has_result:
        self._result = self._fn()
        self._has_result = True
      return self._result

  def Reset(self):
    with self._lock:
      self._has_result = False
      self._result = None


class RandomPermutationSequence:
  """Batches of a random permutation of [0, num) (ref
  `random_ops_kernels.cc:27`).

  Each epoch is one shuffled permutation, consumed `batch` ids at a time
  (the final slice of an epoch may be short). With `repeat=False`,
  `GetNext()` raises StopIteration at epoch end; with `repeat=True` a fresh
  permutation starts seamlessly.
  """

  def __init__(self, num: int, batch: int, repeat: bool = False,
               seed: int = 0):
    assert num > 0 and batch > 0
    self._num = num
    self._batch = batch
    self._repeat = repeat
    self._rng = np.random.default_rng(seed if seed else None)
    self._lock = threading.Lock()
    self._ids: list[int] = []
    self._Fill()

  def _Fill(self):
    self._ids = list(self._rng.permutation(self._num))

  def GetNext(self) -> np.ndarray:
    with self._lock:
      if not self._ids:
        if not self._repeat:
          raise StopIteration("Epoch ended.")
        self._Fill()
      take = self._ids[:self._batch]
      del self._ids[:len(take)]
      return np.asarray(take, np.int64)

  def __iter__(self):
    return self

  def __next__(self) -> np.ndarray:
    return self.GetNext()
