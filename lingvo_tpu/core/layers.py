"""Core NN layers: projections, convs, embeddings, softmax, norms, dropout.

TPU-native re-design of the reference's `lingvo/core/layers.py` (7.3k LoC) and
`bn_layers.py`. Same capability surface — ProjectionLayer/FCLayer (`layers.py:845,1586`),
FeedForwardNet (`:1597`), Conv2D family (`:182-844`), embeddings (`:2679,3018`),
positional embeddings incl. rotary (`:3143-3558`), SimpleFullSoftmax (`:3697`),
deterministic dropout (`:4842-4926`), LayerNorm (`:4927`), BatchNorm
(`bn_layers.py:114`) — but computation is pure jnp/lax, weights are theta
pytrees, and sharding is expressed as mesh-axis names on WeightParams.

Matmul-heavy ops keep bf16-friendly shapes and rely on XLA fusion; no
hand-scheduling.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp

from lingvo_tpu.core import activations
from lingvo_tpu.core import base_layer
from lingvo_tpu.core import py_utils
from lingvo_tpu.core import quant_utils
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.core.py_utils import WeightInit, WeightParams


class IdentityLayer(base_layer.BaseLayer):

  def FProp(self, theta, x, *args):
    return x


# ---------------------------------------------------------------------------
# Projections / feed-forward.
# ---------------------------------------------------------------------------


class ProjectionLayer(base_layer.BaseLayer):
  """y = act(norm(x @ w + b)). Ref: layers.ProjectionLayer (`layers.py:845`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Input depth.")
    p.Define("output_dim", 0, "Output depth.")
    p.Define("activation", "NONE", "Activation name.")
    p.Define("has_bias", True, "Whether to add a bias.")
    p.Define("bias_init", 0.0, "Constant bias initialization.")
    p.Define("batch_norm", False, "Apply BatchNorm before activation.")
    p.Define("ln_tpl", None, "Optional LayerNorm params applied pre-activation.")
    p.Define("weight_norm", False, "Reparameterize w = g * v/||v||.")
    p.Define("qdomain", None,
             "Optional quant_utils.QDomain params: fake-quantize the weight "
             "and the output activation (ref QuantizableLayer wiring).")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.input_dim > 0 and p.output_dim > 0, p.name
    wsdm = p.weight_split_dims_mapping
    self.CreateVariable(
        "w",
        WeightParams(
            shape=(p.input_dim, p.output_dim),
            init=p.params_init,
            dtype=p.dtype,
            tensor_split_dims_mapping=wsdm))
    if p.weight_norm:
      self.CreateVariable(
          "g", WeightParams((p.output_dim,), WeightInit.Constant(0.0), p.dtype))
    if p.has_bias:
      bias_sharding = (wsdm[-1],) if wsdm else None
      self.CreateVariable(
          "b",
          WeightParams((p.output_dim,), WeightInit.Constant(p.bias_init),
                       p.dtype, tensor_split_dims_mapping=bias_sharding))
    if p.batch_norm:
      self.CreateChild("bn", BatchNormLayer.Params().Set(dim=p.output_dim))
    if p.qdomain is not None:
      self.CreateChild("qdomain", p.qdomain.Copy())

  def FProp(self, theta, inputs, paddings=None):
    p = self.p
    th = self.CastTheta(theta)
    x = self.ToFPropDtype(inputs)
    w = th.w
    if isinstance(w, quant_utils.Int8Weight):
      # int8-serving theta: the matmul runs in int8 on the MXU. Weight-norm
      # and fake-quant domains rewrite the float weight and cannot compose
      # with the frozen integer grid.
      assert not p.weight_norm and p.qdomain is None
      out = w.Einsum(x)
    else:
      if p.weight_norm:
        w = jnp.reshape((1.0 + th.g) / jnp.linalg.norm(w, axis=0),
                        (1, -1)) * w
      if p.qdomain is not None:
        # quantize the EFFECTIVE matmul weight (post weight-norm) — QAT must
        # simulate the weight the int8 deployment actually uses
        w = self.qdomain.QuantizeWeight(self.ChildTheta(theta, "qdomain"), w)
      out = jnp.einsum("...i,io->...o", x, w)
    if p.has_bias:
      out = out + th.b
    if p.batch_norm:
      out = self.bn.FProp(theta.bn, out, paddings)
    if p.activation != "NONE":
      out = activations.GetFn(p.activation)(out)
    if p.qdomain is not None:
      out = self.qdomain.QuantizeAct(
          self.ChildTheta(theta, "qdomain"), "act", out)
    if paddings is not None:
      out = py_utils.ApplyPadding(paddings, out)
    return out


class FCLayer(ProjectionLayer):
  """Fully-connected layer with RELU default (`layers.py:1586`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.activation = "RELU"
    return p


class FeedForwardNet(base_layer.BaseLayer):
  """MLP over hidden_layer_dims with dropout (`layers.py:1597`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Input depth.")
    p.Define("hidden_layer_dims", [], "Output dim of each layer.")
    p.Define("activation", "RELU", "Single name or list per layer.")
    p.Define("dropout_prob", 0.0, "Single prob or list per layer.")
    p.Define("has_bias", True, "Bias in each projection.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    dims = [p.input_dim] + list(p.hidden_layer_dims)
    num = len(p.hidden_layer_dims)
    acts = p.activation if isinstance(p.activation, (list, tuple)) else [
        p.activation
    ] * num
    drops = p.dropout_prob if isinstance(p.dropout_prob, (list, tuple)) else [
        p.dropout_prob
    ] * num
    self._dropout_probs = list(drops)
    projs = []
    for i in range(num):
      projs.append(ProjectionLayer.Params().Set(
          input_dim=dims[i], output_dim=dims[i + 1], activation=acts[i],
          has_bias=p.has_bias))
    self.CreateChildren("fc", projs)
    self.CreateChild("dropout", DeterministicDropoutLayer.Params())

  def FProp(self, theta, inputs, paddings=None):
    x = inputs
    for i, layer in enumerate(self.fc):
      x = layer.FProp(theta.fc[i], x, paddings)
      if self._dropout_probs[i] > 0.0:
        x = self.dropout.FProp(
            self.ChildTheta(theta, "dropout"), x,
            keep_prob=1.0 - self._dropout_probs[i], name_suffix=f"l{i}")
    return x


# ---------------------------------------------------------------------------
# Dropout.
# ---------------------------------------------------------------------------


class DeterministicDropoutLayer(base_layer.BaseLayer):
  """Dropout seeded from the step-seed context (`layers.py:4916`).

  Identity when eval-mode or no step seed is active, so eval FProps need no
  key plumbing.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("keep_prob", 1.0, "Keep probability (may be overridden per call).")
    p.Define("noise_shape_broadcast_dims", None,
             "Dims over which the dropout mask broadcasts (memory saving).")
    return p

  def _NameIsRequired(self):
    return False

  def FProp(self, theta, inputs, keep_prob=None, name_suffix="",
            extra_seed=None):
    p = self.p
    kp = p.keep_prob if keep_prob is None else keep_prob
    if kp >= 1.0 or py_utils.DoEval() or not py_utils.HasStepSeed():
      return inputs
    key = py_utils.StepSeed(f"{self.path}/{name_suffix}", extra_seed)
    shape = list(inputs.shape)
    if p.noise_shape_broadcast_dims:
      for d in p.noise_shape_broadcast_dims:
        shape[d] = 1
    mask = jax.random.bernoulli(key, kp, shape)
    return jnp.where(mask, inputs / jnp.asarray(kp, inputs.dtype),
                     jnp.zeros((), inputs.dtype))


DropoutLayer = DeterministicDropoutLayer


# ---------------------------------------------------------------------------
# Normalization.
# ---------------------------------------------------------------------------


class LayerNorm(base_layer.BaseLayer):
  """Layer normalization over the trailing dim (`layers.py:4927`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Depth of the input.")
    p.Define("epsilon", 1e-6, "Variance floor.")
    p.Define("use_fused_layernorm", False, "Hint only; XLA fuses anyway.")
    p.Define("direct_scale", False,
             "If True scale is applied as-is; else (1+scale).")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.input_dim > 0, p.name
    self.CreateVariable(
        "scale", WeightParams((p.input_dim,), WeightInit.Constant(0.0), p.dtype))
    self.CreateVariable(
        "bias", WeightParams((p.input_dim,), WeightInit.Constant(0.0), p.dtype))

  def FProp(self, theta, inputs):
    p = self.p
    th = self.CastTheta(theta)
    x = self.ToFPropDtype(inputs)
    # Always compute moments in f32 for stability under bf16 activations.
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    normed = (x32 - mean) * jax.lax.rsqrt(var + p.epsilon)
    normed = normed.astype(x.dtype)
    scale = th.scale if p.direct_scale else (1.0 + th.scale)
    return normed * scale + th.bias


class RmsNorm(base_layer.BaseLayer):
  """RMS normalization (no centering), common in large LMs."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Depth of the input.")
    p.Define("epsilon", 1e-6, "Variance floor.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self.CreateVariable(
        "scale",
        WeightParams((self.p.input_dim,), WeightInit.Constant(0.0), self.p.dtype))

  def FProp(self, theta, inputs):
    th = self.CastTheta(theta)
    x32 = inputs.astype(jnp.float32)
    ms = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    normed = (x32 * jax.lax.rsqrt(ms + self.p.epsilon)).astype(inputs.dtype)
    return normed * (1.0 + th.scale)


class BatchNormLayer(base_layer.BaseLayer):
  """Batch norm with functional moving-average updates (`bn_layers.py:114`).

  Train mode: uses batch moments, emits moving-stat updates through
  `py_utils.AddForwardStateUpdate` (collected by the train program); if a mesh
  axis name is given in `cross_replica_axes`, moments are all-reduced with
  psum — the TPU-native form of the reference's tpu_cross_replica BN.
  Eval mode: uses moving stats from theta.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("dim", 0, "Depth of the activation.")
    p.Define("decay", 0.999, "Moving-average decay.")
    p.Define("epsilon", 1e-3, "Variance floor.")
    p.Define("cross_replica_axes", None,
             "Mesh axis name(s) to all-reduce moments over (shard_map only).")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.dim > 0, p.name
    self.CreateVariable(
        "beta", WeightParams((p.dim,), WeightInit.Constant(0.0), p.dtype))
    self.CreateVariable(
        "gamma", WeightParams((p.dim,), WeightInit.Constant(0.0), p.dtype))
    # Moving stats live in theta but are non-trainable (collections tag).
    self.CreateVariable(
        "moving_mean",
        WeightParams((p.dim,), WeightInit.Constant(0.0), jnp.float32,
                     collections=("non_trainable", "moving_stats")))
    self.CreateVariable(
        "moving_variance",
        WeightParams((p.dim,), WeightInit.Constant(1.0), jnp.float32,
                     collections=("non_trainable", "moving_stats")))

  def _Moments(self, x32, paddings):
    p = self.p
    reduce_dims = tuple(range(x32.ndim - 1))
    if paddings is None:
      count = jnp.asarray(
          float(math.prod(x32.shape[:-1])), jnp.float32)
      mean_sum = jnp.sum(x32, axis=reduce_dims)
      sq_sum = jnp.sum(jnp.square(x32), axis=reduce_dims)
    else:
      mask = py_utils.SequenceMask(paddings)
      while mask.ndim < x32.ndim:
        mask = mask[..., None]
      # Count of valid positions across ALL reduced dims (broadcast the mask
      # over spatial dims it doesn't cover, excluding the channel dim).
      bmask = jnp.broadcast_to(mask, x32.shape[:-1] + (1,))
      count = jnp.maximum(jnp.sum(bmask), 1.0)
      mean_sum = jnp.sum(x32 * mask, axis=reduce_dims)
      sq_sum = jnp.sum(jnp.square(x32) * mask, axis=reduce_dims)
    if p.cross_replica_axes:
      mean_sum = jax.lax.psum(mean_sum, p.cross_replica_axes)
      sq_sum = jax.lax.psum(sq_sum, p.cross_replica_axes)
      count = jax.lax.psum(count, p.cross_replica_axes)
    mean = mean_sum / count
    var = jnp.maximum(sq_sum / count - jnp.square(mean), 0.0)
    return mean, var

  def FProp(self, theta, inputs, paddings=None):
    p = self.p
    th = self.CastTheta(theta)
    x = self.ToFPropDtype(inputs)
    x32 = x.astype(jnp.float32)
    if py_utils.DoEval():
      mean, var = theta.moving_mean, theta.moving_variance
    else:
      mean, var = self._Moments(x32, paddings)
      new_mean = theta.moving_mean * p.decay + mean * (1.0 - p.decay)
      new_var = theta.moving_variance * p.decay + var * (1.0 - p.decay)
      py_utils.AddForwardStateUpdate(f"{self.path}/moving_mean", new_mean)
      py_utils.AddForwardStateUpdate(f"{self.path}/moving_variance", new_var)
    normed = (x32 - mean) * jax.lax.rsqrt(var + p.epsilon)
    out = (normed.astype(x.dtype) * (1.0 + th.gamma) + th.beta)
    if paddings is not None:
      out = py_utils.ApplyPadding(paddings, out)
    return out


class GroupNormLayer(base_layer.BaseLayer):
  """Group normalization (`bn_layers.py` GroupNorm), used by Conformer."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("dim", 0, "Channel depth.")
    p.Define("num_groups", 32, "Number of groups.")
    p.Define("epsilon", 1e-3, "Variance floor.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.dim % p.num_groups == 0, (p.dim, p.num_groups)
    self.CreateVariable(
        "beta", WeightParams((p.dim,), WeightInit.Constant(0.0), p.dtype))
    self.CreateVariable(
        "gamma", WeightParams((p.dim,), WeightInit.Constant(0.0), p.dtype))

  def FProp(self, theta, inputs):
    p = self.p
    th = self.CastTheta(theta)
    x32 = inputs.astype(jnp.float32)
    shape = x32.shape
    grouped = x32.reshape(shape[:-1] + (p.num_groups, p.dim // p.num_groups))
    axes = tuple(range(1, grouped.ndim - 2)) + (grouped.ndim - 1,)
    mean = jnp.mean(grouped, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(grouped - mean), axis=axes, keepdims=True)
    normed = ((grouped - mean) * jax.lax.rsqrt(var + p.epsilon)).reshape(shape)
    return normed.astype(inputs.dtype) * (1.0 + th.gamma) + th.beta


# ---------------------------------------------------------------------------
# Convolutions (NHWC; lowered straight onto the MXU by XLA).
# ---------------------------------------------------------------------------


class Conv2DLayer(base_layer.BaseLayer):
  """2D convolution + optional BN/activation (`layers.py:182`).

  Input: [batch, height, width, in_channels] (NHWC; time-major ASR uses
  height=time). filter_shape = [fh, fw, in, out], filter_stride = [sh, sw].
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("filter_shape", (0, 0, 0, 0), "[fh, fw, cin, cout].")
    p.Define("filter_stride", (1, 1), "[stride_h, stride_w].")
    p.Define("dilation_rate", (1, 1), "[dil_h, dil_w].")
    p.Define("padding", "SAME", "SAME|VALID.")
    p.Define("activation", "NONE", "Activation name.")
    p.Define("batch_norm", True, "Apply BN after conv (ref default).")
    p.Define("has_bias", False, "Bias (only when no BN).")
    p.Define("causal_convolution", False,
             "Left-pad height (time) so output depends only on the past.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert all(d > 0 for d in p.filter_shape), p.filter_shape
    self.CreateVariable(
        "w", WeightParams(p.filter_shape, p.params_init, p.dtype))
    if p.has_bias:
      self.CreateVariable(
          "b",
          WeightParams((p.filter_shape[-1],), WeightInit.Constant(0.0), p.dtype))
    if p.batch_norm:
      self.CreateChild("bn", BatchNormLayer.Params().Set(dim=p.filter_shape[-1]))

  def _PadForCausal(self, x):
    """Left-pads time (height) so outputs depend only on the past.

    Returns (x, padding_spec) shared by all conv variants.
    """
    p = self.p
    if not p.causal_convolution:
      return x, p.padding
    fh = p.filter_shape[0]
    pad_h = (fh - 1) * p.dilation_rate[0]
    x = jnp.pad(x, ((0, 0), (pad_h, 0), (0, 0), (0, 0)))
    if p.padding == "VALID":
      return x, [(0, 0), (0, 0)]
    # SAME on width, explicit VALID on (already left-padded) time.
    return x, [(0, 0), ((p.filter_shape[1] - 1) // 2, p.filter_shape[1] // 2)]

  def _Conv(self, x, w):
    p = self.p
    x, padding = self._PadForCausal(x)
    return jax.lax.conv_general_dilated(
        x, w,
        window_strides=tuple(p.filter_stride),
        padding=padding,
        rhs_dilation=tuple(p.dilation_rate),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))

  def FProp(self, theta, inputs, paddings=None):
    """paddings: optional [b, t] time paddings (t = height dim)."""
    p = self.p
    th = self.CastTheta(theta)
    x = self.ToFPropDtype(inputs)
    if paddings is not None:
      x = x * py_utils.SequenceMask(paddings, x.dtype)[:, :, None, None]
    out = self._Conv(x, th.w)
    out_paddings = None
    if paddings is not None:
      # Derive from ACTUAL output length (VALID is shorter than t/stride).
      out_paddings = _StridedPaddings(paddings, p.filter_stride[0],
                                      out.shape[1])
    if p.has_bias:
      out = out + th.b
    if p.batch_norm:
      out = self.bn.FProp(theta.bn, out, out_paddings)
    if p.activation != "NONE":
      out = activations.GetFn(p.activation)(out)
    if out_paddings is not None:
      return out * py_utils.SequenceMask(out_paddings, out.dtype)[:, :, None,
                                                                  None], out_paddings
    return out


def _StridedPaddings(paddings, stride, out_len=None):
  """Paddings for a strided (conv/pool) output: window-start positions,
  trimmed to the op's actual output length (VALID < SAME)."""
  out = paddings if stride == 1 else paddings[:, ::stride]
  if out_len is not None:
    assert out.shape[1] >= out_len, (out.shape, out_len)
    out = out[:, :out_len]
  return out


class DepthwiseConv2DLayer(Conv2DLayer):
  """Depthwise conv: filter_shape=[fh, fw, cin, multiplier]."""

  def _Conv(self, x, w):
    p = self.p
    fh, fw, cin, mult = p.filter_shape
    w = jnp.reshape(w, (fh, fw, 1, cin * mult))
    x, padding = self._PadForCausal(x)
    return jax.lax.conv_general_dilated(
        x, w,
        window_strides=tuple(p.filter_stride),
        padding=padding,
        rhs_dilation=tuple(p.dilation_rate),
        feature_group_count=cin,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


class MaxPoolLayer(base_layer.BaseLayer):
  """Max pooling (`layers.py:2285`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("window_shape", (2, 2), "[h, w] window.")
    p.Define("window_stride", (2, 2), "[h, w] stride.")
    p.Define("padding", "SAME", "SAME|VALID.")
    return p

  def FProp(self, theta, inputs, paddings=None):
    p = self.p
    if paddings is not None:
      # Padded frames must not win the max over real negative activations.
      big_neg = jnp.asarray(jnp.finfo(inputs.dtype).min / 2, inputs.dtype)
      inputs = py_utils.ApplyPadding(paddings, inputs, pad_value=big_neg)
    out = jax.lax.reduce_window(
        inputs, -jnp.inf, jax.lax.max,
        (1,) + tuple(p.window_shape) + (1,),
        (1,) + tuple(p.window_stride) + (1,), p.padding)
    if paddings is not None:
      out_paddings = _StridedPaddings(paddings, p.window_stride[0],
                                      out.shape[1])
      out = py_utils.ApplyPadding(out_paddings, out)
      return out, out_paddings
    return out


# ---------------------------------------------------------------------------
# Embeddings & positional embeddings.
# ---------------------------------------------------------------------------


class SimpleEmbeddingLayer(base_layer.BaseLayer):
  """Token embedding lookup (`layers.py:2679`).

  On TPU, gather of a sharded table is fine under GSPMD; optionally use
  one-hot matmul (`use_matmul`) which maps better onto the MXU for small
  vocabularies.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("vocab_size", 0, "Vocabulary size.")
    p.Define("embedding_dim", 0, "Depth of the embedding.")
    p.Define("use_matmul", False, "One-hot matmul instead of gather.")
    p.Define("scale_sqrt_depth", False, "Scale outputs by sqrt(dim).")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.vocab_size > 0 and p.embedding_dim > 0
    self.CreateVariable(
        "emb",
        WeightParams(
            shape=(p.vocab_size, p.embedding_dim),
            init=p.params_init if p.params_init != WeightInit.Xavier() else
            WeightInit.Gaussian(1.0 / math.sqrt(p.embedding_dim)),
            dtype=p.dtype,
            tensor_split_dims_mapping=p.weight_split_dims_mapping))

  def EmbLookup(self, theta, ids):
    p = self.p
    th = self.CastTheta(theta)
    if p.use_matmul:
      one_hot = jax.nn.one_hot(ids, p.vocab_size, dtype=th.emb.dtype)
      # Selection matmul: full precision so lookup == gather bit-for-bit-ish.
      out = jnp.einsum("...v,vd->...d", one_hot, th.emb,
                       precision=jax.lax.Precision.HIGHEST)
    else:
      out = jnp.take(th.emb, ids, axis=0)
    if p.scale_sqrt_depth:
      out = out * math.sqrt(p.embedding_dim)
    return out

  def FProp(self, theta, ids):
    return self.EmbLookup(theta, ids)


class PositionalEmbeddingLayer(base_layer.BaseLayer):
  """Sinusoidal positional embedding (`layers.py:3143`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("embedding_dim", 0, "Depth.")
    p.Define("min_timescale", 1, "Min timescale.")
    p.Define("max_timescale", 1e4, "Max timescale.")
    return p

  def _NameIsRequired(self):
    return False

  def FProp(self, theta, seq_length=None, position=None):
    """Returns [seq_length, dim] or per-position embeddings for `position`."""
    p = self.p
    assert p.embedding_dim % 2 == 0
    if position is None:
      position = jnp.arange(seq_length, dtype=jnp.float32)
    position = position.astype(jnp.float32)
    num_timescales = p.embedding_dim // 2
    log_inc = math.log(p.max_timescale / p.min_timescale) / max(
        1, num_timescales - 1)
    inv_timescales = p.min_timescale * jnp.exp(
        jnp.arange(num_timescales, dtype=jnp.float32) * -log_inc)
    scaled = position[..., None] * inv_timescales
    signal = jnp.concatenate([jnp.sin(scaled), jnp.cos(scaled)], axis=-1)
    return self.ToFPropDtype(signal)


class RotaryPositionalEmbeddingLayer(base_layer.BaseLayer):
  """Rotary position embedding (`layers.py:3466` RoPE)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("embedding_dim", 0, "Per-head dim to rotate (must be even).")
    p.Define("min_timescale", 1, "Min timescale.")
    p.Define("max_timescale", 1e4, "Max timescale.")
    return p

  def _NameIsRequired(self):
    return False

  def FProp(self, theta, inputs, position=None):
    """inputs: [..., t, n, h]; rotates the first embedding_dim of h.

    When embedding_dim < h, the remaining h - embedding_dim features pass
    through unrotated (partial-rotary).
    """
    p = self.p
    dim = p.embedding_dim or inputs.shape[-1]
    assert dim % 2 == 0 and dim <= inputs.shape[-1], (dim, inputs.shape)
    x_rot, x_pass = inputs[..., :dim], inputs[..., dim:]
    half = dim // 2
    fraction = jnp.arange(half, dtype=jnp.float32) / half
    timescale = p.min_timescale * (p.max_timescale / p.min_timescale)**fraction
    t_ax = inputs.ndim - 3
    if position is None:
      position = jnp.arange(inputs.shape[t_ax], dtype=jnp.float32)
      shape = [1] * inputs.ndim
      shape[t_ax] = inputs.shape[t_ax]
      position = position.reshape(shape)
    else:
      while position.ndim < inputs.ndim:
        position = position[..., None]
    sinusoid = position / timescale
    sin, cos = jnp.sin(sinusoid), jnp.cos(sinusoid)
    first, second = jnp.split(x_rot.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate(
        [first * cos - second * sin, second * cos + first * sin], axis=-1)
    rotated = rotated.astype(inputs.dtype)
    if x_pass.shape[-1]:
      return jnp.concatenate([rotated, x_pass], axis=-1)
    return rotated


# ---------------------------------------------------------------------------
# Softmax / output layers.
# ---------------------------------------------------------------------------


class SimpleFullSoftmax(base_layer.BaseLayer):
  """Full softmax with xent helpers (`layers.py:3697`).

  Logits in fprop dtype, log-softmax/xent in float32 (TPU numerics policy).
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Input depth.")
    p.Define("num_classes", 0, "Output classes.")
    p.Define("has_bias", True, "Bias on logits.")
    p.Define("logits_soft_max", 0.0, "If >0, cap logits with tanh.")
    p.Define("xent_block_size", 0,
             "If >0, FProp with class_ids computes the fused blockwise "
             "xent (ops/fused_xent.py) this many vocab entries at a time "
             "and never materializes [..., V] logits (out.logits and "
             "out.log_probs are None; out.argmax/label_log_probs are "
             "provided instead). 0 = exact legacy dense path.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateVariable(
        "linear",
        WeightParams(
            shape=(p.input_dim, p.num_classes),
            init=p.params_init,
            dtype=p.dtype,
            tensor_split_dims_mapping=p.weight_split_dims_mapping))
    if p.has_bias:
      self.CreateVariable(
          "bias",
          WeightParams((p.num_classes,), WeightInit.Constant(0.0), p.dtype))

  def Logits(self, theta, inputs):
    p = self.p
    th = self.CastTheta(theta)
    logits = jnp.einsum("...i,io->...o", self.ToFPropDtype(inputs), th.linear)
    if p.has_bias:
      logits = logits + th.bias
    if p.logits_soft_max > 0:
      logits = p.logits_soft_max * jnp.tanh(logits / p.logits_soft_max)
    return logits

  def XentLossFromLogits(self, logits, class_ids=None, class_probabilities=None,
                         label_smoothing=0.0):
    """Returns NestedMap(per_example_xent, log_probs) in float32."""
    return XentLossFromLogits(logits, self.p.num_classes, class_ids,
                              class_probabilities, label_smoothing)

  def FProp(self, theta, inputs, class_ids=None, class_probabilities=None,
            label_smoothing=0.0):
    p = self.p
    if FusedXentEligible(p, class_ids, class_probabilities):
      th = self.CastTheta(theta)
      return _FusedXentFProp(
          self, self.ToFPropDtype(inputs), th.linear, class_ids,
          label_smoothing, weight_layout="dv",
          bias=th.bias if p.has_bias else None)
    logits = self.Logits(theta, inputs)
    out = self.XentLossFromLogits(
        logits, class_ids, class_probabilities, label_smoothing)
    out.logits = logits
    return out


def FusedXentEligible(p, class_ids, class_probabilities) -> bool:
  """Gate for the blockwise fused LM-head xent: opted in via
  p.xent_block_size, needs integer labels (dense class_probabilities would
  re-materialize [..., V] anyway — fall back to the legacy path)."""
  return (getattr(p, "xent_block_size", 0) > 0 and class_ids is not None
          and class_probabilities is None)


def _FusedXentFProp(layer, inputs, weight, class_ids, label_smoothing,
                    weight_layout, bias=None):
  """Shared fused-path FProp for the softmax layers: same NestedMap shape
  as the dense path minus the [..., V] tensors, plus the per-block argmax
  (so `fraction_of_correct_next_step_preds` needn't re-materialize
  logits) and the label log-probs (the scoring path)."""
  from lingvo_tpu.ops import fused_xent
  p = layer.p
  out = fused_xent.FusedXent(
      inputs, weight, class_ids, block_size=p.xent_block_size,
      bias=bias, logits_soft_max=p.logits_soft_max,
      label_smoothing=label_smoothing, weight_layout=weight_layout)
  return NestedMap(per_example_xent=out.per_example_xent,
                   log_probs=None, logits=None,
                   label_log_probs=out.label_log_prob,
                   argmax=out.argmax)


class SingleShardFullSoftmax(SimpleFullSoftmax):
  """Full softmax for huge vocabularies (ref `layers.py:4494`).

  Two memory levers, composable:
  - vocab-dim sharding: set `weight_split_dims_mapping=(None, 'model')` and
    the [D, V] table plus each logits chunk shard over the model axis
    (GSPMD inserts the collectives) — the reference's SingleShard* family;
  - `chunk_size`: computes per-example xent `chunk_size` rows at a time
    with `lax.map`, never materializing the full [B*T, V] logits
    (ref `layers.py:3991-4040` chunked xent).
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("chunk_size", 0,
             "If >0, rows per xent chunk (memory over one big matmul).")
    return p

  def FProp(self, theta, inputs, class_ids=None, class_probabilities=None,
            label_smoothing=0.0):
    p = self.p
    if p.chunk_size <= 0 or class_ids is None:
      return super().FProp(theta, inputs, class_ids, class_probabilities,
                           label_smoothing)
    assert class_probabilities is None, "chunked path needs class_ids"
    lead_shape = class_ids.shape
    m = int(math.prod(lead_shape))
    x = inputs.reshape(m, inputs.shape[-1])
    ids = class_ids.reshape(m)
    pad = (-m) % p.chunk_size
    if pad:
      x = jnp.pad(x, ((0, pad), (0, 0)))
      ids = jnp.pad(ids, (0, pad))
    xc = x.reshape(-1, p.chunk_size, x.shape[-1])
    idc = ids.reshape(-1, p.chunk_size)

    def _Chunk(args):
      xi, idi = args
      logits = self.Logits(theta, xi)
      out = XentLossFromLogits(logits, p.num_classes, class_ids=idi,
                               label_smoothing=label_smoothing)
      return out.per_example_xent

    xent = jax.lax.map(_Chunk, (xc, idc)).reshape(-1)[:m]
    return NestedMap(per_example_xent=xent.reshape(lead_shape),
                     log_probs=None, logits=None)


class SharedEmbeddingSoftmaxLayer(base_layer.BaseLayer):
  """Ties input embedding and softmax weights (common LM configuration)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("vocab_size", 0, "Vocab.")
    p.Define("embedding_dim", 0, "Depth.")
    p.Define("scale_sqrt_depth", True, "Scale embeddings by sqrt(dim).")
    p.Define("logits_soft_max", 0.0, "If >0, cap logits with tanh.")
    p.Define("xent_block_size", 0,
             "If >0, FProp with class_ids computes the fused blockwise "
             "xent (ops/fused_xent.py) over the tied table and never "
             "materializes [..., V] logits. 0 = exact legacy dense path.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateVariable(
        "emb",
        WeightParams(
            shape=(p.vocab_size, p.embedding_dim),
            init=WeightInit.Gaussian(1.0 / math.sqrt(p.embedding_dim)),
            dtype=p.dtype,
            tensor_split_dims_mapping=p.weight_split_dims_mapping))

  def EmbLookup(self, theta, ids):
    p = self.p
    th = self.CastTheta(theta)
    emb = th.emb
    if isinstance(emb, quant_utils.Int8Weight):
      # gather int8 rows and dequantize by the per-row ('vd') scale — a
      # lookup has no matmul to run in int8, so this is exact w.r.t. the
      # frozen grid.
      rows = jnp.take(emb.w_int8, ids, axis=0).astype(jnp.float32)
      out = (rows * jnp.take(emb.scale.astype(jnp.float32), ids,
                             axis=0)).astype(self.fprop_dtype)
    else:
      out = jnp.take(emb, ids, axis=0)
    if p.scale_sqrt_depth:
      out = out * math.sqrt(p.embedding_dim)
    return out

  def Logits(self, theta, inputs):
    th = self.CastTheta(theta)
    if isinstance(th.emb, quant_utils.Int8Weight):
      # tied softmax over the int8 table: [..., D] x int8 [V, D] ('vd').
      logits = th.emb.Einsum(self.ToFPropDtype(inputs))
    else:
      logits = jnp.einsum("...d,vd->...v", self.ToFPropDtype(inputs), th.emb)
    if self.p.logits_soft_max > 0:
      logits = self.p.logits_soft_max * jnp.tanh(logits / self.p.logits_soft_max)
    return logits

  def XentLossFromLogits(self, logits, class_ids=None, class_probabilities=None,
                         label_smoothing=0.0):
    return XentLossFromLogits(logits, self.p.vocab_size, class_ids,
                              class_probabilities, label_smoothing)

  def FProp(self, theta, inputs, class_ids=None, class_probabilities=None,
            label_smoothing=0.0):
    if (FusedXentEligible(self.p, class_ids, class_probabilities)
        and not isinstance(theta.emb, quant_utils.Int8Weight)):
      # the fused blockwise kernel slices the float table; int8-serving
      # thetas take the dense Logits path below (scoring, not training).
      th = self.CastTheta(theta)
      return _FusedXentFProp(
          self, self.ToFPropDtype(inputs), th.emb, class_ids,
          label_smoothing, weight_layout="vd")
    logits = self.Logits(theta, inputs)
    out = self.XentLossFromLogits(
        logits, class_ids, class_probabilities, label_smoothing)
    out.logits = logits
    return out

  @property
  def num_classes(self):
    return self.p.vocab_size


def XentLossFromLogits(logits, num_classes, class_ids=None,
                       class_probabilities=None, label_smoothing=0.0):
  """Softmax cross-entropy in float32; returns NestedMap(per_example_xent,
  log_probs)."""
  logits32 = logits.astype(jnp.float32)
  log_probs = jax.nn.log_softmax(logits32)
  if class_probabilities is None:
    assert class_ids is not None
    class_probabilities = jax.nn.one_hot(
        class_ids, num_classes, dtype=jnp.float32)
  if label_smoothing > 0.0:
    class_probabilities = ((1.0 - label_smoothing) * class_probabilities +
                           label_smoothing / num_classes)
  per_example_xent = -jnp.sum(class_probabilities * log_probs, axis=-1)
  return NestedMap(per_example_xent=per_example_xent, log_probs=log_probs)


# ---------------------------------------------------------------------------
# Label smoothing (standalone, for seq2seq targets).
# ---------------------------------------------------------------------------


class UniformLabelSmoother(base_layer.BaseLayer):
  """Uniform label smoothing (`layers.py` UniformLabelSmoother)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("num_classes", 0, "Classes.")
    p.Define("uncertainty", 0.1, "Smoothing mass.")
    return p

  def _NameIsRequired(self):
    return False

  def FProp(self, theta, target_ids):
    p = self.p
    one_hot = jax.nn.one_hot(target_ids, p.num_classes, dtype=jnp.float32)
    return (1.0 - p.uncertainty) * one_hot + p.uncertainty / p.num_classes


class EinsumEmbeddingLayer(SimpleEmbeddingLayer):
  """Embedding as a pure einsum over one-hot ids (ref
  `layers.py:3018` EinsumEmbeddingLayer): SimpleEmbeddingLayer with the
  matmul formulation forced on — the MXU-native choice, and the one GSPMD
  partitions cleanly when the table is sharded (gather would all-gather
  the table)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.use_matmul = True
    return p


class SampledSoftmax(base_layer.BaseLayer):
  """Sampled softmax for huge vocabularies (ref `SimpleFullSoftmax`'s
  num_sampled path, `layers.py:3697+` — what the word-level 793k-vocab
  1B-words configs need).

  Training computes logits only over the true class + num_sampled
  log-uniform (Zipfian) negatives with the standard expected-count
  correction; eval uses the full softmax. Sampling draws from the step-seed
  context so it is deterministic per step.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Input depth.")
    p.Define("num_classes", 0, "Full vocabulary size.")
    p.Define("num_sampled", 4096, "Negatives sampled per batch.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.input_dim > 0 and p.num_classes > 0
    self.CreateVariable(
        "w", WeightParams((p.num_classes, p.input_dim), p.params_init,
                          p.dtype,
                          tensor_split_dims_mapping=(
                              p.weight_split_dims_mapping)))
    self.CreateVariable(
        "b", WeightParams((p.num_classes,), WeightInit.Constant(0.0),
                          p.dtype))

  def _LogExpectedCount(self, ids):
    """log E[count(id)] under num_sampled draws of the log-uniform (Zipf)
    sampler (ref TF's log_uniform_candidate_sampler + sampled-softmax
    correction logit - log Q): E[count] = num_sampled * P(id)."""
    ids = ids.astype(jnp.float32)
    log_p = jnp.log(
        jnp.log((ids + 2.0) / (ids + 1.0)) /
        math.log(self.p.num_classes + 1.0))
    return log_p + math.log(self.p.num_sampled)

  def _SampleNegatives(self, key):
    """Log-uniform sampling via inverse CDF: id = floor(exp(u*log(V+1)))-1."""
    p = self.p
    u = jax.random.uniform(key, (p.num_sampled,))
    ids = jnp.exp(u * math.log(p.num_classes + 1.0)) - 1.0
    return jnp.clip(ids.astype(jnp.int32), 0, p.num_classes - 1)

  def Logits(self, theta, inputs):
    """Full logits (eval / decode path)."""
    th = self.CastTheta(theta)
    return jnp.einsum("...d,vd->...v", self.ToFPropDtype(inputs),
                      th.w) + th.b

  def XentLossFromInputs(self, theta, inputs, class_ids):
    """Sampled-softmax xent: inputs [..., D], class_ids [...] -> xent [...].

    Falls back to the full softmax outside training (no step seed).
    """
    p = self.p
    th = self.CastTheta(theta)
    if py_utils.DoEval() or not py_utils.HasStepSeed():
      logits = self.Logits(theta, inputs).astype(jnp.float32)
      return XentLossFromLogits(logits, p.num_classes,
                                class_ids=class_ids).per_example_xent
    key = py_utils.StepSeed(f"{self.path}/sampled_softmax")
    neg_ids = self._SampleNegatives(key)                   # [S]
    x = self.ToFPropDtype(inputs)
    # true-class logit with its correction
    w_true = jnp.take(th.w, class_ids, axis=0)             # [..., D]
    b_true = jnp.take(th.b, class_ids, axis=0)
    true_logit = jnp.sum(x * w_true, -1) + b_true
    true_logit = true_logit.astype(jnp.float32) - self._LogExpectedCount(
        class_ids)
    # negative logits with their corrections
    w_neg = jnp.take(th.w, neg_ids, axis=0)                # [S, D]
    b_neg = jnp.take(th.b, neg_ids, axis=0)
    neg_logits = jnp.einsum("...d,sd->...s", x, w_neg) + b_neg
    neg_logits = neg_logits.astype(jnp.float32) - self._LogExpectedCount(
        neg_ids)
    # mask accidental hits of the true class among negatives
    hit = (neg_ids == class_ids[..., None])
    neg_logits = jnp.where(hit, -1e9, neg_logits)
    all_logits = jnp.concatenate([true_logit[..., None], neg_logits], -1)
    return -jax.nn.log_softmax(all_logits, axis=-1)[..., 0]


class StackingOverTime(base_layer.BaseLayer):
  """Stacks adjacent frames and subsamples time (ref
  `layers.py:2006` StackingOverTime — the classic ASR encoder front):
  [b, t, d] -> [b, ceil(t/stride), d*(left+1+right)]."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("left_context", 0, "Past frames stacked per output frame.")
    p.Define("right_context", 2, "Future frames stacked.")
    p.Define("stride", 3, "Output frame subsampling.")
    return p

  @property
  def window_size(self):
    return self.p.left_context + 1 + self.p.right_context

  def FProp(self, theta, inputs, paddings=None):
    """Returns (stacked [b, t_out, d*window], out_paddings [b, t_out])."""
    p = self.p
    b, t, d = inputs.shape
    if paddings is None:
      paddings = jnp.zeros((b, t), inputs.dtype)
    x = jnp.pad(inputs, ((0, 0), (p.left_context, p.right_context), (0, 0)))
    pad = jnp.pad(paddings, ((0, 0), (p.left_context, p.right_context)),
                  constant_values=1.0)
    frames = [x[:, i:i + t] for i in range(self.window_size)]
    stacked = jnp.concatenate(frames, axis=-1)             # [b, t, d*w]
    stacked = stacked[:, ::p.stride]
    # an output frame is padding iff its CENTER frame was padding (ref)
    out_paddings = pad[:, p.left_context:p.left_context + t][:, ::p.stride]
    return stacked, out_paddings
