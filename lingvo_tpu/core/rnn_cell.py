"""RNN cells: LSTM (plain/LayerNorm), GRU, SRU.

Re-designs `lingvo/core/rnn_cell.py` (LSTMCellSimple:213, GRUCell:2683,
SRUCell:2174). A cell is a pure step: `FProp(theta, state0, inputs) ->
state1` with `GetOutput(state)` extracting the emitted tensor — the exact
shape `recurrent.Recurrent`/`lax.scan` wants. Gate matmuls are fused into one
[D+H, 4H] einsum for the MXU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.core.py_utils import WeightInit, WeightParams


class RNNCell(base_layer.BaseLayer):

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("num_input_nodes", 0, "Input dim D.")
    p.Define("num_output_nodes", 0, "Output/hidden dim H.")
    p.Define("reset_cell_state", False,
             "Reset state at padding boundaries (packed inputs).")
    return p

  def InitState(self, batch_size: int) -> NestedMap:
    raise NotImplementedError

  def GetOutput(self, state: NestedMap) -> jax.Array:
    return state.m

  def PreProcessInputs(self, theta, inputs_btd):
    """Optional time-parallel transform applied ONCE before the scan.

    Cells whose input projection does not depend on recurrent state (SRU)
    override this so the big matmul runs over [b, t, d] outside the
    recurrence; FProp then consumes the transformed per-step inputs.
    """
    return inputs_btd

  def _ApplyPadding(self, new_state, state0, padding):
    """Padded steps: hold state (default) or zero it (reset_cell_state=True,
    so packed segments start fresh after padding — ref reset_cell_state).

    Broadcasts the [b] padding to each state leaf's rank (ConvLSTM states
    are [b, H, W, C])."""
    if padding is None:
      return new_state

    def _Pad(leaf):
      return padding.reshape((-1,) + (1,) * (leaf.ndim - 1)).astype(
          leaf.dtype)

    if self.p.reset_cell_state:
      return jax.tree_util.tree_map(
          lambda n: n * (1.0 - _Pad(n)), new_state)
    return jax.tree_util.tree_map(
        lambda n, o: n * (1.0 - _Pad(n)) + o * _Pad(n), new_state, state0)


class LSTMCellSimple(RNNCell):
  """Standard LSTM with forget bias, optional cell clipping + projection
  (ref LSTMCellSimple:213).

  Quantization: four QDomain hooks matching the reference's placement
  (ref `rnn_cell.py:279-297` qdomain.{weight,fullyconnected,c_state,m_state}
  and the QWeight/QAct calls at `:578-645`). Because cells run inside
  `lax.scan`, use stateless domains (FixedRangeQDomain /
  ScheduledClipQDomain) for the activation hooks — EMA-tracked domains
  would try to emit forward-state updates from inside the scan trace.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("forget_gate_bias", 0.0, "Added to the forget gate preact.")
    p.Define("cell_value_cap", 10.0, "If >0, clip cell values to +-cap.")
    p.Define("num_hidden_nodes", 0,
             "If >0, cell dim differs from output (adds a projection).")
    p.Define("enable_lstm_bias", True, "Use a bias term.")
    p.Define("qdomain_weight", None,
             "QDomain params for the gate matmul weight (ref qdomain.weight).")
    p.Define("qdomain_fullyconnected", None,
             "QDomain for the gate pre-activations ('add_bias' hook).")
    p.Define("qdomain_c_state", None,
             "QDomain for the cell state ('c_output_gate' hook).")
    p.Define("qdomain_m_state", None,
             "QDomain for the emitted m state and output projection.")
    return p

  _QDOMAINS = ("weight", "fullyconnected", "c_state", "m_state")

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    d, h = p.num_input_nodes, self.hidden_size
    self.CreateVariable(
        "wm",
        WeightParams((d + p.num_output_nodes, 4 * h), p.params_init, p.dtype))
    if p.enable_lstm_bias:
      self.CreateVariable(
          "b", WeightParams((4 * h,), WeightInit.Constant(0.0), p.dtype))
    if p.num_hidden_nodes:
      self.CreateVariable(
          "w_proj",
          WeightParams((h, p.num_output_nodes), p.params_init, p.dtype))
    for dom in self._QDOMAINS:
      tpl = p.Get(f"qdomain_{dom}")
      if tpl is not None:
        self.CreateChild(f"qdomain_{dom}", tpl.Copy())

  def _QWeight(self, theta, dom: str, w):
    if self.p.Get(f"qdomain_{dom}") is None:
      return w
    child = getattr(self, f"qdomain_{dom}")
    return child.QuantizeWeight(self.ChildTheta(theta, f"qdomain_{dom}"), w)

  def _QAct(self, theta, dom: str, name: str, x):
    if self.p.Get(f"qdomain_{dom}") is None:
      return x
    child = getattr(self, f"qdomain_{dom}")
    return child.QuantizeAct(
        self.ChildTheta(theta, f"qdomain_{dom}"), name, x)

  @property
  def hidden_size(self):
    return self.p.num_hidden_nodes or self.p.num_output_nodes

  def InitState(self, batch_size):
    p = self.p
    return NestedMap(
        m=jnp.zeros((batch_size, p.num_output_nodes), self.fprop_dtype),
        c=jnp.zeros((batch_size, self.hidden_size), self.fprop_dtype))

  def _Gates(self, theta, xm):
    """Gate pre-activations [b, 4H]; subclass hook (LN variant)."""
    th = self.CastTheta(theta)
    gates = xm @ self._QWeight(theta, "weight", th.wm)
    if self.p.enable_lstm_bias:
      gates = gates + th.b
    return gates

  def FProp(self, theta, state0, inputs, padding=None, preprocessed=False):
    """inputs: [b, D]; padding: optional [b]."""
    del preprocessed  # identity PreProcessInputs
    p = self.p
    th = self.CastTheta(theta)
    xm = jnp.concatenate([self.ToFPropDtype(inputs), state0.m], axis=-1)
    gates = self._QAct(theta, "fullyconnected", "add_bias",
                       self._Gates(theta, xm))
    i, g, f, o = jnp.split(gates, 4, axis=-1)
    f = f + p.forget_gate_bias
    c = jax.nn.sigmoid(f) * state0.c + jax.nn.sigmoid(i) * jnp.tanh(g)
    if p.cell_value_cap > 0:
      c = jnp.clip(c, -p.cell_value_cap, p.cell_value_cap)
    c = self._QAct(theta, "c_state", "c_output_gate", c)
    m = self._QAct(theta, "m_state", "m_output",
                   jax.nn.sigmoid(o) * jnp.tanh(c))
    if p.num_hidden_nodes:
      m = self._QAct(theta, "m_state", "m_output_projection",
                     m @ self._QWeight(theta, "m_state", th.w_proj))
    return self._ApplyPadding(NestedMap(m=m, c=c), state0, padding)


class LayerNormalizedLSTMCellSimple(LSTMCellSimple):
  """LSTM with per-gate LayerNorm (ref LayerNormalizedLSTMCellSimple)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("layer_norm_epsilon", 1e-8, "LN epsilon.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self.CreateVariable(
        "ln_scale",
        WeightParams((4 * self.hidden_size,), WeightInit.Constant(1.0),
                     self.p.dtype))

  def _Gates(self, theta, xm):
    p = self.p
    th = self.CastTheta(theta)
    gates = xm @ self._QWeight(theta, "weight", th.wm)
    # per-gate LN over each H-slice, applied before the bias
    h = self.hidden_size
    gates = gates.reshape(gates.shape[0], 4, h)
    mean = jnp.mean(gates, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(gates - mean), axis=-1, keepdims=True)
    gates = (gates - mean) * jax.lax.rsqrt(var + p.layer_norm_epsilon)
    gates = gates.reshape(gates.shape[0], 4 * h) * th.ln_scale
    if p.enable_lstm_bias:
      gates = gates + th.b
    return gates


class GRUCell(RNNCell):
  """GRU (ref GRUCell:2683)."""

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    d, h = p.num_input_nodes, p.num_output_nodes
    self.CreateVariable(
        "w_rz", WeightParams((d + h, 2 * h), p.params_init, p.dtype))
    self.CreateVariable(
        "w_h", WeightParams((d + h, h), p.params_init, p.dtype))
    self.CreateVariable(
        "b_rz", WeightParams((2 * h,), WeightInit.Constant(0.0), p.dtype))
    self.CreateVariable(
        "b_h", WeightParams((h,), WeightInit.Constant(0.0), p.dtype))

  def InitState(self, batch_size):
    return NestedMap(
        m=jnp.zeros((batch_size, self.p.num_output_nodes), self.fprop_dtype))

  def FProp(self, theta, state0, inputs, padding=None, preprocessed=False):
    del preprocessed  # identity PreProcessInputs
    th = self.CastTheta(theta)
    x = self.ToFPropDtype(inputs)
    xm = jnp.concatenate([x, state0.m], axis=-1)
    r, z = jnp.split(jax.nn.sigmoid(xm @ th.w_rz + th.b_rz), 2, axis=-1)
    h_cand = jnp.tanh(
        jnp.concatenate([x, r * state0.m], axis=-1) @ th.w_h + th.b_h)
    m = (1.0 - z) * state0.m + z * h_cand
    return self._ApplyPadding(NestedMap(m=m), state0, padding)


class SRUCell(RNNCell):
  """Simple Recurrent Unit (ref SRUCell:2174): the input projection is
  time-parallel (computed once over [b, t, d] via PreProcessInputs); only
  cheap elementwise ops recur inside the scan — TPU-friendly."""

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    d, h = p.num_input_nodes, p.num_output_nodes
    self.CreateVariable(
        "w", WeightParams((d, 4 * h), p.params_init, p.dtype))
    self.CreateVariable(
        "b", WeightParams((4 * h,), WeightInit.Constant(0.0), p.dtype))

  def InitState(self, batch_size):
    p = self.p
    return NestedMap(
        m=jnp.zeros((batch_size, p.num_output_nodes), self.fprop_dtype),
        c=jnp.zeros((batch_size, p.num_output_nodes), self.fprop_dtype))

  def PreProcessInputs(self, theta, inputs_btd):
    th = self.CastTheta(theta)
    return self.ToFPropDtype(inputs_btd) @ th.w + th.b

  def FProp(self, theta, state0, inputs, padding=None, preprocessed=False):
    """`preprocessed=True` means `inputs` is the [b, 4H] PreProcessInputs
    output (FRNN sets this); otherwise a raw [b, D] input is projected here.
    """
    proj = inputs if preprocessed else self.PreProcessInputs(theta, inputs)
    x_t, f_pre, r_pre, x_skip = jnp.split(proj, 4, axis=-1)
    f = jax.nn.sigmoid(f_pre)
    r = jax.nn.sigmoid(r_pre)
    c = f * state0.c + (1.0 - f) * x_t
    m = r * jnp.tanh(c) + (1.0 - r) * x_skip
    return self._ApplyPadding(NestedMap(m=m, c=c), state0, padding)


class ConvLSTMCell(RNNCell):
  """Convolutional LSTM over 2D feature maps (ref `rnn_cell.py:2015`
  ConvLSTMCell): states m/c are [b, H, W, C]; gates come from a conv over
  [input, m]."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("inputs_shape", [0, 0, 0], "Per-step input [H, W, C_in].")
    p.Define("cell_shape", [0, 0, 0], "State shape [H, W, C].")
    p.Define("filter_shape", [3, 3], "Conv kernel [fh, fw].")
    p.Define("forget_gate_bias", 1.0, "Added to the forget gate.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    h, w, c = p.cell_shape
    cin = p.inputs_shape[2]
    fh, fw = p.filter_shape
    assert p.inputs_shape[:2] == p.cell_shape[:2], "spatial dims must match"
    self.CreateVariable(
        "w_conv",
        py_utils.WeightParams((fh, fw, cin + c, 4 * c), p.params_init,
                              p.dtype))
    self.CreateVariable(
        "b", py_utils.WeightParams((4 * c,),
                                   py_utils.WeightInit.Constant(0.0),
                                   p.dtype))

  def InitState(self, batch_size):
    h, w, c = self.p.cell_shape
    z = jnp.zeros((batch_size, h, w, c), self.fprop_dtype)
    return NestedMap(m=z, c=z)

  def GetOutput(self, state):
    return state.m

  def FProp(self, theta, state0, inputs, padding=None, preprocessed=False):
    """inputs: [b, H, W, C_in]."""
    del preprocessed
    p = self.p
    th = self.CastTheta(theta)
    xm = jnp.concatenate([self.ToFPropDtype(inputs), state0.m], axis=-1)
    gates = jax.lax.conv_general_dilated(
        xm, th.w_conv, window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC")) + th.b
    i, g, f, o = jnp.split(gates, 4, axis=-1)
    f = f + p.forget_gate_bias
    c = jax.nn.sigmoid(f) * state0.c + jax.nn.sigmoid(i) * jnp.tanh(g)
    m = jax.nn.sigmoid(o) * jnp.tanh(c)
    return self._ApplyPadding(NestedMap(m=m, c=c), state0, padding)
