"""Quantization domains: fake-quant training for on-device int8 inference.

Re-designs `lingvo/core/quant_utils.py` (1.8k LoC: `QuantizableLayer` mixin,
`QDomain` fake-quant domains, clipping-cap schedules) for JAX: fake
quantization is a pure function with a straight-through estimator — XLA
fuses the quantize-dequantize pair into the surrounding matmul, so there is
no custom-op machinery. Activation ranges are tracked through the same
forward-state channel BatchNorm statistics use (EMA of batch max-abs),
matching the reference's `PassiveAsymQDomain` range tracking.

Usage: give a layer's Params a `qdomain` template
(`SymmetricQDomain.Params()`); the layer calls `QuantizeWeight` /
`QuantizeAct` around its matmuls (ProjectionLayer is wired).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.core.py_utils import WeightInit, WeightParams


def FakeQuant(x, scale, bits: int = 8):
  """Quantize-dequantize with a straight-through estimator.

  scale: positive per-tensor (or broadcastable) step size. The rounding is
  invisible to the gradient (STE): backward acts as identity within the
  clip range.
  """
  qmax = 2.0 ** (bits - 1) - 1
  scale = jnp.maximum(scale, 1e-8)
  q = jnp.clip(jnp.round(x / scale), -qmax - 1, qmax) * scale
  return x + jax.lax.stop_gradient(q - x)


class QDomain(base_layer.BaseLayer):
  """Base quantization domain (ref QDomain): no-op."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("bits", 8, "Quantized bit width.")
    return p

  def QuantizeWeight(self, theta, w):
    return w

  def QuantizeAct(self, theta, name: str, x):
    return x


class SymmetricQDomain(QDomain):
  """Symmetric per-tensor fake quant (ref SymmetricScheduledClipQDomain
  without the schedule): weights use their own max-abs; activations use an
  EMA max-abs range tracked as forward state (BN-style)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("ema_decay", 0.99, "Activation range EMA decay.")
    p.Define("act_names", ("act",),
             "Activation hooks this domain owns (one range var each).")
    return p

  def __init__(self, params):
    super().__init__(params)
    for name in self.p.act_names:
      self.CreateVariable(
          f"range_{name}",
          WeightParams((), WeightInit.Constant(1.0), jnp.float32,
                       collections=("non_trainable", "moving_stats")))

  def QuantizeWeight(self, theta, w):
    scale = jnp.max(jnp.abs(w.astype(jnp.float32))) / (
        2.0 ** (self.p.bits - 1) - 1)
    return FakeQuant(w, scale.astype(w.dtype), self.p.bits)

  def QuantizeAct(self, theta, name: str, x):
    p = self.p
    assert name in p.act_names, (name, p.act_names)
    th = self.CastTheta(theta)
    ema = th[f"range_{name}"].astype(jnp.float32)
    if not py_utils.DoEval():
      batch_max = jnp.max(jnp.abs(x.astype(jnp.float32)))
      new_range = p.ema_decay * ema + (1.0 - p.ema_decay) * batch_max
      py_utils.AddForwardStateUpdate(f"{self.path}/range_{name}", new_range)
      rng = new_range
    else:
      rng = ema
    scale = rng / (2.0 ** (p.bits - 1) - 1)
    return FakeQuant(x, scale.astype(x.dtype), p.bits)


class ScheduledClipQDomain(SymmetricQDomain):
  """Adds the reference's clipping-cap schedule (ref ClippingCapSchedule):
  the activation clip range anneals from start_cap to end_cap over
  [clip_start_step, clip_end_step], after which quantization is fully on."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("start_cap", 8.0, "Initial (loose) activation cap.")
    p.Define("end_cap", 1.0, "Final activation cap.")
    p.Define("clip_start_step", 0, "Annealing start.")
    p.Define("clip_end_step", 10000, "Annealing end.")
    return p

  def _Cap(self):
    p = self.p
    step = py_utils.GetGlobalStep()
    if step is None:
      return jnp.asarray(p.end_cap, jnp.float32)
    frac = jnp.clip(
        (step - p.clip_start_step) /
        max(p.clip_end_step - p.clip_start_step, 1), 0.0, 1.0)
    # log-space interpolation (ref ClippingCapSchedule._Value)
    return jnp.exp(jnp.log(p.start_cap) * (1 - frac) +
                   jnp.log(p.end_cap) * frac)

  def QuantizeAct(self, theta, name: str, x):
    cap = self._Cap().astype(x.dtype)
    x = jnp.clip(x, -cap, cap)
    scale = cap.astype(jnp.float32) / (2.0 ** (self.p.bits - 1) - 1)
    return FakeQuant(x, scale.astype(x.dtype), self.p.bits)
