"""Quantization domains: fake-quant training for on-device int8 inference.

Re-designs `lingvo/core/quant_utils.py` (1.8k LoC: `QuantizableLayer` mixin,
`QDomain` fake-quant domains, clipping-cap schedules) for JAX: fake
quantization is a pure function with a straight-through estimator — XLA
fuses the quantize-dequantize pair into the surrounding matmul, so there is
no custom-op machinery. Activation ranges are tracked through the same
forward-state channel BatchNorm statistics use (EMA of batch max-abs),
matching the reference's `PassiveAsymQDomain` range tracking.

Usage: give a layer's Params a `qdomain` template
(`SymmetricQDomain.Params()`); the layer calls `QuantizeWeight` /
`QuantizeAct` around its matmuls (ProjectionLayer is wired).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.core.py_utils import WeightInit, WeightParams


def FakeQuant(x, scale, bits: int = 8):
  """Quantize-dequantize with a straight-through estimator.

  scale: positive per-tensor (or broadcastable) step size. The rounding is
  invisible to the gradient (STE): backward acts as identity within the
  clip range.
  """
  qmax = 2.0 ** (bits - 1) - 1
  scale = jnp.maximum(scale, 1e-8)
  q = jnp.clip(jnp.round(x / scale), -qmax - 1, qmax) * scale
  return x + jax.lax.stop_gradient(q - x)


def MaxAbsSymmetricFakeQuant(w, bits: int):
  """Per-tensor symmetric weight fake quant (scale = max-abs / qmax) —
  the shared weight recipe of every non-per-channel domain."""
  scale = jnp.max(jnp.abs(w.astype(jnp.float32))) / (2.0 ** (bits - 1) - 1)
  return FakeQuant(w, scale.astype(w.dtype), bits)


class QDomain(base_layer.BaseLayer):
  """Base quantization domain (ref QDomain): no-op."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("bits", 8, "Quantized bit width.")
    return p

  def QuantizeWeight(self, theta, w):
    return w

  def QuantizeAct(self, theta, name: str, x):
    return x


class SymmetricQDomain(QDomain):
  """Symmetric per-tensor fake quant (ref SymmetricScheduledClipQDomain
  without the schedule): weights use their own max-abs; activations use an
  EMA max-abs range tracked as forward state (BN-style)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("ema_decay", 0.99, "Activation range EMA decay.")
    p.Define("act_names", ("act",),
             "Activation hooks this domain owns (one range var each).")
    return p

  def __init__(self, params):
    super().__init__(params)
    for name in self.p.act_names:
      self.CreateVariable(
          f"range_{name}",
          WeightParams((), WeightInit.Constant(1.0), jnp.float32,
                       collections=("non_trainable", "moving_stats")))

  def QuantizeWeight(self, theta, w):
    return MaxAbsSymmetricFakeQuant(w, self.p.bits)

  def QuantizeAct(self, theta, name: str, x):
    p = self.p
    assert name in p.act_names, (name, p.act_names)
    th = self.CastTheta(theta)
    ema = th[f"range_{name}"].astype(jnp.float32)
    if not py_utils.DoEval():
      batch_max = jnp.max(jnp.abs(x.astype(jnp.float32)))
      new_range = p.ema_decay * ema + (1.0 - p.ema_decay) * batch_max
      py_utils.AddForwardStateUpdate(f"{self.path}/range_{name}", new_range)
      rng = new_range
    else:
      rng = ema
    scale = rng / (2.0 ** (p.bits - 1) - 1)
    return FakeQuant(x, scale.astype(x.dtype), p.bits)


def FakeQuantAsym(x, scale, zero_point, bits: int = 8):
  """Asymmetric quantize-dequantize with STE (ref PassiveAsymQDomain).

  q = clip(round(x/scale) + zp) mapped back; backward is identity.
  """
  qmax = 2.0 ** bits - 1
  scale = jnp.maximum(scale, 1e-8)
  q = jnp.clip(jnp.round(x / scale) + zero_point, 0.0, qmax)
  dq = (q - zero_point) * scale
  return x + jax.lax.stop_gradient(dq - x)


class PassiveAsymQDomain(QDomain):
  """Asymmetric per-tensor fake quant with tracked min/max ranges (ref
  `quant_utils.py` PassiveAsymQDomain): activations carry EMA min and max
  (not just max-abs), giving a zero point — the right domain for
  post-RELU/softmax tensors whose range is one-sided."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("ema_decay", 0.99, "Range EMA decay.")
    p.Define("act_names", ("act",), "Tracked activation hooks.")
    return p

  def __init__(self, params):
    super().__init__(params)
    for name in self.p.act_names:
      self.CreateVariable(
          f"min_{name}",
          WeightParams((), WeightInit.Constant(0.0), jnp.float32,
                       collections=("non_trainable", "moving_stats")))
      self.CreateVariable(
          f"max_{name}",
          WeightParams((), WeightInit.Constant(1.0), jnp.float32,
                       collections=("non_trainable", "moving_stats")))

  def QuantizeWeight(self, theta, w):
    # weights stay symmetric (zero-centered by construction)
    return MaxAbsSymmetricFakeQuant(w, self.p.bits)

  def QuantizeAct(self, theta, name: str, x):
    p = self.p
    assert name in p.act_names, (name, p.act_names)
    th = self.CastTheta(theta)
    ema_min = th[f"min_{name}"].astype(jnp.float32)
    ema_max = th[f"max_{name}"].astype(jnp.float32)
    if not py_utils.DoEval():
      bmin = jnp.min(x.astype(jnp.float32))
      bmax = jnp.max(x.astype(jnp.float32))
      new_min = p.ema_decay * ema_min + (1.0 - p.ema_decay) * bmin
      new_max = p.ema_decay * ema_max + (1.0 - p.ema_decay) * bmax
      py_utils.AddForwardStateUpdate(f"{self.path}/min_{name}", new_min)
      py_utils.AddForwardStateUpdate(f"{self.path}/max_{name}", new_max)
      lo, hi = new_min, new_max
    else:
      lo, hi = ema_min, ema_max
    hi = jnp.maximum(hi, lo + 1e-6)
    scale = (hi - lo) / (2.0 ** p.bits - 1)
    zero_point = jnp.round(-lo / scale)
    return FakeQuantAsym(x, scale.astype(x.dtype),
                         zero_point.astype(x.dtype), p.bits)


class FixedRangeQDomain(QDomain):
  """Stateless fake quant over a fixed activation range (ref the reference's
  natural-range handling, e.g. `fns.qsoftmax` quantizing post-softmax probs
  over [0, 1]).

  The right domain wherever the range is known a priori — softmax probs
  [0, 1], tanh/cell states [-cap, cap] — and the ONLY kind (besides
  ScheduledClipQDomain) that is safe inside `lax.scan` bodies (RNN cells,
  repeated transformer stacks): it carries no tracked range state, so
  nothing has to escape the scan trace.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("range_min", -1.0, "Lower bound of the activation range.")
    p.Define("range_max", 1.0, "Upper bound.")
    return p

  def QuantizeWeight(self, theta, w):
    return MaxAbsSymmetricFakeQuant(w, self.p.bits)

  def QuantizeAct(self, theta, name: str, x):
    p = self.p
    lo, hi = float(p.range_min), float(p.range_max)
    assert hi > lo, (lo, hi)
    x = jnp.clip(x, lo, hi)
    if lo == -hi:  # symmetric
      scale = hi / (2.0 ** (p.bits - 1) - 1)
      return FakeQuant(x, jnp.asarray(scale, x.dtype), p.bits)
    scale = (hi - lo) / (2.0 ** p.bits - 1)
    zero_point = round(-lo / scale)
    return FakeQuantAsym(x, jnp.asarray(scale, x.dtype),
                         jnp.asarray(zero_point, x.dtype), p.bits)


class PerChannelSymmetricQDomain(SymmetricQDomain):
  """Symmetric fake quant with per-output-channel weight scales (the
  standard int8 deployment recipe; ref quant domains' per-channel option).
  Channel axis = last weight dim."""

  def QuantizeWeight(self, theta, w):
    reduce_axes = tuple(range(w.ndim - 1))
    scale = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=reduce_axes,
                    keepdims=True) / (2.0 ** (self.p.bits - 1) - 1)
    return FakeQuant(w, scale.astype(w.dtype), self.p.bits)


# ---------------------------------------------------------------------------
# Real int8 serving path: quantize once, run integer matmuls on the MXU.
# ---------------------------------------------------------------------------


def _ContractAxes(ndim: int, layout: str, contract_ndim: int | None):
  """Which weight axes are contracted for a given layout.

  'dv': the contraction axes LEAD (w [in..., out...]) — per-channel scales
  live on the trailing output axes. 'vd': the contraction axes TRAIL
  (w [out..., in...]) — scales live on the leading output axes.
  contract_ndim=None keeps the legacy 'dv' default of all-but-last (the
  per-channel-over-last-dim recipe 2-D callers always got).
  """
  assert layout in ("dv", "vd"), layout
  if contract_ndim is None:
    contract_ndim = ndim - 1 if layout == "dv" else 1
  assert 0 < contract_ndim < ndim, (contract_ndim, ndim)
  if layout == "dv":
    return tuple(range(contract_ndim)), contract_ndim
  return tuple(range(ndim - contract_ndim, ndim)), contract_ndim


def Int8QuantizeWeight(w, per_channel: bool = True, layout: str = "dv",
                       contract_ndim: int | None = None):
  """float weight -> (int8 weight, f32 scale) for serving.

  The returned pair feeds `Int8Einsum` with the same layout/contract_ndim.
  Per-channel scales reduce over the CONTRACTION axes only (one scale per
  output channel — the only granularity an integer matmul can fold out of
  the accumulator), keepdims so the scale broadcasts against w:

    layout='dv'  w [in..., out...]  -> scale [1..., out...]
    layout='vd'  w [out..., in...]  -> scale [out..., 1...]

  The default (layout='dv', contract_ndim=None) reduces all-but-last axes —
  bit-identical to the legacy per-channel-over-last-dim behavior (and to
  PerChannelSymmetricQDomain's QAT simulation) for 2-D [in, out] weights.
  """
  w32 = w.astype(jnp.float32)
  if per_channel:
    reduce_axes, _ = _ContractAxes(w.ndim, layout, contract_ndim)
    amax = jnp.max(jnp.abs(w32), axis=reduce_axes, keepdims=True)
  else:
    amax = jnp.max(jnp.abs(w32))
  scale = jnp.maximum(amax / 127.0, 1e-8)
  w_int8 = jnp.clip(jnp.round(w32 / scale), -128, 127).astype(jnp.int8)
  return w_int8, scale


def Int8Einsum(x, w_int8, w_scale, layout: str = "dv",
               contract_ndim: int | None = None):
  """y = x · dequant(w) computed as int8 x int8 -> int32 on the MXU.

  Activations are dynamically quantized per call (per-tensor symmetric).
  x's trailing contract_ndim axes contract against the weight's
  contraction axes (leading for 'dv', trailing for 'vd' — see
  `Int8QuantizeWeight`); w_scale is the matching per-channel scale (or a
  scalar). The legacy call `Int8Einsum(x, w8 [in, out], scale)` is the
  layout='dv', contract_ndim=1 special case. Returns x.dtype with shape
  [..., out...].
  """
  _, k = _ContractAxes(w_int8.ndim, layout, contract_ndim)
  if layout == "dv":
    in_dims, out_dims = w_int8.shape[:k], w_int8.shape[k:]
  else:
    out_dims, in_dims = w_int8.shape[:w_int8.ndim - k], w_int8.shape[
        w_int8.ndim - k:]
  kk = _Prod(in_dims)
  assert tuple(x.shape[x.ndim - k:]) == tuple(in_dims), (x.shape, w_int8.shape)
  batch_shape = x.shape[:x.ndim - k]
  x32 = x.astype(jnp.float32).reshape(batch_shape + (kk,))
  x_scale = jnp.maximum(jnp.max(jnp.abs(x32)) / 127.0, 1e-8)
  x_int8 = jnp.clip(jnp.round(x32 / x_scale), -128, 127).astype(jnp.int8)
  w2 = w_int8.reshape((kk, -1) if layout == "dv" else (-1, kk))
  w_contract = 0 if layout == "dv" else 1
  acc = jax.lax.dot_general(
      x_int8, w2,
      dimension_numbers=(((x_int8.ndim - 1,), (w_contract,)), ((), ())),
      preferred_element_type=jnp.int32)                    # [..., M]
  scale_vec = jnp.reshape(w_scale.astype(jnp.float32), (-1,))
  y = acc.astype(jnp.float32) * x_scale
  if scale_vec.size == 1:
    y = y * scale_vec[0]
  else:
    y = y * scale_vec.reshape((1,) * (acc.ndim - 1) + (-1,))
  return y.reshape(batch_shape + tuple(out_dims)).astype(x.dtype)


def _Prod(dims) -> int:
  out = 1
  for d in dims:
    out *= int(d)
  return out


@jax.tree_util.register_pytree_node_class
class Int8Weight:
  """A theta leaf served as int8: integer values + per-channel f32 scales.

  Layers whose matmuls understand this leaf (ProjectionLayer,
  MultiHeadedAttention projections, SharedEmbeddingSoftmaxLayer) route it
  through `Int8Einsum` — the weight never re-materializes in float. It is
  a registered pytree node, so it rides NestedMap theta through jit /
  donation / CastTheta unchanged (w_int8 is non-floating and passes every
  dtype cast untouched; the f32 scale follows the activation policy).

  layout/contract_ndim describe which axes the consuming einsum contracts
  (see `Int8QuantizeWeight`); they are static aux data, not traced.
  """

  def __init__(self, w_int8, scale, layout: str = "dv",
               contract_ndim: int | None = None):
    self.w_int8 = w_int8
    self.scale = scale
    self.layout = layout
    self.contract_ndim = contract_ndim

  @property
  def shape(self):
    return self.w_int8.shape

  def Dequant(self):
    """The exact float grid the export froze: w_int8 * scale, f32."""
    return self.w_int8.astype(jnp.float32) * self.scale.astype(jnp.float32)

  def Einsum(self, x):
    """x [..., in...] -> [..., out...] via the integer matmul."""
    return Int8Einsum(x, self.w_int8, self.scale, layout=self.layout,
                      contract_ndim=self.contract_ndim)

  @classmethod
  def Quantize(cls, w, layout: str = "dv", contract_ndim: int | None = None):
    w_int8, scale = Int8QuantizeWeight(w, per_channel=True, layout=layout,
                                       contract_ndim=contract_ndim)
    return cls(w_int8, scale, layout=layout, contract_ndim=contract_ndim)

  def tree_flatten(self):
    return (self.w_int8, self.scale), (self.layout, self.contract_ndim)

  @classmethod
  def tree_unflatten(cls, aux, children):
    w_int8, scale = children
    return cls(w_int8, scale, layout=aux[0], contract_ndim=aux[1])

  def __repr__(self):
    shape = tuple(getattr(self.w_int8, "shape", ()))
    return (f"Int8Weight(shape={shape}, layout={self.layout!r}, "
            f"contract_ndim={self.contract_ndim})")


class QuantizableLayer(base_layer.BaseLayer):
  """Mixin giving layers the reference's QWeight/QAct convenience surface
  (ref `quant_utils.QuantizableLayer`): subclasses define a `qdomain` param;
  calls degrade to identity when no domain is configured."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("qdomain", None, "Optional QDomain params.")
    return p

  def _CreateQDomain(self):
    """Call from __init__ after Params are set."""
    if self.p.qdomain is not None:
      self.CreateChild("qdomain_child", self.p.qdomain.Copy())

  def QWeight(self, theta, w):
    if self.p.qdomain is None:
      return w
    return self.qdomain_child.QuantizeWeight(
        self.ChildTheta(theta, "qdomain_child"), w)

  def QAct(self, theta, name, x):
    if self.p.qdomain is None:
      return x
    return self.qdomain_child.QuantizeAct(
        self.ChildTheta(theta, "qdomain_child"), name, x)


class ScheduledClipQDomain(SymmetricQDomain):
  """Adds the reference's clipping-cap schedule (ref ClippingCapSchedule):
  the activation clip range anneals from start_cap to end_cap over
  [clip_start_step, clip_end_step], after which quantization is fully on."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("start_cap", 8.0, "Initial (loose) activation cap.")
    p.Define("end_cap", 1.0, "Final activation cap.")
    p.Define("clip_start_step", 0, "Annealing start.")
    p.Define("clip_end_step", 10000, "Annealing end.")
    return p

  def _Cap(self):
    p = self.p
    step = py_utils.GetGlobalStep()
    if step is None:
      return jnp.asarray(p.end_cap, jnp.float32)
    frac = jnp.clip(
        (step - p.clip_start_step) /
        max(p.clip_end_step - p.clip_start_step, 1), 0.0, 1.0)
    # log-space interpolation (ref ClippingCapSchedule._Value)
    return jnp.exp(jnp.log(p.start_cap) * (1 - frac) +
                   jnp.log(p.end_cap) * frac)

  def QuantizeAct(self, theta, name: str, x):
    cap = self._Cap().astype(x.dtype)
    x = jnp.clip(x, -cap, cap)
    scale = cap.astype(jnp.float32) / (2.0 ** (self.p.bits - 1) - 1)
    return FakeQuant(x, scale.astype(x.dtype), self.p.bits)
