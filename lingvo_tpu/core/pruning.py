"""Magnitude pruning (ref `lingvo/core/pruning_utils.py` + the
model_pruning mask hooks at `base_model.py:1105`).

TPU-native shape: masks are part of the train state (a parallel pytree of
0/1 arrays over the pruned weights), updated on the host between program
runs at a polynomial sparsity schedule, and applied inside TrainStep by
masking theta before FProp and re-masking after the optimizer update —
functional, jit-compatible, no assign ops.
"""

from __future__ import annotations

import re
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.core import hyperparams
from lingvo_tpu.core.nested_map import NestedMap


class PruningSchedule:
  """Polynomial sparsity ramp (ref pruning schedule): 0 -> final_sparsity
  over [begin_step, end_step], updated every `frequency` steps."""

  @classmethod
  def Params(cls):
    p = hyperparams.InstantiableParams(cls)
    p.Define("name", "pruning", "Name.")
    p.Define("weight_regex", r".*\.w", "Which theta paths are pruned.")
    p.Define("final_sparsity", 0.9, "Target fraction of zeros.")
    p.Define("begin_step", 0, "Ramp start.")
    p.Define("end_step", 10000, "Ramp end.")
    p.Define("frequency", 100, "Mask update cadence (steps).")
    p.Define("power", 3.0, "Polynomial decay power (ref: cubic).")
    return p

  def __init__(self, params):
    self.p = params.Copy()

  def SparsityAt(self, step: int) -> float:
    p = self.p
    if step <= p.begin_step:
      return 0.0
    frac = min((step - p.begin_step) / max(p.end_step - p.begin_step, 1),
               1.0)
    return p.final_sparsity * (1.0 - (1.0 - frac) ** p.power)

  def ShouldUpdate(self, step: int, last_update_step: int = -1) -> bool:
    """True when a frequency boundary was CROSSED since the last update —
    the caller only observes steps at program-run boundaries, so an exact
    `step % frequency == 0` test could never fire (e.g. steps_per_loop=64,
    frequency=100)."""
    p = self.p
    if step < p.begin_step:
      return False
    f = max(p.frequency, 1)
    return step // f > last_update_step // f

  def Matches(self, path: str) -> bool:
    return re.fullmatch(self.p.weight_regex, path) is not None


def ComputeMasks(theta: NestedMap, schedule: PruningSchedule,
                 step: int) -> NestedMap:
  """Magnitude masks at the scheduled sparsity: the smallest |w| fraction
  of each matched weight is zeroed (per-tensor threshold, ref magnitude
  pruning)."""
  sparsity = schedule.SparsityAt(step)

  def _One(path, w):
    if not schedule.Matches(path) or np.ndim(w) < 2:
      return jnp.ones_like(w)
    flat = jnp.abs(w.reshape(-1))
    k = int(sparsity * flat.shape[0])
    if k <= 0:
      return jnp.ones_like(w)
    threshold = jnp.sort(flat)[k - 1]
    return (jnp.abs(w) > threshold).astype(w.dtype)

  return theta.TransformWithKey(_One)


def ApplyMasks(theta: NestedMap, masks: NestedMap) -> NestedMap:
  return jax.tree_util.tree_map(lambda w, m: w * m, theta, masks)


def Sparsity(masks: NestedMap, schedule: PruningSchedule) -> float:
  """Realized fraction of zeros over the pruned weights."""
  zeros = total = 0
  for path, m in masks.FlattenItems():
    if schedule.Matches(path) and np.ndim(m) >= 2:
      arr = np.asarray(m)
      zeros += arr.size - int(arr.sum())
      total += arr.size
  return zeros / total if total else 0.0
