"""Signature→Params bridging (ref `lingvo/core/inspect_utils.py`).

Lets a Params tree drive arbitrary callables (e.g. wrapping an external
layer/optimizer class as a configurable component) without hand-writing
`Define` statements: `DefineParams` reflects a callable's signature into a
Params object, `CallWithParams`/`ConstructWithParams` call it back with
those values. Keyword overrides win over params values; parameters the
callable doesn't declare are never passed.
"""

from __future__ import annotations

import inspect

_SKIPPED_KINDS = (inspect.Parameter.VAR_POSITIONAL,
                  inspect.Parameter.VAR_KEYWORD)


def _ExtractParameters(func, ignore, bound):
  ignore = set(ignore or ())
  params = list(inspect.signature(func).parameters.values())
  if bound and params:
    params = params[1:]  # drop self/cls
  return [p for p in params
          if p.kind not in _SKIPPED_KINDS and p.name not in ignore]


def DefineParams(func, params, ignore=None, bound=False):
  """Defines one params entry per explicit parameter of `func`.

  Defaults are copied; parameters without defaults get None. `*args` /
  `**kwargs` catch-alls cannot be reflected and are skipped. Pass
  `bound=True` when `func` is an unbound method whose first arg is
  self/cls.
  """
  for p in _ExtractParameters(func, ignore, bound):
    default = p.default
    if default is inspect.Parameter.empty:
      default = None
    params.Define(p.name, default, "Function parameter.")
  return params


def _MakeArgs(func, params, bound, kwargs):
  args = {}
  for p in _ExtractParameters(func, None, bound):
    if p.name in params:
      args[p.name] = params.Get(p.name)
  args.update(kwargs)
  return args


def CallWithParams(func, params, **kwargs):
  """Calls `func` with matching values from `params` (kwargs override).

  kwargs are forwarded verbatim — a parameter named `bound` or `params`
  cannot collide with this wrapper's own arguments.
  """
  return func(**_MakeArgs(func, params, False, kwargs))


def ConstructWithParams(cls, params, **kwargs):
  """Constructs `cls` with matching values from `params`."""
  return cls(**_MakeArgs(cls.__init__, params, True, kwargs))
