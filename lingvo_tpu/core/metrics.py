"""Metrics: on-device weighted accumulators + host-side rich metrics.

Re-designs `lingvo/core/metrics.py`: the on-device pattern is the reference's
`TpuEvalMetrics` (`metrics.py:258`) — fixed-shape (value, weight) pairs
accumulated across the device loop; under data parallelism GSPMD inserts the
cross-replica sums the reference did by hand (`metrics.py:351`). Host-side
metrics (Average, F1, BLEU-style corpus metrics) consume outfed per-example
tensors.

Convention (same as the reference): a task's FProp returns
`metrics = NestedMap(name=(value, weight), ...)` where `value` is the
weighted mean over examples and `weight` the example count/token count.
"""

from __future__ import annotations

import math
from typing import Any

import jax.numpy as jnp
import numpy as np

from lingvo_tpu.core.nested_map import NestedMap


def AccumulateMetrics(acc: NestedMap | None, metrics: NestedMap) -> NestedMap:
  """Folds one step's (value, weight) metrics into a weighted accumulator.

  Accumulator entry per metric: [weighted_value_sum, weight_sum] (f32[2]),
  fixed-shape so it lives inside jit/scan (ref TpuEvalMetrics packing).
  """
  out = NestedMap()
  for k in metrics.keys():
    v, w = metrics[k]
    pair = jnp.stack([jnp.asarray(v, jnp.float32) * jnp.asarray(w, jnp.float32),
                      jnp.asarray(w, jnp.float32)])
    out[k] = pair if acc is None else acc[k] + pair
  return out


def FinalizeMetrics(acc: NestedMap) -> dict[str, float]:
  """Converts accumulators to {name: weighted mean} floats (host side)."""
  out = {}
  for k in sorted(acc.keys()):
    pair = np.asarray(acc[k])
    out[k] = float(pair[0] / max(pair[1], 1e-8))
  return out


def _MetricKeys(metrics: NestedMap):
  return [k for k in metrics.keys()]


class BaseMetric:

  @property
  def value(self) -> float:
    raise NotImplementedError

  def Summary(self, name: str) -> dict[str, float]:
    return {name: self.value}


class AverageMetric(BaseMetric):
  """Weighted average (`metrics.py:79`)."""

  def __init__(self):
    self._total = 0.0
    self._weight = 0.0

  def Update(self, value: float, weight: float = 1.0):
    self._total += value * weight
    self._weight += weight

  @property
  def total_value(self):
    return self._total

  @property
  def total_weight(self):
    return self._weight

  @property
  def value(self) -> float:
    return self._total / self._weight if self._weight > 0 else 0.0


class UniqueAverageMetric(AverageMetric):
  """Average that de-dups by key (`metrics.py` UniqueAverageMetric)."""

  def __init__(self):
    super().__init__()
    self._seen = set()

  def Update(self, key: str, value: float, weight: float = 1.0):  # type: ignore[override]
    if key in self._seen:
      return
    self._seen.add(key)
    super().Update(value, weight)


class F1Metric(BaseMetric):
  """F1 from TP/FP/FN counts (`metrics.py` F1Metric)."""

  def __init__(self):
    self._tp = self._fp = self._fn = 0.0

  def UpdateTruePositive(self, count: float = 1.0):
    self._tp += count

  def UpdateFalsePositive(self, count: float = 1.0):
    self._fp += count

  def UpdateFalseNegative(self, count: float = 1.0):
    self._fn += count

  @property
  def value(self) -> float:
    precision = self._tp / max(self._tp + self._fp, 1e-8)
    recall = self._tp / max(self._tp + self._fn, 1e-8)
    if precision + recall == 0:
      return 0.0
    return 2 * precision * recall / (precision + recall)


class MCCMetric(BaseMetric):
  """Matthews correlation coefficient (`metrics.py` MCCMetric)."""

  def __init__(self):
    self._tp = self._fp = self._tn = self._fn = 0.0

  def UpdateTruePositive(self, count=1.0):
    self._tp += count

  def UpdateFalsePositive(self, count=1.0):
    self._fp += count

  def UpdateTrueNegative(self, count=1.0):
    self._tn += count

  def UpdateFalseNegative(self, count=1.0):
    self._fn += count

  @property
  def value(self) -> float:
    num = self._tp * self._tn - self._fp * self._fn
    den = math.sqrt((self._tp + self._fp) * (self._tp + self._fn) *
                    (self._tn + self._fp) * (self._tn + self._fn))
    return num / den if den else 0.0


class CorpusBleuMetric(BaseMetric):
  """Corpus BLEU over (ref, hyp) token streams (`metrics.py:240`,
  `scorers.py`)."""

  def __init__(self, max_order: int = 4):
    self._max_order = max_order
    self._matches = [0] * max_order
    self._possible = [0] * max_order
    self._ref_len = 0
    self._hyp_len = 0

  @staticmethod
  def _Ngrams(tokens, order):
    from collections import Counter
    return Counter(
        tuple(tokens[i:i + order]) for i in range(len(tokens) - order + 1))

  def Update(self, ref: str | list, hyp: str | list):
    ref_toks = ref.split() if isinstance(ref, str) else list(ref)
    hyp_toks = hyp.split() if isinstance(hyp, str) else list(hyp)
    self._ref_len += len(ref_toks)
    self._hyp_len += len(hyp_toks)
    for order in range(1, self._max_order + 1):
      ref_ngrams = self._Ngrams(ref_toks, order)
      hyp_ngrams = self._Ngrams(hyp_toks, order)
      overlap = sum((ref_ngrams & hyp_ngrams).values())
      self._matches[order - 1] += overlap
      self._possible[order - 1] += max(len(hyp_toks) - order + 1, 0)

  @property
  def value(self) -> float:
    precisions = []
    for m, p in zip(self._matches, self._possible):
      if p == 0:
        return 0.0
      if m == 0:
        return 0.0
      precisions.append(m / p)
    log_avg = sum(math.log(p) for p in precisions) / self._max_order
    bp = 1.0
    if self._hyp_len < self._ref_len and self._hyp_len > 0:
      bp = math.exp(1.0 - self._ref_len / self._hyp_len)
    return bp * math.exp(log_avg)


class AUCMetric(BaseMetric):
  """Streaming ROC-AUC via rank statistic (`metrics.py:461`)."""

  def __init__(self):
    self._pos_scores: list[float] = []
    self._neg_scores: list[float] = []

  def Update(self, label: int, prob: float):
    (self._pos_scores if label else self._neg_scores).append(prob)

  @property
  def value(self) -> float:
    pos, neg = self._pos_scores, self._neg_scores
    if not pos or not neg:
      return 0.0
    scores = [(s, 1) for s in pos] + [(s, 0) for s in neg]
    scores.sort(key=lambda x: x[0])
    # Average ranks over ties (Mann-Whitney U): a constant-score classifier
    # must get AUC 0.5, not 0.
    rank_sum = 0.0
    i = 0
    n = len(scores)
    while i < n:
      j = i
      while j < n and scores[j][0] == scores[i][0]:
        j += 1
      avg_rank = (i + 1 + j) / 2.0  # ranks i+1..j averaged
      rank_sum += avg_rank * sum(label for _, label in scores[i:j])
      i = j
    n_pos, n_neg = len(pos), len(neg)
    return (rank_sum - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


class CorrelationMetric(BaseMetric):
  """Pearson correlation (`metrics.py:652`)."""

  def __init__(self):
    self._xs: list[float] = []
    self._ys: list[float] = []

  def Update(self, x: float, y: float):
    self._xs.append(x)
    self._ys.append(y)

  @property
  def value(self) -> float:
    if len(self._xs) < 2:
      return 0.0
    x = np.asarray(self._xs)
    y = np.asarray(self._ys)
    denom = x.std() * y.std()
    if denom == 0:
      return 0.0
    return float(((x - x.mean()) * (y - y.mean())).mean() / denom)
