"""Test harness utilities: self-rewriting golden values + numeric-gradient
checks (ref `lingvo/core/test_utils.py:406-468` ReplaceGoldenSingleFloat /
CompareToGoldenSingleFloat / ComputeNumericGradient).

Golden tests lock layer numerics against silent drift: the deterministic
name-derived variable seeds (core/base_layer.py) make outputs reproducible,
so a stored float pins the whole init+FProp path. Run with
`LINGVO_TPU_UPDATE_GOLDENS=1 pytest ...` to rewrite mismatched goldens
in-place in the calling test file (call sites must be one-liners, same
contract as the reference).
"""

from __future__ import annotations

import inspect
import os
import re

import numpy as np

_GOLDEN_CALL_RE = re.compile(
    r"(?P<prefix>.*)\bCompareToGoldenSingleFloat\(\s*"
    r"[-+]?(?:\d+(?:\.\d*)?|\.\d+)(?:[eE][-+]?\d+)?\s*,\s*"
    r"(?P<rest>.*)\)(?P<postfix>.*)\n")


def _ReplaceOneLineInFile(fpath: str, linenum: int, old: str,
                          new: str) -> None:
  with open(fpath) as f:
    lines = f.readlines()
  assert lines[linenum] == old, (
      f"Expected {lines[linenum]!r} at line {linenum + 1} in {fpath}, "
      f"got {old!r}")
  lines[linenum] = new
  with open(fpath, "w") as f:
    f.writelines(lines)


def _ReplaceGoldenSingleFloat(old_line: str, value: float) -> str:
  m = _GOLDEN_CALL_RE.match(old_line)
  assert m, (
      "CompareToGoldenSingleFloat call site must be a one-liner with a "
      f"float literal first argument; got: {old_line!r}")
  assert old_line.count("(") == old_line.count(")"), (
      "CompareToGoldenSingleFloat call site spans multiple lines "
      f"(unbalanced parens) — make it a one-liner: {old_line!r}")
  return (f"{m.group('prefix')}CompareToGoldenSingleFloat("
          f"{value:.6f}, {m.group('rest')}){m.group('postfix')}\n")


def _GoldenCallSite():
  """(fpath, linenum, old_line) of the nearest caller line containing the
  golden comparison (ref ReplaceGoldenStackAnalysis)."""
  for frame in inspect.stack():
    ctx = frame.code_context
    if ctx and "CompareToGoldenSingleFloat" in ctx[0] and (
        frame.filename != __file__):
      return frame.filename, frame.lineno - 1, ctx[0]
  raise AssertionError("no CompareToGoldenSingleFloat call site found")


def UpdateGoldensEnabled() -> bool:
  return bool(os.environ.get("LINGVO_TPU_UPDATE_GOLDENS"))


def CompareToGoldenSingleFloat(golden: float, value, rtol: float = 1e-5,
                               atol: float = 1e-6) -> None:
  """Asserts `value` == the stored golden float; under
  LINGVO_TPU_UPDATE_GOLDENS=1 rewrites the golden literal in the calling
  test source instead (one-liner call sites only)."""
  value = float(np.asarray(value))
  if UpdateGoldensEnabled():
    if not np.isclose(golden, value, rtol=rtol, atol=atol):
      fpath, linenum, old_line = _GoldenCallSite()
      _ReplaceOneLineInFile(fpath, linenum, old_line,
                            _ReplaceGoldenSingleFloat(old_line, value))
    return
  np.testing.assert_allclose(
      value, golden, rtol=rtol, atol=atol,
      err_msg=("golden mismatch — if the change is intentional, rerun with "
               "LINGVO_TPU_UPDATE_GOLDENS=1 to rewrite"))


def ComputeNumericGradient(fn, x, delta: float = 1e-4,
                           step: int = 1) -> np.ndarray:
  """Central-difference gradient of scalar fn at x (ref
  ComputeNumericGradient): checks custom VJPs against finite differences.

  x: np array; returns d fn / d x with every `step`-th element probed
  (others zero) to bound cost on big tensors.
  """
  # Fresh C-contiguous copy: flat writes must alias x (asarray of a
  # non-contiguous input would make reshape(-1) a copy and the probes
  # no-ops), and the caller's array must never be mutated.
  x = np.array(x, np.float64, order="C")
  grad = np.zeros_like(x)
  flat = x.reshape(-1)
  gflat = grad.reshape(-1)
  for i in range(0, flat.size, step):
    orig = flat[i]
    flat[i] = orig + delta
    fp = float(fn(x.reshape(x.shape)))
    flat[i] = orig - delta
    fm = float(fn(x.reshape(x.shape)))
    flat[i] = orig
    gflat[i] = (fp - fm) / (2.0 * delta)
  return grad
