"""Decode-output persistence (ref `lingvo/core/decoder_lib.py`).

Decode jobs emit per-example (key, value) pairs; these helpers persist and
reload them. The reference pickles the kv list and packs NestedMaps into a
`record_pb2.Record` of serialized numpy tensors; here the record format is
a self-contained .npz-style dict (numpy's own portable serialization) so
outputs round-trip without a proto toolchain.
"""

from __future__ import annotations

import io
import pickle

import numpy as np

from lingvo_tpu.core.nested_map import NestedMap


def WriteKeyValuePairs(filename, key_value_pairs) -> None:
  """Writes a list of (key, value) pairs (ref `decoder_lib.py:24`)."""
  with open(filename, "wb") as f:
    pickle.dump(key_value_pairs, f, protocol=pickle.HIGHEST_PROTOCOL)


def ReadKeyValuePairs(filename):
  with open(filename, "rb") as f:
    return pickle.load(f)


def SerializeOutputs(nmap: NestedMap) -> bytes:
  """NestedMap of arrays/scalars/strings -> portable bytes
  (ref `decoder_lib.py:30` SerializeOutputs -> record_pb2.Record)."""
  buf = io.BytesIO()
  flat = dict(nmap.FlattenItems())
  np.savez(buf, **{k: np.asarray(v) for k, v in flat.items()})
  return buf.getvalue()


def DeserializeOutputs(data: bytes) -> NestedMap:
  """Inverse of SerializeOutputs; restores the nested structure."""
  loaded = np.load(io.BytesIO(data), allow_pickle=False)
  out = NestedMap()
  for key in loaded.files:
    arr = loaded[key]
    if arr.dtype.kind in ("U", "S") and arr.ndim == 0:
      arr = arr.item()
    out.Set(key, arr)
  return out
