"""Early stopping on metric plateaus + metric history files.

Re-designs `lingvo/core/early_stop.py` (MetricHistory:24, EarlyStop:126) and
the C++ BestStep op (`ops/best_step_op_kernels.cc`): the history is a jsonl
file of (step, value); BestStep scans it with an optional tolerance; EarlyStop
signals once no improvement has occurred within `window` steps.
"""

from __future__ import annotations

import json
import os

from lingvo_tpu.core import hyperparams


class MetricHistory:
  """Appends (step, value) for one jobname/metric to a history file."""

  def __init__(self, logdir: str, jobname: str, metric: str,
               minimize: bool = True):
    self.jobname = jobname
    self.metric = metric
    self.minimize = minimize
    os.makedirs(logdir, exist_ok=True)
    self.path = os.path.join(logdir, f"{jobname}.{metric}.history.jsonl")

  def ConditionalAppend(self, step: int, value: float) -> None:
    with open(self.path, "a") as f:
      f.write(json.dumps({"step": int(step), "value": float(value)}) + "\n")

  def Read(self) -> list[tuple[int, float]]:
    return ReadHistory(self.path)


def ReadHistory(path: str) -> list[tuple[int, float]]:
  """All (step, value) records of a history file (empty if missing)."""
  if not os.path.exists(path):
    return []
  out = []
  with open(path) as f:
    for line in f:
      if line.strip():
        rec = json.loads(line)
        out.append((rec["step"], rec["value"]))
  return out


def BestStep(history_path: str, tolerance: float = 0.0,
             minimize: bool = True) -> tuple[int, int]:
  """Returns (best_step, last_step) from a history file (ref BestStep op).

  A new best must improve by more than `tolerance` over the incumbent.
  """
  if not os.path.exists(history_path):
    return 0, 0
  best_step = last_step = 0
  best_val = None
  with open(history_path) as f:
    for line in f:
      if not line.strip():
        continue
      rec = json.loads(line)
      step, val = rec["step"], rec["value"]
      last_step = step
      better = (best_val is None or
                (val < best_val - tolerance if minimize else
                 val > best_val + tolerance))
      if better:
        best_val = val
        best_step = step
  return best_step, last_step


class EarlyStop:
  """Plateau detector (ref EarlyStop:126)."""

  @classmethod
  def Params(cls):
    p = hyperparams.InstantiableParams(cls)
    p.Define("name", "early_stop", "Name.")
    p.Define("window", 0, "Steps without improvement before stopping "
             "(0 = disabled).")
    p.Define("tolerance", 0.0, "Required improvement margin.")
    p.Define("metric_history", None, "MetricHistory instance or None.")
    p.Define("min_steps", 0, "Never stop before this step.")
    p.Define("minimize", True, "Lower is better.")
    return p

  def __init__(self, params):
    self.p = params.Copy()
    self.metric_history = self.p.metric_history

  def Stop(self, current_step: int | None = None) -> bool:
    p = self.p
    if p.window <= 0 or self.metric_history is None:
      return False
    # no recorded evals yet -> never stop (a missing history must not read
    # as 'best was step 0')
    if not self.metric_history.Read():
      return False
    best, last = BestStep(self.metric_history.path, p.tolerance, p.minimize)
    step = current_step if current_step is not None else last
    if step < p.min_steps:
      return False
    return (step - best) > p.window
