"""BaseLayer: Params-configured, functionally-pure JAX layers.

Re-designs the reference's layer system (`lingvo/core/base_layer.py:204`) the
TPU-native way. The reference's load-bearing idea — computation is
`FProp(theta, inputs)` with an explicitly passed weight pytree
(`base_layer.py:381`) — maps 1:1 onto JAX; what changes is variable creation:
instead of TF variables held by the layer, a layer only *declares* weight specs
(`CreateVariable`), and `InstantiateVariables(key)` materializes a pure
NestedMap theta with deterministic per-name PRNG folds (parity with the
reference's name-derived seeds, `py_utils.py:1555`).

Sharding: layers carry `device_mesh`-era params re-cast as mesh-axis names —
`weight_split_dims_mapping` / `activation_split_dims_mapping`
(cf. `base_layer.py:262-280`) hold axis-name tuples that lower to
`jax.sharding.PartitionSpec` via `lingvo_tpu.parallel.mesh`.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp

from lingvo_tpu.core import hyperparams
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.core.py_utils import WeightInit, WeightParams


def StackedVariableSpecs(body: "BaseLayer", n: int) -> NestedMap:
  """body's VariableSpecs with a leading stack dim of n (replicated axis).

  Keeps VariableSpecs (param counts, sharding derivation) truthful for
  scan-over-layers / pipeline layers whose theta leaves are stacked.
  """

  def _Stack(wp: WeightParams) -> WeightParams:
    sdm = wp.tensor_split_dims_mapping
    return WeightParams(
        shape=(n,) + tuple(wp.shape),
        init=wp.init,
        dtype=wp.dtype,
        collections=wp.collections,
        tensor_split_dims_mapping=((None,) + tuple(sdm))
        if sdm is not None else None)

  return jax.tree_util.tree_map(_Stack, body.VariableSpecs())


def StackedInstantiateVariables(body: "BaseLayer", key: jax.Array,
                                n: int) -> NestedMap:
  """n independently-seeded copies of body's theta, stacked on axis 0.

  Shared by scan-over-layers (RepeatedTransformerLayer) and pipeline stages
  (PipelinedLayer); the caller must have FinalizePaths()'d the tree.
  """

  def _One(i):
    return body.InstantiateVariables(jax.random.fold_in(key, i))

  return jax.vmap(_One)(jnp.arange(n))


class BaseLayer:
  """Base class for all layers.

  Lifecycle:
    p = MyLayer.Params().Set(...); layer = p.Instantiate()
    theta = layer.InstantiateVariables(jax.random.PRNGKey(0))
    out = layer.FProp(theta, inputs)
  """

  @classmethod
  def Params(cls) -> hyperparams.InstantiableParams:
    p = hyperparams.InstantiableParams(cls)
    p.Define("name", "", "Layer name; forms variable paths.")
    p.Define("dtype", jnp.float32, "Weight dtype.")
    p.Define(
        "fprop_dtype", None,
        "Activation dtype (e.g. jnp.bfloat16 for TPU). None = use dtype.")
    p.Define("params_init", WeightInit.Xavier(),
             "Default weight initializer for this layer.")
    p.Define(
        "random_seed", None,
        "If set, overrides the name-derived seed fold for deterministic "
        "tests.")
    p.Define(
        "mesh_axis_names", None,
        "Logical mesh axis names this layer's shardings refer to "
        "(informational; specs name axes directly).")
    p.Define(
        "weight_split_dims_mapping", None,
        "Per-dim mesh axis names for this layer's main weight(s); lowered to "
        "PartitionSpec (ref: base_layer.py:262-280).")
    p.Define(
        "activation_split_dims_mapping", None,
        "Per-dim mesh axis names for this layer's output activations; applied "
        "via with_sharding_constraint (ref: gshard_utils.MeshSplit).")
    return p

  def __init__(self, params: hyperparams.InstantiableParams):
    if not params.name and self._NameIsRequired():
      params = params.Copy().Set(name=type(self).__name__.lower())
    self._params = params.Copy()
    self._params.Freeze()
    self._children: dict[str, Any] = {}
    self._variable_specs: dict[str, WeightParams] = {}
    self._path: str | None = None
    self._CreateChildrenHook()

  def _NameIsRequired(self) -> bool:
    return True

  def _CreateChildrenHook(self):
    """Subclasses create children/variables in __init__; hook kept for mixins."""

  # ---- properties ----------------------------------------------------------

  @property
  def params(self) -> hyperparams.InstantiableParams:
    return self._params

  @property
  def p(self) -> hyperparams.InstantiableParams:
    return self._params

  @property
  def children(self) -> dict[str, Any]:
    return dict(self._children)

  @property
  def fprop_dtype(self):
    return self.p.fprop_dtype if self.p.fprop_dtype is not None else self.p.dtype

  @property
  def path(self) -> str:
    """Full slash path from the root layer; unique per layer instance.

    Assigned by the root's InstantiateVariables (or FinalizePaths). Used for
    deterministic per-layer PRNG folds and forward-state update keys, so two
    sibling layers never share a trace-time identity.
    """
    return self._path if self._path is not None else self.p.name

  def FinalizePaths(self, root_path: str | None = None) -> None:
    """Assigns full paths to this layer tree (idempotent from the root)."""
    self._AssignPaths(root_path or self.p.name)

  def _AssignPaths(self, path: str) -> None:
    self._path = path
    for cname, child in self._children.items():
      if isinstance(child, list):
        for i, c in enumerate(child):
          c._AssignPaths(f"{path}/{cname}_{i}")
      else:
        child._AssignPaths(f"{path}/{cname}")

  def __getattr__(self, name: str) -> Any:
    # Children are accessible as attributes (self.fc, self.atten, ...).
    children = self.__dict__.get("_children")
    if children is not None and name in children:
      return children[name]
    raise AttributeError(
        f"{type(self).__name__} has no attribute/child {name!r}")

  # ---- construction API ----------------------------------------------------

  def CopyBaseParams(self, child_p: hyperparams.InstantiableParams
                     ) -> hyperparams.InstantiableParams:
    """Propagates dtype/fprop_dtype/init down to a child (ref :287)."""
    p = self.p
    if "dtype" in child_p and child_p.dtype == jnp.float32 and p.dtype != jnp.float32:
      child_p.dtype = p.dtype
    if "fprop_dtype" in child_p and child_p.fprop_dtype is None:
      child_p.fprop_dtype = p.fprop_dtype
    if ("params_init" in child_p and
        child_p.params_init == WeightInit.Xavier() and
        p.params_init != WeightInit.Xavier()):
      child_p.params_init = p.params_init
    return child_p

  def CreateChild(self, name: str, child_params: hyperparams.InstantiableParams):
    """Instantiates a child layer under `name`."""
    if name in self._children:
      raise ValueError(f"Child {name!r} already exists on {self.p.name}")
    cp = child_params.Copy()
    if "name" in cp and not cp.name:
      cp.name = name
    self.CopyBaseParams(cp)
    self._children[name] = cp.Instantiate()
    return self._children[name]

  def CreateChildren(self, name: str,
                     params_list: Sequence[hyperparams.InstantiableParams]):
    """Instantiates a list of child layers under `name`."""
    if name in self._children:
      raise ValueError(f"Children {name!r} already exist on {self.p.name}")
    out = []
    for i, child_params in enumerate(params_list):
      cp = child_params.Copy()
      if "name" in cp and not cp.name:
        cp.name = f"{name}_{i}"
      self.CopyBaseParams(cp)
      out.append(cp.Instantiate())
    self._children[name] = out
    return out

  def CreateVariable(self, name: str, wp: WeightParams):
    """Declares a weight spec; materialized later by InstantiateVariables."""
    if name in self._variable_specs:
      raise ValueError(f"Variable {name!r} already declared on {self.p.name}")
    self._variable_specs[name] = wp

  # ---- variable materialization --------------------------------------------

  def _OwnVariableSpecs(self) -> dict[str, WeightParams]:
    return dict(self._variable_specs)

  def VariableSpecs(self) -> NestedMap:
    """Full spec tree (self + children), mirroring theta's structure."""
    out = NestedMap()
    for name, wp in self._variable_specs.items():
      out[name] = wp
    for cname, child in self._children.items():
      if isinstance(child, list):
        subs = [c.VariableSpecs() for c in child]
        if any(len(s) for s in subs):
          out[cname] = subs
      else:
        sub = child.VariableSpecs()
        if len(sub):
          out[cname] = sub
    return out

  def InstantiateVariables(self, key: jax.Array) -> NestedMap:
    """Materializes theta: a NestedMap of arrays mirroring the layer tree."""
    if self._path is None:
      self.FinalizePaths()
    theta = NestedMap()
    for name, wp in self._variable_specs.items():
      var_path = f"{self.path}/{name}"
      if self.p.random_seed is not None:
        vkey = jax.random.fold_in(
            jax.random.PRNGKey(self.p.random_seed),
            py_utils.GenerateSeedFromName(var_path))
      else:
        vkey = py_utils.FoldInName(key, var_path)
      theta[name] = py_utils.InitWeight(vkey, wp)
    for cname, child in self._children.items():
      if isinstance(child, list):
        subs = [c.InstantiateVariables(key) for c in child]
        if any(len(s) for s in subs):
          theta[cname] = subs
      else:
        sub = child.InstantiateVariables(key)
        if len(sub):
          theta[cname] = sub
    return theta

  # ---- fprop ---------------------------------------------------------------

  def ChildTheta(self, theta: NestedMap, name: str):
    """theta subtree for child `name`; empty map(s) if it has no variables.

    Children without variables are pruned from theta by InstantiateVariables,
    so FProps must fetch child theta through this accessor.
    """
    if name in theta:
      return theta[name]
    child = self._children[name]
    if isinstance(child, list):
      return [NestedMap() for _ in child]
    return NestedMap()

  def FProp(self, theta: NestedMap, *args, **kwargs):
    raise NotImplementedError(f"{type(self).__name__}.FProp")

  def __call__(self, theta: NestedMap, *args, **kwargs):
    return self.FProp(theta, *args, **kwargs)

  def ToFPropDtype(self, x):
    return py_utils.MaybeBfloat16(x, self.fprop_dtype)

  def CastTheta(self, theta: NestedMap) -> NestedMap:
    """Casts floating theta leaves to fprop dtype (bf16 activations policy)."""
    dtype = self.fprop_dtype
    if dtype == self.p.dtype:
      return theta
    return jax.tree_util.tree_map(
        lambda x: py_utils.MaybeBfloat16(x, dtype), theta)

  # ---- decode state (Step API) --------------------------------------------

  def InitStates(self, theta: NestedMap, *args, **kwargs) -> NestedMap:
    """Initial streaming/decode state (ref Step API, `step.py`)."""
    return NestedMap()

  def ExtendStep(self, theta: NestedMap, *args, **kwargs):
    raise NotImplementedError(
        f"{type(self).__name__} does not support incremental decoding")
