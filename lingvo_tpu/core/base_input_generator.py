"""Input generators: host-side batch producers feeding jit'd programs.

Re-designs `lingvo/core/base_input_generator.py` (2.2k LoC) for JAX: no infeed
queue ops — a generator yields NestedMap batches of numpy arrays; the program
moves them to device with `jax.device_put` against the batch sharding (the
TPU-native equivalent of `CreateTpuEnqueueOps`, ref `:446-670`). Per-host
sharding for multi-process setups mirrors `InfeedContextScope`
(`cluster.py:47-59`) via the `num_hosts`/`host_index` params.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import hyperparams
from lingvo_tpu.core.nested_map import NestedMap


class BaseInputGenerator(base_layer.BaseLayer):
  """Produces NestedMap batches (numpy, host-side)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("batch_size", 0, "Per-host batch size.")
    p.Define("num_samples", 0, "Dataset size (0 = infinite/unknown).")
    p.Define("num_hosts", 1, "Total infeed hosts.")
    p.Define("host_index", 0, "This host's index.")
    p.Define("resettable", True, "Whether Reset() restarts the stream.")
    p.Define("require_sequential_order", False,
             "Deterministic in-order iteration (eval).")
    return p

  def __init__(self, params):
    super().__init__(params)
    self._epoch = 0

  def GlobalBatchSize(self) -> int:
    """Total batch across hosts (ref GlobalBatchSize:350)."""
    return self.p.batch_size * self.p.num_hosts

  def InfeedBatchSize(self) -> int:
    """This host's batch (ref InfeedBatchSize:359)."""
    return self.p.batch_size

  def _InputBatch(self) -> NestedMap:
    """Subclass point: produce one batch."""
    raise NotImplementedError

  def GetPreprocessedInputBatch(self) -> NestedMap:
    return self._InputBatch()

  def InputStats(self) -> dict:
    """Generator-side health counters, exported as `input_*` train
    summaries by the programs (ref RecordBatcher stats logging): record /
    drop / partial-flush counts, prefetch queue depth. {} by default."""
    return {}

  def __iter__(self) -> Iterator[NestedMap]:
    while True:
      try:
        yield self.GetPreprocessedInputBatch()
      except StopIteration:
        return

  def Reset(self) -> None:
    self._epoch = 0


class BaseSequenceInputGenerator(BaseInputGenerator):
  """Adds tokenization + length-bucketing config (ref
  `base_input_generator.py:1457` BaseSequenceInputGenerator)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("tokenizer", None, "Tokenizer Params (core.tokenizers).")
    p.Define("bucket_upper_bound", [], "Bucket length bounds, ascending.")
    p.Define("bucket_batch_limit", [],
             "Per-bucket batch sizes (same arity as bucket_upper_bound).")
    return p

  def __init__(self, params):
    super().__init__(params)
    self._tokenizer = (self.p.tokenizer.Instantiate()
                       if self.p.tokenizer is not None else None)

  @property
  def tokenizer(self):
    assert self._tokenizer is not None, "p.tokenizer not set"
    return self._tokenizer

  def StringsToIds(self, texts, max_length: int):
    """(ids sos-prefixed, labels eos-suffixed, paddings) — ref
    `base_input_generator.py:1565`."""
    return self.tokenizer.StringsToIds(texts, max_length)

  def IdsToStrings(self, ids, lens=None):
    return self.tokenizer.IdsToStrings(ids, lens)

  def infeed_bucket_batch_limit(self):
    return list(self.p.bucket_batch_limit)


class FileBasedSequenceInputGenerator(BaseSequenceInputGenerator):
  """Real-data path: C++ record yielder -> per-record processor ->
  length-bucketed batches, prefetched on a host thread.

  The TPU-native re-design of `BaseInputGeneratorFromFiles`
  (`base_input_generator.py:1216-1456`) + `record_batcher.cc`: records come
  from the native yielder (sharded glob, shuffle ring, per-host sharding via
  num_hosts/host_index), `ProcessRecord` (subclass point, ≙ the GenericInput
  user processor) maps bytes -> example NestedMap with a scalar
  `bucket_key`, and batches are assembled per length bucket. Batches are
  padded to [bucket_batch_limit, bound] so every bucket is one static XLA
  shape; a `weights`-aware consumer sees padded rows as weight 0.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("file_pattern", "", "'type:glob' pattern or list of patterns.")
    p.Define("file_pattern_weights", None, "Mix weights for pattern lists.")
    p.Define("shuffle", True, "Shuffle records.")
    p.Define("shuffle_buffer_size", 10000, "Shuffle ring size.")
    p.Define("num_reader_threads", 2, "C++ reader threads.")
    p.Define("max_epochs", 0, "0 = repeat forever.")
    p.Define("seed", 301, "Yielder seed.")
    p.Define("prefetch_buffer_size", 4, "Host-side prefetched batches.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self._batch_iter = None
    self._prefetcher = None
    self._batcher = None

  # -- subclass point --------------------------------------------------------
  def ProcessRecord(self, record: bytes):
    """bytes -> example NestedMap with scalar `bucket_key`, or None to drop."""
    raise NotImplementedError

  # --------------------------------------------------------------------------
  def _MakeSource(self):
    from lingvo_tpu.core import datasource
    p = self.p
    ds = datasource.SimpleDataSource.Params().Set(
        file_pattern=p.file_pattern,
        weights=p.file_pattern_weights,
        shuffle_buffer_size=p.shuffle_buffer_size,
        num_threads=p.num_reader_threads,
        max_epochs=p.max_epochs,
        shuffle=p.shuffle and not p.require_sequential_order,
        seed=p.seed,
        shard_index=p.host_index,
        num_shards=p.num_hosts)
    return ds.Instantiate()

  def _Batches(self):
    from lingvo_tpu.core import datasource
    p = self.p
    batcher = datasource.SequenceBatcher(
        self._MakeSource(), self.ProcessRecord,
        bucket_upper_bound=p.bucket_upper_bound,
        bucket_batch_limit=p.bucket_batch_limit)
    self._batcher = batcher  # kept for InputStats (stats were invisible)
    for batch, limit in ((b, self._LimitFor(b)) for b in batcher):
      yield self._PadBatchDim(batch, limit)

  def _LimitFor(self, batch: NestedMap) -> int:
    # bucket identified by the (padded) time dim of `ids`
    t = batch.Flatten()[0].shape[1] if batch.Flatten() else 0
    p = self.p
    for bound, limit in zip(p.bucket_upper_bound, p.bucket_batch_limit):
      if t <= bound:
        return limit
    return p.bucket_batch_limit[-1]

  def _PadBatchDim(self, batch: NestedMap, limit: int) -> NestedMap:
    b = batch.Flatten()[0].shape[0]
    if b >= limit:
      return batch

    def _Pad(a):
      pad = [(0, limit - b)] + [(0, 0)] * (a.ndim - 1)
      return np.pad(a, pad, constant_values=0)

    out = batch.Transform(_Pad)
    # padded rows are all-padding: paddings=1, weights=0 (suffix match so
    # modality-prefixed leaves like 'text_paddings' are fixed up too)
    for key, val in out.FlattenItems():
      leaf = key.split(".")[-1]
      if leaf == "paddings" or leaf.endswith("_paddings"):
        val[b:] = 1.0
      elif leaf == "weights" or leaf.endswith("_weights"):
        val[b:] = 0.0
    return out

  def _InputBatch(self) -> NestedMap:
    if self._prefetcher is None:
      self._prefetcher = _Prefetcher(self._Batches(),
                                     self.p.prefetch_buffer_size)
    batch = self._prefetcher.Next()
    if batch is None:
      raise StopIteration
    return batch

  def InputStats(self) -> dict:
    """Batcher counters (records / dropped_too_long / flushed_partial /
    batches) + prefetch queue depth. Counters are cumulative ints mutated
    by the prefetch thread; the dict copy is a consistent-enough snapshot
    (GIL-atomic int reads) for summary export."""
    out = {}
    if self._batcher is not None:
      out.update(self._batcher.Snapshot())
    if self._prefetcher is not None:
      out["prefetch_queue_depth"] = self._prefetcher.Depth()
    return out

  def Reset(self):
    super().Reset()
    if self._prefetcher is not None:
      self._prefetcher.Stop()
      self._prefetcher = None


class _Prefetcher:
  """Background thread filling a bounded batch queue (host/device overlap)."""

  def __init__(self, it, capacity: int):
    import queue
    import threading
    self._queue: "queue.Queue" = queue.Queue(maxsize=max(capacity, 1))
    self._stop = threading.Event()
    self._done = False  # latched end-of-stream: Next() must never block on
                        # an exhausted stream (a second eval cycle would
                        # deadlock waiting on the dead filler thread)
    self._error = None  # producer exception, re-raised at the consumer —
                        # a dead filler must not masquerade as end-of-data
    self._thread = threading.Thread(target=self._Fill, args=(it,),
                                    daemon=True)
    self._thread.start()

  def _Fill(self, it):
    try:
      for batch in it:
        while not self._stop.is_set():
          try:
            self._queue.put(batch, timeout=0.2)
            break
          except Exception:
            continue
        if self._stop.is_set():
          return
    except BaseException as e:  # noqa: BLE001
      self._error = e
    finally:
      while not self._stop.is_set():
        try:
          self._queue.put(None, timeout=0.2)  # end-of-stream sentinel
          return
        except Exception:
          continue

  def Next(self):
    if self._done:
      if self._error is not None:
        raise self._error
      return None
    batch = self._queue.get()
    if batch is None:
      self._done = True
      if self._error is not None:
        raise self._error
    return batch

  def Depth(self) -> int:
    """Prefetched batches currently buffered (0 = consumer may starve)."""
    return self._queue.qsize()

  def Stop(self):
    self._stop.set()
    try:
      while True:
        self._queue.get_nowait()
    except Exception:
      pass
    # Wake any consumer blocked in Next()'s untimed get: once stop is set
    # the filler never posts its end-of-stream sentinel, and a blocked
    # consumer (e.g. an async-infeed producer thread being torn down)
    # would otherwise hang forever.
    try:
      self._queue.put_nowait(None)
    except Exception:
      pass


class SyntheticInputGenerator(BaseInputGenerator):
  """Deterministic synthetic batches from a spec (testing/benchmarks).

  spec: NestedMap of (shape_without_batch, dtype, kind) where kind is
  'normal' | 'uniform' | 'int' (with p.vocab_size range) | 'zeros' | 'ones'.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("spec", None, "NestedMap field spec.")
    p.Define("vocab_size", 32000, "Range for int fields.")
    p.Define("seed", 0, "Base RNG seed.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self._step = 0

  def _InputBatch(self) -> NestedMap:
    p = self.p
    rng = np.random.RandomState((p.seed + self._step * 2654435761) % (2**31))
    self._step += 1
    out = NestedMap()
    for key, (shape, dtype, kind) in sorted(p.spec.FlattenItems()):
      full_shape = (p.batch_size,) + tuple(shape)
      if kind == "normal":
        val = rng.randn(*full_shape).astype(dtype)
      elif kind == "uniform":
        val = rng.rand(*full_shape).astype(dtype)
      elif kind == "int":
        val = rng.randint(0, p.vocab_size, full_shape).astype(dtype)
      elif kind == "zeros":
        val = np.zeros(full_shape, dtype)
      elif kind == "ones":
        val = np.ones(full_shape, dtype)
      else:
        raise ValueError(f"Unknown kind {kind}")
      out.Set(key, val)
    return out


class InMemoryInputGenerator(BaseInputGenerator):
  """Batches from fixed in-memory arrays, shuffled per epoch (ref
  BaseTinyDatasetInput, `base_input_generator.py:1706`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("data", None, "NestedMap of numpy arrays, leading dim = N.")
    p.Define("shuffle", True, "Reshuffle each epoch.")
    p.Define("seed", 42, "Shuffle seed.")
    p.Define("repeat", True, "Loop forever; else StopIteration at epoch end.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    leaves = p.data.Flatten()
    self._n = leaves[0].shape[0]
    assert all(l.shape[0] == self._n for l in leaves)
    self._order = np.arange(self._n)
    self._pos = 0
    self._rng = np.random.RandomState(p.seed)
    if p.shuffle and not p.require_sequential_order:
      self._rng.shuffle(self._order)

  def _InputBatch(self) -> NestedMap:
    p = self.p
    bs = p.batch_size
    if not p.repeat:
      if self._pos >= self._n:
        raise StopIteration
      if self._pos + bs > self._n:
        # Final partial batch: pad by wrapping to the epoch start so the
        # batch shape stays static; next call ends the epoch.
        idx = np.concatenate([
            self._order[self._pos:],
            self._order[:bs - (self._n - self._pos)],
        ])
        self._pos = self._n
        self._epoch += 1
        return p.data.Transform(lambda a: a[idx])
    elif self._pos + bs > self._n:
      self._epoch += 1
      self._pos = 0
      if p.shuffle and not p.require_sequential_order:
        self._rng.shuffle(self._order)
    idx = self._order[self._pos:self._pos + bs]
    self._pos += bs
    return p.data.Transform(lambda a: a[idx])

  def Reset(self):
    super().Reset()
    self._pos = 0
    self._rng = np.random.RandomState(self.p.seed)
    self._order = np.arange(self._n)
    if self.p.shuffle and not self.p.require_sequential_order:
      self._rng.shuffle(self._order)

  def EpochBatches(self) -> Iterator[NestedMap]:
    """Yields one epoch in order; final partial batch wrap-padded so every
    example is evaluated with static shapes (eval use)."""
    p = self.p
    for start in range(0, self._n, p.batch_size):
      end = start + p.batch_size
      if end <= self._n:
        idx = np.arange(start, end)
      else:
        idx = np.concatenate(
            [np.arange(start, self._n),
             np.arange(0, end - self._n)])
      yield p.data.Transform(lambda a: a[idx])
