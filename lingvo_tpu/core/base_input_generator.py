"""Input generators: host-side batch producers feeding jit'd programs.

Re-designs `lingvo/core/base_input_generator.py` (2.2k LoC) for JAX: no infeed
queue ops — a generator yields NestedMap batches of numpy arrays; the program
moves them to device with `jax.device_put` against the batch sharding (the
TPU-native equivalent of `CreateTpuEnqueueOps`, ref `:446-670`). Per-host
sharding for multi-process setups mirrors `InfeedContextScope`
(`cluster.py:47-59`) via the `num_hosts`/`host_index` params.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import hyperparams
from lingvo_tpu.core.nested_map import NestedMap


class BaseInputGenerator(base_layer.BaseLayer):
  """Produces NestedMap batches (numpy, host-side)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("batch_size", 0, "Per-host batch size.")
    p.Define("num_samples", 0, "Dataset size (0 = infinite/unknown).")
    p.Define("num_hosts", 1, "Total infeed hosts.")
    p.Define("host_index", 0, "This host's index.")
    p.Define("resettable", True, "Whether Reset() restarts the stream.")
    p.Define("require_sequential_order", False,
             "Deterministic in-order iteration (eval).")
    return p

  def __init__(self, params):
    super().__init__(params)
    self._epoch = 0

  def GlobalBatchSize(self) -> int:
    """Total batch across hosts (ref GlobalBatchSize:350)."""
    return self.p.batch_size * self.p.num_hosts

  def InfeedBatchSize(self) -> int:
    """This host's batch (ref InfeedBatchSize:359)."""
    return self.p.batch_size

  def _InputBatch(self) -> NestedMap:
    """Subclass point: produce one batch."""
    raise NotImplementedError

  def GetPreprocessedInputBatch(self) -> NestedMap:
    return self._InputBatch()

  def __iter__(self) -> Iterator[NestedMap]:
    while True:
      try:
        yield self.GetPreprocessedInputBatch()
      except StopIteration:
        return

  def Reset(self) -> None:
    self._epoch = 0


class SyntheticInputGenerator(BaseInputGenerator):
  """Deterministic synthetic batches from a spec (testing/benchmarks).

  spec: NestedMap of (shape_without_batch, dtype, kind) where kind is
  'normal' | 'uniform' | 'int' (with p.vocab_size range) | 'zeros' | 'ones'.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("spec", None, "NestedMap field spec.")
    p.Define("vocab_size", 32000, "Range for int fields.")
    p.Define("seed", 0, "Base RNG seed.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self._step = 0

  def _InputBatch(self) -> NestedMap:
    p = self.p
    rng = np.random.RandomState((p.seed + self._step * 2654435761) % (2**31))
    self._step += 1
    out = NestedMap()
    for key, (shape, dtype, kind) in sorted(p.spec.FlattenItems()):
      full_shape = (p.batch_size,) + tuple(shape)
      if kind == "normal":
        val = rng.randn(*full_shape).astype(dtype)
      elif kind == "uniform":
        val = rng.rand(*full_shape).astype(dtype)
      elif kind == "int":
        val = rng.randint(0, p.vocab_size, full_shape).astype(dtype)
      elif kind == "zeros":
        val = np.zeros(full_shape, dtype)
      elif kind == "ones":
        val = np.ones(full_shape, dtype)
      else:
        raise ValueError(f"Unknown kind {kind}")
      out.Set(key, val)
    return out


class InMemoryInputGenerator(BaseInputGenerator):
  """Batches from fixed in-memory arrays, shuffled per epoch (ref
  BaseTinyDatasetInput, `base_input_generator.py:1706`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("data", None, "NestedMap of numpy arrays, leading dim = N.")
    p.Define("shuffle", True, "Reshuffle each epoch.")
    p.Define("seed", 42, "Shuffle seed.")
    p.Define("repeat", True, "Loop forever; else StopIteration at epoch end.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    leaves = p.data.Flatten()
    self._n = leaves[0].shape[0]
    assert all(l.shape[0] == self._n for l in leaves)
    self._order = np.arange(self._n)
    self._pos = 0
    self._rng = np.random.RandomState(p.seed)
    if p.shuffle and not p.require_sequential_order:
      self._rng.shuffle(self._order)

  def _InputBatch(self) -> NestedMap:
    p = self.p
    bs = p.batch_size
    if not p.repeat:
      if self._pos >= self._n:
        raise StopIteration
      if self._pos + bs > self._n:
        # Final partial batch: pad by wrapping to the epoch start so the
        # batch shape stays static; next call ends the epoch.
        idx = np.concatenate([
            self._order[self._pos:],
            self._order[:bs - (self._n - self._pos)],
        ])
        self._pos = self._n
        self._epoch += 1
        return p.data.Transform(lambda a: a[idx])
    elif self._pos + bs > self._n:
      self._epoch += 1
      self._pos = 0
      if p.shuffle and not p.require_sequential_order:
        self._rng.shuffle(self._order)
    idx = self._order[self._pos:self._pos + bs]
    self._pos += bs
    return p.data.Transform(lambda a: a[idx])

  def Reset(self):
    super().Reset()
    self._pos = 0
    self._rng = np.random.RandomState(self.p.seed)
    self._order = np.arange(self._n)
    if self.p.shuffle and not self.p.require_sequential_order:
      self._rng.shuffle(self._order)

  def EpochBatches(self) -> Iterator[NestedMap]:
    """Yields one epoch in order; final partial batch wrap-padded so every
    example is evaluated with static shapes (eval use)."""
    p = self.p
    for start in range(0, self._n, p.batch_size):
      end = start + p.batch_size
      if end <= self._n:
        idx = np.arange(start, end)
      else:
        idx = np.concatenate(
            [np.arange(start, self._n),
             np.arange(0, end - self._n)])
      yield p.data.Transform(lambda a: a[idx])
