"""Ragged row descriptors: the shared contract of the unified serving step.

One compiled serving program replaces the three legacy step shapes (pure
decode, mixed prefill+decode, spec-verify). Its activations are PACKED on
a single token axis of static width T: a decode row contributes 1 token,
a prefill row a chunk of tokens, a spec-verify row its last committed
token plus k drafted tokens — and every layer sees the same flat [1, T, D]
activation with per-token routing metadata instead of a padded [B, C, D]
grid. `RaggedRows` is that metadata: a pytree of device arrays (no static
members, so one jit signature covers every admit/decode/spec/retire mix).

Two views of the same pack:

- the TOKEN view (`row_of`, `col_of`, `pos`, `valid`, all [T]): what
  attention needs — each token scatters its K/V through its row's block
  table at global slot `pos` and attends over its own prefix. Padding
  tokens (`valid == False`) write to the trash page and produce garbage
  outputs the engine discards.
- the ROW view (`row_q_pos`, `row_len` [B]; `row_cols` [B, wmax]): what
  O(1)-state mixers need — ssm.GatedSSMLayer gathers its [B, wmax, D]
  per-row chunk via `row_cols`, runs the existing PagedStep recurrence
  (which already handles per-row lengths), and scatters results back to
  the token axis. wmax is implicit in `row_cols`' shape, so it stays a
  shape-static fact without being a python-level argument.

Invariants the builder (serving/scheduler.py BuildRaggedStep) maintains:

- row b's tokens occupy columns 0 .. row_len[b]-1 in kv order; token t
  has `pos[t] == row_q_pos[row_of[t]] + col_of[t]`.
- `row_cols[b, j]` is the token index of row b's j-th token for
  j < row_len[b] and an arbitrary VALID index (0) past it — gathered
  garbage is masked by the consumer via row_len, never read unmasked.
- rows with 0 tokens this step (live but out of budget, or empty slots)
  have row_len == 0 and row_q_pos == the sequence position (NOT 0 —
  q_pos == 0 is the SSM state-reset trigger); empty slots use
  row_q_pos == 1.
- `valid` padding tokens carry row_of/pos clipped into range so device
  gathers stay in bounds.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np


class RaggedRows(NamedTuple):
  """Per-token + per-row routing for one packed ragged step.

  All members are arrays (a jit-transparent pytree). T = packed token
  width, B = engine slots, wmax = widest row this program admits.
  """
  row_of: jnp.ndarray    # [T] int32  slot index of each token
  col_of: jnp.ndarray    # [T] int32  token's column within its row
  pos: jnp.ndarray       # [T] int32  global kv slot the token writes/reads
  valid: jnp.ndarray     # [T] bool   False = padding token
  row_q_pos: jnp.ndarray  # [B] int32  row's first-token global position
  row_len: jnp.ndarray    # [B] int32  tokens the row carries this step
  row_cols: jnp.ndarray   # [B, wmax] int32  token-axis gather indices


def BuildRaggedRows(row_lens, row_q_pos, t: int, wmax: int) -> RaggedRows:
  """Host-side builder: per-row (q_pos, len) -> a packed RaggedRows.

  row_lens/row_q_pos: [B] ints. Rows are packed in slot order; the caller
  guarantees sum(row_lens) <= t and max(row_lens) <= wmax. Returns numpy
  arrays (the engine ships them device-side per step like StepBatch).
  """
  row_lens = np.asarray(row_lens, np.int32)
  row_q_pos = np.asarray(row_q_pos, np.int32)
  b = row_lens.shape[0]
  assert int(row_lens.sum()) <= t, (row_lens, t)
  assert int(row_lens.max(initial=0)) <= wmax, (row_lens, wmax)
  row_of = np.zeros((t,), np.int32)
  col_of = np.zeros((t,), np.int32)
  pos = np.zeros((t,), np.int32)
  valid = np.zeros((t,), bool)
  row_cols = np.zeros((b, wmax), np.int32)
  cursor = 0
  for i in range(b):
    n = int(row_lens[i])
    if n == 0:
      continue
    sl = slice(cursor, cursor + n)
    row_of[sl] = i
    col_of[sl] = np.arange(n)
    pos[sl] = row_q_pos[i] + np.arange(n)
    valid[sl] = True
    row_cols[i, :n] = np.arange(cursor, cursor + n)
    cursor += n
  return RaggedRows(row_of=row_of, col_of=col_of, pos=pos, valid=valid,
                    row_q_pos=row_q_pos, row_len=row_lens,
                    row_cols=row_cols)
