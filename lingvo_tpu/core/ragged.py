"""Ragged row descriptors: the shared contract of the unified serving step.

One compiled serving program replaces the three legacy step shapes (pure
decode, mixed prefill+decode, spec-verify). Its activations are PACKED on
a single token axis of static width T: a decode row contributes 1 token,
a prefill row a chunk of tokens, a spec-verify row its last committed
token plus k drafted tokens — and every layer sees the same flat [1, T, D]
activation with per-token routing metadata instead of a padded [B, C, D]
grid. `RaggedRows` is that metadata: a pytree of device arrays (no static
members, so one jit signature covers every admit/decode/spec/retire mix).

Two views of the same pack:

- the TOKEN view (`row_of`, `col_of`, `pos`, `valid`, all [T]): what
  attention needs — each token scatters its K/V through its row's block
  table at global slot `pos` and attends over its own prefix. Padding
  tokens (`valid == False`) write to the trash page and produce garbage
  outputs the engine discards.
- the ROW view (`row_q_pos`, `row_len` [B]; `row_cols` [B, wmax]): what
  O(1)-state mixers need — ssm.GatedSSMLayer gathers its [B, wmax, D]
  per-row chunk via `row_cols`, runs the existing PagedStep recurrence
  (which already handles per-row lengths), and scatters results back to
  the token axis. wmax is implicit in `row_cols`' shape, so it stays a
  shape-static fact without being a python-level argument.

Invariants the builder (serving/scheduler.py BuildRaggedStep) maintains:

- row b's tokens occupy columns 0 .. row_len[b]-1 in kv order; token t
  has `pos[t] == row_q_pos[row_of[t]] + col_of[t]`.
- `row_cols[b, j]` is the token index of row b's j-th token for
  j < row_len[b] and an arbitrary VALID index (0) past it — gathered
  garbage is masked by the consumer via row_len, never read unmasked.
- rows with 0 tokens this step (live but out of budget, or empty slots)
  have row_len == 0 and row_q_pos == the sequence position (NOT 0 —
  q_pos == 0 is the SSM state-reset trigger); empty slots use
  row_q_pos == 1.
- `valid` padding tokens carry row_of/pos clipped into range so device
  gathers stay in bounds.

Tree speculation (PR 18) packs a token TREE per speculating row in DFS
preorder on the same axis: the root (last committed token) at column 0 and
draft node j at column j+1. Every node keeps its OWN kv slot (`pos` stays
`row_q_pos + col`, so the scatter has no sibling collisions), while the
LOGICAL position a node embeds/attends at is `row_q_pos + depth(node)` —
that is `pos_ids`, which only diverges from `pos` on tree rows. In-step
visibility is the ancestor chain: token t may attend step column c of its
row iff c is an ancestor-or-self, encoded as a 64-bit column bitmask split
into `anc_lo`/`anc_hi` (tree rows are capped at 64 packed columns; the
scheduler clamps width before depth under that cap). Chain rows ship the
sentinel -1/-1 (all columns visible), which keeps the attention mask
bitwise-identical to the pre-tree kernel. `col_parent` is the ROW-view
twin of the same structure: the parent COLUMN of each packed column
(-1 = no in-step parent, i.e. the row's incoming recurrent state), which
is what the SSM tree scan gathers its per-column initial state from.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np


class RaggedRows(NamedTuple):
  """Per-token + per-row routing for one packed ragged step.

  All members are arrays (a jit-transparent pytree). T = packed token
  width, B = engine slots, wmax = widest row this program admits.
  """
  row_of: jnp.ndarray    # [T] int32  slot index of each token
  col_of: jnp.ndarray    # [T] int32  token's column within its row
  pos: jnp.ndarray       # [T] int32  global kv slot the token writes/reads
  valid: jnp.ndarray     # [T] bool   False = padding token
  row_q_pos: jnp.ndarray  # [B] int32  row's first-token global position
  row_len: jnp.ndarray    # [B] int32  tokens the row carries this step
  row_cols: jnp.ndarray   # [B, wmax] int32  token-axis gather indices
  pos_ids: jnp.ndarray   # [T] int32  logical position (rotary); == pos on chains
  anc_lo: jnp.ndarray    # [T] int32  in-step ancestor bitmask, columns 0..31
  anc_hi: jnp.ndarray    # [T] int32  in-step ancestor bitmask, columns 32..63
  col_parent: jnp.ndarray  # [B, wmax] int32  parent column (-1 = row state)


MAX_TREE_COLS = 64  # anc_lo/anc_hi bit budget; scheduler clamps width first.


def TreeDepths(parents) -> np.ndarray:
  """Draft-node depths from DFS parent pointers.

  parents: [R] ints, parent DRAFT index of each draft node (-1 = child of
  the root/committed token). DFS preorder guarantees parents[j] < j.
  Returns [R] depths, root children at depth 1.
  """
  parents = np.asarray(parents, np.int32)
  depth = np.zeros(parents.shape, np.int32)
  for j, p in enumerate(parents):
    assert p < j, (j, p)
    depth[j] = 1 if p < 0 else depth[p] + 1
  return depth


def TreeAncestorMasks(parents) -> tuple[np.ndarray, np.ndarray]:
  """Per-COLUMN ancestor bitmasks (lo, hi) from DFS parent pointers.

  Column 0 is the root; draft j lives at column j+1. Bit c of column
  mask[j] is set iff step column c is an ancestor-or-self of column j.
  Returns two [R+1] int32 arrays (bits 0..31 / 32..63).
  """
  parents = np.asarray(parents, np.int32)
  r = parents.shape[0]
  assert r + 1 <= MAX_TREE_COLS, (r, MAX_TREE_COLS)
  masks = np.zeros((r + 1,), np.int64)
  masks[0] = 1
  for j, p in enumerate(parents):
    col = j + 1
    masks[col] = masks[p + 1] | (np.int64(1) << col)
  lo = (masks & 0xFFFFFFFF).astype(np.uint32).view(np.int32)
  hi = ((masks >> 32) & 0xFFFFFFFF).astype(np.uint32).view(np.int32)
  return lo, hi


def BuildRaggedRows(row_lens, row_q_pos, t: int, wmax: int,
                    row_parents=None) -> RaggedRows:
  """Host-side builder: per-row (q_pos, len) -> a packed RaggedRows.

  row_lens/row_q_pos: [B] ints. Rows are packed in slot order; the caller
  guarantees sum(row_lens) <= t and max(row_lens) <= wmax. Returns numpy
  arrays (the engine ships them device-side per step like StepBatch).

  row_parents: optional {slot: [row_len-1] parent pointers} for TREE rows
  (draft j's parent draft index, -1 = root). Rows absent from the dict are
  chains: pos_ids == pos, anc masks -1 (all visible), col_parent c-1 —
  all bitwise-neutral against the pre-tree program.
  """
  row_lens = np.asarray(row_lens, np.int32)
  row_q_pos = np.asarray(row_q_pos, np.int32)
  b = row_lens.shape[0]
  assert int(row_lens.sum()) <= t, (row_lens, t)
  assert int(row_lens.max(initial=0)) <= wmax, (row_lens, wmax)
  row_of = np.zeros((t,), np.int32)
  col_of = np.zeros((t,), np.int32)
  pos = np.zeros((t,), np.int32)
  valid = np.zeros((t,), bool)
  row_cols = np.zeros((b, wmax), np.int32)
  pos_ids = np.zeros((t,), np.int32)
  anc_lo = np.full((t,), -1, np.int32)
  anc_hi = np.full((t,), -1, np.int32)
  col_parent = np.tile(np.arange(-1, wmax - 1, dtype=np.int32), (b, 1))
  cursor = 0
  for i in range(b):
    n = int(row_lens[i])
    if n == 0:
      continue
    sl = slice(cursor, cursor + n)
    row_of[sl] = i
    col_of[sl] = np.arange(n)
    pos[sl] = row_q_pos[i] + np.arange(n)
    valid[sl] = True
    row_cols[i, :n] = np.arange(cursor, cursor + n)
    parents = None if row_parents is None else row_parents.get(i)
    if parents is not None:
      parents = np.asarray(parents, np.int32)
      assert parents.shape == (n - 1,), (parents.shape, n)
      depths = np.concatenate([[0], TreeDepths(parents)]).astype(np.int32)
      lo, hi = TreeAncestorMasks(parents)
      pos_ids[sl] = row_q_pos[i] + depths
      anc_lo[sl] = lo
      anc_hi[sl] = hi
      col_parent[i, 1:n] = parents + 1
    else:
      pos_ids[sl] = pos[sl]
    cursor += n
  return RaggedRows(row_of=row_of, col_of=col_of, pos=pos, valid=valid,
                    row_q_pos=row_q_pos, row_len=row_lens,
                    row_cols=row_cols, pos_ids=pos_ids,
                    anc_lo=anc_lo, anc_hi=anc_hi, col_parent=col_parent)
