"""Evolved Transformer layers (So et al., https://arxiv.org/abs/1901.11117).

Re-designs `lingvo/core/layers_with_attention.py:1575-1985` (encoder/decoder
branched-convolution blocks + the ET encoder/decoder layer wiring) for the
batch-major JAX stack: [b, t, d] activations, 1-D (depthwise-)separable
convolutions lowered through `lax.conv_general_dilated` (XLA maps these onto
the MXU), padding-aware masking, and causal convolution for the decoder via
left-shifted SAME padding — no time-major transposes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import layers
from lingvo_tpu.core import transformer as transformer_lib
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.core.py_utils import WeightInit, WeightParams


def _MaskPad(x, paddings):
  if paddings is None:
    return x
  return x * (1.0 - paddings)[:, :, None].astype(x.dtype)


class Conv1DLayer(base_layer.BaseLayer):
  """Plain 1-D convolution over time: [b, t, in] -> [b, t, out].

  `causal=True` left-pads so output[t] sees inputs <= t (decoder use).
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("filter_width", 3, "Kernel width over time.")
    p.Define("in_dim", 0, "Input channels.")
    p.Define("out_dim", 0, "Output channels.")
    p.Define("causal", False, "Causal (left-only) padding.")
    p.Define("activation", "NONE", "Output activation.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.in_dim and p.out_dim
    self.CreateVariable(
        "w", WeightParams((p.filter_width, p.in_dim, p.out_dim),
                          p.params_init, p.dtype))
    self.CreateVariable(
        "b", WeightParams((p.out_dim,), WeightInit.Constant(0.0), p.dtype))

  def FProp(self, theta, inputs, paddings=None):
    p = self.p
    th = self.CastTheta(theta)
    x = _MaskPad(self.ToFPropDtype(inputs), paddings)
    if p.causal:
      pad = [(p.filter_width - 1, 0)]
    else:
      left = (p.filter_width - 1) // 2
      pad = [(left, p.filter_width - 1 - left)]
    out = jax.lax.conv_general_dilated(
        x, th.w, window_strides=(1,), padding=pad,
        dimension_numbers=("NWC", "WIO", "NWC"))
    out = out + th.b
    if p.activation != "NONE":
      from lingvo_tpu.core import activations
      out = activations.GetFn(p.activation)(out)
    return _MaskPad(out, paddings)


class SeparableConv1DLayer(base_layer.BaseLayer):
  """Depthwise (over time) + pointwise 1-D separable convolution."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("filter_width", 9, "Depthwise kernel width over time.")
    p.Define("in_dim", 0, "Input channels.")
    p.Define("out_dim", 0, "Output channels.")
    p.Define("causal", False, "Causal (left-only) padding.")
    p.Define("activation", "NONE", "Output activation.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.in_dim and p.out_dim
    self.CreateVariable(
        "depthwise_w",
        WeightParams((p.filter_width, 1, p.in_dim), p.params_init, p.dtype))
    self.CreateVariable(
        "pointwise_w",
        WeightParams((p.in_dim, p.out_dim), p.params_init, p.dtype))
    self.CreateVariable(
        "b", WeightParams((p.out_dim,), WeightInit.Constant(0.0), p.dtype))

  def FProp(self, theta, inputs, paddings=None):
    p = self.p
    th = self.CastTheta(theta)
    x = _MaskPad(self.ToFPropDtype(inputs), paddings)
    if p.causal:
      pad = [(p.filter_width - 1, 0)]
    else:
      left = (p.filter_width - 1) // 2
      pad = [(left, p.filter_width - 1 - left)]
    out = jax.lax.conv_general_dilated(
        x, th.depthwise_w, window_strides=(1,), padding=pad,
        dimension_numbers=("NWC", "WIO", "NWC"),
        feature_group_count=p.in_dim)
    out = jnp.einsum("btd,de->bte", out, th.pointwise_w) + th.b
    if p.activation != "NONE":
      from lingvo_tpu.core import activations
      out = activations.GetFn(p.activation)(out)
    return _MaskPad(out, paddings)


class GluLayer(base_layer.BaseLayer):
  """Gated linear unit block: LN -> (value, sigmoid gate) -> residual
  (ref `layers.py` GluLayer used by the ET encoder)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Model dim.")
    p.Define("dropout_prob", 0.0, "Dropout on the gated output.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.input_dim
    self.CreateChild("ln", layers.LayerNorm.Params().Set(input_dim=p.input_dim))
    self.CreateVariable(
        "w_value", WeightParams((p.input_dim, p.input_dim), p.params_init,
                                p.dtype))
    self.CreateVariable(
        "w_gate", WeightParams((p.input_dim, p.input_dim), p.params_init,
                               p.dtype))
    self.CreateVariable(
        "b_value", WeightParams((p.input_dim,), WeightInit.Constant(0.0),
                                p.dtype))
    self.CreateVariable(
        "b_gate", WeightParams((p.input_dim,), WeightInit.Constant(0.0),
                               p.dtype))
    if p.dropout_prob:
      self.CreateChild(
          "dropout",
          layers.DeterministicDropoutLayer.Params().Set(
              keep_prob=1.0 - p.dropout_prob))

  def FProp(self, theta, inputs, paddings=None):
    th = self.CastTheta(theta)
    x = self.ln.FProp(self.ChildTheta(theta, "ln"), inputs)
    value = jnp.einsum("btd,de->bte", x, th.w_value) + th.b_value
    gate = jnp.einsum("btd,de->bte", x, th.w_gate) + th.b_gate
    out = value * jax.nn.sigmoid(gate)
    if self.p.dropout_prob:
      out = self.dropout.FProp(self.ChildTheta(theta, "dropout"), out)
    return _MaskPad(inputs + out, paddings)


class EvolvedTransformerEncoderBranchedConvsLayer(base_layer.BaseLayer):
  """ET encoder branched-convs block (ref `:1575`).

  LN -> {dense(relu, 4d) | conv3(relu, d/2) zero-padded to 4d} -> sum
  -> LN -> sepconv9 (4d -> d) -> + residual.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Model dim.")
    p.Define("activation", "RELU", "Branch activation.")
    p.Define("dropout_prob", 0.0, "Dropout after each branch.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    d = p.input_dim
    assert d
    self.CreateChild("first_ln", layers.LayerNorm.Params().Set(input_dim=d))
    self.CreateChild("second_ln",
                     layers.LayerNorm.Params().Set(input_dim=4 * d))
    self.CreateChild(
        "dense_layer",
        layers.FCLayer.Params().Set(input_dim=d, output_dim=4 * d,
                                    activation=p.activation))
    self.CreateChild(
        "conv_layer",
        Conv1DLayer.Params().Set(filter_width=3, in_dim=d, out_dim=d // 2,
                                 activation=p.activation))
    self.CreateChild(
        "separable_conv_layer",
        SeparableConv1DLayer.Params().Set(filter_width=9, in_dim=4 * d,
                                          out_dim=d))
    if p.dropout_prob:
      self.CreateChild(
          "dropout",
          layers.DeterministicDropoutLayer.Params().Set(
              keep_prob=1.0 - p.dropout_prob))

  def _Dropout(self, theta, x):
    if self.p.dropout_prob:
      return self.dropout.FProp(self.ChildTheta(theta, "dropout"), x)
    return x

  def FProp(self, theta, inputs, paddings=None):
    p = self.p
    d = p.input_dim
    x = self.first_ln.FProp(self.ChildTheta(theta, "first_ln"), inputs)
    left = self._Dropout(
        theta, self.dense_layer.FProp(self.ChildTheta(theta, "dense_layer"), x))
    right = self._Dropout(
        theta,
        self.conv_layer.FProp(self.ChildTheta(theta, "conv_layer"), x,
                              paddings))
    right = jnp.pad(right, ((0, 0), (0, 0), (0, 4 * d - d // 2)))
    h = left + right
    h = self.second_ln.FProp(self.ChildTheta(theta, "second_ln"), h)
    h = self.separable_conv_layer.FProp(
        self.ChildTheta(theta, "separable_conv_layer"), h, paddings)
    return _MaskPad(inputs + h, paddings)


class EvolvedTransformerDecoderBranchedConvsLayer(base_layer.BaseLayer):
  """ET decoder branched-convs block, causal (ref `:1687`).

  LN -> {sepconv11(relu, 2d) | sepconv7(none, d/2) zero-padded to 2d} -> sum
  -> LN -> sepconv7 (2d -> d) -> + residual. All convs are causal.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Model dim.")
    p.Define("activation", "RELU", "Left-branch activation.")
    p.Define("dropout_prob", 0.0, "Dropout after each conv.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    d = p.input_dim
    assert d
    self.CreateChild("first_ln", layers.LayerNorm.Params().Set(input_dim=d))
    self.CreateChild("second_ln",
                     layers.LayerNorm.Params().Set(input_dim=2 * d))
    self.CreateChild(
        "sep_conv_11",
        SeparableConv1DLayer.Params().Set(filter_width=11, in_dim=d,
                                          out_dim=2 * d, causal=True,
                                          activation=p.activation))
    self.CreateChild(
        "sep_conv_7a",
        SeparableConv1DLayer.Params().Set(filter_width=7, in_dim=d,
                                          out_dim=d // 2, causal=True))
    self.CreateChild(
        "sep_conv_7b",
        SeparableConv1DLayer.Params().Set(filter_width=7, in_dim=2 * d,
                                          out_dim=d, causal=True))
    if p.dropout_prob:
      self.CreateChild(
          "dropout",
          layers.DeterministicDropoutLayer.Params().Set(
              keep_prob=1.0 - p.dropout_prob))

  def _Dropout(self, theta, x):
    if self.p.dropout_prob:
      return self.dropout.FProp(self.ChildTheta(theta, "dropout"), x)
    return x

  def FProp(self, theta, inputs, paddings=None):
    d = self.p.input_dim
    x = self.first_ln.FProp(self.ChildTheta(theta, "first_ln"), inputs)
    left = self._Dropout(
        theta,
        self.sep_conv_11.FProp(self.ChildTheta(theta, "sep_conv_11"), x,
                               paddings))
    right = self._Dropout(
        theta,
        self.sep_conv_7a.FProp(self.ChildTheta(theta, "sep_conv_7a"), x,
                               paddings))
    right = jnp.pad(right, ((0, 0), (0, 0), (0, 2 * d - d // 2)))
    h = left + right
    h = self.second_ln.FProp(self.ChildTheta(theta, "second_ln"), h)
    h = self._Dropout(
        theta,
        self.sep_conv_7b.FProp(self.ChildTheta(theta, "sep_conv_7b"), h,
                               paddings))
    return _MaskPad(inputs + h, paddings)


class EvolvedTransformerEncoderLayer(base_layer.BaseLayer):
  """ET encoder layer: GLU -> branched convs -> transformer (ref `:1807`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Model dim.")
    p.Define("num_heads", 8, "Attention heads.")
    p.Define("hidden_dim", 0, "Transformer FFN dim (0 = 4*input_dim).")
    p.Define("dropout_prob", 0.0, "Dropout.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.input_dim
    self.CreateChild(
        "glu_layer",
        GluLayer.Params().Set(input_dim=p.input_dim,
                              dropout_prob=p.dropout_prob))
    self.CreateChild(
        "branched_convs",
        EvolvedTransformerEncoderBranchedConvsLayer.Params().Set(
            input_dim=p.input_dim, dropout_prob=p.dropout_prob))
    self.CreateChild(
        "transformer_layer",
        transformer_lib.TransformerLayer.Params().Set(
            input_dim=p.input_dim, num_heads=p.num_heads,
            hidden_dim=p.hidden_dim or 4 * p.input_dim))

  def FProp(self, theta, inputs, paddings=None, segment_ids=None):
    x = self.glu_layer.FProp(self.ChildTheta(theta, "glu_layer"), inputs,
                             paddings)
    x = self.branched_convs.FProp(self.ChildTheta(theta, "branched_convs"), x,
                                  paddings)
    return self.transformer_layer.FProp(
        self.ChildTheta(theta, "transformer_layer"), x, paddings,
        segment_ids=segment_ids)


class EvolvedTransformerDecoderLayer(base_layer.BaseLayer):
  """ET decoder layer (ref `:1885`): double-heads self-attention and encoder
  attention branches summed with the residual, then causal branched convs,
  then a transformer layer (SWISH FFN)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Model dim.")
    p.Define("num_heads", 8, "Attention heads (double-heads branch uses 2x).")
    p.Define("hidden_dim", 0, "Transformer FFN dim (0 = 4*input_dim).")
    p.Define("has_aux_atten", True, "Attend to encoder outputs.")
    p.Define("dropout_prob", 0.0, "Dropout.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.input_dim
    self.CreateChild(
        "self_atten_double_heads",
        transformer_lib.TransformerAttentionLayer.Params().Set(
            input_dim=p.input_dim, num_heads=2 * p.num_heads, is_masked=True))
    if p.has_aux_atten:
      self.CreateChild(
          "attend_to_encoder",
          transformer_lib.TransformerAttentionLayer.Params().Set(
              input_dim=p.input_dim, num_heads=p.num_heads))
    self.CreateChild(
        "branched_convs",
        EvolvedTransformerDecoderBranchedConvsLayer.Params().Set(
            input_dim=p.input_dim, dropout_prob=p.dropout_prob))
    ff = transformer_lib.TransformerFeedForwardLayer.Params().Set(
        activation="SWISH")
    self.CreateChild(
        "transformer_layer",
        transformer_lib.TransformerLayer.Params().Set(
            input_dim=p.input_dim, num_heads=p.num_heads,
            hidden_dim=p.hidden_dim or 4 * p.input_dim,
            mask_self_atten=True, has_aux_atten=p.has_aux_atten,
            tr_fflayer_tpl=ff))

  def FProp(self, theta, inputs, paddings=None, aux_vecs=None,
            aux_paddings=None, segment_ids=None):
    p = self.p
    left, _ = self.self_atten_double_heads.FProp(
        self.ChildTheta(theta, "self_atten_double_heads"), inputs,
        paddings=paddings, segment_ids=segment_ids)
    # TransformerAttentionLayer returns residual-added output; recover the
    # branch delta so both branches sum with ONE residual (ref `:1981-1985`).
    h = left
    if p.has_aux_atten:
      assert aux_vecs is not None
      right, _ = self.attend_to_encoder.FProp(
          self.ChildTheta(theta, "attend_to_encoder"), inputs,
          source_vecs=aux_vecs, paddings=aux_paddings)
      h = left + right - inputs
    h = self.branched_convs.FProp(
        self.ChildTheta(theta, "branched_convs"), h, paddings)
    return self.transformer_layer.FProp(
        self.ChildTheta(theta, "transformer_layer"), h, paddings,
        aux_vecs=aux_vecs, aux_paddings=aux_paddings,
        segment_ids=segment_ids)
