"""Sharded large-vocab embedding tables (ref
`lingvo/core/tpu_embedding_layers.py` / `_v1.py` / `_v2.py` +
`tpu_embedding_manager.py`).

The reference drives the TPU embedding mid-level API (host-side enqueue,
load/retrieve around the train loop) because TF cannot express giant sparse
tables in-graph. Under GSPMD none of that machinery is needed: the table is
a regular variable row-sharded over the mesh, the lookup is a one-hot
matmul (MXU-friendly and partitionable — XLA turns it into a collective
gather over the table shards), and optimizer slots shard the same way
automatically. What remains of the reference surface is the table/feature
config and a combiner for multi-valent features.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.core.py_utils import WeightParams


class ShardedEmbeddingTable(base_layer.BaseLayer):
  """One row-sharded table (ref TPUEmbeddingTable)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("vocab_size", 0, "Rows.")
    p.Define("embedding_dim", 0, "Cols.")
    p.Define("shard_axis", "data",
             "Mesh axis the vocab dim shards over (rows split across "
             "chips like the reference's table sharding).")
    p.Define("combiner", "sum", "'sum' | 'mean' for multi-valent lookups.")
    p.Define("scale_sqrt_depth", False, "Scale outputs by sqrt(dim).")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.vocab_size > 0 and p.embedding_dim > 0
    self.CreateVariable(
        "table",
        WeightParams((p.vocab_size, p.embedding_dim), p.params_init, p.dtype,
                     tensor_split_dims_mapping=(p.shard_axis, None)))

  def EmbLookup(self, theta, ids):
    """ids [..., ] int32 -> [..., dim]; one-hot matmul keeps the table
    sharded (gather would force an all-gather of the table)."""
    p = self.p
    th = self.CastTheta(theta)
    one_hot = jax.nn.one_hot(ids, p.vocab_size, dtype=th.table.dtype)
    out = jnp.einsum("...v,vd->...d", one_hot, th.table)
    if p.scale_sqrt_depth:
      out = out * (p.embedding_dim ** 0.5)
    return out

  def MultivalentLookup(self, theta, ids, weights=None):
    """ids [b, n] with optional weights [b, n] -> combined [b, dim]
    (ref combiner semantics: sum or weighted mean over the n values)."""
    p = self.p
    emb = self.EmbLookup(theta, ids)                      # [b, n, d]
    if weights is None:
      weights = jnp.ones(ids.shape, emb.dtype)
    weights = weights.astype(emb.dtype)
    out = jnp.einsum("bnd,bn->bd", emb, weights)
    if p.combiner == "mean":
      out = out / jnp.maximum(
          jnp.sum(weights, axis=-1, keepdims=True), 1e-8)
    return out


class TpuEmbeddingCollection(base_layer.BaseLayer):
  """A set of named tables + feature->table wiring (ref
  TPUEmbeddingLayer/manager: features share tables; one call embeds a
  NestedMap of id features)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("tables", [], "List of (table_name, ShardedEmbeddingTable "
             "Params).")
    p.Define("feature_to_table", {}, "feature name -> table name.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self._table_names = [name for name, _ in p.tables]
    for name, tp in p.tables:
      self.CreateChild(f"table_{name}", tp)
    for feat, tbl in p.feature_to_table.items():
      assert tbl in self._table_names, (feat, tbl)

  def EmbLookup(self, theta, id_features: NestedMap) -> NestedMap:
    """NestedMap of int id arrays -> NestedMap of embeddings."""
    out = NestedMap()
    for feat, ids in id_features.FlattenItems():
      tbl = self.p.feature_to_table[feat]
      table = getattr(self, f"table_{tbl}")
      out.Set(feat, table.EmbLookup(
          self.ChildTheta(theta, f"table_{tbl}"), ids))
    return out
