"""Sharded large-vocab embedding tables (ref
`lingvo/core/tpu_embedding_layers.py` / `_v1.py` / `_v2.py` +
`tpu_embedding_manager.py`).

The reference drives the TPU embedding mid-level API (host-side enqueue,
load/retrieve around the train loop) because TF cannot express giant sparse
tables in-graph. Under GSPMD none of that machinery is needed: the table is
a regular variable row-sharded over the mesh and optimizer slots shard the
same way automatically. Two lookup formulations:

  * one-hot matmul — MXU-friendly, exact, but O(V*d) flops per token; only
    sane for small vocabs (softmax-sized).
  * sharded gather — each device takes the rows it owns (masked local
    `jnp.take`) and a psum over the shard axis combines them; O(tokens*d)
    flops + one all-reduce, which is what makes million-row tables usable
    (the reference's TPU-embedding lookup path,
    `tpu_embedding_layers_v1.py`). Single-device meshes degrade to a plain
    gather.

'auto' picks by vocab size. Per-table optimizers (the mid-level API's
table-specific Adagrad etc.) map onto the existing CompositeOptimizer:
`TpuEmbeddingCollection.OptimizerRules()` emits its regex->optimizer map.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.core.py_utils import WeightParams
from lingvo_tpu.parallel import mesh as mesh_lib


class ShardedEmbeddingTable(base_layer.BaseLayer):
  """One row-sharded table (ref TPUEmbeddingTable)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("vocab_size", 0, "Rows.")
    p.Define("embedding_dim", 0, "Cols.")
    p.Define("shard_axis", "data",
             "Mesh axis the vocab dim shards over (rows split across "
             "chips like the reference's table sharding).")
    p.Define("combiner", "sum", "'sum' | 'mean' for multi-valent lookups.")
    p.Define("scale_sqrt_depth", False, "Scale outputs by sqrt(dim).")
    p.Define("lookup_method", "auto",
             "'one_hot' (O(V*d) matmul), 'gather' (sharded take + psum, "
             "O(tokens*d)), or 'auto' (one_hot only for small vocabs).")
    p.Define("one_hot_vocab_threshold", 8192,
             "'auto' uses the one-hot matmul at or below this vocab size.")
    p.Define("optimizer", None,
             "Optional per-table optimizer Params (ref per-table Adagrad "
             "etc. in the TPU-embedding mid-level API); consumed by "
             "TpuEmbeddingCollection.OptimizerRules -> CompositeOptimizer.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.vocab_size > 0 and p.embedding_dim > 0
    self.CreateVariable(
        "table",
        WeightParams((p.vocab_size, p.embedding_dim), p.params_init, p.dtype,
                     tensor_split_dims_mapping=(p.shard_axis, None)))

  def EmbLookup(self, theta, ids):
    """ids [..., ] int32 -> [..., dim]."""
    p = self.p
    th = self.CastTheta(theta)
    method = p.lookup_method
    if method == "auto":
      method = ("one_hot" if p.vocab_size <= p.one_hot_vocab_threshold
                else "gather")
    if method == "one_hot":
      one_hot = jax.nn.one_hot(ids, p.vocab_size, dtype=th.table.dtype)
      out = jnp.einsum("...v,vd->...d", one_hot, th.table)
    else:
      n_shard = mesh_lib.CurrentMeshAxisSize(p.shard_axis) or 0
      if n_shard > 1 and p.vocab_size % n_shard == 0:
        out = self._ShardedGather(th.table, ids, n_shard)
      else:
        out = jnp.take(th.table, ids, axis=0)
    if p.scale_sqrt_depth:
      out = out * (p.embedding_dim ** 0.5)
    return out

  def _ShardedGather(self, table, ids, n_shard: int):
    """Each device takes from its own row shard; a psum over the shard axis
    assembles the result (every id lives on exactly one shard). Payload of
    the all-reduce is tokens x dim, independent of vocab size; ids arrive
    replicated (shard them over a batch axis upstream if needed)."""
    axis = self.p.shard_axis
    rows = self.p.vocab_size // n_shard
    from jax.sharding import PartitionSpec as P
    mesh = mesh_lib.CurrentMesh()

    def _Local(tbl_l, ids_r):
      lo = jax.lax.axis_index(axis) * rows
      local = ids_r.astype(jnp.int32) - lo
      valid = (local >= 0) & (local < rows)
      emb = jnp.take(tbl_l, jnp.clip(local, 0, rows - 1), axis=0)
      emb = emb * valid[..., None].astype(emb.dtype)
      return jax.lax.psum(emb, axis)

    return mesh_lib.ShardMap(
        _Local, mesh=mesh, in_specs=(P(axis, None), P()),
        out_specs=P())(table, ids)

  def MultivalentLookup(self, theta, ids, weights=None):
    """ids [b, n] with optional weights [b, n] -> combined [b, dim]
    (ref combiner semantics: sum or weighted mean over the n values)."""
    p = self.p
    emb = self.EmbLookup(theta, ids)                      # [b, n, d]
    if weights is None:
      weights = jnp.ones(ids.shape, emb.dtype)
    weights = weights.astype(emb.dtype)
    out = jnp.einsum("bnd,bn->bd", emb, weights)
    if p.combiner == "mean":
      out = out / jnp.maximum(
          jnp.sum(weights, axis=-1, keepdims=True), 1e-8)
    return out


class TpuEmbeddingCollection(base_layer.BaseLayer):
  """A set of named tables + feature->table wiring (ref
  TPUEmbeddingLayer/manager: features share tables; one call embeds a
  NestedMap of id features)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("tables", [], "List of (table_name, ShardedEmbeddingTable "
             "Params).")
    p.Define("feature_to_table", {}, "feature name -> table name.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self._table_names = [name for name, _ in p.tables]
    for name, tp in p.tables:
      self.CreateChild(f"table_{name}", tp)
    for feat, tbl in p.feature_to_table.items():
      assert tbl in self._table_names, (feat, tbl)

  def EmbLookup(self, theta, id_features: NestedMap) -> NestedMap:
    """NestedMap of int id arrays -> NestedMap of embeddings."""
    out = NestedMap()
    for feat, ids in id_features.FlattenItems():
      tbl = self.p.feature_to_table[feat]
      table = getattr(self, f"table_{tbl}")
      out.Set(feat, table.EmbLookup(
          self.ChildTheta(theta, f"table_{tbl}"), ids))
    return out

  def OptimizerRules(self, default_optimizer):
    """(regex, optimizer Params, lr mult) list for CompositeOptimizer —
    routes each table with a per-table `optimizer` to it, everything else
    to `default_optimizer` (ref: per-table optimizer configs of the TPU
    embedding mid-level API, `tpu_embedding_layers_v1.py` load/retrieve
    slot plumbing)."""
    rules = []
    for name, tp in self.p.tables:
      if tp.optimizer is not None:
        rules.append((rf".*\btable_{name}\.", tp.optimizer.Copy(), 1.0))
    rules.append((r".*", default_optimizer, 1.0))
    return rules
