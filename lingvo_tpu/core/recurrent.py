"""Recurrent: the functional scan driver for RNN cells.

Re-designs `lingvo/core/recurrent.py` (`Recurrent:985`). The reference's
1.7k-line hand-written while-loop gradient exists because TF1 graphs could
not differentiate through loops memory-efficiently; `lax.scan` + optional
per-step rematerialization (`jax.checkpoint`) gives the same
memory-efficient BPTT natively, so this module is deliberately thin:
time-major scan over (inputs, paddings) with a cell step, plus accumulator
support via the scan ys.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from lingvo_tpu.core.nested_map import NestedMap


def Recurrent(theta: NestedMap,
              state0: NestedMap,
              inputs: NestedMap,
              cell_fn: Callable[[NestedMap, NestedMap, NestedMap], NestedMap],
              remat: bool = False):
  """Runs cell_fn over the leading (time) dim of every leaf of `inputs`.

  cell_fn(theta, state, inputs_t) -> state1 (a pure step).
  Returns (all_states: leaves [T, ...], final_state).

  remat=True recomputes each step in the backward pass (the memory/compute
  trade the reference's custom gradient made, ref recurrent.py:985).
  """

  def _Step(state, inputs_t):
    state1 = cell_fn(theta, state, inputs_t)
    return state1, state1

  step = jax.checkpoint(_Step) if remat else _Step
  final_state, all_states = jax.lax.scan(step, state0, inputs)
  return all_states, final_state
