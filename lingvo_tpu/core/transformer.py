"""Transformer layers and stacks.

Re-designs the transformer composition layer of the reference
(`batch_major_attention.py`: `TransformerAttentionLayer:5226`,
`TransformerLayer:6265`, `StackedTransformerLayers:7116`,
`RepeatedTransformerLayer:6976`).

The repeated stack is the TPU-native star: N identical layers become ONE
layer with weights stacked on a leading axis, executed with `lax.scan` —
constant compile time in depth, and under GSPMD the stacked weight's leading
axis can also serve as the pipeline stage axis (ref
`gshard_layers.LayerwiseShardablePipelinedLayer:180`; see parallel/pipeline).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import attention as attention_lib
from lingvo_tpu.core import base_layer
from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap


class TransformerFeedForwardLayer(base_layer.BaseLayer):
  """Pre-LN FFN with residual (ref TransformerFeedForwardLayer)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Model dim.")
    p.Define("hidden_dim", 0, "Inner dim.")
    p.Define("activation", "RELU", "Inner activation.")
    p.Define("use_gated_activation", False, "GLU-style gating (e.g. SwiGLU).")
    p.Define("residual_dropout_prob", 0.0, "Dropout on the residual add.")
    p.Define("relu_dropout_prob", 0.0, "Dropout after the inner activation.")
    p.Define("norm_tpl", layers_lib.LayerNorm.Params(), "Norm template.")
    p.Define("add_skip_connection", True, "Residual connection.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.input_dim > 0 and p.hidden_dim > 0
    self.CreateChild("ln", p.norm_tpl.Copy().Set(input_dim=p.input_dim))
    wsdm_in = p.weight_split_dims_mapping  # (None, 'model') typical
    wsdm_out = tuple(reversed(wsdm_in)) if wsdm_in else None
    self.CreateChild(
        "ffn_in",
        layers_lib.ProjectionLayer.Params().Set(
            input_dim=p.input_dim, output_dim=p.hidden_dim,
            activation="NONE", weight_split_dims_mapping=wsdm_in))
    if p.use_gated_activation:
      self.CreateChild(
          "ffn_gate",
          layers_lib.ProjectionLayer.Params().Set(
              input_dim=p.input_dim, output_dim=p.hidden_dim,
              activation="NONE", weight_split_dims_mapping=wsdm_in))
    self.CreateChild(
        "ffn_out",
        layers_lib.ProjectionLayer.Params().Set(
            input_dim=p.hidden_dim, output_dim=p.input_dim,
            activation="NONE", weight_split_dims_mapping=wsdm_out))
    self.CreateChild("dropout", layers_lib.DeterministicDropoutLayer.Params())

  def FProp(self, theta, inputs, paddings=None):
    p = self.p
    from lingvo_tpu.core import activations
    x = self.ln.FProp(theta.ln, inputs)
    h = self.ffn_in.FProp(theta.ffn_in, x)
    act = activations.GetFn(p.activation)
    if p.use_gated_activation:
      h = act(h) * self.ffn_gate.FProp(theta.ffn_gate, x)
    else:
      h = act(h)
    if p.relu_dropout_prob > 0:
      h = self.dropout.FProp(
          self.ChildTheta(theta, "dropout"), h,
          keep_prob=1.0 - p.relu_dropout_prob, name_suffix="relu")
    out = self.ffn_out.FProp(theta.ffn_out, h)
    if p.residual_dropout_prob > 0:
      out = self.dropout.FProp(
          self.ChildTheta(theta, "dropout"), out,
          keep_prob=1.0 - p.residual_dropout_prob, name_suffix="res")
    if paddings is not None:
      out = py_utils.ApplyPadding(paddings, out)
    if p.add_skip_connection:
      out = inputs + out
    return out


class TransformerAttentionLayer(base_layer.BaseLayer):
  """Pre-LN attention block with residual (ref `:5226`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Model dim.")
    p.Define("num_heads", 8, "Heads.")
    p.Define("atten_tpl", attention_lib.MultiHeadedAttention.Params(),
             "Attention template.")
    p.Define("residual_dropout_prob", 0.0, "Residual dropout.")
    p.Define("norm_tpl", layers_lib.LayerNorm.Params(), "Norm template.")
    p.Define("is_masked", False, "Causal self-attention.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateChild("ln", p.norm_tpl.Copy().Set(input_dim=p.input_dim))
    atten_p = p.atten_tpl.Copy().Set(
        input_dim=p.input_dim,
        hidden_dim=p.atten_tpl.hidden_dim or p.input_dim,
        num_heads=p.num_heads)
    self.CreateChild("atten", atten_p)
    self.CreateChild("dropout", layers_lib.DeterministicDropoutLayer.Params())

  def FProp(self, theta, query_vec, source_vecs=None, paddings=None,
            atten_mask=None, segment_ids=None):
    """Self-attention when source_vecs is None; else cross-attention."""
    p = self.p
    x = self.ln.FProp(theta.ln, query_vec)
    if source_vecs is None:
      # causality is passed as a flag (not a materialized mask) so the fused
      # flash kernel can take over when eligible.
      out, probs = self.atten.FProp(
          theta.atten, x, paddings=paddings, atten_mask=atten_mask,
          segment_ids=segment_ids, causal=p.is_masked)
    else:
      out, probs = self.atten.FProp(
          theta.atten, x, key_vec=source_vecs, value_vec=source_vecs,
          paddings=paddings, atten_mask=atten_mask)
    if p.residual_dropout_prob > 0:
      out = self.dropout.FProp(
          self.ChildTheta(theta, "dropout"), out,
          keep_prob=1.0 - p.residual_dropout_prob)
    return query_vec + out, probs

  def InitStates(self, theta, batch_size, max_len):
    return self.atten.InitStates(theta.atten, batch_size, max_len)

  def ExtendStep(self, theta, query_vec, cached_states, cache_paddings=None):
    return self._Step("ExtendStep", theta, query_vec, cached_states,
                      cache_paddings)

  def Prefill(self, theta, query_vec, cached_states, cache_paddings=None,
              live_len=None):
    """Whole-chunk cache priming: query_vec [B, C, D] -> ([B, C, D], states)."""
    return self._Step("Prefill", theta, query_vec, cached_states,
                      cache_paddings, live_len=live_len)

  def _Step(self, method, theta, query_vec, cached_states, cache_paddings,
            **kw):
    x = self.ln.FProp(theta.ln, query_vec)
    out, new_states = getattr(self.atten, method)(
        theta.atten, x, cached_states, paddings=cache_paddings, **kw)
    return query_vec + out, new_states

  def InitPagedStates(self, theta, num_pages, page_size, num_slots=0,
                      kv_cache_dtype=None):
    return self.atten.InitPagedStates(theta.atten, num_pages, page_size,
                                      num_slots=num_slots,
                                      kv_cache_dtype=kv_cache_dtype)

  def PagedStep(self, theta, query_vec, cached_states, block_tables, q_pos,
                in_len, ssm_col_states: bool = False):
    """Block-table continuous-batching step (see attention.PagedStep).

    ssm_col_states: speculative-verify mode — O(1)-state mixers also
    return their per-column state trajectory for rejection rollback
    (ssm.GatedSSMLayer.PagedStep); attention mixers ignore it (KV-page
    rollback is free — the write cursor is host-side and reads never
    pass q_pos + in_len)."""
    x = self.ln.FProp(theta.ln, query_vec)
    if ssm_col_states and hasattr(self.atten, "StateBytesPerSlot"):
      out, new_states = self.atten.PagedStep(
          theta.atten, x, cached_states, block_tables, q_pos, in_len,
          collect_col_states=True)
    else:
      out, new_states = self.atten.PagedStep(
          theta.atten, x, cached_states, block_tables, q_pos, in_len)
    return query_vec + out, new_states

  def RaggedStep(self, theta, query_vec, cached_states, block_tables, rows,
                 ssm_col_states: bool = False):
    """Packed-token continuous-batching step (core/ragged.py RaggedRows);
    query_vec [1, T, D]. Same pre-LN/residual wrapper and spec-verify
    dispatch as PagedStep — only the inner mixer contract changes."""
    x = self.ln.FProp(theta.ln, query_vec)
    if ssm_col_states and hasattr(self.atten, "StateBytesPerSlot"):
      out, new_states = self.atten.RaggedStep(
          theta.atten, x, cached_states, block_tables, rows,
          collect_col_states=True)
    else:
      out, new_states = self.atten.RaggedStep(
          theta.atten, x, cached_states, block_tables, rows)
    return query_vec + out, new_states


class TransformerLayer(base_layer.BaseLayer):
  """Self-atten (+ optional cross-atten) + FFN (ref `TransformerLayer:6265`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Model dim.")
    p.Define("num_heads", 8, "Heads.")
    p.Define("hidden_dim", 0, "FFN inner dim (0 = 4*input).")
    p.Define("mask_self_atten", False, "Causal self-attention (decoder).")
    p.Define("has_aux_atten", False, "Cross-attention to encoder outputs.")
    p.Define("tr_atten_tpl", TransformerAttentionLayer.Params(),
             "Self-attention template.")
    p.Define("tr_aux_atten_tpl", None, "Cross-attention template (None = "
             "same as tr_atten_tpl).")
    p.Define("tr_fflayer_tpl", TransformerFeedForwardLayer.Params(),
             "FFN template.")
    p.Define(
        "mixer_tpl", None,
        "Optional sequence-mixer template replacing the self-attention "
        "inner layer (e.g. ssm.GatedSSMLayer.Params()). The pre-LN/residual "
        "wrapper, decode contract, and paged-serving contract are shared — "
        "only the mixer inside tr_atten_tpl's TransformerAttentionLayer is "
        "swapped, which is how hybrid stacks mix attention and O(1)-state "
        "layers per depth. None = keep tr_atten_tpl.atten_tpl.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    atten_p = p.tr_atten_tpl.Copy().Set(
        input_dim=p.input_dim, num_heads=p.num_heads, is_masked=p.mask_self_atten)
    if p.mixer_tpl is not None:
      atten_p.atten_tpl = p.mixer_tpl.Copy()
    self.CreateChild("self_atten", atten_p)
    if p.has_aux_atten:
      aux_p = (p.tr_aux_atten_tpl or p.tr_atten_tpl).Copy().Set(
          input_dim=p.input_dim, num_heads=p.num_heads, is_masked=False)
      self.CreateChild("aux_atten", aux_p)
    self.CreateChild(
        "fflayer",
        p.tr_fflayer_tpl.Copy().Set(
            input_dim=p.input_dim,
            hidden_dim=p.hidden_dim or 4 * p.input_dim))

  def FProp(self, theta, inputs, paddings=None, aux_vecs=None,
            aux_paddings=None, atten_mask=None, segment_ids=None,
            token_ids=None):
    del token_ids  # only MoE layers with hash gating consume ids
    x, _ = self.self_atten.FProp(
        theta.self_atten, inputs, paddings=paddings, atten_mask=atten_mask,
        segment_ids=segment_ids)
    if self.p.has_aux_atten:
      assert aux_vecs is not None
      x, aux_probs = self.aux_atten.FProp(
          theta.aux_atten, x, source_vecs=aux_vecs, paddings=aux_paddings)
      # consumers that need alignment (e.g. XEnDec target lambdas) collect
      # per-layer cross-attention probs trace-side, no API change
      coll = py_utils.NamedCollectionTop("cross_atten_probs")
      if coll is not None and aux_probs is not None:
        coll[self.path] = aux_probs
    return self.fflayer.FProp(theta.fflayer, x, paddings)

  def InitStates(self, theta, batch_size, max_len):
    return NestedMap(
        self_atten=self.self_atten.InitStates(theta.self_atten, batch_size,
                                              max_len))

  def ExtendStep(self, theta, inputs, cached_states, aux_vecs=None,
                 aux_paddings=None, cache_paddings=None):
    return self._Step("ExtendStep", theta, inputs, cached_states, aux_vecs,
                      aux_paddings, cache_paddings)

  def Prefill(self, theta, inputs, cached_states, aux_vecs=None,
              aux_paddings=None, cache_paddings=None, live_len=None):
    return self._Step("Prefill", theta, inputs, cached_states, aux_vecs,
                      aux_paddings, cache_paddings, live_len=live_len)

  def _Step(self, method, theta, inputs, cached_states, aux_vecs,
            aux_paddings, cache_paddings, **kw):
    x, new_sa = getattr(self.self_atten, method)(
        theta.self_atten, inputs, cached_states.self_atten,
        cache_paddings=cache_paddings, **kw)
    if self.p.has_aux_atten:
      x, _ = self.aux_atten.FProp(
          theta.aux_atten, x, source_vecs=aux_vecs, paddings=aux_paddings)
    out = self.fflayer.FProp(theta.fflayer, x)
    return out, NestedMap(self_atten=new_sa)

  def InitPagedStates(self, theta, num_pages, page_size, num_slots=0,
                      kv_cache_dtype=None):
    assert not self.p.has_aux_atten, (
        "continuous-batching serving is decoder-only (no cross-attention)")
    return NestedMap(self_atten=self.self_atten.InitPagedStates(
        theta.self_atten, num_pages, page_size, num_slots=num_slots,
        kv_cache_dtype=kv_cache_dtype))

  def PagedStep(self, theta, inputs, cached_states, block_tables, q_pos,
                in_len, ssm_col_states: bool = False):
    x, new_sa = self.self_atten.PagedStep(
        theta.self_atten, inputs, cached_states.self_atten, block_tables,
        q_pos, in_len, ssm_col_states=ssm_col_states)
    out = self.fflayer.FProp(theta.fflayer, x)
    return out, NestedMap(self_atten=new_sa)

  def RaggedStep(self, theta, inputs, cached_states, block_tables, rows,
                 ssm_col_states: bool = False):
    x, new_sa = self.self_atten.RaggedStep(
        theta.self_atten, inputs, cached_states.self_atten, block_tables,
        rows, ssm_col_states=ssm_col_states)
    out = self.fflayer.FProp(theta.fflayer, x)
    return out, NestedMap(self_atten=new_sa)


class StackedTransformerLayers(base_layer.BaseLayer):
  """N distinct transformer layers (ref `StackedTransformerLayers:7116`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("num_layers", 0, "Depth.")
    p.Define("transformer_layer_params_tpl", TransformerLayer.Params(),
             "Per-layer template.")
    p.Define(
        "layer_tpls", None,
        "Optional explicit per-layer templates (list of TransformerLayer "
        "Params, length num_layers) overriding transformer_layer_params_tpl "
        "— the hook heterogeneous stacks (hybrid attention/SSM) hang off. "
        "Also the repeat-block body trick: a RepeatedTransformerLayer whose "
        "body is a StackedTransformerLayers with layer_tpls scans one "
        "heterogeneous block of depth k, giving num_layers/k repeats of "
        "e.g. [ssm, ssm, ..., attention].")
    p.Define("final_ln", True, "LayerNorm on the final output.")
    p.Define("input_dim", 0, "Model dim (propagated to layers).")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.num_layers > 0
    if p.layer_tpls:
      assert len(p.layer_tpls) == p.num_layers, (
          len(p.layer_tpls), p.num_layers)
      tpls = [t.Copy() for t in p.layer_tpls]
    else:
      tpls = [p.transformer_layer_params_tpl.Copy()
              for _ in range(p.num_layers)]
    if p.input_dim:
      for t in tpls:
        t.input_dim = p.input_dim
    self.CreateChildren("x_layers", tpls)
    if p.final_ln:
      self.CreateChild(
          "final_ln",
          layers_lib.LayerNorm.Params().Set(
              input_dim=p.input_dim or tpls[0].input_dim))

  def FProp(self, theta, inputs, paddings=None, aux_vecs=None,
            aux_paddings=None, segment_ids=None, token_ids=None):
    x = inputs
    for i, layer in enumerate(self.x_layers):
      x = layer.FProp(theta.x_layers[i], x, paddings, aux_vecs, aux_paddings,
                      segment_ids=segment_ids, token_ids=token_ids)
    if self.p.final_ln:
      x = self.final_ln.FProp(theta.final_ln, x)
    return x

  def InitStates(self, theta, batch_size, max_len):
    return NestedMap(x_layers=[
        l.InitStates(theta.x_layers[i], batch_size, max_len)
        for i, l in enumerate(self.x_layers)
    ])

  def ExtendStep(self, theta, inputs, cached_states, aux_vecs=None,
                 aux_paddings=None, cache_paddings=None):
    return self._Step("ExtendStep", theta, inputs, cached_states, aux_vecs,
                      aux_paddings, cache_paddings)

  def Prefill(self, theta, inputs, cached_states, aux_vecs=None,
              aux_paddings=None, cache_paddings=None, live_len=None):
    return self._Step("Prefill", theta, inputs, cached_states, aux_vecs,
                      aux_paddings, cache_paddings, live_len=live_len)

  def _Step(self, method, theta, inputs, cached_states, aux_vecs,
            aux_paddings, cache_paddings, **kw):
    x = inputs
    new_states = NestedMap(x_layers=[])
    for i, layer in enumerate(self.x_layers):
      x, ns = getattr(layer, method)(theta.x_layers[i], x,
                                     cached_states.x_layers[i], aux_vecs,
                                     aux_paddings,
                                     cache_paddings=cache_paddings, **kw)
      new_states.x_layers.append(ns)
    if self.p.final_ln:
      x = self.final_ln.FProp(theta.final_ln, x)
    return x, new_states

  def InitPagedStates(self, theta, num_pages, page_size, num_slots=0,
                      kv_cache_dtype=None):
    return NestedMap(x_layers=[
        l.InitPagedStates(theta.x_layers[i], num_pages, page_size,
                          num_slots=num_slots, kv_cache_dtype=kv_cache_dtype)
        for i, l in enumerate(self.x_layers)
    ])

  def PagedStep(self, theta, inputs, cached_states, block_tables, q_pos,
                in_len, ssm_col_states: bool = False):
    # forward the spec-verify flag only when set, so layer bodies that
    # predate it (no serving contract) are never handed a surprise kwarg
    kw = {"ssm_col_states": True} if ssm_col_states else {}
    x = inputs
    new_states = NestedMap(x_layers=[])
    for i, layer in enumerate(self.x_layers):
      x, ns = layer.PagedStep(theta.x_layers[i], x,
                              cached_states.x_layers[i], block_tables, q_pos,
                              in_len, **kw)
      new_states.x_layers.append(ns)
    if self.p.final_ln:
      x = self.final_ln.FProp(theta.final_ln, x)
    return x, new_states

  def RaggedStep(self, theta, inputs, cached_states, block_tables, rows,
                 ssm_col_states: bool = False):
    kw = {"ssm_col_states": True} if ssm_col_states else {}
    x = inputs
    new_states = NestedMap(x_layers=[])
    for i, layer in enumerate(self.x_layers):
      x, ns = layer.RaggedStep(theta.x_layers[i], x,
                               cached_states.x_layers[i], block_tables,
                               rows, **kw)
      new_states.x_layers.append(ns)
    if self.p.final_ln:
      x = self.final_ln.FProp(theta.final_ln, x)
    return x, new_states

  def PagedStepPrefix(self, theta, inputs, cached_states, block_tables,
                      q_pos, in_len, num_layers: int):
    """First num_layers layers only — the early-exit draft pass for
    self-speculative decoding. States of the untouched suffix layers pass
    through unchanged so the returned pytree matches PagedStep's (the
    draft loop threads it as a transient carry and discards it)."""
    assert 1 <= num_layers <= len(self.x_layers), (
        num_layers, len(self.x_layers))
    x = inputs
    new_states = NestedMap(x_layers=[])
    for i, layer in enumerate(self.x_layers):
      if i < num_layers:
        x, ns = layer.PagedStep(theta.x_layers[i], x,
                                cached_states.x_layers[i], block_tables,
                                q_pos, in_len)
      else:
        ns = cached_states.x_layers[i]
      new_states.x_layers.append(ns)
    if self.p.final_ln:
      x = self.final_ln.FProp(theta.final_ln, x)
    return x, new_states


class RepeatedTransformerLayer(base_layer.BaseLayer):
  """N IDENTICAL-architecture layers as one scan with stacked weights.

  Ref `RepeatedTransformerLayer:6976` + `repeat_layer.GenericRepeatLayer:80`.
  theta.body has every leaf stacked on axis 0 (length num_layers); FProp scans
  the body over that axis. Compile time is O(1) in depth; per-layer dropout
  folds the scan index into the step seed.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("num_layers", 0, "Repeat count.")
    p.Define("body", TransformerLayer.Params(), "The repeated layer.")
    p.Define("per_layer_checkpoint", True,
             "jax.checkpoint each body iteration (remat for long stacks).")
    p.Define(
        "remat_policy", "full",
        "What the per-layer checkpoint saves: 'full' = save only the layer "
        "boundary and recompute everything in bwd (min memory, ~4/3x "
        "flops); 'dots' = save matmul outputs and recompute only "
        "elementwise ops (near-zero extra flops, more memory); 'none' = "
        "same as per_layer_checkpoint=False.")
    return p

  def __init__(self, params):
    super().__init__(params)
    assert self.p.num_layers > 0
    self.CreateChild("body", self.p.body)

  def InstantiateVariables(self, key):
    if self._path is None:
      self.FinalizePaths()
    return NestedMap(body=base_layer.StackedInstantiateVariables(
        self.body, key, self.p.num_layers))

  def VariableSpecs(self):
    return NestedMap(body=base_layer.StackedVariableSpecs(
        self.body, self.p.num_layers))

  def FProp(self, theta, inputs, paddings=None, aux_vecs=None,
            aux_paddings=None, segment_ids=None, token_ids=None):
    p = self.p
    aux_flag = py_utils.NewAuxFlag()

    def _BodyInner(theta_i, idx, carry):
      # Fold the layer index into step seeds: each scan iteration gets its
      # own dropout masks even though FProp is traced once.
      with py_utils.StepSeedSalt(idx):
        return self.body.FProp(theta_i, carry, paddings, aux_vecs,
                               aux_paddings, segment_ids=segment_ids,
                               token_ids=token_ids)

    wrapped = py_utils.CollectAuxLosses(_BodyInner, aux_flag)

    def _Body(carry, per_layer):
      theta_i, idx = per_layer
      x, aux_sum = wrapped(theta_i, idx, carry)
      return x, aux_sum

    body_fn = _Body
    if p.per_layer_checkpoint and p.remat_policy != "none":
      if p.remat_policy == "dots":
        # also pin the MoE dispatch/combine all-to-all outputs (tagged via
        # checkpoint_name in gshard._DispatchShardMap): without this the
        # backward pass replays both forward all-to-alls per MoE layer —
        # pure ICI traffic for activations 'dots' would have saved anyway
        # had the dispatch been a matmul
        policy = jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            jax.checkpoint_policies.save_only_these_names(
                "moe_dispatched", "moe_combined"))
        body_fn = jax.checkpoint(_Body, policy=policy)
      else:
        body_fn = jax.checkpoint(_Body)
    out, aux_per_layer = jax.lax.scan(body_fn, inputs,
                                      (theta.body, jnp.arange(p.num_layers)))
    if aux_flag.emitted:
      py_utils.AddAuxLoss(f"{self.path}/aux_loss", jnp.sum(aux_per_layer))
    return out

  def InitStates(self, theta, batch_size, max_len):
    def _One(theta_i):
      return self.body.InitStates(theta_i, batch_size, max_len)

    return NestedMap(body=jax.vmap(_One)(theta.body))

  def ExtendStep(self, theta, inputs, cached_states, aux_vecs=None,
                 aux_paddings=None, cache_paddings=None):
    return self._Step("ExtendStep", theta, inputs, cached_states, aux_vecs,
                      aux_paddings, cache_paddings)

  def Prefill(self, theta, inputs, cached_states, aux_vecs=None,
              aux_paddings=None, cache_paddings=None, live_len=None):
    return self._Step("Prefill", theta, inputs, cached_states, aux_vecs,
                      aux_paddings, cache_paddings, live_len=live_len)

  def _Step(self, method, theta, inputs, cached_states, aux_vecs,
            aux_paddings, cache_paddings, **kw):
    def _Body(carry, per_layer):
      theta_i, states_i = per_layer
      x, new_states = getattr(self.body, method)(
          theta_i, carry, states_i, aux_vecs, aux_paddings,
          cache_paddings=cache_paddings, **kw)
      return x, new_states

    out, new_states = jax.lax.scan(_Body, inputs,
                                   (theta.body, cached_states.body))
    return out, NestedMap(body=new_states)

  def InitPagedStates(self, theta, num_pages, page_size, num_slots=0,
                      kv_cache_dtype=None):
    def _One(theta_i):
      return self.body.InitPagedStates(theta_i, num_pages, page_size,
                                       num_slots=num_slots,
                                       kv_cache_dtype=kv_cache_dtype)

    return NestedMap(body=jax.vmap(_One)(theta.body))

  def PagedStep(self, theta, inputs, cached_states, block_tables, q_pos,
                in_len, ssm_col_states: bool = False):
    kw = {"ssm_col_states": True} if ssm_col_states else {}

    def _Body(carry, per_layer):
      theta_i, states_i = per_layer
      x, new_states = self.body.PagedStep(theta_i, carry, states_i,
                                          block_tables, q_pos, in_len, **kw)
      return x, new_states

    out, new_states = jax.lax.scan(_Body, inputs,
                                   (theta.body, cached_states.body))
    return out, NestedMap(body=new_states)

  def RaggedStep(self, theta, inputs, cached_states, block_tables, rows,
                 ssm_col_states: bool = False):
    kw = {"ssm_col_states": True} if ssm_col_states else {}

    def _Body(carry, per_layer):
      theta_i, states_i = per_layer
      x, new_states = self.body.RaggedStep(theta_i, carry, states_i,
                                           block_tables, rows, **kw)
      return x, new_states

    out, new_states = jax.lax.scan(_Body, inputs,
                                   (theta.body, cached_states.body))
    return out, NestedMap(body=new_states)

  def PagedStepPrefix(self, theta, inputs, cached_states, block_tables,
                      q_pos, in_len, num_layers: int):
    """First num_layers FLAT layers — the early-exit draft pass.

    num_layers counts flat transformer layers from the bottom, so it must
    be a multiple of the scanned body's depth (1 for a plain repeat, the
    block depth for hybrid repeat-of-stacked bodies); the scan runs over
    the sliced leading repeats and the suffix repeats' states pass
    through untouched (pytree matches PagedStep's)."""
    body_depth = (len(self.body.x_layers)
                  if hasattr(self.body, "x_layers") else 1)
    assert num_layers % body_depth == 0, (num_layers, body_depth)
    reps = num_layers // body_depth
    assert 1 <= reps <= self.p.num_layers, (reps, self.p.num_layers)
    prefix_theta = jax.tree_util.tree_map(lambda t: t[:reps], theta.body)
    prefix_states = jax.tree_util.tree_map(lambda s: s[:reps],
                                           cached_states.body)

    def _Body(carry, per_layer):
      theta_i, states_i = per_layer
      x, new_states = self.body.PagedStep(theta_i, carry, states_i,
                                          block_tables, q_pos, in_len)
      return x, new_states

    out, new_prefix = jax.lax.scan(_Body, inputs,
                                   (prefix_theta, prefix_states))
    new_body = jax.tree_util.tree_map(
        lambda new, old: jnp.concatenate([new, old[reps:]], axis=0),
        new_prefix, cached_states.body)
    return out, NestedMap(body=new_body)
