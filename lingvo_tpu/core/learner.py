"""Learner: the per-loss optimization pipeline.

Re-implements `lingvo/core/learner.py` (`Learner:31`, `Apply:177`,
`ScaleGradients:434`) functionally: gradient computation happens in the train
program with `jax.grad`; the Learner takes (theta, grads, step, opt_state) and
produces (new_theta, new_opt_state, stats), handling loss-weight scaling,
global-norm clipping, per-value capping, NaN/Inf global skip (ref
`_GetGlobalGradScale:395`), Lp regularization, and the LR schedule.

Under data parallelism the gradients arriving here are already mean-reduced by
GSPMD (batch-dim sharding + jax.grad emits the psum) — the TPU-native form of
the reference's `cross_replica_sum` aggregation (`py_utils.py:3059-3079`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import optimizer as optimizer_lib
from lingvo_tpu.core import py_utils
from lingvo_tpu.core import schedule as schedule_lib
from lingvo_tpu.core.nested_map import NestedMap


class Learner(base_layer.BaseLayer):

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("learning_rate", 1e-3, "Base learning rate.")
    p.Define("lr_schedule", schedule_lib.Constant.Params(),
             "Multiplier schedule on learning_rate.")
    p.Define("optimizer", optimizer_lib.Adam.Params(), "Optimizer template.")
    p.Define("loss_name", "loss",
             "Which entry of the task's metrics dict to optimize.")
    p.Define("clip_gradient_norm_to_value", 0.0,
             "If >0, clip global grad norm to this.")
    p.Define("clip_gradient_single_norm_to_value", 0.0,
             "If >0, clip each tensor's norm to this.")
    p.Define("grad_norm_to_clip_to_zero", 0.0,
             "If >0 and global norm exceeds this, skip the step (outlier "
             "batch rejection).")
    p.Define("skip_nan_gradients", True,
             "Skip updates whose global grad norm is NaN/Inf.")
    p.Define("l2_regularizer_weight", None, "Optional L2 on trainable theta.")
    p.Define("l1_regularizer_weight", None, "Optional L1 on trainable theta.")
    p.Define("grad_aggregation_fn", None,
             "Optional fn(grads)->grads before clipping (e.g. custom psum).")
    p.Define("bprop_variable_filter", None,
             "Regex: only vars whose path matches are trained.")
    p.Define("bprop_variable_exclusion", None,
             "Regex: vars whose path matches are NOT trained.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self.CreateChild("lr_sched", self.p.lr_schedule)
    self.CreateChild("opt", self.p.optimizer)

  # -- variable filtering ----------------------------------------------------

  def TrainableFilter(self, path: str, wp=None) -> bool:
    """Whether the variable at `path` is trained by this learner."""
    import re
    p = self.p
    if wp is not None and "non_trainable" in tuple(wp.collections or ()):
      return False
    if p.bprop_variable_filter and not re.search(p.bprop_variable_filter, path):
      return False
    if p.bprop_variable_exclusion and re.search(p.bprop_variable_exclusion,
                                                path):
      return False
    return True

  # -- regularization (added to the loss by the task's train program) --------

  def RegularizationLoss(self, theta: NestedMap) -> jax.Array:
    p = self.p
    loss = jnp.zeros((), jnp.float32)
    if p.l2_regularizer_weight:
      loss += 0.5 * p.l2_regularizer_weight * sum(
          jnp.sum(jnp.square(w.astype(jnp.float32)))
          for w in jax.tree_util.tree_leaves(theta))
    if p.l1_regularizer_weight:
      loss += p.l1_regularizer_weight * sum(
          jnp.sum(jnp.abs(w.astype(jnp.float32)))
          for w in jax.tree_util.tree_leaves(theta))
    return loss

  # -- state -----------------------------------------------------------------

  def InitState(self, theta: NestedMap) -> NestedMap:
    return self.opt.InitState(theta)

  # -- apply -----------------------------------------------------------------

  def LearningRate(self, step) -> jax.Array:
    return self.p.learning_rate * self.lr_sched.Value(step)

  def Apply(self, theta: NestedMap, grads: NestedMap, step,
            opt_state: NestedMap) -> tuple[NestedMap, NestedMap, NestedMap]:
    """Returns (new_theta, new_opt_state, stats NestedMap)."""
    p = self.p
    if p.grad_aggregation_fn is not None:
      grads = p.grad_aggregation_fn(grads)
    grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)

    grad_norm = py_utils.GlobalNorm(grads)
    stats = NestedMap(grad_norm=grad_norm)

    # Global scale: 0 when non-finite or above clip-to-zero; else optional
    # global-norm clip (ref ScaleGradients:434). NaN norms must be sanitized
    # BEFORE entering any arithmetic: 0 * NaN = NaN would defeat the skip.
    finite = jnp.isfinite(grad_norm)
    safe_norm = jnp.where(finite, grad_norm, 1.0)
    keep = finite if p.skip_nan_gradients else jnp.asarray(True)
    if p.grad_norm_to_clip_to_zero > 0:
      keep = jnp.logical_and(keep, safe_norm <= p.grad_norm_to_clip_to_zero)
    grad_scale = keep.astype(jnp.float32)
    if p.clip_gradient_norm_to_value > 0:
      clip = jnp.minimum(
          1.0, p.clip_gradient_norm_to_value / jnp.maximum(safe_norm, 1e-30))
      grad_scale = grad_scale * clip
    # Zero (not NaN-scale) grads on skipped steps so optimizer slots stay
    # finite; theta/state are additionally rolled back below.
    grads = jax.tree_util.tree_map(
        lambda g: jnp.where(keep, g * grad_scale, jnp.zeros_like(g)), grads)
    if p.clip_gradient_single_norm_to_value > 0:

      def _ClipSingle(g):
        n = jnp.sqrt(jnp.sum(jnp.square(g)) + 1e-30)
        return g * jnp.minimum(1.0, p.clip_gradient_single_norm_to_value / n)

      grads = jax.tree_util.tree_map(_ClipSingle, grads)

    lr = self.LearningRate(step)
    stats.learning_rate = lr
    stats.grad_scale = grad_scale

    new_theta, new_state = self.opt.Update(opt_state, grads, theta, lr, step)
    # Skip = keep everything unchanged when scale hit 0 (NaN or outlier).
    skipped = grad_scale == 0.0
    stats.skipped_step = skipped.astype(jnp.float32)
    new_theta = jax.tree_util.tree_map(
        lambda n, o: jnp.where(skipped, o, n), new_theta, theta)
    new_state = jax.tree_util.tree_map(
        lambda n, o: jnp.where(skipped, o, n), new_state, opt_state)
    return new_theta, new_state, stats
