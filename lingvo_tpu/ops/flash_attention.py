"""Flash attention: fused blocked attention as a Pallas TPU kernel.

The hot op behind long-context training: never materializes the [T, T]
probability matrix. Each grid step owns one query block for one (batch, head)
and streams key/value blocks through VMEM with an online-softmax running
max/denominator — O(T * BLOCK) memory instead of O(T^2) (the reference's only
recourse was approximate windowed/chunked attention,
`batch_major_attention.py:2656,4008`).

Forward is the Pallas kernel; backward (jax.custom_vjp) recomputes attention
through a blocked, per-block-remat'ed XLA implementation — O(T * block)
residual memory, compiler-fused matmuls. On CPU the kernel runs in interpret
mode (used by tests for exactness against plain attention).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1.0e30


def _FlashFwdKernel(q_ref, k_ref, v_ref, out_ref, *, block_k: int,
                    causal: bool, sm_scale: float):
  """One (batch*head, q_block) program: stream K/V blocks, online softmax."""
  q = q_ref[0].astype(jnp.float32) * sm_scale          # [block_q, h]
  block_q = q.shape[0]
  t_kv = k_ref.shape[1]
  q_blk = pl.program_id(1)
  q_pos = q_blk * block_q + jax.lax.broadcasted_iota(
      jnp.int32, (block_q, block_k), 0)

  num_k_blocks = t_kv // block_k

  def _Body(kb, carry):
    m_prev, l_prev, acc = carry
    k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
    v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
    s = q @ k.T                                        # [block_q, block_k]
    if causal:
      k_pos = kb * block_k + jax.lax.broadcasted_iota(
          jnp.int32, (block_q, block_k), 1)
      s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    m_cur = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(s - m_new[:, None])
    alpha = jnp.exp(m_prev - m_new)
    l_new = alpha * l_prev + jnp.sum(p, axis=-1)
    acc = acc * alpha[:, None] + p @ v
    return m_new, l_new, acc

  h = q.shape[-1]
  m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
  l0 = jnp.zeros((block_q,), jnp.float32)
  acc0 = jnp.zeros((block_q, h), jnp.float32)
  if causal:
    # only key blocks up to (and including) this query block contribute
    upper = q_blk + 1
  else:
    upper = num_k_blocks
  m, l, acc = jax.lax.fori_loop(0, upper, _Body, (m0, l0, acc0))
  out = acc / jnp.maximum(l, 1e-20)[:, None]
  out_ref[0] = out.astype(out_ref.dtype)


def _FlashForward(q, k, v, block_q: int, block_k: int, causal: bool,
                  interpret: bool):
  """q/k/v: [bn, t, h] -> [bn, t, h]."""
  bn, t, h = q.shape
  sm_scale = 1.0 / math.sqrt(h)
  grid = (bn, t // block_q)
  kernel = functools.partial(
      _FlashFwdKernel, block_k=block_k, causal=causal, sm_scale=sm_scale)
  return pl.pallas_call(
      kernel,
      out_shape=jax.ShapeDtypeStruct((bn, t, h), q.dtype),
      grid=grid,
      in_specs=[
          pl.BlockSpec((1, block_q, h), lambda b, i: (b, i, 0)),
          pl.BlockSpec((1, t, h), lambda b, i: (b, 0, 0)),
          pl.BlockSpec((1, t, h), lambda b, i: (b, 0, 0)),
      ],
      out_specs=pl.BlockSpec((1, block_q, h), lambda b, i: (b, i, 0)),
      interpret=interpret,
  )(q, k, v)


def _BlockedReferenceAttention(q, k, v, causal: bool, block_q: int):
  """Blocked attention in plain XLA: scan over q blocks with per-block remat.

  Backward through this stores only O(T * block_q) residuals (the scan body
  is jax.checkpoint'ed, so the [block_q, T] probabilities are recomputed in
  the backward pass) — the memory contract flash attention promises, kept in
  the vjp too.
  """
  bn, t, h = q.shape
  scale = 1.0 / math.sqrt(h)
  nq = t // block_q
  q_blocks = q.reshape(bn, nq, block_q, h).swapaxes(0, 1)  # [nq, bn, bq, h]

  @jax.checkpoint
  def _OneBlock(carry, per):
    qb, idx = per
    s = jnp.einsum("bqh,bkh->bqk", qb.astype(jnp.float32) * scale,
                   k.astype(jnp.float32))
    if causal:
      q_pos = idx * block_q + jnp.arange(block_q)[:, None]
      k_pos = jnp.arange(t)[None, :]
      s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bqk,bkh->bqh", p, v.astype(jnp.float32))
    return carry, out.astype(q.dtype)

  _, outs = jax.lax.scan(_OneBlock, (), (q_blocks, jnp.arange(nq)))
  return outs.swapaxes(0, 1).reshape(bn, t, h)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _FlashCore(q, k, v, block_q, block_k, causal, interpret):
  return _FlashForward(q, k, v, block_q, block_k, causal, interpret)


def _FlashCoreFwd(q, k, v, block_q, block_k, causal, interpret):
  out = _FlashForward(q, k, v, block_q, block_k, causal, interpret)
  return out, (q, k, v)


def _FlashCoreBwd(block_q, block_k, causal, interpret, res, g):
  q, k, v = res
  # recompute-based blockwise backward: O(T * block_q) residual memory (the
  # scan body is remat'ed); a full Pallas backward kernel is a later
  # optimization.
  _, vjp = jax.vjp(
      lambda q_, k_, v_: _BlockedReferenceAttention(q_, k_, v_, causal,
                                                    block_q), q, k, v)
  return vjp(g)


_FlashCore.defvjp(_FlashCoreFwd, _FlashCoreBwd)


def FlashAttention(q, k, v, *, causal: bool = True, block_q: int = 128,
                   block_k: int = 128, interpret: bool | None = None):
  """Fused attention. q/k/v: [b, t, n, h] -> [b, t, n, h].

  Scaling by 1/sqrt(h) happens INSIDE (don't pre-scale q). Block sizes are
  shrunk automatically to the largest power of two dividing T; h should be a
  multiple of 128 for the MXU on real TPU. interpret=None auto-selects
  (True off-TPU).
  """
  b, t, n, h = q.shape
  if interpret is None:
    interpret = jax.default_backend() != "tpu"

  def _FitBlock(requested):
    # largest power-of-two block <= requested that divides t
    c = min(requested, t)
    while c > 1 and t % c != 0:
      c //= 2
    return max(c, 1)

  block_q = _FitBlock(block_q)
  block_k = _FitBlock(block_k)
  assert t % block_q == 0 and t % block_k == 0, (t, block_q, block_k)

  def _Flat(x):
    return x.transpose(0, 2, 1, 3).reshape(b * n, t, h)

  out = _FlashCore(_Flat(q), _Flat(k), _Flat(v), block_q, block_k, causal,
                   interpret)
  return out.reshape(b, n, t, h).transpose(0, 2, 1, 3)
