"""Flash attention: fused blocked attention as Pallas TPU kernels.

The hot op behind long-context training: never materializes the [T, T]
probability matrix. Forward and backward are both Pallas kernels (the
reference's only recourse was approximate windowed/chunked attention,
`batch_major_attention.py:2656,4008`; it has no fused exact attention).

Design (TPU-first):
- 3D sequential grid `(batch*heads, q_block, k_block)` with K/V streamed
  through VMEM by BlockSpec — the kernel never holds more than one
  `[block, head_dim]` tile of K/V, so VMEM use is O(block * h), independent
  of sequence length. Pallas double-buffers the HBM->VMEM DMAs across grid
  steps automatically.
- Online softmax in f32 VMEM scratch (running max `m`, denominator `l`,
  accumulator `acc`) carried across the innermost (k) grid dimension.
- Forward also emits the logsumexp `lse = m + log(l)` per query row; the
  backward kernels recompute probabilities from (q, k, lse) per block —
  O(T) residual memory instead of O(T^2).
- Backward = two kernels, matching the standard flash-attention backward:
  a dK/dV pass (grid over k blocks, streaming q blocks) and a dQ pass
  (grid over q blocks, streaming k blocks), with
  `delta = rowsum(dout * out)` precomputed in XLA.
- Causal masking skips fully-masked blocks via `pl.when` (no FLOPs, no
  wrong-bound bug when block_q != block_k).

On CPU the kernels run in interpret mode (used by tests for exactness
against plain attention).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1.0e30

# Per-row stats (running max, denominator, logsumexp, delta) are stored
# broadcast across a 128-lane minor dim: TPU VMEM/HBM are (8, 128)-tiled and
# the Mosaic lowering rejects 2D blocks whose minor dims aren't tile-aligned
# (the round-1 on-hardware failure; same layout as jax's own TPU flash
# kernel's l/m residuals). Segment ids use the same trick: q-side ids
# broadcast over LANES, kv-side ids over SUBLANES with t on the minor axis.
LANES = 128
SUBLANES = 8

# jax 0.4.37 ships TPUCompilerParams; newer jax renames it CompilerParams.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")

# Off-TPU, the Pallas kernel runs in interpret mode (~8-10 ms per grid step
# regardless of the compute inside); below this many T*N*H elements the
# plain-XLA lowering wins outright — bench measured flash_speedup 0.798 at
# [1, 256, 2, 32] — so auto-selected interpret mode falls back to XLA.
# Explicit `interpret=True` always runs the kernel (that's how the
# exactness tests exercise it).
_XLA_FALLBACK_MAX_ELEMS = 1 << 21


def _ApplyCausalMask(s, q_start, k_start, block_q: int, block_k: int):
  q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
  k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
  return jnp.where(q_pos >= k_pos, s, NEG_INF)


def _ApplySegmentMask(s, sq_ref, sk_ref, block_q: int, block_k: int):
  """Masks cross-segment pairs: seg_q == seg_k keeps a pair.

  Padding carries segment 0, so pad queries still attend pad keys — every
  row keeps at least its diagonal, the online-softmax denominator stays
  well-conditioned, and pad outputs are finite garbage that the loss mask
  zeroes (their dout is exactly 0, so no gradient leaks through them).
  """
  del block_q, block_k
  sq = sq_ref[0][:, :1]    # [block_q, LANES] -> [block_q, 1]
  sk = sk_ref[0][:1, :]    # [SUBLANES, block_k] -> [1, block_k]
  return jnp.where(sq == sk, s, NEG_INF)


def _DotF32(a, b, contract):
  """Matmul keeping the inputs' native dtype with f32 accumulation.

  Pre-casting bf16 operands to f32 (the obvious way to get f32 math) forces
  the MXU into f32xf32 mode at a fraction of bf16 throughput; the fast path
  is native-dtype inputs + preferred_element_type=f32, like XLA's own
  attention fusions. `contract` = (a_axis, b_axis).
  """
  return jax.lax.dot_general(
      a, b, (((contract[0],), (contract[1],)), ((), ())),
      preferred_element_type=jnp.float32)


def _RecomputePandDs(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     sq_ref, sk_ref, q_start, k_start, *, block_q: int,
                     block_k: int, causal: bool, sm_scale: float):
  """Shared backward-block recompute: returns (q, k, do, p, ds).

  q/k/do keep their input dtype (MXU fast path); p and ds are f32
  (consumers cast them back for their matmuls). p = exp(s - lse)
  reproduces the forward probabilities from the saved logsumexp;
  ds = p * (dp - delta) * sm_scale is d(loss)/d(q k^T). Both backward
  kernels must use this same definition or dQ vs dK/dV gradients silently
  diverge.
  """
  q = q_ref[0]                                          # [block_q, h]
  k = k_ref[0]                                          # [block_k, h]
  v = v_ref[0]                                          # [block_k, h]
  do = do_ref[0]                                        # [block_q, h]
  lse = lse_ref[0][:, :1]                               # [block_q, 1]
  delta = delta_ref[0][:, :1]                           # [block_q, 1]
  s = _DotF32(q, k, (1, 1)) * sm_scale                  # [block_q, block_k]
  if causal:
    s = _ApplyCausalMask(s, q_start, k_start, block_q, block_k)
  if sq_ref is not None:
    s = _ApplySegmentMask(s, sq_ref, sk_ref, block_q, block_k)
  p = jnp.exp(s - lse)                                  # f32 [bq, bk]
  dp = _DotF32(do, v, (1, 1))                           # [block_q, block_k]
  ds = p * (dp - delta) * sm_scale
  return q, k, do, p, ds


def _FwdKernel(*refs, block_q: int, block_k: int, nk: int, causal: bool,
               sm_scale: float, has_seg: bool):
  """One (batch*head, q_block, k_block) program step."""
  if has_seg:
    (q_ref, k_ref, v_ref, sq_ref, sk_ref, out_ref, lse_ref, m_scr, l_scr,
     acc_scr) = refs
  else:
    q_ref, k_ref, v_ref, out_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    sq_ref = sk_ref = None
  qi = pl.program_id(1)
  kb = pl.program_id(2)
  q_start = qi * block_q
  k_start = kb * block_k

  @pl.when(kb == 0)
  def _Init():
    m_scr[:] = jnp.full_like(m_scr, NEG_INF)
    l_scr[:] = jnp.zeros_like(l_scr)
    acc_scr[:] = jnp.zeros_like(acc_scr)

  # A block contributes unless it is entirely in the causal future:
  # smallest q position is q_start, largest k position is k_start+block_k-1.
  def _Accumulate():
    q = q_ref[0]                                        # [block_q, h]
    k = k_ref[0]                                        # [block_k, h]
    v = v_ref[0]                                        # [block_k, h]
    s = _DotF32(q, k, (1, 1)) * sm_scale                # f32 [bq, bk]
    if causal:
      s = _ApplyCausalMask(s, q_start, k_start, block_q, block_k)
    if sq_ref is not None:
      s = _ApplySegmentMask(s, sq_ref, sk_ref, block_q, block_k)
    m_prev = m_scr[:, :1]                               # [block_q, 1]
    l_prev = l_scr[:, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    # Rows with no unmasked key yet have m_new = NEG_INF; exp(s - m_new)
    # would be exp(0) = 1 for masked entries (causal-only kernels dodge
    # this because the diagonal appears in k-block 0, but segment masks
    # don't). Substitute 0 so masked rows contribute p = exp(NEG_INF) = 0.
    m_safe = jnp.where(m_new <= NEG_INF * 0.5, 0.0, m_new)
    p = jnp.exp(s - m_safe)
    alpha = jnp.exp(m_prev - m_new)
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(
        alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True), l_scr.shape)
    # p rounds to the input dtype for the MXU (standard flash practice)
    acc_scr[:] = acc_scr[:] * alpha + _DotF32(p.astype(v.dtype), v, (1, 0))

  if causal:
    pl.when(k_start <= q_start + block_q - 1)(_Accumulate)
  else:
    _Accumulate()

  if causal:
    # last contributing k block covers query position q_start + block_q - 1
    last_kb = jnp.minimum((q_start + block_q - 1) // block_k, nk - 1)
    is_last = kb == last_kb
  else:
    is_last = kb == nk - 1

  @pl.when(is_last)
  def _Emit():
    l = jnp.maximum(l_scr[:, :1], 1e-20)                # [block_q, 1]
    out_ref[0] = (acc_scr[:] / l).astype(out_ref.dtype)
    lse_ref[0] = jnp.broadcast_to(m_scr[:, :1] + jnp.log(l),
                                  lse_ref.shape[1:]).astype(lse_ref.dtype)


def _FlashForward(q, k, v, seg, block_q: int, block_k: int, causal: bool,
                  interpret: bool):
  """q/k/v: [bn, t, h], seg: [bn, t] int32 or None
  -> (out [bn, t, h], lse [bn, t, LANES])."""
  bn, t, h = q.shape
  sm_scale = 1.0 / math.sqrt(h)
  nq, nk = t // block_q, t // block_k
  kernel = functools.partial(
      _FwdKernel, block_q=block_q, block_k=block_k, nk=nk, causal=causal,
      sm_scale=sm_scale, has_seg=seg is not None)
  if causal:
    # clamp the K/V block index so fully-masked grid steps re-request the
    # previous block — Pallas elides the DMA (no wasted HBM bandwidth).
    kv_blk = lambda i, j: jnp.minimum(j, ((i + 1) * block_q - 1) // block_k)
  else:
    kv_blk = lambda i, j: j
  inputs = [q, k, v]
  in_specs = [
      pl.BlockSpec((1, block_q, h), lambda b, i, j: (b, i, 0)),
      pl.BlockSpec((1, block_k, h), lambda b, i, j: (b, kv_blk(i, j), 0)),
      pl.BlockSpec((1, block_k, h), lambda b, i, j: (b, kv_blk(i, j), 0)),
  ]
  if seg is not None:
    # seg is [b_true, t] (per-batch, not per-head); index maps divide the
    # flattened batch*head grid index back down so heads share one copy
    n_rep = bn // seg.shape[0]
    seg_q = jnp.broadcast_to(seg[:, :, None],
                             (seg.shape[0], t, LANES)).astype(jnp.int32)
    seg_kv = jnp.broadcast_to(seg[:, None, :],
                              (seg.shape[0], SUBLANES, t)).astype(jnp.int32)
    inputs += [seg_q, seg_kv]
    in_specs += [
        pl.BlockSpec((1, block_q, LANES), lambda b, i, j: (b // n_rep, i, 0)),
        pl.BlockSpec((1, SUBLANES, block_k),
                     lambda b, i, j: (b // n_rep, 0, kv_blk(i, j))),
    ]
  out, lse = pl.pallas_call(
      kernel,
      out_shape=[
          jax.ShapeDtypeStruct((bn, t, h), q.dtype),
          jax.ShapeDtypeStruct((bn, t, LANES), jnp.float32),
      ],
      grid=(bn, nq, nk),
      in_specs=in_specs,
      out_specs=[
          pl.BlockSpec((1, block_q, h), lambda b, i, j: (b, i, 0)),
          pl.BlockSpec((1, block_q, LANES), lambda b, i, j: (b, i, 0)),
      ],
      scratch_shapes=[
          pltpu.VMEM((block_q, LANES), jnp.float32),
          pltpu.VMEM((block_q, LANES), jnp.float32),
          pltpu.VMEM((block_q, h), jnp.float32),
      ],
      compiler_params=_CompilerParams(
          dimension_semantics=("parallel", "parallel", "arbitrary")),
      interpret=interpret,
  )(*inputs)
  return out, lse


def _DkDvKernel(*refs, block_q: int, block_k: int, nq: int, causal: bool,
                sm_scale: float, has_seg: bool):
  """One (batch*head, k_block, q_block) step: accumulate dK, dV."""
  if has_seg:
    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, sq_ref, sk_ref,
     dk_ref, dv_ref, dk_scr, dv_scr) = refs
  else:
    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
     dk_scr, dv_scr) = refs
    sq_ref = sk_ref = None
  kb = pl.program_id(1)
  qi = pl.program_id(2)
  q_start = qi * block_q
  k_start = kb * block_k

  @pl.when(qi == 0)
  def _Init():
    dk_scr[:] = jnp.zeros_like(dk_scr)
    dv_scr[:] = jnp.zeros_like(dv_scr)

  def _Accumulate():
    q, _, do, p, ds = _RecomputePandDs(
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, sq_ref, sk_ref,
        q_start, k_start, block_q=block_q, block_k=block_k, causal=causal,
        sm_scale=sm_scale)
    dv_scr[:] = dv_scr[:] + _DotF32(p.astype(do.dtype), do, (0, 0))
    dk_scr[:] = dk_scr[:] + _DotF32(ds.astype(q.dtype), q, (0, 0))

  if causal:
    pl.when(k_start <= q_start + block_q - 1)(_Accumulate)
  else:
    _Accumulate()

  @pl.when(qi == nq - 1)
  def _Emit():
    dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
    dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _DqKernel(*refs, block_q: int, block_k: int, nk: int, causal: bool,
              sm_scale: float, has_seg: bool):
  """One (batch*head, q_block, k_block) step: accumulate dQ."""
  if has_seg:
    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, sq_ref, sk_ref,
     dq_ref, dq_scr) = refs
  else:
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr = refs
    sq_ref = sk_ref = None
  qi = pl.program_id(1)
  kb = pl.program_id(2)
  q_start = qi * block_q
  k_start = kb * block_k

  @pl.when(kb == 0)
  def _Init():
    dq_scr[:] = jnp.zeros_like(dq_scr)

  def _Accumulate():
    _, k, _, _, ds = _RecomputePandDs(
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, sq_ref, sk_ref,
        q_start, k_start, block_q=block_q, block_k=block_k, causal=causal,
        sm_scale=sm_scale)
    dq_scr[:] = dq_scr[:] + _DotF32(ds.astype(k.dtype), k, (1, 0))

  if causal:
    pl.when(k_start <= q_start + block_q - 1)(_Accumulate)
  else:
    _Accumulate()

  @pl.when(kb == nk - 1)
  def _Emit():
    dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _FlashBackward(q, k, v, seg, out, lse, do, block_q: int, block_k: int,
                   causal: bool, interpret: bool):
  bn, t, h = q.shape
  sm_scale = 1.0 / math.sqrt(h)
  nq, nk = t // block_q, t // block_k
  has_seg = seg is not None
  delta = jnp.broadcast_to(
      jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1,
              keepdims=True), (bn, t, LANES))           # [bn, t, LANES]
  if causal:
    kv_blk = lambda i, j: jnp.minimum(j, ((i + 1) * block_q - 1) // block_k)
    qi_of = lambda j, i: jnp.maximum(i, (j * block_k) // block_q)
  else:
    kv_blk = lambda i, j: j
    qi_of = lambda j, i: i
  q_idx = lambda b, j, i: (b, qi_of(j, i), 0)
  row_idx = lambda b, j, i: (b, qi_of(j, i), 0)

  dkdv_inputs = [q, k, v, do, lse, delta]
  dkdv_specs = [
      pl.BlockSpec((1, block_q, h), q_idx),                      # q
      pl.BlockSpec((1, block_k, h), lambda b, j, i: (b, j, 0)),  # k
      pl.BlockSpec((1, block_k, h), lambda b, j, i: (b, j, 0)),  # v
      pl.BlockSpec((1, block_q, h), q_idx),                      # do
      pl.BlockSpec((1, block_q, LANES), row_idx),                # lse
      pl.BlockSpec((1, block_q, LANES), row_idx),                # delta
  ]
  if has_seg:
    n_rep = bn // seg.shape[0]
    seg_q3 = jnp.broadcast_to(seg[:, :, None],
                              (seg.shape[0], t, LANES)).astype(jnp.int32)
    seg_kv3 = jnp.broadcast_to(seg[:, None, :],
                               (seg.shape[0], SUBLANES, t)).astype(jnp.int32)
    dkdv_inputs += [seg_q3, seg_kv3]
    dkdv_specs += [
        pl.BlockSpec((1, block_q, LANES),
                     lambda b, j, i: (b // n_rep, qi_of(j, i), 0)),
        pl.BlockSpec((1, SUBLANES, block_k),
                     lambda b, j, i: (b // n_rep, 0, j)),
    ]
  dk, dv = pl.pallas_call(
      functools.partial(
          _DkDvKernel, block_q=block_q, block_k=block_k, nq=nq,
          causal=causal, sm_scale=sm_scale, has_seg=has_seg),
      out_shape=[
          jax.ShapeDtypeStruct((bn, t, h), k.dtype),
          jax.ShapeDtypeStruct((bn, t, h), v.dtype),
      ],
      grid=(bn, nk, nq),
      in_specs=dkdv_specs,
      out_specs=[
          pl.BlockSpec((1, block_k, h), lambda b, j, i: (b, j, 0)),
          pl.BlockSpec((1, block_k, h), lambda b, j, i: (b, j, 0)),
      ],
      scratch_shapes=[
          pltpu.VMEM((block_k, h), jnp.float32),
          pltpu.VMEM((block_k, h), jnp.float32),
      ],
      compiler_params=_CompilerParams(
          dimension_semantics=("parallel", "parallel", "arbitrary")),
      interpret=interpret,
  )(*dkdv_inputs)

  dq_inputs = [q, k, v, do, lse, delta]
  dq_specs = [
      pl.BlockSpec((1, block_q, h), lambda b, i, j: (b, i, 0)),  # q
      pl.BlockSpec((1, block_k, h), lambda b, i, j: (b, kv_blk(i, j), 0)),
      pl.BlockSpec((1, block_k, h), lambda b, i, j: (b, kv_blk(i, j), 0)),
      pl.BlockSpec((1, block_q, h), lambda b, i, j: (b, i, 0)),  # do
      pl.BlockSpec((1, block_q, LANES), lambda b, i, j: (b, i, 0)),  # lse
      pl.BlockSpec((1, block_q, LANES), lambda b, i, j: (b, i, 0)),  # delta
  ]
  if has_seg:
    dq_inputs += [seg_q3, seg_kv3]
    dq_specs += [
        pl.BlockSpec((1, block_q, LANES),
                     lambda b, i, j: (b // n_rep, i, 0)),
        pl.BlockSpec((1, SUBLANES, block_k),
                     lambda b, i, j: (b // n_rep, 0, kv_blk(i, j))),
    ]
  dq = pl.pallas_call(
      functools.partial(
          _DqKernel, block_q=block_q, block_k=block_k, nk=nk, causal=causal,
          sm_scale=sm_scale, has_seg=has_seg),
      out_shape=jax.ShapeDtypeStruct((bn, t, h), q.dtype),
      grid=(bn, nq, nk),
      in_specs=dq_specs,
      out_specs=pl.BlockSpec((1, block_q, h), lambda b, i, j: (b, i, 0)),
      scratch_shapes=[pltpu.VMEM((block_q, h), jnp.float32)],
      compiler_params=_CompilerParams(
          dimension_semantics=("parallel", "parallel", "arbitrary")),
      interpret=interpret,
  )(*dq_inputs)
  return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _FlashCore(q, k, v, seg, block_q, block_k, causal, interpret):
  out, _ = _FlashForward(q, k, v, seg, block_q, block_k, causal, interpret)
  return out


def _FlashCoreFwd(q, k, v, seg, block_q, block_k, causal, interpret):
  out, lse = _FlashForward(q, k, v, seg, block_q, block_k, causal, interpret)
  return out, (q, k, v, seg, out, lse)


def _FlashCoreBwd(block_q, block_k, causal, interpret, res, g):
  q, k, v, seg, out, lse = res
  dq, dk, dv = _FlashBackward(q, k, v, seg, out, lse, g, block_q, block_k,
                              causal, interpret)
  return dq, dk, dv, None


_FlashCore.defvjp(_FlashCoreFwd, _FlashCoreBwd)


def _XlaAttention(q, k, v, seg, causal: bool):
  """Plain-XLA twin of the kernel's semantics for small off-TPU shapes.

  q/k/v: [b, t, n, h]; seg: [b, t] int32 or None (pairs with different ids
  masked; pad rows carry id 0 and attend each other, matching the kernel).
  Scaling by 1/sqrt(h) applied internally, f32 softmax, output in q.dtype.
  Natively differentiable — no custom VJP needed.
  """
  b, t, n, h = q.shape
  s = jnp.einsum("bqnh,bknh->bnqk", q, k,
                 preferred_element_type=jnp.float32) / math.sqrt(h)
  keep = jnp.ones((b, 1, t, t), jnp.bool_)
  if causal:
    keep &= jnp.tril(jnp.ones((t, t), jnp.bool_))[None, None]
  if seg is not None:
    keep &= (seg[:, None, :, None] == seg[:, None, None, :])
  s = jnp.where(keep, s, NEG_INF)
  p = jax.nn.softmax(s, axis=-1)
  out = jnp.einsum("bnqk,bknh->bqnh", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
  return out.astype(q.dtype)


def SelectedLowering(t: int, n: int, h: int,
                     interpret: bool | None = None) -> str:
  """Which lowering FlashAttention will run for a [*, t, n, h] input:
  'pallas' (real TPU), 'pallas-interpret' (explicit interpret=True, or a
  large off-TPU shape), or 'xla' (auto-interpret small shape)."""
  if interpret is None:
    if jax.default_backend() == "tpu":
      return "pallas"
    if t * n * h < _XLA_FALLBACK_MAX_ELEMS:
      return "xla"
    return "pallas-interpret"
  return "pallas-interpret" if interpret else "pallas"


def SupportedOnTpu(t: int, with_segments: bool = False) -> bool:
  """Whether a [*, t, *, *] input can lower on real TPU hardware.

  Without segments any t whose fitted blocks divide it works (t % 16 is
  plenty); the segment path additionally needs the fitted block_k to stay
  128-lane aligned, i.e. t a multiple of 128 (see _FlashForward specs).
  """
  if t % 16 != 0:
    return False
  return not with_segments or t % LANES == 0


def FlashAttention(q, k, v, *, causal: bool = True, segment_ids=None,
                   block_q: int = 1024, block_k: int = 1024,
                   interpret: bool | None = None):
  """Fused attention. q/k/v: [b, t, n, h] -> [b, t, n, h].

  segment_ids: optional [b, t] int — packed-input segment mask (pairs with
  different ids never attend; padding should carry id 0, whose positions
  produce finite loss-masked garbage rather than NaN). This is what lets
  the packed GShard LM recipe run on the fused kernel.

  Scaling by 1/sqrt(h) happens INSIDE (don't pre-scale q). Block sizes are
  shrunk automatically to the largest power of two dividing T; h should be a
  multiple of 128 for the MXU on real TPU. interpret=None auto-selects
  (True off-TPU).

  Default blocks are 1024x1024 (measured on v5e at [4,2048,8,128] fwd+bwd
  causal bf16: 1.87 ms vs 7.92 ms with 128x128 blocks and 8.37 ms for naive
  XLA attention — small blocks leave the MXU idle behind per-block VPU
  softmax work). VMEM at these defaults is dominated by the
  [block_q, block_k] f32 intermediates (s/p — and dp/ds in the backward —
  at 4 MB each, ~16 MB live in the bwd recompute), not the ~256 KB q/k/v
  tiles; shrink block_k first on parts with smaller VMEM than v5e's.
  """
  b, t, n, h = q.shape
  lowering = SelectedLowering(t, n, h, interpret)
  if lowering == "xla":
    # auto-selected interpret mode on a small shape: interpret-mode grid
    # overhead dwarfs the compute, plain XLA is strictly faster. Explicit
    # interpret=True (kernel tests) never takes this branch.
    seg = None
    if segment_ids is not None:
      seg = segment_ids.astype(jnp.int32)
    return _XlaAttention(q, k, v, seg, causal)
  if interpret is None:
    interpret = jax.default_backend() != "tpu"

  def _FitBlock(requested):
    # largest power-of-two block <= requested that divides t
    c = min(requested, t)
    while c > 1 and t % c != 0:
      c //= 2
    return max(c, 1)

  block_q = _FitBlock(block_q)
  block_k = _FitBlock(block_k)
  assert t % block_q == 0 and t % block_k == 0, (t, block_q, block_k)
  if not interpret and segment_ids is not None and (
      block_k % LANES != 0 or block_q % SUBLANES != 0):
    # the segment-id kv spec puts block_k on the 128-lane minor axis; a
    # shrunken block (t not a multiple of 128) cannot lower on TPU —
    # callers gate on SupportedOnTpu, this is the backstop
    raise ValueError(
        f"segment_ids flash path needs block_q % {SUBLANES} == 0 and "
        f"block_k % {LANES} == 0 on TPU; t={t} gave ({block_q}, {block_k}). "
        "Pad t to a multiple of 128 or use the unfused path.")

  def _Flat(x):
    return x.transpose(0, 2, 1, 3).reshape(b * n, t, h)

  seg = None
  if segment_ids is not None:
    # [b, t]; heads share one copy (the kernels' index maps divide the
    # flattened batch*head index back down, matching _Flat's b-major order)
    seg = segment_ids.astype(jnp.int32)
  out = _FlashCore(_Flat(q), _Flat(k), _Flat(v), seg, block_q, block_k,
                   causal, interpret)
  return out.reshape(b, n, t, h).transpose(0, 2, 1, 3)
