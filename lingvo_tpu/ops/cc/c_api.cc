// C ABI surface for the input-pipeline library (consumed via ctypes).

#include <cstdint>
#include <cstring>
#include <memory>
#include <string>
#include <vector>

#include "record_io.h"
#include "record_yielder.h"

namespace lingvo_tpu {

namespace {

// Wraps a yielder with a pending-record slot so a too-small caller buffer
// never loses the record (two-call protocol: a call that returns a size
// larger than buf_len leaves the record pending for the next call).
struct YielderHandle {
  std::unique_ptr<RecordYielder> yielder;
  std::string pending;
  int pending_source = 0;
  bool has_pending = false;
};

bool TypeSupported(const std::string& type) {
  return type == "text" || type == "tfrecord" || type == "recordio" ||
         type == "iota";
}

}  // namespace

extern "C" {

// Returns nullptr when the type prefix is unknown or the glob matches no
// files (ref record_yielder.cc fails loudly on "Found no files") — the
// Python wrapper raises.
void* LTYielderNew(const char* file_pattern, uint64_t seed,
                   int64_t shuffle_buffer_size, int32_t num_threads,
                   int64_t max_epochs, int32_t shuffle, int32_t shard_index,
                   int32_t num_shards) {
  std::string type, pattern;
  RecordIterator::ParseSpec(file_pattern, &type, &pattern);
  if (!TypeSupported(type)) return nullptr;
  if (type != "iota" && RecordIterator::Glob(pattern).empty()) return nullptr;
  YielderOptions opts;
  opts.file_pattern = file_pattern;
  opts.seed = seed;
  opts.shuffle_buffer_size = shuffle_buffer_size;
  opts.num_threads = num_threads;
  opts.max_epochs = max_epochs;
  opts.shuffle = shuffle != 0;
  opts.shard_index = shard_index;
  opts.num_shards = num_shards;
  auto* h = new YielderHandle();
  h->yielder = std::make_unique<BasicRecordYielder>(opts);
  return h;
}

void* LTMixYielderNew(void** children, const double* weights, int32_t n,
                      uint64_t seed) {
  std::vector<std::unique_ptr<RecordYielder>> kids;
  std::vector<double> w(weights, weights + n);
  for (int32_t i = 0; i < n; ++i) {
    auto* child = static_cast<YielderHandle*>(children[i]);
    kids.emplace_back(std::move(child->yielder));
    delete child;
  }
  auto* h = new YielderHandle();
  h->yielder = std::make_unique<WeightedMixRecordYielder>(
      std::move(kids), w, seed);
  return h;
}

// Fills buf (cap buf_len) with the next record; returns the record length,
// or -1 when exhausted. If the returned length exceeds buf_len the record
// was NOT consumed — call again with a buffer of at least that size.
int64_t LTYielderNext(void* handle, char* buf, int64_t buf_len,
                      int32_t* source_id) {
  auto* h = static_cast<YielderHandle*>(handle);
  if (!h->has_pending) {
    int src = 0;
    if (!h->yielder->Yield(&h->pending, &src)) return -1;
    h->pending_source = src;
    h->has_pending = true;
  }
  int64_t n = static_cast<int64_t>(h->pending.size());
  if (n > buf_len) return n;  // record stays pending
  std::memcpy(buf, h->pending.data(), n);
  if (source_id) *source_id = h->pending_source;
  h->has_pending = false;
  return n;
}

int64_t LTYielderEpochs(void* handle) {
  return static_cast<YielderHandle*>(handle)->yielder->EpochsCompleted();
}

void LTYielderFree(void* handle) {
  delete static_cast<YielderHandle*>(handle);
}

}  // extern "C"

}  // namespace lingvo_tpu
