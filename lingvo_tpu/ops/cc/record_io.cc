#include "record_io.h"

#include <glob.h>
#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>

namespace lingvo_tpu {
namespace {

// ---- text lines -----------------------------------------------------------

class TextLineIterator : public RecordIterator {
 public:
  explicit TextLineIterator(const std::string& path)
      : f_(fopen(path.c_str(), "rb")) {}
  ~TextLineIterator() override {
    if (f_) fclose(f_);
  }
  bool Next(std::string* record) override {
    if (!f_) return false;
    record->clear();
    int c;
    bool any = false;
    while ((c = fgetc(f_)) != EOF) {
      any = true;
      if (c == '\n') return true;
      record->push_back(static_cast<char>(c));
    }
    return any;
  }

 private:
  FILE* f_;
};

// ---- TFRecord (the reference's primary container) -------------------------
// Layout per record: uint64 length | uint32 masked_crc(length) | data |
// uint32 masked_crc(data). CRCs are not verified (payloads are checked by
// downstream parsers; matches common fast-reader behavior).

class TFRecordIterator : public RecordIterator {
 public:
  explicit TFRecordIterator(const std::string& path)
      : f_(fopen(path.c_str(), "rb")) {}
  ~TFRecordIterator() override {
    if (f_) fclose(f_);
  }
  bool Next(std::string* record) override {
    if (!f_) return false;
    uint64_t len = 0;
    if (fread(&len, sizeof(len), 1, f_) != 1) return false;
    // A corrupt/truncated file can carry an absurd length; bound it so we
    // fail cleanly instead of attempting a multi-GB resize (std::bad_alloc).
    // Callers treat `false` as end-of-stream, so make the corruption visible.
    if (len > kMaxRecordBytes) {
      fprintf(stderr,
              "lingvo_tpu record_io: record length %llu exceeds %llu — "
              "corrupt TFRecord file; dropping remainder of shard\n",
              (unsigned long long)len, (unsigned long long)kMaxRecordBytes);
      return false;
    }
    if (fseek(f_, 4, SEEK_CUR) != 0) return false;  // length crc
    record->resize(len);
    if (len > 0 && fread(record->data(), 1, len, f_) != len) return false;
    if (fseek(f_, 4, SEEK_CUR) != 0) return false;  // data crc
    return true;
  }

  static constexpr uint64_t kMaxRecordBytes = 1ull << 30;  // 1 GiB

 private:
  FILE* f_;
};

// ---- length-prefixed binary (our own simple container) --------------------

class RecordIOIterator : public RecordIterator {
 public:
  explicit RecordIOIterator(const std::string& path)
      : f_(fopen(path.c_str(), "rb")) {}
  ~RecordIOIterator() override {
    if (f_) fclose(f_);
  }
  bool Next(std::string* record) override {
    if (!f_) return false;
    uint32_t len = 0;
    if (fread(&len, sizeof(len), 1, f_) != 1) return false;
    if (len > TFRecordIterator::kMaxRecordBytes) {
      fprintf(stderr,
              "lingvo_tpu record_io: record length %u exceeds max — corrupt "
              "recordio file; dropping remainder of shard\n", len);
      return false;
    }
    record->resize(len);
    if (len > 0 && fread(record->data(), 1, len, f_) != len) return false;
    return true;
  }

 private:
  FILE* f_;
};

// ---- iota (synthetic, for tests: "iota:<N>" yields "0".."N-1") ------------

class IotaIterator : public RecordIterator {
 public:
  explicit IotaIterator(const std::string& spec)
      : n_(std::strtoll(spec.c_str(), nullptr, 10)) {}
  bool Next(std::string* record) override {
    if (i_ >= n_) return false;
    *record = std::to_string(i_++);
    return true;
  }

 private:
  int64_t n_;
  int64_t i_ = 0;
};

}  // namespace

std::unique_ptr<RecordIterator> RecordIterator::Open(const std::string& type,
                                                     const std::string& path) {
  if (type == "text") return std::make_unique<TextLineIterator>(path);
  if (type == "tfrecord") return std::make_unique<TFRecordIterator>(path);
  if (type == "recordio") return std::make_unique<RecordIOIterator>(path);
  if (type == "iota") return std::make_unique<IotaIterator>(path);
  return nullptr;
}

std::vector<std::string> RecordIterator::Glob(const std::string& pattern) {
  std::vector<std::string> out;
  glob_t g;
  if (glob(pattern.c_str(), 0, nullptr, &g) == 0) {
    for (size_t i = 0; i < g.gl_pathc; ++i) out.emplace_back(g.gl_pathv[i]);
  }
  globfree(&g);
  std::sort(out.begin(), out.end());
  return out;
}

void RecordIterator::ParseSpec(const std::string& spec, std::string* type,
                               std::string* pattern) {
  auto pos = spec.find(':');
  if (pos == std::string::npos) {
    *type = "text";
    *pattern = spec;
  } else {
    *type = spec.substr(0, pos);
    *pattern = spec.substr(pos + 1);
  }
}

}  // namespace lingvo_tpu
