// BasicRecordYielder: multi-threaded sharded file reading with a shuffle
// ring and epoch tracking.
//
// Re-designs lingvo/core/ops/record_yielder.{h,cc} (BasicRecordYielder:170)
// without the TF runtime: worker threads stream shards through RecordIterator
// into a bounded shuffle buffer; Yield() pops a uniformly-random element.
// Epoch boundaries are tracked so callers can stop after N epochs
// (require_sequential/eval mode uses shuffle_buffer=1, threads=1).
// WeightedMixRecordYielder samples child yielders by weight
// (ref weighted_mix_record_yielder.cc).

#ifndef LINGVO_TPU_OPS_RECORD_YIELDER_H_
#define LINGVO_TPU_OPS_RECORD_YIELDER_H_

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <memory>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include "record_io.h"

namespace lingvo_tpu {

struct YielderOptions {
  std::string file_pattern;   // "type:glob"
  uint64_t seed = 301;
  int64_t shuffle_buffer_size = 10000;
  int num_threads = 2;
  int64_t max_epochs = 0;     // 0 = repeat forever
  bool shuffle = true;
  // Sharding across infeed hosts: this yielder reads files where
  // (file_index % num_shards) == shard_index.
  int shard_index = 0;
  int num_shards = 1;
};

class RecordYielder {
 public:
  virtual ~RecordYielder() = default;
  // Returns false when the stream is exhausted (max_epochs reached).
  virtual bool Yield(std::string* record, int* source_id) = 0;
  virtual int64_t EpochsCompleted() const = 0;
};

class BasicRecordYielder : public RecordYielder {
 public:
  explicit BasicRecordYielder(const YielderOptions& opts);
  ~BasicRecordYielder() override;

  bool Yield(std::string* record, int* source_id) override;
  int64_t EpochsCompleted() const override { return epochs_done_; }

 private:
  void WorkerLoop(int worker_id);
  bool BufferFull() const {
    return static_cast<int64_t>(buf_.size()) >= opts_.shuffle_buffer_size;
  }

  YielderOptions opts_;
  std::vector<std::string> files_;
  std::string type_;
  std::mt19937_64 rng_;

  mutable std::mutex mu_;
  std::condition_variable not_full_;
  std::condition_variable not_empty_;
  std::deque<std::string> buf_;
  std::atomic<int64_t> epochs_done_{0};
  bool producers_done_ = false;
  bool stop_ = false;
  std::vector<std::thread> threads_;

  // work queue of (file index) for the current epoch
  std::vector<int> epoch_files_;
  size_t next_file_ = 0;
  int active_workers_ = 0;
  int64_t current_epoch_ = 0;
  void RefillEpochLocked();
};

class WeightedMixRecordYielder : public RecordYielder {
 public:
  WeightedMixRecordYielder(std::vector<std::unique_ptr<RecordYielder>> kids,
                           const std::vector<double>& weights, uint64_t seed);
  bool Yield(std::string* record, int* source_id) override;
  int64_t EpochsCompleted() const override;

 private:
  std::vector<std::unique_ptr<RecordYielder>> kids_;
  std::vector<double> weights_;
  std::discrete_distribution<int> dist_;
  std::mt19937_64 rng_;
  std::mutex mu_;
};

}  // namespace lingvo_tpu

#endif  // LINGVO_TPU_OPS_RECORD_YIELDER_H_
