// Sequence packing: assign variable-length segments to fixed [rows, time]
// slots (best-fit: fullest row that still fits), producing per-segment
// (row, offset) with -1 for dropped segments.
//
// Re-implements the semantics of the reference's PackSequences op
// (lingvo/core/ops/pack_ops.cc, x_ops.cc:1061-1304): the caller turns the
// assignment into ids/segment_ids/segment_pos arrays (done vectorized in
// numpy on the Python side — no per-token work here).

#include <cstdint>
#include <vector>

namespace lingvo_tpu {

extern "C" {

// lens: [n] segment lengths. Outputs (size n): row index (-1 = dropped),
// time offset within the row. Returns number of packed segments.
int64_t LTPackSequences(const int32_t* lens, int64_t n, int32_t num_rows,
                        int32_t time, int32_t* out_row, int32_t* out_offset,
                        int32_t spread_first_n) {
  (void)spread_first_n;  // reserved (ref pack_ops spread knob)
  std::vector<int32_t> used(num_rows, 0);
  int64_t packed = 0;
  for (int64_t i = 0; i < n; ++i) {
    int32_t len = lens[i];
    out_row[i] = -1;
    out_offset[i] = 0;
    if (len <= 0 || len > time) continue;
    // best-fit: the fullest row that still fits (ties -> lowest index);
    // empty rows are only opened when nothing else fits, maximizing density.
    int32_t best = -1;
    int32_t best_used = -1;
    for (int32_t r = 0; r < num_rows; ++r) {
      if (used[r] + len <= time && used[r] > best_used) {
        best = r;
        best_used = used[r];
      }
    }
    if (best >= 0) {
      out_row[i] = best;
      out_offset[i] = used[best];
      used[best] += len;
      ++packed;
    }
  }
  return packed;
}

}  // extern "C"

}  // namespace lingvo_tpu
