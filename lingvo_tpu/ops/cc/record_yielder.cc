#include "record_yielder.h"

#include <algorithm>
#include <cstdio>

namespace lingvo_tpu {

BasicRecordYielder::BasicRecordYielder(const YielderOptions& opts)
    : opts_(opts), rng_(opts.seed) {
  std::string type, pattern;
  RecordIterator::ParseSpec(opts_.file_pattern, &type, &pattern);
  std::vector<std::string> all;
  if (type == "iota") {
    all.push_back(pattern);  // single virtual "file"
  } else {
    all = RecordIterator::Glob(pattern);
  }
  for (size_t i = 0; i < all.size(); ++i) {
    if (static_cast<int>(i % opts_.num_shards) == opts_.shard_index) {
      files_.push_back(all[i]);
    }
  }
  type_ = type;
  {
    std::lock_guard<std::mutex> l(mu_);
    RefillEpochLocked();
  }
  int n = std::max(1, opts_.num_threads);
  for (int i = 0; i < n; ++i) {
    threads_.emplace_back([this, i] { WorkerLoop(i); });
  }
}

BasicRecordYielder::~BasicRecordYielder() {
  {
    std::lock_guard<std::mutex> l(mu_);
    stop_ = true;
  }
  not_full_.notify_all();
  not_empty_.notify_all();
  for (auto& t : threads_) t.join();
}

void BasicRecordYielder::RefillEpochLocked() {
  epoch_files_.clear();
  for (size_t i = 0; i < files_.size(); ++i) {
    epoch_files_.push_back(static_cast<int>(i));
  }
  if (opts_.shuffle) {
    std::shuffle(epoch_files_.begin(), epoch_files_.end(), rng_);
  }
  next_file_ = 0;
}

void BasicRecordYielder::WorkerLoop(int worker_id) {
  (void)worker_id;
  {
    std::lock_guard<std::mutex> l(mu_);
    if (files_.empty()) {  // nothing to read: mark done, don't spin
      producers_done_ = true;
      not_empty_.notify_all();
      return;
    }
  }
  while (true) {
    int file_idx = -1;
    {
      std::unique_lock<std::mutex> l(mu_);
      while (!stop_ && !producers_done_ &&
             next_file_ >= epoch_files_.size() && active_workers_ > 0) {
        // wait for the epoch to finish draining before rolling over
        not_full_.wait_for(l, std::chrono::milliseconds(50));
      }
      if (stop_ || producers_done_) return;
      if (next_file_ >= epoch_files_.size()) {
        // this worker observes the epoch end
        epochs_done_.fetch_add(1);
        ++current_epoch_;
        if (opts_.max_epochs > 0 && current_epoch_ >= opts_.max_epochs) {
          producers_done_ = true;
          not_empty_.notify_all();
          return;
        }
        RefillEpochLocked();
      }
      file_idx = epoch_files_[next_file_++];
      ++active_workers_;
    }

    auto it = RecordIterator::Open(type_, files_[file_idx]);
    std::string rec;
    while (it && it->Next(&rec)) {
      std::unique_lock<std::mutex> l(mu_);
      not_full_.wait(l, [this] { return stop_ || !BufferFull(); });
      if (stop_) {
        --active_workers_;
        return;
      }
      buf_.push_back(std::move(rec));
      rec.clear();
      not_empty_.notify_one();
    }
    {
      std::lock_guard<std::mutex> l(mu_);
      --active_workers_;
    }
    not_full_.notify_all();
    not_empty_.notify_all();
  }
}

bool BasicRecordYielder::Yield(std::string* record, int* source_id) {
  std::unique_lock<std::mutex> l(mu_);
  not_empty_.wait(l, [this] {
    // tail records may still be in flight until active workers drain
    return stop_ || !buf_.empty() ||
           (producers_done_ && active_workers_ == 0);
  });
  if (buf_.empty()) return false;  // exhausted or stopping
  if (opts_.shuffle) {
    size_t idx = (buf_.size() > 1) ? (rng_() % buf_.size()) : 0;
    std::swap(buf_[idx], buf_.back());
    *record = std::move(buf_.back());
    buf_.pop_back();
  } else {
    // sequential mode: strict FIFO
    *record = std::move(buf_.front());
    buf_.pop_front();
  }
  if (source_id) *source_id = 0;
  not_full_.notify_one();
  return true;
}

WeightedMixRecordYielder::WeightedMixRecordYielder(
    std::vector<std::unique_ptr<RecordYielder>> kids,
    const std::vector<double>& weights, uint64_t seed)
    : kids_(std::move(kids)), weights_(weights),
      dist_(weights.begin(), weights.end()), rng_(seed) {}

bool WeightedMixRecordYielder::Yield(std::string* record, int* source_id) {
  std::lock_guard<std::mutex> l(mu_);
  // Renormalize over non-exhausted children: a dead high-weight child must
  // not starve live low-weight siblings.
  while (true) {
    bool any_alive = false;
    for (double w : weights_) {
      if (w > 0) any_alive = true;
    }
    if (!any_alive) return false;
    int k = dist_(rng_);
    if (weights_[k] <= 0) continue;  // (dist may lag one rebuild)
    int unused = 0;
    if (kids_[k]->Yield(record, &unused)) {
      if (source_id) *source_id = k;
      return true;
    }
    weights_[k] = 0.0;  // exhausted: remove and rebuild the distribution
    bool rebuild_ok = false;
    for (double w : weights_) {
      if (w > 0) rebuild_ok = true;
    }
    if (!rebuild_ok) return false;
    dist_ = std::discrete_distribution<int>(weights_.begin(), weights_.end());
  }
}

int64_t WeightedMixRecordYielder::EpochsCompleted() const {
  int64_t m = 0;
  for (const auto& k : kids_) m = std::max(m, k->EpochsCompleted());
  return m;
}

}  // namespace lingvo_tpu
