// Tokenizers: ascii char-level + vocab (whitespace token -> id).
//
// Re-implements the semantics of the reference's C++ tokenizer kernels
// (lingvo/core/ops/ascii_tokenizer.cc, simple_vocab.cc, registered in
// x_ops.cc:613-860): AsciiTokenizer lowercases and maps chars to a fixed id
// space; VocabTokenizer looks up whitespace-split tokens in a file-loaded
// vocabulary with <unk> fallback. Ids layout (ascii): 0=<s>/pad 1=</s>
// 2=<n_> 3..28='a'..'z' 29..38='0'..'9' 39=' ' 40..=punct table, 73=<unk>.

#include <cctype>
#include <cstdint>
#include <cstring>
#include <fstream>
#include <string>
#include <unordered_map>
#include <vector>

namespace lingvo_tpu {
namespace {

constexpr int kSos = 0, kEos = 1, kNewline = 2, kUnk = 73, kSpace = 39;
const char kPunct[] = "!\"#$%&'()*+,-./:;<=>?@[\\]^_`{|}~";

int AsciiCharToId(char c) {
  if (c == '\n') return kNewline;
  c = static_cast<char>(std::tolower(static_cast<unsigned char>(c)));
  if (c >= 'a' && c <= 'z') return 3 + (c - 'a');
  if (c >= '0' && c <= '9') return 29 + (c - '0');
  if (c == ' ') return kSpace;
  const char* p = std::strchr(kPunct, c);
  if (p && c != '\0') return 40 + static_cast<int>(p - kPunct);
  return kUnk;
}

char AsciiIdToChar(int id) {
  if (id == kNewline) return '\n';
  if (id >= 3 && id <= 28) return static_cast<char>('a' + id - 3);
  if (id >= 29 && id <= 38) return static_cast<char>('0' + id - 29);
  if (id == kSpace) return ' ';
  if (id >= 40 && id < 40 + static_cast<int>(sizeof(kPunct) - 1)) {
    return kPunct[id - 40];
  }
  return '?';
}

struct Vocab {
  std::unordered_map<std::string, int32_t> token_to_id;
  std::vector<std::string> id_to_token;
  int32_t unk_id = 0;
};

}  // namespace

extern "C" {

// ---- ascii ---------------------------------------------------------------

// Encodes text into out_ids (cap max_len). Returns emitted length.
// append_eos: write kEos as the final id (truncating if needed).
int32_t LTAsciiToIds(const char* text, int32_t text_len, int32_t* out_ids,
                     int32_t max_len, int32_t append_eos) {
  int32_t n = 0;
  for (int32_t i = 0; i < text_len && n < max_len; ++i) {
    out_ids[n++] = AsciiCharToId(text[i]);
  }
  if (append_eos && max_len > 0) {
    if (n >= max_len) n = max_len - 1;
    out_ids[n++] = kEos;
  }
  return n;
}

// Decodes ids into out_text (cap max_len); stops at eos. Returns length.
int32_t LTIdsToAscii(const int32_t* ids, int32_t n, char* out_text,
                     int32_t max_len) {
  int32_t m = 0;
  for (int32_t i = 0; i < n && m < max_len; ++i) {
    if (ids[i] == kEos) break;
    if (ids[i] == kSos) continue;
    out_text[m++] = AsciiIdToChar(ids[i]);
  }
  return m;
}

// ---- vocab ---------------------------------------------------------------

// Loads a vocab file (one token per line). Returns handle or null.
void* LTVocabLoad(const char* path, const char* unk_token) {
  std::ifstream f(path);
  if (!f) return nullptr;
  auto* v = new Vocab();
  std::string line;
  while (std::getline(f, line)) {
    if (!line.empty() && line.back() == '\r') line.pop_back();
    v->token_to_id.emplace(line, static_cast<int32_t>(v->id_to_token.size()));
    v->id_to_token.push_back(line);
  }
  auto it = v->token_to_id.find(unk_token);
  v->unk_id = (it == v->token_to_id.end()) ? 0 : it->second;
  return v;
}

void LTVocabFree(void* vocab) { delete static_cast<Vocab*>(vocab); }

int32_t LTVocabSize(void* vocab) {
  return static_cast<int32_t>(static_cast<Vocab*>(vocab)->id_to_token.size());
}

// Whitespace-splits text, looks up each token. Returns emitted count.
int32_t LTVocabToIds(void* vocab, const char* text, int32_t text_len,
                     int32_t* out_ids, int32_t max_len) {
  auto* v = static_cast<Vocab*>(vocab);
  int32_t n = 0;
  int32_t i = 0;
  while (i < text_len && n < max_len) {
    while (i < text_len && std::isspace(static_cast<unsigned char>(text[i])))
      ++i;
    int32_t start = i;
    while (i < text_len && !std::isspace(static_cast<unsigned char>(text[i])))
      ++i;
    if (i > start) {
      std::string tok(text + start, i - start);
      auto it = v->token_to_id.find(tok);
      out_ids[n++] = (it == v->token_to_id.end()) ? v->unk_id : it->second;
    }
  }
  return n;
}

// Joins ids back to space-separated tokens. Returns written length.
int32_t LTVocabToText(void* vocab, const int32_t* ids, int32_t n,
                      char* out_text, int32_t max_len) {
  auto* v = static_cast<Vocab*>(vocab);
  int32_t m = 0;
  for (int32_t i = 0; i < n; ++i) {
    if (ids[i] < 0 || ids[i] >= static_cast<int32_t>(v->id_to_token.size()))
      continue;
    const std::string& tok = v->id_to_token[ids[i]];
    if (i > 0 && m < max_len) out_text[m++] = ' ';
    for (char c : tok) {
      if (m >= max_len) return m;
      out_text[m++] = c;
    }
  }
  return m;
}

}  // extern "C"

}  // namespace lingvo_tpu
