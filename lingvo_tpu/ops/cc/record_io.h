// Record iterators: pluggable file-format readers.
//
// TPU-native re-design of the reference's record plumbing
// (lingvo/core/ops/record_yielder.h:62 RecordIterator registry): no TF Env /
// kernel deps — plain POSIX IO, registered by file-type prefix
// ("tfrecord:/path", "text:/path", "iota:N" for synthetic tests).

#ifndef LINGVO_TPU_OPS_RECORD_IO_H_
#define LINGVO_TPU_OPS_RECORD_IO_H_

#include <functional>
#include <memory>
#include <string>
#include <vector>

namespace lingvo_tpu {

class RecordIterator {
 public:
  virtual ~RecordIterator() = default;
  // Returns false at end of file/stream.
  virtual bool Next(std::string* record) = 0;

  // Factory: "type:pattern" -> iterator for one concrete file.
  static std::unique_ptr<RecordIterator> Open(const std::string& type,
                                              const std::string& path);
  // Expands a (possibly comma-free) glob pattern to sorted file paths.
  static std::vector<std::string> Glob(const std::string& pattern);
  // Splits "type:pattern" (default type "text").
  static void ParseSpec(const std::string& spec, std::string* type,
                        std::string* pattern);
};

}  // namespace lingvo_tpu

#endif  // LINGVO_TPU_OPS_RECORD_IO_H_
