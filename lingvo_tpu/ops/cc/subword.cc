// Subword tokenizers: WPM (wordpiece) and BPE (merge-ops).
//
// Re-implements the semantics of the reference's subword tokenization
// (lingvo/core/wpm_encoder.py greedy wordpiece; BpeWordsToIds /
// BpeIdsToWords C++ kernels registered in x_ops.cc:613-860 which consume a
// merge-codes file + a subword-vocab file) as a from-scratch C++ library
// with a C ABI for ctypes.
//
// WPM: vocab file, one piece per line. Two marker conventions are
// auto-detected:
//   - sentencepiece style: word-initial pieces start with "\xe2\x96\x81" (▁)
//   - BERT style: continuation pieces start with "##"
// Encoding is greedy longest-match-first per whitespace word; a word with
// no decomposition maps to <unk>.
//
// BPE: codes file of "left right" merge operations in priority order
// (optionally with a leading "#version" line), vocab file of one subword
// per line (id = line number). Words end with the "</w>" marker before
// merging, matching the classic subword-nmt scheme the reference's BPE
// files use.

#include <cstdint>
#include <cstring>
#include <fstream>
#include <memory>
#include <string>
#include <unordered_map>
#include <vector>

namespace lingvo_tpu {
namespace {

const char kSpmMarker[] = "\xe2\x96\x81";  // ▁

struct SubwordVocab {
  std::unordered_map<std::string, int32_t> token_to_id;
  std::vector<std::string> id_to_token;
  int32_t unk_id = 0;
  bool spm_style = false;   // word-start marker ▁
  bool bert_style = false;  // continuation marker ##

  int32_t Lookup(const std::string& tok) const {
    auto it = token_to_id.find(tok);
    return it == token_to_id.end() ? -1 : it->second;
  }
};

SubwordVocab* LoadVocab(const char* path, const char* unk_token) {
  std::ifstream f(path);
  if (!f) return nullptr;
  auto v = std::make_unique<SubwordVocab>();
  std::string line;
  while (std::getline(f, line)) {
    if (!line.empty() && line.back() == '\r') line.pop_back();
    // vocab lines may be "token" or "token<TAB>count"
    auto tab = line.find('\t');
    if (tab != std::string::npos) line = line.substr(0, tab);
    if (line.rfind(kSpmMarker, 0) == 0) v->spm_style = true;
    if (line.rfind("##", 0) == 0) v->bert_style = true;
    v->token_to_id.emplace(line, static_cast<int32_t>(v->id_to_token.size()));
    v->id_to_token.push_back(line);
  }
  auto it = v->token_to_id.find(unk_token);
  v->unk_id = (it == v->token_to_id.end()) ? 0 : it->second;
  return v.release();
}

// Splits text on whitespace.
std::vector<std::string> SplitWords(const char* text, int32_t len) {
  std::vector<std::string> words;
  int32_t i = 0;
  while (i < len) {
    while (i < len && (text[i] == ' ' || text[i] == '\t' || text[i] == '\n' ||
                       text[i] == '\r'))
      ++i;
    int32_t start = i;
    while (i < len && !(text[i] == ' ' || text[i] == '\t' ||
                        text[i] == '\n' || text[i] == '\r'))
      ++i;
    if (i > start) words.emplace_back(text + start, i - start);
  }
  return words;
}

// Greedy longest-match wordpiece of one word. Returns false -> <unk>.
bool WpmSegmentWord(const SubwordVocab& v, const std::string& word,
                    std::vector<int32_t>* out) {
  std::string w = v.spm_style ? (kSpmMarker + word) : word;
  size_t start = 0;
  std::vector<int32_t> pieces;
  while (start < w.size()) {
    size_t end = w.size();
    int32_t found = -1;
    while (end > start) {
      std::string piece = w.substr(start, end - start);
      if (v.bert_style && start > 0) piece = "##" + piece;
      found = v.Lookup(piece);
      if (found >= 0) break;
      --end;
    }
    if (found < 0) return false;
    pieces.push_back(found);
    start = end;
  }
  out->insert(out->end(), pieces.begin(), pieces.end());
  return true;
}

struct Bpe {
  SubwordVocab* vocab = nullptr;
  // merge rank of "left right" pair (lower = applied first)
  std::unordered_map<std::string, int32_t> merge_rank;
  ~Bpe() { delete vocab; }

  int32_t Rank(const std::string& a, const std::string& b) const {
    auto it = merge_rank.find(a + " " + b);
    return it == merge_rank.end() ? INT32_MAX : it->second;
  }
};

// Splits a UTF-8 string into code points (as byte strings).
std::vector<std::string> Utf8Chars(const std::string& s) {
  std::vector<std::string> out;
  size_t i = 0;
  while (i < s.size()) {
    unsigned char c = s[i];
    size_t n = (c < 0x80) ? 1 : (c < 0xE0) ? 2 : (c < 0xF0) ? 3 : 4;
    if (i + n > s.size()) n = 1;  // malformed: take the byte
    out.push_back(s.substr(i, n));
    i += n;
  }
  return out;
}

// Classic BPE: chars + "</w>" on the last char, merge best-ranked pair
// until no merge applies.
std::vector<std::string> BpeSegmentWord(const Bpe& bpe,
                                        const std::string& word) {
  std::vector<std::string> parts = Utf8Chars(word);
  if (parts.empty()) return parts;
  parts.back() += "</w>";
  while (parts.size() > 1) {
    int best = -1;
    int32_t best_rank = INT32_MAX;
    for (size_t i = 0; i + 1 < parts.size(); ++i) {
      int32_t r = bpe.Rank(parts[i], parts[i + 1]);
      if (r < best_rank) {
        best_rank = r;
        best = static_cast<int>(i);
      }
    }
    if (best < 0) break;
    parts[best] += parts[best + 1];
    parts.erase(parts.begin() + best + 1);
  }
  return parts;
}

}  // namespace

extern "C" {

// ---- WPM ------------------------------------------------------------------

void* LTWpmLoad(const char* vocab_path, const char* unk_token) {
  return LoadVocab(vocab_path, unk_token);
}

void LTWpmFree(void* h) { delete static_cast<SubwordVocab*>(h); }

int32_t LTWpmSize(void* h) {
  return static_cast<int32_t>(
      static_cast<SubwordVocab*>(h)->id_to_token.size());
}

// Encodes text; returns number of ids emitted (<= max_len).
int32_t LTWpmEncode(void* h, const char* text, int32_t text_len,
                    int32_t* out_ids, int32_t max_len) {
  auto* v = static_cast<SubwordVocab*>(h);
  std::vector<int32_t> ids;
  for (const auto& word : SplitWords(text, text_len)) {
    if (!WpmSegmentWord(*v, word, &ids)) ids.push_back(v->unk_id);
  }
  int32_t n = static_cast<int32_t>(ids.size());
  if (n > max_len) n = max_len;
  std::memcpy(out_ids, ids.data(), n * sizeof(int32_t));
  return n;
}

// Decodes ids to text; reverses the marker convention. Returns length.
int32_t LTWpmDecode(void* h, const int32_t* ids, int32_t n, char* out_text,
                    int32_t max_len) {
  auto* v = static_cast<SubwordVocab*>(h);
  std::string out;
  for (int32_t i = 0; i < n; ++i) {
    if (ids[i] < 0 || ids[i] >= static_cast<int32_t>(v->id_to_token.size()))
      continue;
    std::string tok = v->id_to_token[ids[i]];
    if (v->spm_style) {
      if (tok.rfind(kSpmMarker, 0) == 0) {
        if (!out.empty()) out += ' ';
        tok = tok.substr(sizeof(kSpmMarker) - 1);
      }
      out += tok;
    } else if (v->bert_style) {
      if (tok.rfind("##", 0) == 0) {
        out += tok.substr(2);
      } else {
        if (!out.empty()) out += ' ';
        out += tok;
      }
    } else {
      if (!out.empty()) out += ' ';
      out += tok;
    }
  }
  int32_t m = static_cast<int32_t>(out.size());
  if (m > max_len) m = max_len;
  std::memcpy(out_text, out.data(), m);
  return m;
}

// ---- BPE ------------------------------------------------------------------

void* LTBpeLoad(const char* codes_path, const char* vocab_path,
                const char* unk_token) {
  std::ifstream codes(codes_path);
  if (!codes) return nullptr;
  auto bpe = std::make_unique<Bpe>();
  bpe->vocab = LoadVocab(vocab_path, unk_token);
  if (!bpe->vocab) return nullptr;
  std::string line;
  int32_t rank = 0;
  while (std::getline(codes, line)) {
    if (!line.empty() && line.back() == '\r') line.pop_back();
    if (line.empty() || line[0] == '#') continue;  // "#version" header
    bpe->merge_rank.emplace(line, rank++);
  }
  return bpe.release();
}

void LTBpeFree(void* h) { delete static_cast<Bpe*>(h); }

int32_t LTBpeSize(void* h) {
  return static_cast<int32_t>(
      static_cast<Bpe*>(h)->vocab->id_to_token.size());
}

int32_t LTBpeEncode(void* h, const char* text, int32_t text_len,
                    int32_t* out_ids, int32_t max_len) {
  auto* bpe = static_cast<Bpe*>(h);
  std::vector<int32_t> ids;
  for (const auto& word : SplitWords(text, text_len)) {
    for (const auto& piece : BpeSegmentWord(*bpe, word)) {
      int32_t id = bpe->vocab->Lookup(piece);
      ids.push_back(id < 0 ? bpe->vocab->unk_id : id);
    }
  }
  int32_t n = static_cast<int32_t>(ids.size());
  if (n > max_len) n = max_len;
  std::memcpy(out_ids, ids.data(), n * sizeof(int32_t));
  return n;
}

int32_t LTBpeDecode(void* h, const int32_t* ids, int32_t n, char* out_text,
                    int32_t max_len) {
  auto* bpe = static_cast<Bpe*>(h);
  std::string out;
  for (int32_t i = 0; i < n; ++i) {
    const auto& toks = bpe->vocab->id_to_token;
    if (ids[i] < 0 || ids[i] >= static_cast<int32_t>(toks.size())) continue;
    std::string tok = toks[ids[i]];
    auto endw = tok.find("</w>");
    if (endw != std::string::npos) {
      out += tok.substr(0, endw);
      out += ' ';
    } else {
      out += tok;
    }
  }
  while (!out.empty() && out.back() == ' ') out.pop_back();
  int32_t m = static_cast<int32_t>(out.size());
  if (m > max_len) m = max_len;
  std::memcpy(out_text, out.data(), m);
  return m;
}

}  // extern "C"

}  // namespace lingvo_tpu
