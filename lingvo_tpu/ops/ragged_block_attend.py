"""One ragged kernel for decode, chunked prefill, and spec-verify.

`ops/block_decode.py` gave each SEQUENCE one query per step; prefill and
the spec-verify window needed their own multi-query lowerings, so the
serving engine compiled three step programs and padded prefill rows to a
static chunk. This op is the unification the Ragged Paged Attention
formulation actually calls for: the batch axis is a PACKED TOKEN axis.
Each of the T query tokens carries

- `row_of[t]`  — which batch row (block table) it belongs to, and
- `q_end[t]`   — one past the global KV slot it may attend, i.e. its own
  causal horizon `q_pos + 1` within its sequence.

A `q_len=1` decode row contributes one token, a prefill chunk contributes
`q_len` tokens with ascending `q_end` (causal within the chunk for free —
each token simply sees a shorter prefix), and a spec-verify window is
`k+1` tokens the same way. One op, one compiled program; rows of wildly
different query lengths pack densely instead of padding to the widest.

Layout contract (the serving engine maintains it, same as block_decode):
- a row's logical slot s lives at pool page `block_tables[row, s // P]`,
  offset `s % P`; the K/V for every query token were written BEFORE the
  call (scatter-before-read), so token t's newest visible slot is its own.
- table entries past a row's live pages are unspecified — freed pages may
  already belong to another sequence and must never influence the output.
- `q_end[t] = 0` marks a PADDING token: output 0, no pages read.
- q arrives PRE-SCALED, exactly like BlockDecode/FlashDecode.

Two lowerings, asserted bit-identical (the established twin pattern):

- `_PallasRaggedAttend` — grid `(T, t_pages)`; `row_of`, the block tables,
  and `q_end` ride scalar prefetch, so the page index map resolves
  `block_tables[row_of[t], j]` before the DMA is issued. Dead pages clamp
  to the token's last live page (DMA elided, `pl.when` skips compute) —
  and because consecutive tokens of one row walk the same table, the
  revisited blocks hit the same elision.
- `_XlaRaggedAttend` — `fori_loop` with a dynamic trip count of
  `ceil(max(q_end) / P)` over per-token gathered pages. Tokens whose
  horizon falls short of the batch max process extra pages fully masked —
  bitwise a no-op through `_PageAttend` (alpha == 1, p == 0), which keeps
  the twins exactly equal despite different iteration spaces.

Both route every page through the SAME `_PageAttend` (and int8 pools
through the same `_DequantPages`), so the float-op sequence is identical
and interpret-mode equality holds bitwise — including against
`BlockDecode` itself: a T-token all-decode pack reproduces BlockDecode's
output bit for bit, which is what lets the engine collapse to one program
without moving a single token (asserted in tests).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lingvo_tpu.ops.flash_attention import (  # single source of truth
    LANES, NEG_INF, _CompilerParams)
from lingvo_tpu.ops.flash_decode import _Finish, _PageAttend
from lingvo_tpu.ops.block_decode import _DequantPages
from lingvo_tpu.ops.block_decode import SupportedOnTpu  # noqa: F401  (same
# Mosaic tiling gate: page_size and h on the 128-lane minor axes; re-exported
# so callers gate the ragged kernel through one name per op module.)


# -- XLA twin (the CPU serving path) -----------------------------------------


def _AncestorOk(slot, c, lo, hi):
  """In-step ancestor visibility for key slots `slot` (already [?, P]).

  c = slot - q_start (position within the row's packed step window); bit c
  of the token's (lo | hi << 32) mask says whether step column c is an
  ancestor-or-self. Slots below the window (c < 0, the committed prefix)
  clip to bit 0, which every tree mask sets (the root is an ancestor of
  all); chain rows ship lo = hi = -1 so every bit reads 1 and the combined
  mask stays bitwise the pre-tree causal mask. Slots at c >= 64 only occur
  on chain rows (tree rows are capped at 64 columns), where -1 again
  yields 1."""
  cc = jnp.clip(c, 0, 63)
  word = jnp.where(cc < 32, lo, hi)
  sh = jnp.where(cc < 32, cc, cc - 32)
  return jnp.bitwise_and(jax.lax.shift_right_logical(word, sh), 1) == 1


def _XlaRaggedAttend(q, k_pool, v_pool, block_tables, row_of, q_end,
                     page_size: int, k_scale=None, v_scale=None,
                     q_start=None, anc_lo=None, anc_hi=None):
  """q: [T, N, H]; pools [NP, P, N, H]; tables [B, t_pages] int32;
  row_of/q_end [T] int32. -> [T, N, H].

  Dynamic trip count over the batch-max live page: per step the work is
  O(T * max(q_end)), not O(T * t_pages * P). k_scale/v_scale [NP, N, P]
  switch on the int8 path via the shared `_DequantPages`. q_start/anc_lo/
  anc_hi [T] int32 add per-token in-step ancestor masking for tree rows
  (None = chain semantics, bitwise the unmasked kernel)."""
  t, n, h = q.shape
  np_total, page, _, _ = k_pool.shape
  assert page == page_size, (page, page_size)
  t_pages = block_tables.shape[1]
  ends = q_end.astype(jnp.int32)
  if q_start is None:
    q_start = jnp.zeros((t,), jnp.int32)
    anc_lo = anc_hi = jnp.full((t,), -1, jnp.int32)
  starts = q_start.astype(jnp.int32)
  lo = anc_lo.astype(jnp.int32)
  hi = anc_hi.astype(jnp.int32)
  trip = jnp.clip((jnp.max(ends) + page_size - 1) // page_size, 0, t_pages)
  tables = jnp.clip(block_tables.astype(jnp.int32), 0, np_total - 1)
  rows = jnp.clip(row_of.astype(jnp.int32), 0, tables.shape[0] - 1)
  tok_tables = tables[rows]                                # [T, t_pages]

  batched_attend = jax.vmap(_PageAttend)

  def _Body(j, carry):
    m, l, acc = carry
    pid = jax.lax.dynamic_index_in_dim(tok_tables, j, axis=1, keepdims=False)
    k_page = k_pool[pid]                                   # [T, P, N, H]
    v_page = v_pool[pid]
    if k_scale is not None:
      k_page = _DequantPages(k_page, k_scale[pid])
      v_page = _DequantPages(v_page, v_scale[pid])
    slot = j * page_size + jnp.arange(page_size, dtype=jnp.int32)  # [P]
    causal = slot[None, :] < ends[:, None]                 # [T, P]
    ok = _AncestorOk(slot[None, :], slot[None, :] - starts[:, None],
                     lo[:, None], hi[:, None])
    keep = (causal & ok).astype(jnp.float32)[:, None, :]
    return batched_attend(q, k_page, v_page, keep, m, l, acc)

  m0 = jnp.full((t, n, 1), NEG_INF, jnp.float32)
  l0 = jnp.zeros((t, n, 1), jnp.float32)
  acc0 = jnp.zeros((t, n, h), jnp.float32)
  _, l, acc = jax.lax.fori_loop(0, trip, _Body, (m0, l0, acc0))
  return _Finish(l, acc, q.dtype)


# -- Pallas TPU kernel -------------------------------------------------------


def _RaggedAttendKernel(row_of_ref, tables_ref, ends_ref, starts_ref,
                        lo_ref, hi_ref, q_ref, k_ref,
                        v_ref, *rest, page_size: int, t_pages: int):
  """One (token, logical page) program step; scratch carried over pages.

  Same body as `_BlockDecodeKernel` with the batch id replaced by the
  packed-token id: the per-program length is the TOKEN's causal horizon
  `q_end[t]`, not a per-sequence length. Float and int8 calls share the
  body (int8 threads two extra scale blocks, dequantized via the shared
  `_DequantPages`) so the control flow cannot drift."""
  if len(rest) == 6:
    ks_ref, vs_ref, out_ref, m_scr, l_scr, acc_scr = rest
  else:
    ks_ref = vs_ref = None
    out_ref, m_scr, l_scr, acc_scr = rest
  ti = pl.program_id(0)
  j = pl.program_id(1)
  ln = ends_ref[ti]

  @pl.when(j == 0)
  def _Init():
    m_scr[:] = jnp.full_like(m_scr, NEG_INF)
    l_scr[:] = jnp.zeros_like(l_scr)
    acc_scr[:] = jnp.zeros_like(acc_scr)

  @pl.when(j * page_size < ln)
  def _Accumulate():
    slot = j * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, page_size), 1)                       # [1, P]
    ok = _AncestorOk(slot, slot - starts_ref[ti],
                     lo_ref[ti], hi_ref[ti])                # [1, P]
    keep = ((slot < ln) & ok).astype(jnp.float32)           # [1, P]
    k_page, v_page = k_ref[0], v_ref[0]
    if ks_ref is not None:
      k_page = _DequantPages(k_page, ks_ref[0])
      v_page = _DequantPages(v_page, vs_ref[0])
    m, l, acc = _PageAttend(q_ref[0], k_page, v_page, keep, m_scr[:, :1],
                            l_scr[:, :1], acc_scr[:])
    m_scr[:] = jnp.broadcast_to(m, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l, l_scr.shape)
    acc_scr[:] = acc

  @pl.when(j == t_pages - 1)
  def _Emit():
    out_ref[0] = _Finish(l_scr[:, :1], acc_scr[:], out_ref.dtype)


def _PallasRaggedAttend(q, k_pool, v_pool, block_tables, row_of, q_end,
                        page_size: int, interpret: bool = False,
                        k_scale=None, v_scale=None,
                        q_start=None, anc_lo=None, anc_hi=None):
  """Pallas lowering of _XlaRaggedAttend. q: [T, N, H] -> [T, N, H]."""
  t, n, h = q.shape
  np_total, page, _, _ = k_pool.shape
  assert page == page_size, (page, page_size)
  t_pages = block_tables.shape[1]
  tables = jnp.clip(block_tables.astype(jnp.int32), 0, np_total - 1)
  rows = jnp.clip(row_of.astype(jnp.int32), 0, tables.shape[0] - 1)
  ends = q_end.astype(jnp.int32)
  if q_start is None:
    q_start = jnp.zeros((t,), jnp.int32)
    anc_lo = anc_hi = jnp.full((t,), -1, jnp.int32)
  starts = q_start.astype(jnp.int32)
  lo = anc_lo.astype(jnp.int32)
  hi = anc_hi.astype(jnp.int32)

  # Dead logical pages clamp to the TOKEN's last live page: Pallas
  # re-requests the same physical block and elides the HBM DMA, pl.when
  # skips compute. A stale table entry past a token's horizon never
  # reaches VMEM — the page-reuse-after-eviction guarantee.
  def _PageIdx(ti, j, row_ref, tables_ref, ends_ref, s_ref, lo_ref, hi_ref):
    last = jnp.maximum(
        (ends_ref[ti] + page_size - 1) // page_size - 1, 0)
    last = jnp.minimum(last, t_pages - 1)
    return (tables_ref[row_ref[ti], jnp.minimum(j, last)], 0, 0, 0)

  def _ScaleIdx(ti, j, row_ref, tables_ref, ends_ref, s_ref, lo_ref, hi_ref):
    return _PageIdx(ti, j, row_ref, tables_ref, ends_ref,
                    s_ref, lo_ref, hi_ref)[:3]

  def _TokIdx(ti, j, r_ref, t_ref, e_ref, s_ref, lo_ref, hi_ref):
    return (ti, 0, 0)

  in_specs = [
      pl.BlockSpec((1, n, h), _TokIdx),
      pl.BlockSpec((1, page_size, n, h), _PageIdx),
      pl.BlockSpec((1, page_size, n, h), _PageIdx),
  ]
  operands = [rows, tables, ends, starts, lo, hi, q, k_pool, v_pool]
  if k_scale is not None:
    in_specs += [
        pl.BlockSpec((1, n, page_size), _ScaleIdx),
        pl.BlockSpec((1, n, page_size), _ScaleIdx),
    ]
    operands += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]

  grid_spec = pltpu.PrefetchScalarGridSpec(
      num_scalar_prefetch=6,
      grid=(t, t_pages),
      in_specs=in_specs,
      out_specs=pl.BlockSpec((1, n, h), _TokIdx),
      scratch_shapes=[
          pltpu.VMEM((n, LANES), jnp.float32),
          pltpu.VMEM((n, LANES), jnp.float32),
          pltpu.VMEM((n, h), jnp.float32),
      ],
  )
  kernel = functools.partial(_RaggedAttendKernel, page_size=page_size,
                             t_pages=t_pages)
  return pl.pallas_call(
      kernel,
      grid_spec=grid_spec,
      out_shape=jax.ShapeDtypeStruct((t, n, h), q.dtype),
      compiler_params=_CompilerParams(
          dimension_semantics=("parallel", "arbitrary")),
      interpret=interpret,
  )(*operands)


# -- public entry ------------------------------------------------------------


def RaggedAttend(q, k_pool, v_pool, block_tables, row_of, q_end, *,
                 page_size: int, k_scale=None, v_scale=None,
                 q_start=None, anc_lo=None, anc_hi=None,
                 lowering: str = "auto", interpret: bool | None = None):
  """Packed-token ragged paged attention — decode, prefill, and verify
  rows in one call.

  q: [T, N, H] packed query tokens, ALREADY scaled; every token's K/V was
  written to the pool before the call.
  k_pool/v_pool: [num_pages, page_size, N, H] global page pool.
  block_tables: [B, pages_per_seq] int32 physical page ids; entries past a
  row's live pages are arbitrary and never influence the output.
  row_of: [T] int32 — batch row (block-table index) of each token.
  q_end: [T] int32 — one past each token's highest attendable global slot
  (its `q_pos + 1`); 0 marks a padding token, whose output is 0.
  k_scale/v_scale: [num_pages, N, page_size] f32 sidecars for int8 pools
  (both or neither); pages dequantize in-kernel via `_DequantPages`.
  q_start/anc_lo/anc_hi: [T] int32 tree-speculation operands — q_start is
  the token's row step-window start (its row_q_pos) and anc_lo/anc_hi the
  64-bit ancestor-column bitmask; all three or none. None keeps chain
  semantics bitwise (every in-step predecessor visible).
  lowering: 'auto' (Pallas on real TPU, XLA twin elsewhere) | 'pallas' |
  'xla'. Returns [T, N, H].
  """
  assert q.ndim == 3, q.shape
  assert lowering in ("auto", "pallas", "xla"), lowering
  assert (k_scale is None) == (v_scale is None), "pass both scales or neither"
  tree_args = (q_start is not None, anc_lo is not None, anc_hi is not None)
  assert all(tree_args) or not any(tree_args), \
      "pass q_start+anc_lo+anc_hi together or none"
  if k_scale is not None:
    assert k_pool.dtype == jnp.int8, k_pool.dtype
  if q_start is not None:
    q_start = jnp.asarray(q_start)
    anc_lo = jnp.asarray(anc_lo)
    anc_hi = jnp.asarray(anc_hi)
  on_tpu = jax.default_backend() == "tpu"
  if lowering == "auto":
    lowering = "pallas" if on_tpu else "xla"
  if lowering == "xla":
    return _XlaRaggedAttend(q, k_pool, v_pool, block_tables,
                            jnp.asarray(row_of), jnp.asarray(q_end),
                            page_size, k_scale=k_scale, v_scale=v_scale,
                            q_start=q_start, anc_lo=anc_lo, anc_hi=anc_hi)
  if interpret is None:
    interpret = not on_tpu
  return _PallasRaggedAttend(q, k_pool, v_pool, block_tables,
                             jnp.asarray(row_of), jnp.asarray(q_end),
                             page_size, interpret=interpret,
                             k_scale=k_scale, v_scale=v_scale,
                             q_start=q_start, anc_lo=anc_lo, anc_hi=anc_hi)
