"""Block-table ragged paged attention over a global KV page pool.

The continuous-batching evolution of `ops/flash_decode.py`: instead of one
contiguous `[B, max_len]` cache slab per batch (every sequence pays max_len
HBM whether it uses it or not), K/V live in a GLOBAL pool of fixed-size
pages `[num_pages, page_size, N, H]` and each sequence owns an arbitrary
set of pages named by a per-sequence *block table* `[B, pages_per_seq]` of
physical page ids — the "Ragged Paged Attention" formulation. Sequences of
wildly different lengths share one pool, pages are recycled the moment a
sequence finishes, and admission/eviction never reshapes device buffers.

Layout contract (the serving engine maintains it):
- sequence i's tokens occupy logical slots [0, seq_len_i), contiguously;
  logical slot s lives at pool page `block_tables[i, s // page_size]`,
  offset `s % page_size`. No left-padding — unlike the gshard_decode dense
  layout there are no cache_paddings; dead slots are simply `>= seq_len`.
- block-table entries past a sequence's live pages are unspecified (the
  kernels clamp/mask; freed pages may already belong to another sequence,
  so they must never influence the output).
- q arrives PRE-SCALED, exactly like FlashDecode.

Two lowerings of the single-query decode op, asserted bit-identical:

- `_PallasBlockDecode` — grid `(B, pages_per_seq)`; the block table and the
  per-sequence lengths ride scalar prefetch, so the page index map resolves
  `block_tables[b, j]` before the DMA is issued (dead pages clamp to the
  last live page: Pallas re-requests the same block and elides the copy,
  `pl.when` skips their compute).
- `_XlaBlockDecode` — `fori_loop` with a dynamic trip count of
  `ceil(max(seq_lens) / page_size)` over per-row gathered pages. Rows whose
  lengths fall short of the batch max process extra pages fully masked —
  bitwise a no-op through `_PageAttend` (alpha == 1, p == 0), which is what
  keeps the twins exactly equal despite different iteration spaces.

`BlockPrefill` is the multi-query sibling (C prompt-chunk queries per row,
causal within the chunk) used for chunked prefill interleaved with decode;
it is an XLA-only lowering — the single-query kernel is the steady-state
hot op, prefill happens once per admitted request.

Quantized pools: when the pool stores int8 (see `lingvo_tpu/quant/kv.py`),
callers pass the f32 scale sidecars `k_scale`/`v_scale` of shape
[num_pages, N, page_size] — transposed so the Pallas scale block's minor
dimension is page_size (a multiple of 128 lanes whenever `SupportedOnTpu`
admits the kernel at all). Both lowerings dequantize through the SAME
`_DequantPages` helper right before `_PageAttend`, which is what keeps the
int8 twins bitwise-identical just like the float pair. In the Pallas
lowering the scales ride VMEM blocks whose index map resolves through the
scalar-prefetched block table — dead logical pages clamp to the row's last
live page, so scale DMAs are elided exactly like the K/V page DMAs. (The
full per-slot sidecar is too large for SMEM at serving sizes, so the
scales are NOT themselves scalar-prefetch operands — only the table and
lengths are.)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lingvo_tpu.ops.flash_attention import (  # single source of truth
    LANES, NEG_INF, _CompilerParams)
from lingvo_tpu.ops.flash_decode import _DotF32, _Finish, _PageAttend


def GatherPages(pool, block_tables):
  """pool [NP, P, N, H] + tables [B, T] -> dense [B, T*P, N, H].

  The dense-cache view of a block-table layout: row i's logical slots in
  order. Reference path for tests and the ineligible-config fallback in
  `MultiHeadedAttention.PagedStep` (out-of-range table entries clamp, the
  caller masks dead slots)."""
  b, t_pages = block_tables.shape
  np_total, page, n, h = pool.shape
  pages = pool[jnp.clip(block_tables, 0, np_total - 1)]  # [B, T, P, N, H]
  return pages.reshape(b, t_pages * page, n, h)


def GatherScales(scales, block_tables):
  """sidecar [NP, N, P] + tables [B, T] -> dense [B, T*P, N].

  The `GatherPages` sibling for scale sidecars: per-slot-per-head scales in
  logical-slot order, aligned with the [B, T*P, N, H] gathered pages, for
  the dense-fallback dequantization in `MultiHeadedAttention.PagedStep`."""
  b, t_pages = block_tables.shape
  np_total, n, page = scales.shape
  s = scales[jnp.clip(block_tables, 0, np_total - 1)]     # [B, T, N, P]
  return jnp.swapaxes(s, 2, 3).reshape(b, t_pages * page, n)


def _DequantPages(pages, scales):
  """pages [..., P, N, H] int8 + scales [..., N, P] f32 -> f32 pages.

  THE shared dequantize-on-read: both the Pallas kernel and the XLA twin
  (and `BlockPrefill`) funnel quantized pages through this exact sequence
  of float ops before `_PageAttend`, so the int8 lowerings stay
  bitwise-identical for the same reason the float ones do."""
  s = jnp.swapaxes(scales.astype(jnp.float32), -1, -2)[..., None]
  return pages.astype(jnp.float32) * s


# -- XLA twin (the CPU serving path) -----------------------------------------


def _XlaBlockDecode(q, k_pool, v_pool, block_tables, seq_lens,
                    page_size: int, k_scale=None, v_scale=None):
  """q: [B, N, H]; pools [NP, P, N, H]; tables [B, T] int32; seq_lens [B]
  int32 (live slots per row; the query attends slots < seq_len). -> [B, N, H].

  Dynamic trip count over the batch-max live page — per decode step the
  work is O(max live length over the batch), not O(T * page_size).
  k_scale/v_scale [NP, N, P] switch on the int8 path: pages dequantize
  through `_DequantPages` right before `_PageAttend` (scales None leaves
  the float path untouched, op for op)."""
  b = q.shape[0]
  np_total, page, n, h = k_pool.shape
  assert page == page_size, (page, page_size)
  t_pages = block_tables.shape[1]
  lens = seq_lens.astype(jnp.int32)
  # lens may legally reach (or, out of contract, exceed) the table capacity;
  # clamp the trip like the Pallas grid never exceeds t_pages.
  trip = jnp.clip((jnp.max(lens) + page_size - 1) // page_size, 0, t_pages)
  tables = jnp.clip(block_tables.astype(jnp.int32), 0, np_total - 1)

  batched_attend = jax.vmap(_PageAttend)

  def _Body(j, carry):
    m, l, acc = carry
    pid = jax.lax.dynamic_index_in_dim(tables, j, axis=1, keepdims=False)
    k_page = k_pool[pid]                                   # [B, P, N, H]
    v_page = v_pool[pid]
    if k_scale is not None:
      k_page = _DequantPages(k_page, k_scale[pid])
      v_page = _DequantPages(v_page, v_scale[pid])
    slot = j * page_size + jnp.arange(page_size, dtype=jnp.int32)  # [P]
    keep = (slot[None, :] < lens[:, None]).astype(jnp.float32)[:, None, :]
    return batched_attend(q, k_page, v_page, keep, m, l, acc)

  m0 = jnp.full((b, n, 1), NEG_INF, jnp.float32)
  l0 = jnp.zeros((b, n, 1), jnp.float32)
  acc0 = jnp.zeros((b, n, h), jnp.float32)
  _, l, acc = jax.lax.fori_loop(0, trip, _Body, (m0, l0, acc0))
  return _Finish(l, acc, q.dtype)


# -- Pallas TPU kernel -------------------------------------------------------


def _BlockDecodeKernel(tables_ref, lens_ref, q_ref, k_ref, v_ref, *rest,
                       page_size: int, t_pages: int):
  """One (batch, logical page) program step; scratch carried over pages.

  One body serves both storage modes so the control flow cannot drift:
  the float call passes (out_ref, scratch...), the int8 call additionally
  threads the scale blocks (ks_ref, vs_ref, out_ref, scratch...) and
  dequantizes via the shared `_DequantPages` before `_PageAttend`."""
  if len(rest) == 6:
    ks_ref, vs_ref, out_ref, m_scr, l_scr, acc_scr = rest
  else:
    ks_ref = vs_ref = None
    out_ref, m_scr, l_scr, acc_scr = rest
  bi = pl.program_id(0)
  j = pl.program_id(1)
  ln = lens_ref[bi]

  @pl.when(j == 0)
  def _Init():
    m_scr[:] = jnp.full_like(m_scr, NEG_INF)
    l_scr[:] = jnp.zeros_like(l_scr)
    acc_scr[:] = jnp.zeros_like(acc_scr)

  @pl.when(j * page_size < ln)
  def _Accumulate():
    slot = j * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, page_size), 1)                       # [1, P]
    keep = (slot < ln).astype(jnp.float32)                  # [1, P]
    k_page, v_page = k_ref[0], v_ref[0]
    if ks_ref is not None:
      k_page = _DequantPages(k_page, ks_ref[0])
      v_page = _DequantPages(v_page, vs_ref[0])
    m, l, acc = _PageAttend(q_ref[0], k_page, v_page, keep, m_scr[:, :1],
                            l_scr[:, :1], acc_scr[:])
    m_scr[:] = jnp.broadcast_to(m, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l, l_scr.shape)
    acc_scr[:] = acc

  @pl.when(j == t_pages - 1)
  def _Emit():
    out_ref[0] = _Finish(l_scr[:, :1], acc_scr[:], out_ref.dtype)


def _PallasBlockDecode(q, k_pool, v_pool, block_tables, seq_lens,
                       page_size: int, interpret: bool = False,
                       k_scale=None, v_scale=None):
  """Pallas lowering of _XlaBlockDecode. q: [B, N, H] -> [B, N, H]."""
  b, n, h = q.shape
  np_total, page, _, _ = k_pool.shape
  assert page == page_size, (page, page_size)
  t_pages = block_tables.shape[1]
  tables = jnp.clip(block_tables.astype(jnp.int32), 0, np_total - 1)
  lens = seq_lens.astype(jnp.int32)

  # Dead logical pages clamp to the row's last live page: Pallas re-requests
  # the same physical block and elides the HBM DMA, pl.when skips compute.
  # A stale table entry past the live range therefore never reaches VMEM.
  def _PageIdx(bi, j, tables_ref, lens_ref):
    last = jnp.maximum(
        (lens_ref[bi] + page_size - 1) // page_size - 1, 0)
    last = jnp.minimum(last, t_pages - 1)
    return (tables_ref[bi, jnp.minimum(j, last)], 0, 0, 0)

  # Scale sidecar blocks resolve their page through the same prefetched
  # table lookup, so their DMAs are elided for dead pages exactly like k/v.
  def _ScaleIdx(bi, j, tables_ref, lens_ref):
    return _PageIdx(bi, j, tables_ref, lens_ref)[:3]

  in_specs = [
      pl.BlockSpec((1, n, h), lambda bi, j, t_ref, l_ref: (bi, 0, 0)),
      pl.BlockSpec((1, page_size, n, h), _PageIdx),
      pl.BlockSpec((1, page_size, n, h), _PageIdx),
  ]
  operands = [tables, lens, q, k_pool, v_pool]
  if k_scale is not None:
    in_specs += [
        pl.BlockSpec((1, n, page_size), _ScaleIdx),
        pl.BlockSpec((1, n, page_size), _ScaleIdx),
    ]
    operands += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]

  grid_spec = pltpu.PrefetchScalarGridSpec(
      num_scalar_prefetch=2,
      grid=(b, t_pages),
      in_specs=in_specs,
      out_specs=pl.BlockSpec((1, n, h),
                             lambda bi, j, t_ref, l_ref: (bi, 0, 0)),
      scratch_shapes=[
          pltpu.VMEM((n, LANES), jnp.float32),
          pltpu.VMEM((n, LANES), jnp.float32),
          pltpu.VMEM((n, h), jnp.float32),
      ],
  )
  kernel = functools.partial(_BlockDecodeKernel, page_size=page_size,
                             t_pages=t_pages)
  return pl.pallas_call(
      kernel,
      grid_spec=grid_spec,
      out_shape=jax.ShapeDtypeStruct((b, n, h), q.dtype),
      compiler_params=_CompilerParams(
          dimension_semantics=("parallel", "arbitrary")),
      interpret=interpret,
  )(*operands)


# -- public entries ----------------------------------------------------------


def BlockDecode(q, k_pool, v_pool, block_tables, seq_lens, *, page_size: int,
                k_scale=None, v_scale=None, lowering: str = "auto",
                interpret: bool | None = None):
  """Single-query block-table paged decode attention.

  q: [B, 1, N, H] — the newest query per sequence, ALREADY scaled (the
  caller wrote its K/V to the pool before calling; slot seq_len-1).
  k_pool/v_pool: [num_pages, page_size, N, H] global page pool.
  block_tables: [B, pages_per_seq] int32 physical page ids; entries past a
  row's live pages are arbitrary and never influence the output.
  seq_lens: [B] int32 live-slot counts (the query attends slots
  [0, seq_len)); 0 marks an inactive row, whose output is 0.
  k_scale/v_scale: [num_pages, N, page_size] f32 sidecars for int8 pools
  (both or neither); pages dequantize in-kernel via `_DequantPages`.
  lowering: 'auto' (Pallas on real TPU, XLA twin elsewhere) | 'pallas' |
  'xla'. Returns [B, 1, N, H].
  """
  assert q.ndim == 4 and q.shape[1] == 1, q.shape
  assert lowering in ("auto", "pallas", "xla"), lowering
  assert (k_scale is None) == (v_scale is None), "pass both scales or neither"
  if k_scale is not None:
    assert k_pool.dtype == jnp.int8, k_pool.dtype
  q3 = q[:, 0]
  on_tpu = jax.default_backend() == "tpu"
  if lowering == "auto":
    lowering = "pallas" if on_tpu else "xla"
  if lowering == "xla":
    out = _XlaBlockDecode(q3, k_pool, v_pool, block_tables,
                          jnp.asarray(seq_lens), page_size,
                          k_scale=k_scale, v_scale=v_scale)
  else:
    if interpret is None:
      interpret = not on_tpu
    out = _PallasBlockDecode(q3, k_pool, v_pool, block_tables,
                             jnp.asarray(seq_lens), page_size,
                             interpret=interpret,
                             k_scale=k_scale, v_scale=v_scale)
  return out[:, None]


def BlockPrefill(q, k_pool, v_pool, block_tables, q_pos, in_len, *,
                 page_size: int, k_scale=None, v_scale=None):
  """Ragged multi-query paged attention for chunked prefill steps.

  q: [B, C, N, H] pre-scaled chunk queries; query c of row b sits at global
  slot `q_pos[b] + c` and attends its own sequence's slots `<= q_pos[b] + c`
  (causal within the chunk; the chunk's K/V were written to the pool before
  this call). in_len: [B] int32 valid-query counts — queries `c >= in_len[b]`
  (decode rows' dead tail, inactive rows) return 0 and never contribute.
  k_scale/v_scale [NP, N, P] f32 sidecars dequantize int8 pools on read.
  XLA-only lowering (one fori_loop over live pages, online softmax); the
  single-query BlockDecode kernel is the steady-state path. -> [B, C, N, H].
  """
  b, c, n, h = q.shape
  np_total, page, _, _ = k_pool.shape
  assert page == page_size, (page, page_size)
  assert (k_scale is None) == (v_scale is None), "pass both scales or neither"
  t_pages = block_tables.shape[1]
  q_pos = q_pos.astype(jnp.int32)
  in_len = in_len.astype(jnp.int32)
  tables = jnp.clip(block_tables.astype(jnp.int32), 0, np_total - 1)
  pos = q_pos[:, None] + jnp.arange(c, dtype=jnp.int32)[None]    # [B, C]
  valid = jnp.arange(c, dtype=jnp.int32)[None] < in_len[:, None]  # [B, C]
  end = q_pos + in_len
  trip = jnp.clip((jnp.max(end) + page_size - 1) // page_size, 0, t_pages)

  def _Body(j, carry):
    m, l, acc = carry
    pid = jax.lax.dynamic_index_in_dim(tables, j, axis=1, keepdims=False)
    k_page = k_pool[pid]                                   # [B, P, N, H]
    v_page = v_pool[pid]
    if k_scale is not None:
      k_page = _DequantPages(k_page, k_scale[pid])
      v_page = _DequantPages(v_page, v_scale[pid])
    slot = j * page_size + jnp.arange(page_size, dtype=jnp.int32)  # [P]
    keep = ((slot[None, None, :] <= pos[:, :, None])
            & valid[:, :, None])                           # [B, C, P]
    # [B, C, N, H] x [B, P, N, H] -> [B, C, N, P]
    s = _DotF32(q, k_page, (((3,), (3,)), ((0, 2), (0, 2))))
    s = jnp.moveaxis(s, 1, 2)                              # [B, C, N, P]
    s = jnp.where(keep[:, :, None, :], s, NEG_INF)
    m_cur = jnp.max(s, axis=-1, keepdims=True)             # [B, C, N, 1]
    m_new = jnp.maximum(m, m_cur)
    m_safe = jnp.where(m_new <= NEG_INF * 0.5, 0.0, m_new)
    p = jnp.exp(s - m_safe)
    alpha = jnp.exp(m - m_new)
    l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
    # [B, C, N, P] x [B, P, N, H] -> [B, C, N, H]
    pv = _DotF32(p.astype(v_page.dtype), v_page,
                 (((3,), (1,)), ((0, 2), (0, 2))))
    pv = jnp.moveaxis(pv, 1, 2)
    return m_new, l_new, alpha * acc + pv

  m0 = jnp.full((b, c, n, 1), NEG_INF, jnp.float32)
  l0 = jnp.zeros((b, c, n, 1), jnp.float32)
  acc0 = jnp.zeros((b, c, n, h), jnp.float32)
  _, l, acc = jax.lax.fori_loop(0, trip, _Body, (m0, l0, acc0))
  return _Finish(l, acc, q.dtype)


def SupportedOnTpu(page_size: int, h: int,
                   kv_dtype: str = "float32") -> bool:
  """Whether the Pallas block-decode lowering can run on real TPU hardware.

  Same Mosaic tiling constraint as flash_decode: page_size rides the
  128-lane minor axis of the in-kernel keep tiles and h the minor axis of
  the k/v page blocks. int8 pools add no NEW constraint: the int8 minimum
  tile is (32, 128) sublanes x lanes, subsumed by the %128 gates, and the
  f32 scale sidecar's minor axis is page_size, already a lane multiple
  here. The XLA twin has no such constraint."""
  del kv_dtype  # int8 needs nothing extra today; fp8 may.
  return page_size % LANES == 0 and h % LANES == 0
