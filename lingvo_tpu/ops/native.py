"""ctypes bindings for the native input-pipeline library.

Python surface over lingvo_tpu/ops/cc: RecordYielder (sharded files, shuffle
ring, epochs — ref `record_yielder.cc`), weighted mixing, PackSequences (ref
`pack_ops.cc`), AsciiTokenizer / Vocab tokenizer (ref `tokenizer_ops`).
Builds the .so on first use (g++, ~2s) and caches it next to the sources.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

_CC_DIR = os.path.join(os.path.dirname(__file__), "cc")
_SO_PATH = os.path.join(_CC_DIR, "liblingvo_tpu_ops.so")
_LIB = None
_LOCK = threading.Lock()


def _BuildIfNeeded():
  # Rebuild when the source *content* changes — mtimes are arbitrary after a
  # fresh checkout, so a stale .so could otherwise shadow newer sources.
  srcs = sorted(
      f for f in os.listdir(_CC_DIR)
      if f.endswith((".cc", ".h")) or f == "Makefile")
  digest = hashlib.sha256()
  for f in srcs:
    with open(os.path.join(_CC_DIR, f), "rb") as fh:
      digest.update(f.encode())
      digest.update(fh.read())
  stamp = os.path.join(_CC_DIR, ".build_hash")
  want = digest.hexdigest()
  have = None
  if os.path.exists(stamp):
    with open(stamp) as fh:
      have = fh.read().strip()
  if not os.path.exists(_SO_PATH) or have != want:
    subprocess.run(["make", "-C", _CC_DIR, "-s", "-B"], check=True)
    with open(stamp, "w") as fh:
      fh.write(want)


def Lib() -> ctypes.CDLL:
  global _LIB
  with _LOCK:
    if _LIB is None:
      _BuildIfNeeded()
      lib = ctypes.CDLL(_SO_PATH)
      # signatures
      lib.LTYielderNew.restype = ctypes.c_void_p
      lib.LTYielderNew.argtypes = [
          ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int64, ctypes.c_int32,
          ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32
      ]
      lib.LTMixYielderNew.restype = ctypes.c_void_p
      lib.LTMixYielderNew.argtypes = [
          ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_double),
          ctypes.c_int32, ctypes.c_uint64
      ]
      lib.LTYielderNext.restype = ctypes.c_int64
      lib.LTYielderNext.argtypes = [
          ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
          ctypes.POINTER(ctypes.c_int32)
      ]
      lib.LTYielderEpochs.restype = ctypes.c_int64
      lib.LTYielderEpochs.argtypes = [ctypes.c_void_p]
      lib.LTYielderFree.argtypes = [ctypes.c_void_p]
      lib.LTPackSequences.restype = ctypes.c_int64
      lib.LTPackSequences.argtypes = [
          ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, ctypes.c_int32,
          ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
          ctypes.POINTER(ctypes.c_int32), ctypes.c_int32
      ]
      lib.LTAsciiToIds.restype = ctypes.c_int32
      lib.LTAsciiToIds.argtypes = [
          ctypes.c_char_p, ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
          ctypes.c_int32, ctypes.c_int32
      ]
      lib.LTIdsToAscii.restype = ctypes.c_int32
      lib.LTIdsToAscii.argtypes = [
          ctypes.POINTER(ctypes.c_int32), ctypes.c_int32, ctypes.c_char_p,
          ctypes.c_int32
      ]
      lib.LTVocabLoad.restype = ctypes.c_void_p
      lib.LTVocabLoad.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
      lib.LTVocabFree.argtypes = [ctypes.c_void_p]
      lib.LTVocabSize.restype = ctypes.c_int32
      lib.LTVocabSize.argtypes = [ctypes.c_void_p]
      lib.LTVocabToIds.restype = ctypes.c_int32
      lib.LTVocabToIds.argtypes = [
          ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
          ctypes.POINTER(ctypes.c_int32), ctypes.c_int32
      ]
      for prefix in ("LTWpm", "LTBpe"):
        load = getattr(lib, prefix + "Load")
        load.restype = ctypes.c_void_p
        load.argtypes = ([ctypes.c_char_p, ctypes.c_char_p] if prefix ==
                         "LTWpm" else
                         [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p])
        getattr(lib, prefix + "Free").argtypes = [ctypes.c_void_p]
        size = getattr(lib, prefix + "Size")
        size.restype = ctypes.c_int32
        size.argtypes = [ctypes.c_void_p]
        enc = getattr(lib, prefix + "Encode")
        enc.restype = ctypes.c_int32
        enc.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32
        ]
        dec = getattr(lib, prefix + "Decode")
        dec.restype = ctypes.c_int32
        dec.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_int32
        ]
      lib.LTVocabToText.restype = ctypes.c_int32
      lib.LTVocabToText.argtypes = [
          ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
          ctypes.c_char_p, ctypes.c_int32
      ]
      _LIB = lib
  return _LIB


class RecordYielder:
  """Streams shuffled records from sharded files (C++ threads)."""

  def __init__(self, file_pattern: str, seed: int = 301,
               shuffle_buffer_size: int = 10000, num_threads: int = 2,
               max_epochs: int = 0, shuffle: bool = True,
               shard_index: int = 0, num_shards: int = 1,
               max_record_bytes: int = 1 << 20):
    self._lib = Lib()
    self._handle = self._lib.LTYielderNew(
        file_pattern.encode(), seed, shuffle_buffer_size, num_threads,
        max_epochs, int(shuffle), shard_index, num_shards)
    if not self._handle:
      raise ValueError(
          f"RecordYielder: no files match {file_pattern!r} (or unknown "
          "type prefix; known: text/tfrecord/recordio/iota)")
    self._buf = ctypes.create_string_buffer(max_record_bytes)

  def Next(self) -> bytes | None:
    """Returns the next record, or None when the stream is exhausted."""
    src = ctypes.c_int32(0)
    n = self._lib.LTYielderNext(self._handle, self._buf,
                                len(self._buf), ctypes.byref(src))
    if n < 0:
      return None
    if n > len(self._buf):
      # record stayed pending C-side; retry with a bigger buffer (lossless)
      self._buf = ctypes.create_string_buffer(int(n))
      return self.Next()
    return ctypes.string_at(self._buf, n)

  @property
  def epochs_completed(self) -> int:
    return self._lib.LTYielderEpochs(self._handle)

  def __iter__(self):
    while True:
      rec = self.Next()
      if rec is None:
        return
      yield rec

  def Close(self):
    if self._handle:
      self._lib.LTYielderFree(self._handle)
      self._handle = None

  def __del__(self):
    try:
      self.Close()
    except Exception:
      pass


def PackSequences(lens, num_rows: int, time: int,
                  spread_first_n: int = 0):
  """Best-fit packing: returns (row[n], offset[n]); row -1 = dropped.

  spread_first_n is reserved for reference-parity spreading and currently
  ignored by the native implementation.
  """
  lib = Lib()
  lens = np.ascontiguousarray(lens, np.int32)
  n = len(lens)
  row = np.empty(n, np.int32)
  off = np.empty(n, np.int32)
  lib.LTPackSequences(
      lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n, num_rows, time,
      row.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
      off.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), spread_first_n)
  return row, off


def ApplyPacking(sequences, row, offset, num_rows, time, pad_value=0,
                 extra_payloads=None, return_used=False):
  """Materializes packed ids/segment_ids/segment_pos from an assignment.

  `extra_payloads`: optional dict {name: list-of-arrays} packed with the same
  assignment (e.g. labels alongside ids); returned as a dict after seg_pos.
  `return_used`: also return the list of sequence indices that were placed
  (row >= 0) — callers keeping a pending pool drop exactly these.
  """
  ids = np.full((num_rows, time), pad_value, np.int32)
  extras = {name: np.full((num_rows, time), pad_value, np.int32)
            for name in (extra_payloads or {})}
  seg_ids = np.zeros((num_rows, time), np.int32)
  seg_pos = np.zeros((num_rows, time), np.int32)
  seg_counter = np.zeros(num_rows, np.int32)
  used = []
  for i, seq in enumerate(sequences):
    r = int(row[i])
    if r < 0:
      continue
    o = int(offset[i])
    L = len(seq)
    ids[r, o:o + L] = seq
    for name, payload in (extra_payloads or {}).items():
      extras[name][r, o:o + L] = payload[i][:L]
    seg_counter[r] += 1
    seg_ids[r, o:o + L] = seg_counter[r]
    seg_pos[r, o:o + L] = np.arange(L)
    used.append(i)
  out = (ids, seg_ids, seg_pos)
  if extra_payloads is not None:
    out = out + (extras,)
  if return_used:
    out = out + (used,)
  return out


class AsciiTokenizer:
  """Char-level tokenizer (ref ascii_tokenizer.cc id space)."""

  vocab_size = 76
  sos_id, eos_id, unk_id = 0, 1, 73

  def StringsToIds(self, texts, max_len: int, append_eos: bool = True):
    lib = Lib()
    b = len(texts)
    ids = np.zeros((b, max_len), np.int32)
    lens = np.zeros(b, np.int32)
    for i, text in enumerate(texts):
      data = text.encode() if isinstance(text, str) else bytes(text)
      out = np.zeros(max_len, np.int32)
      n = lib.LTAsciiToIds(data, len(data),
                           out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                           max_len, int(append_eos))
      ids[i, :n] = out[:n]
      lens[i] = n
    paddings = (np.arange(max_len)[None, :] >= lens[:, None]).astype(
        np.float32)
    return ids, paddings

  def IdsToStrings(self, ids, lens=None):
    lib = Lib()
    out = []
    for i in range(len(ids)):
      row = np.ascontiguousarray(ids[i], np.int32)
      n = int(lens[i]) if lens is not None else len(row)
      buf = ctypes.create_string_buffer(4 * max(n, 1))
      m = lib.LTIdsToAscii(
          row.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n, buf,
          len(buf))
      out.append(buf.raw[:m].decode("utf-8", errors="replace"))
    return out


class VocabTokenizer:
  """Whitespace-token vocab lookup (ref simple_vocab.cc)."""

  def __init__(self, vocab_path: str, unk_token: str = "<unk>"):
    self._lib = Lib()
    self._handle = self._lib.LTVocabLoad(vocab_path.encode(),
                                         unk_token.encode())
    if not self._handle:
      raise FileNotFoundError(vocab_path)

  @property
  def vocab_size(self) -> int:
    return self._lib.LTVocabSize(self._handle)

  def StringsToIds(self, texts, max_len: int):
    b = len(texts)
    ids = np.zeros((b, max_len), np.int32)
    lens = np.zeros(b, np.int32)
    for i, text in enumerate(texts):
      data = text.encode() if isinstance(text, str) else bytes(text)
      out = np.zeros(max_len, np.int32)
      n = self._lib.LTVocabToIds(
          self._handle, data, len(data),
          out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), max_len)
      ids[i, :n] = out[:n]
      lens[i] = n
    paddings = (np.arange(max_len)[None, :] >= lens[:, None]).astype(
        np.float32)
    return ids, paddings

  def IdsToStrings(self, ids, lens=None):
    out = []
    for i in range(len(ids)):
      row = np.ascontiguousarray(ids[i], np.int32)
      n = int(lens[i]) if lens is not None else len(row)
      buf = ctypes.create_string_buffer(64 * max(n, 1))
      m = self._lib.LTVocabToText(
          self._handle, row.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
          n, buf, len(buf))
      out.append(buf.raw[:m].decode("utf-8", errors="replace"))
    return out

  def __del__(self):
    try:
      if self._handle:
        self._lib.LTVocabFree(self._handle)
    except Exception:
      pass

class _SubwordTokenizerBase:
  """Shared encode/decode surface for the C++ subword tokenizers."""

  _PREFIX = ""

  def __init__(self):
    self._lib = Lib()
    self._handle = None

  def _Fn(self, name):
    return getattr(self._lib, self._PREFIX + name)

  @property
  def vocab_size(self) -> int:
    return self._Fn("Size")(self._handle)

  def StringsToIds(self, texts, max_len: int):
    b = len(texts)
    ids = np.zeros((b, max_len), np.int32)
    lens = np.zeros(b, np.int32)
    for i, text in enumerate(texts):
      data = text.encode() if isinstance(text, str) else bytes(text)
      out = np.zeros(max_len, np.int32)
      n = self._Fn("Encode")(
          self._handle, data, len(data),
          out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), max_len)
      ids[i, :n] = out[:n]
      lens[i] = n
    paddings = (np.arange(max_len)[None, :] >= lens[:, None]).astype(
        np.float32)
    return ids, paddings

  def IdsToStrings(self, ids, lens=None):
    out = []
    for i in range(len(ids)):
      row = np.ascontiguousarray(ids[i], np.int32)
      n = int(lens[i]) if lens is not None else len(row)
      buf = ctypes.create_string_buffer(64 * max(n, 1))
      m = self._Fn("Decode")(
          self._handle, row.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
          n, buf, len(buf))
      out.append(buf.raw[:m].decode("utf-8", errors="replace"))
    return out

  def __del__(self):
    try:
      if self._handle:
        self._Fn("Free")(self._handle)
    except Exception:
      pass


class WpmTokenizer(_SubwordTokenizerBase):
  """Greedy longest-match wordpiece (ref wpm_encoder.py semantics).

  Auto-detects the marker convention from the vocab file: sentencepiece
  word-start "▁" or BERT continuation "##".
  """

  _PREFIX = "LTWpm"

  def __init__(self, vocab_path: str, unk_token: str = "<unk>"):
    super().__init__()
    self._handle = self._lib.LTWpmLoad(vocab_path.encode(),
                                       unk_token.encode())
    if not self._handle:
      raise FileNotFoundError(vocab_path)


class BpeTokenizer(_SubwordTokenizerBase):
  """Merge-ops BPE (ref BpeWordsToIds kernel semantics: codes file of merge
  operations in priority order + subword vocab file, "</w>" end-of-word)."""

  _PREFIX = "LTBpe"

  def __init__(self, codes_path: str, vocab_path: str,
               unk_token: str = "<unk>"):
    super().__init__()
    self._handle = self._lib.LTBpeLoad(codes_path.encode(),
                                       vocab_path.encode(),
                                       unk_token.encode())
    if not self._handle:
      raise FileNotFoundError(f"{codes_path} / {vocab_path}")
