"""Chunked gated linear-recurrence scan (state-space duality form).

The training/prefill hot op behind `core/ssm.py`. Semantics per head — a
matrix-valued linear recurrence over time with scalar input-dependent decay:

    S_t = a_t * S_{t-1} + v_t outer b_t        # S: [H, S] state matrix
    y_t = S_t @ c_t                            # readout AFTER update, so the
                                               # diagonal (t attends t) term
                                               # is included

with `a_t = exp(decay_log_t)`, `decay_log_t <= 0`. This is the "state space
duality" (SSD) form: unrolled, y_t = sum_{t'<=t} exp(cum_t - cum_t')
(c_t . b_t') v_t' — i.e. causal linear attention with a multiplicative decay
mask — which is what the chunked lowerings exploit.

Four lowerings of the SAME recurrence:

- `sequential` — `lax.scan` over single tokens through `SequentialStep`.
  `core/ssm.py`'s ExtendStep calls `SequentialStep` directly, so this
  lowering IS the decode path and the two agree bitwise by construction.
- `associative` — `jax.lax.associative_scan` over (a, v outer b) pairs with
  the affine combine (a_l*a_r, a_r*u_l + u_r). Materializes the full
  [T, H, S] state trajectory: the O(T*H*S)-memory textbook reference the
  chunked paths are tested against, not a production path.
- `chunked` — the XLA production path: reshape T into [num_chunks, Q],
  run the quadratic intra-chunk form + O(1)-state inter-chunk carry of
  `_ChunkBody` under `lax.scan`. Linear memory in T, matmul-shaped work.
- `pallas` — a Pallas TPU kernel with grid (B*N, num_chunks); the chunk
  axis is sequential ("arbitrary") with the running state carried in f32
  VMEM scratch across grid steps, exactly like `flash_decode`'s per-page
  scratch carry. Every chunk routes through the SAME `_ChunkBody` as the
  XLA chunked path, so interpret-mode equality holds bitwise — the
  `flash_decode`/`block_decode` twin-lowering pattern.

Numerical contract: all scan math is f32 regardless of input dtype (the
recurrence compounds products over thousands of steps; bf16 state drifts).
Outputs are f32; the caller casts.

Masking contract (the caller — `core/ssm.py` — prepares inputs):
- padded step: decay_log = 0 AND v = 0  ->  S_t = S_{t-1} exactly.
- segment reset: decay_log = RESET_LOG (-60). exp(-60) ~ 9e-27, so any
  leaked history underflows an f32 add against O(1) activations — an
  exact reset in practice — while cumsums inside a chunk stay O(100), so
  within-segment decay differences are NOT absorbed the way a -1e30
  sentinel would absorb them (catastrophic-cancellation trap).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lingvo_tpu.ops.flash_attention import (  # single source of truth
    LANES, SUBLANES, _CompilerParams)

# Segment-boundary decay: see the masking contract in the module docstring.
RESET_LOG = -60.0
# Mask value for "never attend" inside a chunk (exp(_MASK_LOG) == 0.0 in f32).
_MASK_LOG = -1.0e30


def SequentialStep(s, decay_log, b_t, c_t, v_t):
  """One recurrence step. The decode path (`ssm.ExtendStep`) calls this.

  s: [..., H, S] f32 state, decay_log: [...] f32, b_t/c_t: [..., S],
  v_t: [..., H]. Returns (s_new [..., H, S], y [..., H]), both f32.
  """
  s = s.astype(jnp.float32)
  a = jnp.exp(decay_log.astype(jnp.float32))[..., None, None]
  u = (v_t.astype(jnp.float32)[..., :, None]
       * b_t.astype(jnp.float32)[..., None, :])
  s_new = a * s + u
  y = jnp.einsum("...s,...hs->...h", c_t.astype(jnp.float32), s_new)
  return s_new, y


def _SequentialScan(decay_log, b_in, c_in, v, s0):
  """lax.scan over single tokens. Flat inputs: decay_log [R, T],
  b_in/c_in [R, T, S], v [R, T, H], s0 [R, H, S]. R = B*N."""

  def _Step(s, xs):
    dl, bt, ct, vt = xs
    s_new, y = SequentialStep(s, dl, bt, ct, vt)
    return s_new, y

  xs = (decay_log.swapaxes(0, 1), b_in.swapaxes(0, 1),
        c_in.swapaxes(0, 1), v.swapaxes(0, 1))
  s_fin, ys = jax.lax.scan(_Step, s0, xs)
  return ys.swapaxes(0, 1), s_fin


def _AssociativeScan(decay_log, b_in, c_in, v, s0):
  """jax.lax.associative_scan reference. Same flat shapes as above.

  Materializes the [R, T, H, S] state trajectory — reference only.
  """
  a = jnp.exp(decay_log)[..., None, None]              # [R, T, 1, 1]
  u = v[..., :, None] * b_in[..., None, :]             # [R, T, H, S]

  def _Combine(left, right):
    a_l, u_l = left
    a_r, u_r = right
    return a_l * a_r, a_r * u_l + u_r

  a_cum, s_all = jax.lax.associative_scan(_Combine, (a, u), axis=1)
  # Thread the initial state through the cumulative decay.
  s_all = s_all + a_cum * s0[:, None]
  y = jnp.einsum("rts,rths->rth", c_in, s_all)
  return y, s_all[:, -1]


def _ChunkBody(s_in, dl2, b_c, c_c, v_c):
  """One chunk of the recurrence for one (batch, head) pair.

  s_in: [H, S] f32 incoming state, dl2: [Q, 1] f32 log-decay, b_c/c_c:
  [Q, S] f32, v_c: [Q, H] f32. Returns (y [Q, H], s_out [H, S]).

  Both the XLA chunked lowering (vmapped over B*N) and the Pallas kernel
  (per grid step) call exactly this, so the float-op sequence — and the
  bits, in interpret mode — match. Everything stays rank-2: TPU Mosaic
  has no appetite for 1-D vectors, and [Q, 1] broadcasts are free.
  """
  cum = jnp.cumsum(dl2, axis=0)                        # [Q, 1]
  # Inter-chunk: position t sees s_in through decay exp(cum_t).
  y_inter = jnp.dot(c_c * jnp.exp(cum),                # [Q, S]
                    s_in.T, precision=None,
                    preferred_element_type=jnp.float32)  # [Q, H]
  # Intra-chunk quadratic form: exp(cum_t - cum_t') (c_t . b_t'), t' <= t.
  scores = jnp.dot(c_c, b_c.T, precision=None,
                   preferred_element_type=jnp.float32)   # [Q, P]
  dmat = cum - cum.swapaxes(0, 1)                        # [Q, P]
  q = dl2.shape[0]
  row = jax.lax.broadcasted_iota(jnp.int32, (q, q), 0)
  col = jax.lax.broadcasted_iota(jnp.int32, (q, q), 1)
  decay = jnp.exp(jnp.where(row >= col, dmat, _MASK_LOG))
  y_intra = jnp.dot(scores * decay, v_c, precision=None,
                    preferred_element_type=jnp.float32)  # [Q, H]
  # State out: decay the incoming state across the whole chunk, add each
  # token's outer-product contribution decayed from its position to the end.
  tot = cum[-1:]                                         # [1, 1]
  w_tail = jnp.exp(tot - cum)                            # [Q, 1]
  s_out = (jnp.exp(tot) * s_in
           + jnp.dot((v_c * w_tail).T, b_c, precision=None,
                     preferred_element_type=jnp.float32))  # [H, S]
  return y_inter + y_intra, s_out


def _PadChunks(decay_log, b_in, c_in, v, chunk_size):
  """Right-pad T to a chunk multiple with identity steps (dl=0, u=0)."""
  t = decay_log.shape[1]
  t_pad = -(-t // chunk_size) * chunk_size
  if t_pad == t:
    return decay_log, b_in, c_in, v, t_pad
  pad = t_pad - t
  decay_log = jnp.pad(decay_log, ((0, 0), (0, pad)))
  b_in = jnp.pad(b_in, ((0, 0), (0, pad), (0, 0)))
  c_in = jnp.pad(c_in, ((0, 0), (0, pad), (0, 0)))
  v = jnp.pad(v, ((0, 0), (0, pad), (0, 0)))
  return decay_log, b_in, c_in, v, t_pad


def _ChunkedXla(decay_log, b_in, c_in, v, s0, chunk_size):
  """XLA chunked lowering: lax.scan over chunks of vmapped _ChunkBody."""
  r, t = decay_log.shape
  s_dim, h = b_in.shape[-1], v.shape[-1]
  decay_log, b_in, c_in, v, t_pad = _PadChunks(
      decay_log, b_in, c_in, v, chunk_size)
  nc = t_pad // chunk_size
  # [R, T, ...] -> [NC, R, Q, ...] so the chunk axis leads for lax.scan.
  dl = decay_log.reshape(r, nc, chunk_size, 1).swapaxes(0, 1)
  bb = b_in.reshape(r, nc, chunk_size, s_dim).swapaxes(0, 1)
  cc = c_in.reshape(r, nc, chunk_size, s_dim).swapaxes(0, 1)
  vv = v.reshape(r, nc, chunk_size, h).swapaxes(0, 1)

  def _Scan(s, xs):
    y, s_new = jax.vmap(_ChunkBody)(s, *xs)
    return s_new, y

  s_fin, ys = jax.lax.scan(_Scan, s0, (dl, bb, cc, vv))
  y = ys.swapaxes(0, 1).reshape(r, t_pad, h)[:, :t]
  return y, s_fin


def _ScanKernel(dl_ref, b_ref, c_ref, v_ref, s0_ref, y_ref, sfin_ref,
                s_scr, *, num_chunks):
  """Pallas kernel: grid (R, NC); chunk axis sequential, state in scratch."""
  j = pl.program_id(1)

  @pl.when(j == 0)
  def _Init():
    s_scr[:] = s0_ref[0]

  y, s_new = _ChunkBody(s_scr[:], dl_ref[0, 0], b_ref[0, 0], c_ref[0, 0],
                        v_ref[0, 0])
  y_ref[0, 0] = y
  s_scr[:] = s_new

  @pl.when(j == num_chunks - 1)
  def _Emit():
    sfin_ref[0] = s_scr[:]


def _ChunkedPallas(decay_log, b_in, c_in, v, s0, chunk_size,
                   interpret=False):
  """Pallas twin of _ChunkedXla. Same flat [R, T, ...] contract."""
  r, t = decay_log.shape
  s_dim, h = b_in.shape[-1], v.shape[-1]
  decay_log, b_in, c_in, v, t_pad = _PadChunks(
      decay_log, b_in, c_in, v, chunk_size)
  nc = t_pad // chunk_size
  dl = decay_log.reshape(r, nc, chunk_size, 1)
  bb = b_in.reshape(r, nc, chunk_size, s_dim)
  cc = c_in.reshape(r, nc, chunk_size, s_dim)
  vv = v.reshape(r, nc, chunk_size, h)

  kernel = functools.partial(_ScanKernel, num_chunks=nc)
  y, s_fin = pl.pallas_call(
      kernel,
      grid=(r, nc),
      in_specs=[
          pl.BlockSpec((1, 1, chunk_size, 1), lambda ri, j: (ri, j, 0, 0)),
          pl.BlockSpec((1, 1, chunk_size, s_dim),
                       lambda ri, j: (ri, j, 0, 0)),
          pl.BlockSpec((1, 1, chunk_size, s_dim),
                       lambda ri, j: (ri, j, 0, 0)),
          pl.BlockSpec((1, 1, chunk_size, h), lambda ri, j: (ri, j, 0, 0)),
          pl.BlockSpec((1, h, s_dim), lambda ri, j: (ri, 0, 0)),
      ],
      out_specs=[
          pl.BlockSpec((1, 1, chunk_size, h), lambda ri, j: (ri, j, 0, 0)),
          pl.BlockSpec((1, h, s_dim), lambda ri, j: (ri, 0, 0)),
      ],
      out_shape=[
          jax.ShapeDtypeStruct((r, nc, chunk_size, h), jnp.float32),
          jax.ShapeDtypeStruct((r, h, s_dim), jnp.float32),
      ],
      scratch_shapes=[pltpu.VMEM((h, s_dim), jnp.float32)],
      compiler_params=_CompilerParams(
          dimension_semantics=("parallel", "arbitrary")),
      interpret=interpret,
  )(dl, bb, cc, vv, s0)
  return y.reshape(r, t_pad, h)[:, :t], s_fin


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _PallasScan(decay_log, b_in, c_in, v, s0, chunk_size, interpret):
  return _ChunkedPallas(decay_log, b_in, c_in, v, s0, chunk_size,
                        interpret=interpret)


def _PallasScanFwd(decay_log, b_in, c_in, v, s0, chunk_size, interpret):
  out = _PallasScan(decay_log, b_in, c_in, v, s0, chunk_size, interpret)
  return out, (decay_log, b_in, c_in, v, s0)


def _PallasScanBwd(chunk_size, interpret, residuals, cots):
  # The XLA chunked path computes the same floats (shared _ChunkBody), so
  # its VJP is the principled backward for the Pallas forward — the same
  # trick fused_xent uses (recompute-based custom_vjp).
  del interpret
  decay_log, b_in, c_in, v, s0 = residuals
  _, vjp = jax.vjp(
      lambda *args: _ChunkedXla(*args, chunk_size), decay_log, b_in, c_in,
      v, s0)
  return vjp(cots)


_PallasScan.defvjp(_PallasScanFwd, _PallasScanBwd)


def SupportedOnTpu(chunk_size: int, state_dim: int, head_dim: int) -> bool:
  """Whether the Pallas lowering can run on real TPU hardware.

  Conservative, mirroring flash_decode.SupportedOnTpu: the state/head dims
  ride the 128-lane minor axis and the chunk axis rides sublanes.
  """
  return (chunk_size % SUBLANES == 0 and state_dim % LANES == 0
          and head_dim % LANES == 0)


def SsdScan(decay_log, b_in, c_in, v, s0=None, *, chunk_size: int = 64,
            lowering: str = "auto", interpret: bool | None = None):
  """Gated linear-recurrence scan over a batch of sequences.

  decay_log: [B, T, N] f32 log-decay per (step, head), <= 0. Caller encodes
    padding (0 with zeroed v) and segment resets (RESET_LOG) here.
  b_in: [B, T, N, S] input projection ("write keys").
  c_in: [B, T, N, S] output projection ("read keys").
  v:    [B, T, N, H] values.
  s0:   optional [B, N, H, S] f32 initial state (zeros when None).
  lowering: 'auto' (pallas on real TPU when SupportedOnTpu, else chunked),
    'chunked', 'pallas', 'associative', or 'sequential'.
  Returns (y [B, T, N, H] f32, s_final [B, N, H, S] f32).
  """
  assert lowering in ("auto", "chunked", "pallas", "associative",
                      "sequential"), lowering
  b, t, n = decay_log.shape
  s_dim, h = b_in.shape[-1], v.shape[-1]
  on_tpu = jax.default_backend() == "tpu"
  if lowering == "auto":
    lowering = ("pallas" if on_tpu and SupportedOnTpu(chunk_size, s_dim, h)
                else "chunked")
  # Flatten (B, N) into one row axis: every lowering is per-(batch, head).
  f32 = jnp.float32
  dl = decay_log.astype(f32).transpose(0, 2, 1).reshape(b * n, t)
  bb = b_in.astype(f32).transpose(0, 2, 1, 3).reshape(b * n, t, s_dim)
  cc = c_in.astype(f32).transpose(0, 2, 1, 3).reshape(b * n, t, s_dim)
  vv = v.astype(f32).transpose(0, 2, 1, 3).reshape(b * n, t, h)
  if s0 is None:
    s0f = jnp.zeros((b * n, h, s_dim), f32)
  else:
    s0f = s0.astype(f32).reshape(b * n, h, s_dim)

  if lowering == "sequential":
    y, s_fin = _SequentialScan(dl, bb, cc, vv, s0f)
  elif lowering == "associative":
    y, s_fin = _AssociativeScan(dl, bb, cc, vv, s0f)
  elif lowering == "chunked":
    y, s_fin = _ChunkedXla(dl, bb, cc, vv, s0f, chunk_size)
  else:
    if interpret is None:
      interpret = not on_tpu
    y, s_fin = _PallasScan(dl, bb, cc, vv, s0f, chunk_size, interpret)

  y = y.reshape(b, n, t, h).transpose(0, 2, 1, 3)
  s_fin = s_fin.reshape(b, n, h, s_dim)
  return y, s_fin
