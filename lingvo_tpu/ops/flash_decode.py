"""Length-aware paged flash decode over a pre-allocated KV cache.

The incremental-decode hot op: one query token per sequence attends to a
`[B, S, N, H]` KV cache of which only slots `[0, time_step]` have ever been
written. The dense path (`attention.py` ExtendStep) reads all S slots every
step and masks the unwritten tail — O(S) work regardless of how little of
the cache is live. This op blocks the cache time axis into fixed-size
*pages* and only reads pages up to `time_step` (the tail page is masked
in-kernel), the "Ragged Paged Attention" formulation specialized to a
single query per sequence.

Two lowerings of the SAME algorithm, asserted bit-identical in tests:

- `_PallasDecode` — a Pallas TPU kernel. Grid `(B, num_pages)`; the page
  index map clamps to the last live page via a scalar-prefetched
  `time_step` (`pltpu.PrefetchScalarGridSpec`), so Pallas elides the HBM
  DMAs for dead pages, and `pl.when` skips their compute. Online softmax
  (running max / denominator / accumulator) in f32 VMEM scratch, same
  layout tricks as `ops/flash_attention.py` (per-row stats broadcast
  across the 128-lane minor dim).
- `_XlaDecode` — a pure-XLA twin: `lax.fori_loop` with a *dynamic* trip
  count of `time_step // page_size + 1` over `dynamic_slice`d pages. This
  is the CPU serving path: Pallas interpret mode charges ~8-10 ms per grid
  step on CPU regardless of the compute inside, which would bury the
  paging win; the XLA loop actually skips dead pages.

Both lowerings route every page through `_PageAttend`, so the float-op
sequence is identical and interpret-mode equality holds bitwise.

Contract differences from FlashAttention:
- q arrives PRE-SCALED (the caller applies per-dim-scale / 1/sqrt(h));
  no internal scaling.
- no causal masking beyond the `slot <= time_step` length mask (the one
  query IS the newest position).
- a fully-masked row (every live slot padded) returns 0, not the dense
  path's uniform-softmax garbage; callers never expose such rows.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lingvo_tpu.ops.flash_attention import (  # single source of truth
    LANES, NEG_INF, SUBLANES, _CompilerParams)


def _DotF32(a, b, dims):
  """dot_general with f32 accumulation, native input dtype (MXU fast path)."""
  return jax.lax.dot_general(a, b, dims, preferred_element_type=jnp.float32)


def _PageAttend(q, k_page, v_page, keep, m, l, acc):
  """One page of online-softmax attention for one sequence.

  q: [N, H] (pre-scaled), k_page/v_page: [P, N, H], keep: f32 [1, P]
  (1.0 = attend, 0.0 = masked: dead slot or cache padding),
  m/l: f32 [N, 1] running max / denominator, acc: f32 [N, H].
  Returns updated (m, l, acc). Both lowerings call exactly this, so the
  float-op sequence (and thus the bits) match across Pallas and XLA.
  """
  # [N, H] x [P, N, H] -> [N, P], contraction over H, batch over N.
  s = _DotF32(q, k_page, (((1,), (2,)), ((0,), (1,))))
  s = jnp.where(keep > 0.5, s, NEG_INF)                  # [N, P]
  m_cur = jnp.max(s, axis=-1, keepdims=True)             # [N, 1]
  m_new = jnp.maximum(m, m_cur)
  # All-masked-so-far rows have m_new = NEG_INF; exp(s - m_new) would turn
  # masked entries into exp(0) = 1. Same guard as flash_attention._FwdKernel.
  m_safe = jnp.where(m_new <= NEG_INF * 0.5, 0.0, m_new)
  p = jnp.exp(s - m_safe)                                # f32 [N, P]
  alpha = jnp.exp(m - m_new)                             # [N, 1]
  l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
  # [N, P] x [P, N, H] -> [N, H]: contraction over P, batch over N.
  pv = _DotF32(p.astype(v_page.dtype), v_page, (((1,), (0,)), ((0,), (1,))))
  acc_new = acc * alpha + pv
  return m_new, l_new, acc_new


def _Finish(l, acc, dtype):
  return (acc / jnp.maximum(l, 1e-20)).astype(dtype)


# -- XLA twin (the CPU path) -------------------------------------------------


def _XlaDecode(q, k_cache, v_cache, time_step, page_size: int,
               cache_paddings=None):
  """q: [B, N, H], caches [B, S, N, H], time_step scalar int32 -> [B, N, H].

  Dynamic-trip-count fori_loop over live pages only: the work per decode
  step is O(time_step), not O(S).
  """
  b, s, n, h = k_cache.shape
  assert s % page_size == 0, (s, page_size)
  t = time_step.astype(jnp.int32)
  # t is in [0, s-1] per the ExtendStep contract; the clamp keeps an
  # out-of-contract t >= s from re-reading the (dynamic-slice-clamped) last
  # page with unclamped slot ids — the Pallas grid never exceeds num_pages,
  # and the twins must agree bitwise.
  num_live = jnp.minimum(t // page_size + 1, s // page_size)

  if cache_paddings is None:
    pad = jnp.zeros((b, s), jnp.float32)
  else:
    pad = cache_paddings.astype(jnp.float32)

  batched_attend = jax.vmap(_PageAttend)

  def _Body(pi, carry):
    m, l, acc = carry
    start = pi * page_size
    k_page = jax.lax.dynamic_slice_in_dim(k_cache, start, page_size, axis=1)
    v_page = jax.lax.dynamic_slice_in_dim(v_cache, start, page_size, axis=1)
    pad_page = jax.lax.dynamic_slice_in_dim(pad, start, page_size, axis=1)
    slot = start + jnp.arange(page_size, dtype=jnp.int32)   # [P]
    keep = ((slot[None, :] <= t).astype(jnp.float32)
            * (1.0 - pad_page))[:, None, :]                 # [B, 1, P]
    return batched_attend(q, k_page, v_page, keep, m, l, acc)

  m0 = jnp.full((b, n, 1), NEG_INF, jnp.float32)
  l0 = jnp.zeros((b, n, 1), jnp.float32)
  acc0 = jnp.zeros((b, n, h), jnp.float32)
  _, l, acc = jax.lax.fori_loop(0, num_live, _Body, (m0, l0, acc0))
  return _Finish(l, acc, q.dtype)


# -- Pallas TPU kernel -------------------------------------------------------


def _DecodeKernel(t_ref, q_ref, k_ref, v_ref, pad_ref, out_ref, m_scr, l_scr,
                  acc_scr, *, page_size: int, num_pages: int):
  """One (batch, page) program step; scratch carried across the page dim."""
  j = pl.program_id(1)
  t = t_ref[0]

  @pl.when(j == 0)
  def _Init():
    m_scr[:] = jnp.full_like(m_scr, NEG_INF)
    l_scr[:] = jnp.zeros_like(l_scr)
    acc_scr[:] = jnp.zeros_like(acc_scr)

  @pl.when(j * page_size <= t)
  def _Accumulate():
    q = q_ref[0]                                        # [N, H]
    k_page = k_ref[0]                                   # [P, N, H]
    v_page = v_ref[0]
    slot = j * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, page_size), 1)                   # [1, P]
    keep = ((slot <= t).astype(jnp.float32)
            * (1.0 - pad_ref[0][:1, :]))                # [1, P]
    m, l, acc = _PageAttend(q, k_page, v_page, keep, m_scr[:, :1],
                            l_scr[:, :1], acc_scr[:])
    m_scr[:] = jnp.broadcast_to(m, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l, l_scr.shape)
    acc_scr[:] = acc

  @pl.when(j == num_pages - 1)
  def _Emit():
    out_ref[0] = _Finish(l_scr[:, :1], acc_scr[:], out_ref.dtype)


def _PallasDecode(q, k_cache, v_cache, time_step, page_size: int,
                  cache_paddings=None, interpret: bool = False):
  """Pallas lowering of _XlaDecode. q: [B, N, H] -> [B, N, H]."""
  b, s, n, h = k_cache.shape
  assert s % page_size == 0, (s, page_size)
  num_pages = s // page_size
  if cache_paddings is None:
    pad = jnp.zeros((b, s), jnp.float32)
  else:
    pad = cache_paddings.astype(jnp.float32)
  # kv-side mask rides the same SUBLANES trick as flash_attention's segment
  # ids: broadcast over sublanes with the time axis minor.
  pad3 = jnp.broadcast_to(pad[:, None, :], (b, SUBLANES, s))
  t_arr = jnp.reshape(time_step.astype(jnp.int32), (1,))

  # Clamp dead pages to the last live page: Pallas re-requests the same
  # block and elides the DMA, so dead pages cost neither HBM bandwidth nor
  # (thanks to pl.when) compute.
  def _PageIdx(j, t_ref):
    return jnp.minimum(j, t_ref[0] // page_size)

  grid_spec = pltpu.PrefetchScalarGridSpec(
      num_scalar_prefetch=1,
      grid=(b, num_pages),
      in_specs=[
          pl.BlockSpec((1, n, h), lambda bi, j, t_ref: (bi, 0, 0)),
          pl.BlockSpec((1, page_size, n, h),
                       lambda bi, j, t_ref: (bi, _PageIdx(j, t_ref), 0, 0)),
          pl.BlockSpec((1, page_size, n, h),
                       lambda bi, j, t_ref: (bi, _PageIdx(j, t_ref), 0, 0)),
          pl.BlockSpec((1, SUBLANES, page_size),
                       lambda bi, j, t_ref: (bi, 0, _PageIdx(j, t_ref))),
      ],
      out_specs=pl.BlockSpec((1, n, h), lambda bi, j, t_ref: (bi, 0, 0)),
      scratch_shapes=[
          pltpu.VMEM((n, LANES), jnp.float32),
          pltpu.VMEM((n, LANES), jnp.float32),
          pltpu.VMEM((n, h), jnp.float32),
      ],
  )
  kernel = functools.partial(_DecodeKernel, page_size=page_size,
                             num_pages=num_pages)
  return pl.pallas_call(
      kernel,
      grid_spec=grid_spec,
      out_shape=jax.ShapeDtypeStruct((b, n, h), q.dtype),
      compiler_params=_CompilerParams(
          dimension_semantics=("parallel", "arbitrary")),
      interpret=interpret,
  )(t_arr, q, k_cache, v_cache, pad3)


# -- public entry ------------------------------------------------------------


def FlashDecode(q, k_cache, v_cache, time_step, *, page_size: int,
                cache_paddings=None, lowering: str = "auto",
                interpret: bool | None = None):
  """Paged single-token decode attention.

  q: [B, 1, N, H] — the newest query, ALREADY scaled (per-dim scale or
  1/sqrt(h); unlike FlashAttention nothing is applied internally).
  k_cache/v_cache: [B, S, N, H] with slots [0, time_step] live (the caller
  writes slot `time_step` before calling). time_step: scalar int32.
  cache_paddings: optional [B, S] f32, 1.0 = never attend this slot.
  lowering: 'auto' (Pallas on real TPU, XLA twin elsewhere), 'pallas',
  or 'xla'. interpret: forced interpret mode for the Pallas lowering
  (auto: True off-TPU). Returns [B, 1, N, H].
  """
  assert q.ndim == 4 and q.shape[1] == 1, q.shape
  assert lowering in ("auto", "pallas", "xla"), lowering
  q3 = q[:, 0]
  on_tpu = jax.default_backend() == "tpu"
  if lowering == "auto":
    lowering = "pallas" if on_tpu else "xla"
  if lowering == "xla":
    out = _XlaDecode(q3, k_cache, v_cache, jnp.asarray(time_step),
                     page_size, cache_paddings)
  else:
    if interpret is None:
      interpret = not on_tpu
    out = _PallasDecode(q3, k_cache, v_cache, jnp.asarray(time_step),
                        page_size, cache_paddings, interpret=interpret)
  return out[:, None]


def SupportedShape(max_len: int, page_size: int) -> bool:
  """Whether a [B, max_len, N, H] cache can take the paged path."""
  return page_size > 0 and max_len % page_size == 0 and max_len >= page_size


def SupportedOnTpu(page_size: int, h: int) -> bool:
  """Whether the Pallas lowering can run on real TPU hardware.

  Conservative: page_size rides the 128-lane minor axis of the pad/keep
  tiles and h the minor axis of the k/v page blocks, so both must be
  LANES-aligned for Mosaic tiling (small shapes fail to lower or pad
  severely). The XLA twin has no such constraint — off-TPU callers should
  not consult this."""
  return page_size % LANES == 0 and h % LANES == 0
