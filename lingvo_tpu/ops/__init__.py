"""TPU-native fused ops: Pallas kernels with XLA twins for CPU.

- flash_attention: blocked exact attention, fwd + bwd kernels
- flash_decode: length-aware paged single-token decode attention
- fused_xent: blockwise LM-head + cross-entropy (no [B, T, V] logits)
"""
