"""Fused blockwise LM-head + cross-entropy: no [B, T, V] logits, ever.

The training-side twin of `ops/flash_decode.py`: `SimpleFullSoftmax` /
`SharedEmbeddingSoftmaxLayer` materialize full `[B, T, V]` logits and then
cast them to f32 for log-softmax — at vocab 32k that tensor is the peak
activation of the whole train step, and it is the one activation
`RepeatedTransformerLayer`'s remat_policy can never save (the head sits
outside the scanned stack). This op streams the vocabulary in fixed-size
blocks with an online logsumexp, so neither the forward nor the backward
pass ever holds more than one `[rows, block]` logits tile.

Forward, per vocab block (one `hidden @ emb_block` einsum each):
  running max `m` / denominator `l` (the flash-attention online-softmax
  recurrence), the gathered label logit, the running sum of logits (for
  label smoothing's uniform term), and a running argmax. From those five
  scalars per row: lse = m + log(l) and
  xent = lse - (1-ls) * label_logit - (ls/V) * sum_logits,
  algebraically identical to dense `-sum(q * log_softmax(logits))` with
  q = (1-ls) * onehot + ls/V.

Backward (`jax.custom_vjp`): recomputes each block's logits and softmax
from the saved lse and accumulates
  d_logits = ct_xent * (softmax - q) [+ the lse/label/sum cotangents]
  d_hidden += d_logits @ emb_block;  d_emb_block = d_logits^T @ hidden
block-by-block, so the backward is as memory-lean as the forward. The
`logits_soft_max` tanh cap chains through as (1 - (logit/cap)^2).

Two lowerings of the same algorithm (the `flash_decode` twin-kernel
pattern), both routing per-block math through `_BlockLogits`/`_BlockStats`:

- `_XlaStats` — a `lax.scan` over vocab blocks; the reference
  implementation and the CPU path (Pallas interpret mode charges ~8-10 ms
  per grid step regardless of the compute inside).
- `_PallasStats` — a Pallas TPU kernel, grid `(row_tiles, vocab_blocks)`
  with the running stats in f32 VMEM scratch broadcast across the 128-lane
  minor dim (the `flash_attention` layout trick).

Numerics (see docs/fused_xent.md):
- block logits are computed with f32 accumulation
  (`preferred_element_type`), bias-add / tanh cap / all running stats in
  f32. Under bf16 fprop this is slightly MORE accurate than the dense
  path (which forms bf16 logits before the f32 log-softmax) — close, not
  bit-exact. With f32 params both paths agree to float tolerance.
- labels must lie in [0, V); out-of-range labels give lse (dense gives 0).
- `per_example_xent`, `label_log_prob` and `lse` carry exact gradients;
  `argmax` is integer (no tangent).
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lingvo_tpu.ops.flash_attention import (  # single source of truth
    LANES, NEG_INF, SUBLANES, _CompilerParams)

_BIG_IDX = 2 ** 30  # plain int: jnp scalars would be captured consts in Pallas


class _Cfg(NamedTuple):
  """Static (hashable) config for the custom_vjp core."""
  block_size: int
  vocab: int          # true vocab size V (blocks may overhang, masked)
  vd: bool            # weight layout: True = [V, D], False = [D, V]
  soft_cap: float     # logits_soft_max tanh cap; 0 = off
  label_smoothing: float
  lowering: str       # 'auto' | 'pallas' | 'xla'
  interpret: bool | None


class FusedXentOutput(NamedTuple):
  """All leading dims match class_ids; everything but argmax is f32."""
  per_example_xent: jax.Array   # smoothed cross-entropy
  label_log_prob: jax.Array     # log softmax(logits)[label] (no smoothing)
  lse: jax.Array                # logsumexp over the full vocab
  argmax: jax.Array             # int32 argmax over the full vocab


def _DotF32(a, b, dims):
  """dot_general with f32 accumulation, native input dtype (MXU fast path)."""
  return jax.lax.dot_general(a, b, dims, preferred_element_type=jnp.float32)


def _NumBlocks(vocab: int, block: int) -> int:
  return -(-vocab // block)


def _BlockLogits(x, w_blk, b_blk, soft_cap: float, vd: bool):
  """One block of capped logits in f32.

  x: [R, D] (fprop dtype), w_blk: [bs, D] (vd) or [D, bs] (dv),
  b_blk: [1, bs]. Returns f32 [R, bs]. Shared by both lowerings so the
  float-op sequence matches across Pallas and XLA.
  """
  if vd:
    s = _DotF32(x, w_blk, (((1,), (1,)), ((), ())))
  else:
    s = _DotF32(x, w_blk, (((1,), (0,)), ((), ())))
  s = s + b_blk.astype(jnp.float32)
  if soft_cap > 0.0:
    s = soft_cap * jnp.tanh(s / soft_cap)
  return s


def _BlockStats(s, start, labels, valid, carry):
  """Online-stats update for one vocab block.

  s: f32 [R, bs] capped logits, start: traced int32 global offset of this
  block, labels: int32 [R, 1], valid: f32 [1, bs] (0.0 marks the padded
  overhang past V) or None when the block is statically known to be fully
  in-vocab — the masking passes vanish from the compiled loop then, which
  is why configs should prefer block sizes dividing V. carry:
  (m, l, sum_logits, label_logit, amax) with float stats [R, 1], amax
  int32 [R, 1] and sum_logits None when label smoothing is off (its only
  consumer). Both lowerings call exactly this, so Pallas and XLA agree
  (to dot-blocking tolerance).
  """
  m, l, sumlog, llog, amax = carry
  s_m = s if valid is None else jnp.where(valid > 0.5, s, NEG_INF)
  m_cur = jnp.max(s_m, axis=-1, keepdims=True)            # [R, 1]
  m_new = jnp.maximum(m, m_cur)
  # All-masked-so-far rows have m_new = NEG_INF; exp(s - m_new) would turn
  # masked entries into exp(0) = 1. Same guard as flash_attention.
  m_safe = jnp.where(m_new <= NEG_INF * 0.5, 0.0, m_new)
  p = jnp.exp(s_m - m_safe)
  alpha = jnp.exp(m - m_new)                              # [R, 1]
  l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
  if sumlog is not None:
    masked = s if valid is None else jnp.where(valid > 0.5, s, 0.0)
    sumlog = sumlog + jnp.sum(masked, axis=-1, keepdims=True)
  iota = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)  # [R, bs]
  onehot = iota == (labels - start)
  llog_new = llog + jnp.sum(jnp.where(onehot, s, 0.0), axis=-1,
                            keepdims=True)
  # First-occurrence argmax (jnp.argmax tie-break): within the block the
  # smallest index attaining the max; across blocks strict > keeps the
  # earlier block on ties.
  idx_cur = start + jnp.min(
      jnp.where(s_m >= m_cur, iota, _BIG_IDX), axis=-1, keepdims=True)
  amax_new = jnp.where(m_cur > m, idx_cur, amax)
  return m_new, l_new, sumlog, llog_new, amax_new


def _InitCarry(rows: int, need_sumlog: bool):
  return (jnp.full((rows, 1), NEG_INF, jnp.float32),
          jnp.zeros((rows, 1), jnp.float32),
          jnp.zeros((rows, 1), jnp.float32) if need_sumlog else None,
          jnp.zeros((rows, 1), jnp.float32),
          jnp.zeros((rows, 1), jnp.int32))


def _PadVocab(w, b, cfg: _Cfg):
  """Pads weight/bias so the block loop is uniform; no-op (and no copy)
  when block_size divides V — configs should prefer that."""
  nb = _NumBlocks(cfg.vocab, cfg.block_size)
  v_pad = nb * cfg.block_size
  extra = v_pad - cfg.vocab
  if extra:
    w = jnp.pad(w, ((0, extra), (0, 0)) if cfg.vd else ((0, 0), (0, extra)))
    b = jnp.pad(b, (0, extra))
  return w, b, nb


def _SliceBlock(w, b, start, cfg: _Cfg):
  bs = cfg.block_size
  if cfg.vd:
    w_blk = jax.lax.dynamic_slice_in_dim(w, start, bs, axis=0)
  else:
    w_blk = jax.lax.dynamic_slice_in_dim(w, start, bs, axis=1)
  b_blk = jax.lax.dynamic_slice(b, (start,), (bs,))[None, :]
  return w_blk, b_blk


def _ValidMask(start, cfg: _Cfg):
  """None (statically) when every block is fully in-vocab: the masking
  passes disappear from the compiled per-block loop."""
  if cfg.vocab % cfg.block_size == 0:
    return None
  iota = jax.lax.broadcasted_iota(jnp.int32, (1, cfg.block_size), 1)
  return ((start + iota) < cfg.vocab).astype(jnp.float32)


# -- XLA reference lowering (the CPU path) -----------------------------------


def _XlaStats(x, w, b, labels, cfg: _Cfg):
  """x: [M, D], w: [V, D] or [D, V], b: [V], labels: int32 [M]
  -> (lse, label_logit, sum_logits, argmax), each [M]."""
  m_rows = x.shape[0]
  w_pad, b_pad, nb = _PadVocab(w, b, cfg)
  labels2 = labels[:, None]

  def _Body(carry, i):
    start = i * cfg.block_size
    w_blk, b_blk = _SliceBlock(w_pad, b_pad, start, cfg)
    s = _BlockLogits(x, w_blk, b_blk, cfg.soft_cap, cfg.vd)
    return _BlockStats(s, start, labels2, _ValidMask(start, cfg), carry), ()

  (m, l, sumlog, llog, amax), _ = jax.lax.scan(
      _Body, _InitCarry(m_rows, cfg.label_smoothing > 0.0),
      jnp.arange(nb, dtype=jnp.int32))
  lse = m[:, 0] + jnp.log(jnp.maximum(l[:, 0], 1e-37))
  return lse, llog[:, 0], None if sumlog is None else sumlog[:, 0], amax[:, 0]


# -- Pallas TPU kernel -------------------------------------------------------


def _FwdKernel(x_ref, w_ref, b_ref, lab_ref, lse_ref, llog_ref, sum_ref,
               amax_ref, m_scr, l_scr, sum_scr, llog_scr, amax_scr, *,
               cfg: _Cfg, nb: int):
  """One (row_tile, vocab_block) program step; stats carried in scratch."""
  j = pl.program_id(1)

  @pl.when(j == 0)
  def _Init():
    m_scr[:] = jnp.full_like(m_scr, NEG_INF)
    l_scr[:] = jnp.zeros_like(l_scr)
    sum_scr[:] = jnp.zeros_like(sum_scr)
    llog_scr[:] = jnp.zeros_like(llog_scr)
    amax_scr[:] = jnp.zeros_like(amax_scr)

  start = j * cfg.block_size
  need_sumlog = cfg.label_smoothing > 0.0
  s = _BlockLogits(x_ref[:], w_ref[:], b_ref[:1, :], cfg.soft_cap, cfg.vd)
  carry = (m_scr[:, :1], l_scr[:, :1],
           sum_scr[:, :1] if need_sumlog else None, llog_scr[:, :1],
           amax_scr[:, :1])
  m, l, sumlog, llog, amax = _BlockStats(
      s, start, lab_ref[:, :1], _ValidMask(start, cfg), carry)
  m_scr[:] = jnp.broadcast_to(m, m_scr.shape)
  l_scr[:] = jnp.broadcast_to(l, l_scr.shape)
  if need_sumlog:
    sum_scr[:] = jnp.broadcast_to(sumlog, sum_scr.shape)
  llog_scr[:] = jnp.broadcast_to(llog, llog_scr.shape)
  amax_scr[:] = jnp.broadcast_to(amax, amax_scr.shape)

  @pl.when(j == nb - 1)
  def _Emit():
    lse = m_scr[:, :1] + jnp.log(jnp.maximum(l_scr[:, :1], 1e-37))
    lse_ref[:] = jnp.broadcast_to(lse, lse_ref.shape)
    llog_ref[:] = llog_scr[:]
    sum_ref[:] = sum_scr[:]
    amax_ref[:] = amax_scr[:]


def _PallasStats(x, w, b, labels, cfg: _Cfg, interpret: bool):
  """Pallas lowering of _XlaStats (row-tiled grid, stats in VMEM)."""
  m_rows, d = x.shape
  rb = min(128, SUBLANES * _NumBlocks(m_rows, SUBLANES))
  m_pad = rb * _NumBlocks(m_rows, rb)
  if m_pad != m_rows:
    x = jnp.pad(x, ((0, m_pad - m_rows), (0, 0)))
    labels = jnp.pad(labels, (0, m_pad - m_rows))
  w_pad, b_pad, nb = _PadVocab(w, b, cfg)
  bs = cfg.block_size
  # Row stats / per-row ints broadcast across the 128-lane minor dim and
  # the bias across SUBLANES (same Mosaic tiling trick as flash_attention).
  lab2 = jnp.broadcast_to(labels[:, None], (m_pad, LANES))
  b2 = jnp.broadcast_to(b_pad[None, :], (SUBLANES, nb * bs))
  if cfg.vd:
    w_spec = pl.BlockSpec((bs, d), lambda mi, j: (j, 0))
  else:
    w_spec = pl.BlockSpec((d, bs), lambda mi, j: (0, j))
  out_shape = [jax.ShapeDtypeStruct((m_pad, LANES), jnp.float32)] * 3 + [
      jax.ShapeDtypeStruct((m_pad, LANES), jnp.int32)]
  stat_spec = pl.BlockSpec((rb, LANES), lambda mi, j: (mi, 0))
  kernel = functools.partial(_FwdKernel, cfg=cfg, nb=nb)
  lse, llog, sumlog, amax = pl.pallas_call(
      kernel,
      grid=(m_pad // rb, nb),
      in_specs=[
          pl.BlockSpec((rb, d), lambda mi, j: (mi, 0)),
          w_spec,
          pl.BlockSpec((SUBLANES, bs), lambda mi, j: (0, j)),
          stat_spec,
      ],
      out_specs=[stat_spec] * 4,
      out_shape=out_shape,
      scratch_shapes=[pltpu.VMEM((rb, LANES), jnp.float32)] * 4 + [
          pltpu.VMEM((rb, LANES), jnp.int32)],
      compiler_params=_CompilerParams(
          dimension_semantics=("parallel", "arbitrary")),
      interpret=interpret,
  )(x, w_pad, b2, lab2)
  return (lse[:m_rows, 0], llog[:m_rows, 0],
          sumlog[:m_rows, 0] if cfg.label_smoothing > 0.0 else None,
          amax[:m_rows, 0])


# -- custom_vjp core ---------------------------------------------------------


def _Stats(x, w, b, labels, cfg: _Cfg):
  on_tpu = jax.default_backend() == "tpu"
  lowering = cfg.lowering
  if lowering == "auto":
    lowering = "pallas" if (
        on_tpu and SupportedOnTpu(cfg.block_size, x.shape[-1])) else "xla"
  if lowering == "xla":
    return _XlaStats(x, w, b, labels, cfg)
  interpret = cfg.interpret if cfg.interpret is not None else not on_tpu
  return _PallasStats(x, w, b, labels, cfg, interpret=interpret)


def _Finish(lse, llog, sumlog, cfg: _Cfg):
  ls = cfg.label_smoothing
  if ls > 0.0:
    return lse - (1.0 - ls) * llog - (ls / cfg.vocab) * sumlog
  return lse - llog  # sumlog is statically None then


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _FusedXentCore(x, w, b, labels, cfg: _Cfg):
  lse, llog, sumlog, amax = _Stats(x, w, b, labels, cfg)
  return _Finish(lse, llog, sumlog, cfg), llog - lse, lse, amax


def _CoreFwd(x, w, b, labels, cfg: _Cfg):
  lse, llog, sumlog, amax = _Stats(x, w, b, labels, cfg)
  out = (_Finish(lse, llog, sumlog, cfg), llog - lse, lse, amax)
  return out, (x, w, b, labels, lse)


def _CoreBwd(cfg: _Cfg, res, cts):
  """Block-recompute backward: d_logits = ct_xent * (softmax - q) + the
  label_log_prob / lse cotangents, chained through the tanh cap; never
  materializes more than one [M, block] tile."""
  x, w, b, labels, lse = res
  g_xent, g_llp, g_lse, _ = cts  # argmax is integer: no tangent
  m_rows = x.shape[0]
  ls = cfg.label_smoothing
  w_pad, b_pad, nb = _PadVocab(w, b, cfg)
  labels2 = labels[:, None]
  lse2 = lse[:, None]

  def _AsCol(g):
    # Symbolic-zero cotangents arrive as float0 ad.Zero stand-ins only for
    # whole outputs jax never touched; materialize as f32 columns.
    if g is None or getattr(g, "dtype", None) == jax.dtypes.float0:
      return jnp.zeros((m_rows, 1), jnp.float32)
    return g.astype(jnp.float32)[:, None]

  g1, g2, g3 = _AsCol(g_xent), _AsCol(g_llp), _AsCol(g_lse)
  # xent = lse - (1-ls)*llog - ls/V*sumlog; llp = llog - lse.
  # d/dlogit: lse -> softmax, llog -> onehot, sumlog -> 1 (on valid
  # entries). Collect the three cotangents into per-term coefficients:
  coef_p = g1 - g2 + g3              # softmax term
  coef_oh = g2 - (1.0 - ls) * g1     # onehot term
  coef_ones = -(ls / cfg.vocab) * g1 if ls > 0.0 else None

  def _Body(dx, i):
    start = i * cfg.block_size
    w_blk, b_blk = _SliceBlock(w_pad, b_pad, start, cfg)
    s = _BlockLogits(x, w_blk, b_blk, cfg.soft_cap, cfg.vd)
    valid = _ValidMask(start, cfg)
    s_m = s if valid is None else jnp.where(valid > 0.5, s, NEG_INF)
    p = jnp.exp(s_m - lse2)
    iota = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    onehot = (iota == (labels2 - start)).astype(jnp.float32)
    # Invalid entries vanish on their own: p = exp(NEG_INF - lse) = 0 and
    # the onehot never matches past V — only the smoothing term needs the
    # explicit mask.
    dz = coef_p * p + coef_oh * onehot
    if coef_ones is not None:
      dz = dz + (coef_ones if valid is None else coef_ones * valid)
    if cfg.soft_cap > 0.0:
      dz = dz * (1.0 - (s / cfg.soft_cap) ** 2)
    # Matmuls in fprop dtype with f32 accumulation, like the dense bwd
    # under mixed precision.
    dzc = dz.astype(x.dtype)
    if cfg.vd:
      dx = dx + _DotF32(dzc, w_blk, (((1,), (0,)), ((), ())))
    else:
      dx = dx + _DotF32(dzc, w_blk, (((1,), (1,)), ((), ())))
    # Each block's weight rows get their whole gradient from this one
    # step: emit [bs, D] (both layouts) as stacked scan outputs — written
    # in place, unlike a carried [V, D] buffer, which XLA copies per step.
    dw_blk = _DotF32(dzc, x, (((0,), (0,)), ((), ())))         # [bs, D]
    return dx, (dw_blk.astype(w.dtype), jnp.sum(dz, axis=0))

  dx, (dw, db) = jax.lax.scan(_Body, jnp.zeros(x.shape, jnp.float32),
                              jnp.arange(nb, dtype=jnp.int32))
  dw = dw.reshape(-1, x.shape[1])[:cfg.vocab]                  # [V, D]
  if not cfg.vd:
    dw = dw.T
  d_labels = np.zeros(labels.shape, jax.dtypes.float0)
  return (dx.astype(x.dtype), dw,
          db.reshape(-1)[:cfg.vocab].astype(b.dtype), d_labels)


_FusedXentCore.defvjp(_CoreFwd, _CoreBwd)


# -- public entry ------------------------------------------------------------


def FusedXent(inputs, weight, class_ids, *, block_size: int, bias=None,
              logits_soft_max: float = 0.0, label_smoothing: float = 0.0,
              weight_layout: str = "vd", lowering: str = "auto",
              interpret: bool | None = None) -> FusedXentOutput:
  """Blockwise fused LM-head + softmax cross-entropy.

  inputs: [..., D] activations (fprop dtype). weight: [V, D]
  (weight_layout='vd', the tied-embedding layout) or [D, V] ('dv', the
  SimpleFullSoftmax layout). class_ids: int32 [...] in [0, V).
  bias: optional [V]. logits_soft_max: tanh cap (0 = off).
  lowering: 'auto' (Pallas on real TPU when `SupportedOnTpu`, XLA
  elsewhere), 'pallas', or 'xla'. interpret: forced interpret mode for the
  Pallas lowering (auto: True off-TPU).

  Gradients flow to inputs/weight/bias through per_example_xent,
  label_log_prob and lse. Prefer a block_size dividing V: a ragged tail
  costs one padded copy of the weight per step.
  """
  assert weight_layout in ("vd", "dv"), weight_layout
  assert lowering in ("auto", "pallas", "xla"), lowering
  vd = weight_layout == "vd"
  vocab = weight.shape[0] if vd else weight.shape[1]
  d = weight.shape[1] if vd else weight.shape[0]
  assert inputs.shape[-1] == d, (inputs.shape, weight.shape)
  assert block_size > 0
  lead = class_ids.shape
  assert inputs.shape[:-1] == lead, (inputs.shape, lead)
  x = inputs.reshape(-1, d)
  labels = class_ids.reshape(-1).astype(jnp.int32)
  b = bias if bias is not None else jnp.zeros((vocab,), weight.dtype)
  cfg = _Cfg(block_size=int(min(block_size, vocab)),
             vocab=int(vocab), vd=vd, soft_cap=float(logits_soft_max),
             label_smoothing=float(label_smoothing), lowering=lowering,
             interpret=interpret)
  xent, llp, lse, amax = _FusedXentCore(x, weight, b, labels, cfg)
  return FusedXentOutput(
      per_example_xent=xent.reshape(lead),
      label_log_prob=llp.reshape(lead),
      lse=lse.reshape(lead),
      argmax=amax.reshape(lead))


def SupportedOnTpu(block_size: int, d: int) -> bool:
  """Whether the Pallas lowering can run on real TPU hardware.

  Conservative: the vocab block rides the 128-lane minor axis of the
  logits tile and D the minor axis of the activation/weight blocks, so
  both must be LANES-aligned for Mosaic tiling. The XLA lowering has no
  such constraint — off-TPU callers should not consult this."""
  return block_size % LANES == 0 and d % LANES == 0
