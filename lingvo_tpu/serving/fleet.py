"""Disaggregated serving fleet: a router over N `ServingLoop` replicas.

`ServingFleet` composes the single-replica pieces the previous PRs
built — the continuous-batching engine (engine.py), its CoW prefix
cache (prefix_cache.py), per-replica telemetry (observe/) — into the
deployment shape that actually serves traffic:

- **Phase 1, prefix-aware routing** (`serving/router.py`): each request
  is scored against every UP replica by expected prefix-cache
  hit_tokens (a router-side shadow radix index of what was routed
  where) minus queue depth (each replica's `scheduler/queue_depth`
  snapshot — the same key a /statusz scrape spells, so the scoring path
  is transport-agnostic). Chat sessions pin to the replica holding
  their conversation prefix. Alternative policies: `round_robin` (the
  bench baseline) and `least_loaded` (observe/aggregate.LeastLoaded).
- **Phase 2, prefill/decode disaggregation**: an optional prefill
  worker group absorbs prompt processing so a long prompt never steals
  a decode replica's ragged-step token budget. A prefill worker is an
  ordinary ServingLoop with a prefix cache: the fleet submits the
  prompt there with max_new=1, the worker runs its normal chunked
  prefill and caches the prompt's full-page KV; the fleet then hands
  those pages to the decode replica page-granularly
  (`engine.AdoptPrefix`: gather out of the worker pool, optional
  transport channel, scatter into the decode pool, insert into the
  decode replica's prefix cache — int8 scale sidecars are just more
  paged leaves and ride along). The decode replica's own admission then
  sees a warm full-page prefix hit and prefills only the uncached tail,
  which is what makes disaggregated streams BYTE-IDENTICAL to unified
  ones: the same admission machinery runs, just against a pre-warmed
  cache. In-process fleets move pages with a direct device copy
  (`channel=None`); multi-host fleets lower the same gathered blocks
  through `parallel/sendrecv.SendPages` (`SendRecvChannel`).
- **Failover**: `KillReplica` (or any death the health scrape detects)
  cancels the replica's in-flight work; the fleet resubmits every
  outstanding FleetHandle — admitted or still queued — to a surviving
  replica, re-prefilling from scratch (or a warm sibling prefix, if the
  router finds one). Greedy decoding makes the regenerated stream
  byte-identical, so a `FleetHandle.Result` caller never observes the
  death. Sessions pinned to the dead replica re-pin on their next turn.
- **Hot theta swap**: `UpdateTheta` fans out to every worker; with
  `prefix_swap_persist` engines the radix trees survive the swap
  (stale-marked, refreshed in place by the next prefill of each
  prefix — prefix_cache.MarkStale), and the router's shadow index stays
  valid since it tracks WHERE prefixes live, not what theta computed
  them. Without persistence the shadow drops with the trees.

Threading: the fleet serializes its own bookkeeping (router, outstanding
tables, handoff queue) under one lock; engine locks nest inside it and
never the reverse (engine loop threads know nothing of the fleet). The
disaggregation pump is one daemon thread polling finished prefills.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import jax

from lingvo_tpu import observe
from lingvo_tpu.observe import aggregate
from lingvo_tpu.observe import schema as observe_schema
from lingvo_tpu.parallel import mesh as mesh_lib
from lingvo_tpu.parallel import sendrecv
from lingvo_tpu.serving import router as router_lib
from lingvo_tpu.serving import scheduler as scheduler_lib

_UNSET = object()


class FleetHandle:
  """Per-request handle that survives replica failover.

  Wraps the current replica's StreamHandle; the fleet rebinds it when a
  replica dies and the request is resubmitted elsewhere. `Result` is the
  contract: it returns the finished token stream no matter how many
  homes the request had (greedy regeneration is byte-identical).
  `Tokens` yields from the final result — a fleet handle does not
  live-stream, since a mid-stream rebind would have to retract tokens.
  """

  def __init__(self, fleet, prompt, max_new, session, seed, eos_id,
               priority=0, tenant=None):
    self._fleet = fleet
    self.prompt = list(prompt)
    self.max_new = max_new
    self.session = session
    self.seed = seed
    self.eos_id = eos_id
    self.priority = priority
    self.tenant = tenant
    self.replica: Optional[str] = None   # current home's label
    self.finish_reason: Optional[str] = None
    self._cond = threading.Condition()
    self._inner = None                   # current StreamHandle
    self._gen = 0                        # bumped per rebind
    self._cancelled = False

  # fleet-side
  def _Rebind(self, handle, label):
    with self._cond:
      self._inner = handle
      self.replica = label
      self._gen += 1
      self._cond.notify_all()

  def _Settled(self) -> bool:
    """Finished for good: the current home completed it (a cancelled
    inner handle is a dead replica's artifact, not completion — unless
    the user cancelled)."""
    inner = self._inner
    return (inner is not None and inner.done
            and (inner.finish_reason != "cancelled" or self._cancelled))

  # user-side
  @property
  def done(self) -> bool:
    with self._cond:
      return self._Settled()

  def Result(self, timeout: Optional[float] = None) -> list:
    """Blocks until the request finishes (across any failovers);
    returns all generated tokens."""
    deadline = None if timeout is None else time.monotonic() + timeout

    def _Left():
      if deadline is None:
        return None
      left = deadline - time.monotonic()
      if left <= 0:
        raise TimeoutError("fleet request still running")
      return left

    while True:
      with self._cond:
        while self._inner is None and not self._cancelled:
          if not self._cond.wait(timeout=_Left()):
            raise TimeoutError("fleet request still awaiting dispatch")
        if self._inner is None:   # cancelled before ever dispatched
          self.finish_reason = "cancelled"
          return []
        inner, gen = self._inner, self._gen
      toks = inner.Result(timeout=_Left())
      if inner.finish_reason != "cancelled" or self._cancelled:
        self.finish_reason = ("cancelled" if self._cancelled
                              else inner.finish_reason)
        return toks
      # the home replica died under this request: wait out the rebind
      with self._cond:
        while self._gen == gen and not self._cancelled:
          if not self._cond.wait(timeout=_Left()):
            raise TimeoutError("fleet request awaiting failover rebind")

  def Tokens(self, timeout: Optional[float] = None):
    """Yields the finished stream (see class docstring: no live
    streaming across rebinds)."""
    yield from self.Result(timeout=timeout)

  def Cancel(self) -> bool:
    return self._fleet.Cancel(self)


class SendRecvChannel:
  """Multi-host lowering of the page handoff: moves gathered page
  blocks between two workers' shards with one collective-permute
  (`parallel/sendrecv.SendPages`) over a fleet mesh axis.

  In-process fleets sharing a device pass `channel=None` to AdoptPrefix
  (direct copy); this channel exists for fleets whose prefill and
  decode groups live on different slices of one mesh — and as the
  executable spec of the wire protocol (tests run it on a host-device
  mesh). Each block is fed in replicated, permuted shard-to-shard, and
  read back from the destination shard.
  """

  def __init__(self, mesh, axis_name: str, src: int, dst: int):
    self.mesh = mesh
    self.axis_name = axis_name
    self.src = int(src)
    self.dst = int(dst)

  def Transfer(self, blocks):
    spec = jax.sharding.PartitionSpec
    pairs = [(self.src, self.dst)]

    def _Send(b):
      moved = sendrecv.SendPages(b, pairs, self.axis_name)
      return moved[None]   # per-shard leading axis: stack, then pick dst

    fn = mesh_lib.ShardMap(_Send, self.mesh, in_specs=spec(),
                           out_specs=spec(self.axis_name), check_vma=False)
    return [fn(b)[self.dst] for b in blocks]


class _Handoff:
  """One disaggregated request waiting on its prefill worker."""

  __slots__ = ("fh", "worker", "prefill_handle", "target")

  def __init__(self, fh, worker, prefill_handle, target):
    self.fh = fh
    self.worker = worker               # prefill worker label
    self.prefill_handle = prefill_handle
    self.target = target               # intended decode replica label


class ServingFleet:
  """Router + N decode replicas (+ optional prefill worker group).

  replicas: ordered {label: ServingLoop} — the DECODE group; declaration
  order is the router's deterministic tie-break order. policy: 'prefix'
  (default, PrefixRouter), 'round_robin', or 'least_loaded'.
  prefill: optional ordered {label: ServingLoop} prefill worker group
  (labels must not collide with decode labels); non-empty turns on
  disaggregation — every prompt with at least one full page prefills on
  a worker and its KV pages are handed to the decode replica before the
  decode submit. Workers need a prefix cache (it is how finished pages
  survive until the handoff); decode replicas need one to adopt into.
  channel: optional transport for the page blocks (SendRecvChannel);
  None = direct device copy. load_weight/load_key/pin_sessions:
  PrefixRouter knobs. serve_port: export fleet-level /statusz (router
  section + fleet stats) via observe/export.py.
  """

  def __init__(self, replicas, *, policy: str = "prefix", prefill=None,
               channel=None, load_weight: Optional[float] = None,
               load_key=None, pin_sessions: bool = True,
               serve_port: Optional[int] = None):
    self._engines = dict(replicas)
    self.order = list(self._engines)
    assert self.order, "a fleet needs at least one decode replica"
    if policy not in ("prefix", "round_robin", "least_loaded"):
      raise ValueError(f"unknown routing policy {policy!r}")
    self.policy = policy
    self._prefill_engines = dict(prefill or {})
    self.prefill_order = list(self._prefill_engines)
    overlap = set(self.order) & set(self.prefill_order)
    assert not overlap, f"labels serve both groups: {sorted(overlap)}"
    self.channel = channel
    page_sizes = {e.page_size for e in self._engines.values()}
    assert len(page_sizes) == 1, (
        f"replicas disagree on page_size: {sorted(page_sizes)} — prefix "
        "routing and page handoff key on page-aligned chunks")
    self.page_size = page_sizes.pop()
    if self.disaggregated:
      for lb, eng in list(self._engines.items()) + list(
          self._prefill_engines.items()):
        assert eng.prefix_cache is not None, (
            f"disaggregation requires a prefix cache on every worker "
            f"({lb} has none): workers park finished pages in theirs, "
            "decode replicas adopt into theirs")
    router_kw = {} if load_key is None else {"load_key": load_key}
    self.router = router_lib.PrefixRouter(
        self.page_size, self.order, load_weight=load_weight,
        pin_sessions=pin_sessions, **router_kw)
    self._lock = threading.RLock()
    self._up = set(self.order) | set(self.prefill_order)
    self._rr = 0
    self._outstanding = {lb: {} for lb in self.order}   # label -> {id(fh): fh}
    self._pending: list[_Handoff] = []
    self._pump: Optional[threading.Thread] = None
    self._running = False
    self._req_counter = 0
    # fleet-level counters (FLEET_STATS_KEYS; router section rides along)
    self.requests = 0
    self.failovers = 0
    self.resubmitted_requests = 0
    self.handoffs = 0
    self.handoff_pages = 0
    self.handoff_fallbacks = 0
    self.theta_swaps = 0
    self.priority_requests = 0
    self.quota_rejections = 0
    self.metrics = observe.MetricsRegistry("fleet")
    self.metrics.SectionFn("router", self.router.Stats)
    self.metrics.SectionFn("fleet", self._ScalarStats)
    self.status_server = None
    if serve_port is not None:
      self.status_server = observe.StatusServer(
          serve_port, registry=self.metrics, name="fleet",
          statusz_fn=self.Stats).Start()

  # -- properties -------------------------------------------------------------

  @property
  def disaggregated(self) -> bool:
    return bool(self._prefill_engines)

  def Engine(self, label: str):
    """The ServingLoop behind a label (either group)."""
    return self._engines.get(label) or self._prefill_engines[label]

  # -- lifecycle --------------------------------------------------------------

  def Start(self):
    with self._lock:
      if self._running:
        return self
      self._running = True
    for eng in list(self._engines.values()) + list(
        self._prefill_engines.values()):
      eng.Start()
    if self.disaggregated:
      self._pump = threading.Thread(target=self._PumpLoop, daemon=True,
                                    name="fleet-handoff-pump")
      self._pump.start()
    return self

  def Stop(self, drain: bool = True, timeout: float = 60.0):
    with self._lock:
      if not self._running:
        return
      if drain:
        # flush pending handoffs so their decode submits exist to drain
        deadline = time.monotonic() + timeout
        while self._pending and time.monotonic() < deadline:
          self._lock.release()
          try:
            time.sleep(0.005)
          finally:
            self._lock.acquire()
      self._running = False
    if self._pump is not None:
      self._pump.join(timeout=timeout)
      self._pump = None
    for eng in list(self._engines.values()) + list(
        self._prefill_engines.values()):
      if eng._running:   # a killed replica is already down
        eng.Stop(drain=drain, timeout=timeout)
    if self.status_server is not None:
      self.status_server.Stop()
      self.status_server = None

  # -- routing ----------------------------------------------------------------

  def _Snapshots(self) -> dict:
    """{label: registry snapshot or None (DOWN)} for the decode group —
    the router's scoring input; in-process twin of a /statusz sweep."""
    out = {}
    for lb in self.order:
      out[lb] = (self._engines[lb].metrics.Snapshot()
                 if lb in self._up else None)
    return out

  def _Pick(self, prompt, session, priority: int = 0) -> str:
    snapshots = self._Snapshots()
    if self.policy == "prefix":
      return self.router.Route(prompt, snapshots, session=session,
                               priority=priority)
    live = [lb for lb in self.order if snapshots.get(lb) is not None]
    if not live:
      raise RuntimeError(f"no UP replica among {self.order}")
    if self.policy == "round_robin":
      lb = live[self._rr % len(live)]
      self._rr += 1
      return lb
    docs = {lb: {"snapshot": snapshots[lb]} for lb in live}
    return aggregate.LeastLoaded(docs, order=self.order) or live[0]

  def _PickPrefillWorker(self, prompt) -> Optional[str]:
    live = [lb for lb in self.prefill_order if lb in self._up]
    if not live:
      return None
    docs = {lb: {"snapshot": self._prefill_engines[lb].metrics.Snapshot()}
            for lb in live}
    return aggregate.LeastLoaded(docs, order=self.prefill_order) or live[0]

  # -- submission -------------------------------------------------------------

  def Submit(self, prompt, max_new_tokens: Optional[int] = None,
             session=None, seed: Optional[int] = None,
             eos_id=_UNSET, priority: int = 0, tenant=None) -> FleetHandle:
    """Routes and queues one request; returns its fleet handle.

    session: opaque chat-session key — requests sharing it pin to one
    replica (its cache holds the conversation prefix). seed: per-request
    sampling seed, defaulted to a FLEET-global counter so a request
    resubmitted (failover) or replayed on another replica draws the
    same stream at temperature > 0.
    priority/tenant: SLO class + quota label, forwarded to the replica
    engine (meaningful only when replicas run scheduler_mode='priority').
    A priority > 0 request routes on class-aware load ("scheduler/
    queue_depth_high") rather than raw queue depth. Quotas are enforced
    PER REPLICA by the engine's scheduler (a fleet of N replicas admits
    ~N x the per-replica rate; scheduler.QuotaExceeded propagates from
    here when the routed replica's bucket is dry)."""
    with self._lock:
      assert self._running, "Submit before Start()"
      self._req_counter += 1
      self.requests += 1
      if priority > 0:
        self.priority_requests += 1
      if seed is None:
        seed = self._req_counter
      fh = FleetHandle(self, prompt, max_new_tokens, session, seed, eos_id,
                       priority=priority, tenant=tenant)
      if self.disaggregated and len(prompt) >= self.page_size:
        if self.policy == "prefix":
          # route WITHOUT tagging the shadow: "warm" must read whether
          # some EARLIER request already put the full prefix there
          label = self.router.Route(prompt, self._Snapshots(),
                                    session=session, note=False,
                                    priority=priority)
          warm = self.router.shadow.ExpectedHitTokens(label, prompt)
          self.router.shadow.NoteRouted(label, prompt)
        else:
          label = self._Pick(prompt, session, priority=priority)
          warm = 0
        full = (len(prompt) // self.page_size) * self.page_size
        if warm < min(full, len(prompt) - 1):
          worker = self._PickPrefillWorker(prompt)
          if worker is not None:
            ph = self._prefill_engines[worker].Submit(
                list(prompt), max_new_tokens=1, seed=seed)
            self._pending.append(_Handoff(fh, worker, ph, label))
            return fh
      else:
        label = self._Pick(prompt, session, priority=priority)
      self._Dispatch(fh, label)
    return fh

  def _Dispatch(self, fh: FleetHandle, label: str):
    """Submits to a decode replica and binds (caller holds the lock).

    A dry per-replica quota bucket raises scheduler.QuotaExceeded out of
    the user's Submit; on RE-dispatch (failover, handoff landing) the
    original admission already paid, so the retry goes quota-exempt —
    a replica death must never turn into a quota rejection."""
    eng = self._engines[label]
    kwargs = {} if fh.eos_id is _UNSET else {"eos_id": fh.eos_id}
    try:
      h = eng.Submit(list(fh.prompt), max_new_tokens=fh.max_new,
                     seed=fh.seed, priority=fh.priority, tenant=fh.tenant,
                     **kwargs)
    except scheduler_lib.QuotaExceeded:
      if fh._inner is not None:   # re-dispatch: quota was already paid
        h = eng.Submit(list(fh.prompt), max_new_tokens=fh.max_new,
                       seed=fh.seed, priority=fh.priority, **kwargs)
      else:
        self.quota_rejections += 1
        raise
    self._outstanding[label][id(fh)] = fh
    fh._Rebind(h, label)

  def Cancel(self, fh: FleetHandle) -> bool:
    with self._lock:
      with fh._cond:
        fh._cancelled = True
        inner, label = fh._inner, fh.replica
        fh._cond.notify_all()
      for hd in self._pending:
        if hd.fh is fh and not hd.prefill_handle.done:
          hd.prefill_handle.Cancel()   # don't waste worker prefill budget
      self._pending = [hd for hd in self._pending if hd.fh is not fh]
      if label is not None:
        self._outstanding.get(label, {}).pop(id(fh), None)
      if inner is not None and not inner.done:
        return inner.Cancel()
      return inner is None

  # -- disaggregation pump ----------------------------------------------------

  def _PumpLoop(self):
    while True:
      with self._lock:
        if not self._running:
          return
        moved = self._PumpOnce()
      if not moved:
        time.sleep(0.002)

  def _PumpOnce(self) -> int:
    """Lands every finished prefill: adopt pages into the decode
    replica, then dispatch the decode submit (caller holds the lock).
    Returns handoffs landed."""
    still, moved = [], 0
    for hd in self._pending:
      if not hd.prefill_handle.done:
        still.append(hd)
        continue
      moved += 1
      target = hd.target
      if target not in self._up:   # decode home died while prefilling
        target = self._Pick(hd.fh.prompt, hd.fh.session)
      if hd.prefill_handle.finish_reason == "cancelled":
        # the prefill worker died mid-prompt: decode prefills cold
        self.handoff_fallbacks += 1
      else:
        adopted = self._engines[target].AdoptPrefix(
            hd.fh.prompt, self._prefill_engines[hd.worker],
            channel=self.channel)
        self.handoffs += 1
        self.handoff_pages += adopted // self.page_size
      self._Dispatch(hd.fh, target)
    self._pending = still
    return moved

  # -- failover ---------------------------------------------------------------

  def KillReplica(self, label: str, timeout: float = 30.0):
    """Simulates (or administratively performs) a replica death: stops
    the engine without draining — cancelling everything it held — then
    resubmits every outstanding fleet request, admitted or still queued,
    to a surviving replica. FleetHandle callers never notice beyond
    latency: greedy regeneration is byte-identical."""
    with self._lock:
      if label not in self._up:
        return
      self._up.discard(label)
      self.failovers += 1
      if label in self._engines:
        self.router.OnReplicaDown(label)
    eng = self.Engine(label)
    eng.Stop(drain=False, timeout=timeout)
    with self._lock:
      for fh in list(self._outstanding.get(label, {}).values()):
        self._outstanding[label].pop(id(fh), None)
        if fh._Settled():
          continue   # finished before the axe fell: stream already out
        new_label = self._Pick(fh.prompt, fh.session)
        self._Dispatch(fh, new_label)
        self.resubmitted_requests += 1
      # prefill handoffs on a dead worker fall back in the pump (their
      # handles read finish_reason == "cancelled"); dead decode targets
      # re-pick there too. Nothing else to do here.

  # -- theta swap -------------------------------------------------------------

  def UpdateTheta(self, theta, persist_prefix: Optional[bool] = None):
    """Hot-swaps every worker's checkpoint mid-traffic. persist_prefix
    None defers to each engine's own prefix_swap_persist knob; the
    router's shadow index survives exactly when the replicas' trees do
    (see PrefixRouter.OnThetaSwap)."""
    engines = list(self._engines.values()) + list(
        self._prefill_engines.values())
    with self._lock:
      for eng in engines:
        eng.UpdateTheta(theta, persist_prefix=persist_prefix)
      persisted = (all(e.prefix_swap_persist for e in engines)
                   if persist_prefix is None else bool(persist_prefix))
      self.router.OnThetaSwap(persisted)
      self.theta_swaps += 1

  # -- introspection ----------------------------------------------------------

  def _ScalarStats(self) -> dict:
    with self._lock:
      up = len([lb for lb in self.order if lb in self._up])
      return {
          "policy": self.policy,
          "disaggregated": self.disaggregated,
          "replicas": len(self.order),
          "replicas_up": up,
          "replicas_down": len(self.order) - up,
          "requests": self.requests,
          "failovers": self.failovers,
          "resubmitted_requests": self.resubmitted_requests,
          "handoffs": self.handoffs,
          "handoff_pages": self.handoff_pages,
          "handoff_fallbacks": self.handoff_fallbacks,
          "theta_swaps": self.theta_swaps,
          "priority_requests": self.priority_requests,
          "quota_rejections": self.quota_rejections,
      }

  def Stats(self) -> dict:
    """Fleet-level stats (observe/schema.py FLEET_STATS_KEYS): scalar
    counters plus the nested `router` section. Per-replica engine stats
    stay on the replicas' own /statusz — the fleet view is about
    routing, failover and handoff, not a re-export of N engines."""
    with self._lock:
      stats = self._ScalarStats()
      stats["router"] = self.router.Stats()
    assert set(stats) == observe_schema.FLEET_STATS_KEYS, sorted(stats)
    return stats
