"""KV block allocator: host-side ownership of the global page pool.

The device side of paged serving is a dumb `[num_pages, page_size, N, H]`
pool (attention.InitPagedStates); everything that makes it a cache — which
pages belong to which sequence, which are free — lives here, in plain
Python on the host, updated between device steps. That split keeps every
compiled program shape-static: admitting or evicting a sequence only
rewrites small int32 block tables, never reshapes device buffers.

Allocation policy: a min-heap free list. Always handing out the
lowest-numbered free page keeps the live set packed toward the low end of
the pool — eviction "defragments" by construction (freed high pages sink
to the back of the heap and are reused last), so a long-running server's
working set stays dense without ever copying K/V between pages.

O(1)-state mixers (core/ssm.py) need a second, much simpler resource:
`StateSlotPool`. An SSM layer's decode state is a fixed [B, N, H, S]
array — one constant-size matrix per batch row, no growth with sequence
length, nothing to page. Its unit of ownership is the decode SLOT (batch
row) itself, which the scheduler already assigns; the pool just records
which sequence holds which slot and prices it in bytes so admission
accounting and Stats() can compare KV-page HBM against flat mixer-state
HBM (the ISSUE's more-concurrent-requests-at-fixed-HBM criterion).
"""

from __future__ import annotations

import heapq


class OutOfPages(Exception):
  """Raised by Allocate when the pool cannot satisfy the request."""


class PageAllocator:
  """Owns [0, num_pages) of the device pool; sequences hold disjoint sets.

  NOT thread-safe on its own — the serving engine serializes all calls
  under its scheduler lock. The trash page the engine appends to the
  device pool is outside [0, num_pages) and never managed here.
  """

  def __init__(self, num_pages: int, page_size: int, page_bytes: int = 0):
    assert num_pages > 0 and page_size > 0, (num_pages, page_size)
    self.num_pages = num_pages
    self.page_size = page_size
    # device bytes one logical page costs across EVERY layer's pool, scale
    # sidecars included (metadata only — the engine prices it from its KV
    # census so quantized pools report honest HBM numbers)
    self.page_bytes = int(page_bytes)
    self._free = list(range(num_pages))  # already a valid min-heap
    self._owned: dict[object, list[int]] = {}
    self.peak_in_use = 0
    # speculative-decoding rollback accounting: token slots that were
    # written by a verify step and then rejected. Rollback is pure cursor
    # arithmetic — the scheduler simply doesn't advance `seq.pos` past the
    # accepted prefix, and the next cycle re-writes the same slots (reads
    # are bounded by q_pos + in_len, so stale K/V past the cursor is never
    # attended). No page ever moves; this counter is the only trace.
    self.rolled_back_tokens = 0

  # -- queries ---------------------------------------------------------------

  @property
  def num_free(self) -> int:
    return len(self._free)

  @property
  def num_in_use(self) -> int:
    return self.num_pages - len(self._free)

  def PagesFor(self, num_tokens: int) -> int:
    """Pages needed to hold num_tokens logical slots."""
    return -(-num_tokens // self.page_size)

  def CanAllocate(self, n: int) -> bool:
    return n <= len(self._free)

  def PagesOf(self, seq_id) -> list[int]:
    """The sequence's pages in logical order (index i = logical page i)."""
    return list(self._owned[seq_id])

  def Stats(self) -> dict:
    out = {
        "num_pages": self.num_pages,
        "page_size": self.page_size,
        "in_use": self.num_in_use,
        "free": self.num_free,
        "utilization": self.num_in_use / self.num_pages,
        "peak_in_use": self.peak_in_use,
        "num_sequences": len(self._owned),
        "rolled_back_tokens": self.rolled_back_tokens,
    }
    if self.page_bytes:
      out["page_bytes"] = self.page_bytes
      out["pool_bytes"] = self.page_bytes * self.num_pages
    return out

  # -- mutations -------------------------------------------------------------

  def Allocate(self, seq_id, n: int) -> list[int]:
    """Grants n MORE pages to seq_id (appended to its logical order).

    All-or-nothing: raises OutOfPages without side effects if fewer than n
    pages are free — the scheduler checks CanAllocate first and queues the
    request instead of admitting it."""
    if n > len(self._free):
      raise OutOfPages(f"need {n} pages, {len(self._free)} free")
    got = [heapq.heappop(self._free) for _ in range(n)]
    self._owned.setdefault(seq_id, []).extend(got)
    self.peak_in_use = max(self.peak_in_use, self.num_in_use)
    return got

  def NoteRollback(self, num_tokens: int):
    """Records num_tokens rejected verify-step writes (cursor rollback)."""
    assert num_tokens >= 0, num_tokens
    self.rolled_back_tokens += int(num_tokens)

  def Free(self, seq_id) -> int:
    """Returns every page owned by seq_id to the pool; returns the count.

    Idempotent: freeing an unknown/already-freed id is a no-op (eviction
    and cancellation can race to the same sequence at a step boundary)."""
    pages = self._owned.pop(seq_id, [])
    for pg in pages:
      heapq.heappush(self._free, pg)
    return len(pages)


class StateSlotPool:
  """Ownership of O(1) mixer-state slots (one per decode batch row).

  Device-side the state is a `[num_slots, ...]` array per SSM layer
  (ssm.GatedSSMLayer.InitPagedStates); row i belongs to whichever
  sequence the scheduler placed in decode slot i, and is reset device-
  side on that sequence's first step (q_pos == 0), so acquisition never
  touches the device. Like PageAllocator this is host bookkeeping only,
  serialized by the engine's scheduler lock.

  bytes_per_slot: per-sequence mixer-state HBM across ALL SSM layers
  (sum of StateBytesPerSlot) — constant in sequence length, which is the
  whole point; Stats() exposes it next to the allocator's page numbers.
  """

  def __init__(self, num_slots: int, bytes_per_slot: int):
    assert num_slots > 0 and bytes_per_slot >= 0, (num_slots, bytes_per_slot)
    self.num_slots = num_slots
    self.bytes_per_slot = int(bytes_per_slot)
    self._slot_of: dict[object, int] = {}
    self._owner: dict[int, object] = {}
    self.peak_in_use = 0

  @property
  def num_in_use(self) -> int:
    return len(self._slot_of)

  @property
  def num_free(self) -> int:
    return self.num_slots - len(self._slot_of)

  def Acquire(self, seq_id, slot: int):
    """Binds seq_id to decode slot `slot` (must be free)."""
    assert 0 <= slot < self.num_slots, (slot, self.num_slots)
    assert slot not in self._owner, (
        f"slot {slot} already owned by {self._owner[slot]!r}")
    assert seq_id not in self._slot_of, seq_id
    self._slot_of[seq_id] = slot
    self._owner[slot] = seq_id
    self.peak_in_use = max(self.peak_in_use, self.num_in_use)

  def Release(self, seq_id) -> bool:
    """Unbinds seq_id's slot. Idempotent, mirroring PageAllocator.Free."""
    slot = self._slot_of.pop(seq_id, None)
    if slot is None:
      return False
    del self._owner[slot]
    return True

  def SlotOf(self, seq_id):
    return self._slot_of.get(seq_id)

  def Stats(self) -> dict:
    return {
        "num_slots": self.num_slots,
        "bytes_per_slot": self.bytes_per_slot,
        "in_use": self.num_in_use,
        "free": self.num_free,
        "peak_in_use": self.peak_in_use,
        "state_bytes_in_use": self.num_in_use * self.bytes_per_slot,
    }
