"""KV block allocator: host-side ownership of the global page pool.

The device side of paged serving is a dumb `[num_pages, page_size, N, H]`
pool (attention.InitPagedStates); everything that makes it a cache — which
pages belong to which sequence, which are free — lives here, in plain
Python on the host, updated between device steps. That split keeps every
compiled program shape-static: admitting or evicting a sequence only
rewrites small int32 block tables, never reshapes device buffers.

Allocation policy: a min-heap free list. Always handing out the
lowest-numbered free page keeps the live set packed toward the low end of
the pool — eviction "defragments" by construction (freed high pages sink
to the back of the heap and are reused last), so a long-running server's
working set stays dense without ever copying K/V between pages.

Prefix sharing (serving/prefix_cache.py) adds REFCOUNTS on top: a page
may be referenced by several sequences (a shared system-prompt prefix)
and/or by the prefix cache itself. `Allocate` grants exclusive pages
(refcount 1); `Share` lets a second owner borrow pages already resident;
`Retain`/`Release` are the cache's ownerless references. `Free` only
DECREMENTS — a page returns to the free heap exactly when its last
reference drops, which preserves both standing contracts: `Allocate`
stays all-or-nothing over the free heap, and reclaimed pages re-enter
the same min-heap (lowest-first defrag by construction). `CopyOnWrite`
is the write-hazard escape hatch: before a sequence writes into a page
it does not exclusively own, the scheduler swaps in a fresh private page
(the engine copies the bytes device-side); `AssertExclusive` makes any
missed hazard — including a speculative-decoding rollback rewrite — a
loud failure instead of silent cross-request corruption.

O(1)-state mixers (core/ssm.py) need a second, much simpler resource:
`StateSlotPool`. An SSM layer's decode state is a fixed [B, N, H, S]
array — one constant-size matrix per batch row, no growth with sequence
length, nothing to page. Its unit of ownership is the decode SLOT (batch
row) itself, which the scheduler already assigns; the pool just records
which sequence holds which slot and prices it in bytes so admission
accounting and Stats() can compare KV-page HBM against flat mixer-state
HBM (the ISSUE's more-concurrent-requests-at-fixed-HBM criterion).
"""

from __future__ import annotations

import heapq


class OutOfPages(Exception):
  """Raised by Allocate when the pool cannot satisfy the request."""


# Logical-slot sentinel for a page spilled to the host tier: the owner
# keeps its position in the logical order (so restore scatters the saved
# bytes back to the SAME logical slot) but holds no device page there.
HOLE = -1


class PageAllocator:
  """Owns [0, num_pages) of the device pool; sequences hold disjoint sets.

  NOT thread-safe on its own — the serving engine serializes all calls
  under its scheduler lock. The trash page the engine appends to the
  device pool is outside [0, num_pages) and never managed here.
  """

  def __init__(self, num_pages: int, page_size: int, page_bytes: int = 0):
    assert num_pages > 0 and page_size > 0, (num_pages, page_size)
    self.num_pages = num_pages
    self.page_size = page_size
    # device bytes one logical page costs across EVERY layer's pool, scale
    # sidecars included (metadata only — the engine prices it from its KV
    # census so quantized pools report honest HBM numbers)
    self.page_bytes = int(page_bytes)
    self._free = list(range(num_pages))  # already a valid min-heap
    self._owned: dict[object, list[int]] = {}
    # page -> reference count (sequence owners + cache retains). Absent
    # means free. Pages return to the heap only when this hits 0.
    self._ref: dict[int, int] = {}
    self.peak_in_use = 0
    # speculative-decoding rollback accounting: token slots that were
    # written by a verify step and then rejected. Rollback is pure cursor
    # arithmetic — the scheduler simply doesn't advance `seq.pos` past the
    # accepted prefix, and the next cycle re-writes the same slots (reads
    # are bounded by q_pos + in_len, so stale K/V past the cursor is never
    # attended). No page ever moves; this counter is the only trace.
    self.rolled_back_tokens = 0

  # -- queries ---------------------------------------------------------------

  @property
  def num_free(self) -> int:
    return len(self._free)

  @property
  def num_in_use(self) -> int:
    return self.num_pages - len(self._free)

  def PagesFor(self, num_tokens: int) -> int:
    """Pages needed to hold num_tokens logical slots."""
    return -(-num_tokens // self.page_size)

  def CanAllocate(self, n: int) -> bool:
    return n <= len(self._free)

  def PagesOf(self, seq_id) -> list[int]:
    """The sequence's pages in logical order (index i = logical page i).
    Spilled logical slots read HOLE until FillHoles re-backs them."""
    return list(self._owned[seq_id])

  def HoleCount(self, seq_id) -> int:
    """Logical slots seq_id holds that were spilled (no device page)."""
    return sum(1 for pg in self._owned.get(seq_id, ()) if pg == HOLE)

  def PrivatePages(self, seq_id, num_tokens: int) -> list[tuple[int, int]]:
    """(logical_idx, page) pairs seq_id exclusively owns among the pages
    covering its first num_tokens logical slots — the pages whose BYTES a
    preemption must save to the host tier. Shared pages (a borrowed or
    inserted prefix) stay device-resident across a spill: the sequence's
    reference pins them, so they restore by simply still being there.
    Trailing private pages past the written cursor hold no data and are
    freed without saving."""
    data = self.PagesFor(num_tokens)
    out = []
    for idx, pg in enumerate(self._owned.get(seq_id, ())):
      if idx >= data:
        break
      if pg != HOLE and self._ref.get(pg, 0) == 1:
        out.append((idx, pg))
    return out

  def RefCount(self, page: int) -> int:
    """References on `page` (0 = free)."""
    return self._ref.get(page, 0)

  @property
  def shared_pages(self) -> int:
    """Pages currently referenced more than once (the sharing win)."""
    return sum(1 for r in self._ref.values() if r >= 2)

  def AssertExclusive(self, seq_id, start_token: int, num_tokens: int):
    """Write-hazard guard: every page covering logical token slots
    [start_token, start_token + num_tokens) must be referenced ONLY by
    seq_id. A device write (including a speculative verify step whose
    rejected tail will be re-written after rollback) to a page another
    sequence or the prefix cache references would corrupt their streams;
    copy-on-write at admission is supposed to make this impossible."""
    if num_tokens <= 0:
      return
    pages = self._owned[seq_id]
    lo = start_token // self.page_size
    hi = (start_token + num_tokens - 1) // self.page_size
    for idx in range(lo, min(hi, len(pages) - 1) + 1):
      pg = pages[idx]
      assert pg != HOLE, (
          f"seq {seq_id!r} writing tokens [{start_token}, "
          f"{start_token + num_tokens}) through spilled logical page {idx} "
          "— FillHoles must re-back a restored sequence before any step")
      assert self._ref.get(pg, 0) == 1, (
          f"seq {seq_id!r} writing tokens [{start_token}, "
          f"{start_token + num_tokens}) would touch page {pg} (logical "
          f"{idx}) with refcount {self._ref.get(pg, 0)} — shared pages "
          f"must be copy-on-write'd before any write")

  def Stats(self) -> dict:
    out = {
        "num_pages": self.num_pages,
        "page_size": self.page_size,
        "in_use": self.num_in_use,
        "free": self.num_free,
        "utilization": self.num_in_use / self.num_pages,
        "peak_in_use": self.peak_in_use,
        "num_sequences": len(self._owned),
        "rolled_back_tokens": self.rolled_back_tokens,
        "shared_pages": self.shared_pages,
    }
    if self.page_bytes:
      out["page_bytes"] = self.page_bytes
      out["pool_bytes"] = self.page_bytes * self.num_pages
    return out

  # -- mutations -------------------------------------------------------------

  def Allocate(self, seq_id, n: int) -> list[int]:
    """Grants n MORE pages to seq_id (appended to its logical order).

    All-or-nothing: raises OutOfPages without side effects if fewer than n
    pages are free — the scheduler checks CanAllocate first and queues the
    request instead of admitting it."""
    if n > len(self._free):
      raise OutOfPages(f"need {n} pages, {len(self._free)} free")
    got = [heapq.heappop(self._free) for _ in range(n)]
    for pg in got:
      self._ref[pg] = 1
    self._owned.setdefault(seq_id, []).extend(got)
    self.peak_in_use = max(self.peak_in_use, self.num_in_use)
    return got

  def Share(self, seq_id, pages: list[int]):
    """Appends already-resident `pages` to seq_id's logical order, adding
    one reference each. The free heap is untouched — sharing is how a
    request's footprint stops counting against the pool."""
    if not pages:
      return
    for pg in pages:
      assert self._ref.get(pg, 0) >= 1, f"cannot share free page {pg}"
      self._ref[pg] += 1
    self._owned.setdefault(seq_id, []).extend(pages)

  def Retain(self, page: int):
    """Adds an ownerless reference (the prefix cache holding a page alive
    past its writer's retirement)."""
    assert self._ref.get(page, 0) >= 1, f"cannot retain free page {page}"
    self._ref[page] += 1

  def Release(self, page: int):
    """Drops one ownerless reference (cache eviction/invalidation)."""
    self._DecRef(page)

  def CopyOnWrite(self, seq_id, logical_idx: int):
    """Replaces seq_id's shared logical page with a fresh private one.

    Returns (old_page, new_page) for the engine to copy device-side, or
    None when the page is already exclusive. All-or-nothing like Allocate:
    raises OutOfPages without side effects when the pool is empty."""
    pages = self._owned[seq_id]
    old = pages[logical_idx]
    if self._ref.get(old, 0) == 1:
      return None
    (new,) = self.Allocate(seq_id, 1)
    self._owned[seq_id].pop()        # Allocate appended; splice in place
    pages[logical_idx] = new
    self._DecRef(old)
    return (old, new)

  def SpillPrivate(self, seq_id) -> int:
    """Preemption, device half: releases every page seq_id exclusively
    owns, leaving HOLE sentinels at their logical slots; returns the
    count released. Shared pages (refcount >= 2 — a borrowed prefix, or
    pages the prefix cache retained) KEEP their reference: they stay
    device-resident and un-evictable, which is what makes restore of a
    prefix-sharing sequence correct without re-spilling shared bytes.
    The caller must have gathered the private DATA pages' bytes
    (PrivatePages) to the host tier first — this only drops ownership."""
    pages = self._owned.get(seq_id)
    assert pages is not None, f"spill of unknown sequence {seq_id!r}"
    freed = 0
    for idx, pg in enumerate(pages):
      if pg != HOLE and self._ref.get(pg, 0) == 1:
        self._DecRef(pg)
        pages[idx] = HOLE
        freed += 1
    return freed

  def FillHoles(self, seq_id) -> list[tuple[int, int]]:
    """Restore, device half: re-backs every HOLE with a fresh exclusive
    page, all-or-nothing (raises OutOfPages with no side effects when
    the pool cannot cover them — the scheduler keeps the sequence
    parked). Returns (logical_idx, page) pairs so the engine can scatter
    the host-tier bytes back into exactly the logical slots they left."""
    pages = self._owned.get(seq_id)
    assert pages is not None, f"restore of unknown sequence {seq_id!r}"
    holes = [idx for idx, pg in enumerate(pages) if pg == HOLE]
    if len(holes) > len(self._free):
      raise OutOfPages(
          f"restore needs {len(holes)} pages, {len(self._free)} free")
    got = [heapq.heappop(self._free) for _ in range(len(holes))]
    out = []
    for idx, pg in zip(holes, got):
      self._ref[pg] = 1
      pages[idx] = pg
      out.append((idx, pg))
    self.peak_in_use = max(self.peak_in_use, self.num_in_use)
    return out

  def NoteRollback(self, num_tokens: int):
    """Records num_tokens rejected verify-step writes (cursor rollback)."""
    assert num_tokens >= 0, num_tokens
    self.rolled_back_tokens += int(num_tokens)

  def _DecRef(self, page: int):
    r = self._ref.get(page, 0)
    assert r >= 1, f"double free of page {page}"
    if r == 1:
      del self._ref[page]
      heapq.heappush(self._free, page)
    else:
      self._ref[page] = r - 1

  def Free(self, seq_id) -> int:
    """Drops seq_id's reference on every page it holds; returns the count
    of pages released (pages shared with other owners survive — they
    return to the pool when the LAST reference drops).

    Idempotent: freeing an unknown/already-freed id is a no-op (eviction
    and cancellation can race to the same sequence at a step boundary).
    HOLE slots (spilled pages) hold no device reference to drop."""
    pages = self._owned.pop(seq_id, [])
    n = 0
    for pg in pages:
      if pg != HOLE:
        self._DecRef(pg)
        n += 1
    return n


class StateSlotPool:
  """Ownership of O(1) mixer-state slots (one per decode batch row).

  Device-side the state is a `[num_slots, ...]` array per SSM layer
  (ssm.GatedSSMLayer.InitPagedStates); row i belongs to whichever
  sequence the scheduler placed in decode slot i, and is reset device-
  side on that sequence's first step (q_pos == 0), so acquisition never
  touches the device. Like PageAllocator this is host bookkeeping only,
  serialized by the engine's scheduler lock.

  bytes_per_slot: per-sequence mixer-state HBM across ALL SSM layers
  (sum of StateBytesPerSlot) — constant in sequence length, which is the
  whole point; Stats() exposes it next to the allocator's page numbers.
  """

  def __init__(self, num_slots: int, bytes_per_slot: int):
    assert num_slots > 0 and bytes_per_slot >= 0, (num_slots, bytes_per_slot)
    self.num_slots = num_slots
    self.bytes_per_slot = int(bytes_per_slot)
    self._slot_of: dict[object, int] = {}
    self._owner: dict[int, object] = {}
    self.peak_in_use = 0

  @property
  def num_in_use(self) -> int:
    return len(self._slot_of)

  @property
  def num_free(self) -> int:
    return self.num_slots - len(self._slot_of)

  def Acquire(self, seq_id, slot: int):
    """Binds seq_id to decode slot `slot` (must be free)."""
    assert 0 <= slot < self.num_slots, (slot, self.num_slots)
    assert slot not in self._owner, (
        f"slot {slot} already owned by {self._owner[slot]!r}")
    assert seq_id not in self._slot_of, seq_id
    self._slot_of[seq_id] = slot
    self._owner[slot] = seq_id
    self.peak_in_use = max(self.peak_in_use, self.num_in_use)

  def Release(self, seq_id) -> bool:
    """Unbinds seq_id's slot. Idempotent, mirroring PageAllocator.Free."""
    slot = self._slot_of.pop(seq_id, None)
    if slot is None:
      return False
    del self._owner[slot]
    return True

  def SlotOf(self, seq_id):
    return self._slot_of.get(seq_id)

  def Stats(self) -> dict:
    return {
        "num_slots": self.num_slots,
        "bytes_per_slot": self.bytes_per_slot,
        "in_use": self.num_in_use,
        "free": self.num_free,
        "peak_in_use": self.peak_in_use,
        "state_bytes_in_use": self.num_in_use * self.bytes_per_slot,
    }


class SpillEntry:
  """One preempted sequence's host-tier state.

  logical_idxs: which logical pages the saved blocks re-occupy at
  restore (only the PRIVATE pages that held written data — shared
  prefix pages never leave the device, and trailing reserved pages
  hold no data worth moving). blocks: per-paged-leaf host arrays, each
  [len(logical_idxs), ...] in logical_idxs order — int8 K/V pools and
  their f32 scale sidecars are separate leaves and ride along
  unchanged; None on device-free schedulers (unit tests). state_row:
  per-slot-leaf host arrays of the sequence's O(1) mixer state row
  (None for attention-only stacks).
  """

  __slots__ = ("logical_idxs", "blocks", "state_row", "nbytes")

  def __init__(self, logical_idxs, blocks, state_row):
    self.logical_idxs = list(logical_idxs)
    self.blocks = blocks
    self.state_row = state_row
    n = 0
    for arr in (blocks or []):
      n += getattr(arr, "nbytes", 0)
    for arr in (state_row or []):
      n += getattr(arr, "nbytes", 0)
    self.nbytes = int(n)


class HostPageStore:
  """The host memory tier preempted KV pages and SSM state spill to.

  Pure host bookkeeping (numpy blocks in a dict), serialized by the
  engine lock like the allocator. The contract that makes preemption
  invisible to the stream: Put saves the exact device bytes (the engine
  gathers pages through the same jitted page IO the fleet handoff
  uses, so the round trip is a bitwise memcpy), Pop returns them once
  for the restore scatter, Drop discards a cancelled sequence's entry.
  Counters feed scheduler Stats(): host_bytes is the live tier size,
  spilled/restored pages are monotonic totals.
  """

  def __init__(self):
    self._entries: dict = {}
    self.spilled_pages = 0
    self.restored_pages = 0
    self.host_bytes = 0
    self.peak_host_bytes = 0

  def __len__(self) -> int:
    return len(self._entries)

  def __contains__(self, seq_id) -> bool:
    return seq_id in self._entries

  def Put(self, seq_id, logical_idxs, blocks=None, state_row=None):
    assert seq_id not in self._entries, f"double spill of {seq_id!r}"
    entry = SpillEntry(logical_idxs, blocks, state_row)
    self._entries[seq_id] = entry
    self.spilled_pages += len(entry.logical_idxs)
    self.host_bytes += entry.nbytes
    self.peak_host_bytes = max(self.peak_host_bytes, self.host_bytes)
    return entry

  def Peek(self, seq_id) -> SpillEntry:
    return self._entries[seq_id]

  def Pop(self, seq_id) -> SpillEntry:
    entry = self._entries.pop(seq_id)
    self.restored_pages += len(entry.logical_idxs)
    self.host_bytes -= entry.nbytes
    return entry

  def Drop(self, seq_id) -> bool:
    """Discards a cancelled sequence's entry (not counted as restored)."""
    entry = self._entries.pop(seq_id, None)
    if entry is None:
      return False
    self.host_bytes -= entry.nbytes
    return True

  def Stats(self) -> dict:
    return {
        "entries": len(self._entries),
        "spilled_pages": self.spilled_pages,
        "restored_pages": self.restored_pages,
        "host_bytes": self.host_bytes,
        "peak_host_bytes": self.peak_host_bytes,
    }
