"""KV block allocator: host-side ownership of the global page pool.

The device side of paged serving is a dumb `[num_pages, page_size, N, H]`
pool (attention.InitPagedStates); everything that makes it a cache — which
pages belong to which sequence, which are free — lives here, in plain
Python on the host, updated between device steps. That split keeps every
compiled program shape-static: admitting or evicting a sequence only
rewrites small int32 block tables, never reshapes device buffers.

Allocation policy: a min-heap free list. Always handing out the
lowest-numbered free page keeps the live set packed toward the low end of
the pool — eviction "defragments" by construction (freed high pages sink
to the back of the heap and are reused last), so a long-running server's
working set stays dense without ever copying K/V between pages.
"""

from __future__ import annotations

import heapq


class OutOfPages(Exception):
  """Raised by Allocate when the pool cannot satisfy the request."""


class PageAllocator:
  """Owns [0, num_pages) of the device pool; sequences hold disjoint sets.

  NOT thread-safe on its own — the serving engine serializes all calls
  under its scheduler lock. The trash page the engine appends to the
  device pool is outside [0, num_pages) and never managed here.
  """

  def __init__(self, num_pages: int, page_size: int):
    assert num_pages > 0 and page_size > 0, (num_pages, page_size)
    self.num_pages = num_pages
    self.page_size = page_size
    self._free = list(range(num_pages))  # already a valid min-heap
    self._owned: dict[object, list[int]] = {}
    self.peak_in_use = 0

  # -- queries ---------------------------------------------------------------

  @property
  def num_free(self) -> int:
    return len(self._free)

  @property
  def num_in_use(self) -> int:
    return self.num_pages - len(self._free)

  def PagesFor(self, num_tokens: int) -> int:
    """Pages needed to hold num_tokens logical slots."""
    return -(-num_tokens // self.page_size)

  def CanAllocate(self, n: int) -> bool:
    return n <= len(self._free)

  def PagesOf(self, seq_id) -> list[int]:
    """The sequence's pages in logical order (index i = logical page i)."""
    return list(self._owned[seq_id])

  def Stats(self) -> dict:
    return {
        "num_pages": self.num_pages,
        "page_size": self.page_size,
        "in_use": self.num_in_use,
        "free": self.num_free,
        "utilization": self.num_in_use / self.num_pages,
        "peak_in_use": self.peak_in_use,
        "num_sequences": len(self._owned),
    }

  # -- mutations -------------------------------------------------------------

  def Allocate(self, seq_id, n: int) -> list[int]:
    """Grants n MORE pages to seq_id (appended to its logical order).

    All-or-nothing: raises OutOfPages without side effects if fewer than n
    pages are free — the scheduler checks CanAllocate first and queues the
    request instead of admitting it."""
    if n > len(self._free):
      raise OutOfPages(f"need {n} pages, {len(self._free)} free")
    got = [heapq.heappop(self._free) for _ in range(n)]
    self._owned.setdefault(seq_id, []).extend(got)
    self.peak_in_use = max(self.peak_in_use, self.num_in_use)
    return got

  def Free(self, seq_id) -> int:
    """Returns every page owned by seq_id to the pool; returns the count.

    Idempotent: freeing an unknown/already-freed id is a no-op (eviction
    and cancellation can race to the same sequence at a step boundary)."""
    pages = self._owned.pop(seq_id, [])
    for pg in pages:
      heapq.heappush(self._free, pg)
    return len(pages)
