"""Global prefix cache: cross-request KV page sharing with CoW admission.

Millions of requests carrying the same system prompt should pay its
prefill ONCE. The block-table indirection of paged serving already lets
two sequences' tables point at the same physical page (the kernels were
proven alias-tolerant by the hostile stale-table test in PR 6), so all a
prefix cache needs is host-side bookkeeping:

- a **radix/prefix tree with one node per FULL page**, keyed by the
  page's page_size token-id chunk. A path from the root spells a
  page-aligned token prefix; each node maps its chunk to the resident
  physical page holding that chunk's K/V. Only COMPLETE pages are ever
  cached — a partially-filled tail page is private to its writer, which
  is what makes the sharing story simple: divergence inside a page can
  only happen on a page the cache never handed out (plus the one
  full-cover case the scheduler copy-on-writes, below).
- **refcounts on the PageAllocator** (kv_cache.py): the cache holds one
  `Retain` reference per node, each borrowing sequence holds one `Share`
  reference, and a page is physically reclaimed only when the last
  reference drops. Cached-but-unreferenced pages (refcount 1, cache
  only) are exactly the evictable set.
- **LRU eviction under pool pressure**: when admission cannot reserve a
  request's uncached remainder, the scheduler asks the cache to release
  least-recently-probed unreferenced pages. Nodes with live borrowers
  are never evicted (their refcount > 1); evicting a node orphans its
  subtree's deeper nodes, so eviction walks leaves-first.
- **invalidation**: a checkpoint/theta swap makes every cached page
  stale (`Invalidate()` drops the whole tree), and pools of different
  kv_cache_dtype must never cross-share (`Bind` invalidates on dtype or
  allocator mismatch — an int8 page is bytes-incompatible with a bf16
  probe even if the token chunk matches).
- **tree persistence across swaps** (`MarkStale()`): a hot theta swap
  invalidates the cached K/V *values* but not the token-chunk *keys* —
  the tree shape and LRU ordering describe the live traffic mix, which
  the new theta will reproduce. MarkStale bumps a generation counter so
  walks stop at the first stale node (stale pages are never handed out),
  while `Insert` refreshes a stale node in place with the re-prefilled
  page: one warm re-prefill per prefix restores hit_tokens without a
  cold restart of the radix tree. Drop-everything `Invalidate` remains
  the default swap behavior (engine knob `prefix_swap_persist`).

The one write-into-shared-page case: when a probe covers the WHOLE
prompt, prefill must still recompute the last prompt token to produce
first-token logits, and that write lands in the final matched page. The
scheduler copy-on-writes that page at admission (allocator.CopyOnWrite),
so device writes NEVER touch a page with refcount > 1 — an invariant
`PageAllocator.AssertExclusive` checks on every step build, which is
also what keeps speculative-decoding rollback (a cursor rewind + rewrite
of the same slots) safe against sharing.

Thread safety: like the allocator/scheduler, this is plain host state
serialized by the engine's scheduler lock.
"""

from __future__ import annotations

from typing import Optional

from lingvo_tpu.serving import kv_cache


class _Node:
  """One cached full page: `chunk` (page_size token tuple) -> `page`.

  `gen` is the cache generation the page's K/V was computed under; a
  node whose gen trails the cache's is stale (theta swapped since) and
  is skipped by walks until Insert refreshes it in place."""

  __slots__ = ("chunk", "page", "parent", "children", "last_used", "gen")

  def __init__(self, chunk, page, parent, gen=0):
    self.chunk = chunk
    self.page = page
    self.parent = parent
    self.children: dict = {}
    self.last_used = 0
    self.gen = gen


class PrefixCache:
  """Page-granular radix tree over one engine's page pool.

  max_pages: cap on pages the cache may retain (None = bounded only by
  the pool; eviction then happens purely under admission pressure).
  kv_cache_dtype: the pool's effective KV dtype — recorded so `Bind`
  can refuse to carry entries across pools that disagree.
  """

  def __init__(self, allocator: Optional[kv_cache.PageAllocator] = None,
               kv_cache_dtype: Optional[str] = None,
               max_pages: Optional[int] = None):
    self.alloc = allocator
    self.kv_cache_dtype = kv_cache_dtype
    self.max_pages = max_pages
    self._root = _Node(None, None, None)
    self._nodes: dict[int, _Node] = {}   # page -> node (eviction walk)
    self._tick = 0                       # monotonic LRU clock
    self._gen = 0                        # bumped by MarkStale (theta swap)
    # counters surfaced via Stats() -> prefix_cache/* registry section
    self.hits = 0
    self.misses = 0
    self.hit_tokens = 0
    self.evictions = 0
    self.cow_copies = 0
    self.refreshed_pages = 0

  # -- binding / invalidation -------------------------------------------------

  def Bind(self, allocator: kv_cache.PageAllocator,
           kv_cache_dtype: Optional[str]):
    """Attaches the cache to an engine's pool. A cache built against a
    different allocator or kv dtype is invalidated first: page ids are
    meaningless across pools, and int8 vs bf16 pages never cross-share."""
    if self.alloc is not allocator or self.kv_cache_dtype != kv_cache_dtype:
      self.Invalidate()
    self.alloc = allocator
    self.kv_cache_dtype = kv_cache_dtype
    return self

  def Invalidate(self) -> int:
    """Drops every cached page (checkpoint/theta swap: all K/V is stale).
    Borrowing sequences keep their references — their pages just stop
    being offered to new requests. Returns pages released."""
    n = len(self._nodes)
    if self.alloc is not None:
      for page in self._nodes:
        self.alloc.Release(page)
    self.evictions += n
    self._root = _Node(None, None, None)
    self._nodes = {}
    return n

  def MarkStale(self) -> int:
    """Theta swapped but the tree should survive: bumps the cache
    generation so every resident page becomes stale — never offered to a
    probe, still occupying its node so the next prefill of the same
    chunk refreshes it in place (Insert). O(1); pages stay retained and
    remain reclaimable under pressure (EvictLru takes stale leaves like
    any other unreferenced leaf). Returns pages marked stale."""
    if self._nodes:
      self._gen += 1
    return len(self._nodes)

  # -- queries ----------------------------------------------------------------

  @property
  def cached_pages(self) -> int:
    return len(self._nodes)

  def _Chunks(self, prompt):
    ps = self.alloc.page_size
    for i in range(len(prompt) // ps):
      yield tuple(prompt[i * ps:(i + 1) * ps])

  def _Walk(self, prompt, touch: bool):
    node, pages = self._root, []
    for chunk in self._Chunks(prompt):
      child = node.children.get(chunk)
      if child is None or child.gen != self._gen:
        break   # missing, or stale K/V from a pre-swap generation
      if touch:
        self._tick += 1
        child.last_used = self._tick
      pages.append(child.page)
      node = child
    return pages

  def PeekHitTokens(self, prompt) -> int:
    """Reusable-token count a Probe would return — no counters, no LRU
    touch (Submit-time introspection)."""
    matched = len(self._Walk(prompt, touch=False)) * self.alloc.page_size
    return min(matched, len(prompt) - 1) if matched else 0

  def Probe(self, prompt) -> tuple[list[int], int]:
    """Longest cached page-aligned prefix of `prompt` — PURE: no counters,
    no LRU touch. Admission may probe the same queued request every
    engine step while the pool is full; only the probe that turns into an
    admission counts (NoteAdmitted).

    Returns (pages, matched_tokens) where pages[i] holds prompt tokens
    [i*page_size, (i+1)*page_size)."""
    pages = self._Walk(prompt, touch=False)
    return pages, len(pages) * self.alloc.page_size

  def NoteAdmitted(self, prompt, matched_tokens: int):
    """Records one admission's cache outcome: a hit when any page
    matched (LRU-touching the matched path), else a miss. hit_tokens
    counts tokens whose prefill is actually SKIPPED — min(matched,
    len(prompt) - 1), since a full-cover match still recomputes the last
    prompt token for its logits."""
    if matched_tokens > 0:
      self._Walk(prompt, touch=True)
      self.hits += 1
      self.hit_tokens += min(matched_tokens, len(prompt) - 1)
    else:
      self.misses += 1

  # -- mutations --------------------------------------------------------------

  def Insert(self, prompt, pages: list[int]):
    """Caches `prompt`'s full-page prefix: pages[i] must hold the i-th
    page_size chunk (the scheduler passes the sequence's own pages right
    after prefill completes). Existing nodes win — the first writer's
    page stays canonical and later identical prefixes share it; only
    chunks not yet present retain new pages. A STALE node (generation
    behind, post-MarkStale) is refreshed in place: its old page is
    released, the freshly prefilled one retained, and the node keeps its
    position and children — how hit_tokens recover after a persisted
    theta swap. Respects max_pages by evicting LRU unreferenced pages
    first and stopping (prefix-complete) when room runs out."""
    node = self._root
    for i, chunk in enumerate(self._Chunks(prompt)):
      if i >= len(pages):
        break
      child = node.children.get(chunk)
      if child is not None and child.gen != self._gen:
        page = pages[i]
        if page != child.page:
          if page in self._nodes:
            break   # page already caches a different chunk (stale insert)
          self.alloc.Release(child.page)
          del self._nodes[child.page]
          self.alloc.Retain(page)
          child.page = page
          self._nodes[page] = child
        child.gen = self._gen
        self.refreshed_pages += 1
      elif child is None:
        if self.max_pages is not None and len(self._nodes) >= self.max_pages:
          if self.EvictLru(len(self._nodes) - self.max_pages + 1) == 0:
            break
        page = pages[i]
        if page in self._nodes:
          break   # page already caches a different chunk (stale insert)
        self.alloc.Retain(page)
        child = _Node(chunk, page, node, gen=self._gen)
        node.children[chunk] = child
        self._nodes[page] = child
      self._tick += 1
      child.last_used = self._tick
      node = child

  def EvictLru(self, n: int) -> int:
    """Releases up to n least-recently-used UNREFERENCED cached pages
    (refcount 1: cache-only — pages some sequence still borrows are
    pinned by their refcount). Evicts leaves-first so the tree never
    holds a child whose parent is gone; an inner node only becomes
    evictable once its subtree is. Returns pages released."""
    released = 0
    while released < n:
      victims = [nd for nd in self._nodes.values()
                 if not nd.children and self.alloc.RefCount(nd.page) == 1]
      if not victims:
        break
      victims.sort(key=lambda nd: nd.last_used)
      for nd in victims:
        if released >= n:
          break
        self.alloc.Release(nd.page)
        del self._nodes[nd.page]
        del nd.parent.children[nd.chunk]
        released += 1
        self.evictions += 1
    return released

  def EvictForPressure(self, shortfall: int) -> int:
    """Admission pressure valve: frees up to `shortfall` pages back to
    the pool. No-op for shortfall <= 0."""
    return self.EvictLru(shortfall) if shortfall > 0 else 0

  def NoteCow(self):
    """One copy-on-write page split performed on behalf of this cache."""
    self.cow_copies += 1

  # -- introspection ----------------------------------------------------------

  def Stats(self) -> dict:
    ps = self.alloc.page_size if self.alloc is not None else 0
    stale = sum(1 for nd in self._nodes.values() if nd.gen != self._gen)
    return {
        "enabled": True,
        "hits": self.hits,
        "misses": self.misses,
        "hit_tokens": self.hit_tokens,
        "evictions": self.evictions,
        "cow_copies": self.cow_copies,
        "cached_pages": self.cached_pages,
        "cached_tokens": self.cached_pages * ps,
        "stale_pages": stale,
        "refreshed_pages": self.refreshed_pages,
    }
