"""Inference export: task subgraphs -> serialized StableHLO + manifest.

Re-designs `lingvo/core/inference_graph_exporter.py` (+inference_graph.proto):
`task.Inference()` returns {subgraph_name: (fn, example_inputs)}; each is
jit-lowered and serialized with `jax.export` (StableHLO), with a JSON
manifest of feeds/fetches shapes/dtypes — the TPU-native InferenceGraph.
Weights are saved alongside via orbax so the Predictor restores everything
from one directory.
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.core.nested_map import NestedMap


def _ToNestedMap(tree):
  """Plain dicts (orbax restore output) -> NestedMap, recursively."""
  if isinstance(tree, dict):
    return NestedMap({k: _ToNestedMap(v) for k, v in tree.items()})
  if isinstance(tree, list):
    return [_ToNestedMap(v) for v in tree]
  return tree


def _SpecManifest(tree) -> Any:
  return jax.tree_util.tree_map(
      lambda x: {"shape": list(np.shape(x)),
                 "dtype": str(np.asarray(x).dtype)}, tree)


class InferenceGraphExporter:
  """Exports a task's inference subgraphs + theta to `export_dir`."""

  @staticmethod
  def Export(task, theta: NestedMap, export_dir: str,
             bfloat16_activations: bool = False) -> dict:
    os.makedirs(export_dir, exist_ok=True)
    subgraphs = task.Inference()
    manifest = {"subgraphs": {}}
    from jax import export as jax_export
    for name, (fn, example_inputs) in subgraphs.items():
      if bfloat16_activations:
        example_inputs = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x, example_inputs)

      def wrapped(theta_, inputs_, fn=fn):
        return fn(theta_, inputs_)

      args = (theta, example_inputs)
      exported = jax_export.export(jax.jit(wrapped))(
          *jax.tree_util.tree_map(
              lambda x: jax.ShapeDtypeStruct(np.shape(x),
                                             np.asarray(x).dtype), args))
      blob = exported.serialize()
      with open(os.path.join(export_dir, f"{name}.stablehlo"), "wb") as f:
        f.write(blob)
      manifest["subgraphs"][name] = {
          "feeds": _SpecManifest(example_inputs),
          "fetches": "see exported signature",
          "artifact": f"{name}.stablehlo",
      }
    # weights
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(export_dir, "theta"), dict(theta=theta))
    ckptr.wait_until_finished()
    with open(os.path.join(export_dir, "inference_graph.json"), "w") as f:
      json.dump(manifest, f, indent=2)
    return manifest


class Predictor:
  """Loads an export dir and runs subgraphs (ref predictor.py:58)."""

  def __init__(self, export_dir: str):
    self._dir = export_dir
    with open(os.path.join(export_dir, "inference_graph.json")) as f:
      self._manifest = json.load(f)
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(os.path.join(export_dir, "theta"))
    self._theta = _ToNestedMap(restored["theta"])
    self._fns = {}
    from jax import export as jax_export
    for name, info in self._manifest["subgraphs"].items():
      with open(os.path.join(export_dir, info["artifact"]), "rb") as f:
        self._fns[name] = jax_export.deserialize(f.read())

  @property
  def subgraph_names(self):
    return sorted(self._fns)

  def Run(self, subgraph_name: str, inputs) -> Any:
    """Runs a subgraph on `inputs` (same structure as export-time example)."""
    exported = self._fns[subgraph_name]
    return exported.call(self._theta, inputs)
