"""Inference export: task subgraphs -> serialized StableHLO + manifest.

Re-designs `lingvo/core/inference_graph_exporter.py` (+inference_graph.proto):
`task.Inference()` returns {subgraph_name: (fn, example_inputs)}; each is
jit-lowered and serialized with `jax.export` (StableHLO), with a JSON
manifest of feeds/fetches shapes/dtypes — the TPU-native InferenceGraph.
Weights are saved alongside via orbax so the Predictor restores everything
from one directory.
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.core.nested_map import NestedMap


def _ToNestedMap(tree):
  """Plain dicts (orbax restore output) -> NestedMap, recursively."""
  if isinstance(tree, dict):
    return NestedMap({k: _ToNestedMap(v) for k, v in tree.items()})
  if isinstance(tree, list):
    return [_ToNestedMap(v) for v in tree]
  return tree


def _SpecManifest(tree) -> Any:
  return jax.tree_util.tree_map(
      lambda x: {"shape": list(np.shape(x)),
                 "dtype": str(np.asarray(x).dtype)}, tree)


# weight leaves eligible for int8 deployment: the hot matmul operands
_INT8_WEIGHT_NAMES = frozenset(
    ("w", "wm", "w_proj", "wi", "wo", "w_query", "w_key", "w_value",
     "w_post", "emb", "pw_in", "pw_out"))


def QuantizeThetaInt8(theta: NestedMap):
  """theta -> (frozen_theta, int8_tree).

  frozen_theta: matmul weights replaced by their dequantized per-channel
  int8 values — the exported graph then computes exactly what an int8
  deployment reproduces (the serving-side counterpart of the QAT
  simulation; ref inference_graph_exporter's dtype-override rewrites).
  int8_tree: {path: {"w_int8", "scale"}} — the actual low-bit artifact for
  integer-math consumers (pairs with quant_utils.Int8Einsum).

  Each leaf is quantized under its serving layout (quant.weights table):
  per-channel scales reduce over the axes the consuming einsum contracts,
  so `Predictor.Int8ServingTheta()` can mount the same pairs as Int8Weight
  nodes for real integer matmuls. Artifact-only names (MoE experts, ...)
  keep the legacy all-but-last-dim reduction; weights under a Repeated
  stack's `.body.` get per-repeat scales (the repeat axis is batch, not
  contraction).
  """
  from lingvo_tpu.quant import weights as quant_weights
  frozen = theta.DeepCopy()
  int8_tree = {}
  for path, leaf in theta.FlattenItems():
    name = path.rsplit(".", 1)[-1]
    arr = np.asarray(leaf)
    stacked = quant_weights.IsStackedPath(path)
    # jnp.issubdtype: np's returns False for bfloat16 (ml_dtypes), which
    # would silently skip every bf16-trained weight
    if name not in _INT8_WEIGHT_NAMES or arr.ndim < (3 if stacked else 2) or (
        not jnp.issubdtype(arr.dtype, jnp.floating)):
      continue
    layout, k = quant_weights.WeightLayoutFor(name)
    w8 = quant_weights.QuantizeLeafInt8(
        jnp.asarray(arr, jnp.float32), layout, k, stacked)
    int8_tree[path] = {"w_int8": np.asarray(w8.w_int8),
                       "scale": np.asarray(w8.scale)}
    frozen.Set(path, w8.Dequant().astype(leaf.dtype))
  return frozen, int8_tree


class InferenceGraphExporter:
  """Exports a task's inference subgraphs + theta to `export_dir`."""

  @staticmethod
  def Export(task, theta: NestedMap, export_dir: str,
             bfloat16_activations: bool = False,
             quantize_int8: bool = False) -> dict:
    os.makedirs(export_dir, exist_ok=True)
    int8_tree = None
    if quantize_int8:
      theta, int8_tree = QuantizeThetaInt8(theta)
      if not int8_tree:
        raise ValueError(
            "quantize_int8 requested but no theta leaf qualified "
            f"(eligible weight names: {sorted(_INT8_WEIGHT_NAMES)}) — "
            "the export would silently serve float weights")
    subgraphs = task.Inference()
    manifest = {"subgraphs": {}, "quantize_int8": bool(quantize_int8)}
    from jax import export as jax_export
    for name, (fn, example_inputs) in subgraphs.items():
      if bfloat16_activations:
        example_inputs = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x, example_inputs)

      def wrapped(theta_, inputs_, fn=fn):
        return fn(theta_, inputs_)

      args = (theta, example_inputs)
      exported = jax_export.export(jax.jit(wrapped))(
          *jax.tree_util.tree_map(
              lambda x: jax.ShapeDtypeStruct(np.shape(x),
                                             np.asarray(x).dtype), args))
      blob = exported.serialize()
      with open(os.path.join(export_dir, f"{name}.stablehlo"), "wb") as f:
        f.write(blob)
      manifest["subgraphs"][name] = {
          "feeds": _SpecManifest(example_inputs),
          "fetches": "see exported signature",
          "artifact": f"{name}.stablehlo",
      }
    # weights
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(export_dir, "theta"), dict(theta=theta))
    ckptr.wait_until_finished()
    if int8_tree:
      ckptr.save(os.path.join(export_dir, "theta_int8"),
                 dict(int8=int8_tree))
      ckptr.wait_until_finished()
      manifest["int8_artifact"] = "theta_int8"
      manifest["int8_weights"] = sorted(int8_tree)
      from lingvo_tpu.quant import weights as quant_weights
      layouts = {}
      for path in sorted(int8_tree):
        leaf_name = path.rsplit(".", 1)[-1]
        layout, k = quant_weights.WeightLayoutFor(leaf_name)
        layouts[path] = {
            "layout": layout, "contract_ndim": k,
            "stacked": quant_weights.IsStackedPath(path),
            "serving_eligible":
                leaf_name in quant_weights.SERVING_WEIGHT_LAYOUTS,
        }
      manifest["int8_layouts"] = layouts
    with open(os.path.join(export_dir, "inference_graph.json"), "w") as f:
      json.dump(manifest, f, indent=2)
    return manifest


class Predictor:
  """Loads an export dir and runs subgraphs (ref predictor.py:58)."""

  def __init__(self, export_dir: str):
    self._dir = export_dir
    with open(os.path.join(export_dir, "inference_graph.json")) as f:
      self._manifest = json.load(f)
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(os.path.join(export_dir, "theta"))
    self._theta = _ToNestedMap(restored["theta"])
    self._fns = {}
    from jax import export as jax_export
    for name, info in self._manifest["subgraphs"].items():
      with open(os.path.join(export_dir, info["artifact"]), "rb") as f:
        self._fns[name] = jax_export.deserialize(f.read())

  @property
  def subgraph_names(self):
    return sorted(self._fns)

  def Run(self, subgraph_name: str, inputs) -> Any:
    """Runs a subgraph on `inputs` (same structure as export-time example)."""
    exported = self._fns[subgraph_name]
    return exported.call(self._theta, inputs)

  def Int8Weights(self) -> dict | None:
    """The int8 deployment artifact ({path: {w_int8, scale}}), or None for
    a float export. Pairs with quant_utils.Int8Einsum on integer-math
    serving stacks; the exported graph itself already computes on the
    dequantized grid (QuantizeThetaInt8 froze it)."""
    if not self._manifest.get("int8_artifact"):
      return None
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(
        os.path.join(self._dir, self._manifest["int8_artifact"]))
    return restored["int8"]

  def Int8ServingTheta(self, mode: str = "int8") -> NestedMap:
    """The restored theta with serving-eligible leaves mounted from the
    int8 artifact.

    mode='int8': Int8Weight nodes — decode projections run integer
    matmuls (quant_utils.Int8Einsum) with a bounded, reported numeric
    delta vs the frozen export. mode='dequant': the float dequantization
    grid `w_int8 * scale` — bitwise identical to the frozen theta the
    export saved (the freeze contract), so ScoreSequences through it
    matches the exported graph exactly.
    """
    int8_tree = self.Int8Weights()
    if int8_tree is None:
      raise ValueError(
          "Int8ServingTheta requires an export made with quantize_int8=True")
    from lingvo_tpu.quant import weights as quant_weights
    theta, _ = quant_weights.Int8ServingThetaFromArtifact(
        self._theta, int8_tree, mode=mode)
    return theta
