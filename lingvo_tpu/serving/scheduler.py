"""Continuous-batching request scheduler.

Owns the host-side serving state machine: a FIFO of waiting requests, a
fixed array of B decode slots, and the page allocator. Each engine
iteration is admit → build → (device step) → commit:

- `Admit` moves queued requests into free slots while the allocator can
  reserve their WHOLE worst-case footprint (ceil((prompt + max_new) /
  page_size) pages) up front. Reserve-all-on-admission means an admitted
  sequence can never run out of pages mid-flight, so there is no
  preemption/swap machinery — pool pressure shows up only as queueing
  (the allocator-exhaustion satellite: graceful, never a crash). With a
  prefix cache attached (serving/prefix_cache.py), admission first
  probes the cache: matched full-page prefixes are BORROWED (refcount
  shares, not fresh pages), only the uncached remainder is charged to
  the pool — so shared pages stop counting against the reservation,
  which is the concurrency jump — and prefill starts at the first
  uncached token. A match covering the whole prompt copy-on-writes its
  final page, because prefill must recompute the last prompt token.
- `BuildStep` flattens the live slots into one batch for the compiled
  PagedStep program. Steady state is a pure decode step (chunk width
  C == 1, every live row feeds its last sampled token). Whenever any slot
  is still prefilling, the step widens to C == prefill_chunk and becomes a
  MIXED step: prefilling rows consume up to C prompt tokens, decoding rows
  ride along with in_len == 1 — decode is never stalled behind prefill,
  which is the per-step prefill budget the ISSUE asks for.
- `CommitStep` folds the device's sampled tokens back in: advances prompt
  cursors, turns finished prefills into decoders (their first generated
  token is the sample at the last valid chunk position), appends decode
  tokens, retires sequences on max_new/EOS, and frees their slot + pages
  immediately so `Admit` can refill the slot on the very next iteration.

SLO-aware scheduling (`scheduler_mode='priority'`, opt-in; 'fifo' is the
bit-exact legacy default): requests carry a `priority` class and a
`tenant` label. Admission serves the highest priority class first;
within a class, preempted work resumes before fresh work, and fresh
admissions are weighted-fair across tenants (least admitted-token
service per unit weight goes first). Under pool pressure a strictly
higher-priority arrival PREEMPTS a victim — lowest priority first,
fewest generated tokens first — by spilling its private KV pages and
O(1)-mixer state row to a host tier (`kv_cache.HostPageStore`) and
parking it in a PREEMPTED queue; re-admission restores the saved bytes
into fresh pages at the same logical slots and resumes from the spilled
cursor, no recompute. The device halves (page gather/scatter, state row
gather/scatter) are injected by the engine as `spill_fn`/`restore_fn` /
`state_spill_fn`/`state_restore_fn` callbacks, so the scheduler itself
stays device-free. Per-tenant token-rate quotas (`TokenBucket`) gate
`Submit`, raising `QuotaExceeded` before any state is created.

Sequences/requests are identified by the user-visible request id. The
scheduler is deliberately device-free (pure Python + numpy) so its
lifecycle is unit-testable with fabricated sample arrays.
"""

from __future__ import annotations

import collections
import enum
import time
from typing import Optional

import numpy as np

from lingvo_tpu.core import ragged
from lingvo_tpu.serving import kv_cache


class SeqState(enum.Enum):
  QUEUED = "queued"
  PREFILL = "prefill"
  DECODE = "decode"
  FINISHED = "finished"
  CANCELLED = "cancelled"
  PREEMPTED = "preempted"


class QuotaExceeded(Exception):
  """Raised by Submit when the tenant's token-rate bucket is empty."""


class TokenBucket:
  """Per-tenant token-rate quota: `rate` tokens/sec up to `burst` deep.

  A request is charged its whole worst-case footprint (prompt + max_new)
  at Submit — the same unit admission reserves pages for — so a tenant
  cannot laundromat quota by submitting long generations cheaply.
  clock: injectable monotonic-seconds source (tests)."""

  def __init__(self, rate: float, burst: float, clock=None):
    assert rate >= 0 and burst > 0, (rate, burst)
    self.rate = float(rate)
    self.burst = float(burst)
    self._clock = clock if clock is not None else time.monotonic
    self._level = float(burst)
    self._last = self._clock()

  def _Refill(self):
    now = self._clock()
    self._level = min(self.burst,
                      self._level + (now - self._last) * self.rate)
    self._last = now

  def TryTake(self, n: float) -> bool:
    """Charges n tokens if the bucket covers them; False otherwise."""
    self._Refill()
    if n <= self._level:
      self._level -= n
      return True
    return False

  @property
  def level(self) -> float:
    self._Refill()
    return self._level


class Request:
  """One user request: prompt ids + generation budget.

  seed: per-request sampling seed (core/sampling.py row stream). Defaults
  to the request id for int ids, so every request has a replayable stream
  even when the caller doesn't pick one: resubmitting with the same seed
  under the same checkpoint yields the same continuation regardless of
  which slot or batch neighbors it is scheduled with.

  spec_k: per-request speculative-decoding knob. None (default) defers to
  the engine — full draft length k when the engine speculates, the exact
  legacy single-token path otherwise. 0 opts this request out of
  speculation entirely; n > 0 caps its draft length at min(n, engine k).
  Only consulted by engines with a draft source configured.

  spec_w: per-request TREE-speculation width knob — the number of
  branches the draft tree forks into at depth 1 (core/ragged.py tree
  contract). None (default) defers to the engine's draft width, 1 forces
  a linear chain (the exact PR-11 behavior), n > 1 caps the width at
  min(n, engine w). Only consulted when the engine's draft source has
  width > 1.

  priority: SLO class, higher = more urgent (default 0). Consulted only
  by `scheduler_mode='priority'` schedulers: admission serves higher
  classes first, and a strictly higher-priority arrival may preempt a
  lower one under pool pressure. FIFO schedulers ignore it.

  tenant: opaque tenant label for quota + fairness accounting (None =
  the anonymous tenant). Weighted-fair admission within a priority
  class and per-tenant token-rate quotas key on it.
  """

  def __init__(self, req_id, prompt, max_new_tokens: int,
               eos_id: Optional[int] = None, seed: Optional[int] = None,
               spec_k: Optional[int] = None, spec_w: Optional[int] = None,
               priority: int = 0, tenant=None):
    prompt = [int(t) for t in prompt]
    assert len(prompt) >= 1, "empty prompt"
    assert max_new_tokens >= 1, max_new_tokens
    assert spec_k is None or spec_k >= 0, spec_k
    assert spec_w is None or spec_w >= 1, spec_w
    self.id = req_id
    self.prompt = prompt
    self.max_new = int(max_new_tokens)
    self.eos_id = eos_id
    self.spec_k = spec_k
    self.spec_w = spec_w
    self.priority = int(priority)
    self.tenant = tenant
    if seed is None:
      seed = req_id if isinstance(req_id, int) else abs(hash(req_id))
    self.seed = int(seed) % (2**31)


class Sequence:
  """A request's in-flight decode state (slot-resident)."""

  def __init__(self, request: Request):
    self.req = request
    self.state = SeqState.QUEUED
    self.pos = 0          # tokens WRITTEN to the KV cache so far
    self.out = []         # generated tokens (out[-1] may not be cached yet)
    self.finish_reason = None
    self.slot = None      # decode slot index, set at admission (telemetry)
    # committed tokens an independent draft model's recurrent state has
    # consumed so far (speculative decoding only; engine-maintained)
    self.draft_pos = 0
    # prefix-cache admission results: prompt tokens whose prefill was
    # skipped (seq.pos starts there), and (src, dst) physical page pairs
    # the engine must copy device-side before this sequence's first step
    self.reused_tokens = 0
    self.cow_pairs: list[tuple[int, int]] = []
    # submission order within the scheduler (priority-mode tie-break)
    self.arrival = 0

  @property
  def id(self):
    return self.req.id

  @property
  def prompt_remaining(self) -> int:
    return len(self.req.prompt) - self.pos


class StepBatch:
  """One flattened device step (numpy; the engine jits over it)."""

  def __init__(self, ids, q_pos, in_len, rows, mixed: bool,
               prompt_tokens: int, row_seeds=None, row_pos=None,
               row_k=None):
    self.ids = ids          # [B, C] int32
    self.q_pos = q_pos      # [B] int32
    self.in_len = in_len    # [B] int32 (0 = inactive row)
    self.rows = rows        # slot -> Sequence or None, frozen at build time
    self.mixed = mixed      # True if any prefill row rode this step
    self.prompt_tokens = prompt_tokens  # prompt tokens consumed this step
    # sampling inputs: per-request seed + per-request output index (tokens
    # generated so far) — together they make each draw a pure function of
    # (engine seed, request seed, output position), never of scheduling
    self.row_seeds = row_seeds  # [B] int32
    self.row_pos = row_pos      # [B] int32
    # verify steps only: per-row draft length (in_len = row_k + 1); the
    # engine fills ids[:, 1:] with the draft's proposals before launch
    self.row_k = row_k          # [B] int32 or None


class RaggedBatch:
  """One packed ragged device step (numpy; the engine jits over it).

  The unified replacement for all three StepBatch shapes: a decode row
  carries 1 + row_w * row_k tokens (row_k > 0 is the spec-verify lane; a
  row_w > 1 row packs a token TREE of row_w branches, each a chain of
  row_k drafts, in DFS order — core/ragged.py), a prefill row a
  token-budgeted chunk, and every composition launches through the SAME
  compiled program. `rows_desc` is the core/ragged.RaggedRows routing
  pytree; `tok_ids` is the matching packed [T] token stream — draft
  columns hold 0 until the engine fills proposals: branch bi's depth-d
  node at rows_desc.row_cols[i, 1 + bi * row_k[i] + d].

  The row-level view (ids / q_pos / in_len / rows / row_seeds / row_pos
  / row_k) deliberately speaks the StepBatch protocol so
  spec_decode.SpecRunner.Draft consumes a RaggedBatch unchanged. in_len
  is nonzero ONLY for rows that draft this step, so the draft pass
  activates exactly those — prefill rows ride the same device step
  without drafting, which is what lets spec cycles proceed while
  admissions are still prefilling (the legacy engine had to finish every
  prefill before its first verify step).
  """

  def __init__(self, tok_ids, rows_desc: ragged.RaggedRows, rows,
               mixed: bool, prompt_tokens: int, row_seeds, row_pos,
               row_k, any_spec: bool, ids0, row_w=None,
               width_clamps: int = 0):
    self.tok_ids = tok_ids        # [T] int32 packed token stream
    self.rows_desc = rows_desc    # core/ragged.RaggedRows (numpy members)
    self.rows = rows              # slot -> Sequence or None, frozen at build
    self.mixed = mixed            # True if any prompt token rode this step
    self.prompt_tokens = prompt_tokens
    self.row_seeds = row_seeds    # [B] int32
    self.row_pos = row_pos        # [B] int32
    self.row_k = row_k            # [B] int32 per-branch draft depth this step
    self.any_spec = any_spec      # host fast-path: Draft is skipped if False
    # [B] int32 tree width this step (1 = chain; row_w * row_k draft slots)
    self.row_w = (row_w if row_w is not None
                  else np.ones_like(np.asarray(row_k)))
    self.width_clamps = width_clamps  # rows whose width the pack cap shrank
    # -- StepBatch-protocol adapter for the draft source ----------------
    self.ids = ids0               # [B, 1] int32: column-0 feedback token
    self.q_pos = rows_desc.row_q_pos
    self.in_len = np.where(row_k > 0, 1, 0).astype(np.int32)


class Scheduler:
  """Admission + step building + commit over B slots and a page pool."""

  def __init__(self, max_slots: int, allocator: kv_cache.PageAllocator,
               table_pages: int, prefill_chunk: int,
               needs_kv_pages: bool = True,
               state_pool: Optional[kv_cache.StateSlotPool] = None,
               prefix_cache=None, scheduler_mode: str = "fifo",
               host_store: Optional[kv_cache.HostPageStore] = None,
               tenant_quotas=None, tenant_weights=None, clock=None):
    """table_pages: block-table width (pages per sequence) — the static
    max_seq_len / page_size bound every compiled program carries.
    prefill_chunk: prompt tokens a prefilling row consumes per mixed step.
    needs_kv_pages: False for pure-O(1)-mixer stacks (no attention layer
    writes the paged pool) — admission is then bounded by slots only, and
    the allocator is never charged. state_pool: slot-ownership accounting
    for O(1) mixer states (acquired on admit, released on retirement).
    prefix_cache: optional serving/prefix_cache.PrefixCache bound to
    `allocator` — admission probes/borrows cached prefix pages and
    completed prefills insert theirs; None keeps the exact legacy path.
    scheduler_mode: 'fifo' (default, the bit-exact legacy admission
    path) or 'priority' (SLO classes + weighted-fair tenants +
    preemption by page spill — module docstring). host_store: the host
    tier preempted pages spill to (priority mode builds one when None).
    tenant_quotas: {tenant: TokenBucket | (rate, burst)} token-rate
    quotas enforced at Submit. tenant_weights: {tenant: weight} for
    weighted-fair admission within a priority class (default 1.0).
    clock: injectable monotonic-seconds source for quota refill (tests).
    """
    assert max_slots >= 1 and table_pages >= 1 and prefill_chunk >= 1
    assert scheduler_mode in ("fifo", "priority"), scheduler_mode
    self.max_slots = max_slots
    self.alloc = allocator
    self.table_pages = table_pages
    self.prefill_chunk = prefill_chunk
    self.needs_kv_pages = needs_kv_pages
    self.state_pool = state_pool
    self.prefix_cache = prefix_cache
    self.scheduler_mode = scheduler_mode
    self.host_store = host_store
    if self.host_store is None and scheduler_mode == "priority":
      self.host_store = kv_cache.HostPageStore()
    # device halves of spill/restore, injected by the owning engine
    # (None on device-free schedulers: spills then move no bytes, which
    # is exactly right for unit tests and pageless stacks)
    self.spill_fn = None          # pages -> host blocks (per paged leaf)
    self.restore_fn = None        # (pages, blocks) -> scatters them back
    self.state_spill_fn = None    # slot -> host rows (per slot leaf)
    self.state_restore_fn = None  # (slot, rows) -> scatters them back
    self.allow_preempt = True     # priority WITHOUT spill: sweep arm knob
    self.tenant_weights = dict(tenant_weights or {})
    self.quotas = {}
    for tenant, q in (tenant_quotas or {}).items():
      self.quotas[tenant] = (q if isinstance(q, TokenBucket)
                             else TokenBucket(q[0], q[1], clock=clock))
    self.waiting = collections.deque()        # of Sequence (QUEUED)
    self.preempted = collections.deque()      # of Sequence (PREEMPTED)
    self.slots: list[Optional[Sequence]] = [None] * max_slots
    self._by_id: dict[object, Sequence] = {}
    # block tables as one stable [B, table_pages] array, rewritten on
    # admit/evict only (steady-state decode steps reuse it as-is)
    self.block_tables = np.zeros((max_slots, table_pages), np.int32)
    # counters surfaced via engine Stats()
    self.admitted = 0
    self.finished = 0
    self.cancelled = 0
    self.rejected_overlong = 0
    self.slots_live_peak = 0
    # admissions where cached-prefix ordering picked past the FIFO head
    self.prefix_ordered_admissions = 0
    # tree-speculation rows whose branch count the packed-row cap shrank
    self.width_clamps = 0
    # SLO accounting (priority mode; zeros under fifo)
    self.preemptions = 0
    self.restores = 0
    self.quota_rejections = 0
    self._arrival = 0
    self._tenant_service: dict = {}   # tenant -> admitted token footprint

  # -- submission ------------------------------------------------------------

  def Submit(self, request: Request) -> Sequence:
    # the max_seq_len capacity bound holds for pageless stacks too: the
    # compiled step programs still carry table_pages-wide block tables,
    # and q_pos positions beyond the bound were never validated
    total = len(request.prompt) + request.max_new
    if self.alloc.PagesFor(total) > self.table_pages:
      self.rejected_overlong += 1
      raise ValueError(
          f"request {request.id!r} needs {self.alloc.PagesFor(total)} pages "
          f"(prompt {len(request.prompt)} + max_new {request.max_new}) but "
          f"block tables hold {self.table_pages}")
    bucket = self.quotas.get(request.tenant)
    if bucket is not None and not bucket.TryTake(total):
      self.quota_rejections += 1
      raise QuotaExceeded(
          f"tenant {request.tenant!r} over token-rate quota: request "
          f"footprint {total} exceeds bucket level {bucket.level:.0f} "
          f"(rate {bucket.rate}/s, burst {bucket.burst:.0f})")
    seq = Sequence(request)
    self._arrival += 1
    seq.arrival = self._arrival
    self._by_id[request.id] = seq
    self.waiting.append(seq)
    return seq

  def Cancel(self, req_id) -> bool:
    """Marks a request cancelled; resources return at the next boundary."""
    seq = self._by_id.get(req_id)
    if seq is None or seq.state in (SeqState.FINISHED, SeqState.CANCELLED):
      return False
    if seq.state is SeqState.QUEUED:
      try:
        self.waiting.remove(seq)
      except ValueError:
        pass
      self._Retire(seq, SeqState.CANCELLED, "cancelled")
      self.cancelled += 1
      return True
    if seq.state is SeqState.PREEMPTED:
      # parked off-device: drop the host-tier entry, then release the
      # refs it still holds on shared prefix pages (Free skips HOLEs)
      try:
        self.preempted.remove(seq)
      except ValueError:
        pass
      if self.host_store is not None:
        self.host_store.Drop(seq.id)
      self._Retire(seq, SeqState.CANCELLED, "cancelled")
      self.cancelled += 1
      return True
    seq.state = SeqState.CANCELLED   # slot/pages reclaimed by EvictCancelled
    seq.finish_reason = "cancelled"
    return True

  # -- boundary phases -------------------------------------------------------

  def EvictCancelled(self) -> list:
    """Frees slots/pages of mid-flight cancellations. Call before Admit."""
    evicted = []
    for i, seq in enumerate(self.slots):
      if seq is not None and seq.state is SeqState.CANCELLED:
        self.slots[i] = None
        self.alloc.Free(seq.id)
        if self.state_pool is not None:
          self.state_pool.Release(seq.id)
        self.cancelled += 1
        evicted.append(seq)
    return evicted

  def _AdmitPages(self, seq: Sequence) -> bool:
    """Reserves seq's whole footprint, borrowing cached prefix pages.

    Probes the prefix cache (if any) for the prompt's longest cached
    page-aligned prefix, pins those pages with refcount shares, charges
    the pool only for the uncached remainder, copy-on-writes any shared
    page prefill will write into (only the final matched page, and only
    on a full-cover match), and rewinds seq.pos past the reused tokens.
    Returns False with NO net side effects when the pool cannot cover
    the remainder even after evicting unreferenced cached pages."""
    req = seq.req
    total = self.alloc.PagesFor(len(req.prompt) + req.max_new)
    shared, matched = [], 0
    if self.prefix_cache is not None:
      shared, matched = self.prefix_cache.Probe(req.prompt)
    # prefill resumes at the first uncached token; a full-cover match
    # still recomputes the LAST prompt token (its logits seed decoding)
    p0 = min(matched, len(req.prompt) - 1)
    first_write_page = p0 // self.alloc.page_size
    n_cow = max(len(shared) - first_write_page, 0)
    need_new = (total - len(shared)) + n_cow
    # pin the borrowed pages FIRST (refcount >= 2 makes them un-evictable),
    # then squeeze the pool: cached-but-unreferenced pages yield under
    # admission pressure
    self.alloc.Share(seq.id, shared)
    if not self.alloc.CanAllocate(need_new):
      if self.prefix_cache is not None:
        self.prefix_cache.EvictForPressure(need_new - self.alloc.num_free)
      if not self.alloc.CanAllocate(need_new):
        self.alloc.Free(seq.id)   # undo the share; head-of-line blocks
        return False
    cow = []
    for idx in range(first_write_page, len(shared)):
      pair = self.alloc.CopyOnWrite(seq.id, idx)
      if pair is not None:
        cow.append(pair)
        if self.prefix_cache is not None:
          self.prefix_cache.NoteCow()
    if total > len(shared):
      self.alloc.Allocate(seq.id, total - len(shared))
    if self.prefix_cache is not None:
      self.prefix_cache.NoteAdmitted(req.prompt, matched)
    seq.pos = p0
    seq.reused_tokens = p0
    seq.cow_pairs = cow
    return True

  def _NextWaiting(self) -> int:
    """Index into self.waiting of the next admission candidate.

    Strict FIFO without a prefix cache. With one attached, reorders
    WITHIN the admission head — the first max_slots queued requests —
    preferring the largest cached-prefix match (FIFO breaks ties, so
    all-miss windows degenerate to the legacy order). Admitting the
    best-cached candidate first matters under pool pressure: its shared
    pages get pinned (refcount > 1, un-evictable) before cache-missing
    admissions squeeze the pool and evict them, so the same eviction
    budget yields strictly more reused tokens. The window bound keeps
    starvation no worse than head-of-line blocking: nothing deeper than
    the head window ever jumps the queue, and a passed-over head is
    retried every boundary."""
    if self.prefix_cache is None or len(self.waiting) <= 1:
      return 0
    best, best_hit = 0, -1
    for j, seq in enumerate(self.waiting):
      if j >= self.max_slots:
        break
      hit = self.prefix_cache.PeekHitTokens(seq.req.prompt)
      if hit > best_hit:
        best, best_hit = j, hit
    return best

  def Admit(self) -> list:
    """Admits queued (and, in priority mode, preempted) requests.

    'fifo': the bit-exact legacy path (_AdmitFifo) — FIFO with
    head-window prefix-cache reordering and intentional head-of-line
    blocking. 'priority': highest SLO class first, preempted-before-
    fresh and weighted-fair tenants within a class, preemption by page
    spill under pressure (_AdmitPriority)."""
    if self.scheduler_mode == "priority":
      return self._AdmitPriority()
    return self._AdmitFifo()

  def _AdmitFifo(self) -> list:
    """Admits waiting requests into free slots while pages last.

    FIFO, except that within the head window the largest cached-prefix
    match goes first (_NextWaiting). Head-of-line blocking on the pool
    is intentional: skipping a big request to admit a small one behind
    it would starve the big one — so when the cache-ordered pick fails
    to fit, the true FIFO head still gets its legacy try, and admission
    stops only when that fails too."""
    admitted = []
    for i in range(self.max_slots):
      if self.slots[i] is not None or not self.waiting:
        continue
      if self.needs_kv_pages:
        pick = self._NextWaiting()
        seq = self.waiting[pick]
        if not self._AdmitPages(seq):
          if pick == 0:
            break
          pick, seq = 0, self.waiting[0]
          if not self._AdmitPages(seq):
            break
        if pick:
          self.prefix_ordered_admissions += 1
        del self.waiting[pick]
        pages = self.alloc.PagesOf(seq.id)
      else:
        # pure O(1)-mixer stack: nothing pages, a free slot IS admission
        seq = self.waiting.popleft()
        pages = []
      self.slots[i] = seq
      seq.state = SeqState.PREFILL
      seq.slot = i
      self.block_tables[i, :] = 0
      self.block_tables[i, :len(pages)] = pages
      if self.state_pool is not None:
        self.state_pool.Acquire(seq.id, i)
      self.admitted += 1
      self.slots_live_peak = max(
          self.slots_live_peak, sum(s is not None for s in self.slots))
      admitted.append(seq)
    return admitted

  # -- priority admission + preemption (scheduler_mode='priority') -----------

  def _CandidateKey(self, seq: Sequence):
    """Admission order: highest class, then resume-before-fresh, then
    weighted-fair across tenants (least admitted-token service per unit
    weight), then arrival order."""
    service = self._tenant_service.get(seq.req.tenant, 0)
    weight = self.tenant_weights.get(seq.req.tenant, 1.0)
    return (-seq.req.priority,
            0 if seq.state is SeqState.PREEMPTED else 1,
            service / weight, seq.arrival)

  def _NextCandidate(self) -> Optional[Sequence]:
    candidates = list(self.preempted) + list(self.waiting)
    if not candidates:
      return None
    return min(candidates, key=self._CandidateKey)

  def _PickVictim(self, min_priority: int) -> Optional[Sequence]:
    """The live sequence a class-`min_priority` arrival may preempt:
    strictly lower priority only (no same-class thrash), lowest class
    first, least generated tokens first (cheapest progress to park)."""
    live = [s for s in self.slots
            if s is not None and s.req.priority < min_priority
            and s.state in (SeqState.PREFILL, SeqState.DECODE)]
    if not live:
      return None
    return min(live, key=lambda s: (s.req.priority, len(s.out), s.arrival))

  def _Preempt(self, victim: Sequence):
    """Spills `victim` to the host tier and parks it PREEMPTED.

    Only its PRIVATE pages move: the data pages' bytes are gathered
    device→host (spill_fn) BEFORE SpillPrivate returns them to the
    pool; trailing reserved pages hold no data and are just freed.
    Shared prefix pages keep the victim's refcount — they stay device-
    resident and pinned, so the prefix cache's nodes stay valid. The
    O(1)-mixer state row rides along (state_spill_fn); the draft-model
    cursor resets so a restored row replays its committed stream into
    whatever slot it lands in, exactly like a fresh admission."""
    i = victim.slot
    logical_idxs, blocks = [], None
    if self.needs_kv_pages:
      private = self.alloc.PrivatePages(victim.id, victim.pos)
      if private and self.spill_fn is not None:
        blocks = self.spill_fn([pg for _, pg in private])
      logical_idxs = [li for li, _ in private]
      self.alloc.SpillPrivate(victim.id)
    state_row = None
    if self.state_pool is not None:
      if self.state_spill_fn is not None and victim.pos > 0:
        state_row = self.state_spill_fn(i)
      self.state_pool.Release(victim.id)
    self.host_store.Put(victim.id, logical_idxs, blocks, state_row)
    self.slots[i] = None
    self.block_tables[i, :] = 0
    victim.slot = None
    victim.state = SeqState.PREEMPTED
    victim.draft_pos = 0
    self.preempted.append(victim)
    self.preemptions += 1

  def _ReAdmit(self, seq: Sequence, i: int) -> bool:
    """Restores a PREEMPTED sequence into slot i from its host-tier
    entry: re-backs every spilled logical page with a fresh exclusive
    page (FillHoles, all-or-nothing), scatters the saved bytes into
    exactly the logical slots they left, re-binds a state slot and
    scatters the saved mixer-state row, and resumes from the spilled
    cursor (PREFILL if prompt remains, DECODE otherwise). Returns False
    with no side effects when the pool cannot cover the holes."""
    if self.needs_kv_pages:
      holes = self.alloc.HoleCount(seq.id)
      if not self.alloc.CanAllocate(holes):
        if self.prefix_cache is not None:
          self.prefix_cache.EvictForPressure(holes - self.alloc.num_free)
        if not self.alloc.CanAllocate(holes):
          return False
    entry = self.host_store.Pop(seq.id)
    pages = []
    if self.needs_kv_pages:
      filled = dict(self.alloc.FillHoles(seq.id))
      if entry.blocks is not None and entry.logical_idxs:
        self.restore_fn([filled[li] for li in entry.logical_idxs],
                        entry.blocks)
      pages = self.alloc.PagesOf(seq.id)
    self.slots[i] = seq
    seq.slot = i
    seq.state = (SeqState.PREFILL if seq.prompt_remaining > 0
                 else SeqState.DECODE)
    self.block_tables[i, :] = 0
    self.block_tables[i, :len(pages)] = pages
    if self.state_pool is not None:
      self.state_pool.Acquire(seq.id, i)
      if entry.state_row is not None and self.state_restore_fn is not None:
        self.state_restore_fn(i, entry.state_row)
    self.restores += 1
    return True

  def _TryAdmitInto(self, seq: Sequence, i: int) -> bool:
    """One admission attempt into free slot i — restore for PREEMPTED
    candidates, the normal reserve-whole-footprint path for fresh ones.
    False (no side effects) when pages don't cover it."""
    if seq.state is SeqState.PREEMPTED:
      if not self._ReAdmit(seq, i):
        return False
      self.preempted.remove(seq)
    else:
      if self.needs_kv_pages:
        if not self._AdmitPages(seq):
          return False
        pages = self.alloc.PagesOf(seq.id)
      else:
        pages = []
      self.waiting.remove(seq)
      self.slots[i] = seq
      seq.state = SeqState.PREFILL
      seq.slot = i
      self.block_tables[i, :] = 0
      self.block_tables[i, :len(pages)] = pages
      if self.state_pool is not None:
        self.state_pool.Acquire(seq.id, i)
      tenant = seq.req.tenant
      self._tenant_service[tenant] = (
          self._tenant_service.get(tenant, 0)
          + len(seq.req.prompt) + seq.req.max_new)
      self.admitted += 1
    self.slots_live_peak = max(
        self.slots_live_peak, sum(s is not None for s in self.slots))
    return True

  def _AdmitPriority(self) -> list:
    """Priority admission: repeatedly place the best candidate
    (_CandidateKey) into a free slot; when slots or pages run out and
    the candidate outranks a running sequence, preempt the cheapest
    strictly-lower-priority victim and retry. Admission stops when the
    best candidate neither fits nor outranks anyone — lower-class
    candidates behind it would steal its resources, so head-of-line
    blocking WITHIN a class is kept (starvation-safe), while higher
    classes always jump the line."""
    admitted = []
    while True:
      cand = self._NextCandidate()
      if cand is None:
        break
      free_i = next((i for i, s in enumerate(self.slots) if s is None),
                    None)
      if free_i is not None and self._TryAdmitInto(cand, free_i):
        admitted.append(cand)
        continue
      victim = (self._PickVictim(cand.req.priority)
                if self.allow_preempt else None)
      if victim is None:
        break
      self._Preempt(victim)
    return admitted

  def HasWork(self) -> bool:
    return (any(s is not None for s in self.slots) or bool(self.waiting)
            or bool(self.preempted))

  def BuildStep(self) -> Optional[StepBatch]:
    """Flattens live slots into one [B, C] device step (None if idle)."""
    rows = list(self.slots)
    if not any(s is not None for s in rows):
      return None
    mixed = any(s is not None and s.state is SeqState.PREFILL for s in rows)
    c = self.prefill_chunk if mixed else 1
    b = self.max_slots
    ids = np.zeros((b, c), np.int32)
    q_pos = np.zeros((b,), np.int32)
    in_len = np.zeros((b,), np.int32)
    row_seeds = np.zeros((b,), np.int32)
    row_pos = np.zeros((b,), np.int32)
    prompt_tokens = 0
    for i, seq in enumerate(rows):
      if seq is None:
        continue
      q_pos[i] = seq.pos
      row_seeds[i] = seq.req.seed
      row_pos[i] = len(seq.out)
      if seq.state is SeqState.PREFILL:
        n = min(c, seq.prompt_remaining)
        ids[i, :n] = seq.req.prompt[seq.pos:seq.pos + n]
        in_len[i] = n
        prompt_tokens += n
      else:  # DECODE: feed the last sampled token (writes it to the cache)
        ids[i, 0] = seq.out[-1]
        in_len[i] = 1
      if self.needs_kv_pages:
        # prefix sharing invariant: this row's KV writes must land only
        # in pages it exclusively owns (CoW happened at admission)
        self.alloc.AssertExclusive(seq.id, seq.pos, int(in_len[i]))
    return StepBatch(ids, q_pos, in_len, rows, mixed, prompt_tokens,
                     row_seeds=row_seeds, row_pos=row_pos)

  def CommitStep(self, batch: StepBatch, sampled: np.ndarray) -> list:
    """Folds sampled [B, C] back into the state machine.

    Returns [(request_id, token or None, finished: bool)] events in slot
    order — one event per live row that produced a token or finished."""
    events = []
    for i, seq in enumerate(batch.rows):
      if seq is None or seq.state is SeqState.CANCELLED:
        continue   # cancelled mid-step: drop the token, evict at boundary
      if seq.state is SeqState.PREFILL:
        n = int(batch.in_len[i])
        seq.pos += n
        if seq.prompt_remaining > 0:
          continue                       # more prompt chunks to go
        tok = int(sampled[i, n - 1])     # sample after the LAST prompt token
        seq.state = SeqState.DECODE
        if self.prefix_cache is not None and self.needs_kv_pages:
          # the prompt's K/V is now fully resident: cache its full-page
          # prefix (the partial tail page — and every decode page after
          # it — stays private to this sequence)
          n_full = len(seq.req.prompt) // self.alloc.page_size
          if n_full > 0:
            self.prefix_cache.Insert(
                seq.req.prompt, self.alloc.PagesOf(seq.id)[:n_full])
      elif seq.state is SeqState.DECODE:
        seq.pos += 1                     # the fed-back token is now cached
        tok = int(sampled[i, 0])
      else:
        continue
      seq.out.append(tok)
      done_eos = (seq.req.eos_id is not None and tok == seq.req.eos_id)
      done_len = len(seq.out) >= seq.req.max_new
      if done_eos or done_len:
        self.slots[i] = None
        self.alloc.Free(seq.id)
        if self.state_pool is not None:
          self.state_pool.Release(seq.id)
        self.finished += 1
        self._Retire(seq, SeqState.FINISHED, "eos" if done_eos else "length")
        events.append((seq.id, tok, True))
      else:
        events.append((seq.id, tok, False))
    return events

  # -- speculative decoding (draft-and-verify) -------------------------------

  def BuildVerifyStep(self, k: int) -> Optional[StepBatch]:
    """Flattens live DECODE slots into one ragged [B, k+1] VERIFY step.

    Row i carries its last emitted token at column 0 (exactly the token a
    plain decode step would feed) plus row_k[i] draft slots the engine
    fills after running the draft source; in_len = row_k + 1 makes the
    step ragged through the SAME masking the mixed prefill path uses, so
    rows that opt out (spec_k = 0) ride along with in_len == 1 — their
    column-0 logits are the legacy decode logits.

    row_k is clamped to the request's remaining token budget, which also
    bounds every KV write to the pages reserved at admission (positions
    written are q_pos .. q_pos + row_k <= prompt + max_new - 1).

    Returns None when any live row is still prefilling (the caller takes
    a normal mixed step) or when no row speculates this cycle (the caller
    falls back to BuildStep)."""
    assert k >= 1, k
    rows = list(self.slots)
    live = [s for s in rows if s is not None]
    if not live or any(s.state is SeqState.PREFILL for s in live):
      return None
    b, c = self.max_slots, k + 1
    ids = np.zeros((b, c), np.int32)
    q_pos = np.zeros((b,), np.int32)
    in_len = np.zeros((b,), np.int32)
    row_seeds = np.zeros((b,), np.int32)
    row_pos = np.zeros((b,), np.int32)
    row_k = np.zeros((b,), np.int32)
    any_spec = False
    for i, seq in enumerate(rows):
      if seq is None or seq.state is not SeqState.DECODE:
        continue
      q_pos[i] = seq.pos
      row_seeds[i] = seq.req.seed
      row_pos[i] = len(seq.out)
      ids[i, 0] = seq.out[-1]
      rk = k if seq.req.spec_k is None else min(seq.req.spec_k, k)
      rk = min(rk, seq.req.max_new - len(seq.out))
      row_k[i] = max(rk, 0)
      in_len[i] = row_k[i] + 1
      any_spec = any_spec or row_k[i] > 0
      if self.needs_kv_pages:
        # rollback safety against prefix sharing: the verify step writes
        # (and, after rejection, REWRITES) slots pos..pos+row_k — those
        # pages must never be shared with another request or the cache
        self.alloc.AssertExclusive(seq.id, seq.pos, int(in_len[i]))
    if not any_spec:
      return None
    return StepBatch(ids, q_pos, in_len, rows, mixed=False, prompt_tokens=0,
                     row_seeds=row_seeds, row_pos=row_pos, row_k=row_k)

  def CommitVerifyStep(self, batch: StepBatch, out_tokens: np.ndarray,
                       accept_len: np.ndarray) -> list:
    """Folds a verify step back in: emits each row's accepted prefix plus
    the correction/bonus token, rolls the KV cursor back over the
    rejected tail (pure accounting — rejected slots are re-written next
    cycle, and reads never pass q_pos + in_len), and retires on
    eos/max_new exactly like CommitStep.

    out_tokens [B, k+1], accept_len [B] from the verify program. Returns
    the same [(request_id, token, finished)] event list as CommitStep,
    possibly several events per row."""
    events = []
    for i, seq in enumerate(batch.rows):
      if seq is None or seq.state is not SeqState.DECODE:
        continue   # cancelled mid-step: drop the tokens, evict at boundary
      rk = int(batch.row_k[i])
      m = min(int(accept_len[i]), rk)
      # drafted-but-rejected tail: cursor rollback, counted on the pool
      self.alloc.NoteRollback(rk - m)
      committed = 0
      for j in range(m + 1):
        tok = int(out_tokens[i, j])
        seq.pos += 1            # verify wrote this column's K/V already
        seq.out.append(tok)
        committed += 1
        done_eos = (seq.req.eos_id is not None and tok == seq.req.eos_id)
        done_len = len(seq.out) >= seq.req.max_new
        if done_eos or done_len:
          self.slots[i] = None
          self.alloc.Free(seq.id)
          if self.state_pool is not None:
            self.state_pool.Release(seq.id)
          self.finished += 1
          self._Retire(seq, SeqState.FINISHED,
                       "eos" if done_eos else "length")
          events.append((seq.id, tok, True))
          break
        events.append((seq.id, tok, False))
      if committed < m + 1:
        # accepted tokens truncated by an early eos are rolled back too
        self.alloc.NoteRollback(m + 1 - committed)
    return events

  # -- unified ragged step ----------------------------------------------------

  def BuildRaggedStep(self, t: int, wmax: int, spec_k: int = 0,
                      spec_w: int = 1) -> Optional[RaggedBatch]:
    """Packs every live slot into ONE [T]-token ragged step (None if idle).

    t: packed token width — static, the engine sizes it once as
    max_slots * (1 + spec_w * spec_k) + prefill token budget, so every
    admit / decode / spec / retire mix reuses one compiled program.
    wmax: widest row the program admits (>= 1 + spec_w * spec_k).
    spec_k: engine draft depth (0 = no draft source configured).
    spec_w: engine draft-tree width (1 = chain speculation).

    Decode rows are mandatory and packed first: 1 feedback token plus
    row_w * row_k draft slots. row_k is clamped per request exactly like
    BuildVerifyStep (request opt-out/cap, remaining max_new budget, and
    the packed-row cap); row_w (tree rows only) is clamped WIDTH BEFORE
    DEPTH under min(wmax, ragged.MAX_TREE_COLS) — under pressure a
    request loses branches before it loses per-branch depth, because a
    deep chain keeps the accepted-length upside that extra siblings only
    hedge. Each clamped row bumps `width_clamps`. A row_w > 1 row packs
    its tree in DFS order (branch bi's depth-d node at column
    1 + bi * row_k + d) and ships parent pointers so
    ragged.BuildRaggedRows emits ancestor masks; row_w == 1 rows stay
    chain-packed — bitwise the pre-tree build. Prefill rows then consume
    the LEFTOVER budget in slot order, each taking up to
    min(wmax, budget, prompt_remaining) prompt tokens. Decode latency
    therefore never stalls behind prefill, prefill rides every step
    instead of alternating with it, spec cycles run while other rows are
    still prefilling, and decode capacity left idle by empty slots flows
    to prefill instead of padding. Rows that fit no budget this step
    ride with row_len == 0.
    """
    rows = list(self.slots)
    if not any(s is not None for s in rows):
      return None
    b = self.max_slots
    row_len = np.zeros((b,), np.int32)
    row_q_pos = np.ones((b,), np.int32)  # empty slot: 1, never SSM-reset 0
    row_seeds = np.zeros((b,), np.int32)
    row_pos = np.zeros((b,), np.int32)
    row_k = np.zeros((b,), np.int32)
    row_w = np.ones((b,), np.int32)
    row_parents = {}
    ids0 = np.zeros((b, 1), np.int32)
    budget = t
    any_spec = False
    width_clamps = 0
    for i, seq in enumerate(rows):
      if seq is None:
        continue
      row_q_pos[i] = seq.pos
      row_seeds[i] = seq.req.seed
      row_pos[i] = len(seq.out)
      if seq.state is not SeqState.DECODE:
        continue
      rk = 0
      rw = 1
      if spec_k > 0:
        rk = spec_k if seq.req.spec_k is None else min(seq.req.spec_k, spec_k)
        rk = min(rk, seq.req.max_new - len(seq.out))
        rk = max(rk, 0)
        if rk > 0 and spec_w > 1:
          rw = spec_w if seq.req.spec_w is None else min(seq.req.spec_w,
                                                         spec_w)
          rw = max(rw, 1)
        if rw > 1:
          cap = min(wmax, ragged.MAX_TREE_COLS)
          room = rw * rk   # pageless stack: only the packed-row cap binds
          if self.needs_kv_pages:
            # transient tree writes (slots q_pos+1 .. q_pos+rw*rk) must
            # stay inside the pages reserved at admission: block-table
            # entries past the footprint alias pool page 0, so an
            # unclamped tree near its max_new budget would scatter draft
            # K/V into another sequence's page. Chains can't overflow —
            # rk <= max_new - len(out) already bounds q_pos + rk.
            cap_tok = len(self.alloc.PagesOf(seq.id)) * self.alloc.page_size
            room = cap_tok - 1 - seq.pos
          want = rw
          while rw > 1 and (1 + rw * rk > cap or rw * rk > room):
            rw -= 1
          if rw < want:
            width_clamps += 1
          if rw > 1:
            rk = min(rk, (cap - 1) // rw)
        if rw == 1:
          rk = min(rk, wmax - 1)   # exact chain clamp (pre-tree behavior)
      row_k[i] = rk
      row_w[i] = rw
      any_spec = any_spec or rk > 0
      ids0[i, 0] = seq.out[-1]
      row_len[i] = 1 + rw * rk
      budget -= 1 + rw * rk
      if rw > 1:
        # DFS preorder parents: branch bi is a chain whose head hangs off
        # the root (-1) and whose depth-d node follows its predecessor
        parents = np.empty((rw * rk,), np.int32)
        for bi in range(rw):
          for d in range(rk):
            j = bi * rk + d
            parents[j] = -1 if d == 0 else j - 1
        row_parents[i] = parents
    assert budget >= 0, (t, row_len)  # engine sizes t for worst-case decode
    prompt_tokens = 0
    for i, seq in enumerate(rows):
      if seq is None or seq.state is not SeqState.PREFILL:
        continue
      n = min(wmax, budget, seq.prompt_remaining)
      row_len[i] = n
      budget -= n
      prompt_tokens += n
    desc = ragged.BuildRaggedRows(row_len, row_q_pos, t, wmax,
                                  row_parents or None)
    tok_ids = np.zeros((t,), np.int32)
    for i, seq in enumerate(rows):
      n = int(row_len[i])
      if seq is None or n == 0:
        continue
      cols = desc.row_cols[i, :n]
      if seq.state is SeqState.PREFILL:
        tok_ids[cols] = seq.req.prompt[seq.pos:seq.pos + n]
      else:
        tok_ids[cols[0]] = seq.out[-1]  # draft columns stay 0 until Draft
      if self.needs_kv_pages:
        # same exclusivity invariant as BuildStep/BuildVerifyStep: every
        # slot this row writes (and, on spec rollback, REWRITES) lives in
        # pages CoW-private to it
        self.alloc.AssertExclusive(seq.id, seq.pos, n)
    self.width_clamps += width_clamps
    return RaggedBatch(tok_ids, desc, rows, prompt_tokens > 0,
                       prompt_tokens, row_seeds, row_pos, row_k, any_spec,
                       ids0, row_w=row_w, width_clamps=width_clamps)

  def _Finish(self, i: int, seq: Sequence, done_eos: bool):
    """Retires slot i's sequence (shared CommitRaggedStep epilogue)."""
    self.slots[i] = None
    self.alloc.Free(seq.id)
    if self.state_pool is not None:
      self.state_pool.Release(seq.id)
    self.finished += 1
    self._Retire(seq, SeqState.FINISHED, "eos" if done_eos else "length")

  def CommitRaggedStep(self, batch: RaggedBatch, sampled_tok: np.ndarray,
                       out_tokens=None, accept_len=None) -> list:
    """Folds one ragged step back in: CommitStep + CommitVerifyStep, unified.

    sampled_tok [T]: the program's per-token draws — token t's draw is a
    pure function of (engine seed, row seed, row output position), so a
    prefill row reads its LAST prompt token's column and a plain decode
    row its only column, exactly the draws the legacy [B, C] programs
    made. out_tokens [B, k+1] / accept_len [B]: the verify lane, consumed
    only by rows with row_k > 0 (their column-0 entry is bitwise the
    plain draw, so routing rk == 0 rows through sampled_tok is
    equivalent — and keeps the no-spec engine free of verify outputs).
    Returns the same [(request_id, token, finished)] event list as the
    legacy commits, possibly several events per speculating row."""
    events = []
    desc = batch.rows_desc
    for i, seq in enumerate(batch.rows):
      if seq is None or seq.state is SeqState.CANCELLED:
        continue   # cancelled mid-step: drop the tokens, evict at boundary
      n = int(desc.row_len[i])
      if seq.state is SeqState.PREFILL:
        if n == 0:
          continue                       # out of token budget this step
        seq.pos += n
        if seq.prompt_remaining > 0:
          continue                       # more prompt tokens to go
        tok = int(sampled_tok[desc.row_cols[i, n - 1]])
        seq.state = SeqState.DECODE
        if self.prefix_cache is not None and self.needs_kv_pages:
          n_full = len(seq.req.prompt) // self.alloc.page_size
          if n_full > 0:
            self.prefix_cache.Insert(
                seq.req.prompt, self.alloc.PagesOf(seq.id)[:n_full])
      elif seq.state is SeqState.DECODE:
        rk = int(batch.row_k[i])
        if rk > 0:
          # spec-verify lane: accepted path + correction/bonus, cursor
          # rollback over every other tree node — CommitVerifyStep
          # semantics generalized to row_w branches (chain: row_w == 1).
          # The engine's in-program KV repair already moved the accepted
          # path's K/V into the canonical chain slots, so advancing
          # seq.pos by m + 1 lands on bit-correct cache state.
          rw = int(batch.row_w[i])
          m = min(int(accept_len[i]), rk)
          self.alloc.NoteRollback(rw * rk - m)
          committed = 0
          for j in range(m + 1):
            tok = int(out_tokens[i, j])
            seq.pos += 1        # verify wrote this column's K/V already
            seq.out.append(tok)
            committed += 1
            done_eos = (seq.req.eos_id is not None and tok == seq.req.eos_id)
            if done_eos or len(seq.out) >= seq.req.max_new:
              self._Finish(i, seq, done_eos)
              events.append((seq.id, tok, True))
              break
            events.append((seq.id, tok, False))
          if committed < m + 1:
            # accepted tokens truncated by an early eos roll back too
            self.alloc.NoteRollback(m + 1 - committed)
          continue
        seq.pos += 1                     # the fed-back token is now cached
        tok = int(sampled_tok[desc.row_cols[i, 0]])
      else:
        continue
      seq.out.append(tok)
      done_eos = (seq.req.eos_id is not None and tok == seq.req.eos_id)
      if done_eos or len(seq.out) >= seq.req.max_new:
        self._Finish(i, seq, done_eos)
        events.append((seq.id, tok, True))
      else:
        events.append((seq.id, tok, False))
    return events

  def _Retire(self, seq: Sequence, state: SeqState, reason: str):
    seq.state = state
    seq.finish_reason = reason
    self.alloc.Free(seq.id)   # idempotent
    if self.state_pool is not None:
      self.state_pool.Release(seq.id)   # idempotent

  # -- introspection ---------------------------------------------------------

  def Stats(self) -> dict:
    live = [s for s in self.slots if s is not None]
    host = self.host_store.Stats() if self.host_store is not None else {}
    parked = list(self.preempted) + list(self.waiting)
    return {
        "slots": self.max_slots,
        "slots_live": len(live),
        "slots_prefill": sum(s.state is SeqState.PREFILL for s in live),
        "queue_depth": len(self.waiting),
        "admitted": self.admitted,
        "finished": self.finished,
        "cancelled": self.cancelled,
        "rejected_overlong": self.rejected_overlong,
        "needs_kv_pages": self.needs_kv_pages,
        "slots_live_peak": self.slots_live_peak,
        "prefix_ordered_admissions": self.prefix_ordered_admissions,
        "width_clamps": self.width_clamps,
        "scheduler_mode": self.scheduler_mode,
        "preemptions": self.preemptions,
        "restores": self.restores,
        "preempted_queued": len(self.preempted),
        "quota_rejections": self.quota_rejections,
        "spilled_pages": host.get("spilled_pages", 0),
        "restored_pages": host.get("restored_pages", 0),
        "host_bytes": host.get("host_bytes", 0),
        # class-aware load signal for the router: work parked ABOVE the
        # default class (a replica drowning in priority traffic should
        # repel more of it even when its plain queue_depth looks fine)
        "queue_depth_high": sum(s.req.priority > 0 for s in parked),
    }
