"""Prefix-aware request routing for a fleet of serving replicas.

A fleet of N data-parallel `ServingLoop` replicas multiplies throughput
but DIVIDES the prefix cache: each replica only caches what it has
served, so a load-oblivious router scatters every popular system prompt
across all N pools and pays its prefill N times. The router's job is to
make the fleet's caches behave like one big cache, with two signals:

- a **shadow radix index** (`ShadowPrefixIndex`): a router-side tree
  over the leading page-size token chunks of every prompt it has routed,
  each node tagged with the replica labels that received that prefix.
  It predicts `prefix_cache` hit_tokens per replica WITHOUT a network
  round-trip per request — the replicas' real caches are the ground
  truth (scraped via /statusz), the shadow is the router's cheap,
  slightly-optimistic model of them (it can overestimate after replica
  eviction; the cost of a wrong guess is one re-prefill, never a wrong
  stream).
- **replica load** from the telemetry substrate: each replica's
  `scheduler/queue_depth` out of its registry snapshot — scraped
  (`observe/aggregate.Scrape`) for out-of-process replicas or read
  in-process (`registry.Snapshot()`) for a co-located fleet. Both spell
  the same keys, so the scoring path is transport-agnostic.

Scoring: `expected_hit_tokens(replica, prompt) - load_weight *
queue_depth(replica)`, maximized over UP replicas; ties break on the
fleet's declared replica order (deterministic, never dict order —
mirror routers scoring the same scrape agree). Chat sessions are PINNED:
once a session routes somewhere, later turns follow it while the
replica stays up — its cache holds the whole conversation prefix, which
the shadow index cannot even represent (it only sees leading chunks).

DOWN handling: a replica whose snapshot is missing (scrape error,
killed) is routed AROUND — it never scores, pinned sessions on it
re-route (counted `rerouted_down`) and re-pin to their new home. Only a
fleet with zero UP replicas raises.

Thread safety: plain host state; the owning fleet serializes calls
under its submit lock (same discipline as scheduler/prefix_cache under
the engine lock).
"""

from __future__ import annotations

from typing import Optional

from lingvo_tpu.observe import schema as observe_schema


class _ShadowNode:
  """One routed page_size chunk: which replicas have seen this prefix,
  each tagged with the router tick of its most recent routing."""

  __slots__ = ("chunk", "parent", "children", "labels")

  def __init__(self, chunk, parent):
    self.chunk = chunk
    self.parent = parent
    self.children: dict = {}
    self.labels: dict = {}   # replica label -> last routed tick


class ShadowPrefixIndex:
  """Router-side radix over the leading page-size chunks it has routed.

  max_nodes bounds memory (LRU leaves evicted first, like the real
  cache's eviction walk); max_depth bounds per-prompt work — beyond a
  few pages of shared prefix the routing decision is already made.
  """

  def __init__(self, page_size: int, max_nodes: int = 4096,
               max_depth: int = 16):
    assert page_size >= 1 and max_nodes >= 1 and max_depth >= 1
    self.page_size = page_size
    self.max_nodes = max_nodes
    self.max_depth = max_depth
    self._root = _ShadowNode(None, None)
    self._count = 0
    self._tick = 0
    self.evictions = 0

  def _Chunks(self, prompt):
    ps = self.page_size
    for i in range(min(len(prompt) // ps, self.max_depth)):
      yield tuple(prompt[i * ps:(i + 1) * ps])

  def NoteRouted(self, label, prompt):
    """Records that `prompt` was routed to replica `label`: its leading
    chunks will shortly be in that replica's prefix cache."""
    self._tick += 1
    node = self._root
    for chunk in self._Chunks(prompt):
      child = node.children.get(chunk)
      if child is None:
        if self._count >= self.max_nodes and self._EvictLru() == 0:
          return
        child = _ShadowNode(chunk, node)
        node.children[chunk] = child
        self._count += 1
      child.labels[label] = self._tick
      node = child

  def ExpectedHitTokens(self, label, prompt) -> int:
    """Predicted prefix_cache hit_tokens were `prompt` routed to
    `label` — matched full-page tokens along the shadow path that
    replica has seen, capped at len(prompt)-1 like the real cache (a
    full-cover hit still recomputes the last token)."""
    node, matched = self._root, 0
    for chunk in self._Chunks(prompt):
      child = node.children.get(chunk)
      if child is None or label not in child.labels:
        break
      matched += self.page_size
      node = child
    return min(matched, len(prompt) - 1) if matched else 0

  def _Leaves(self):
    out, stack = [], [self._root]
    while stack:
      node = stack.pop()
      kids = list(node.children.values())
      if not kids and node is not self._root:
        out.append(node)
      stack.extend(kids)
    return out

  def _EvictLru(self) -> int:
    """Drops the least-recently-routed leaf (leaves-first, like the real
    cache: an inner node outlives its subtree)."""
    leaves = self._Leaves()
    if not leaves:
      return 0
    victim = min(leaves, key=lambda nd: max(nd.labels.values(), default=0))
    del victim.parent.children[victim.chunk]
    self._count -= 1
    self.evictions += 1
    return 1

  def DropReplica(self, label):
    """Forgets everything routed to `label` (replica died, or swapped
    theta without tree persistence): its tags go, and nodes no replica
    remembers are pruned bottom-up."""
    stack, post = [self._root], []
    while stack:
      node = stack.pop()
      post.append(node)
      stack.extend(node.children.values())
    for node in reversed(post):   # children before parents
      node.labels.pop(label, None)
      if node is not self._root and not node.labels and not node.children:
        del node.parent.children[node.chunk]
        self._count -= 1

  def Clear(self):
    self._root = _ShadowNode(None, None)
    self._count = 0

  @property
  def nodes(self) -> int:
    return self._count


class PrefixRouter:
  """Scores replicas for one request: shadow-predicted prefix hit vs
  queue depth, with session pinning and deterministic tie-breaks.

  order: the fleet's replica labels in declaration order — the
  tie-break and iteration order everywhere (never dict order).
  load_key: the snapshot key read as load — or a sequence of keys whose
  numeric values SUM (e.g. ("scheduler/queue_depth",
  "scheduler/slots_live") counts every in-system request, immune to the
  queued-vs-admitted race during a submit burst).
  load_weight: tokens of expected prefix hit one unit of queue depth
  cancels; default page_size (one queued request outweighs one cached
  page — mild load bias that still lets a multi-page prefix pull its
  session home).
  """

  def __init__(self, page_size: int, order, *,
               load_key: str = "scheduler/queue_depth",
               load_weight: Optional[float] = None,
               pin_sessions: bool = True,
               shadow_max_nodes: int = 4096):
    self.order = list(order)
    assert self.order, "a router needs at least one replica label"
    self.load_keys = ([load_key] if isinstance(load_key, str)
                      else list(load_key))
    self.load_weight = float(page_size if load_weight is None else load_weight)
    self.pin_sessions = pin_sessions
    self.shadow = ShadowPrefixIndex(page_size, max_nodes=shadow_max_nodes)
    self._pins: dict = {}          # session -> replica label
    self.requests_routed = 0
    self.pinned_routed = 0
    self.prefix_routed = 0
    self.balanced_routed = 0
    self.rerouted_down = 0
    self.priority_routed = 0

  def Route(self, prompt, snapshots: dict, session=None,
            note: bool = True, priority: int = 0) -> str:
    """Picks the replica for `prompt`. snapshots: {label: registry
    snapshot dict, or None/missing for a DOWN replica} — in-process
    `registry.Snapshot()` and a scraped /statusz `doc["snapshot"]` both
    qualify. Raises RuntimeError only when every replica is DOWN.

    priority > 0 routes on load WITHIN the request's class: the load
    term reads "scheduler/queue_depth_high" (work parked above the
    default class) instead of the configured load keys — a replica
    drowning in default-class traffic preempts its way clear, so only
    same-or-higher-class congestion should repel a priority request.
    Snapshots without the key (pre-SLO replicas) fall back to the
    configured keys for that replica.

    note=False skips tagging the shadow index with this routing — for a
    caller that must first inspect the PRE-routing shadow state (the
    fleet's disaggregation warm-skip) and will NoteRouted itself."""
    live = [lb for lb in self.order if snapshots.get(lb) is not None]
    if not live:
      raise RuntimeError(
          f"no UP replica among {self.order}: nothing to route to")
    self.requests_routed += 1
    if self.pin_sessions and session is not None:
      pinned = self._pins.get(session)
      if pinned is not None:
        if pinned in live:
          self.pinned_routed += 1
          if note:
            self.shadow.NoteRouted(pinned, prompt)
          return pinned
        self.rerouted_down += 1   # pinned home is DOWN: re-route, re-pin
    if priority > 0:
      self.priority_routed += 1
    best, best_score, best_hit = None, None, 0
    for lb in live:
      hit = self.shadow.ExpectedHitTokens(lb, prompt)
      load_keys = self.load_keys
      if priority > 0 and "scheduler/queue_depth_high" in snapshots[lb]:
        load_keys = ["scheduler/queue_depth_high"]
      load = 0
      for key in load_keys:
        v = snapshots[lb].get(key, 0)
        if not isinstance(v, bool) and isinstance(v, (int, float)):
          load += v
      score = hit - self.load_weight * load
      if best_score is None or score > best_score:   # strict >: order wins ties
        best, best_score, best_hit = lb, score, hit
    if best_hit > 0:
      self.prefix_routed += 1
    else:
      self.balanced_routed += 1
    if self.pin_sessions and session is not None:
      self._pins[session] = best
    if note:
      self.shadow.NoteRouted(best, prompt)
    return best

  def OnReplicaDown(self, label):
    """A replica died: forget its shadow entries so scoring stops
    crediting it. Sessions pinned to it re-route lazily (Route sees the
    pin is not live) — their next turn counts `rerouted_down`."""
    self.shadow.DropReplica(label)

  def OnThetaSwap(self, persisted: bool):
    """The fleet hot-swapped theta. With tree persistence the replicas
    keep their (stale, refresh-in-place) trees, so the shadow stays an
    honest model of WHERE prefixes live; without it every replica
    dropped its cache and the shadow must drop too."""
    if not persisted:
      self.shadow.Clear()

  @property
  def sessions_pinned(self) -> int:
    return len(self._pins)

  def Stats(self) -> dict:
    """The `router/*` registry section (observe/schema.py
    ROUTER_STATS_KEYS)."""
    stats = {
        "requests_routed": self.requests_routed,
        "pinned_routed": self.pinned_routed,
        "prefix_routed": self.prefix_routed,
        "balanced_routed": self.balanced_routed,
        "rerouted_down": self.rerouted_down,
        "sessions_pinned": self.sessions_pinned,
        "shadow_nodes": self.shadow.nodes,
        "shadow_evictions": self.shadow.evictions,
        "priority_routed": self.priority_routed,
    }
    assert set(stats) == observe_schema.ROUTER_STATS_KEYS, sorted(stats)
    return stats
